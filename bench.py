"""Benchmark: quorum-rounds/sec/chip on the flagship fuzzing config.

Default (driver contract): prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"} for the flagship case —
config 2, fused engine on TPU.

``--sweep``: one JSON line per (protocol x engine) case — the full measured
table of BASELINE.md, reproducible in one command.  ``--record PATH``
additionally writes the sweep to a JSON artifact (list of case dicts);
``tests/test_perf_regression.py`` gates future rounds against that artifact
(each case must stay >= 0.7x its recorded value on TPU).

Metric definition (BASELINE.md): quorum-rounds/sec/chip — each scheduler
tick advances every instance's consensus state machine by one protocol
round (deliver -> vote -> quorum-check), so throughput = instances x ticks
/ wall-clock.  North star: >= 10M at 1M concurrent instances on a v5e-1.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

NORTH_STAR = 10_000_000.0  # BASELINE.md north-star target


def _configs(platform: str):
    """The sweep table: (name, SimConfig, engine, chunk, depth) per case.

    TPU sizes match BASELINE.md's measured rows (1M instances).  The CPU
    rig shrinks instances and skips the fused engine (the Pallas TPU
    interpreter replays the stream bit-exactly but ~1000x slower — it is a
    correctness tool, not a benchmark path).

    Per-case chunk (ticks per device dispatch): protocol ticks do identical
    work regardless of chunking, so the measured-best chunk is used —
    dispatch boundaries through the axon tunnel cost ~10-17% at chunk 64
    (measured 2026-07-30: config2 321.8M @ 64 -> 378.1M @ 1024).  EXCEPT
    config3long, where chunk IS the compaction cadence (schedule-relevant:
    a bigger chunk leaves lanes idle at a full window, padding the metric
    with non-work ticks) — it stays at the run/soak operating default 64.

    Per-case depth (dispatch pipeline, harness.pipeline): the *-pipelined
    rows group 4 chunk-64 bodies per dispatch — the schedule of the chunk-64
    serial row (identical fingerprint AND identical stream) at a quarter of
    the dispatch count, which is how the chunk-boundary tax is recovered
    where the chunk size itself is schedule-relevant.  The chunk-64 serial
    config2 row sits alongside as the pipelined-vs-serial comparison pair.
    """
    import dataclasses

    from paxos_tpu.core.telemetry import TelemetryConfig
    from paxos_tpu.harness.config import (
        config2_dueling_drop,
        config3_long,
        config3_multipaxos,
        config5_sweep,
    )

    on_tpu = platform == "tpu"
    n = 1 << 20 if on_tpu else 1 << 13
    sweep = {c.protocol: c for c in config5_sweep(n_inst=n)}
    # Telemetry-overhead row: flagship config with the full flight recorder
    # on (counters + ring + histogram).  The recorder-OFF row above is the
    # one the perf gate bands at 0.7x — off must stay free (same schedule,
    # same fingerprint); this row measures what ON costs, for the README
    # overhead table.
    tel_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n),
        telemetry=TelemetryConfig(counters=True, ring_depth=64, hist_bins=16),
    )
    # Coverage-overhead row: flagship config with the on-device coverage
    # sketch on at the CLI default size (64 words = 2048 Bloom bits/lane).
    # Same contract as the telemetry row: OFF is gated free at 0.7x by the
    # base row; this row prices ON (two hash insertions + a popcount per
    # tick, plus 64 extra packed words per lane through the fused engine).
    from paxos_tpu.obs.coverage import CoverageConfig

    cov_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n), coverage=CoverageConfig(words=64)
    )
    # Exposure-overhead row: flagship config with the fault-exposure
    # counters on (6x2 packed int32 counters/lane through the generic
    # passthrough).  Same contract again: OFF is gated free by the base
    # row; this row prices ON (a handful of masked popcount-adds per tick)
    # and backs the README's "within 10%" acceptance claim.
    from paxos_tpu.obs.exposure import ExposureConfig

    exp_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n), exposure=ExposureConfig(counters=True)
    )
    cases = [
        ("config2-paxos", config2_dueling_drop(n_inst=n), 1024, 1),
        ("config2-paxos-telemetry", tel_cfg, 1024, 1),
        ("config2-paxos-coverage", cov_cfg, 1024, 1),
        ("config2-paxos-exposure", exp_cfg, 1024, 1),
        ("config5-fastpaxos", sweep["fastpaxos"], 256, 1),
        ("config5-raftcore", sweep["raftcore"], 256, 1),
        ("config3-multipaxos", config3_multipaxos(n_inst=n), 256, 1),
        # Long-log mode: 16-slot window sliding over a 256-slot log with
        # decided-prefix compaction at every chunk boundary (cost included).
        ("config3long-multipaxos", config3_long(n_inst=n), 64, 1),
        # Pipelined-vs-serial pair at the schedule-relevant operating chunk.
        ("config2-paxos-chunk64", config2_dueling_drop(n_inst=n), 64, 1),
        ("config2-paxos-chunk64-pipelined",
         config2_dueling_drop(n_inst=n), 64, 4),
        ("config3long-multipaxos-pipelined", config3_long(n_inst=n), 64, 4),
    ]
    engines = ("fused", "xla") if on_tpu else ("xla",)
    # The big-chunk win is the fused path's (dispatch amortization over a
    # VMEM-resident kernel); the XLA engine gains <2% from chunk 1024 while
    # its timed work grows 16x — XLA rows stay at 64 so the sweep and the
    # TPU perf gate finish in minutes.  The CPU rig caps everything at 64.
    def case_chunk(eng, chunk):
        return chunk if (on_tpu and eng == "fused") else min(chunk, 64)

    return [
        (name, cfg, eng, case_chunk(eng, chunk), depth)
        for name, cfg, chunk, depth in cases
        for eng in engines
    ]


def bench_case(
    cfg, engine: str, chunk: int = 64, timed_chunks: int = 4,
    repeats: int = 3, pipeline_depth: int = 1,
) -> dict:
    """Measure one (config, engine) case; returns the result dict.

    ``repeats`` timed groups of ``timed_chunks`` chunks each are measured
    after one warmup group; ``value`` is the BEST group's throughput (the
    standard min-time discipline — noise on a shared tunnel only ever
    slows a run down) and ``throughput_runs`` records every group so a
    reader can judge the spread.

    ``pipeline_depth`` groups that many chunk bodies per device dispatch
    (harness.pipeline) — same ticks, same schedule, 1/depth the dispatch
    count — and must divide ``timed_chunks`` so every timed group is a
    whole number of dispatches.
    """
    import jax

    from paxos_tpu.harness.checkpoint import stream_id
    from paxos_tpu.harness.config import validate_pipeline_depth
    from paxos_tpu.harness.run import (
        init_plan,
        init_state,
        make_advance_grouped,
        make_longlog,
        summarize,
    )

    depth = validate_pipeline_depth(pipeline_depth)
    if timed_chunks % depth:
        raise ValueError(
            f"timed_chunks={timed_chunks} must be a multiple of "
            f"pipeline_depth={depth} (whole dispatches per timed group)"
        )
    platform = jax.devices()[0].platform
    state = init_state(cfg)
    plan = init_plan(cfg)
    # On-device footprint + the effective fused block, recorded in every
    # row so a packing regression (bytes creeping back up, block degrading)
    # shows in BENCH_* without re-running the roofline.  The bytes are what
    # THIS engine carries: packed codec words for fused rows, the unpacked
    # pytree for xla rows (which never packs).  eval_shape/leaf-shape based:
    # free, computed before the state is donated away.
    from paxos_tpu.kernels.fused_tick import fit_block
    from paxos_tpu.utils import bitops

    state_bytes = (
        bitops.codec_for(cfg.protocol, state).bytes_per_lane(state)
        if engine == "fused"
        else bitops.unpacked_bytes_per_lane(state)
    )
    sid = stream_id(cfg, engine)
    eff_block = (
        fit_block(sid["block"], cfg.n_inst, warn=False)
        if engine == "fused" else None
    )
    # Long-log: compaction rides in the timed loop (traced into each chunk).
    advance = make_advance_grouped(
        cfg, plan, engine, compact=bool(make_longlog(cfg))
    )

    # Warmup: compile + one dispatch of the grouped program.  NOTE: timing
    # must end with a device->host readback, not block_until_ready — on the
    # axon tunnel backend block_until_ready can return before execution
    # finishes.
    state = advance(state, chunk, depth)
    int(state.tick)

    ticks = timed_chunks * chunk
    runs = []
    violations = 0
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        for _ in range(timed_chunks // depth):
            state = advance(state, chunk, depth)
        violations = int(state.learner.violations.sum())  # forces completion
        runs.append(cfg.n_inst * ticks / (time.perf_counter() - t0))

    # Post-run measurement audit (outside the timed loop): summarize runs
    # the packed-ballot overflow guard, so a corrupted MP campaign raises
    # here instead of recording untrustworthy violation counts.
    summarize(state, log_total=cfg.fault.log_total)

    value = max(runs)
    return {
        "metric": "quorum-rounds/sec/chip",
        "value": round(value, 1),
        "unit": "instance-rounds/sec",
        "vs_baseline": round(value / NORTH_STAR, 3),
        "n_instances": cfg.n_inst,
        "chunk": chunk,
        "pipeline_depth": depth,
        "ticks": ticks,
        "seconds": round(cfg.n_inst * ticks / value, 4),
        "throughput_runs": [round(r, 1) for r in runs],
        "platform": platform,
        "engine": engine,
        "protocol": cfg.protocol,
        "violations": violations,
        "state_bytes_per_lane": state_bytes,
        "block": eff_block,
        # Stream lineage (VERDICT r4 weak#3): the fused block this case ran
        # under — replays must match it or the schedule differs.
        "stream": sid,
        "config_fingerprint": cfg.fingerprint(),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sweep", action="store_true",
                    help="bench all protocols x engines (one JSON line each)")
    ap.add_argument("--record", metavar="PATH",
                    help="with --sweep: also write the case list to PATH")
    ap.add_argument(
        "--pipeline-depth", type=int, default=None, metavar="K",
        help="flagship case only: chunks grouped per device dispatch "
        "(harness.pipeline; default 16 on TPU — 64-tick chunks in "
        "1024-tick dispatches, the measured-best dispatch size — else 4)",
    )
    args = ap.parse_args(argv)
    if args.record and not args.sweep:
        ap.error("--record requires --sweep")

    import jax

    # rbg is markedly faster than threefry on TPU for the per-tick mask
    # sampling; streams stay deterministic per (seed, tick) within the impl.
    jax.config.update("jax_default_prng_impl", "rbg")
    platform = jax.devices()[0].platform

    if args.sweep:
        results = []
        for name, cfg, engine, chunk, depth in _configs(platform):
            out = bench_case(cfg, engine, chunk=chunk, pipeline_depth=depth)
            out["case"] = name
            results.append(out)
            print(json.dumps(out), flush=True)
        if args.record:
            with open(args.record, "w") as f:
                json.dump(results, f, indent=1)
        return

    from paxos_tpu.harness.config import config2_dueling_drop

    n_inst = 1 << 20 if platform != "cpu" else 1 << 14  # 1,048,576 on TPU
    cfg = config2_dueling_drop(n_inst=n_inst, seed=0)
    # Engine: the fused Pallas path (whole chunk resident in VMEM) on TPU;
    # the scanned XLA path on CPU (Mosaic doesn't target host CPUs).
    # Flagship dispatch shape: the OPERATING chunk of 64 (the run/soak and
    # long-log compaction cadence), pipelined --pipeline-depth chunks per
    # dispatch.  At the TPU default of 16 the dispatched program is
    # structurally the old chunk-1024 program — the dispatch-boundary tax
    # (~10-17% at serial chunk 64, see _configs) is recovered without
    # giving up the chunk-64 cadence.
    engine = "fused" if platform == "tpu" else "xla"
    depth = args.pipeline_depth
    if depth is None:
        depth = 16 if platform == "tpu" else 4
    print(json.dumps(bench_case(
        cfg, engine, chunk=64, timed_chunks=4 * depth, pipeline_depth=depth
    )))


if __name__ == "__main__":
    sys.exit(main())
