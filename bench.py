"""Benchmark: quorum-rounds/sec/chip on the flagship fuzzing config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric definition (BASELINE.md): quorum-rounds/sec/chip — each scheduler
tick advances every instance's consensus state machine by one protocol
round (deliver -> vote -> quorum-check), so throughput = instances x ticks
/ wall-clock.  North star: >= 10M at 1M concurrent instances on a v5e-1.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax

    # rbg is markedly faster than threefry on TPU for the per-tick mask
    # sampling; streams stay deterministic per (seed, tick) within the impl.
    jax.config.update("jax_default_prng_impl", "rbg")

    import jax.numpy as jnp

    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import (
        base_key,
        get_step_fn,
        init_plan,
        init_state,
        run_chunk,
    )

    platform = jax.devices()[0].platform
    n_inst = 1 << 20 if platform != "cpu" else 1 << 14  # 1,048,576 on TPU
    cfg = config2_dueling_drop(n_inst=n_inst, seed=0)

    state = init_state(cfg)
    plan = init_plan(cfg)

    # Engine: the fused Pallas path (whole chunk resident in VMEM) on TPU;
    # the scanned XLA path on CPU (Mosaic doesn't target host CPUs).
    engine = "fused" if platform == "tpu" else "xla"
    if engine == "fused":
        from paxos_tpu.kernels.fused_tick import fused_paxos_chunk

        def advance(s, n):
            return fused_paxos_chunk(s, jnp.int32(cfg.seed), plan, cfg.fault, n)

    else:
        step = get_step_fn(cfg.protocol)
        key = base_key(cfg)

        def advance(s, n):
            return run_chunk(s, key, plan, cfg.fault, n, step)

    chunk = 64
    # Warmup: compile + one chunk.  NOTE: timing must end with a device->host
    # readback, not block_until_ready — on the axon tunnel backend
    # block_until_ready can return before execution finishes.
    state = advance(state, chunk)
    int(state.tick)

    timed_chunks = 4
    t0 = time.perf_counter()
    for _ in range(timed_chunks):
        state = advance(state, chunk)
    violations = int(state.learner.violations.sum())  # forces completion
    dt = time.perf_counter() - t0

    ticks = timed_chunks * chunk
    value = n_inst * ticks / dt
    baseline = 10_000_000.0  # BASELINE.md north-star target
    out = {
        "metric": "quorum-rounds/sec/chip",
        "value": round(value, 1),
        "unit": "instance-rounds/sec",
        "vs_baseline": round(value / baseline, 3),
        "n_instances": n_inst,
        "ticks": ticks,
        "seconds": round(dt, 4),
        "platform": platform,
        "engine": engine,
        "violations": violations,
        "config_fingerprint": cfg.fingerprint(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
