"""Benchmark: quorum-rounds/sec/chip on the flagship fuzzing config.

Default (driver contract): prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"} for the flagship case —
config 2, fused engine on TPU.

``--sweep``: one JSON line per (protocol x engine) case — the full measured
table of BASELINE.md, reproducible in one command.  ``--record PATH``
additionally writes the rows to a JSON artifact (list of case dicts);
``tests/test_perf_regression.py`` gates future rounds against that artifact
(each case must stay >= 0.7x its recorded value on TPU), and
``paxos_tpu bench-compare`` diffs any fresh ``--record`` file against it
with a noise-aware tolerance (exit 2 on regression).

Provenance: every row follows ``obs.perf.BENCH_ROW_SCHEMA`` — per-run
samples (not just a mean), median/min/stdev, explicit warm-up vs measured
group counts, config fingerprint, engine, platform, packed-layout version,
and the host-span perf summary (occupancy, chunk-latency percentiles,
compile vs steady-state split).

Metric definition (BASELINE.md): quorum-rounds/sec/chip — each scheduler
tick advances every instance's consensus state machine by one protocol
round (deliver -> vote -> quorum-check), so throughput = instances x ticks
/ wall-clock.  North star: >= 10M at 1M concurrent instances on a v5e-1.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

NORTH_STAR = 10_000_000.0  # BASELINE.md north-star target

_ROOFLINE_MOD = None  # scripts/roofline.py, loaded once per process


def _tick_ops_per_lane(cfg, block: int) -> float:
    """Census op count (alu + codec_alu + reduce per lane-tick) for ``cfg``.

    Traced FRESH at bench time from the same ``tick_census`` the roofline
    artifact uses, so every row records the op count of the program it
    actually measured (ROOFLINE.json could be stale, and the flagship /
    CPU cases have no committed census entry).  This is the denominator of
    the VPU roofline — a bench-compare delta with an unchanged
    ``ops_per_lane_tick`` is clock/schedule, a changed one is an op-count
    cut (or regression).
    """
    global _ROOFLINE_MOD
    if _ROOFLINE_MOD is None:
        import importlib.util
        import pathlib

        path = pathlib.Path(__file__).resolve().parent / "scripts"
        spec = importlib.util.spec_from_file_location(
            "_bench_roofline_census", path / "roofline.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _ROOFLINE_MOD = mod
    census = _ROOFLINE_MOD.tick_census(cfg, block)
    return round(
        census["alu_per_lane_tick"]
        + census["codec_alu_per_lane_tick"]
        + census["reduce_per_lane_tick"],
        1,
    )


def _configs(platform: str):
    """The sweep table: (name, SimConfig, engine, chunk, depth) per case.

    TPU sizes match BASELINE.md's measured rows (1M instances).  The CPU
    rig shrinks instances and skips the fused engine (the Pallas TPU
    interpreter replays the stream bit-exactly but ~1000x slower — it is a
    correctness tool, not a benchmark path).

    Per-case chunk (ticks per device dispatch): protocol ticks do identical
    work regardless of chunking, so the measured-best chunk is used —
    dispatch boundaries through the axon tunnel cost ~10-17% at chunk 64
    (measured 2026-07-30: config2 321.8M @ 64 -> 378.1M @ 1024).  EXCEPT
    config3long, where chunk IS the compaction cadence (schedule-relevant:
    a bigger chunk leaves lanes idle at a full window, padding the metric
    with non-work ticks) — it stays at the run/soak operating default 64.

    Per-case depth (dispatch pipeline, harness.pipeline): the *-pipelined
    rows group 4 chunk-64 bodies per dispatch — the schedule of the chunk-64
    serial row (identical fingerprint AND identical stream) at a quarter of
    the dispatch count, which is how the chunk-boundary tax is recovered
    where the chunk size itself is schedule-relevant.  The chunk-64 serial
    config2 row sits alongside as the pipelined-vs-serial comparison pair.
    """
    import dataclasses

    from paxos_tpu.core.telemetry import TelemetryConfig
    from paxos_tpu.harness.config import (
        config2_dueling_drop,
        config3_long,
        config3_multipaxos,
        config5_sweep,
    )

    on_tpu = platform == "tpu"
    n = 1 << 20 if on_tpu else 1 << 13
    sweep = {c.protocol: c for c in config5_sweep(n_inst=n)}
    # Telemetry-overhead row: flagship config with the full flight recorder
    # on (counters + ring + histogram).  The recorder-OFF row above is the
    # one the perf gate bands at 0.7x — off must stay free (same schedule,
    # same fingerprint); this row measures what ON costs, for the README
    # overhead table.
    tel_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n),
        telemetry=TelemetryConfig(counters=True, ring_depth=64, hist_bins=16),
    )
    # Coverage-overhead row: flagship config with the on-device coverage
    # sketch on at the CLI default size (64 words = 2048 Bloom bits/lane).
    # Same contract as the telemetry row: OFF is gated free at 0.7x by the
    # base row; this row prices ON (two hash insertions + a popcount per
    # tick, plus 64 extra packed words per lane through the fused engine).
    from paxos_tpu.obs.coverage import CoverageConfig

    cov_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n), coverage=CoverageConfig(words=64)
    )
    # Exposure-overhead row: flagship config with the fault-exposure
    # counters on (6x2 packed int32 counters/lane through the generic
    # passthrough).  Same contract again: OFF is gated free by the base
    # row; this row prices ON (a handful of masked popcount-adds per tick)
    # and backs the README's "within 10%" acceptance claim.
    from paxos_tpu.obs.exposure import ExposureConfig

    exp_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n), exposure=ExposureConfig(counters=True)
    )
    # Margin-overhead row: flagship config with the safety-margin counters
    # on (4 packed int32 minima/counts per lane through the generic
    # passthrough).  Same contract again: OFF is gated free by the base
    # row; this row prices ON (masked min/count reductions over the
    # learner table the checker already scans).
    from paxos_tpu.obs.margin import MarginConfig

    mar_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n), margin=MarginConfig(counters=True)
    )
    # Workload-overhead row: flagship config with the client-workload
    # plane on (one arrival draw per tick plus the ring/histogram folds).
    # OFF is gated free by the base row; this row prices ON — the only
    # plane whose ON cost includes a PRNG draw.
    from paxos_tpu.workload.generator import WorkloadConfig

    wl_cfg = dataclasses.replace(
        config2_dueling_drop(n_inst=n),
        workload=WorkloadConfig(mix="mixed", rate=0.1),
    )
    cases = [
        ("config2-paxos", config2_dueling_drop(n_inst=n), 1024, 1),
        ("config2-paxos-telemetry", tel_cfg, 1024, 1),
        ("config2-paxos-coverage", cov_cfg, 1024, 1),
        ("config2-paxos-exposure", exp_cfg, 1024, 1),
        ("config2-paxos-margin", mar_cfg, 1024, 1),
        ("config2-paxos-workload", wl_cfg, 1024, 1),
        ("config5-fastpaxos", sweep["fastpaxos"], 256, 1),
        ("config5-raftcore", sweep["raftcore"], 256, 1),
        ("config3-multipaxos", config3_multipaxos(n_inst=n), 256, 1),
        # Long-log mode: 16-slot window sliding over a 256-slot log with
        # decided-prefix compaction at every chunk boundary (cost included).
        ("config3long-multipaxos", config3_long(n_inst=n), 64, 1),
        # Pipelined-vs-serial pair at the schedule-relevant operating chunk.
        ("config2-paxos-chunk64", config2_dueling_drop(n_inst=n), 64, 1),
        ("config2-paxos-chunk64-pipelined",
         config2_dueling_drop(n_inst=n), 64, 4),
        ("config3long-multipaxos-pipelined", config3_long(n_inst=n), 64, 4),
    ]
    engines = ("fused", "xla") if on_tpu else ("xla",)
    # The big-chunk win is the fused path's (dispatch amortization over a
    # VMEM-resident kernel); the XLA engine gains <2% from chunk 1024 while
    # its timed work grows 16x — XLA rows stay at 64 so the sweep and the
    # TPU perf gate finish in minutes.  The CPU rig caps everything at 64.
    def case_chunk(eng, chunk):
        return chunk if (on_tpu and eng == "fused") else min(chunk, 64)

    return [
        (name, cfg, eng, case_chunk(eng, chunk), depth)
        for name, cfg, chunk, depth in cases
        for eng in engines
    ]


def bench_case(
    cfg, engine: str, chunk: int = 64, timed_chunks: int = 4,
    repeats: int = 3, pipeline_depth: int = 1, warmup_groups: int = 1,
    profile_dir: "str | None" = None,
) -> dict:
    """Measure one (config, engine) case; returns the result dict.

    ``warmup_groups`` full groups run first — identical in shape to the
    timed groups (compile lands in the first one, cache warming in the
    rest) and recorded as ``warmup_runs`` so the steady-state bias is
    *visible* in the row instead of silently folded into the first timed
    sample.  Then ``repeats`` timed groups of ``timed_chunks`` chunks each
    are measured; ``value`` is the BEST group's throughput (the standard
    min-time discipline — noise on a shared tunnel only ever slows a run
    down) and ``samples`` records every group so a reader can judge the
    spread (``median``/``min``/``stdev`` summarize it for the
    ``bench-compare`` noise model).

    ``pipeline_depth`` groups that many chunk bodies per device dispatch
    (harness.pipeline) — same ticks, same schedule, 1/depth the dispatch
    count — and must divide ``timed_chunks`` so every timed group is a
    whole number of dispatches.

    ``profile_dir`` wraps the measured region in ``jax.profiler.trace``
    (XLA op/memory timelines, viewable in TensorBoard/Perfetto); the path
    is recorded in the row so the trace links back to its provenance.

    Every dispatch is also wrapped in a ``HostSpanRecorder`` span, and the
    row carries the derived ``obs.perf`` summary — bench is the one place
    where the perf plane is on by default.
    """
    import jax

    from paxos_tpu.harness.checkpoint import stream_id
    from paxos_tpu.harness.config import validate_pipeline_depth
    from paxos_tpu.harness.run import (
        init_plan,
        init_state,
        make_advance_grouped,
        make_longlog,
        summarize,
    )
    from paxos_tpu.harness.trace import profile
    from paxos_tpu.obs import perf as perf_mod
    from paxos_tpu.obs.host_spans import HostSpanRecorder

    depth = validate_pipeline_depth(pipeline_depth)
    if timed_chunks % depth:
        raise ValueError(
            f"timed_chunks={timed_chunks} must be a multiple of "
            f"pipeline_depth={depth} (whole dispatches per timed group)"
        )
    if warmup_groups < 1:
        raise ValueError("warmup_groups must be >= 1 (compile must land "
                         "outside the measured region)")
    platform = jax.devices()[0].platform
    state = init_state(cfg)
    plan = init_plan(cfg)
    # On-device footprint + the effective fused block, recorded in every
    # row so a packing regression (bytes creeping back up, block degrading)
    # shows in BENCH_* without re-running the roofline.  The bytes are what
    # THIS engine carries: packed codec words for fused rows, the unpacked
    # pytree for xla rows (which never packs).  eval_shape/leaf-shape based:
    # free, computed before the state is donated away.
    from paxos_tpu.kernels.fused_tick import fit_block, fused_fns
    from paxos_tpu.utils import bitops

    state_bytes = (
        bitops.codec_for(cfg.protocol, state).bytes_per_lane(state)
        if engine == "fused"
        else bitops.unpacked_bytes_per_lane(state)
    )
    sid = stream_id(cfg, engine)
    eff_block = (
        fit_block(sid["block"], cfg.n_inst, warn=False)
        if engine == "fused" else None
    )
    # Long-log: compaction rides in the timed loop (traced into each chunk).
    advance = make_advance_grouped(
        cfg, plan, engine, compact=bool(make_longlog(cfg))
    )

    ticks = timed_chunks * chunk
    rec = HostSpanRecorder(time.perf_counter)
    state_box = [state]
    done_ticks = [0]
    violations = [0]

    def one_group(samples: list) -> None:
        # NOTE: timing must end with a device->host readback, not
        # block_until_ready — on the axon tunnel backend block_until_ready
        # can return before execution finishes.
        st = state_box[0]
        t0 = time.perf_counter()
        for _ in range(timed_chunks // depth):
            with rec.span("dispatch", tick_start=done_ticks[0],
                          ticks=chunk * depth, groups=depth):
                st = advance(st, chunk, depth)
            done_ticks[0] += chunk * depth
        with rec.span("probe", tick=done_ticks[0]):
            violations[0] = int(st.learner.violations.sum())
        samples.append(cfg.n_inst * ticks / (time.perf_counter() - t0))
        state_box[0] = st

    # Warmup: groups identical in shape to the timed ones (satellite fix —
    # the old single-dispatch warmup left compile residue and cold caches
    # in the first timed sample).  Recorded, reported, never measured.
    warmup_runs: list = []
    for _ in range(warmup_groups):
        one_group(warmup_runs)

    runs: list = []
    with profile(profile_dir):
        for _ in range(max(repeats, 1)):
            one_group(runs)

    # Post-run measurement audit (outside the timed loop): summarize runs
    # the packed-ballot overflow guard, so a corrupted MP campaign raises
    # here instead of recording untrustworthy violation counts.
    summarize(state_box[0], log_total=cfg.fault.log_total)

    perf = perf_mod.perf_summary(rec, cfg.n_inst)
    if eff_block is not None:
        perf["vmem"] = perf_mod.vmem_gauges(state_bytes, eff_block)

    value = max(runs)
    row = {
        "schema": perf_mod.BENCH_ROW_SCHEMA,
        "metric": "quorum-rounds/sec/chip",
        "value": round(value, 1),
        "unit": "instance-rounds/sec",
        "vs_baseline": round(value / NORTH_STAR, 3),
        "samples": [round(r, 1) for r in runs],
        "median": round(statistics.median(runs), 1),
        "min": round(min(runs), 1),
        "stdev": round(statistics.stdev(runs), 1) if len(runs) > 1 else 0.0,
        "warmup_groups": warmup_groups,
        "timed_groups": len(runs),
        "warmup_runs": [round(r, 1) for r in warmup_runs],
        "n_instances": cfg.n_inst,
        "chunk": chunk,
        "pipeline_depth": depth,
        "ticks": ticks,
        "seconds": round(cfg.n_inst * ticks / value, 4),
        # Legacy alias for pre-schema artifact readers (r4-r9 perf gate).
        "throughput_runs": [round(r, 1) for r in runs],
        "platform": platform,
        "engine": engine,
        "protocol": cfg.protocol,
        "violations": violations[0],
        # v2 schema: the fused-tick census op count this row ran under
        # (XLA rows census at the protocol's default fused block — the op
        # count is a property of the tick program, not the engine).
        "ops_per_lane_tick": _tick_ops_per_lane(
            cfg,
            eff_block if eff_block is not None
            else fit_block(fused_fns(cfg.protocol)[2], cfg.n_inst,
                           warn=False),
        ),
        "state_bytes_per_lane": state_bytes,
        "block": eff_block,
        # Stream lineage (VERDICT r4 weak#3): the fused block this case ran
        # under — replays must match it or the schedule differs.
        "stream": sid,
        "layout_version": bitops.layout_version(cfg.protocol),
        "config_fingerprint": cfg.fingerprint(),
        "perf": perf,
    }
    if profile_dir:
        row["profile_dir"] = profile_dir
    return row


def _attach_roofline(row: dict, case_name: str) -> None:
    """Roofline occupancy vs the committed ROOFLINE.json census (TPU only).

    The census was measured at the flagship sizes, so the ceiling only
    means something when the row ran on the same platform; CPU rows and
    unknown cases pass through untouched.
    """
    import pathlib

    from paxos_tpu.obs import perf as perf_mod

    if row.get("platform") != "tpu":
        return
    path = pathlib.Path(__file__).resolve().parent / "ROOFLINE.json"
    if not path.exists():
        return
    roof = json.loads(path.read_text())
    case = next(
        (c for c in roof.get("cases", []) if c.get("case") == case_name), None
    )
    if case is None:
        return
    gauges = perf_mod.roofline_gauges(row["value"], case, roof)
    if gauges:
        row.setdefault("perf", {})["roofline"] = gauges


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sweep", action="store_true",
                    help="bench all protocols x engines (one JSON line each)")
    ap.add_argument("--record", metavar="PATH",
                    help="also write the measured rows (a JSON list) to PATH "
                    "— the artifact `paxos_tpu bench-compare` diffs against")
    ap.add_argument(
        "--pipeline-depth", type=int, default=None, metavar="K",
        help="flagship case only: chunks grouped per device dispatch "
        "(harness.pipeline; default 16 on TPU — 64-tick chunks in "
        "1024-tick dispatches, the measured-best dispatch size — else 4)",
    )
    ap.add_argument(
        "--n-inst", type=int, default=None, metavar="N",
        help="flagship case only: instance-count override (smoke tests "
        "shrink it; the recorded artifact uses the platform default)",
    )
    ap.add_argument(
        "--warmup-groups", type=int, default=1, metavar="W",
        help="unmeasured warm-up groups before the timed ones (default 1; "
        "each is shaped exactly like a timed group)",
    )
    ap.add_argument(
        "--profile-dir", metavar="DIR", default=None,
        help="flagship case only: wrap the measured region in "
        "jax.profiler.trace(DIR) and link DIR from the row",
    )
    args = ap.parse_args(argv)

    import jax

    # rbg is markedly faster than threefry on TPU for the per-tick mask
    # sampling; streams stay deterministic per (seed, tick) within the impl.
    jax.config.update("jax_default_prng_impl", "rbg")
    platform = jax.devices()[0].platform

    if args.sweep:
        results = []
        for name, cfg, engine, chunk, depth in _configs(platform):
            out = bench_case(cfg, engine, chunk=chunk, pipeline_depth=depth,
                             warmup_groups=args.warmup_groups)
            out["case"] = name
            _attach_roofline(out, name)
            results.append(out)
            print(json.dumps(out), flush=True)
        if args.record:
            with open(args.record, "w") as f:
                json.dump(results, f, indent=1)
        return

    from paxos_tpu.harness.config import config2_dueling_drop

    if args.n_inst is not None:
        n_inst = args.n_inst
    else:
        n_inst = 1 << 20 if platform != "cpu" else 1 << 14  # 1M on TPU
    cfg = config2_dueling_drop(n_inst=n_inst, seed=0)
    # Engine: the fused Pallas path (whole chunk resident in VMEM) on TPU;
    # the scanned XLA path on CPU (Mosaic doesn't target host CPUs).
    # Flagship dispatch shape: the OPERATING chunk of 64 (the run/soak and
    # long-log compaction cadence), pipelined --pipeline-depth chunks per
    # dispatch.  At the TPU default of 16 the dispatched program is
    # structurally the old chunk-1024 program — the dispatch-boundary tax
    # (~10-17% at serial chunk 64, see _configs) is recovered without
    # giving up the chunk-64 cadence.
    engine = "fused" if platform == "tpu" else "xla"
    depth = args.pipeline_depth
    if depth is None:
        depth = 16 if platform == "tpu" else 4
    row = bench_case(
        cfg, engine, chunk=64, timed_chunks=4 * depth, pipeline_depth=depth,
        warmup_groups=args.warmup_groups, profile_dir=args.profile_dir,
    )
    row["case"] = "config2-paxos-flagship"
    print(json.dumps(row))
    if args.record:
        with open(args.record, "w") as f:
            json.dump([row], f, indent=1)


if __name__ == "__main__":
    sys.exit(main())
