// Native differential oracle: event-driven single-decree Paxos in C++.
//
// Reference parity (SURVEY.md §3.1 native-code note, §5.2.1): the reference
// stack is pure Haskell — its "native runtime" is GHC itself — so the new
// framework's native tier is not a port but a TPU-adjacent toolchain piece:
// an independently written, sanitizer-friendly golden model that fuzzes the
// same protocol the JAX kernels implement, at millions of scheduler events
// per second on the host CPU.  It triangulates three implementations
// (C++ oracle, Python golden model, batched JAX kernels): all must satisfy
// agreement + validity on every seed.
//
// Deliberately mirrors the *semantics*, not the code, of
// paxos_tpu/cpu_ref/golden.py: asynchronous scheduler = seeded random choice
// among enabled events (deliver one in-flight message, or fire one proposer
// timeout), network = multiset with drop/duplicate faults, safety recomputed
// from the full accept-event history.
//
// Build: g++ -O2 -shared -fPIC -o libpaxos_oracle.so paxos_oracle.cc
// ABI: see run_batch / bench_steps at the bottom (plain C, ctypes-friendly).

#include <array>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

// splitmix64 + xorshift: tiny, seedable, independent of any Python RNG.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9e3779b97f4a7c15ull) {
    next();
    next();
  }
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // Uniform double in [0, 1).
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // Uniform int in [0, n).
  int below(int n) { return static_cast<int>(next() % static_cast<uint64_t>(n)); }
};

constexpr int kMaxProposers = 8;  // matches paxos_tpu.core.ballot.MAX_PROPOSERS
constexpr int kValueBase = 100;   // proposer p proposes kValueBase + p

inline int make_ballot(int rnd, int pid) { return rnd * kMaxProposers + pid + 1; }
inline int ballot_round(int bal) { return (bal - 1) / kMaxProposers; }

// Shared omniscient-oracle bookkeeping: a voter bitmask per
// (key, ballot, value) accept/commit event, where key is the log slot for
// Multi-Paxos and 0 for the single-decree protocols.  Only the
// bookkeeping is shared — each sim's protocol logic stays independent.
struct History {
  std::vector<int32_t> key, bal, val;
  std::vector<uint32_t> mask;

  void record(int acc, int32_t k, int32_t b, int32_t v) {
    for (size_t i = 0; i < bal.size(); ++i) {
      if (key[i] == k && bal[i] == b && val[i] == v) {
        mask[i] |= 1u << acc;
        return;
      }
    }
    key.push_back(k);
    bal.push_back(b);
    val.push_back(v);
    mask.push_back(1u << acc);
  }

  // Distinct (key, value) pairs among events passing the per-event
  // ``chosen(i)`` predicate, in first-chosen order (an A,B,A quorum-event
  // order yields two entries, not three).
  template <typename F>
  void distinct_chosen(F&& chosen, std::vector<int32_t>* out_key,
                       std::vector<int32_t>* out_val) const {
    for (size_t i = 0; i < bal.size(); ++i) {
      if (!chosen(i)) continue;
      bool seen = false;
      for (size_t j = 0; j < out_key->size() && !seen; ++j)
        seen = (*out_key)[j] == key[i] && (*out_val)[j] == val[i];
      if (!seen) {
        out_key->push_back(key[i]);
        out_val->push_back(val[i]);
      }
    }
  }
};

enum Kind : uint8_t { PREPARE, PROMISE, ACCEPT, ACCEPTED };

struct Msg {
  Kind kind;
  int8_t src;  // proposer id for requests, acceptor id for replies
  int8_t dst;
  int32_t bal;
  int32_t val;
  int32_t prev_bal;
  int32_t prev_val;
};

struct Acceptor {
  int32_t promised = 0;
  int32_t acc_bal = 0;
  int32_t acc_val = 0;
};

struct Proposer {
  enum Phase { P1, P2, DONE };
  int pid;
  int32_t own_val;
  int rnd = 0;
  int32_t bal;
  Phase phase = P1;
  uint32_t heard = 0;  // acceptor bitmask, like the device kernels
  int32_t best_bal = 0;
  int32_t best_val = 0;
  int32_t prop_val = 0;
  int32_t decided_val = -1;

  explicit Proposer(int p) : pid(p), own_val(kValueBase + p), bal(make_ballot(0, p)) {}
};

struct Result {
  int32_t decided;
  int32_t agreement_ok;
  int32_t validity_ok;
  int32_t n_chosen;
  int32_t steps;
};

struct Sim {
  int n_prop, n_acc, quorum;
  double p_drop, p_dup, timeout_weight;
  Rng rng;
  std::vector<Acceptor> acceptors;
  std::vector<Proposer> proposers;
  std::vector<Msg> network;
  History hist;  // accept events keyed (0, ballot, value)

  Sim(uint64_t seed, int np, int na, double pd, double pdup, double tw)
      : n_prop(np), n_acc(na), quorum(na / 2 + 1), p_drop(pd), p_dup(pdup),
        timeout_weight(tw), rng(seed) {
    acceptors.resize(n_acc);
    for (int p = 0; p < n_prop; ++p) proposers.emplace_back(p);
    for (auto& p : proposers) broadcast(p, PREPARE);
  }

  void offer(const Msg& m) {
    if (rng.uniform() >= p_drop) network.push_back(m);
  }

  void broadcast(Proposer& p, Kind kind) {
    for (int a = 0; a < n_acc; ++a) {
      offer(Msg{kind, static_cast<int8_t>(p.pid), static_cast<int8_t>(a), p.bal,
                p.prop_val, 0, 0});
    }
  }

  void dispatch(const Msg& m) {
    switch (m.kind) {
      case PREPARE: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal > a.promised) {
          a.promised = m.bal;
          offer(Msg{PROMISE, m.dst, m.src, m.bal, 0, a.acc_bal, a.acc_val});
        }
        break;
      }
      case ACCEPT: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal >= a.promised) {
          a.promised = a.promised > m.bal ? a.promised : m.bal;
          a.acc_bal = m.bal;
          a.acc_val = m.val;
          hist.record(m.dst, 0, m.bal, m.val);
          offer(Msg{ACCEPTED, m.dst, m.src, m.bal, m.val, 0, 0});
        }
        break;
      }
      case PROMISE: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::P1 || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        if (m.prev_bal > p.best_bal) {
          p.best_bal = m.prev_bal;
          p.best_val = m.prev_val;
        }
        if (__builtin_popcount(p.heard) >= quorum) {
          p.phase = Proposer::P2;
          p.heard = 0;
          p.prop_val = p.best_bal > 0 ? p.best_val : p.own_val;
          broadcast(p, ACCEPT);
        }
        break;
      }
      case ACCEPTED: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::P2 || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        if (__builtin_popcount(p.heard) >= quorum) {
          p.phase = Proposer::DONE;
          p.decided_val = p.prop_val;
        }
        break;
      }
    }
  }

  bool all_done() const {
    for (const auto& p : proposers)
      if (p.phase != Proposer::DONE) return false;
    return true;
  }

  Result run(int max_steps) {
    int steps = 0;
    while (steps < max_steps && !all_done()) {
      ++steps;
      if (!network.empty() && rng.uniform() >= timeout_weight) {
        int i = rng.below(static_cast<int>(network.size()));
        Msg m = network[i];
        if (rng.uniform() >= p_dup) {  // not duplicated: consume the slot
          network[i] = network.back();
          network.pop_back();
        }
        dispatch(m);
      } else {
        // Fire one live proposer's timeout.
        int live = 0;
        for (const auto& p : proposers) live += p.phase != Proposer::DONE;
        if (live == 0) break;
        int pick = rng.below(live);
        for (auto& p : proposers) {
          if (p.phase == Proposer::DONE) continue;
          if (pick-- == 0) {
            ++p.rnd;
            p.bal = make_ballot(p.rnd, p.pid);
            p.phase = Proposer::P1;
            p.heard = 0;
            p.best_bal = p.best_val = 0;
            broadcast(p, PREPARE);
            break;
          }
        }
      }
    }

    // Omniscient oracle: distinct chosen values over the accept history.
    std::vector<int32_t> ck, cv;
    hist.distinct_chosen(
        [&](size_t i) { return __builtin_popcount(hist.mask[i]) >= quorum; },
        &ck, &cv);
    int n_chosen = static_cast<int>(cv.size());
    int32_t chosen_val = cv.empty() ? -1 : cv.back();
    bool validity = true;
    for (int32_t v : cv)
      validity &= v >= kValueBase && v < kValueBase + n_prop;
    bool agreement = n_chosen <= 1;
    for (const auto& p : proposers) {
      if (p.decided_val >= 0)
        agreement &= n_chosen == 1 && p.decided_val == chosen_val;
    }
    return Result{all_done() ? 1 : 0, agreement ? 1 : 0, validity ? 1 : 0,
                  n_chosen, steps};
  }
};

// ---------------------------------------------------------------------------
// Multi-Paxos oracle (round-2: second protocol so triangulation isn't
// single-protocol).  Mirrors the SEMANTICS of
// paxos_tpu/protocols/multipaxos.py — whole-log phase 1 (promises carry the
// full accepted log), slot-by-slot phase 2, leader preemption via timeout
// events — under this file's own event-driven scheduler.  Tick-based leases
// don't exist here: preemption timeouts subsume them (the lease only decides
// WHEN a follower challenges; safety must hold for ANY challenge schedule,
// which is exactly what random timeout events explore).
// ---------------------------------------------------------------------------

namespace mp {

constexpr int kMaxLog = 32;

inline int32_t own_slot_value(int pid, int slot) {
  return (pid + 1) * 1000 + slot;  // multipaxos.own_slot_value
}

enum Kind : uint8_t { PREPARE, PROMISE, ACCEPT, ACCEPTED };

struct Msg {
  Kind kind;
  int8_t src;
  int8_t dst;
  int32_t bal;
  int32_t slot;
  int32_t val;
  int32_t log_bal[kMaxLog];  // PROMISE payload: full accepted log snapshot
  int32_t log_val[kMaxLog];
};

struct Acceptor {
  int32_t promised = 0;
  int32_t log_bal[kMaxLog] = {};
  int32_t log_val[kMaxLog] = {};
};

struct Proposer {
  enum Phase { FOLLOW, CAND, LEAD, DONE };
  int pid;
  int rnd = 0;
  int32_t bal = 0;
  Phase phase = FOLLOW;
  uint32_t heard = 0;
  int commit_idx = 0;
  int32_t recov_bal[kMaxLog] = {};
  int32_t recov_val[kMaxLog] = {};
  int32_t decided[kMaxLog] = {};

  explicit Proposer(int p) : pid(p) {}
};

struct Sim {
  int n_prop, n_acc, log_len, quorum;
  double p_drop, p_dup, timeout_weight;
  Rng rng;
  std::vector<Acceptor> acceptors;
  std::vector<Proposer> proposers;
  std::vector<Msg> network;
  History hist;  // accept events keyed (slot, ballot, value)

  Sim(uint64_t seed, int np, int na, int ll, double pd, double pdup, double tw)
      : n_prop(np), n_acc(na), log_len(ll), quorum(na / 2 + 1), p_drop(pd),
        p_dup(pdup), timeout_weight(tw), rng(seed ^ 0xa5a5a5a5ull) {
    acceptors.resize(n_acc);
    for (int p = 0; p < n_prop; ++p) proposers.emplace_back(p);
  }

  void offer(const Msg& m) {
    if (rng.uniform() >= p_drop) network.push_back(m);
  }

  void drive_slot(Proposer& p) {  // broadcast ACCEPT for the current slot
    if (p.commit_idx >= log_len) {
      p.phase = Proposer::DONE;
      return;
    }
    int s = p.commit_idx;
    int32_t v = p.recov_bal[s] > 0 ? p.recov_val[s] : own_slot_value(p.pid, s);
    for (int a = 0; a < n_acc; ++a) {
      Msg m{};
      m.kind = ACCEPT;
      m.src = static_cast<int8_t>(p.pid);
      m.dst = static_cast<int8_t>(a);
      m.bal = p.bal;
      m.slot = s;
      m.val = v;
      offer(m);
    }
  }

  void dispatch(const Msg& m) {
    switch (m.kind) {
      case PREPARE: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal > a.promised) {
          a.promised = m.bal;
          Msg r{};
          r.kind = PROMISE;
          r.src = m.dst;
          r.dst = m.src;
          r.bal = m.bal;
          std::memcpy(r.log_bal, a.log_bal, sizeof(a.log_bal));
          std::memcpy(r.log_val, a.log_val, sizeof(a.log_val));
          offer(r);
        }
        break;
      }
      case ACCEPT: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal >= a.promised) {
          a.promised = a.promised > m.bal ? a.promised : m.bal;
          a.log_bal[m.slot] = m.bal;
          a.log_val[m.slot] = m.val;
          hist.record(m.dst, m.slot, m.bal, m.val);
          Msg r{};
          r.kind = ACCEPTED;
          r.src = m.dst;
          r.dst = m.src;
          r.bal = m.bal;
          r.slot = m.slot;
          r.val = m.val;
          offer(r);
        }
        break;
      }
      case PROMISE: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::CAND || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        // Whole-log recovery: per-slot max-ballot fold over promises.
        for (int s = 0; s < log_len; ++s) {
          if (m.log_bal[s] > p.recov_bal[s]) {
            p.recov_bal[s] = m.log_bal[s];
            p.recov_val[s] = m.log_val[s];
          }
        }
        if (__builtin_popcount(p.heard) >= quorum) {
          p.phase = Proposer::LEAD;
          p.heard = 0;
          p.commit_idx = 0;
          drive_slot(p);
        }
        break;
      }
      case ACCEPTED: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::LEAD || m.bal != p.bal ||
            m.slot != p.commit_idx)
          break;
        p.heard |= 1u << m.src;
        if (__builtin_popcount(p.heard) >= quorum) {
          p.decided[p.commit_idx] = m.val;
          p.heard = 0;
          ++p.commit_idx;
          drive_slot(p);
        }
        break;
      }
    }
  }

  bool any_done() const {
    for (const auto& p : proposers)
      if (p.phase == Proposer::DONE) return true;
    return false;
  }

  Result run(int max_steps) {
    int steps = 0;
    while (steps < max_steps && !any_done()) {
      ++steps;
      if (!network.empty() && rng.uniform() >= timeout_weight) {
        int i = rng.below(static_cast<int>(network.size()));
        Msg m = network[i];
        if (rng.uniform() >= p_dup) {
          network[i] = network.back();
          network.pop_back();
        }
        dispatch(m);
      } else {
        // Preemption/lease surrogate: any non-DONE proposer may challenge
        // with the next ballot (a LEAD proposer re-elects itself too —
        // harmless, and it models a stale leader recovering leadership).
        int live = 0;
        for (const auto& p : proposers) live += p.phase != Proposer::DONE;
        if (live == 0) break;
        int pick = rng.below(live);
        for (auto& p : proposers) {
          if (p.phase == Proposer::DONE) continue;
          if (pick-- == 0) {
            ++p.rnd;
            p.bal = make_ballot(p.rnd, p.pid);
            p.phase = Proposer::CAND;
            p.heard = 0;
            for (int s = 0; s < log_len; ++s)
              p.recov_bal[s] = p.recov_val[s] = 0;
            for (int a = 0; a < n_acc; ++a) {
              Msg m{};
              m.kind = PREPARE;
              m.src = static_cast<int8_t>(p.pid);
              m.dst = static_cast<int8_t>(a);
              m.bal = p.bal;
              offer(m);
            }
            break;
          }
        }
      }
    }

    // Omniscient per-slot oracle: distinct chosen values per slot.
    std::vector<int32_t> ck, cv;
    hist.distinct_chosen(
        [&](size_t i) { return __builtin_popcount(hist.mask[i]) >= quorum; },
        &ck, &cv);
    int32_t chosen_val[kMaxLog];
    int chosen_cnt[kMaxLog] = {};
    bool validity = true;
    int slots_chosen = 0;
    for (size_t i = 0; i < ck.size(); ++i) {
      int s = ck[i];
      ++chosen_cnt[s];
      chosen_val[s] = cv[i];
      // Validity: some proposer proposes this value FOR THIS SLOT.
      validity &= cv[i] % 1000 == s && cv[i] / 1000 >= 1 &&
                  cv[i] / 1000 <= n_prop;
    }
    bool agreement = true;
    for (int s = 0; s < log_len; ++s) {
      agreement &= chosen_cnt[s] <= 1;
      slots_chosen += chosen_cnt[s] >= 1;
    }
    // A DONE proposer's decided log must match the chosen values exactly.
    for (const auto& p : proposers) {
      if (p.phase != Proposer::DONE) continue;
      for (int s = 0; s < log_len; ++s)
        agreement &= chosen_cnt[s] == 1 && p.decided[s] == chosen_val[s];
    }
    return Result{any_done() ? 1 : 0, agreement ? 1 : 0, validity ? 1 : 0,
                  slots_chosen, steps};
  }
};

}  // namespace mp

// ---------------------------------------------------------------------------
// Fast Paxos oracle (round-3: third protocol — the subtlest recovery logic).
// Mirrors the SEMANTICS of paxos_tpu/protocols/fastpaxos.py: a shared
// round-0 fast ballot every proposer's Accept(own_val) rides immediately
// (no phase 1), vote-at-most-once-per-ballot acceptors, fast-quorum
// (default ceil(3n/4)) choice at round 0, and coordinated recovery in
// classic rounds >= 1 — a value v is CHOOSABLE at the highest reported
// ballot k iff the acceptors that reported voting v at k plus those not
// heard from could still contain a fast quorum; if some value is choosable
// the recovering proposer must adopt it (lowest value id on ties, matching
// the kernel's first_true pick), else its own value is safe.  Fast
// Flexible Paxos (arXiv:2008.02671) quorum overrides q1/q2/q_fast are
// supported; 0 = classic defaults.  Unsafe triples are the bug-injection
// leg: the oracle itself must then FIND agreement violations.
// ---------------------------------------------------------------------------

namespace fp {

enum Kind : uint8_t { PREPARE, PROMISE, ACCEPT, ACCEPTED };

struct Msg {
  Kind kind;
  int8_t src;
  int8_t dst;
  int32_t bal;
  int32_t val;
  int32_t prev_bal;  // PROMISE payload: acceptor's accepted pair
  int32_t prev_val;
};

struct Acceptor {
  int32_t promised = 0;
  int32_t acc_bal = 0;
  int32_t acc_val = 0;
};

struct Proposer {
  enum Phase { P1, P2, DONE, FAST };  // matches core/fp_state.py
  int pid;
  int32_t own_val;
  int32_t bal;
  Phase phase = FAST;
  uint32_t heard = 0;
  int32_t best_bal = 0;
  uint32_t rep_mask[kMaxProposers] = {};  // per-value-id voter bitmasks
  int32_t prop_val = 0;
  int32_t decided_val = -1;

  explicit Proposer(int p)
      : pid(p), own_val(kValueBase + p), bal(make_ballot(0, 0)) {}
};

struct Sim {
  int n_prop, n_acc, q1, q2, qf;
  double p_drop, p_dup, timeout_weight;
  Rng rng;
  std::vector<Acceptor> acceptors;
  std::vector<Proposer> proposers;
  std::vector<Msg> network;
  History hist;  // accept events keyed (0, ballot, value)

  Sim(uint64_t seed, int np, int na, int q1_, int q2_, int qf_, double pd,
      double pdup, double tw)
      : n_prop(np), n_acc(na), q1(q1_ ? q1_ : na / 2 + 1),
        q2(q2_ ? q2_ : na / 2 + 1), qf(qf_ ? qf_ : (3 * na + 3) / 4),
        p_drop(pd), p_dup(pdup), timeout_weight(tw),
        rng(seed ^ 0x5bd1e995ull) {
    acceptors.resize(n_acc);
    for (int p = 0; p < n_prop; ++p) proposers.emplace_back(p);
    // The fast round is in flight at step 0 (core/fp_state.py init).
    for (auto& p : proposers) {
      for (int a = 0; a < n_acc; ++a) {
        offer(Msg{ACCEPT, static_cast<int8_t>(p.pid), static_cast<int8_t>(a),
                  p.bal, p.own_val, 0, 0});
      }
    }
  }

  void offer(const Msg& m) {
    if (rng.uniform() >= p_drop) network.push_back(m);
  }

  void dispatch(const Msg& m) {
    switch (m.kind) {
      case PREPARE: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal > a.promised) {
          a.promised = m.bal;
          offer(Msg{PROMISE, m.dst, m.src, m.bal, 0, a.acc_bal, a.acc_val});
        }
        break;
      }
      case ACCEPT: {
        Acceptor& a = acceptors[m.dst];
        // Vote at most once per ballot: never switch values within a round
        // (re-accepting the identical pair stays idempotent for dups).
        bool revote = m.bal > a.acc_bal ||
                      (m.bal == a.acc_bal && m.val == a.acc_val);
        if (m.bal >= a.promised && revote) {
          a.promised = a.promised > m.bal ? a.promised : m.bal;
          a.acc_bal = m.bal;
          a.acc_val = m.val;
          hist.record(m.dst, 0, m.bal, m.val);
          offer(Msg{ACCEPTED, m.dst, m.src, m.bal, m.val, 0, 0});
        }
        break;
      }
      case PROMISE: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::P1 || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        // Per-value voter masks at the highest reported accepted ballot.
        bool valid = m.prev_bal > 0 && m.prev_val >= kValueBase &&
                     m.prev_val < kValueBase + n_prop;
        if (valid) {
          if (m.prev_bal > p.best_bal) {
            p.best_bal = m.prev_bal;
            for (int v = 0; v < kMaxProposers; ++v) p.rep_mask[v] = 0;
          }
          if (m.prev_bal == p.best_bal)
            p.rep_mask[m.prev_val - kValueBase] |= 1u << m.src;
        }
        if (__builtin_popcount(p.heard) >= q1) {
          int unheard = n_acc - __builtin_popcount(p.heard);
          int32_t v = p.own_val;
          if (p.best_bal > 0) {
            if (ballot_round(p.best_bal) == 0) {
              // k fast: adopt the (lowest-vid) choosable value if any.
              for (int vid = 0; vid < n_prop; ++vid) {
                if (p.rep_mask[vid] != 0 &&
                    __builtin_popcount(p.rep_mask[vid]) + unheard >= qf) {
                  v = kValueBase + vid;
                  break;
                }
              }
            } else {
              // k classic: adopt k's (unique) value.
              for (int vid = 0; vid < n_prop; ++vid) {
                if (p.rep_mask[vid] != 0) {
                  v = kValueBase + vid;
                  break;
                }
              }
            }
          }
          p.phase = Proposer::P2;
          p.heard = 0;
          p.prop_val = v;
          for (int a = 0; a < n_acc; ++a) {
            offer(Msg{ACCEPT, static_cast<int8_t>(p.pid),
                      static_cast<int8_t>(a), p.bal, v, 0, 0});
          }
        }
        break;
      }
      case ACCEPTED: {
        Proposer& p = proposers[m.dst];
        bool in_vote = p.phase == Proposer::P2 || p.phase == Proposer::FAST;
        if (!in_vote || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        int need = p.phase == Proposer::FAST ? qf : q2;
        if (__builtin_popcount(p.heard) >= need) {
          p.decided_val =
              p.phase == Proposer::FAST ? p.own_val : p.prop_val;
          p.phase = Proposer::DONE;
        }
        break;
      }
    }
  }

  bool all_done() const {
    for (const auto& p : proposers)
      if (p.phase != Proposer::DONE) return false;
    return true;
  }

  Result run(int max_steps) {
    int steps = 0;
    while (steps < max_steps && !all_done()) {
      ++steps;
      if (!network.empty() && rng.uniform() >= timeout_weight) {
        int i = rng.below(static_cast<int>(network.size()));
        Msg m = network[i];
        if (rng.uniform() >= p_dup) {
          network[i] = network.back();
          network.pop_back();
        }
        dispatch(m);
      } else {
        // Collision/loss recovery: a non-DONE proposer abandons its round
        // and starts a classic round at the next ballot.
        int live = 0;
        for (const auto& p : proposers) live += p.phase != Proposer::DONE;
        if (live == 0) break;
        int pick = rng.below(live);
        for (auto& p : proposers) {
          if (p.phase == Proposer::DONE) continue;
          if (pick-- == 0) {
            p.bal = make_ballot(ballot_round(p.bal) + 1, p.pid);
            p.phase = Proposer::P1;
            p.heard = 0;
            p.best_bal = 0;
            for (int v = 0; v < kMaxProposers; ++v) p.rep_mask[v] = 0;
            for (int a = 0; a < n_acc; ++a) {
              offer(Msg{PREPARE, static_cast<int8_t>(p.pid),
                        static_cast<int8_t>(a), p.bal, 0, 0, 0});
            }
            break;
          }
        }
      }
    }

    // Omniscient oracle: the choice threshold is per-round-kind (q_fast
    // for the fast round 0, q2 for classic rounds); distinct chosen values.
    std::vector<int32_t> ck, cv;
    hist.distinct_chosen(
        [&](size_t i) {
          int need = ballot_round(hist.bal[i]) == 0 ? qf : q2;
          return __builtin_popcount(hist.mask[i]) >= need;
        },
        &ck, &cv);
    int n_chosen = static_cast<int>(cv.size());
    int32_t chosen_val = cv.empty() ? -1 : cv.back();
    bool validity = true;
    for (int32_t v : cv)
      validity &= v >= kValueBase && v < kValueBase + n_prop;
    bool agreement = n_chosen <= 1;
    for (const auto& p : proposers) {
      if (p.decided_val >= 0)
        agreement &= n_chosen == 1 && p.decided_val == chosen_val;
    }
    return Result{all_done() ? 1 : 0, agreement ? 1 : 0, validity ? 1 : 0,
                  n_chosen, steps};
  }
};

}  // namespace fp

// ---------------------------------------------------------------------------
// Raft-core oracle (round-3: fourth protocol — the native matrix is square).
// Mirrors the SEMANTICS of paxos_tpu/protocols/raftcore.py: leader election
// with the election restriction (grant iff the candidate's entry term is at
// least the voter's), one vote per term (strictly increasing grants; the
// vote fence also rises on accepted appends), entry adoption from vote
// replies (grants AND denials carry the voter's entry; the candidate keeps
// the highest-term one across retries), and single-entry commit on a
// majority of acks at the leader's term.  ``no_restriction`` /
// ``no_adoption`` disable one safety leg each — the exhaustive checker
// proved either alone suffices and both off violates; this oracle is the
// event-driven falsifiability counterpart of that result.
// ---------------------------------------------------------------------------

namespace raft {

enum Kind : uint8_t { REQVOTE, VOTE, APPEND, ACK };

struct Msg {
  Kind kind;
  int8_t src;
  int8_t dst;
  int32_t term;
  int32_t granted;   // VOTE: 1 = granted
  int32_t ent_term;  // REQVOTE: candidate's entry term; VOTE: voter's entry
  int32_t ent_val;   // VOTE payload / APPEND value
};

struct Voter {
  int32_t voted = 0;  // highest term granted or appended (the vote fence)
  int32_t ent_term = 0;
  int32_t ent_val = 0;
};

struct Cand {
  enum Phase { CAND, LEAD, DONE };
  int pid;
  int32_t own_val;
  int32_t bal;
  Phase phase = CAND;
  uint32_t heard = 0;
  int32_t ent_term = 0;  // adopted entry (kept across retries)
  int32_t ent_val = 0;
  int32_t prop_val = 0;
  int32_t decided_val = -1;

  explicit Cand(int p)
      : pid(p), own_val(kValueBase + p), bal(make_ballot(0, p)) {}
};

struct Sim {
  int n_prop, n_acc, quorum;
  bool no_restriction, no_adoption;
  double p_drop, p_dup, timeout_weight;
  Rng rng;
  std::vector<Voter> voters;
  std::vector<Cand> cands;
  std::vector<Msg> network;
  History hist;  // append-accept events keyed (0, term, value)

  Sim(uint64_t seed, int np, int na, bool norestr, bool noadopt, double pd,
      double pdup, double tw)
      : n_prop(np), n_acc(na), quorum(na / 2 + 1), no_restriction(norestr),
        no_adoption(noadopt), p_drop(pd), p_dup(pdup), timeout_weight(tw),
        rng(seed ^ 0xc3a5c85c97cb3127ull) {
    voters.resize(n_acc);
    for (int p = 0; p < n_prop; ++p) cands.emplace_back(p);
    for (auto& c : cands) request_votes(c);
  }

  void offer(const Msg& m) {
    if (rng.uniform() >= p_drop) network.push_back(m);
  }

  void request_votes(Cand& c) {
    for (int a = 0; a < n_acc; ++a) {
      offer(Msg{REQVOTE, static_cast<int8_t>(c.pid), static_cast<int8_t>(a),
                c.bal, 0, c.ent_term, 0});
    }
  }

  void dispatch(const Msg& m) {
    switch (m.kind) {
      case REQVOTE: {
        Voter& v = voters[m.dst];
        bool restrict_ok = no_restriction || m.ent_term >= v.ent_term;
        bool grant = m.term > v.voted && restrict_ok;
        if (grant) v.voted = m.term;
        // Replies go out for grants AND denials, carrying the voter's
        // (pre-update — unchanged by REQVOTE) entry.
        offer(Msg{VOTE, m.dst, m.src, m.term, grant ? 1 : 0, v.ent_term,
                  v.ent_val});
        break;
      }
      case VOTE: {
        Cand& c = cands[m.dst];
        if (c.phase != Cand::CAND || m.term != c.bal) break;
        if (!no_adoption && m.ent_term > c.ent_term) {
          c.ent_term = m.ent_term;
          c.ent_val = m.ent_val;
        }
        if (m.granted) c.heard |= 1u << m.src;
        if (__builtin_popcount(c.heard) >= quorum) {
          int32_t val = c.ent_term > 0 ? c.ent_val : c.own_val;
          c.phase = Cand::LEAD;
          c.heard = 0;
          c.prop_val = val;
          c.ent_term = c.bal;  // the leader's proposal is its own entry now
          c.ent_val = val;
          for (int a = 0; a < n_acc; ++a) {
            offer(Msg{APPEND, static_cast<int8_t>(c.pid),
                      static_cast<int8_t>(a), c.bal, 0, 0, val});
          }
        }
        break;
      }
      case APPEND: {
        Voter& v = voters[m.dst];
        if (m.term >= v.voted) {
          v.voted = m.term;  // >= v.voted by the guard
          v.ent_term = m.term;
          v.ent_val = m.ent_val;
          hist.record(m.dst, 0, m.term, m.ent_val);
          offer(Msg{ACK, m.dst, m.src, m.term, 0, 0, 0});
        }
        break;
      }
      case ACK: {
        Cand& c = cands[m.dst];
        if (c.phase != Cand::LEAD || m.term != c.bal) break;
        c.heard |= 1u << m.src;
        if (__builtin_popcount(c.heard) >= quorum) {
          c.phase = Cand::DONE;
          c.decided_val = c.prop_val;
        }
        break;
      }
    }
  }

  bool all_done() const {
    for (const auto& c : cands)
      if (c.phase != Cand::DONE) return false;
    return true;
  }

  Result run(int max_steps) {
    int steps = 0;
    while (steps < max_steps && !all_done()) {
      ++steps;
      if (!network.empty() && rng.uniform() >= timeout_weight) {
        int i = rng.below(static_cast<int>(network.size()));
        Msg m = network[i];
        if (rng.uniform() >= p_dup) {
          network[i] = network.back();
          network.pop_back();
        }
        dispatch(m);
      } else {
        // Election timeout: a non-DONE candidate (a stale leader included)
        // runs at the next term, keeping its adopted entry.
        int live = 0;
        for (const auto& c : cands) live += c.phase != Cand::DONE;
        if (live == 0) break;
        int pick = rng.below(live);
        for (auto& c : cands) {
          if (c.phase == Cand::DONE) continue;
          if (pick-- == 0) {
            c.bal = make_ballot(ballot_round(c.bal) + 1, c.pid);
            c.phase = Cand::CAND;
            c.heard = 0;
            request_votes(c);
            break;
          }
        }
      }
    }

    // Omniscient oracle: distinct committed values over the append-accept
    // history at majority quorums.
    //
    // "Majority-accepted at a term" is NOT a stable commit point in general
    // Raft (Figure 8: a majority-replicated entry from an old term can be
    // overwritten before a new-term entry commits on top of it).  It IS
    // stable in this single-entry model, and only because of the
    // restriction/adoption interplay the exhaustive checker mechanizes
    // (cpu_ref/raft_exhaustive.py, BASELINE.md raftcore decomposition row):
    // a voter that accepted (t, v) refuses RequestVote to any candidate
    // whose last-accepted term is lower (election restriction), so a
    // candidate that wins a majority with a single entry majority-accepted
    // at term t must have intersected that majority and therefore ADOPTS
    // (t, v) as its own entry — there is no "commit on top" step that could
    // race, because there is exactly one slot.  Do not copy this chosen
    // predicate into a multi-entry context unchanged; there the commit
    // point is the leader's commitIndex advance over its OWN term.  Under
    // the bug-injection legs (restriction/adoption disabled) a flagged
    // "violation" may thus be a majority-accepted-then-superseded entry
    // rather than two actually-committed values — which is exactly the
    // hazard those legs exist to demonstrate.
    std::vector<int32_t> ck, cv;
    hist.distinct_chosen(
        [&](size_t i) { return __builtin_popcount(hist.mask[i]) >= quorum; },
        &ck, &cv);
    int n_chosen = static_cast<int>(cv.size());
    int32_t chosen_val = cv.empty() ? -1 : cv.back();
    bool validity = true;
    for (int32_t v : cv)
      validity &= v >= kValueBase && v < kValueBase + n_prop;
    bool agreement = n_chosen <= 1;
    for (const auto& c : cands) {
      if (c.decided_val >= 0)
        agreement &= n_chosen == 1 && c.decided_val == chosen_val;
    }
    return Result{all_done() ? 1 : 0, agreement ? 1 : 0, validity ? 1 : 0,
                  n_chosen, steps};
  }
};

}  // namespace raft

// ---------------------------------------------------------------------------
// Native bounded exhaustive explorer (VERDICT r3 #4).
//
// The Python checkers (cpu_ref/exhaustive.py) are the binding constraint on
// verification depth: the deepest recorded bound (30M states) took 2.6 h of
// single-core Python.  This explorer ports the BFS/dedup core to C++ for
// classic Paxos, mirroring the Python transition system EXACTLY — same
// ballot packing, same deliver/timeout actions, same GC reductions (incl.
// their unsafe_accept carve-outs), same invariants — so distinct-state
// counts cross-validate bit-for-bit at shared bounds
// (tests/test_native_oracle.py: 602,641 at (2,3) retries<=1; 5,804,454 at
// retries (2,1)).
//
// State identity: canonical serialization (sorted net multiset, voters
// sorted by (ballot, value) — the same canonical orders the Python tuples
// use) deduplicated via 128-bit fingerprints in an open-addressing table.
// Fingerprinting is the one deliberate divergence from Python's exact-set
// semantics: at N explored states the expected collision count is
// N^2 / 2^129 (~1e-21 at 1e9 states), and a collision can only UNDERCOUNT
// by one state, never fabricate a violation — acceptable for pushing
// bounds 10-100x deeper, and the cross-validated small bounds confirm
// zero drift in practice.
//
// Counterexample TRACES stay the Python checker's job (it keeps the full
// action trace per stack entry); this explorer reports existence — the
// falsifiability contract is that unsafe_accept finds a violation at the
// same bounds Python does.

namespace px_explore {

constexpr int kMaxAccE = 8;   // heard/voter masks are uint8_t
constexpr int kMaxPropE = 4;  // explorer bound (Python allows 8; 2-3 used)
constexpr int P1 = 0, P2 = 1, PDONE = 2;

// Serialized-state layout (all fields fit uint8_t: ballots rnd*8+pid+1 with
// rnd <= 30, values 100+pid <= 103, masks over <= 8 acceptors):
//   acc[n_acc][3]  promised, acc_bal, acc_val
//   prop[n_prop][7] phase, rnd, heard, best_bal, best_val, prop_val, decided
//   nv, voters[nv][3]  bal, val, mask   (sorted by (bal, val))
//   nm, net[nm][6]  kind, src, dst, bal, v1, v2  (sorted lexicographically)
struct EState {
  uint8_t acc[kMaxAccE][3];
  uint8_t prop[kMaxPropE][7];
  std::vector<std::array<uint8_t, 3>> voters;
  std::vector<std::array<uint8_t, 6>> net;
};

struct ECfg {
  int n_prop, n_acc, quorum;
  int max_round[kMaxPropE];
  bool unsafe_accept;
};

inline void serialize(const ECfg& c, const EState& s, std::vector<uint8_t>* out) {
  out->clear();
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) out->push_back(s.acc[a][f]);
  for (int p = 0; p < c.n_prop; ++p)
    for (int f = 0; f < 7; ++f) out->push_back(s.prop[p][f]);
  // u16 counts: the API's bound-validated worst case (n_prop=4, n_acc=8,
  // max_round=29) can hold hundreds of undelivered PREPAREs, which a u8
  // count would silently wrap — corrupting state identity.
  out->push_back(static_cast<uint8_t>(s.voters.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.voters.size() >> 8));
  for (const auto& v : s.voters) out->insert(out->end(), v.begin(), v.end());
  out->push_back(static_cast<uint8_t>(s.net.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.net.size() >> 8));
  for (const auto& m : s.net) out->insert(out->end(), m.begin(), m.end());
}

inline void deserialize(const ECfg& c, const uint8_t* b, EState* s) {
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) s->acc[a][f] = *b++;
  for (int p = 0; p < c.n_prop; ++p)
    for (int f = 0; f < 7; ++f) s->prop[p][f] = *b++;
  int nv = b[0] | (b[1] << 8);
  b += 2;
  s->voters.assign(nv, {});
  for (int i = 0; i < nv; ++i) {
    std::memcpy(s->voters[i].data(), b, 3);
    b += 3;
  }
  int nm = b[0] | (b[1] << 8);
  b += 2;
  s->net.assign(nm, {});
  for (int i = 0; i < nm; ++i) {
    std::memcpy(s->net[i].data(), b, 6);
    b += 6;
  }
}

// 128-bit fingerprint: two independent 64-bit mix chains (splitmix-style
// avalanche per 8-byte word, distinct seeds).
struct Fp128 {
  uint64_t hi, lo;
};

inline uint64_t mix64(uint64_t z) {
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

inline Fp128 fingerprint(const std::vector<uint8_t>& b) {
  uint64_t h1 = 0x243f6a8885a308d3ull, h2 = 0x13198a2e03707344ull;
  size_t i = 0;
  for (; i + 8 <= b.size(); i += 8) {
    uint64_t w;
    std::memcpy(&w, b.data() + i, 8);
    h1 = mix64(h1 ^ w) * 0x9e3779b97f4a7c15ull;
    h2 = mix64(h2 + w) ^ (h2 >> 29);
    h2 *= 0xc2b2ae3d27d4eb4full;
  }
  uint64_t tail = 0x9ull;  // length/domain tag so "" != "\0"
  for (; i < b.size(); ++i) tail = (tail << 8) | b[i];
  tail ^= static_cast<uint64_t>(b.size()) << 56;
  h1 = mix64(h1 ^ tail);
  h2 = mix64(h2 + tail + 0x85ebca6bull);
  if (h1 == 0 && h2 == 0) h1 = 1;  // 0 is the empty-slot sentinel
  return {h1, h2};
}

// Open-addressing 128-bit set (linear probing, power-of-two capacity,
// grow at 60% load).  16 bytes/slot: ~1e9 states in ~27 GB after growth.
class FpSet {
 public:
  explicit FpSet(size_t cap_pow2 = 1 << 20) : mask_(cap_pow2 - 1), n_(0) {
    tab_.assign(cap_pow2, {0, 0});
  }
  // Returns true if newly inserted.
  bool insert(Fp128 f) {
    size_t i = static_cast<size_t>(f.hi) & mask_;
    for (;;) {
      Fp128& slot = tab_[i];
      if (slot.hi == 0 && slot.lo == 0) {
        slot = f;
        if (++n_ * 5 > tab_.size() * 3) grow();
        return true;
      }
      if (slot.hi == f.hi && slot.lo == f.lo) return false;
      i = (i + 1) & mask_;
    }
  }
  size_t size() const { return n_; }

 private:
  void grow() {
    std::vector<Fp128> old;
    old.swap(tab_);
    mask_ = mask_ * 2 + 1;
    tab_.assign(mask_ + 1, {0, 0});
    for (const Fp128& f : old) {
      if (f.hi == 0 && f.lo == 0) continue;
      size_t i = static_cast<size_t>(f.hi) & mask_;
      while (!(tab_[i].hi == 0 && tab_[i].lo == 0)) i = (i + 1) & mask_;
      tab_[i] = f;
    }
  }
  std::vector<Fp128> tab_;
  size_t mask_, n_;
};

// Byte-arena DFS stack: entries are [bytes][len u16] so pops read the
// trailing length — one allocation total, no per-state vectors.
class StateStack {
 public:
  void push(const std::vector<uint8_t>& b) {
    arena_.insert(arena_.end(), b.begin(), b.end());
    arena_.push_back(static_cast<uint8_t>(b.size() & 0xff));
    arena_.push_back(static_cast<uint8_t>(b.size() >> 8));
    ++n_;
  }
  bool pop(std::vector<uint8_t>* out) {
    if (arena_.empty()) return false;
    size_t len = arena_[arena_.size() - 2] |
                 (static_cast<size_t>(arena_.back()) << 8);
    out->assign(arena_.end() - 2 - len, arena_.end() - 2);
    arena_.resize(arena_.size() - 2 - len);
    --n_;
    return true;
  }
  size_t size() const { return n_; }

 private:
  std::vector<uint8_t> arena_;
  size_t n_ = 0;
};

inline void record_vote(EState* s, int a, int bal, int val) {
  for (auto& v : s->voters) {
    if (v[0] == bal && v[1] == val) {
      v[2] |= static_cast<uint8_t>(1u << a);
      return;
    }
  }
  std::array<uint8_t, 3> e = {static_cast<uint8_t>(bal),
                              static_cast<uint8_t>(val),
                              static_cast<uint8_t>(1u << a)};
  // Keep sorted by (bal, val) — Python's sorted(dict.items()) order.
  auto it = s->voters.begin();
  while (it != s->voters.end() &&
         ((*it)[0] < e[0] || ((*it)[0] == e[0] && (*it)[1] < e[1])))
    ++it;
  s->voters.insert(it, e);
}

inline void push_msg(EState* s, std::array<uint8_t, 6> m) {
  auto it = s->net.begin();
  while (it != s->net.end() && *it < m) ++it;
  s->net.insert(it, m);
}

// Mirrors exhaustive._gc exactly (including the unsafe_accept carve-outs:
// under the injected bug a stale ACCEPT is the bug, and promised-ballot
// monotonicity no longer justifies the PREPARE prune).
inline void gc(const ECfg& c, EState* s) {
  size_t w = 0;
  for (size_t i = 0; i < s->net.size(); ++i) {
    const auto& m = s->net[i];
    int kind = m[0], dst = m[2], bal = m[3];
    bool drop = false;
    if (kind == 0) {  // PREPARE
      drop = bal <= s->acc[dst][0] && !c.unsafe_accept;
    } else if (kind == 2) {  // ACCEPT
      drop = bal < s->acc[dst][0] && !c.unsafe_accept;
    } else {
      int phase = s->prop[dst][0], rnd = s->prop[dst][1];
      if (phase == PDONE || bal != make_ballot(rnd, dst)) drop = true;
      else if (kind == 1 && phase != P1) drop = true;   // PROMISE
      else if (kind == 3 && phase != P2) drop = true;   // ACCEPTED
    }
    if (!drop) s->net[w++] = s->net[i];
  }
  s->net.resize(w);
}

// Mirrors exhaustive._deliver exactly; consumes net[i].
inline void deliver(const ECfg& c, EState* s, size_t i) {
  std::array<uint8_t, 6> m = s->net[i];
  s->net.erase(s->net.begin() + i);
  int kind = m[0], src = m[1], dst = m[2], bal = m[3], v1 = m[4], v2 = m[5];

  if (kind == 0) {  // PREPARE -> promise if above
    uint8_t* a = s->acc[dst];
    if (bal > a[0]) {
      uint8_t abal = a[1], aval = a[2];
      a[0] = static_cast<uint8_t>(bal);
      push_msg(s, {1, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                   static_cast<uint8_t>(bal), abal, aval});
    }
  } else if (kind == 2) {  // ACCEPT
    uint8_t* a = s->acc[dst];
    if (c.unsafe_accept || bal >= a[0]) {
      a[0] = static_cast<uint8_t>(bal);  // Python sets promised=bal too
      a[1] = static_cast<uint8_t>(bal);
      a[2] = static_cast<uint8_t>(v1);
      record_vote(s, dst, bal, v1);
      push_msg(s, {3, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                   static_cast<uint8_t>(bal), static_cast<uint8_t>(v1), 0});
    }
  } else if (kind == 1) {  // PROMISE
    uint8_t* p = s->prop[dst];
    if (p[0] == P1 && bal == make_ballot(p[1], dst)) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (v1 > p[3]) {
        p[3] = static_cast<uint8_t>(v1);
        p[4] = static_cast<uint8_t>(v2);
      }
      if (__builtin_popcount(p[2]) >= c.quorum) {
        p[5] = p[3] > 0 ? p[4] : static_cast<uint8_t>(kValueBase + dst);
        p[0] = P2;
        p[2] = 0;
        for (int a = 0; a < c.n_acc; ++a)
          push_msg(s, {2, static_cast<uint8_t>(dst), static_cast<uint8_t>(a),
                       static_cast<uint8_t>(bal), p[5], 0});
      }
    }
  } else {  // ACCEPTED
    uint8_t* p = s->prop[dst];
    if (p[0] == P2 && bal == make_ballot(p[1], dst)) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (__builtin_popcount(p[2]) >= c.quorum) {
        p[0] = PDONE;
        p[6] = p[5];
      }
    }
  }
}

// Mirrors exhaustive._timeout: abandon the ballot, retry one round higher.
inline void timeout(const ECfg& c, EState* s, int p) {
  uint8_t dec = s->prop[p][6];
  int rnd = s->prop[p][1] + 1;
  int bal = make_ballot(rnd, p);
  s->prop[p][0] = P1;
  s->prop[p][1] = static_cast<uint8_t>(rnd);
  s->prop[p][2] = 0;
  s->prop[p][3] = 0;
  s->prop[p][4] = 0;
  s->prop[p][5] = 0;
  s->prop[p][6] = dec;
  for (int a = 0; a < c.n_acc; ++a)
    push_msg(s, {0, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                 static_cast<uint8_t>(bal), 0, 0});
}

struct ExploreResult {
  int64_t states = 0;
  int64_t decided_states = 0;
  int32_t violation = 0;
  int32_t status = 0;  // 0 ok, 1 violation, 2 max_states exceeded
  uint32_t chosen_union = 0;  // bitmask over value ids (val - kValueBase)
  int64_t peak_frontier = 0;
};

// Invariants (exhaustive.check_state): agreement, validity, decided<=chosen.
inline bool check_state(const ECfg& c, const EState& s, ExploreResult* r) {
  uint32_t chosen_mask = 0;
  int n_chosen = 0;
  bool valid = true;
  for (const auto& v : s.voters) {
    if (__builtin_popcount(v[2]) >= c.quorum) {
      int vid = v[1] - kValueBase;
      if (vid < 0 || vid >= c.n_prop) valid = false;
      else if (!(chosen_mask & (1u << vid))) {
        chosen_mask |= 1u << vid;
        ++n_chosen;
      }
    }
  }
  r->chosen_union |= chosen_mask;
  bool any_done = false, decided_ok = true;
  for (int p = 0; p < c.n_prop; ++p) {
    if (s.prop[p][0] == PDONE) {
      any_done = true;
      int vid = s.prop[p][6] - kValueBase;
      if (vid < 0 || vid >= c.n_prop || !(chosen_mask & (1u << vid)))
        decided_ok = false;
    }
  }
  if (any_done) ++r->decided_states;
  return n_chosen <= 1 && valid && decided_ok;
}

inline ExploreResult explore(const ECfg& c, int64_t max_states,
                             int64_t progress_every) {
  ExploreResult r;
  EState init{};  // value-init zeroes acc/prop; vectors start empty
  for (int p = 0; p < c.n_prop; ++p)
    for (int a = 0; a < c.n_acc; ++a)
      push_msg(&init, {0, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                       static_cast<uint8_t>(make_ballot(0, p)), 0, 0});

  FpSet visited;
  StateStack stack;
  std::vector<uint8_t> buf, popped;
  serialize(c, init, &buf);
  visited.insert(fingerprint(buf));
  stack.push(buf);

  EState s, succ;
  while (stack.pop(&popped)) {
    deserialize(c, popped.data(), &s);
    ++r.states;
    if (!check_state(c, s, &r)) {
      r.violation = 1;
      r.status = 1;
      return r;
    }
    if (r.states > max_states) {  // mirrors Python: exactly-max completes
      r.status = 2;
      return r;
    }
    if (progress_every && r.states % progress_every == 0)
      std::fprintf(stderr, "# explore: %lld states, frontier %zu\n",
                   static_cast<long long>(r.states), stack.size());
    // Successors: deliver each in-flight message; timeout each live
    // proposer below its retry bound.  Dedup at PUSH (equivalent reachable
    // set to Python's dedup-at-pop, with a bounded frontier).
    size_t nm = s.net.size();
    for (size_t i = 0; i < nm; ++i) {
      succ = s;
      deliver(c, &succ, i);
      gc(c, &succ);
      serialize(c, succ, &buf);
      if (visited.insert(fingerprint(buf))) stack.push(buf);
    }
    for (int p = 0; p < c.n_prop; ++p) {
      if (s.prop[p][0] != PDONE && s.prop[p][1] < c.max_round[p]) {
        succ = s;
        timeout(c, &succ, p);
        gc(c, &succ);
        serialize(c, succ, &buf);
        if (visited.insert(fingerprint(buf))) stack.push(buf);
      }
    }
    if (static_cast<int64_t>(stack.size()) > r.peak_frontier)
      r.peak_frontier = static_cast<int64_t>(stack.size());
  }
  return r;
}

}  // namespace px_explore

// ---------------------------------------------------------------------------
// Bounded exhaustive exploration of MULTI-PAXOS — the native counterpart of
// cpu_ref/mp_exhaustive.check_mp_exhaustive, sharing px_explore's dedup
// machinery (128-bit fingerprints, byte-arena DFS).  The transition system
// mirrors the Python checker action for action: whole-log phase 1 (PROMISE
// carries the acceptor's full accepted log), slot-by-slot phase 2 with
// per-slot max-ballot recovery, nondeterministic leadership challenges
// bounded by max_round, and the same GC rules.  One encoding change, state
// counts unaffected: command values own_slot_value(p, s) = (p+1)*1000 + s
// don't fit a byte, so they ride as compact ids p*L + s + 1 — the map is a
// bijection that preserves comparison order (pid-major, then slot; slot <
// L <= 1000), so per-slot max folds and canonical sort orders agree with
// Python's and the two state graphs are isomorphic (cross-validated:
// tests/test_native_oracle.py asserts exact count equality at shared
// bounds).
// ---------------------------------------------------------------------------

namespace mp_explore {

constexpr int kMaxAccE = 8;
constexpr int kMaxPropE = 3;
constexpr int kMaxLogE = 4;
constexpr int FOLLOW = 0, CAND = 1, LEAD = 2, MDONE = 3;

struct MpMsg {
  // (kind, src, dst, bal, slot, val, payload) — Python's 7-tuple order.
  // payload: PROMISE only, the full log as 2L bytes of (bal, val_id).
  uint8_t f[6];
  std::array<uint8_t, 2 * kMaxLogE> payload;

  bool less(const MpMsg& o, int plen) const {
    for (int i = 0; i < 6; ++i) {
      if (f[i] != o.f[i]) return f[i] < o.f[i];
    }
    // Same kind; payloads are both empty (non-PROMISE) or both 2L bytes.
    if (f[0] != 1) return false;
    for (int i = 0; i < plen; ++i)
      if (payload[i] != o.payload[i]) return payload[i] < o.payload[i];
    return false;
  }
};

struct MpState {
  uint8_t promised[kMaxAccE];
  uint8_t log[kMaxAccE][2 * kMaxLogE];  // (bal, val_id) per slot
  // prop: phase, rnd, heard, ci + recov[2L] + dec[L]
  uint8_t prop[kMaxPropE][4];
  uint8_t recov[kMaxPropE][2 * kMaxLogE];
  uint8_t dec[kMaxPropE][kMaxLogE];
  std::vector<std::array<uint8_t, 4>> votes;  // (slot, bal, val_id, mask)
  std::vector<MpMsg> net;
};

struct MpCfg {
  int n_prop, n_acc, log_len, quorum;
  int max_round[kMaxPropE];
  bool no_recovery;
};

inline int vid(int p, int s, int L) { return p * L + s + 1; }

inline void mp_serialize(const MpCfg& c, const MpState& s,
                         std::vector<uint8_t>* out) {
  out->clear();
  const int L2 = 2 * c.log_len;
  for (int a = 0; a < c.n_acc; ++a) {
    out->push_back(s.promised[a]);
    out->insert(out->end(), s.log[a], s.log[a] + L2);
  }
  for (int p = 0; p < c.n_prop; ++p) {
    out->insert(out->end(), s.prop[p], s.prop[p] + 4);
    out->insert(out->end(), s.recov[p], s.recov[p] + L2);
    out->insert(out->end(), s.dec[p], s.dec[p] + c.log_len);
  }
  out->push_back(static_cast<uint8_t>(s.votes.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.votes.size() >> 8));
  for (const auto& v : s.votes) out->insert(out->end(), v.begin(), v.end());
  out->push_back(static_cast<uint8_t>(s.net.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.net.size() >> 8));
  for (const auto& m : s.net) {
    out->insert(out->end(), m.f, m.f + 6);
    if (m.f[0] == 1)  // PROMISE payload
      out->insert(out->end(), m.payload.begin(), m.payload.begin() + L2);
  }
}

inline void mp_deserialize(const MpCfg& c, const uint8_t* b, MpState* s) {
  const int L2 = 2 * c.log_len;
  for (int a = 0; a < c.n_acc; ++a) {
    s->promised[a] = *b++;
    std::memcpy(s->log[a], b, L2);
    b += L2;
  }
  for (int p = 0; p < c.n_prop; ++p) {
    std::memcpy(s->prop[p], b, 4);
    b += 4;
    std::memcpy(s->recov[p], b, L2);
    b += L2;
    std::memcpy(s->dec[p], b, c.log_len);
    b += c.log_len;
  }
  int nv = b[0] | (b[1] << 8);
  b += 2;
  s->votes.assign(nv, {});
  for (int i = 0; i < nv; ++i) {
    std::memcpy(s->votes[i].data(), b, 4);
    b += 4;
  }
  int nm = b[0] | (b[1] << 8);
  b += 2;
  s->net.assign(nm, {});
  for (int i = 0; i < nm; ++i) {
    std::memcpy(s->net[i].f, b, 6);
    b += 6;
    if (s->net[i].f[0] == 1) {
      std::memcpy(s->net[i].payload.data(), b, L2);
      b += L2;
    }
  }
}

inline void mp_push_msg(const MpCfg& c, MpState* s, MpMsg m) {
  const int L2 = 2 * c.log_len;
  auto it = s->net.begin();
  while (it != s->net.end() && it->less(m, L2)) ++it;
  s->net.insert(it, m);
}

inline void mp_record(MpState* s, int a, int slot, int bal, int val) {
  for (auto& v : s->votes) {
    if (v[0] == slot && v[1] == bal && v[2] == val) {
      v[3] |= static_cast<uint8_t>(1u << a);
      return;
    }
  }
  std::array<uint8_t, 4> e = {static_cast<uint8_t>(slot),
                              static_cast<uint8_t>(bal),
                              static_cast<uint8_t>(val),
                              static_cast<uint8_t>(1u << a)};
  auto it = s->votes.begin();
  while (it != s->votes.end() &&
         std::lexicographical_compare(it->begin(), it->begin() + 3,
                                      e.begin(), e.begin() + 3))
    ++it;
  s->votes.insert(it, e);
}

// mp_exhaustive._drive: the leader's ACCEPT broadcast (or DONE past the log).
inline void mp_drive(const MpCfg& c, MpState* s, int p) {
  int ci = s->prop[p][3];
  if (ci >= c.log_len) {
    s->prop[p][0] = MDONE;
    s->prop[p][2] = 0;
    return;
  }
  int rb = s->recov[p][2 * ci], rv = s->recov[p][2 * ci + 1];
  int val = (c.no_recovery || rb == 0) ? vid(p, ci, c.log_len) : rv;
  int bal = make_ballot(s->prop[p][1], p);
  s->prop[p][0] = LEAD;
  s->prop[p][2] = 0;
  for (int a = 0; a < c.n_acc; ++a) {
    MpMsg m{};
    m.f[0] = 2;  // ACCEPT
    m.f[1] = static_cast<uint8_t>(p);
    m.f[2] = static_cast<uint8_t>(a);
    m.f[3] = static_cast<uint8_t>(bal);
    m.f[4] = static_cast<uint8_t>(ci);
    m.f[5] = static_cast<uint8_t>(val);
    mp_push_msg(c, s, m);
  }
}

// mp_exhaustive._deliver; consumes net[i].
inline void mp_deliver(const MpCfg& c, MpState* s, size_t i) {
  MpMsg m = s->net[i];
  s->net.erase(s->net.begin() + i);
  const int L2 = 2 * c.log_len;
  int kind = m.f[0], src = m.f[1], dst = m.f[2], bal = m.f[3], slot = m.f[4],
      val = m.f[5];

  if (kind == 0) {  // PREPARE: promise + full-log payload
    if (bal > s->promised[dst]) {
      MpMsg r{};
      r.f[0] = 1;  // PROMISE
      r.f[1] = static_cast<uint8_t>(dst);
      r.f[2] = static_cast<uint8_t>(src);
      r.f[3] = static_cast<uint8_t>(bal);
      std::memcpy(r.payload.data(), s->log[dst], L2);  // pre-promise log
      s->promised[dst] = static_cast<uint8_t>(bal);
      mp_push_msg(c, s, r);
    }
  } else if (kind == 2) {  // ACCEPT
    if (bal >= s->promised[dst]) {
      s->log[dst][2 * slot] = static_cast<uint8_t>(bal);
      s->log[dst][2 * slot + 1] = static_cast<uint8_t>(val);
      if (bal > s->promised[dst]) s->promised[dst] = static_cast<uint8_t>(bal);
      mp_record(s, dst, slot, bal, val);
      MpMsg r{};
      r.f[0] = 3;  // ACCEPTED
      r.f[1] = static_cast<uint8_t>(dst);
      r.f[2] = static_cast<uint8_t>(src);
      r.f[3] = static_cast<uint8_t>(bal);
      r.f[4] = static_cast<uint8_t>(slot);
      r.f[5] = static_cast<uint8_t>(val);
      mp_push_msg(c, s, r);
    }
  } else if (kind == 1) {  // PROMISE
    uint8_t* p = s->prop[dst];
    if (p[0] == CAND && bal == make_ballot(p[1], dst)) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (!c.no_recovery) {
        // Per-slot max over (bal, val) pairs — val_id order matches
        // own_slot_value order, so ties break exactly as in Python.
        for (int t = 0; t < c.log_len; ++t) {
          uint8_t* r = &s->recov[dst][2 * t];
          const uint8_t* q = &m.payload[2 * t];
          if (q[0] > r[0] || (q[0] == r[0] && q[1] > r[1])) {
            r[0] = q[0];
            r[1] = q[1];
          }
        }
      }
      if (__builtin_popcount(p[2]) >= c.quorum) {
        p[3] = 0;  // commit_idx = 0
        mp_drive(c, s, dst);
      }
    }
  } else {  // ACCEPTED
    uint8_t* p = s->prop[dst];
    if (p[0] == LEAD && bal == make_ballot(p[1], dst) && slot == p[3]) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (__builtin_popcount(p[2]) >= c.quorum) {
        s->dec[dst][slot] = static_cast<uint8_t>(val);
        p[3] = static_cast<uint8_t>(slot + 1);
        mp_drive(c, s, dst);
      }
    }
  }
}

// mp_exhaustive._timeout: challenge for leadership at the next ballot.
inline void mp_timeout(const MpCfg& c, MpState* s, int p) {
  int rnd = s->prop[p][1] + 1;
  int bal = make_ballot(rnd, p);
  s->prop[p][0] = CAND;
  s->prop[p][1] = static_cast<uint8_t>(rnd);
  s->prop[p][2] = 0;
  s->prop[p][3] = 0;
  std::memset(s->recov[p], 0, 2 * c.log_len);
  for (int a = 0; a < c.n_acc; ++a) {
    MpMsg m{};
    m.f[0] = 0;  // PREPARE
    m.f[1] = static_cast<uint8_t>(p);
    m.f[2] = static_cast<uint8_t>(a);
    m.f[3] = static_cast<uint8_t>(bal);
    mp_push_msg(c, s, m);
  }
}

// mp_exhaustive._gc.
inline void mp_gc(const MpCfg& c, MpState* s) {
  size_t w = 0;
  for (size_t i = 0; i < s->net.size(); ++i) {
    const MpMsg& m = s->net[i];
    int kind = m.f[0], dst = m.f[2], bal = m.f[3], slot = m.f[4];
    bool drop = false;
    if (kind == 0) {
      drop = bal <= s->promised[dst];
    } else if (kind == 2) {
      drop = bal < s->promised[dst];
    } else {
      int phase = s->prop[dst][0], rnd = s->prop[dst][1];
      if (phase == MDONE || bal != make_ballot(rnd, dst)) drop = true;
      else if (kind == 1 && phase != CAND) drop = true;
      else if (kind == 3 && (phase != LEAD || slot != s->prop[dst][3]))
        drop = true;
    }
    if (!drop) s->net[w++] = s->net[i];
  }
  s->net.resize(w);
}

// mp_exhaustive.check_state: per-slot agreement + validity + DONE-log match.
inline bool mp_check(const MpCfg& c, const MpState& s,
                     px_explore::ExploreResult* r) {
  // Per-slot chosen-value masks over val_ids (<= kMaxPropE * kMaxLogE = 12).
  uint32_t chosen[kMaxLogE] = {0, 0, 0, 0};
  for (const auto& v : s.votes) {
    if (__builtin_popcount(v[3]) >= c.quorum) chosen[v[0]] |= 1u << v[2];
  }
  bool ok = true;
  for (int t = 0; t < c.log_len; ++t) {
    uint32_t m = chosen[t];
    if (__builtin_popcount(m) > 1) ok = false;
    while (m) {
      int id = __builtin_ctz(m);
      m &= m - 1;
      int p = (id - 1) / c.log_len, sl = (id - 1) % c.log_len;
      if (sl != t || p < 0 || p >= c.n_prop) ok = false;
      r->chosen_union |= 1u << (id - 1);
    }
  }
  bool any_done = false;
  for (int p = 0; p < c.n_prop; ++p) {
    if (s.prop[p][0] != MDONE) continue;
    any_done = true;
    // The DONE proposer's replicated log must be exactly the chosen set
    // per slot (Python: per_slot[s] == {dec[s]} — set equality).
    for (int t = 0; t < c.log_len; ++t)
      if (s.dec[p][t] == 0 || chosen[t] != (1u << s.dec[p][t])) ok = false;
  }
  if (any_done) ++r->decided_states;
  return ok;
}

inline px_explore::ExploreResult mp_explore_run(const MpCfg& c,
                                                int64_t max_states,
                                                int64_t progress_every) {
  px_explore::ExploreResult r;
  MpState init{};  // all-zero roles, empty net/votes

  px_explore::FpSet visited;
  px_explore::StateStack stack;
  std::vector<uint8_t> buf, popped;
  mp_serialize(c, init, &buf);
  visited.insert(px_explore::fingerprint(buf));
  stack.push(buf);

  MpState s, succ;
  while (stack.pop(&popped)) {
    mp_deserialize(c, popped.data(), &s);
    ++r.states;
    if (!mp_check(c, s, &r)) {
      r.violation = 1;
      r.status = 1;
      return r;
    }
    if (r.states > max_states) {
      r.status = 2;
      return r;
    }
    if (progress_every && r.states % progress_every == 0)
      std::fprintf(stderr, "# mp explore: %lld states, frontier %zu\n",
                   static_cast<long long>(r.states), stack.size());
    size_t nm = s.net.size();
    for (size_t i = 0; i < nm; ++i) {
      succ = s;
      mp_deliver(c, &succ, i);
      mp_gc(c, &succ);
      mp_serialize(c, succ, &buf);
      if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
    }
    for (int p = 0; p < c.n_prop; ++p) {
      if (s.prop[p][0] != MDONE && s.prop[p][1] < c.max_round[p]) {
        succ = s;
        mp_timeout(c, &succ, p);
        mp_gc(c, &succ);
        mp_serialize(c, succ, &buf);
        if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
      }
    }
    if (static_cast<int64_t>(stack.size()) > r.peak_frontier)
      r.peak_frontier = static_cast<int64_t>(stack.size());
  }
  return r;
}

}  // namespace mp_explore

// ---------------------------------------------------------------------------
// Bounded exhaustive exploration of FAST PAXOS — the native counterpart of
// cpu_ref/fp_exhaustive.check_fp_exhaustive, completing the explorer matrix
// (VERDICT r4 missing#1) with the repo's subtlest logic: the shared round-0
// fast ballot, vote-at-most-once acceptors, and coordinated recovery's
// choosable rule.  Shares px_explore's dedup core (128-bit fingerprints,
// byte-arena DFS) and mirrors the Python transition system action for
// action — same init (every proposer's fast ACCEPT in flight), same
// deliver/timeout, same GC reductions, same per-round-kind choice
// thresholds — so distinct-state counts cross-validate bit-for-bit at
// shared bounds (tests/test_native_oracle.py: 4,013,181 at 2x5acc,
// retries (1, 0)).  adopt_any injects the wrong-recovery bug (skip the
// choosable filter) and must find a violation at the same bounds Python
// does; the livelock-bug leg (fast-round retry) stays Python-side with the
// liveness machinery.
// ---------------------------------------------------------------------------

namespace fp_explore {

constexpr int kMaxAccE = 8;
constexpr int kMaxPropE = 4;
// Phases (core/fp_state.py): P1, P2, DONE, FAST.
constexpr int P1 = 0, P2 = 1, FDONE = 2, FAST = 3;
constexpr int kFastBal = 1;  // make_ballot(0, 0): the shared fast ballot

// Serialized-state layout (all fields fit uint8_t):
//   acc[n_acc][3]   promised, acc_bal, acc_val
//   prop[n_prop][6] phase, rnd, heard, best_bal, prop_val, decided
//   rep[n_prop][n_prop]  per-value-id reporter bitmasks at best_bal
//   nv u16, voters[nv][3]  bal, val, mask  (sorted by (bal, val))
//   nm u16, net[nm][6]  kind, src, dst, bal, v1, v2  (sorted)
struct FpState {
  uint8_t acc[kMaxAccE][3];
  uint8_t prop[kMaxPropE][6];
  uint8_t rep[kMaxPropE][kMaxPropE];
  std::vector<std::array<uint8_t, 3>> voters;
  std::vector<std::array<uint8_t, 6>> net;
};

struct FCfg {
  int n_prop, n_acc, q1, q2, fquorum;
  int max_round[kMaxPropE];
  bool adopt_any;
};

inline void serialize(const FCfg& c, const FpState& s,
                      std::vector<uint8_t>* out) {
  out->clear();
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) out->push_back(s.acc[a][f]);
  for (int p = 0; p < c.n_prop; ++p) {
    for (int f = 0; f < 6; ++f) out->push_back(s.prop[p][f]);
    for (int v = 0; v < c.n_prop; ++v) out->push_back(s.rep[p][v]);
  }
  out->push_back(static_cast<uint8_t>(s.voters.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.voters.size() >> 8));
  for (const auto& v : s.voters) out->insert(out->end(), v.begin(), v.end());
  out->push_back(static_cast<uint8_t>(s.net.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.net.size() >> 8));
  for (const auto& m : s.net) out->insert(out->end(), m.begin(), m.end());
}

inline void deserialize(const FCfg& c, const uint8_t* b, FpState* s) {
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) s->acc[a][f] = *b++;
  for (int p = 0; p < c.n_prop; ++p) {
    for (int f = 0; f < 6; ++f) s->prop[p][f] = *b++;
    for (int v = 0; v < c.n_prop; ++v) s->rep[p][v] = *b++;
  }
  int nv = b[0] | (b[1] << 8);
  b += 2;
  s->voters.assign(nv, {});
  for (int i = 0; i < nv; ++i) {
    std::memcpy(s->voters[i].data(), b, 3);
    b += 3;
  }
  int nm = b[0] | (b[1] << 8);
  b += 2;
  s->net.assign(nm, {});
  for (int i = 0; i < nm; ++i) {
    std::memcpy(s->net[i].data(), b, 6);
    b += 6;
  }
}

inline void record_vote(FpState* s, int a, int bal, int val) {
  for (auto& v : s->voters) {
    if (v[0] == bal && v[1] == val) {
      v[2] |= static_cast<uint8_t>(1u << a);
      return;
    }
  }
  std::array<uint8_t, 3> e = {static_cast<uint8_t>(bal),
                              static_cast<uint8_t>(val),
                              static_cast<uint8_t>(1u << a)};
  auto it = s->voters.begin();
  while (it != s->voters.end() &&
         ((*it)[0] < e[0] || ((*it)[0] == e[0] && (*it)[1] < e[1])))
    ++it;
  s->voters.insert(it, e);
}

inline void push_msg(FpState* s, std::array<uint8_t, 6> m) {
  auto it = s->net.begin();
  while (it != s->net.end() && *it < m) ++it;
  s->net.insert(it, m);
}

// fp_exhaustive._recovery_pick: the value choice at q1 completion.
inline int recovery_pick(const FCfg& c, int pid, int heard, int best_bal,
                         const uint8_t* rep) {
  if (best_bal == 0) return kValueBase + pid;
  if (c.adopt_any) {  // BUG INJECTION: ignore choosability entirely
    for (int v = 0; v < c.n_prop; ++v)
      if (rep[v]) return kValueBase + v;
    return kValueBase + pid;
  }
  if (ballot_round(best_bal) == 0) {  // recovering the fast round
    int unheard = c.n_acc - __builtin_popcount(heard);
    for (int v = 0; v < c.n_prop; ++v)
      if (rep[v] && __builtin_popcount(rep[v]) + unheard >= c.fquorum)
        return kValueBase + v;
    return kValueBase + pid;
  }
  // Classic round: its unique owner proposed exactly one value.
  for (int v = 0; v < c.n_prop; ++v)
    if (rep[v]) return kValueBase + v;
  return kValueBase + pid;
}

// Mirrors fp_exhaustive._deliver exactly; consumes net[i].
inline void deliver(const FCfg& c, FpState* s, size_t i) {
  std::array<uint8_t, 6> m = s->net[i];
  s->net.erase(s->net.begin() + i);
  int kind = m[0], src = m[1], dst = m[2], bal = m[3], v1 = m[4], v2 = m[5];

  if (kind == 0) {  // PREPARE
    uint8_t* a = s->acc[dst];
    if (bal > a[0]) {
      uint8_t abal = a[1], aval = a[2];
      a[0] = static_cast<uint8_t>(bal);
      push_msg(s, {1, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                   static_cast<uint8_t>(bal), abal, aval});
    }
  } else if (kind == 2) {  // ACCEPT: vote at most once per ballot
    uint8_t* a = s->acc[dst];
    bool revote = bal > a[1] || (bal == a[1] && v1 == a[2]);
    if (bal >= a[0] && revote) {
      a[0] = static_cast<uint8_t>(std::max<int>(a[0], bal));
      a[1] = static_cast<uint8_t>(bal);
      a[2] = static_cast<uint8_t>(v1);
      record_vote(s, dst, bal, v1);
      push_msg(s, {3, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                   static_cast<uint8_t>(bal), static_cast<uint8_t>(v1), 0});
    }
  } else if (kind == 1) {  // PROMISE
    uint8_t* p = s->prop[dst];
    if (p[0] == P1 && bal == make_ballot(p[1], dst)) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (v1 > 0 && v2 >= kValueBase && v2 - kValueBase < c.n_prop) {
        int vid = v2 - kValueBase;
        if (v1 > p[3]) {
          p[3] = static_cast<uint8_t>(v1);
          std::memset(s->rep[dst], 0, kMaxPropE);
        }
        if (v1 == p[3]) s->rep[dst][vid] |= static_cast<uint8_t>(1u << src);
      }
      if (__builtin_popcount(p[2]) >= c.q1) {
        p[4] = static_cast<uint8_t>(
            recovery_pick(c, dst, p[2], p[3], s->rep[dst]));
        p[0] = P2;
        p[2] = 0;
        for (int a = 0; a < c.n_acc; ++a)
          push_msg(s, {2, static_cast<uint8_t>(dst), static_cast<uint8_t>(a),
                       static_cast<uint8_t>(bal), p[4], 0});
      }
    }
  } else {  // ACCEPTED: per-round-kind quorum (fast at round 0, q2 classic)
    uint8_t* p = s->prop[dst];
    bool fast_ok = p[0] == FAST && bal == kFastBal;
    bool p2_ok = p[0] == P2 && bal == make_ballot(p[1], dst);
    if (fast_ok || p2_ok) {
      p[2] |= static_cast<uint8_t>(1u << src);
      int need = fast_ok ? c.fquorum : c.q2;
      if (__builtin_popcount(p[2]) >= need) {
        p[0] = FDONE;
        p[5] = p[4];
      }
    }
  }
}

// Mirrors fp_exhaustive._timeout (bump=True; the no-bump livelock leg stays
// Python-side): abandon the round, start the next CLASSIC one, keep pv/dec.
inline void timeout(const FCfg& c, FpState* s, int p) {
  int rnd = s->prop[p][1] + 1;
  int bal = make_ballot(rnd, p);
  s->prop[p][0] = P1;
  s->prop[p][1] = static_cast<uint8_t>(rnd);
  s->prop[p][2] = 0;
  s->prop[p][3] = 0;
  std::memset(s->rep[p], 0, kMaxPropE);
  for (int a = 0; a < c.n_acc; ++a)
    push_msg(s, {0, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                 static_cast<uint8_t>(bal), 0, 0});
}

// Mirrors fp_exhaustive._gc: no prune depends on a rule adopt_any (a
// PROPOSER pick) could break, so the same reductions serve both modes.
inline void gc(const FCfg& c, FpState* s) {
  size_t w = 0;
  for (size_t i = 0; i < s->net.size(); ++i) {
    const auto& m = s->net[i];
    int kind = m[0], dst = m[2], bal = m[3], v1 = m[4];
    bool drop = false;
    if (kind == 0) {  // PREPARE
      drop = bal <= s->acc[dst][0];
    } else if (kind == 2) {  // ACCEPT
      const uint8_t* a = s->acc[dst];
      bool revote = bal > a[1] || (bal == a[1] && v1 == a[2]);
      drop = bal < a[0] || !revote;
    } else {
      int phase = s->prop[dst][0], rnd = s->prop[dst][1];
      if (phase == FDONE) drop = true;
      else if (kind == 1 && (phase != P1 || bal != make_ballot(rnd, dst)))
        drop = true;
      else if (kind == 3) {
        bool fast_ok = phase == FAST && bal == kFastBal;
        bool p2_ok = phase == P2 && bal == make_ballot(rnd, dst);
        drop = !(fast_ok || p2_ok);
      }
    }
    if (!drop) s->net[w++] = s->net[i];
  }
  s->net.resize(w);
}

// fp_exhaustive.check_state: agreement (per-round-kind choice thresholds),
// validity, decided <= chosen.
inline bool check_state(const FCfg& c, const FpState& s,
                        px_explore::ExploreResult* r) {
  uint32_t chosen_mask = 0;
  int n_chosen = 0;
  bool valid = true;
  for (const auto& v : s.voters) {
    int need = ballot_round(v[0]) == 0 ? c.fquorum : c.q2;
    if (__builtin_popcount(v[2]) >= need) {
      int vid = v[1] - kValueBase;
      if (vid < 0 || vid >= c.n_prop) valid = false;
      else if (!(chosen_mask & (1u << vid))) {
        chosen_mask |= 1u << vid;
        ++n_chosen;
      }
    }
  }
  r->chosen_union |= chosen_mask;
  bool any_done = false, decided_ok = true;
  for (int p = 0; p < c.n_prop; ++p) {
    if (s.prop[p][0] == FDONE) {
      any_done = true;
      int vid = s.prop[p][5] - kValueBase;
      if (vid < 0 || vid >= c.n_prop || !(chosen_mask & (1u << vid)))
        decided_ok = false;
    }
  }
  if (any_done) ++r->decided_states;
  return n_chosen <= 1 && valid && decided_ok;
}

inline px_explore::ExploreResult explore(const FCfg& c, int64_t max_states,
                                         int64_t progress_every) {
  px_explore::ExploreResult r;
  FpState init{};
  for (int p = 0; p < c.n_prop; ++p) {
    init.prop[p][0] = FAST;
    init.prop[p][4] = static_cast<uint8_t>(kValueBase + p);
    for (int a = 0; a < c.n_acc; ++a)
      push_msg(&init, {2, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                       kFastBal, static_cast<uint8_t>(kValueBase + p), 0});
  }

  px_explore::FpSet visited;
  px_explore::StateStack stack;
  std::vector<uint8_t> buf, popped;
  serialize(c, init, &buf);
  visited.insert(px_explore::fingerprint(buf));
  stack.push(buf);

  FpState s, succ;
  while (stack.pop(&popped)) {
    deserialize(c, popped.data(), &s);
    ++r.states;
    if (!check_state(c, s, &r)) {
      r.violation = 1;
      r.status = 1;
      return r;
    }
    if (r.states > max_states) {
      r.status = 2;
      return r;
    }
    if (progress_every && r.states % progress_every == 0)
      std::fprintf(stderr, "# fp explore: %lld states, frontier %zu\n",
                   static_cast<long long>(r.states), stack.size());
    size_t nm = s.net.size();
    for (size_t i = 0; i < nm; ++i) {
      succ = s;
      deliver(c, &succ, i);
      gc(c, &succ);
      serialize(c, succ, &buf);
      if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
    }
    for (int p = 0; p < c.n_prop; ++p) {
      if (s.prop[p][0] != FDONE && s.prop[p][1] < c.max_round[p]) {
        succ = s;
        timeout(c, &succ, p);
        gc(c, &succ);
        serialize(c, succ, &buf);
        if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
      }
    }
    if (static_cast<int64_t>(stack.size()) > r.peak_frontier)
      r.peak_frontier = static_cast<int64_t>(stack.size());
  }
  return r;
}

}  // namespace fp_explore

// ---------------------------------------------------------------------------
// Bounded exhaustive exploration of RAFT-CORE — the native counterpart of
// cpu_ref/raft_exhaustive.check_raft_exhaustive, the last cell of the
// explorer matrix (VERDICT r4 missing#1): election restriction,
// one-vote-per-term fencing, entry adoption from vote replies (grants AND
// denials), heartbeat append/ack commit.  Shares px_explore's dedup core
// and mirrors the Python transition system action for action, so counts
// cross-validate bit-for-bit at shared bounds (1,233,894 at 2x3,
// symmetric single retry).  no_restriction / no_adoption disable one
// safety leg each — either alone must stay clean, both off must find a
// violation, natively reproducing the Python decomposition.
// ---------------------------------------------------------------------------

namespace raft_explore {

constexpr int kMaxAccE = 8;
constexpr int kMaxPropE = 4;
constexpr int RCAND = 0, RLEAD = 1, RDONE = 2;

// Serialized-state layout:
//   acc[n_acc][3]   voted, ent_term, ent_val
//   cand[n_prop][7] phase, rnd, heard, ent_term, ent_val, prop_val, decided
//   nv u16, events[nv][3]  term, val, mask  (sorted by (term, val))
//   nm u16, net[nm][7]  kind, src, dst, term, x, y, z  (sorted)
//     REQVOTE: x = cand_last;  VOTE: x = granted, y = ent_term, z = ent_val
//     APPEND:  x = value;      ACK: unused
struct RfState {
  uint8_t acc[kMaxAccE][3];
  uint8_t cand[kMaxPropE][7];
  std::vector<std::array<uint8_t, 3>> events;
  std::vector<std::array<uint8_t, 7>> net;
};

struct RCfg {
  int n_prop, n_acc, quorum;
  int max_round[kMaxPropE];
  bool no_restriction, no_adoption;
};

inline void serialize(const RCfg& c, const RfState& s,
                      std::vector<uint8_t>* out) {
  out->clear();
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) out->push_back(s.acc[a][f]);
  for (int p = 0; p < c.n_prop; ++p)
    for (int f = 0; f < 7; ++f) out->push_back(s.cand[p][f]);
  out->push_back(static_cast<uint8_t>(s.events.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.events.size() >> 8));
  for (const auto& v : s.events) out->insert(out->end(), v.begin(), v.end());
  out->push_back(static_cast<uint8_t>(s.net.size() & 0xff));
  out->push_back(static_cast<uint8_t>(s.net.size() >> 8));
  for (const auto& m : s.net) out->insert(out->end(), m.begin(), m.end());
}

inline void deserialize(const RCfg& c, const uint8_t* b, RfState* s) {
  for (int a = 0; a < c.n_acc; ++a)
    for (int f = 0; f < 3; ++f) s->acc[a][f] = *b++;
  for (int p = 0; p < c.n_prop; ++p)
    for (int f = 0; f < 7; ++f) s->cand[p][f] = *b++;
  int nv = b[0] | (b[1] << 8);
  b += 2;
  s->events.assign(nv, {});
  for (int i = 0; i < nv; ++i) {
    std::memcpy(s->events[i].data(), b, 3);
    b += 3;
  }
  int nm = b[0] | (b[1] << 8);
  b += 2;
  s->net.assign(nm, {});
  for (int i = 0; i < nm; ++i) {
    std::memcpy(s->net[i].data(), b, 7);
    b += 7;
  }
}

inline void record_event(RfState* s, int a, int term, int val) {
  for (auto& v : s->events) {
    if (v[0] == term && v[1] == val) {
      v[2] |= static_cast<uint8_t>(1u << a);
      return;
    }
  }
  std::array<uint8_t, 3> e = {static_cast<uint8_t>(term),
                              static_cast<uint8_t>(val),
                              static_cast<uint8_t>(1u << a)};
  auto it = s->events.begin();
  while (it != s->events.end() &&
         ((*it)[0] < e[0] || ((*it)[0] == e[0] && (*it)[1] < e[1])))
    ++it;
  s->events.insert(it, e);
}

inline void push_msg(RfState* s, std::array<uint8_t, 7> m) {
  auto it = s->net.begin();
  while (it != s->net.end() && *it < m) ++it;
  s->net.insert(it, m);
}

// Mirrors raft_exhaustive._deliver exactly; consumes net[i].
inline void deliver(const RCfg& c, RfState* s, size_t i) {
  std::array<uint8_t, 7> m = s->net[i];
  s->net.erase(s->net.begin() + i);
  int kind = m[0], src = m[1], dst = m[2], term = m[3], x = m[4], y = m[5],
      z = m[6];

  if (kind == 0) {  // REQVOTE: one vote per term + election restriction
    uint8_t* a = s->acc[dst];
    bool grant = term > a[0] && (c.no_restriction || x >= a[1]);
    if (grant) a[0] = static_cast<uint8_t>(term);
    // Reply grant or denial with the (pre-update) entry — the gossip
    // channel candidates adopt from.
    push_msg(s, {1, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                 static_cast<uint8_t>(term), grant ? uint8_t{1} : uint8_t{0},
                 a[1], a[2]});
  } else if (kind == 1) {  // VOTE
    uint8_t* p = s->cand[dst];
    if (p[0] == RCAND && term == make_ballot(p[1], dst)) {
      if (x) p[2] |= static_cast<uint8_t>(1u << src);
      if (!c.no_adoption && y > p[3]) {
        p[3] = static_cast<uint8_t>(y);
        p[4] = static_cast<uint8_t>(z);
      }
      if (__builtin_popcount(p[2]) >= c.quorum) {
        int pv = p[3] > 0 ? p[4] : kValueBase + dst;
        p[5] = static_cast<uint8_t>(pv);
        p[0] = RLEAD;
        p[2] = 0;
        p[3] = static_cast<uint8_t>(term);  // records proposal at own term
        p[4] = static_cast<uint8_t>(pv);
        for (int a = 0; a < c.n_acc; ++a)
          push_msg(s, {2, static_cast<uint8_t>(dst), static_cast<uint8_t>(a),
                       static_cast<uint8_t>(term), static_cast<uint8_t>(pv),
                       0, 0});
      }
    }
  } else if (kind == 2) {  // APPEND
    uint8_t* a = s->acc[dst];
    if (term >= a[0]) {
      a[0] = static_cast<uint8_t>(std::max<int>(a[0], term));
      a[1] = static_cast<uint8_t>(term);
      a[2] = static_cast<uint8_t>(x);
      record_event(s, dst, term, x);
      push_msg(s, {3, static_cast<uint8_t>(dst), static_cast<uint8_t>(src),
                   static_cast<uint8_t>(term), 0, 0, 0});
    }
  } else {  // ACK
    uint8_t* p = s->cand[dst];
    if (p[0] == RLEAD && term == make_ballot(p[1], dst)) {
      p[2] |= static_cast<uint8_t>(1u << src);
      if (__builtin_popcount(p[2]) >= c.quorum) {
        p[0] = RDONE;
        p[6] = p[5];
      }
    }
  }
}

// Mirrors raft_exhaustive._timeout (bump=True; the same-term re-election
// livelock leg stays Python-side): the adopted entry PERSISTS across
// retries — it is the candidate's log.
inline void timeout(const RCfg& c, RfState* s, int p) {
  int rnd = s->cand[p][1] + 1;
  int bal = make_ballot(rnd, p);
  s->cand[p][0] = RCAND;
  s->cand[p][1] = static_cast<uint8_t>(rnd);
  s->cand[p][2] = 0;
  for (int a = 0; a < c.n_acc; ++a)
    push_msg(s, {0, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                 static_cast<uint8_t>(bal), s->cand[p][3], 0, 0});
}

// Mirrors raft_exhaustive._gc: conservative — a REQVOTE below the voter's
// term is kept only while its denial reply could still matter.
inline void gc(const RCfg& c, RfState* s) {
  size_t w = 0;
  for (size_t i = 0; i < s->net.size(); ++i) {
    const auto& m = s->net[i];
    int kind = m[0], src = m[1], dst = m[2], term = m[3];
    bool drop = false;
    if (kind == 0) {  // REQVOTE
      int phase = s->cand[src][0], rnd = s->cand[src][1];
      bool reply_dead = phase != RCAND || term != make_ballot(rnd, src);
      drop = term <= s->acc[dst][0] && reply_dead;
    } else if (kind == 1) {  // VOTE
      int phase = s->cand[dst][0], rnd = s->cand[dst][1];
      drop = phase != RCAND || term != make_ballot(rnd, dst);
    } else if (kind == 2) {  // APPEND
      drop = term < s->acc[dst][0];
    } else {  // ACK
      int phase = s->cand[dst][0], rnd = s->cand[dst][1];
      drop = phase != RLEAD || term != make_ballot(rnd, dst);
    }
    if (!drop) s->net[w++] = s->net[i];
  }
  s->net.resize(w);
}

// raft_exhaustive.check_state: agreement over committed (majority-appended)
// values, validity, decided <= chosen.
inline bool check_state(const RCfg& c, const RfState& s,
                        px_explore::ExploreResult* r) {
  uint32_t chosen_mask = 0;
  int n_chosen = 0;
  bool valid = true;
  for (const auto& v : s.events) {
    if (__builtin_popcount(v[2]) >= c.quorum) {
      int vid = v[1] - kValueBase;
      if (vid < 0 || vid >= c.n_prop) valid = false;
      else if (!(chosen_mask & (1u << vid))) {
        chosen_mask |= 1u << vid;
        ++n_chosen;
      }
    }
  }
  r->chosen_union |= chosen_mask;
  bool any_done = false, decided_ok = true;
  for (int p = 0; p < c.n_prop; ++p) {
    if (s.cand[p][0] == RDONE) {
      any_done = true;
      int vid = s.cand[p][6] - kValueBase;
      if (vid < 0 || vid >= c.n_prop || !(chosen_mask & (1u << vid)))
        decided_ok = false;
    }
  }
  if (any_done) ++r->decided_states;
  return n_chosen <= 1 && valid && decided_ok;
}

inline px_explore::ExploreResult explore(const RCfg& c, int64_t max_states,
                                         int64_t progress_every) {
  px_explore::ExploreResult r;
  RfState init{};
  for (int p = 0; p < c.n_prop; ++p)
    for (int a = 0; a < c.n_acc; ++a)
      push_msg(&init, {0, static_cast<uint8_t>(p), static_cast<uint8_t>(a),
                       static_cast<uint8_t>(make_ballot(0, p)), 0, 0, 0});

  px_explore::FpSet visited;
  px_explore::StateStack stack;
  std::vector<uint8_t> buf, popped;
  serialize(c, init, &buf);
  visited.insert(px_explore::fingerprint(buf));
  stack.push(buf);

  RfState s, succ;
  while (stack.pop(&popped)) {
    deserialize(c, popped.data(), &s);
    ++r.states;
    if (!check_state(c, s, &r)) {
      r.violation = 1;
      r.status = 1;
      return r;
    }
    if (r.states > max_states) {
      r.status = 2;
      return r;
    }
    if (progress_every && r.states % progress_every == 0)
      std::fprintf(stderr, "# raft explore: %lld states, frontier %zu\n",
                   static_cast<long long>(r.states), stack.size());
    size_t nm = s.net.size();
    for (size_t i = 0; i < nm; ++i) {
      succ = s;
      deliver(c, &succ, i);
      gc(c, &succ);
      serialize(c, succ, &buf);
      if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
    }
    for (int p = 0; p < c.n_prop; ++p) {
      if (s.cand[p][0] != RDONE && s.cand[p][1] < c.max_round[p]) {
        succ = s;
        timeout(c, &succ, p);
        gc(c, &succ);
        serialize(c, succ, &buf);
        if (visited.insert(px_explore::fingerprint(buf))) stack.push(buf);
      }
    }
    if (static_cast<int64_t>(stack.size()) > r.peak_frontier)
      r.peak_frontier = static_cast<int64_t>(stack.size());
  }
  return r;
}

}  // namespace raft_explore

}  // namespace

extern "C" {

// Packing limits: voter sets are uint32 bitmasks; ballots pack (round, pid)
// with kMaxProposers.  Out-of-range topologies would silently corrupt
// verdicts (shift UB / ballot collisions) — fail loudly instead.
static bool valid_topology(int32_t n_prop, int32_t n_acc) {
  return n_prop >= 1 && n_prop <= kMaxProposers && n_acc >= 1 && n_acc <= 32;
}

// Runs `n_runs` independent seeded instances; fills `out` with 5 int32 per
// run: decided, agreement_ok, validity_ok, n_chosen, steps.  On an invalid
// topology every field is set to -1 (the Python wrapper validates first).
void run_batch(uint64_t seed0, int32_t n_runs, int32_t n_prop, int32_t n_acc,
               double p_drop, double p_dup, double timeout_weight,
               int32_t max_steps, int32_t* out) {
  if (!valid_topology(n_prop, n_acc)) {
    for (int32_t i = 0; i < 5 * n_runs; ++i) out[i] = -1;
    return;
  }
  for (int32_t r = 0; r < n_runs; ++r) {
    Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, p_drop, p_dup,
            timeout_weight);
    Result res = sim.run(max_steps);
    std::memcpy(out + 5 * r, &res, sizeof(res));
  }
}

// Multi-Paxos batch: same 5-int32-per-run layout as run_batch, with
// n_chosen reporting the count of slots chosen (not distinct values).
void mp_run_batch(uint64_t seed0, int32_t n_runs, int32_t n_prop,
                  int32_t n_acc, int32_t log_len, double p_drop, double p_dup,
                  double timeout_weight, int32_t max_steps, int32_t* out) {
  if (!valid_topology(n_prop, n_acc) || log_len < 1 ||
      log_len > mp::kMaxLog) {
    for (int32_t i = 0; i < 5 * n_runs; ++i) out[i] = -1;
    return;
  }
  for (int32_t r = 0; r < n_runs; ++r) {
    mp::Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, log_len,
                p_drop, p_dup, timeout_weight);
    Result res = sim.run(max_steps);
    std::memcpy(out + 5 * r, &res, sizeof(res));
  }
}

// Fast Paxos batch: same 5-int32-per-run layout; q1/q2/q_fast of 0 select
// the classic defaults (majority / majority / ceil(3n/4)).  The caller is
// responsible for knowing whether the triple is FFP-safe — unsafe triples
// are the falsifiability leg (the oracle must then find violations).
void fp_run_batch(uint64_t seed0, int32_t n_runs, int32_t n_prop,
                  int32_t n_acc, int32_t q1, int32_t q2, int32_t q_fast,
                  double p_drop, double p_dup, double timeout_weight,
                  int32_t max_steps, int32_t* out) {
  if (!valid_topology(n_prop, n_acc) || q1 < 0 || q1 > n_acc || q2 < 0 ||
      q2 > n_acc || q_fast < 0 || q_fast > n_acc) {
    for (int32_t i = 0; i < 5 * n_runs; ++i) out[i] = -1;
    return;
  }
  for (int32_t r = 0; r < n_runs; ++r) {
    fp::Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, q1, q2,
                q_fast, p_drop, p_dup, timeout_weight);
    Result res = sim.run(max_steps);
    std::memcpy(out + 5 * r, &res, sizeof(res));
  }
}

// Raft-core batch: same 5-int32-per-run layout.  no_restriction /
// no_adoption disable one safety leg each (both off must let the oracle
// find agreement violations — the event-driven counterpart of the
// exhaustive checker's two-leg decomposition).
void raft_run_batch(uint64_t seed0, int32_t n_runs, int32_t n_prop,
                    int32_t n_acc, int32_t no_restriction,
                    int32_t no_adoption, double p_drop, double p_dup,
                    double timeout_weight, int32_t max_steps, int32_t* out) {
  if (!valid_topology(n_prop, n_acc)) {
    for (int32_t i = 0; i < 5 * n_runs; ++i) out[i] = -1;
    return;
  }
  for (int32_t r = 0; r < n_runs; ++r) {
    raft::Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc,
                  no_restriction != 0, no_adoption != 0, p_drop, p_dup,
                  timeout_weight);
    Result res = sim.run(max_steps);
    std::memcpy(out + 5 * r, &res, sizeof(res));
  }
}

// CPU-reference throughput: total scheduler events processed across
// `n_runs` instances (the number BASELINE.md's config-1 row asks for).
int64_t bench_steps(uint64_t seed0, int32_t n_runs, int32_t n_prop,
                    int32_t n_acc, double p_drop, double p_dup,
                    double timeout_weight, int32_t max_steps) {
  if (!valid_topology(n_prop, n_acc)) return -1;
  int64_t total = 0;
  for (int32_t r = 0; r < n_runs; ++r) {
    Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, p_drop, p_dup,
            timeout_weight);
    total += sim.run(max_steps).steps;
  }
  return total;
}


// Bounded exhaustive exploration of classic Paxos (the native counterpart
// of cpu_ref/exhaustive.check_exhaustive; see px_explore above).  Fills
// out[0..5] = states, decided_states, violation, status, chosen-value
// bitmask (bit v = value kValueBase+v ever chosen), peak frontier size.
// status: 0 clean, 1 violation found, 2 max_states exceeded, -1 invalid
// topology.  progress_every > 0 prints a stderr line every that many
// states.
// Bounded exhaustive exploration of Multi-Paxos (native counterpart of
// cpu_ref/mp_exhaustive.check_mp_exhaustive; see mp_explore above).  Same
// out[0..5] layout as explore_paxos, except out[4]'s chosen bitmask is over
// compact value ids p * log_len + s (the wrapper decodes to
// own_slot_value).  no_recovery injects the skipped-recovery bug (must
// find a violation at the same bounds Python does).
void explore_multipaxos(int32_t n_prop, int32_t n_acc, int32_t log_len,
                        const int32_t* max_round, int64_t max_states,
                        int32_t no_recovery, int64_t progress_every,
                        int64_t* out) {
  for (int i = 0; i < 6; ++i) out[i] = 0;
  if (n_prop < 1 || n_prop > mp_explore::kMaxPropE || n_acc < 1 ||
      n_acc > mp_explore::kMaxAccE || log_len < 1 ||
      log_len > mp_explore::kMaxLogE) {
    out[3] = -1;
    return;
  }
  mp_explore::MpCfg c;
  c.n_prop = n_prop;
  c.n_acc = n_acc;
  c.log_len = log_len;
  c.quorum = n_acc / 2 + 1;
  c.no_recovery = no_recovery != 0;
  for (int p = 0; p < n_prop; ++p) {
    if (max_round[p] < 0 || max_round[p] > 29) {
      out[3] = -1;
      return;
    }
    c.max_round[p] = max_round[p];
  }
  px_explore::ExploreResult r =
      mp_explore::mp_explore_run(c, max_states, progress_every);
  out[0] = r.states;
  out[1] = r.decided_states;
  out[2] = r.violation;
  out[3] = r.status;
  out[4] = r.chosen_union;
  out[5] = r.peak_frontier;
}

// Bounded exhaustive exploration of Fast Paxos (native counterpart of
// cpu_ref/fp_exhaustive.check_fp_exhaustive; see fp_explore above).  Same
// out[0..5] layout as explore_paxos (chosen bitmask over value ids
// val - kValueBase).  q1/q2/q_fast of 0 select the classic defaults
// (majority / majority / ceil(3n/4)); nonzero triples model FFP quorums —
// unsafe ones are the falsifiability leg.  adopt_any injects the
// wrong-recovery bug (must find a violation at the same bounds Python
// does).
void explore_fastpaxos(int32_t n_prop, int32_t n_acc, int32_t q1, int32_t q2,
                       int32_t q_fast, const int32_t* max_round,
                       int64_t max_states, int32_t adopt_any,
                       int64_t progress_every, int64_t* out) {
  for (int i = 0; i < 6; ++i) out[i] = 0;
  if (n_prop < 1 || n_prop > fp_explore::kMaxPropE || n_acc < 1 ||
      n_acc > fp_explore::kMaxAccE || q1 < 0 || q1 > n_acc || q2 < 0 ||
      q2 > n_acc || q_fast < 0 || q_fast > n_acc) {
    out[3] = -1;
    return;
  }
  fp_explore::FCfg c;
  c.n_prop = n_prop;
  c.n_acc = n_acc;
  int quorum = n_acc / 2 + 1;
  c.q1 = q1 ? q1 : quorum;
  c.q2 = q2 ? q2 : quorum;
  c.fquorum = q_fast ? q_fast : (3 * n_acc + 3) / 4;  // ceil(3n/4)
  c.adopt_any = adopt_any != 0;
  for (int p = 0; p < n_prop; ++p) {
    if (max_round[p] < 0 || max_round[p] > 29) {
      out[3] = -1;
      return;
    }
    c.max_round[p] = max_round[p];
  }
  px_explore::ExploreResult r =
      fp_explore::explore(c, max_states, progress_every);
  out[0] = r.states;
  out[1] = r.decided_states;
  out[2] = r.violation;
  out[3] = r.status;
  out[4] = r.chosen_union;
  out[5] = r.peak_frontier;
}

// Bounded exhaustive exploration of Raft-core (native counterpart of
// cpu_ref/raft_exhaustive.check_raft_exhaustive; see raft_explore above).
// Same out[0..5] layout.  no_restriction / no_adoption disable one safety
// leg each (either alone must stay clean; both off must find a violation).
void explore_raftcore(int32_t n_prop, int32_t n_acc, const int32_t* max_round,
                      int64_t max_states, int32_t no_restriction,
                      int32_t no_adoption, int64_t progress_every,
                      int64_t* out) {
  for (int i = 0; i < 6; ++i) out[i] = 0;
  if (n_prop < 1 || n_prop > raft_explore::kMaxPropE || n_acc < 1 ||
      n_acc > raft_explore::kMaxAccE) {
    out[3] = -1;
    return;
  }
  raft_explore::RCfg c;
  c.n_prop = n_prop;
  c.n_acc = n_acc;
  c.quorum = n_acc / 2 + 1;
  c.no_restriction = no_restriction != 0;
  c.no_adoption = no_adoption != 0;
  for (int p = 0; p < n_prop; ++p) {
    if (max_round[p] < 0 || max_round[p] > 29) {
      out[3] = -1;
      return;
    }
    c.max_round[p] = max_round[p];
  }
  px_explore::ExploreResult r =
      raft_explore::explore(c, max_states, progress_every);
  out[0] = r.states;
  out[1] = r.decided_states;
  out[2] = r.violation;
  out[3] = r.status;
  out[4] = r.chosen_union;
  out[5] = r.peak_frontier;
}

void explore_paxos(int32_t n_prop, int32_t n_acc, const int32_t* max_round,
                   int64_t max_states, int32_t unsafe_accept,
                   int64_t progress_every, int64_t* out) {
  for (int i = 0; i < 6; ++i) out[i] = 0;
  if (n_prop < 1 || n_prop > px_explore::kMaxPropE || n_acc < 1 ||
      n_acc > px_explore::kMaxAccE) {
    out[3] = -1;
    return;
  }
  px_explore::ECfg c;
  c.n_prop = n_prop;
  c.n_acc = n_acc;
  c.quorum = n_acc / 2 + 1;
  c.unsafe_accept = unsafe_accept != 0;
  for (int p = 0; p < n_prop; ++p) {
    // Ballot fields are uint8_t: rnd*8+pid+1 <= 255 needs rnd <= 30.
    if (max_round[p] < 0 || max_round[p] > 29) {
      out[3] = -1;
      return;
    }
    c.max_round[p] = max_round[p];
  }
  px_explore::ExploreResult r =
      px_explore::explore(c, max_states, progress_every);
  out[0] = r.states;
  out[1] = r.decided_states;
  out[2] = r.violation;
  out[3] = r.status;
  out[4] = r.chosen_union;
  out[5] = r.peak_frontier;
}

}  // extern "C"
