// Native differential oracle: event-driven single-decree Paxos in C++.
//
// Reference parity (SURVEY.md §3.1 native-code note, §5.2.1): the reference
// stack is pure Haskell — its "native runtime" is GHC itself — so the new
// framework's native tier is not a port but a TPU-adjacent toolchain piece:
// an independently written, sanitizer-friendly golden model that fuzzes the
// same protocol the JAX kernels implement, at millions of scheduler events
// per second on the host CPU.  It triangulates three implementations
// (C++ oracle, Python golden model, batched JAX kernels): all must satisfy
// agreement + validity on every seed.
//
// Deliberately mirrors the *semantics*, not the code, of
// paxos_tpu/cpu_ref/golden.py: asynchronous scheduler = seeded random choice
// among enabled events (deliver one in-flight message, or fire one proposer
// timeout), network = multiset with drop/duplicate faults, safety recomputed
// from the full accept-event history.
//
// Build: g++ -O2 -shared -fPIC -o libpaxos_oracle.so paxos_oracle.cc
// ABI: see run_batch / bench_steps at the bottom (plain C, ctypes-friendly).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// splitmix64 + xorshift: tiny, seedable, independent of any Python RNG.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9e3779b97f4a7c15ull) {
    next();
    next();
  }
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // Uniform double in [0, 1).
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // Uniform int in [0, n).
  int below(int n) { return static_cast<int>(next() % static_cast<uint64_t>(n)); }
};

constexpr int kMaxProposers = 8;  // matches paxos_tpu.core.ballot.MAX_PROPOSERS
constexpr int kValueBase = 100;   // proposer p proposes kValueBase + p

inline int make_ballot(int rnd, int pid) { return rnd * kMaxProposers + pid + 1; }

enum Kind : uint8_t { PREPARE, PROMISE, ACCEPT, ACCEPTED };

struct Msg {
  Kind kind;
  int8_t src;  // proposer id for requests, acceptor id for replies
  int8_t dst;
  int32_t bal;
  int32_t val;
  int32_t prev_bal;
  int32_t prev_val;
};

struct Acceptor {
  int32_t promised = 0;
  int32_t acc_bal = 0;
  int32_t acc_val = 0;
};

struct Proposer {
  enum Phase { P1, P2, DONE };
  int pid;
  int32_t own_val;
  int rnd = 0;
  int32_t bal;
  Phase phase = P1;
  uint32_t heard = 0;  // acceptor bitmask, like the device kernels
  int32_t best_bal = 0;
  int32_t best_val = 0;
  int32_t prop_val = 0;
  int32_t decided_val = -1;

  explicit Proposer(int p) : pid(p), own_val(kValueBase + p), bal(make_ballot(0, p)) {}
};

struct Result {
  int32_t decided;
  int32_t agreement_ok;
  int32_t validity_ok;
  int32_t n_chosen;
  int32_t steps;
};

struct Sim {
  int n_prop, n_acc, quorum;
  double p_drop, p_dup, timeout_weight;
  Rng rng;
  std::vector<Acceptor> acceptors;
  std::vector<Proposer> proposers;
  std::vector<Msg> network;
  // Accept-event history: acceptor bitmask per (ballot, value), linear table
  // (ballot counts stay tiny at single-instance scale).
  std::vector<int32_t> ev_bal, ev_val;
  std::vector<uint32_t> ev_mask;

  Sim(uint64_t seed, int np, int na, double pd, double pdup, double tw)
      : n_prop(np), n_acc(na), quorum(na / 2 + 1), p_drop(pd), p_dup(pdup),
        timeout_weight(tw), rng(seed) {
    acceptors.resize(n_acc);
    for (int p = 0; p < n_prop; ++p) proposers.emplace_back(p);
    for (auto& p : proposers) broadcast(p, PREPARE);
  }

  void offer(const Msg& m) {
    if (rng.uniform() >= p_drop) network.push_back(m);
  }

  void broadcast(Proposer& p, Kind kind) {
    for (int a = 0; a < n_acc; ++a) {
      offer(Msg{kind, static_cast<int8_t>(p.pid), static_cast<int8_t>(a), p.bal,
                p.prop_val, 0, 0});
    }
  }

  void record_accept(int acc, int32_t bal, int32_t val) {
    for (size_t i = 0; i < ev_bal.size(); ++i) {
      if (ev_bal[i] == bal && ev_val[i] == val) {
        ev_mask[i] |= 1u << acc;
        return;
      }
    }
    ev_bal.push_back(bal);
    ev_val.push_back(val);
    ev_mask.push_back(1u << acc);
  }

  void dispatch(const Msg& m) {
    switch (m.kind) {
      case PREPARE: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal > a.promised) {
          a.promised = m.bal;
          offer(Msg{PROMISE, m.dst, m.src, m.bal, 0, a.acc_bal, a.acc_val});
        }
        break;
      }
      case ACCEPT: {
        Acceptor& a = acceptors[m.dst];
        if (m.bal >= a.promised) {
          a.promised = a.promised > m.bal ? a.promised : m.bal;
          a.acc_bal = m.bal;
          a.acc_val = m.val;
          record_accept(m.dst, m.bal, m.val);
          offer(Msg{ACCEPTED, m.dst, m.src, m.bal, m.val, 0, 0});
        }
        break;
      }
      case PROMISE: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::P1 || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        if (m.prev_bal > p.best_bal) {
          p.best_bal = m.prev_bal;
          p.best_val = m.prev_val;
        }
        if (__builtin_popcount(p.heard) >= quorum) {
          p.phase = Proposer::P2;
          p.heard = 0;
          p.prop_val = p.best_bal > 0 ? p.best_val : p.own_val;
          broadcast(p, ACCEPT);
        }
        break;
      }
      case ACCEPTED: {
        Proposer& p = proposers[m.dst];
        if (p.phase != Proposer::P2 || m.bal != p.bal) break;
        p.heard |= 1u << m.src;
        if (__builtin_popcount(p.heard) >= quorum) {
          p.phase = Proposer::DONE;
          p.decided_val = p.prop_val;
        }
        break;
      }
    }
  }

  bool all_done() const {
    for (const auto& p : proposers)
      if (p.phase != Proposer::DONE) return false;
    return true;
  }

  Result run(int max_steps) {
    int steps = 0;
    while (steps < max_steps && !all_done()) {
      ++steps;
      if (!network.empty() && rng.uniform() >= timeout_weight) {
        int i = rng.below(static_cast<int>(network.size()));
        Msg m = network[i];
        if (rng.uniform() >= p_dup) {  // not duplicated: consume the slot
          network[i] = network.back();
          network.pop_back();
        }
        dispatch(m);
      } else {
        // Fire one live proposer's timeout.
        int live = 0;
        for (const auto& p : proposers) live += p.phase != Proposer::DONE;
        if (live == 0) break;
        int pick = rng.below(live);
        for (auto& p : proposers) {
          if (p.phase == Proposer::DONE) continue;
          if (pick-- == 0) {
            ++p.rnd;
            p.bal = make_ballot(p.rnd, p.pid);
            p.phase = Proposer::P1;
            p.heard = 0;
            p.best_bal = p.best_val = 0;
            broadcast(p, PREPARE);
            break;
          }
        }
      }
    }

    // Omniscient oracle over the full accept history.
    int n_chosen = 0;
    int32_t chosen_val = -1;
    bool validity = true;
    for (size_t i = 0; i < ev_bal.size(); ++i) {
      if (__builtin_popcount(ev_mask[i]) >= quorum) {
        if (n_chosen == 0 || ev_val[i] != chosen_val) ++n_chosen;
        chosen_val = ev_val[i];
        validity &= ev_val[i] >= kValueBase && ev_val[i] < kValueBase + n_prop;
      }
    }
    bool agreement = n_chosen <= 1;
    for (const auto& p : proposers) {
      if (p.decided_val >= 0)
        agreement &= n_chosen == 1 && p.decided_val == chosen_val;
    }
    return Result{all_done() ? 1 : 0, agreement ? 1 : 0, validity ? 1 : 0,
                  n_chosen, steps};
  }
};

}  // namespace

extern "C" {

// Packing limits: voter sets are uint32 bitmasks; ballots pack (round, pid)
// with kMaxProposers.  Out-of-range topologies would silently corrupt
// verdicts (shift UB / ballot collisions) — fail loudly instead.
static bool valid_topology(int32_t n_prop, int32_t n_acc) {
  return n_prop >= 1 && n_prop <= kMaxProposers && n_acc >= 1 && n_acc <= 32;
}

// Runs `n_runs` independent seeded instances; fills `out` with 5 int32 per
// run: decided, agreement_ok, validity_ok, n_chosen, steps.  On an invalid
// topology every field is set to -1 (the Python wrapper validates first).
void run_batch(uint64_t seed0, int32_t n_runs, int32_t n_prop, int32_t n_acc,
               double p_drop, double p_dup, double timeout_weight,
               int32_t max_steps, int32_t* out) {
  if (!valid_topology(n_prop, n_acc)) {
    for (int32_t i = 0; i < 5 * n_runs; ++i) out[i] = -1;
    return;
  }
  for (int32_t r = 0; r < n_runs; ++r) {
    Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, p_drop, p_dup,
            timeout_weight);
    Result res = sim.run(max_steps);
    std::memcpy(out + 5 * r, &res, sizeof(res));
  }
}

// CPU-reference throughput: total scheduler events processed across
// `n_runs` instances (the number BASELINE.md's config-1 row asks for).
int64_t bench_steps(uint64_t seed0, int32_t n_runs, int32_t n_prop,
                    int32_t n_acc, double p_drop, double p_dup,
                    double timeout_weight, int32_t max_steps) {
  if (!valid_topology(n_prop, n_acc)) return -1;
  int64_t total = 0;
  for (int32_t r = 0; r < n_runs; ++r) {
    Sim sim(seed0 + static_cast<uint64_t>(r), n_prop, n_acc, p_drop, p_dup,
            timeout_weight);
    total += sim.run(max_steps).steps;
  }
  return total;
}

}  // extern "C"
