"""paxos_tpu — a TPU-native batched-consensus fuzzing framework.

A brand-new framework with the capabilities of ``rgrover/cloud-haskell-paxos``
(see SURVEY.md): the reference's Proposer/Acceptor/Learner Cloud Haskell
processes and their send/expect message loop get a vectorized twin in which
thousands-to-millions of independent consensus instances advance in lockstep
as one fused JAX array program — ``vmap`` semantics over an ``instances``
axis, ``lax.scan`` over scheduler ticks, ``pjit`` sharding over a device
mesh — while message drop/reorder/duplication, acceptor crashes, and
Byzantine equivocation are injected as PRNG masks and safety/liveness
invariants are checked on-device.

Reference parity map (SURVEY.md §2: no file:line citations are possible —
the reference mount was empty at survey time; provenance labels per §0):

- ``Network.Transport`` seam [B]        -> :mod:`paxos_tpu.transport`
- ``distributed-process`` actor runtime  -> :mod:`paxos_tpu.core` (state
  arrays) + :mod:`paxos_tpu.protocols` (role transition functions)
- SimpleLocalnet deployment backend     -> :mod:`paxos_tpu.harness`
- Paxos roles / ``PaxosMessage`` [B]    -> :mod:`paxos_tpu.core.messages`,
  :mod:`paxos_tpu.protocols.paxos`
- monitors / failure notification       -> :mod:`paxos_tpu.faults`
- (new) on-device invariant checking    -> :mod:`paxos_tpu.check`
- (new) mesh sharding                   -> :mod:`paxos_tpu.parallel`
"""

__version__ = "0.1.0"

from paxos_tpu.core import ballot  # noqa: F401


def __getattr__(name):
    """Lazy top-level API: ``paxos_tpu.run`` / ``soak`` / ``shrink`` /
    ``SimConfig`` without paying the harness import at package import."""
    if name == "run":
        from paxos_tpu.harness.run import run

        return run
    if name == "soak":
        from paxos_tpu.harness.soak import soak

        return soak
    if name == "shrink":
        from paxos_tpu.harness.shrink import shrink

        return shrink
    if name == "SimConfig":
        from paxos_tpu.harness.config import SimConfig

        return SimConfig
    if name == "check_exhaustive":
        from paxos_tpu.cpu_ref.exhaustive import check_exhaustive

        return check_exhaustive
    if name == "check_mp_exhaustive":
        from paxos_tpu.cpu_ref.mp_exhaustive import check_mp_exhaustive

        return check_mp_exhaustive
    if name == "check_fp_exhaustive":
        from paxos_tpu.cpu_ref.fp_exhaustive import check_fp_exhaustive

        return check_fp_exhaustive
    if name == "check_raft_exhaustive":
        from paxos_tpu.cpu_ref.raft_exhaustive import check_raft_exhaustive

        return check_raft_exhaustive
    raise AttributeError(f"module 'paxos_tpu' has no attribute {name!r}")
