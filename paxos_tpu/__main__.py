from paxos_tpu.harness.cli import main

raise SystemExit(main())
