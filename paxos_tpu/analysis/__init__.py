"""Static determinism auditor: jaxpr-level PRNG/purity/structure checks.

Everything here runs at TRACE time — no campaign is executed.  The three
audit layers (``prng_audit``, ``purity``, ``structure``) consume closed
jaxprs produced by ``trace`` and report :class:`~paxos_tpu.analysis.audit.Finding`
records; ``audit.run_audit`` orchestrates the full matrix and backs the
``paxos_tpu audit`` CLI subcommand.
"""

from paxos_tpu.analysis.audit import AuditReport, Finding, run_audit

__all__ = ["AuditReport", "Finding", "run_audit"]
