"""Audit orchestrator: trace the matrix, run every check, report findings.

``run_audit`` is the single entry point behind the ``paxos_tpu audit``
CLI subcommand, scripts/audit.sh, the tier-1 smoke, and tests/test_audit.
Exit discipline: a clean audit returns a report with zero findings; the
CLI maps findings to exit code 2 (distinct from crashes).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, Optional


@dataclasses.dataclass(frozen=True)
class Finding:
    """One audit violation — ``message`` must name the offending stream /
    primitive / leaf / file so the fix needs no re-tracing to locate.

    ``data`` optionally carries the same facts structured (source leaf,
    sink, primitive, ...) for machine consumers of ``audit --json`` —
    the bench/fleet gates parse it instead of regexing ``message``."""

    check: str  # e.g. "stream-collision", "purity", "flow-observer"
    where: str  # "protocol/config trace" or "file:line"
    message: str
    data: Optional[dict] = None

    def __str__(self) -> str:
        return f"[{self.check}] {self.message}"


@dataclasses.dataclass
class AuditReport:
    findings: list
    checks_run: int
    protocols: tuple
    configs: tuple

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        return json.dumps(
            {
                "ok": self.ok,
                "checks_run": self.checks_run,
                "protocols": list(self.protocols),
                "configs": list(self.configs),
                "findings": [dataclasses.asdict(f) for f in self.findings],
            },
            indent=2,
        )

    def summary(self) -> str:
        lines = [
            f"audit: {self.checks_run} checks over "
            f"{len(self.protocols)} protocols x {len(self.configs)} configs"
        ]
        if self.ok:
            lines.append("audit: OK (no findings)")
        else:
            lines.append(f"audit: {len(self.findings)} finding(s)")
            lines += [f"  {f}" for f in self.findings]
        return "\n".join(lines)


def run_audit(
    protocols: Optional[Iterable[str]] = None,
    configs: Optional[Iterable[str]] = None,
    structure: bool = False,
    lint: bool = True,
) -> AuditReport:
    """Trace every (protocol, config) cell and run the audit layers.

    ``structure`` additionally runs the default-off leaf checks and the
    golden treedef/config-fingerprint diffs (default OFF — see
    :mod:`paxos_tpu.analysis.structure`).  ``lint`` runs the AST pass
    over the traced packages (once, not per cell).
    """
    from paxos_tpu.analysis import flow as flow_mod
    from paxos_tpu.analysis import prng_audit, purity, structure as struct_mod
    from paxos_tpu.analysis import trace as trace_mod

    protos = tuple(protocols) if protocols else trace_mod.PROTOCOLS
    confs = tuple(configs) if configs else tuple(trace_mod.CONFIG_MATRIX)
    for p in protos:
        if p not in trace_mod.PROTOCOLS:
            raise ValueError(f"unknown protocol {p!r}")
    for c in confs:
        if c not in trace_mod.CONFIG_MATRIX:
            raise ValueError(f"unknown audit config {c!r}")

    findings: list = []
    checks = 0
    for protocol in protos:
        # Packed-layout version guard is ALWAYS on (not gated behind
        # ``structure``): a layout edit without a version bump corrupts
        # live checkpoints, which is never a release-gate-only concern.
        findings += struct_mod.audit_layout(protocol)
        # Write-set + clamp-hoist guards are likewise always on: a tick
        # writing outside its declared *_TICK_WRITES would have that write
        # silently dropped by the delta codec, and a ballot clamp leaking
        # back into the per-tick body silently re-taxes every tick.
        findings += struct_mod.audit_write_set(protocol)
        findings += struct_mod.audit_clamp_hoist(protocol)
        checks += 3
        traces = {}
        for config_name in confs:
            cfg = trace_mod.build_config(protocol, config_name)
            xla = trace_mod.trace_xla_step(protocol, cfg)
            ctr = trace_mod.trace_counter_tick(protocol, cfg)
            plan = trace_mod.trace_plan_sample(cfg)
            traces[config_name] = (xla, ctr)
            f = cfg.fault
            wload_on = cfg.workload.enabled()
            findings += prng_audit.audit_xla_folds(
                protocol, config_name, xla, f, wload_on=wload_on
            )
            findings += prng_audit.audit_counter_streams(
                protocol, config_name, ctr, f, wload_on=wload_on
            )
            findings += prng_audit.audit_dead_draws(protocol, config_name, xla)
            findings += prng_audit.audit_plan_folds(
                protocol, config_name, plan, f
            )
            findings += purity.audit_jaxpr_purity(
                f"{protocol}/{config_name} xla step", xla
            )
            findings += purity.audit_jaxpr_purity(
                f"{protocol}/{config_name} fused tick", ctr
            )
            # Dataflow non-interference theorems (analysis/flow.py) are
            # ALWAYS on: a leaked observer value or an off-site fault knob
            # is a silent corruption of every campaign, not a release-gate
            # concern.  Same for the eqn-count budget — silent trace
            # blowup taxes every compile and every tick.
            findings += flow_mod.audit_flow(
                protocol, config_name, cfg, xla, ctr
            )
            findings += flow_mod.audit_eqn_budget(
                protocol, config_name, xla, ctr
            )
            # The arrival-sampling/queue scope must appear exactly when
            # the workload plane is on (both engines fold the queue under
            # workload.generator.WLOAD_SCOPE).
            findings += flow_mod.audit_wload_scope(
                protocol, config_name, wload_on, xla, ctr
            )
            checks += 9
            if structure:
                findings += struct_mod.audit_default_off_leaves(
                    protocol, config_name, cfg
                )
                findings += struct_mod.audit_goldens(protocol, config_name, cfg)
                checks += 2
        if "default" in traces and "telemetry" in traces:
            findings += prng_audit.audit_telemetry_parity(
                protocol,
                traces["default"][0], traces["telemetry"][0],
                traces["default"][1], traces["telemetry"][1],
            )
            checks += 1
        if "default" in traces and "coverage" in traces:
            findings += prng_audit.audit_coverage_parity(
                protocol,
                traces["default"][0], traces["coverage"][0],
                traces["default"][1], traces["coverage"][1],
            )
            checks += 1
        if "default" in traces and "margin" in traces:
            findings += prng_audit.audit_margin_parity(
                protocol,
                traces["default"][0], traces["margin"][0],
                traces["default"][1], traces["margin"][1],
            )
            checks += 1
        if "default" in traces and "workload" in traces:
            # Not a pure observer: the workload plane legitimately draws
            # the arrival stream, so parity means "exactly that draw and
            # nothing else" (see prng_audit.audit_workload_parity).
            findings += prng_audit.audit_workload_parity(
                protocol,
                traces["default"][0], traces["workload"][0],
                traces["default"][1], traces["workload"][1],
            )
            checks += 1
        if "gray-chaos" in traces and "exposure" in traces:
            # Exposure's audit baseline is gray-chaos, not default: the
            # exposure cell rides the gray-chaos faults so its per-class
            # arms actually trace (see trace._exposure).
            findings += prng_audit.audit_exposure_parity(
                protocol,
                traces["gray-chaos"][0], traces["exposure"][0],
                traces["gray-chaos"][1], traces["exposure"][1],
            )
            checks += 1
    if lint:
        findings += purity.audit_traced_sources()
        checks += 1
    return AuditReport(
        findings=findings, checks_run=checks, protocols=protos, configs=confs
    )
