"""Dataflow non-interference auditor: taint analysis over traced jaxprs.

The repo's central contract — observers never influence protocol behavior,
faults act only through their declared injection sites, lanes are
independent — has so far been enforced *dynamically*, by bit-identical
golden schedule digests at a handful of pinned configs.  This pass makes
the contract *static*: a dataflow proof over the closed jaxprs of every
(protocol, config) audit cell that holds for all inputs, not just the
sampled ones.  Three always-on theorems:

1. **Observer non-interference** — taint seeded at the telemetry /
   coverage / exposure / margin Optional leaves of the step input must
   never reach a protocol-state output or any PRNG-consuming eqn.
   Observer leaves may flow into observer outputs (that's their job).
2. **Fault-channel confinement** — taint seeded at every ``FaultPlan``
   leaf may reach protocol state only through a *registered injection
   site*: a ``faults.injector.fault_site(name)`` scope whose name is
   registered (with the matching fault channel) either globally in
   ``injector.INJECTOR_FAULT_SITES`` or in the owning protocol's
   ``*_FAULT_SITES`` table (core/*state.py).  Plan leaves reaching
   observer outputs (exposure counts faults; telemetry records them) are
   legitimate and exempt.
3. **Lane independence** — every eqn touching lane-indexed state (any
   leaf whose trailing axis is the instance axis) must preserve that
   axis elementwise/slice/broadcast-wise; cross-lane reductions are
   accepted only under a ``kernels.quorum.lane_reduce(name)`` scope with
   ``name`` in :data:`LANE_REDUCE_SITES`.

Plus a checker-isolation corollary of (1): taint seeded at the learner
(checker) leaves must not reach non-learner protocol state — the checker
observes, it must not steer.  Multi-Paxos is exempt by design: its
leader lease legitimately consumes ``learner.chosen`` counts
(protocols/multipaxos.py ``chosen_count`` -> lease/progress logic).

Sites and allowlists are ``jax.named_scope`` tags — metadata riding each
eqn's ``source_info.name_stack``, zero device ops, schedules stay
bit-identical (the goldens pin this).  Findings name the source leaf, the
sink, and the offending primitive with its file:line, in the PR 4
auditor's reporting style.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax

from paxos_tpu.analysis.audit import Finding
from paxos_tpu.analysis.jaxpr_tools import Literal, is_prng_eqn
from paxos_tpu.faults.injector import INJECTOR_FAULT_SITES

# Leaf-path prefixes of the observer planes (theorem 1 seeds; also the
# exempt sinks for theorems 1 and 2 — observers may read anything).  The
# client-workload queue counts here too: its arrival RANDOMNESS rides a
# registered stream (audited by prng_audit.audit_workload_parity), but
# its STATE must never steer the protocol — open-loop means the queue
# observes the commit edge, it does not gate proposals.
OBSERVER_PREFIXES = ("telemetry.", "coverage.", "exposure.", "margin.",
                     "wload.")

# Leaf-path prefix of the safety checker's state (checker-isolation seeds).
CHECKER_PREFIX = "learner."

# Protocols whose checker legitimately feeds protocol logic (see module
# docstring) — checker-isolation is skipped there, the other theorems run.
CHECKER_EXEMPT = ("multipaxos",)

# FaultPlan leaf -> fault channel.  A registered site absorbs exactly its
# declared channels, so e.g. the skew site cannot launder a crash window.
PLAN_CHANNELS = {
    "crash_start": "crash",
    "crash_end": "crash",
    "pcrash_start": "crash",
    "pcrash_end": "crash",
    "equivocate": "equiv",
    "part_start": "partition",
    "part_end": "partition",
    "aside": "partition",
    "pside": "partition",
    "part_dir": "partition",
    "link_drop": "flaky",
    "link_dup": "flaky",
    "ptimeout": "skew",
    "pboff": "skew",
    "link_delay": "delay",
}

# Allowlisted cross-lane reduction regions (kernels.quorum.lane_reduce
# tags).  "summarize" = report reductions (harness/run.py), "quorum" =
# future cross-lane quorum-system merges (ROADMAP item 1),
# "coverage_union" = the union Bloom filter (obs/coverage.py).
LANE_REDUCE_SITES = frozenset({"summarize", "quorum", "coverage_union"})

_SITE_RE = re.compile(r"__fault_site__([A-Za-z0-9_]+?)(?:/|$)")
_LANE_RE = re.compile(r"__lane_ok__([A-Za-z0-9_]+?)(?:/|$)")

# Elementwise (shape-preserving, lane-preserving) primitives seen across
# the audit matrix plus common neighbors.  An unlisted primitive touching
# lane-indexed data is a finding — extend deliberately, not defensively.
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "max", "min",
    "and", "or", "xor", "not", "neg", "sign", "abs", "exp", "exp2", "log",
    "log1p", "expm1", "tanh", "logistic", "sqrt", "rsqrt", "cbrt", "floor",
    "ceil", "round", "eq", "ne", "lt", "le", "gt", "ge", "select_n",
    "convert_element_type", "bitcast_convert_type", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "population_count",
    "clz", "clamp", "stop_gradient", "copy", "nextafter", "is_finite",
    "erf", "erf_inv", "erfc", "sin", "cos", "atan2", "square",
    "reduce_precision", "real", "imag",
})

_REDUCES = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "reduce_xor", "argmax", "argmin", "reduce",
})

_CUMULATIVE = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})

# Call-like higher-order primitives: one inner jaxpr, invars/outvars 1:1.
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat", "checkpoint", "custom_jvp_call",
    "custom_vjp_call", "custom_jvp_call_jaxpr",
})


def _src(eqn) -> str:
    """"file:line (function)" for an eqn, via jax's own summarizer."""
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown source>"


def _scopes(eqn) -> "tuple[tuple[str, ...], tuple[str, ...]]":
    """(fault-site names, lane-ok names) tagged on this eqn's name stack."""
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:
        return (), ()
    return (
        tuple(_SITE_RE.findall(stack)),
        tuple(_LANE_RE.findall(stack)),
    )


def _call_jaxpr(eqn):
    """The inner jaxpr of a call-like eqn (invars/outvars map 1:1)."""
    inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    if inner is None:
        return None
    return inner.jaxpr if hasattr(inner, "jaxpr") else inner


def fault_sites(protocol: str) -> "dict[str, frozenset[str]]":
    """Registered site name -> absorbable channels for ``protocol``."""
    if protocol == "paxos":
        from paxos_tpu.core.state import PAXOS_FAULT_SITES as table
    elif protocol == "multipaxos":
        from paxos_tpu.core.mp_state import MP_FAULT_SITES as table
    elif protocol == "fastpaxos":
        from paxos_tpu.core.fp_state import FP_FAULT_SITES as table
    elif protocol == "raftcore":
        from paxos_tpu.core.raft_state import RAFT_FAULT_SITES as table
    elif protocol == "synchpaxos":
        from paxos_tpu.core.sp_state import SP_FAULT_SITES as table
    else:
        raise ValueError(f"unknown protocol {protocol!r}")
    merged = dict(INJECTOR_FAULT_SITES)
    merged.update(table)
    return {name: frozenset(chans) for name, chans in merged.items()}


@dataclasses.dataclass(frozen=True, order=True)
class Label:
    """One taint mark: ``kind`` in {obs, fault, checker}, the source
    ``leaf`` path it was seeded at, and (fault only) its ``channel``."""

    kind: str
    leaf: str
    channel: str = ""


@dataclasses.dataclass(frozen=True)
class FlowSpec:
    """Everything the engines need to know about one traced step program:
    the invar layout ([state leaves..., middle leaves..., plan leaves...]),
    the leaf paths, the lane width, and the protocol's site registry."""

    protocol: str
    state_paths: "tuple[str, ...]"
    plan_paths: "tuple[str, ...]"
    n_inst: int
    sites: "dict[str, frozenset[str]]"
    check_checker: bool = True


def build_spec(protocol: str, cfg) -> FlowSpec:
    """Spec for ``cfg``'s trace cell (leaf inventory from fresh templates)."""
    from paxos_tpu.harness.run import init_plan, init_state
    from paxos_tpu.utils import bitops

    return FlowSpec(
        protocol=protocol,
        state_paths=tuple(bitops.leaf_paths(init_state(cfg))),
        plan_paths=tuple(bitops.leaf_paths(init_plan(cfg))),
        n_inst=cfg.n_inst,
        sites=fault_sites(protocol),
        check_checker=protocol not in CHECKER_EXEMPT,
    )


def _read(env, atom):
    if isinstance(atom, Literal):
        return frozenset()
    return env.get(atom, frozenset())


class _TaintEngine:
    """Theorems 1, 2 and checker isolation: label propagation with
    site absorption, then sink checks on the state outvars."""

    def __init__(self, spec: FlowSpec, where: str):
        self.spec = spec
        self.where = where
        self.findings: "list[Finding]" = []
        self._seen: set = set()
        self._bad_sites: set = set()

    # -- finding helpers ---------------------------------------------------

    def _emit(self, check: str, message: str, data: dict) -> None:
        key = (check, data.get("source"), data.get("sink"))
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            Finding(check=check, where=self.where, message=message, data=data)
        )

    def _unregistered(self, name: str, eqn) -> None:
        if name in self._bad_sites:
            return
        self._bad_sites.add(name)
        self.findings.append(
            Finding(
                check="flow-site",
                where=self.where,
                message=(
                    f"{self.where}: fault_site tag {name!r} is not registered"
                    f" for protocol {self.spec.protocol!r} (at {_src(eqn)})"
                    " — add it to the protocol's *_FAULT_SITES table or"
                    " injector.INJECTOR_FAULT_SITES"
                ),
                data={"site": name, "primitive": eqn.primitive.name},
            )
        )

    # -- propagation -------------------------------------------------------

    def _absorb(self, labels, site_names, eqn):
        if not labels:
            return labels
        out = labels
        for name in site_names:
            chans = self.spec.sites.get(name)
            if chans is None:
                self._unregistered(name, eqn)
                continue
            out = frozenset(
                l for l in out
                if not (l.kind == "fault" and l.channel in chans)
            )
        return out

    def run(self, closed) -> "list[Finding]":
        jaxpr = closed.jaxpr
        spec = self.spec
        n_state, n_plan = len(spec.state_paths), len(spec.plan_paths)
        env: dict = {}
        producer: dict = {}
        for i, v in enumerate(jaxpr.invars[:n_state]):
            path = spec.state_paths[i]
            if path.startswith(OBSERVER_PREFIXES):
                env[v] = frozenset({Label("obs", path)})
            elif spec.check_checker and path.startswith(CHECKER_PREFIX):
                env[v] = frozenset({Label("checker", path)})
        for i, v in enumerate(jaxpr.invars[len(jaxpr.invars) - n_plan:]):
            path = spec.plan_paths[i]
            env[v] = frozenset(
                {Label("fault", path, PLAN_CHANNELS.get(path, "other"))}
            )
        self._walk(jaxpr, env, producer, frozenset())
        self._check_sinks(jaxpr, env, producer)
        return self.findings

    def _walk(self, jaxpr, env, producer, inherited) -> None:
        for eqn in jaxpr.eqns:
            sites, _ = _scopes(eqn)
            active = inherited | frozenset(sites)
            prim = eqn.primitive.name
            inner = _call_jaxpr(eqn) if prim in _CALL_PRIMS else None
            if inner is not None and len(inner.invars) == len(eqn.invars):
                sub_env: dict = {}
                sub_prod: dict = {}
                for ov, iv in zip(inner.invars, eqn.invars):
                    sub_env[ov] = self._absorb(_read(env, iv), active, eqn)
                self._walk(inner, sub_env, sub_prod, active)
                for ov_out, ov_in in zip(eqn.outvars, inner.outvars):
                    env[ov_out] = _read(sub_env, ov_in)
                    producer[ov_out] = sub_prod.get(ov_in, eqn)
                continue
            if prim == "cond":
                self._walk_cond(eqn, env, producer, active)
                continue
            if prim == "scan":
                self._walk_fixpoint(
                    eqn, env, producer, active,
                    eqn.params["jaxpr"].jaxpr, eqn.params["num_carry"],
                )
                continue
            if prim == "while":
                self._walk_fixpoint(
                    eqn, env, producer, active,
                    eqn.params["body_jaxpr"].jaxpr, len(eqn.outvars),
                    n_skip=eqn.params["cond_nconsts"]
                    + eqn.params["body_nconsts"],
                )
                continue
            # Default (covers every first-order primitive and any unmapped
            # higher-order one, conservatively): union of input labels.
            labels = frozenset().union(
                *(_read(env, v) for v in eqn.invars)
            ) if eqn.invars else frozenset()
            labels = self._absorb(labels, active, eqn)
            if labels and is_prng_eqn(eqn):
                for l in sorted(labels):
                    self._emit(
                        "flow-prng",
                        f"{self.where}: {l.kind} leaf {l.leaf!r} feeds"
                        f" PRNG primitive {prim!r} at {_src(eqn)} — PRNG"
                        " streams must not depend on"
                        f" {'observer' if l.kind == 'obs' else l.kind}"
                        " data",
                        {
                            "theorem": "prng",
                            "source": l.leaf,
                            "sink": f"prng:{prim}",
                            "primitive": prim,
                            "site": _src(eqn),
                        },
                    )
            for ov in eqn.outvars:
                env[ov] = labels
                producer[ov] = eqn

    def _walk_cond(self, eqn, env, producer, active) -> None:
        joined: "list[frozenset]" = [
            frozenset() for _ in eqn.outvars
        ]
        for branch in eqn.params["branches"]:
            bj = branch.jaxpr if hasattr(branch, "jaxpr") else branch
            sub_env = {}
            for ov, iv in zip(bj.invars, eqn.invars[1:]):
                sub_env[ov] = self._absorb(_read(env, iv), active, eqn)
            self._walk(bj, sub_env, {}, active)
            for i, ov_in in enumerate(bj.outvars):
                joined[i] = joined[i] | _read(sub_env, ov_in)
        pred = self._absorb(_read(env, eqn.invars[0]), active, eqn)
        for ov, labels in zip(eqn.outvars, joined):
            env[ov] = labels | pred
            producer[ov] = eqn

    def _walk_fixpoint(
        self, eqn, env, producer, active, body, n_carry, n_skip=None
    ) -> None:
        """Label fixpoint over a scan/while carry (labels only grow, so
        at most len(carry)+1 rounds)."""
        ins = [self._absorb(_read(env, v), active, eqn) for v in eqn.invars]
        if n_skip is None:  # scan: consts then carry then xs
            n_consts = eqn.params["num_consts"]
            pre, carry, xs = (
                ins[:n_consts],
                ins[n_consts:n_consts + n_carry],
                ins[n_consts + n_carry:],
            )
        else:  # while: cond+body consts then carry
            pre, carry, xs = ins[:n_skip], ins[n_skip:], []
        for _ in range(len(carry) + 2):
            sub_env = {}
            for ov, labels in zip(body.invars, pre + carry + xs):
                sub_env[ov] = labels
            self._walk(body, sub_env, {}, active)
            outs = [_read(sub_env, ov) for ov in body.outvars]
            new_carry = [
                c | o for c, o in zip(carry, outs[:len(carry)])
            ]
            if new_carry == carry:
                break
            carry = new_carry
        ys = outs[len(carry):] if n_skip is None else []
        for ov, labels in zip(eqn.outvars, carry + ys):
            env[ov] = labels
            producer[ov] = eqn

    # -- sinks -------------------------------------------------------------

    def _check_sinks(self, jaxpr, env, producer) -> None:
        spec = self.spec
        for i, ov in enumerate(jaxpr.outvars):
            if isinstance(ov, Literal) or i >= len(spec.state_paths):
                continue
            path = spec.state_paths[i]
            if path.startswith(OBSERVER_PREFIXES):
                continue  # observers may read anything
            eqn = producer.get(ov)
            via = (
                f"produced by {eqn.primitive.name!r} at {_src(eqn)}"
                if eqn is not None
                else "passed through unchanged"
            )
            prim = eqn.primitive.name if eqn is not None else "<passthrough>"
            site = _src(eqn) if eqn is not None else "<input>"
            for l in sorted(_read(env, ov)):
                if l.kind == "obs":
                    self._emit(
                        "flow-observer",
                        f"{self.where}: observer leaf {l.leaf!r} reaches"
                        f" protocol-state output {path!r} ({via}) —"
                        " observers must not influence protocol behavior",
                        {
                            "theorem": "observer",
                            "source": l.leaf,
                            "sink": path,
                            "primitive": prim,
                            "site": site,
                        },
                    )
                elif l.kind == "fault":
                    self._emit(
                        "flow-fault",
                        f"{self.where}: fault-plan leaf {l.leaf!r}"
                        f" (channel {l.channel!r}) reaches protocol-state"
                        f" output {path!r} outside any registered"
                        f" injection site ({via})",
                        {
                            "theorem": "fault",
                            "source": l.leaf,
                            "sink": path,
                            "channel": l.channel,
                            "primitive": prim,
                            "site": site,
                        },
                    )
                elif l.kind == "checker" and not path.startswith(
                    CHECKER_PREFIX
                ):
                    self._emit(
                        "flow-checker",
                        f"{self.where}: checker leaf {l.leaf!r} reaches"
                        f" protocol-state output {path!r} ({via}) — the"
                        " safety checker observes, it must not steer",
                        {
                            "theorem": "checker",
                            "source": l.leaf,
                            "sink": path,
                            "primitive": prim,
                            "site": site,
                        },
                    )


class _LaneEngine:
    """Theorem 3: every eqn touching lane-indexed data must preserve the
    trailing instance axis; cross-lane mixing only under an allowlisted
    ``lane_reduce`` tag."""

    def __init__(self, spec: FlowSpec, where: str):
        self.spec = spec
        self.where = where
        self.findings: "list[Finding]" = []
        self._seen: set = set()

    def _emit(self, eqn, source: Optional[str], reason: str) -> None:
        prim = eqn.primitive.name
        key = (prim, source, reason)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            Finding(
                check="flow-lane",
                where=self.where,
                message=(
                    f"{self.where}: {reason} (primitive {prim!r} at"
                    f" {_src(eqn)}, lane data from"
                    f" {source or '<unknown leaf>'!r}) — lanes must stay"
                    " independent outside allowlisted reductions"
                ),
                data={
                    "theorem": "lane",
                    "source": source,
                    "sink": f"eqn:{prim}",
                    "primitive": prim,
                    "site": _src(eqn),
                },
            )
        )

    def run(self, closed) -> "list[Finding]":
        jaxpr = closed.jaxpr
        spec = self.spec
        n_state, n_plan = len(spec.state_paths), len(spec.plan_paths)
        axes: dict = {}
        src: dict = {}
        for i, v in enumerate(jaxpr.invars[:n_state]):
            shape = getattr(v.aval, "shape", ())
            if shape and shape[-1] == spec.n_inst:
                axes[v] = len(shape) - 1
                src[v] = spec.state_paths[i]
        for i, v in enumerate(jaxpr.invars[len(jaxpr.invars) - n_plan:]):
            shape = getattr(v.aval, "shape", ())
            if shape and shape[-1] == spec.n_inst:
                axes[v] = len(shape) - 1
                src[v] = spec.plan_paths[i]
        self._walk(jaxpr, axes, src, frozenset())
        return self.findings

    def _lane_ok(self, eqn, inherited) -> bool:
        _, tags = _scopes(eqn)
        return any(
            t in LANE_REDUCE_SITES for t in tuple(inherited) + tags
        )

    def _walk(self, jaxpr, axes, src, inherited) -> None:
        for eqn in jaxpr.eqns:
            tracked = [
                (v, axes[v])
                for v in eqn.invars
                if not isinstance(v, Literal) and v in axes
            ]
            if not tracked:
                continue
            _, tags = _scopes(eqn)
            ok_here = inherited | frozenset(
                t for t in tags if t in LANE_REDUCE_SITES
            )
            source = next(
                (src[v] for v, _ in tracked if v in src), None
            )
            prim = eqn.primitive.name
            inner = _call_jaxpr(eqn) if prim in _CALL_PRIMS else None
            if inner is not None and len(inner.invars) == len(eqn.invars):
                sub_axes, sub_src = {}, {}
                for ov, iv in zip(inner.invars, eqn.invars):
                    if not isinstance(iv, Literal) and iv in axes:
                        sub_axes[ov] = axes[iv]
                        if iv in src:
                            sub_src[ov] = src[iv]
                self._walk(inner, sub_axes, sub_src, ok_here)
                for ov_out, ov_in in zip(eqn.outvars, inner.outvars):
                    if ov_in in sub_axes:
                        axes[ov_out] = sub_axes[ov_in]
                        src[ov_out] = sub_src.get(ov_in, source)
                continue
            outs = self._rule(eqn, axes, src, source, ok_here)
            if outs is None:
                continue
            for ov, ax in zip(eqn.outvars, outs):
                if ax is not None:
                    axes[ov] = ax
                    src.setdefault(ov, source)

    # -- per-primitive lane rules -----------------------------------------

    def _rule(self, eqn, axes, src, source, ok_here):
        """Output lane axes for one eqn (None entries = untracked), or
        ``None`` after emitting a finding / handling outputs itself."""
        prim = eqn.primitive.name
        tracked = [
            (v, axes[v])
            for v in eqn.invars
            if not isinstance(v, Literal) and v in axes
        ]
        in_ax = tracked[0][1]
        allowed = self._lane_ok(eqn, ok_here)

        def viol(reason):
            if not allowed:
                self._emit(eqn, source, reason)
            return None

        if prim in _ELEMENTWISE:
            if any(ax != in_ax for _, ax in tracked):
                return viol("elementwise op mixes different lane axes")
            return [in_ax] * len(eqn.outvars)

        if prim == "broadcast_in_dim":
            dims = eqn.params["broadcast_dimensions"]
            return [dims[in_ax]]

        if prim in _REDUCES:
            red_axes = eqn.params.get("axes", eqn.params.get("dimensions"))
            if red_axes is None:
                red_axes = ()
            if in_ax in red_axes:
                return viol("cross-lane reduction over the instance axis")
            shift = sum(1 for a in red_axes if a < in_ax)
            return [in_ax - shift] * len(eqn.outvars)

        if prim in _CUMULATIVE:
            if eqn.params.get("axis") == in_ax:
                return viol("cumulative op scans across the instance axis")
            return [in_ax] * len(eqn.outvars)

        if prim == "squeeze":
            dims = eqn.params["dimensions"]
            if in_ax in dims:
                return viol("squeeze removes the instance axis")
            return [in_ax - sum(1 for d in dims if d < in_ax)]

        if prim == "reshape":
            operand = eqn.invars[0]
            if operand not in axes:
                return [None]
            old = operand.aval.shape
            new = eqn.params["new_sizes"]
            ax = axes[operand]
            keep = len(old) - ax  # trailing block that must survive
            if len(new) >= keep and tuple(new[len(new) - keep:]) == tuple(
                old[ax:]
            ):
                return [len(new) - keep]
            return viol("reshape folds the instance axis into another")

        if prim == "transpose":
            perm = eqn.params["permutation"]
            return [perm.index(in_ax)]

        if prim == "slice":
            operand = eqn.invars[0]
            ax = axes[operand]
            start = eqn.params["start_indices"][ax]
            limit = eqn.params["limit_indices"][ax]
            strides = eqn.params["strides"]
            stride = 1 if strides is None else strides[ax]
            if start == 0 and limit == operand.aval.shape[ax] and stride == 1:
                return [ax]
            return viol("partial slice along the instance axis")

        if prim == "concatenate":
            if eqn.params["dimension"] == in_ax:
                return viol("concatenate along the instance axis")
            if any(ax != in_ax for _, ax in tracked):
                return viol("concatenate mixes different lane axes")
            return [in_ax]

        if prim == "pad":
            cfg = eqn.params["padding_config"][in_ax]
            if tuple(cfg) != (0, 0, 0):
                return viol("pad along the instance axis")
            return [in_ax]

        if prim == "rev":
            if in_ax in eqn.params["dimensions"]:
                return viol("reverse permutes the instance axis")
            return [in_ax]

        if prim == "sort":
            if eqn.params.get("dimension") == in_ax:
                return viol("sort along the instance axis")
            return [axes.get(v) for v in eqn.invars]

        if prim == "dynamic_slice":
            operand = eqn.invars[0]
            if operand not in axes:
                return [None]
            ax = axes[operand]
            idx_tracked = any(
                v in axes
                for v in eqn.invars[1:]
                if not isinstance(v, Literal)
            )
            full = (
                eqn.params["slice_sizes"][ax] == operand.aval.shape[ax]
            )
            if full and not idx_tracked:
                return [ax]
            return viol("dynamic_slice addresses the instance axis")

        if prim == "dynamic_update_slice":
            operand, update = eqn.invars[0], eqn.invars[1]
            if operand not in axes and update not in axes:
                return [None]
            ax = axes.get(operand, axes.get(update))
            idx_tracked = any(
                v in axes
                for v in eqn.invars[2:]
                if not isinstance(v, Literal)
            )
            shapes_ok = (
                operand.aval.shape[ax] == update.aval.shape[ax]
                if ax < min(len(operand.aval.shape), len(update.aval.shape))
                else False
            )
            if shapes_ok and not idx_tracked:
                return [ax]
            return viol("dynamic_update_slice addresses the instance axis")

        if prim in ("gather", "scatter", "scatter-add", "scatter_add",
                    "scatter_mul", "scatter_min", "scatter_max"):
            return viol(
                "gather/scatter on lane-indexed data (no lane-preserving"
                " rule — use elementwise one-hot selects in step code)"
            )

        if prim == "cond":
            for branch in eqn.params["branches"]:
                bj = branch.jaxpr if hasattr(branch, "jaxpr") else branch
                sub_axes, sub_src = {}, {}
                for ov, iv in zip(bj.invars, eqn.invars[1:]):
                    if not isinstance(iv, Literal) and iv in axes:
                        sub_axes[ov] = axes[iv]
                        if iv in src:
                            sub_src[ov] = src[iv]
                self._walk(bj, sub_axes, sub_src, ok_here)
                for ov_out, ov_in in zip(eqn.outvars, bj.outvars):
                    if ov_in in sub_axes:
                        axes[ov_out] = sub_axes[ov_in]
                        src.setdefault(ov_out, source)
            return None

        if prim == "scan":
            return self._rule_scan(eqn, axes, src, source, ok_here, viol)

        if prim == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            n_skip = eqn.params["cond_nconsts"] + eqn.params["body_nconsts"]
            sub_axes, sub_src = {}, {}
            for ov, iv in zip(body.invars, eqn.invars[n_skip:]):
                if not isinstance(iv, Literal) and iv in axes:
                    sub_axes[ov] = axes[iv]
                    if iv in src:
                        sub_src[ov] = src[iv]
            self._walk(body, sub_axes, sub_src, ok_here)
            for ov_out, (ov_in, iv) in zip(
                eqn.outvars, zip(body.outvars, eqn.invars[n_skip:])
            ):
                carry_ax = axes.get(iv)
                if carry_ax is not None:
                    if sub_axes.get(ov_in) != carry_ax:
                        viol("lane axis not preserved through while carry")
                    else:
                        axes[ov_out] = carry_ax
                        src.setdefault(ov_out, source)
            return None

        return viol(f"no lane-propagation rule for primitive {prim!r}")

    def _rule_scan(self, eqn, axes, src, source, ok_here, viol):
        body = eqn.params["jaxpr"].jaxpr
        n_consts = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        sub_axes, sub_src = {}, {}
        for k, (ov, iv) in enumerate(zip(body.invars, eqn.invars)):
            if isinstance(iv, Literal) or iv not in axes:
                continue
            ax = axes[iv]
            if k >= n_consts + n_carry:  # xs: scan axis 0 stripped
                if ax == 0:
                    viol("scan iterates over the instance axis")
                    continue
                ax = ax - 1
            sub_axes[ov] = ax
            if iv in src:
                sub_src[ov] = src[iv]
        self._walk(body, sub_axes, sub_src, ok_here)
        for i, ov_out in enumerate(eqn.outvars):
            ov_in = body.outvars[i]
            if i < n_carry:
                iv = eqn.invars[n_consts + i]
                carry_ax = axes.get(iv)
                if carry_ax is None:
                    continue
                if sub_axes.get(ov_in) != carry_ax:
                    viol("lane axis not preserved through scan carry")
                else:
                    axes[ov_out] = carry_ax
                    src.setdefault(ov_out, source)
            else:  # ys stack a new leading axis
                ax = sub_axes.get(ov_in)
                if ax is not None:
                    axes[ov_out] = ax + 1
                    src.setdefault(ov_out, source)
        return None


# ---------------------------------------------------------------------------
# Jaxpr-size budget (satellite): total eqn counts per audit cell, pinned in
# analysis/goldens.EQN_GOLDENS the way layout/treedef goldens pin structure.

# Unexplained growth tolerance: absolute floor for tiny traces, relative
# for big ones.  Re-record deliberate changes with `audit --record-goldens`.
EQN_BUDGET_ABS = 24
EQN_BUDGET_REL = 0.10


def count_eqns(closed) -> int:
    """Total eqn count of a closed jaxpr, recursing into sub-jaxprs."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed

    def walk(jx) -> int:
        total = 0
        for eqn in jx.eqns:
            total += 1
            for v in eqn.params.values():
                vs = v if isinstance(v, (tuple, list)) else (v,)
                for b in vs:
                    if hasattr(b, "jaxpr"):
                        total += walk(b.jaxpr)
                    elif hasattr(b, "eqns"):
                        total += walk(b)
        return total

    return walk(jaxpr)


def audit_eqn_budget(
    protocol: str, config_name: str, xla, ctr
) -> "list[Finding]":
    """Compare this cell's recursive eqn counts against EQN_GOLDENS."""
    from paxos_tpu.analysis.goldens import EQN_GOLDENS

    golden = EQN_GOLDENS.get((protocol, config_name))
    if golden is None:
        return []  # cell not pinned (e.g. a future config) — nothing to diff
    findings = []
    for kind, closed in (("xla", xla), ("ctr", ctr)):
        want = golden[kind]
        got = count_eqns(closed)
        tol = max(EQN_BUDGET_ABS, int(want * EQN_BUDGET_REL))
        if abs(got - want) > tol:
            direction = "grew" if got > want else "shrank"
            findings.append(
                Finding(
                    check="eqn-budget",
                    where=f"{protocol}/{config_name} {kind} trace",
                    message=(
                        f"{protocol}/{config_name} {kind} trace {direction}"
                        f" to {got} eqns (golden {want}, tolerance"
                        f" {tol}) — unexplained trace-size drift; if"
                        " deliberate, re-record with"
                        " `paxos_tpu audit --record-goldens`"
                    ),
                    data={
                        "kind": kind,
                        "got": got,
                        "want": want,
                        "tolerance": tol,
                    },
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Client-workload scope registration: both engines fold the queue under
# workload.generator.WLOAD_SCOPE (a jax.named_scope, zero device ops), so
# the tag's presence in a traced step is exactly "the queue fold traced".


def _has_scope(closed, tag: str) -> bool:
    from paxos_tpu.analysis.jaxpr_tools import iter_eqns

    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    for eqn in iter_eqns(jaxpr):
        try:
            if tag in str(eqn.source_info.name_stack):
                return True
        except Exception:
            continue
    return False


def audit_wload_scope(
    protocol: str, config_name: str, wload_on: bool, xla, ctr
) -> "list[Finding]":
    """The arrival-sampling/queue scope appears iff the workload is on.

    On with the tag absent = the queue fold silently no-oped (the SLO
    report would read all-zero and look like a perfectly idle system);
    off with the tag present = default-off is violated structurally even
    if the PRNG half happened to stay clean."""
    from paxos_tpu.workload.generator import WLOAD_SCOPE

    findings = []
    for kind, closed in (("xla step", xla), ("fused tick", ctr)):
        where = f"{protocol}/{config_name} {kind}"
        present = _has_scope(closed, WLOAD_SCOPE)
        if wload_on and not present:
            findings.append(Finding(
                check="wload-scope", where=where,
                message=(
                    f"workload plane is ON for {where} but the "
                    f"{WLOAD_SCOPE!r} scope never traced: the client-queue "
                    f"fold silently no-oped (wload leaf missing or the "
                    f"protocol's observe() hook was dropped)"
                ),
            ))
        elif not wload_on and present:
            findings.append(Finding(
                check="wload-scope", where=where,
                message=(
                    f"{WLOAD_SCOPE!r} scope traced in {where} although the "
                    f"workload plane is off: the queue fold must trace "
                    f"away when cfg.workload.mix == 'off'"
                ),
            ))
    return findings


# ---------------------------------------------------------------------------
# Entry points


def analyze_step_jaxpr(closed, spec: FlowSpec, where: str) -> "list[Finding]":
    """All flow theorems over one traced step program."""
    findings = _TaintEngine(spec, where).run(closed)
    findings += _LaneEngine(spec, where).run(closed)
    return findings


def audit_flow(
    protocol: str, config_name: str, cfg, xla, ctr
) -> "list[Finding]":
    """Flow pass for one audit cell: both engines' traces, all theorems."""
    spec = build_spec(protocol, cfg)
    findings = analyze_step_jaxpr(
        xla, spec, f"{protocol}/{config_name} xla step"
    )
    findings += analyze_step_jaxpr(
        ctr, spec, f"{protocol}/{config_name} fused tick"
    )
    return findings
