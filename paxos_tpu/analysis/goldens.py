"""Recorded structural goldens for the audit matrix (see structure.py).

Re-record deliberately after an INTENTIONAL state-layout or
config-default change (``structure.record_goldens`` prints a fresh
table; ``python -m paxos_tpu audit --structure`` diffs against it) and
call out the checkpoint/schedule break in the PR description.

Reading the table is itself documentation: gray/corrupt configs share
the default treedef (gray faults live in the *plan*, not the state),
while stale, telemetry, and coverage each add their own leaves.

Round 8 re-record: the coverage plane (obs.coverage) added an Optional
``coverage`` leaf to every protocol state, so every TREEDEF cell re-keyed
(default-off still prunes it to None — the leaf exists in the treedef
string as None, which is the point of the fingerprint) and the new
"coverage" audit column landed.  LAYOUT_GOLDENS are byte-identical to
round 7: the sketch rides the fused engine's generic passthrough codec,
touching no packed word.

Round 9 re-record: the fault-exposure plane (obs.exposure) added an
Optional ``exposure`` leaf to every protocol state — same contract, so
again every TREEDEF cell re-keyed and the "exposure" audit column landed.
CONFIG_GOLDENS kept every existing cell (the fingerprint drops a
default-off ExposureConfig, so recorded campaigns keep their identity)
and LAYOUT_GOLDENS are byte-identical to round 8: the counters ride the
same generic passthrough codec, touching no packed word.

Round 11 re-record: the delta-codec release (*-packed-v2).  proposer.bal
widened for the chunk-boundary ballot-clamp hoist (17 bits single-decree,
12 bits Multi-Paxos — headroom over the unchanged report limits), and
``bitops.layout_fields`` now folds the per-protocol ``__reads__`` /
``__writes__`` tick declarations, so every LAYOUT cell re-keyed and every
CONFIG cell re-keyed through the version fold.  TREEDEF cells are
byte-identical to round 9: packing width is invisible to the pytree
structure.
"""

# (protocol, config_name) -> sha256[:16] of str(tree_structure(init_state))
TREEDEF_GOLDENS: dict = {
    ("paxos", "default"): "70a1f204f28dd0aa",
    ("paxos", "gray-chaos"): "70a1f204f28dd0aa",
    ("paxos", "corrupt"): "70a1f204f28dd0aa",
    ("paxos", "stale"): "0fcacc1bd7c74b55",
    ("paxos", "telemetry"): "7a56062c9b43bf0e",
    ("paxos", "coverage"): "7fc0dc957ffba1a6",
    ("paxos", "exposure"): "abf4caef44447651",
    ("multipaxos", "default"): "88bd02bb2b5551ef",
    ("multipaxos", "gray-chaos"): "88bd02bb2b5551ef",
    ("multipaxos", "corrupt"): "88bd02bb2b5551ef",
    ("multipaxos", "stale"): "f67f33b1f405dec3",
    ("multipaxos", "telemetry"): "3c50da89e2d28493",
    ("multipaxos", "coverage"): "56706cb41780cc81",
    ("multipaxos", "exposure"): "7a8170eb91005d93",
    ("fastpaxos", "default"): "e913bd8567a69327",
    ("fastpaxos", "gray-chaos"): "e913bd8567a69327",
    ("fastpaxos", "corrupt"): "e913bd8567a69327",
    ("fastpaxos", "stale"): "5457e8db0c93e25f",
    ("fastpaxos", "telemetry"): "eb85b0ad26ba060b",
    ("fastpaxos", "coverage"): "4e778741ff9e754a",
    ("fastpaxos", "exposure"): "49a01bd8d6395d03",
    ("raftcore", "default"): "4677b44e023ecd4e",
    ("raftcore", "gray-chaos"): "4677b44e023ecd4e",
    ("raftcore", "corrupt"): "4677b44e023ecd4e",
    ("raftcore", "stale"): "02ee82c800930ef8",
    ("raftcore", "telemetry"): "c837c63a9ea5977d",
    ("raftcore", "coverage"): "9ad9c3c4300d53ab",
    ("raftcore", "exposure"): "33c040107e72e5c6",
}

# (protocol, config_name) -> SimConfig.fingerprint() of the audit config
# Re-recorded once for the packed-layout release: fingerprint() now folds
# the per-protocol layout version (paxos-packed-v1 / multipaxos-packed-v1 /
# fastpaxos-packed-v1 / raftcore-packed-v1), re-keying every cell.
CONFIG_GOLDENS: dict = {
    ("paxos", "default"): "18de70331e1f13fe",
    ("paxos", "gray-chaos"): "d375ecd0a0130cae",
    ("paxos", "corrupt"): "eb408e35f2743ee1",
    ("paxos", "stale"): "9bda52d0d855f214",
    ("paxos", "telemetry"): "a71171b4a628a1be",
    ("paxos", "coverage"): "aeaca5f24fbdfcea",
    ("paxos", "exposure"): "9d9c96379b0b9972",
    ("multipaxos", "default"): "3cc71d01ec7ec84e",
    ("multipaxos", "gray-chaos"): "120f1c32622f6769",
    ("multipaxos", "corrupt"): "04b29093ed3c7ad6",
    ("multipaxos", "stale"): "74305d7853d2b18c",
    ("multipaxos", "telemetry"): "e69a9168cd12ae35",
    ("multipaxos", "coverage"): "035d59fe1e972a90",
    ("multipaxos", "exposure"): "b73cc15a9d4d42f7",
    ("fastpaxos", "default"): "f666d3ca9066fcb7",
    ("fastpaxos", "gray-chaos"): "5c52340743718cc9",
    ("fastpaxos", "corrupt"): "6dd54955e967856c",
    ("fastpaxos", "stale"): "2cb53cfea1744c3f",
    ("fastpaxos", "telemetry"): "904e07b30eb99bd4",
    ("fastpaxos", "coverage"): "70390a8635254d21",
    ("fastpaxos", "exposure"): "994c005d0bf061b3",
    ("raftcore", "default"): "db4b28950ad681d8",
    ("raftcore", "gray-chaos"): "3250ae1b49be26b9",
    ("raftcore", "corrupt"): "ce3ffc88b74b0b9f",
    ("raftcore", "stale"): "68b16adbda72f7ce",
    ("raftcore", "telemetry"): "12dfb29f71807ce0",
    ("raftcore", "coverage"): "d78aa0ad54c87736",
    ("raftcore", "exposure"): "faecd36c8698b3e9",
}

# protocol -> {"version": layout version string, "fields": canonical per-field
# descriptors from bitops.layout_fields}.  The audit's layout-version guard
# (structure.audit_layout, always ON in `paxos_tpu audit`) diffs the live
# tables against this: an edited field with an UNCHANGED version is the
# failure mode this exists to catch — silently re-binning live campaign
# state.  Bump the *_LAYOUT_VERSION in core/*_state.py, re-record here, and
# name the version in the commit.
LAYOUT_GOLDENS: dict = {
    "paxos": {
        "version": "paxos-packed-v2",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.bal', 'proposer.best_bal', 'proposer.best_val', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "multipaxos": {
        "version": "multipaxos-packed-v2",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('accepted.*', 'acceptor.*', 'base', 'coverage.*', 'exposure.*', 'learner.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('accepted.*', 'acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "accepted.bal":
                "word=accd slot=0 bits=12 signed=0 bool=0 bv=None",
            "accepted.present":
                "word=accd slot=2 bits=1 signed=0 bool=1 bv=None",
            "accepted.val":
                "word=accd slot=1 bits=13 signed=0 bool=0 bv=None",
            "acceptor.log":
                "stream=acc_log bal=11 val=13",
            "acceptor.snap_log":
                "stream=snap_log bal=11 val=13 optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=18 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=13 signed=0 bool=0 bv=None",
            "learner.lt_bv":
                "word=lt slot=0 bits=24 signed=0 bool=0 bv=(11, 13)",
            "learner.lt_mask":
                "word=lt slot=1 bits=n_acc signed=0 bool=0 bv=None",
            "promises.bal":
                "word=prom slot=0 bits=12 signed=0 bool=0 bv=None",
            "promises.p_bv":
                "stream=prom_bv bal=11 val=13",
            "promises.present":
                "word=prom slot=1 bits=1 signed=0 bool=1 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.candidate_timer":
                "word=prop0 slot=3 bits=12 signed=0 bool=0 bv=None",
            "proposer.commit_idx":
                "word=prop0 slot=2 bits=6 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop1 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.last_chosen_count":
                "word=prop1 slot=1 bits=16 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.recov_bv":
                "stream=recov bal=11 val=13",
            "requests.bal":
                "word=req slot=0 bits=12 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=13 signed=0 bool=0 bv=None",
        },
    },
    "fastpaxos": {
        "version": "fastpaxos-packed-v2",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.bal', 'proposer.best_bal', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.rep_mask', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "raftcore": {
        "version": "raftcore-packed-v2",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.voted', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'proposer.bal', 'proposer.decided_val', 'proposer.ent_term', 'proposer.ent_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.ent_term":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_term":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_voted":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.voted":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.ent_term":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.ent_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=15 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
}
