"""Recorded structural goldens for the audit matrix (see structure.py).

Re-record deliberately after an INTENTIONAL state-layout or
config-default change (``structure.record_goldens`` prints a fresh
table; ``python -m paxos_tpu audit --structure`` diffs against it) and
call out the checkpoint/schedule break in the PR description.

Reading the table is itself documentation: gray/corrupt configs share
the default treedef (gray faults live in the *plan*, not the state),
while stale, telemetry, and coverage each add their own leaves.

Round 8 re-record: the coverage plane (obs.coverage) added an Optional
``coverage`` leaf to every protocol state, so every TREEDEF cell re-keyed
(default-off still prunes it to None — the leaf exists in the treedef
string as None, which is the point of the fingerprint) and the new
"coverage" audit column landed.  LAYOUT_GOLDENS are byte-identical to
round 7: the sketch rides the fused engine's generic passthrough codec,
touching no packed word.

Round 9 re-record: the fault-exposure plane (obs.exposure) added an
Optional ``exposure`` leaf to every protocol state — same contract, so
again every TREEDEF cell re-keyed and the "exposure" audit column landed.
CONFIG_GOLDENS kept every existing cell (the fingerprint drops a
default-off ExposureConfig, so recorded campaigns keep their identity)
and LAYOUT_GOLDENS are byte-identical to round 8: the counters ride the
same generic passthrough codec, touching no packed word.

Round 11 re-record: the delta-codec release (*-packed-v2).  proposer.bal
widened for the chunk-boundary ballot-clamp hoist (17 bits single-decree,
12 bits Multi-Paxos — headroom over the unchanged report limits), and
``bitops.layout_fields`` now folds the per-protocol ``__reads__`` /
``__writes__`` tick declarations, so every LAYOUT cell re-keyed and every
CONFIG cell re-keyed through the version fold.  TREEDEF cells are
byte-identical to round 9: packing width is invisible to the pytree
structure.

Round 12 re-record: the safety-margin plane (obs.margin) added an
Optional ``margin`` leaf to every protocol state (TREEDEF re-key, same
contract as rounds 8/9) and its counters joined the fused passthrough
via the per-protocol ``__reads__``/``__writes__`` globs — since
``bitops.layout_fields`` folds those declarations, every LAYOUT cell
re-keyed under the *-packed-v3 versions and every CONFIG cell re-keyed
through the version fold.  No packed word changed: margin arrays ride
the generic passthrough codec, like coverage and exposure before them.
"""

# (protocol, config_name) -> sha256[:16] of str(tree_structure(init_state))
TREEDEF_GOLDENS: dict = {
    ("paxos", "default"): "b944b96eecb6916b",
    ("paxos", "gray-chaos"): "b944b96eecb6916b",
    ("paxos", "corrupt"): "b944b96eecb6916b",
    ("paxos", "stale"): "57701d5e08af921d",
    ("paxos", "telemetry"): "908380c70bf11357",
    ("paxos", "coverage"): "020d06ba22d05602",
    ("paxos", "exposure"): "88c737d571032a75",
    ("paxos", "margin"): "c947f544922d8dec",
    ("multipaxos", "default"): "4c14452e0c86cf21",
    ("multipaxos", "gray-chaos"): "4c14452e0c86cf21",
    ("multipaxos", "corrupt"): "4c14452e0c86cf21",
    ("multipaxos", "stale"): "3bd7c26ccfe579f4",
    ("multipaxos", "telemetry"): "323fcfc3ea7b5a65",
    ("multipaxos", "coverage"): "f56ad531d82cf7de",
    ("multipaxos", "exposure"): "8987d6e996265649",
    ("multipaxos", "margin"): "349ec6b34e3a8e5b",
    ("fastpaxos", "default"): "dc7bc31711913343",
    ("fastpaxos", "gray-chaos"): "dc7bc31711913343",
    ("fastpaxos", "corrupt"): "dc7bc31711913343",
    ("fastpaxos", "stale"): "d55120263fd2c558",
    ("fastpaxos", "telemetry"): "6c909576a4254e82",
    ("fastpaxos", "coverage"): "58d871e93cedb922",
    ("fastpaxos", "exposure"): "1557839690837a21",
    ("fastpaxos", "margin"): "eb72261b26b797f0",
    ("raftcore", "default"): "e3edde71713d0764",
    ("raftcore", "gray-chaos"): "e3edde71713d0764",
    ("raftcore", "corrupt"): "e3edde71713d0764",
    ("raftcore", "stale"): "e8b2170a5e3c9bdd",
    ("raftcore", "telemetry"): "dc51a7e9f7d6e61d",
    ("raftcore", "coverage"): "299c2f793394aaa8",
    ("raftcore", "exposure"): "3207dd7b792d96e6",
    ("raftcore", "margin"): "2e4b9fcbe2bfeb7b",
}

# (protocol, config_name) -> SimConfig.fingerprint() of the audit config
# Re-recorded once for the packed-layout release: fingerprint() now folds
# the per-protocol layout version (paxos-packed-v1 / multipaxos-packed-v1 /
# fastpaxos-packed-v1 / raftcore-packed-v1), re-keying every cell.
CONFIG_GOLDENS: dict = {
    ("paxos", "default"): "2f2c18a912fd9d9f",
    ("paxos", "gray-chaos"): "1ca7815b8ded8f80",
    ("paxos", "corrupt"): "34b6abbb425004e2",
    ("paxos", "stale"): "4700921b7f908b7f",
    ("paxos", "telemetry"): "15fd1a096d103553",
    ("paxos", "coverage"): "8ac6f2bb875b4564",
    ("paxos", "exposure"): "c07f92cc60bbf635",
    ("paxos", "margin"): "e17ce877e256b71c",
    ("multipaxos", "default"): "a92a094d538d14e8",
    ("multipaxos", "gray-chaos"): "d2d0078df18f7bdc",
    ("multipaxos", "corrupt"): "70b8b09fbdab2c0b",
    ("multipaxos", "stale"): "eb1a07fa0d72ae6f",
    ("multipaxos", "telemetry"): "889fed636367e055",
    ("multipaxos", "coverage"): "21ae9e433def7c67",
    ("multipaxos", "exposure"): "d6ec699879cdc876",
    ("multipaxos", "margin"): "5457a5841cb263e1",
    ("fastpaxos", "default"): "1e0a4848f3c6713a",
    ("fastpaxos", "gray-chaos"): "f23cda06403ec7e2",
    ("fastpaxos", "corrupt"): "f64e61267636c6c4",
    ("fastpaxos", "stale"): "5531b38c51d3389b",
    ("fastpaxos", "telemetry"): "d547af2c3903f6fd",
    ("fastpaxos", "coverage"): "41bfdaf87b1d61cb",
    ("fastpaxos", "exposure"): "3d4360e4c1e628df",
    ("fastpaxos", "margin"): "b975b70c4f9e7b4f",
    ("raftcore", "default"): "8b3a6800f7c68486",
    ("raftcore", "gray-chaos"): "c511f800922f6478",
    ("raftcore", "corrupt"): "cbebe656f68feba2",
    ("raftcore", "stale"): "aeba76a9df603c7e",
    ("raftcore", "telemetry"): "8289428af0eba4d7",
    ("raftcore", "coverage"): "4e059d075c566e47",
    ("raftcore", "exposure"): "65e509af4be13f0e",
    ("raftcore", "margin"): "0f9cc700f0b45551",
}

# protocol -> {"version": layout version string, "fields": canonical per-field
# descriptors from bitops.layout_fields}.  The audit's layout-version guard
# (structure.audit_layout, always ON in `paxos_tpu audit`) diffs the live
# tables against this: an edited field with an UNCHANGED version is the
# failure mode this exists to catch — silently re-binning live campaign
# state.  Bump the *_LAYOUT_VERSION in core/*_state.py, re-record here, and
# name the version in the commit.
LAYOUT_GOLDENS: dict = {
    "paxos": {
        "version": "paxos-packed-v3",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.best_bal', 'proposer.best_val', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "multipaxos": {
        "version": "multipaxos-packed-v3",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('accepted.*', 'acceptor.*', 'base', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('accepted.*', 'acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "accepted.bal":
                "word=accd slot=0 bits=12 signed=0 bool=0 bv=None",
            "accepted.present":
                "word=accd slot=2 bits=1 signed=0 bool=1 bv=None",
            "accepted.val":
                "word=accd slot=1 bits=13 signed=0 bool=0 bv=None",
            "acceptor.log":
                "stream=acc_log bal=11 val=13",
            "acceptor.snap_log":
                "stream=snap_log bal=11 val=13 optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=18 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=13 signed=0 bool=0 bv=None",
            "learner.lt_bv":
                "word=lt slot=0 bits=24 signed=0 bool=0 bv=(11, 13)",
            "learner.lt_mask":
                "word=lt slot=1 bits=n_acc signed=0 bool=0 bv=None",
            "promises.bal":
                "word=prom slot=0 bits=12 signed=0 bool=0 bv=None",
            "promises.p_bv":
                "stream=prom_bv bal=11 val=13",
            "promises.present":
                "word=prom slot=1 bits=1 signed=0 bool=1 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.candidate_timer":
                "word=prop0 slot=3 bits=12 signed=0 bool=0 bv=None",
            "proposer.commit_idx":
                "word=prop0 slot=2 bits=6 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop1 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.last_chosen_count":
                "word=prop1 slot=1 bits=16 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.recov_bv":
                "stream=recov bal=11 val=13",
            "requests.bal":
                "word=req slot=0 bits=12 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=13 signed=0 bool=0 bv=None",
        },
    },
    "fastpaxos": {
        "version": "fastpaxos-packed-v3",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.best_bal', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.rep_mask', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "raftcore": {
        "version": "raftcore-packed-v3",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.voted', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.decided_val', 'proposer.ent_term', 'proposer.ent_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.ent_term":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_term":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_voted":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.voted":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.ent_term":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.ent_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=15 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
}

# Recursive eqn counts per (protocol, config) audit cell, both engines —
# the jaxpr-size budget (PR 14).  A cell drifting past analysis/flow.py's
# tolerance (max(24, 10%)) fails the always-on `eqn-budget` audit check:
# trace blowup taxes every compile and usually signals an accidental
# unfused arm or a lost gate.  Deliberate changes re-record via
# `paxos_tpu audit --record-goldens` (prints this dict ready to paste).
EQN_GOLDENS: dict = {
    ("paxos", "default"): {"xla": 606, "ctr": 594},
    ("paxos", "gray-chaos"): {"xla": 824, "ctr": 885},
    ("paxos", "corrupt"): {"xla": 774, "ctr": 881},
    ("paxos", "stale"): {"xla": 787, "ctr": 883},
    ("paxos", "telemetry"): {"xla": 756, "ctr": 744},
    ("paxos", "coverage"): {"xla": 926, "ctr": 914},
    ("paxos", "exposure"): {"xla": 981, "ctr": 1042},
    ("paxos", "margin"): {"xla": 680, "ctr": 668},
    ("multipaxos", "default"): {"xla": 767, "ctr": 739},
    ("multipaxos", "gray-chaos"): {"xla": 1023, "ctr": 1079},
    ("multipaxos", "corrupt"): {"xla": 983, "ctr": 1088},
    ("multipaxos", "stale"): {"xla": 996, "ctr": 1090},
    ("multipaxos", "telemetry"): {"xla": 920, "ctr": 892},
    ("multipaxos", "coverage"): {"xla": 1258, "ctr": 1230},
    ("multipaxos", "exposure"): {"xla": 1175, "ctr": 1231},
    ("multipaxos", "margin"): {"xla": 845, "ctr": 817},
    ("fastpaxos", "default"): {"xla": 818, "ctr": 806},
    ("fastpaxos", "gray-chaos"): {"xla": 1120, "ctr": 1181},
    ("fastpaxos", "corrupt"): {"xla": 1070, "ctr": 1177},
    ("fastpaxos", "stale"): {"xla": 1083, "ctr": 1179},
    ("fastpaxos", "telemetry"): {"xla": 968, "ctr": 956},
    ("fastpaxos", "coverage"): {"xla": 1138, "ctr": 1126},
    ("fastpaxos", "exposure"): {"xla": 1279, "ctr": 1340},
    ("fastpaxos", "margin"): {"xla": 912, "ctr": 900},
    ("raftcore", "default"): {"xla": 638, "ctr": 626},
    ("raftcore", "gray-chaos"): {"xla": 856, "ctr": 917},
    ("raftcore", "corrupt"): {"xla": 806, "ctr": 913},
    ("raftcore", "stale"): {"xla": 819, "ctr": 915},
    ("raftcore", "telemetry"): {"xla": 788, "ctr": 776},
    ("raftcore", "coverage"): {"xla": 958, "ctr": 946},
    ("raftcore", "exposure"): {"xla": 1011, "ctr": 1072},
    ("raftcore", "margin"): {"xla": 712, "ctr": 700},
}
