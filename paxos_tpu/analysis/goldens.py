"""Recorded structural goldens for the audit matrix (see structure.py).

Re-record deliberately after an INTENTIONAL state-layout or
config-default change (``structure.record_goldens`` prints a fresh
table; ``python -m paxos_tpu audit --structure`` diffs against it) and
call out the checkpoint/schedule break in the PR description.

Reading the table is itself documentation: gray/corrupt configs share
the default treedef (gray faults live in the *plan*, not the state),
while stale, telemetry, and coverage each add their own leaves.

Round 8 re-record: the coverage plane (obs.coverage) added an Optional
``coverage`` leaf to every protocol state, so every TREEDEF cell re-keyed
(default-off still prunes it to None — the leaf exists in the treedef
string as None, which is the point of the fingerprint) and the new
"coverage" audit column landed.  LAYOUT_GOLDENS are byte-identical to
round 7: the sketch rides the fused engine's generic passthrough codec,
touching no packed word.

Round 9 re-record: the fault-exposure plane (obs.exposure) added an
Optional ``exposure`` leaf to every protocol state — same contract, so
again every TREEDEF cell re-keyed and the "exposure" audit column landed.
CONFIG_GOLDENS kept every existing cell (the fingerprint drops a
default-off ExposureConfig, so recorded campaigns keep their identity)
and LAYOUT_GOLDENS are byte-identical to round 8: the counters ride the
same generic passthrough codec, touching no packed word.
"""

# (protocol, config_name) -> sha256[:16] of str(tree_structure(init_state))
TREEDEF_GOLDENS: dict = {
    ("paxos", "default"): "70a1f204f28dd0aa",
    ("paxos", "gray-chaos"): "70a1f204f28dd0aa",
    ("paxos", "corrupt"): "70a1f204f28dd0aa",
    ("paxos", "stale"): "0fcacc1bd7c74b55",
    ("paxos", "telemetry"): "7a56062c9b43bf0e",
    ("paxos", "coverage"): "7fc0dc957ffba1a6",
    ("paxos", "exposure"): "abf4caef44447651",
    ("multipaxos", "default"): "88bd02bb2b5551ef",
    ("multipaxos", "gray-chaos"): "88bd02bb2b5551ef",
    ("multipaxos", "corrupt"): "88bd02bb2b5551ef",
    ("multipaxos", "stale"): "f67f33b1f405dec3",
    ("multipaxos", "telemetry"): "3c50da89e2d28493",
    ("multipaxos", "coverage"): "56706cb41780cc81",
    ("multipaxos", "exposure"): "7a8170eb91005d93",
    ("fastpaxos", "default"): "e913bd8567a69327",
    ("fastpaxos", "gray-chaos"): "e913bd8567a69327",
    ("fastpaxos", "corrupt"): "e913bd8567a69327",
    ("fastpaxos", "stale"): "5457e8db0c93e25f",
    ("fastpaxos", "telemetry"): "eb85b0ad26ba060b",
    ("fastpaxos", "coverage"): "4e778741ff9e754a",
    ("fastpaxos", "exposure"): "49a01bd8d6395d03",
    ("raftcore", "default"): "4677b44e023ecd4e",
    ("raftcore", "gray-chaos"): "4677b44e023ecd4e",
    ("raftcore", "corrupt"): "4677b44e023ecd4e",
    ("raftcore", "stale"): "02ee82c800930ef8",
    ("raftcore", "telemetry"): "c837c63a9ea5977d",
    ("raftcore", "coverage"): "9ad9c3c4300d53ab",
    ("raftcore", "exposure"): "33c040107e72e5c6",
}

# (protocol, config_name) -> SimConfig.fingerprint() of the audit config
# Re-recorded once for the packed-layout release: fingerprint() now folds
# the per-protocol layout version (paxos-packed-v1 / multipaxos-packed-v1 /
# fastpaxos-packed-v1 / raftcore-packed-v1), re-keying every cell.
CONFIG_GOLDENS: dict = {
    ("paxos", "default"): "f50cfbfdf74b11c0",
    ("paxos", "gray-chaos"): "a68d36156e155a29",
    ("paxos", "corrupt"): "1b476cdd907b5933",
    ("paxos", "stale"): "dd2e59a672568867",
    ("paxos", "telemetry"): "45769fa2f93945e0",
    ("paxos", "coverage"): "1688a7b588e353ce",
    ("paxos", "exposure"): "603bc79585bdf597",
    ("multipaxos", "default"): "c43e601ef68a237f",
    ("multipaxos", "gray-chaos"): "ef22269046287409",
    ("multipaxos", "corrupt"): "8175e48831a73e89",
    ("multipaxos", "stale"): "f68540b11905991c",
    ("multipaxos", "telemetry"): "4ea3f797b32bc566",
    ("multipaxos", "coverage"): "acdbcb7fcb033a3b",
    ("multipaxos", "exposure"): "8cacc47bbd0378c5",
    ("fastpaxos", "default"): "cb51e3867a43b91b",
    ("fastpaxos", "gray-chaos"): "d311d7e3d86192e7",
    ("fastpaxos", "corrupt"): "72485f432fb7393a",
    ("fastpaxos", "stale"): "0bc8e8e18a940735",
    ("fastpaxos", "telemetry"): "298edfbc20970277",
    ("fastpaxos", "coverage"): "4cf16c0d9ad6ccc6",
    ("fastpaxos", "exposure"): "ea463f9d5b1e9a59",
    ("raftcore", "default"): "ff49ab17defc9057",
    ("raftcore", "gray-chaos"): "1755349e01c9d063",
    ("raftcore", "corrupt"): "040a2cdb1838612f",
    ("raftcore", "stale"): "291ba0bd46e6cd30",
    ("raftcore", "telemetry"): "d0b50c940de6b66a",
    ("raftcore", "coverage"): "b2628ea1f5ad5604",
    ("raftcore", "exposure"): "a505137b82c1938e",
}

# protocol -> {"version": layout version string, "fields": canonical per-field
# descriptors from bitops.layout_fields}.  The audit's layout-version guard
# (structure.audit_layout, always ON in `paxos_tpu audit`) diffs the live
# tables against this: an edited field with an UNCHANGED version is the
# failure mode this exists to catch — silently re-binning live campaign
# state.  Bump the *_LAYOUT_VERSION in core/*_state.py, re-record here, and
# name the version in the commit.
LAYOUT_GOLDENS: dict = {
    "paxos": {
        "version": "paxos-packed-v1",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "multipaxos": {
        "version": "multipaxos-packed-v1",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "accepted.bal":
                "word=accd slot=0 bits=12 signed=0 bool=0 bv=None",
            "accepted.present":
                "word=accd slot=2 bits=1 signed=0 bool=1 bv=None",
            "accepted.val":
                "word=accd slot=1 bits=13 signed=0 bool=0 bv=None",
            "acceptor.log":
                "stream=acc_log bal=11 val=13",
            "acceptor.snap_log":
                "stream=snap_log bal=11 val=13 optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=18 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=13 signed=0 bool=0 bv=None",
            "learner.lt_bv":
                "word=lt slot=0 bits=24 signed=0 bool=0 bv=(11, 13)",
            "learner.lt_mask":
                "word=lt slot=1 bits=n_acc signed=0 bool=0 bv=None",
            "promises.bal":
                "word=prom slot=0 bits=12 signed=0 bool=0 bv=None",
            "promises.p_bv":
                "stream=prom_bv bal=11 val=13",
            "promises.present":
                "word=prom slot=1 bits=1 signed=0 bool=1 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=11 signed=0 bool=0 bv=None",
            "proposer.candidate_timer":
                "word=prop0 slot=3 bits=12 signed=0 bool=0 bv=None",
            "proposer.commit_idx":
                "word=prop0 slot=2 bits=6 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop1 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.last_chosen_count":
                "word=prop1 slot=1 bits=16 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.recov_bv":
                "stream=recov bal=11 val=13",
            "requests.bal":
                "word=req slot=0 bits=12 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=13 signed=0 bool=0 bv=None",
        },
    },
    "fastpaxos": {
        "version": "fastpaxos-packed-v1",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "raftcore": {
        "version": "raftcore-packed-v1",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.voted', 0))]",
            "acceptor.ent_term":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_term":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_voted":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.voted":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=15 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.ent_term":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.ent_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=15 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
}
