"""Recorded structural goldens for the audit matrix (see structure.py).

Re-record deliberately after an INTENTIONAL state-layout or
config-default change (``structure.record_goldens`` prints a fresh
table; ``python -m paxos_tpu audit --structure`` diffs against it) and
call out the checkpoint/schedule break in the PR description.

Reading the table is itself documentation: gray/corrupt configs share
the default treedef (gray faults live in the *plan*, not the state),
while stale and telemetry each add their own leaves.
"""

# (protocol, config_name) -> sha256[:16] of str(tree_structure(init_state))
TREEDEF_GOLDENS: dict = {
    ("paxos", "default"): "9ca86b00e7246200",
    ("paxos", "gray-chaos"): "9ca86b00e7246200",
    ("paxos", "corrupt"): "9ca86b00e7246200",
    ("paxos", "stale"): "2bfb7ddd9a9f5d8f",
    ("paxos", "telemetry"): "9d5b41ec09f7eab4",
    ("multipaxos", "default"): "e04bc854b35b2523",
    ("multipaxos", "gray-chaos"): "e04bc854b35b2523",
    ("multipaxos", "corrupt"): "e04bc854b35b2523",
    ("multipaxos", "stale"): "7718aed26d17215b",
    ("multipaxos", "telemetry"): "c566b8202d265ce7",
    ("fastpaxos", "default"): "fb315f08a32a08bf",
    ("fastpaxos", "gray-chaos"): "fb315f08a32a08bf",
    ("fastpaxos", "corrupt"): "fb315f08a32a08bf",
    ("fastpaxos", "stale"): "b95ad0ab7eb44998",
    ("fastpaxos", "telemetry"): "d3013fac26dae0b3",
    ("raftcore", "default"): "0620776d1e658d16",
    ("raftcore", "gray-chaos"): "0620776d1e658d16",
    ("raftcore", "corrupt"): "0620776d1e658d16",
    ("raftcore", "stale"): "8cb260a60823125a",
    ("raftcore", "telemetry"): "195f5cdf656377b4",
}

# (protocol, config_name) -> SimConfig.fingerprint() of the audit config
CONFIG_GOLDENS: dict = {
    ("paxos", "default"): "c66870e38738f078",
    ("paxos", "gray-chaos"): "c5d88efa1593e109",
    ("paxos", "corrupt"): "5610069aa64745b5",
    ("paxos", "stale"): "c1d24005bcc4cdd8",
    ("paxos", "telemetry"): "1e8ea8111735cffe",
    ("multipaxos", "default"): "1b934c22f736e9bc",
    ("multipaxos", "gray-chaos"): "3a0d10f31d095527",
    ("multipaxos", "corrupt"): "3f275ddad81a8896",
    ("multipaxos", "stale"): "2e64fd633a49c9eb",
    ("multipaxos", "telemetry"): "bf30a9aa158d482b",
    ("fastpaxos", "default"): "f0a2ff5f1f64c308",
    ("fastpaxos", "gray-chaos"): "9c2fe26d8b088798",
    ("fastpaxos", "corrupt"): "1b4a7bbe877196e5",
    ("fastpaxos", "stale"): "fa0b8b6c5cc2fd6f",
    ("fastpaxos", "telemetry"): "f172a2995af2be65",
    ("raftcore", "default"): "e278086e1936256a",
    ("raftcore", "gray-chaos"): "68c1f0b05b7f58d2",
    ("raftcore", "corrupt"): "1a7251d43bd82aa3",
    ("raftcore", "stale"): "5baa20380323d476",
    ("raftcore", "telemetry"): "c6fbcef2b33dd732",
}
