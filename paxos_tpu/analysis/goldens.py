"""Recorded structural goldens for the audit matrix (see structure.py).

Re-record deliberately after an INTENTIONAL state-layout or
config-default change (``structure.record_goldens`` prints a fresh
table; ``python -m paxos_tpu audit --structure`` diffs against it) and
call out the checkpoint/schedule break in the PR description.

Reading the table is itself documentation: gray/corrupt configs share
the default treedef (gray faults live in the *plan*, not the state),
while stale, telemetry, and coverage each add their own leaves.

Round 8 re-record: the coverage plane (obs.coverage) added an Optional
``coverage`` leaf to every protocol state, so every TREEDEF cell re-keyed
(default-off still prunes it to None — the leaf exists in the treedef
string as None, which is the point of the fingerprint) and the new
"coverage" audit column landed.  LAYOUT_GOLDENS are byte-identical to
round 7: the sketch rides the fused engine's generic passthrough codec,
touching no packed word.

Round 9 re-record: the fault-exposure plane (obs.exposure) added an
Optional ``exposure`` leaf to every protocol state — same contract, so
again every TREEDEF cell re-keyed and the "exposure" audit column landed.
CONFIG_GOLDENS kept every existing cell (the fingerprint drops a
default-off ExposureConfig, so recorded campaigns keep their identity)
and LAYOUT_GOLDENS are byte-identical to round 8: the counters ride the
same generic passthrough codec, touching no packed word.

Round 11 re-record: the delta-codec release (*-packed-v2).  proposer.bal
widened for the chunk-boundary ballot-clamp hoist (17 bits single-decree,
12 bits Multi-Paxos — headroom over the unchanged report limits), and
``bitops.layout_fields`` now folds the per-protocol ``__reads__`` /
``__writes__`` tick declarations, so every LAYOUT cell re-keyed and every
CONFIG cell re-keyed through the version fold.  TREEDEF cells are
byte-identical to round 9: packing width is invisible to the pytree
structure.

Round 12 re-record: the safety-margin plane (obs.margin) added an
Optional ``margin`` leaf to every protocol state (TREEDEF re-key, same
contract as rounds 8/9) and its counters joined the fused passthrough
via the per-protocol ``__reads__``/``__writes__`` globs — since
``bitops.layout_fields`` folds those declarations, every LAYOUT cell
re-keyed under the *-packed-v3 versions and every CONFIG cell re-keyed
through the version fold.  No packed word changed: margin arrays ride
the generic passthrough codec, like coverage and exposure before them.

Round 15 re-record: the bounded-delay fault dimension plus SynchPaxos.
``MsgBuf`` (and the Multi-Paxos promise/accepted buffers) gained an
optional ``until`` delivery-stamp leaf (None when ``p_delay`` is off), so
every TREEDEF cell re-keyed; ``FaultConfig`` gained the delay/SynchPaxos
knobs (p_delay / delay_max / delta / sp_unsafe_fast / ballot_stride), so
every CONFIG cell re-keyed through the fingerprint; the four existing
layouts bumped to *-packed-v4 (the ``until`` stamps ride the full-int32
passthrough, no packed word changed) and the synchpaxos rows landed
(synchpaxos-packed-v1 shares the classic single-decree widths).  The new
"delay-chaos" audit column pins the delay-lit trace across the matrix.

Round 20 re-record: the client-workload plane (workload.generator) added
an Optional ``wload`` leaf to every protocol state, so every TREEDEF cell
re-keyed (same contract as the coverage/exposure/margin rounds — the leaf
prunes to None by default) and the new "workload" audit column landed.
CONFIG_GOLDENS kept every existing cell (the fingerprint drops a
default-off WorkloadConfig), EQN_GOLDENS kept every existing cell (the
queue fold traces away when off), and LAYOUT_GOLDENS are byte-identical:
the queue's all-int32 instance-minor leaves ride the fused engine's
generic passthrough codec, touching no packed word and no version.
"""

# (protocol, config_name) -> sha256[:16] of str(tree_structure(init_state))
TREEDEF_GOLDENS: dict = {
    ("paxos", "default"): "5b68067ec67cd8f3",
    ("paxos", "gray-chaos"): "5b68067ec67cd8f3",
    ("paxos", "corrupt"): "5b68067ec67cd8f3",
    ("paxos", "stale"): "214005225c4b30d7",
    ("paxos", "delay-chaos"): "8040a2d86b0e3922",
    ("paxos", "telemetry"): "e81814bfe41f2847",
    ("paxos", "coverage"): "59d9e2ade2a41040",
    ("paxos", "exposure"): "617fb904a1d2de58",
    ("paxos", "margin"): "dd3bfa617441f218",
    ("paxos", "workload"): "172db31596257348",
    ("multipaxos", "default"): "25446d485a187cc6",
    ("multipaxos", "gray-chaos"): "25446d485a187cc6",
    ("multipaxos", "corrupt"): "25446d485a187cc6",
    ("multipaxos", "stale"): "93373ccf87ddf28b",
    ("multipaxos", "delay-chaos"): "623cc58e1b5fdd5a",
    ("multipaxos", "telemetry"): "ff3b5cbfa90590fa",
    ("multipaxos", "coverage"): "42f0149f3a8459aa",
    ("multipaxos", "exposure"): "dc6abbea27d4739d",
    ("multipaxos", "margin"): "b509ab92222e5e1c",
    ("multipaxos", "workload"): "fe5c46c3d2a23b53",
    ("fastpaxos", "default"): "33b0c6cd94ba8f10",
    ("fastpaxos", "gray-chaos"): "33b0c6cd94ba8f10",
    ("fastpaxos", "corrupt"): "33b0c6cd94ba8f10",
    ("fastpaxos", "stale"): "ac7a7fbec5816693",
    ("fastpaxos", "delay-chaos"): "6b6fde4537283781",
    ("fastpaxos", "telemetry"): "efc13861f431ffe2",
    ("fastpaxos", "coverage"): "f5d6f3e70e0e7681",
    ("fastpaxos", "exposure"): "7a57c110b828c3a9",
    ("fastpaxos", "margin"): "dfeeb43853dae9f1",
    ("fastpaxos", "workload"): "a2e3ae26318df6ff",
    ("raftcore", "default"): "effd9ee1f4606c8a",
    ("raftcore", "gray-chaos"): "effd9ee1f4606c8a",
    ("raftcore", "corrupt"): "effd9ee1f4606c8a",
    ("raftcore", "stale"): "66b6cf1fd6351a98",
    ("raftcore", "delay-chaos"): "e2b3eb86baea1890",
    ("raftcore", "telemetry"): "e109e6520e22dca3",
    ("raftcore", "coverage"): "0715366f9e84b225",
    ("raftcore", "exposure"): "4e9e8115fa03d799",
    ("raftcore", "margin"): "c1901f2e1d945707",
    ("raftcore", "workload"): "ec26d3d0b419ef69",
    ("synchpaxos", "default"): "6de0d059f2d0f1e7",
    ("synchpaxos", "gray-chaos"): "6de0d059f2d0f1e7",
    ("synchpaxos", "corrupt"): "6de0d059f2d0f1e7",
    ("synchpaxos", "stale"): "fbe06abc599bfddb",
    ("synchpaxos", "delay-chaos"): "e30590e38bc17f25",
    ("synchpaxos", "telemetry"): "08951d730a500c22",
    ("synchpaxos", "coverage"): "18766842f67347bb",
    ("synchpaxos", "exposure"): "4b68a12f326b06cf",
    ("synchpaxos", "margin"): "bf9b0703ba86227f",
    ("synchpaxos", "workload"): "cb3fdf53e74abda9",
}

# (protocol, config_name) -> SimConfig.fingerprint() of the audit config
# Re-recorded once for the packed-layout release: fingerprint() now folds
# the per-protocol layout version (paxos-packed-v1 / multipaxos-packed-v1 /
# fastpaxos-packed-v1 / raftcore-packed-v1), re-keying every cell.
CONFIG_GOLDENS: dict = {
    ("paxos", "default"): "d2367d0ccaf4df1e",
    ("paxos", "gray-chaos"): "9f09bee6a58b0247",
    ("paxos", "corrupt"): "00576b428f4cdec5",
    ("paxos", "stale"): "9ca806c50a1fe1b9",
    ("paxos", "delay-chaos"): "cad3ea76428a3a00",
    ("paxos", "telemetry"): "526797092404957d",
    ("paxos", "coverage"): "2d8f71710d52fe5f",
    ("paxos", "exposure"): "3def41a92aedfc70",
    ("paxos", "margin"): "555d36a19b0c3b31",
    ("paxos", "workload"): "93d13ab24e8b5726",
    ("multipaxos", "default"): "cf1c4abcbad29c64",
    ("multipaxos", "gray-chaos"): "0ecc0377861dde26",
    ("multipaxos", "corrupt"): "ed256ed66b19bbf7",
    ("multipaxos", "stale"): "fd1fcb1dffa8d769",
    ("multipaxos", "delay-chaos"): "e39169374aab173c",
    ("multipaxos", "telemetry"): "dccc306fe36d43cd",
    ("multipaxos", "coverage"): "be71e2b9117cbdd3",
    ("multipaxos", "exposure"): "d78d94882cfdc4bf",
    ("multipaxos", "margin"): "d8702c56eb7c03ba",
    ("multipaxos", "workload"): "9dbf46690801b92a",
    ("fastpaxos", "default"): "d154a3728a21c32c",
    ("fastpaxos", "gray-chaos"): "26e04659a98a4689",
    ("fastpaxos", "corrupt"): "e11dfadc0b1bb7e1",
    ("fastpaxos", "stale"): "afa9b79d3d4c124c",
    ("fastpaxos", "delay-chaos"): "90f2518ec0118977",
    ("fastpaxos", "telemetry"): "e6e09fbb82dd00df",
    ("fastpaxos", "coverage"): "be0e831f1f236579",
    ("fastpaxos", "exposure"): "abd8b026f01be70d",
    ("fastpaxos", "margin"): "7ccac7cc9158e4a4",
    ("fastpaxos", "workload"): "09d47f881bcceb81",
    ("raftcore", "default"): "2cfa9a3a96ee74ec",
    ("raftcore", "gray-chaos"): "7636267dbe764fc8",
    ("raftcore", "corrupt"): "e34cf38c966c8a95",
    ("raftcore", "stale"): "6fc365e38059ece0",
    ("raftcore", "delay-chaos"): "a2430716e6f2bfa5",
    ("raftcore", "telemetry"): "ad85e3d15e7712e4",
    ("raftcore", "coverage"): "b02c399b79465535",
    ("raftcore", "exposure"): "c29538c03042099b",
    ("raftcore", "margin"): "652762bc86ac291b",
    ("raftcore", "workload"): "8d74a01a7d5c4778",
    ("synchpaxos", "default"): "2eab6bb74daf06c1",
    ("synchpaxos", "gray-chaos"): "01a9b04108544a5d",
    ("synchpaxos", "corrupt"): "fb9411399ef3cf70",
    ("synchpaxos", "stale"): "486822d837a9f317",
    ("synchpaxos", "delay-chaos"): "975ec41373231359",
    ("synchpaxos", "telemetry"): "db353533a4be68b1",
    ("synchpaxos", "coverage"): "52194be2f0538706",
    ("synchpaxos", "exposure"): "a79f1ab6f217adf3",
    ("synchpaxos", "margin"): "bdc106defdc4a800",
    ("synchpaxos", "workload"): "e781e75ed94943c4",
}

# protocol -> {"version": layout version string, "fields": canonical per-field
# descriptors from bitops.layout_fields}.  The audit's layout-version guard
# (structure.audit_layout, always ON in `paxos_tpu audit`) diffs the live
# tables against this: an edited field with an UNCHANGED version is the
# failure mode this exists to catch — silently re-binning live campaign
# state.  Bump the *_LAYOUT_VERSION in core/*_state.py, re-record here, and
# name the version in the commit.
LAYOUT_GOLDENS: dict = {
    "paxos": {
        "version": "paxos-packed-v4",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.best_bal', 'proposer.best_val', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "multipaxos": {
        "version": "multipaxos-packed-v4",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('accepted.*', 'acceptor.*', 'base', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('accepted.*', 'acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'promises.*', 'proposer.*', 'requests.*', 'telemetry.*', 'tick')",
            "accepted.bal":
                "word=accd slot=0 bits=12 signed=0 bool=0 bv=None",
            "accepted.present":
                "word=accd slot=2 bits=1 signed=0 bool=1 bv=None",
            "accepted.val":
                "word=accd slot=1 bits=13 signed=0 bool=0 bv=None",
            "acceptor.log":
                "stream=acc_log bal=11 val=13",
            "acceptor.snap_log":
                "stream=snap_log bal=11 val=13 optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=18 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=13 signed=0 bool=0 bv=None",
            "learner.lt_bv":
                "word=lt slot=0 bits=24 signed=0 bool=0 bv=(11, 13)",
            "learner.lt_mask":
                "word=lt slot=1 bits=n_acc signed=0 bool=0 bv=None",
            "promises.bal":
                "word=prom slot=0 bits=12 signed=0 bool=0 bv=None",
            "promises.p_bv":
                "stream=prom_bv bal=11 val=13",
            "promises.present":
                "word=prom slot=1 bits=1 signed=0 bool=1 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.candidate_timer":
                "word=prop0 slot=3 bits=12 signed=0 bool=0 bv=None",
            "proposer.commit_idx":
                "word=prop0 slot=2 bits=6 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop1 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.last_chosen_count":
                "word=prop1 slot=1 bits=16 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.recov_bv":
                "stream=recov bal=11 val=13",
            "requests.bal":
                "word=req slot=0 bits=12 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=13 signed=0 bool=0 bv=None",
        },
    },
    "fastpaxos": {
        "version": "fastpaxos-packed-v4",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.best_bal', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.rep_mask', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "raftcore": {
        "version": "raftcore-packed-v4",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.voted', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.decided_val', 'proposer.ent_term', 'proposer.ent_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.ent_term":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_term":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_voted":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.voted":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.ent_term":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.ent_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=15 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
    "synchpaxos": {
        "version": "synchpaxos-packed-v1",
        "fields": {
            "__dims__":
                "[('n_acc', ('acceptor.promised', 0))]",
            "__reads__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.*', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "__writes__":
                "('acceptor.*', 'coverage.*', 'exposure.*', 'learner.*', 'margin.*', 'proposer.bal', 'proposer.best_bal', 'proposer.best_val', 'proposer.decided_val', 'proposer.heard', 'proposer.phase', 'proposer.prop_val', 'proposer.timer', 'replies.*', 'requests.*', 'telemetry.*', 'tick')",
            "acceptor.acc_bal":
                "word=acc slot=1 bits=15 signed=0 bool=0 bv=None",
            "acceptor.promised":
                "word=acc slot=0 bits=15 signed=0 bool=0 bv=None",
            "acceptor.snap_bal":
                "word=snap_acc slot=1 bits=15 signed=0 bool=0 bv=None optional",
            "acceptor.snap_promised":
                "word=snap_acc slot=0 bits=15 signed=0 bool=0 bv=None optional",
            "learner.chosen":
                "word=chosen slot=0 bits=1 signed=0 bool=1 bv=None",
            "learner.chosen_tick":
                "word=chosen slot=2 bits=19 signed=1 bool=0 bv=None",
            "learner.chosen_val":
                "word=chosen slot=1 bits=12 signed=0 bool=0 bv=None",
            "learner.lt_bal":
                "word=lt slot=0 bits=15 signed=0 bool=0 bv=None",
            "learner.lt_mask":
                "word=lt slot=2 bits=n_acc signed=0 bool=0 bv=None",
            "learner.lt_val":
                "word=lt slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.bal":
                "word=prop0 slot=0 bits=17 signed=0 bool=0 bv=None",
            "proposer.best_bal":
                "word=prop2 slot=1 bits=15 signed=0 bool=0 bv=None",
            "proposer.best_val":
                "word=prop3 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.decided_val":
                "word=prop3 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.heard":
                "word=prop2 slot=0 bits=16 signed=0 bool=0 bv=None",
            "proposer.own_val":
                "word=prop1 slot=0 bits=12 signed=0 bool=0 bv=None",
            "proposer.phase":
                "word=prop0 slot=1 bits=2 signed=0 bool=0 bv=None",
            "proposer.prop_val":
                "word=prop1 slot=1 bits=12 signed=0 bool=0 bv=None",
            "proposer.timer":
                "word=prop0 slot=2 bits=13 signed=1 bool=0 bv=None",
            "replies.bal":
                "word=rep slot=0 bits=15 signed=0 bool=0 bv=None",
            "replies.present":
                "word=rep slot=2 bits=1 signed=0 bool=1 bv=None",
            "replies.v2":
                "word=rep slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.bal":
                "word=req slot=0 bits=15 signed=0 bool=0 bv=None",
            "requests.present":
                "word=req slot=2 bits=1 signed=0 bool=1 bv=None",
            "requests.v1":
                "word=req slot=1 bits=12 signed=0 bool=0 bv=None",
            "requests.v2":
                "zero like=req",
        },
    },
}

# Recursive eqn counts per (protocol, config) audit cell, both engines —
# the jaxpr-size budget (PR 14).  A cell drifting past analysis/flow.py's
# tolerance (max(24, 10%)) fails the always-on `eqn-budget` audit check:
# trace blowup taxes every compile and usually signals an accidental
# unfused arm or a lost gate.  Deliberate changes re-record via
# `paxos_tpu audit --record-goldens` (prints this dict ready to paste).
EQN_GOLDENS: dict = {
    ("paxos", "default"): {"xla": 606, "ctr": 594},
    ("paxos", "gray-chaos"): {"xla": 824, "ctr": 885},
    ("paxos", "corrupt"): {"xla": 774, "ctr": 881},
    ("paxos", "stale"): {"xla": 787, "ctr": 883},
    ("paxos", "delay-chaos"): {"xla": 845, "ctr": 957},
    ("paxos", "telemetry"): {"xla": 756, "ctr": 744},
    ("paxos", "coverage"): {"xla": 926, "ctr": 914},
    ("paxos", "exposure"): {"xla": 981, "ctr": 1042},
    ("paxos", "margin"): {"xla": 680, "ctr": 668},
    ("paxos", "workload"): {"xla": 747, "ctr": 744},
    ("multipaxos", "default"): {"xla": 767, "ctr": 739},
    ("multipaxos", "gray-chaos"): {"xla": 1023, "ctr": 1079},
    ("multipaxos", "corrupt"): {"xla": 983, "ctr": 1088},
    ("multipaxos", "stale"): {"xla": 996, "ctr": 1090},
    ("multipaxos", "delay-chaos"): {"xla": 1034, "ctr": 1124},
    ("multipaxos", "telemetry"): {"xla": 920, "ctr": 892},
    ("multipaxos", "coverage"): {"xla": 1258, "ctr": 1230},
    ("multipaxos", "exposure"): {"xla": 1175, "ctr": 1231},
    ("multipaxos", "margin"): {"xla": 845, "ctr": 817},
    ("multipaxos", "workload"): {"xla": 908, "ctr": 889},
    ("fastpaxos", "default"): {"xla": 818, "ctr": 806},
    ("fastpaxos", "gray-chaos"): {"xla": 1120, "ctr": 1181},
    ("fastpaxos", "corrupt"): {"xla": 1070, "ctr": 1177},
    ("fastpaxos", "stale"): {"xla": 1083, "ctr": 1179},
    ("fastpaxos", "delay-chaos"): {"xla": 1141, "ctr": 1253},
    ("fastpaxos", "telemetry"): {"xla": 968, "ctr": 956},
    ("fastpaxos", "coverage"): {"xla": 1138, "ctr": 1126},
    ("fastpaxos", "exposure"): {"xla": 1279, "ctr": 1340},
    ("fastpaxos", "margin"): {"xla": 912, "ctr": 900},
    ("fastpaxos", "workload"): {"xla": 960, "ctr": 957},
    ("raftcore", "default"): {"xla": 638, "ctr": 626},
    ("raftcore", "gray-chaos"): {"xla": 856, "ctr": 917},
    ("raftcore", "corrupt"): {"xla": 806, "ctr": 913},
    ("raftcore", "stale"): {"xla": 819, "ctr": 915},
    ("raftcore", "delay-chaos"): {"xla": 877, "ctr": 989},
    ("raftcore", "telemetry"): {"xla": 788, "ctr": 776},
    ("raftcore", "coverage"): {"xla": 958, "ctr": 946},
    ("raftcore", "exposure"): {"xla": 1011, "ctr": 1072},
    ("raftcore", "margin"): {"xla": 712, "ctr": 700},
    ("raftcore", "workload"): {"xla": 779, "ctr": 776},
    ("synchpaxos", "default"): {"xla": 648, "ctr": 636},
    ("synchpaxos", "gray-chaos"): {"xla": 865, "ctr": 926},
    ("synchpaxos", "corrupt"): {"xla": 817, "ctr": 924},
    ("synchpaxos", "stale"): {"xla": 830, "ctr": 926},
    ("synchpaxos", "delay-chaos"): {"xla": 893, "ctr": 1005},
    ("synchpaxos", "telemetry"): {"xla": 799, "ctr": 787},
    ("synchpaxos", "coverage"): {"xla": 968, "ctr": 956},
    ("synchpaxos", "exposure"): {"xla": 1030, "ctr": 1091},
    ("synchpaxos", "margin"): {"xla": 722, "ctr": 710},
    ("synchpaxos", "workload"): {"xla": 790, "ctr": 787},
}
