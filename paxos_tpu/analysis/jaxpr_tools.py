"""Jaxpr walking utilities shared by the audit layers.

All checks work on *closed* jaxprs from ``jax.make_jaxpr``.  Higher-order
primitives (pjit, scan, cond, while, ...) carry their bodies as
``ClosedJaxpr``/``Jaxpr`` values inside ``eqn.params`` — every walker here
recurses into those, so a draw buried three pjit levels deep is seen
exactly like a top-level one.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Iterator

import jax
from jax.interpreters import partial_eval as pe

from paxos_tpu.kernels.counter_prng import stream_salt

Jaxpr = jax.core.Jaxpr
ClosedJaxpr = jax.core.ClosedJaxpr
Literal = jax.core.Literal

# Primitives that consume or produce PRNG state.  Matched by prefix so new
# key-array primitives (random_clone, ...) are conservatively included.
_PRNG_PREFIXES = ("random_", "threefry")


def is_prng_eqn(eqn: Any) -> bool:
    return eqn.primitive.name.startswith(_PRNG_PREFIXES)


def _inner_jaxprs(value: Any) -> Iterator[Jaxpr]:
    """Yield any jaxprs nested in a single eqn.params value."""
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _inner_jaxprs(v)


def iter_eqns(jaxpr: Jaxpr) -> Iterator[Any]:
    """Depth-first over every equation, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for param in eqn.params.values():
            for inner in _inner_jaxprs(param):
                yield from iter_eqns(inner)


def literal_ints(eqn: Any) -> list[int]:
    """Integer values of the eqn's Literal invars (traced invars skipped)."""
    out = []
    for v in eqn.invars:
        if isinstance(v, Literal):
            try:
                out.append(int(v.val))
            except (TypeError, ValueError):
                continue
    return out


def fold_in_constants(jaxpr: Jaxpr) -> Counter:
    """Multiset of literal fold_in constants reachable from ``jaxpr``.

    Only *literal* fold data counts — ``fold_in(key, tick)`` with a traced
    tick has no literal invar and is invisible here (by design: the stream
    registry governs the compile-time constants, not runtime tick values).
    """
    consts: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name == "random_fold_in":
            for c in literal_ints(eqn):
                consts[c] += 1
    return consts


def split_widths(jaxpr: Jaxpr) -> Counter:
    """Multiset of ``random_split`` fan-out widths in the trace."""
    widths: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name == "random_split":
            shape = eqn.params.get("shape")
            if shape:
                widths[int(shape[0])] += 1
    return widths


def counter_salt_streams(jaxpr: Jaxpr, max_stream: int = 64) -> Counter:
    """Recover counter-PRNG stream ids from a fused-engine trace.

    ``counter_bits(seed, stream, shape)`` emits exactly one ``add`` whose
    literal operand is ``stream_salt(stream)`` — a 32-bit golden-ratio
    multiple, far outside the range of shape/index constants, so scanning
    add-literals against the salt table recovers each draw exactly once
    with no false positives for stream ids < ``max_stream``.
    """
    salt_to_stream = {stream_salt(s): s for s in range(max_stream)}
    streams: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "add":
            continue
        for c in literal_ints(eqn):
            if c in salt_to_stream:
                streams[salt_to_stream[c]] += 1
    return streams


def prng_signature(jaxpr: Jaxpr) -> Counter:
    """Multiset of (primitive, literal fold const or None) PRNG eqns.

    Two traces with equal signatures draw the same streams the same number
    of times — the comparison behind the telemetry-parity check.
    """
    sig: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if not is_prng_eqn(eqn):
            continue
        lits = literal_ints(eqn)
        sig[(eqn.primitive.name, lits[0] if lits else None)] += 1
    return sig


def dead_prng_draws(closed: ClosedJaxpr) -> list[tuple[str, int | None]]:
    """PRNG eqns that dead-code elimination removes from ``closed``.

    A draw whose output never reaches an outvar is a schedule landmine:
    it costs trace/compile time today and silently shifts sibling streams
    the day someone starts consuming it.  Returns (primitive, fold const)
    pairs present in the original trace but absent after DCE.
    """
    live_jaxpr, _ = pe.dce_jaxpr(
        closed.jaxpr, [True] * len(closed.jaxpr.outvars)
    )
    before = prng_signature(closed.jaxpr)
    after = prng_signature(live_jaxpr)
    dead = before - after
    return sorted(dead.elements(), key=lambda t: (t[0], t[1] is None, t[1]))


def has_prng_eqns(jaxpr: Jaxpr) -> list[str]:
    """Names of any jax.random machinery primitives present (fused-engine
    traces must return [] — counter streams never touch key arrays)."""
    return sorted({e.primitive.name for e in iter_eqns(jaxpr) if is_prng_eqn(e)})
