"""PRNG stream auditor: traced draws vs the core.streams registry.

Checks (per protocol x config trace):

- every counter-PRNG stream id recovered from a fused-tick trace is
  registered for the protocol's family, and drawn at most once per tick
  (a second draw = stream reuse = correlated masks);
- every literal ``fold_in`` constant in an XLA-step trace is a registered
  tick fold, and in a plan trace a registered plan fold;
- gray streams/folds appear ONLY when their knob is on, and never in a
  default-config trace (the default-off-is-free contract, stream half);
- exactly one family-width ``random_split`` per step, and nothing splits
  wider (a wider split would silently renumber every pre-gray stream);
- DCE removes no PRNG eqn (a dead draw shifts sibling streams the day it
  gains a consumer — the bug class this auditor was built after);
- fused-engine traces contain zero ``jax.random`` machinery;
- telemetry-on traces draw the exact same streams as default (telemetry
  consumes no randomness).
"""

from __future__ import annotations

from collections import Counter

from paxos_tpu.analysis import jaxpr_tools as jt
from paxos_tpu.analysis.audit import Finding
from paxos_tpu.core import streams as streams_mod
from paxos_tpu.faults.injector import FaultConfig, links_dup


def _allowed_gray_tick_names(cfg: FaultConfig) -> set:
    """Tick-domain gray draws whose knobs are ON for this config."""
    names = set()
    if cfg.p_flaky > 0.0:
        names.add("LINK_BITS")
    if links_dup(cfg):
        names.add("DUP_BITS")
    if cfg.p_corrupt > 0.0:
        names.add("CORRUPT")
    if cfg.p_delay > 0.0:
        names |= {"DELAY_BITS", "LAT_BITS"}
    return names


def expected_plan_folds(cfg: FaultConfig) -> set:
    """Exact PLAN_FOLDS constants a plan trace must contain for ``cfg``."""
    names = set()
    if cfg.p_asym > 0.0:
        names |= {"PART_DIR", "CUT_REQ"}
    if cfg.p_flaky > 0.0:
        names |= {"FLAKY", "FLAKY_DROP"}
        if links_dup(cfg):
            names.add("FLAKY_DUP")
    if cfg.timeout_skew > 0:
        names.add("PTIMEOUT")
    if cfg.backoff_skew > 1:
        names.add("PBOFF")
    if cfg.p_delay > 0.0:
        names.add("LINK_DELAY")
    return {streams_mod.PLAN_FOLDS[n] for n in names}


def audit_counter_streams(
    protocol: str, config_name: str, closed, cfg: FaultConfig,
    wload_on: bool = False,
) -> list:
    """Audit a fused-tick trace's counter-PRNG stream ids."""
    findings = []
    where = f"{protocol}/{config_name} fused tick"
    family = streams_mod.family_of(protocol)
    registered = set(family.streams.values())
    streams = jt.counter_salt_streams(closed.jaxpr)
    allowed_gray = {
        family.streams[n]
        for n in _allowed_gray_tick_names(cfg)
        if n in family.streams
    }
    for sid, count in sorted(streams.items()):
        if sid not in registered:
            findings.append(Finding(
                check="stream-registry", where=where,
                message=(
                    f"unregistered counter stream {sid} drawn in {where}: "
                    f"not in core.streams.{family.name} "
                    f"(registered: {sorted(registered)})"
                ),
            ))
            continue
        name = family.by_id()[sid]
        if count > 1:
            findings.append(Finding(
                check="stream-collision", where=where,
                message=(
                    f"counter stream {sid} ({family.name}.{name}) drawn "
                    f"{count}x in one tick in {where}: stream reuse makes "
                    f"the draws bit-identical (correlated masks)"
                ),
            ))
        if sid in family.gray_ids() and sid not in allowed_gray:
            findings.append(Finding(
                check="gray-gating", where=where,
                message=(
                    f"gray stream {sid} ({family.name}.{name}) drawn in "
                    f"{where} although its fault knob is off: gray draws "
                    f"must trace away when disabled (default-off-is-free)"
                ),
            ))
        if sid in family.wload_ids() and not wload_on:
            findings.append(Finding(
                check="wload-gating", where=where,
                message=(
                    f"workload stream {sid} ({family.name}.{name}) drawn "
                    f"in {where} although the client-workload plane is "
                    f"off: arrival draws must trace away when "
                    f"cfg.workload.mix == 'off' (default-off-is-free)"
                ),
            ))
    # The fused engine must never touch jax.random machinery: key-array
    # primitives have no Mosaic lowering and would fork the schedule from
    # the reference replay.
    rnd = jt.has_prng_eqns(closed.jaxpr)
    if rnd:
        findings.append(Finding(
            check="counter-engine-purity", where=where,
            message=(
                f"jax.random primitives {rnd} inside {where}: the fused "
                f"engine draws only from kernels.counter_prng"
            ),
        ))
    return findings


def audit_xla_folds(
    protocol: str, config_name: str, closed, cfg: FaultConfig,
    wload_on: bool = False,
) -> list:
    """Audit an XLA-step trace's fold_in constants and split widths."""
    findings = []
    where = f"{protocol}/{config_name} xla step"
    family = streams_mod.family_of(protocol)
    tick_by_const = {v: k for k, v in streams_mod.TICK_FOLDS.items()}
    allowed = {
        streams_mod.TICK_FOLDS[n] for n in _allowed_gray_tick_names(cfg)
    }
    wload_fold = streams_mod.TICK_FOLDS["ARRIVAL_BITS"]
    if wload_on:
        allowed.add(wload_fold)
    for const, count in sorted(jt.fold_in_constants(closed.jaxpr).items()):
        if const not in tick_by_const:
            findings.append(Finding(
                check="fold-registry", where=where,
                message=(
                    f"unregistered fold_in constant {const} in {where}: "
                    f"tick-domain folds must come from "
                    f"core.streams.TICK_FOLDS "
                    f"({sorted(streams_mod.TICK_FOLDS.values())})"
                ),
            ))
            continue
        name = tick_by_const[const]
        if count > 1:
            findings.append(Finding(
                check="fold-collision", where=where,
                message=(
                    f"fold_in({const}) (TICK_FOLDS.{name}) appears {count}x "
                    f"in {where}: duplicate folds yield identical keys"
                ),
            ))
        if const not in allowed:
            if const == wload_fold:
                findings.append(Finding(
                    check="wload-gating", where=where,
                    message=(
                        f"workload fold_in({const}) (TICK_FOLDS.{name}) "
                        f"traced in {where} although the client-workload "
                        f"plane is off (default-off-is-free)"
                    ),
                ))
            else:
                findings.append(Finding(
                    check="gray-gating", where=where,
                    message=(
                        f"gray fold_in({const}) (TICK_FOLDS.{name}) traced "
                        f"in {where} although its fault knob is off"
                    ),
                ))
    widths = jt.split_widths(closed.jaxpr)
    fam_width = family.gray_base
    if widths.get(fam_width, 0) != 1:
        findings.append(Finding(
            check="split-width", where=where,
            message=(
                f"expected exactly one {fam_width}-way random_split "
                f"(the {family.name} protocol-stream split) in {where}, "
                f"saw widths {dict(sorted(widths.items()))}"
            ),
        ))
    for w in widths:
        if w > fam_width:
            findings.append(Finding(
                check="split-width", where=where,
                message=(
                    f"{w}-way random_split in {where} exceeds the "
                    f"{family.name} family width {fam_width}: widening the "
                    f"split renumbers every pre-gray stream"
                ),
            ))
    return findings


def audit_dead_draws(protocol: str, config_name: str, closed) -> list:
    """Flag PRNG eqns that dead-code elimination removes."""
    findings = []
    where = f"{protocol}/{config_name} xla step"
    for prim, const in jt.dead_prng_draws(closed):
        detail = f"{prim}({const})" if const is not None else prim
        findings.append(Finding(
            check="dead-draw", where=where,
            message=(
                f"dead PRNG eqn {detail} in {where}: its output is unused, "
                f"so it can be deleted today but silently shifts sibling "
                f"streams the day someone consumes it — gate it on its "
                f"knob instead"
            ),
        ))
    return findings


def audit_plan_folds(protocol: str, config_name: str, closed, cfg) -> list:
    """Audit a plan-sample trace: exact registered fold set for the knobs."""
    findings = []
    where = f"{protocol}/{config_name} plan sample"
    plan_by_const = {v: k for k, v in streams_mod.PLAN_FOLDS.items()}
    seen = jt.fold_in_constants(closed.jaxpr)
    expected = expected_plan_folds(cfg)
    for const, count in sorted(seen.items()):
        if const not in plan_by_const:
            findings.append(Finding(
                check="fold-registry", where=where,
                message=(
                    f"unregistered fold_in constant {const} in {where}: "
                    f"plan-domain folds must come from "
                    f"core.streams.PLAN_FOLDS "
                    f"({sorted(streams_mod.PLAN_FOLDS.values())})"
                ),
            ))
        elif count > 1:
            findings.append(Finding(
                check="fold-collision", where=where,
                message=(
                    f"fold_in({const}) (PLAN_FOLDS.{plan_by_const[const]}) "
                    f"appears {count}x in {where}"
                ),
            ))
    missing = expected - set(seen)
    extra = {c for c in seen if c in plan_by_const} - expected
    if missing:
        names = sorted(plan_by_const[c] for c in missing)
        findings.append(Finding(
            check="plan-folds", where=where,
            message=(
                f"plan trace in {where} is missing expected gray folds "
                f"{names} for the enabled knobs"
            ),
        ))
    if extra:
        names = sorted(plan_by_const[c] for c in extra)
        findings.append(Finding(
            check="gray-gating", where=where,
            message=(
                f"plan trace in {where} draws gray folds {names} although "
                f"their knobs are off (default-off-is-free)"
            ),
        ))
    return findings


def _audit_observer_parity(
    protocol: str, check: str, feature: str,
    default_xla, feat_xla, default_ctr, feat_ctr,
) -> list:
    """A pure observer (telemetry, coverage) must consume no randomness:
    its feature-on traces carry identical PRNG signatures to default."""
    findings = []
    sig_d = jt.prng_signature(default_xla.jaxpr)
    sig_t = jt.prng_signature(feat_xla.jaxpr)
    if sig_d != sig_t:
        delta = (sig_t - sig_d) + (sig_d - sig_t)
        findings.append(Finding(
            check=check, where=f"{protocol} xla step",
            message=(
                f"{feature}-on xla trace for {protocol} changes the PRNG "
                f"eqn multiset (diff: {dict(delta)}): {feature} must draw "
                f"no randomness"
            ),
        ))
    str_d = jt.counter_salt_streams(default_ctr.jaxpr)
    str_t = jt.counter_salt_streams(feat_ctr.jaxpr)
    if str_d != str_t:
        delta = (str_t - str_d) + (str_d - str_t)
        findings.append(Finding(
            check=check, where=f"{protocol} fused tick",
            message=(
                f"{feature}-on fused trace for {protocol} changes the "
                f"counter-stream multiset (diff: {dict(delta)})"
            ),
        ))
    return findings


def audit_telemetry_parity(
    protocol: str, default_xla, telem_xla, default_ctr, telem_ctr
) -> list:
    """Telemetry must consume no randomness: identical PRNG signatures."""
    return _audit_observer_parity(
        protocol, "telemetry-parity", "telemetry",
        default_xla, telem_xla, default_ctr, telem_ctr,
    )


def audit_coverage_parity(
    protocol: str, default_xla, cov_xla, default_ctr, cov_ctr
) -> list:
    """The coverage sketch must consume no randomness — and its digest
    constants use no add-literals, so ``counter_salt_streams`` cannot
    mistake a hash fold for a new PRNG stream (obs.coverage docstring)."""
    return _audit_observer_parity(
        protocol, "coverage-parity", "coverage",
        default_xla, cov_xla, default_ctr, cov_ctr,
    )


def audit_exposure_parity(
    protocol: str, base_xla, exp_xla, base_ctr, exp_ctr
) -> list:
    """The fault-exposure counters must consume no randomness.

    Compared against the GRAY-CHAOS cell (not default): exposure's
    per-class arms read event signals the fault hooks already computed,
    so the exposure-on trace must match the same-faults exposure-off
    trace — its counting is pure int32 arithmetic over existing values
    (obs.exposure docstring)."""
    return _audit_observer_parity(
        protocol, "exposure-parity", "exposure",
        base_xla, exp_xla, base_ctr, exp_ctr,
    )


def audit_workload_parity(
    protocol: str, default_xla, wl_xla, default_ctr, wl_ctr
) -> list:
    """The client-workload plane draws EXACTLY the arrival stream — no more.

    Unlike the pure observers, the workload plane legitimately consumes
    randomness (one Bernoulli arrival draw per tick), so plain signature
    identity is the wrong contract.  The right one: the workload-on trace
    must differ from default by exactly one ``fold_in(ARRIVAL_BITS)`` +
    one bits draw on the XLA engine (key wrap/unwrap machinery rides
    along, literal-free) and exactly one ``ARRIVAL`` counter-stream draw
    on the fused engine — anything else is a schedule perturbation the
    default-off goldens cannot see."""
    findings = []
    family = streams_mod.family_of(protocol)
    arrival_fold = streams_mod.TICK_FOLDS["ARRIVAL_BITS"]
    sig_d = jt.prng_signature(default_xla.jaxpr)
    sig_w = jt.prng_signature(wl_xla.jaxpr)
    removed = sig_d - sig_w
    added = sig_w - sig_d
    bad_extra = {
        k: n for k, n in added.items()
        if k != ("random_fold_in", arrival_fold)
        and not (k[1] is None and k[0] != "random_fold_in")
    }
    if (
        removed
        or added.get(("random_fold_in", arrival_fold), 0) != 1
        or added.get(("random_bits", None), 0) != 1
        or bad_extra
    ):
        findings.append(Finding(
            check="workload-parity", where=f"{protocol} xla step",
            message=(
                f"workload-on xla trace for {protocol} must add exactly "
                f"one fold_in({arrival_fold}) (TICK_FOLDS.ARRIVAL_BITS) + "
                f"one bits draw over default; saw added "
                f"{dict(added)}, removed {dict(removed)}"
            ),
        ))
    str_d = jt.counter_salt_streams(default_ctr.jaxpr)
    str_w = jt.counter_salt_streams(wl_ctr.jaxpr)
    arrival_sid = family.streams["ARRIVAL"]
    if dict(str_w - str_d) != {arrival_sid: 1} or (str_d - str_w):
        findings.append(Finding(
            check="workload-parity", where=f"{protocol} fused tick",
            message=(
                f"workload-on fused trace for {protocol} must add exactly "
                f"one draw of counter stream {arrival_sid} "
                f"({family.name}.ARRIVAL) over default; saw added "
                f"{dict(str_w - str_d)}, removed {dict(str_d - str_w)}"
            ),
        ))
    return findings


def audit_margin_parity(
    protocol: str, default_xla, mar_xla, default_ctr, mar_ctr
) -> list:
    """The safety-margin counters must consume no randomness.

    Margin folds are pure int32 min/count reductions over learner-table
    and promise/accept state the step already computed (obs.margin
    docstring), so the margin-on traces must carry identical PRNG
    signatures to the default cell."""
    return _audit_observer_parity(
        protocol, "margin-parity", "margin",
        default_xla, mar_xla, default_ctr, mar_ctr,
    )
