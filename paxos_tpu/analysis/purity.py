"""Purity / determinism lint: jaxpr layer + AST layer.

Jaxpr layer: a traced protocol program must be a pure array function —
no host callbacks (results depend on host scheduling), no effects, no
XLA-nondeterministic primitives, no data-dependent output shapes.

AST layer: the traced packages must not even *import* host entropy or
wall-clock facilities (``np.random``, ``random``, ``secrets``, ``time``,
``os.urandom``).  Tracing would catch a call on the traced path, but the
AST pass also catches module-level and conditional uses that a single
trace misses.  Host-side packages (harness, cpu_ref) are exempt: they
legitimately time campaigns and talk to the OS.
"""

from __future__ import annotations

import ast
from pathlib import Path

from paxos_tpu.analysis import jaxpr_tools as jt
from paxos_tpu.analysis.audit import Finding

# Primitives whose results depend on the host or are documented as
# nondeterministic on XLA.  ``rng_uniform`` is XLA's stateful RNG op —
# explicitly not reproducible across backends.
DISALLOWED_PRIMITIVES = frozenset({
    "pure_callback",
    "io_callback",
    "debug_callback",
    "outside_call",
    "infeed",
    "outfeed",
    "rng_uniform",
})

# Packages whose modules end up inside traced programs.  harness/ and
# cpu_ref/ are host-side by design and excluded.  obs/ is host-side decode
# but held to the same no-entropy/no-clock bar on purpose: span
# reconstruction must be a pure function of the decoded ring, and its
# wall clock is INJECTED by the harness (obs.host_spans), never imported.
# fuzz/ (PR 13) is host-side scheduling but deterministic BY CONTRACT: its
# splitmix64 energy/mutation streams must stay pure-integer — replayable
# campaigns and mergeable per-shard corpora both depend on it.
TRACED_PACKAGES = (
    "protocols", "core", "faults", "kernels", "transport", "check",
    "utils", "parallel", "obs", "fuzz",
)

_BANNED_MODULES = {
    "random": "stdlib random (host entropy)",
    "secrets": "secrets (host entropy)",
    "time": "wall clock",
}
# numpy aliases resolved per-module; `<alias>.random` attribute is banned.
_NUMPY_NAMES = {"numpy"}


def audit_jaxpr_purity(where: str, closed) -> list:
    """Lint one closed jaxpr for host traffic / nondeterminism."""
    findings = []
    for eqn in jt.iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in DISALLOWED_PRIMITIVES:
            findings.append(Finding(
                check="purity", where=where,
                message=(
                    f"disallowed primitive '{name}' in {where}: traced "
                    f"protocol programs must not call back to the host or "
                    f"use nondeterministic XLA ops"
                ),
            ))
    effects = closed.jaxpr.effects
    if effects:
        findings.append(Finding(
            check="purity", where=where,
            message=(
                f"traced program in {where} carries JAX effects "
                f"{sorted(str(e) for e in effects)}: step functions must "
                f"be effect-free"
            ),
        ))
    for i, var in enumerate(closed.jaxpr.outvars):
        shape = getattr(var.aval, "shape", ())
        if not all(isinstance(d, int) for d in shape):
            findings.append(Finding(
                check="purity", where=where,
                message=(
                    f"output {i} of {where} has data-dependent shape "
                    f"{shape}: dynamic shapes break the fixed-layout "
                    f"scan/checkpoint contract"
                ),
            ))
    return findings


class _HostEntropyVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list = []
        self._numpy_aliases: set = set()
        self._os_aliases: set = set()

    def _flag(self, node: ast.AST, what: str):
        self.findings.append(Finding(
            check="ast-lint", where=f"{self.path}:{node.lineno}",
            message=(
                f"{what} at {self.path}:{node.lineno}: traced modules "
                f"must draw randomness only from jax.random or "
                f"kernels.counter_prng, and never read the host clock"
            ),
        ))

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            root = alias.name.split(".")[0]
            if root in _BANNED_MODULES:
                self._flag(node, f"import of {alias.name} "
                                 f"({_BANNED_MODULES[root]})")
            if alias.name in _NUMPY_NAMES:
                self._numpy_aliases.add(alias.asname or alias.name)
            if alias.name == "numpy.random":
                self._flag(node, "import of numpy.random (host-seeded RNG)")
            if alias.name == "os":
                self._os_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        mod = (node.module or "").split(".")[0]
        if mod in _BANNED_MODULES:
            self._flag(node, f"import from {node.module} "
                             f"({_BANNED_MODULES[mod]})")
        if node.module == "numpy" and any(
            a.name == "random" for a in node.names
        ):
            self._flag(node, "import of numpy.random (host-seeded RNG)")
        if node.module == "os" and any(
            a.name == "urandom" for a in node.names
        ):
            self._flag(node, "import of os.urandom (host entropy)")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        base = node.value
        if isinstance(base, ast.Name):
            if base.id in self._numpy_aliases and node.attr == "random":
                self._flag(node, f"use of {base.id}.random (host-seeded RNG)")
            if base.id in self._os_aliases and node.attr == "urandom":
                self._flag(node, f"use of {base.id}.urandom (host entropy)")
        self.generic_visit(node)


def lint_file(path: Path, repo_relative: str | None = None) -> list:
    """AST-lint one python file; returns findings (empty = clean)."""
    rel = repo_relative or str(path)
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as e:
        return [Finding(
            check="ast-lint", where=f"{rel}:{e.lineno}",
            message=f"syntax error while linting {rel}:{e.lineno}: {e.msg}",
        )]
    visitor = _HostEntropyVisitor(rel)
    visitor.visit(tree)
    return visitor.findings


def audit_traced_sources(package_root: Path | None = None) -> list:
    """AST-lint every module of every traced package."""
    root = package_root or Path(__file__).resolve().parent.parent
    findings = []
    for pkg in TRACED_PACKAGES:
        pkg_dir = root / pkg
        if not pkg_dir.is_dir():
            continue
        for path in sorted(pkg_dir.rglob("*.py")):
            rel = str(path.relative_to(root.parent))
            findings.extend(lint_file(path, rel))
    return findings
