"""Structural verifier: default-off leaves prune, treedefs match goldens.

The default-off-is-free contract has a structural half the PRNG audit
can't see: a disabled knob must leave its state/plan leaves as ``None``
(pruned from the pytree, zero bytes on device), and the *shape of the
pytree itself* for the default config must not drift between sessions —
a new always-on leaf is a silent per-lane memory tax and invalidates
checkpoints.  Goldens for treedef fingerprints and config fingerprints
live in :mod:`paxos_tpu.analysis.goldens`.

Default OFF in the audit CLI (``--structure`` enables): golden diffs are
a release gate, not an every-trace invariant, and intentionally fail
when a PR deliberately adds a state leaf (then: re-record via
``python -m paxos_tpu audit --structure --record-goldens``).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable

import jax

from paxos_tpu.analysis import goldens
from paxos_tpu.analysis.audit import Finding
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import init_plan, init_state

# Leaves that exist only when their knob is on; field-name prefix match,
# applied recursively over the state dataclass tree.
_KNOB_LEAVES = (
    # (field predicate, knob predicate, knob description)
    (
        lambda name: name == "telemetry",
        lambda cfg: cfg.telemetry.enabled(),
        "telemetry disabled",
    ),
    (
        lambda name: name.startswith("snap_"),
        lambda cfg: cfg.fault.stale_k > 0,
        "stale_k == 0",
    ),
    (
        lambda name: name == "until",
        lambda cfg: cfg.fault.p_delay > 0.0,
        "p_delay == 0",
    ),
    (
        lambda name: name == "coverage",
        lambda cfg: cfg.coverage.enabled(),
        "coverage disabled",
    ),
    (
        lambda name: name == "exposure",
        lambda cfg: cfg.exposure.enabled(),
        "exposure disabled",
    ),
    (
        lambda name: name == "margin",
        lambda cfg: cfg.margin.enabled(),
        "margin disabled",
    ),
    (
        lambda name: name == "wload",
        lambda cfg: cfg.workload.enabled(),
        "workload disabled",
    ),
)

_PLAN_GRAY_FIELDS = (
    "part_dir", "link_drop", "link_dup", "ptimeout", "pboff", "link_delay",
)


def treedef_fingerprint(tree) -> str:
    """Shape-independent pytree-structure digest (leaf *placement*, not
    leaf values: ``None`` vs array is visible, 64 vs 1M lanes is not)."""
    s = str(jax.tree_util.tree_structure(tree))
    return hashlib.sha256(s.encode()).hexdigest()[:16]


def _walk_dataclass_fields(obj, prefix: str = ""):
    """Yield (dotted_name, value) for every dataclass field, recursively."""
    if not dataclasses.is_dataclass(obj):
        return
    for f in dataclasses.fields(obj):
        value = getattr(obj, f.name)
        name = f"{prefix}{f.name}"
        yield name, value
        if dataclasses.is_dataclass(value):
            yield from _walk_dataclass_fields(value, prefix=f"{name}.")


def audit_default_off_leaves(
    protocol: str,
    config_name: str,
    cfg: SimConfig,
    state_builder: Callable = init_state,
    plan_builder: Callable = init_plan,
) -> list:
    """Knob-off leaves must be None; knob-on leaves must be populated."""
    findings = []
    where = f"{protocol}/{config_name}"
    state = state_builder(cfg)
    for name, value in _walk_dataclass_fields(state):
        for field_pred, knob_pred, off_reason in _KNOB_LEAVES:
            leaf = name.rsplit(".", 1)[-1]
            if not field_pred(leaf):
                continue
            if knob_pred(cfg) and value is None:
                findings.append(Finding(
                    check="structure", where=where,
                    message=(
                        f"state leaf '{name}' is None in {where} although "
                        f"its knob is ON: the feature silently no-ops"
                    ),
                ))
            elif not knob_pred(cfg) and value is not None:
                findings.append(Finding(
                    check="structure", where=where,
                    message=(
                        f"state leaf '{name}' is allocated in {where} "
                        f"although {off_reason}: default-off leaves must "
                        f"prune to None (zero bytes, unchanged treedef)"
                    ),
                ))
    plan = plan_builder(cfg)
    fault = cfg.fault
    expect_on = {
        "part_dir": fault.p_asym > 0.0,
        "link_drop": fault.p_flaky > 0.0,
        "link_dup": fault.p_flaky > 0.0
        and (fault.p_dup > 0.0 or fault.flaky_dup > 0.0),
        "ptimeout": fault.timeout_skew > 0,
        "pboff": fault.backoff_skew > 1,
        "link_delay": fault.p_delay > 0.0,
    }
    for field in _PLAN_GRAY_FIELDS:
        value = getattr(plan, field)
        if expect_on[field] and value is None:
            findings.append(Finding(
                check="structure", where=where,
                message=(
                    f"FaultPlan.{field} is None in {where} although its "
                    f"gray knob is ON"
                ),
            ))
        elif not expect_on[field] and value is not None:
            findings.append(Finding(
                check="structure", where=where,
                message=(
                    f"FaultPlan.{field} is allocated in {where} although "
                    f"its gray knob is off: plan gray fields must prune "
                    f"to None"
                ),
            ))
    return findings


def audit_goldens(
    protocol: str,
    config_name: str,
    cfg: SimConfig,
    state_builder: Callable = init_state,
) -> list:
    """Diff treedef + config fingerprints against the recorded goldens."""
    findings = []
    where = f"{protocol}/{config_name}"
    key = (protocol, config_name)
    got_tree = treedef_fingerprint(state_builder(cfg))
    want_tree = goldens.TREEDEF_GOLDENS.get(key)
    if want_tree is None:
        findings.append(Finding(
            check="structure-golden", where=where,
            message=(
                f"no treedef golden recorded for {where}: run "
                f"`python -m paxos_tpu audit --structure --record-goldens`"
            ),
        ))
    elif got_tree != want_tree:
        findings.append(Finding(
            check="structure-golden", where=where,
            message=(
                f"state treedef for {where} drifted: {got_tree} != golden "
                f"{want_tree} — a leaf was added/removed/reordered; if "
                f"intentional, re-record goldens and call out the "
                f"checkpoint break in the PR"
            ),
        ))
    got_cfg = cfg.fingerprint()
    want_cfg = goldens.CONFIG_GOLDENS.get(key)
    if want_cfg is None:
        findings.append(Finding(
            check="structure-golden", where=where,
            message=f"no config-fingerprint golden recorded for {where}",
        ))
    elif got_cfg != want_cfg:
        findings.append(Finding(
            check="structure-golden", where=where,
            message=(
                f"config fingerprint for {where} drifted: {got_cfg} != "
                f"golden {want_cfg} — a SimConfig/FaultConfig default "
                f"changed, which re-seeds every recorded campaign"
            ),
        ))
    return findings


def audit_layout(protocol: str) -> list:
    """Packed-layout guard: a changed layout table must bump its version.

    Always ON in ``run_audit`` (unlike the ``--structure`` goldens): the
    packed layout is the on-device representation of every lane, so an
    edited field with an unchanged ``*_LAYOUT_VERSION`` silently re-bins
    live campaign state — checkpoints decode garbage and the config
    fingerprint (which folds the version) claims continuity it no longer
    has.  Diffs :func:`paxos_tpu.utils.bitops.layout_fields` against
    ``goldens.LAYOUT_GOLDENS`` and names the exact fields that moved.
    """
    from paxos_tpu.utils import bitops

    findings = []
    where = f"{protocol}/layout"
    got_version = bitops.layout_version(protocol)
    got_fields = bitops.layout_fields(protocol)
    golden = goldens.LAYOUT_GOLDENS.get(protocol)
    if golden is None:
        findings.append(Finding(
            check="layout-version", where=where,
            message=(
                f"no packed-layout golden recorded for {protocol}: run "
                f"`python -m paxos_tpu audit --record-goldens`"
            ),
        ))
        return findings
    want_version, want_fields = golden["version"], golden["fields"]
    if got_fields != want_fields:
        changed = sorted(
            path
            for path in set(got_fields) | set(want_fields)
            if got_fields.get(path) != want_fields.get(path)
        )
        detail = "; ".join(
            f"{p}: {want_fields.get(p, '<absent>')} -> "
            f"{got_fields.get(p, '<absent>')}"
            for p in changed
        )
        if got_version == want_version:
            findings.append(Finding(
                check="layout-version", where=where,
                message=(
                    f"packed layout for {protocol} changed WITHOUT a "
                    f"version bump (still {got_version!r}): field(s) "
                    f"[{', '.join(changed)}] moved ({detail}) — bump "
                    f"*_LAYOUT_VERSION in core/*_state.py, then re-record "
                    f"goldens"
                ),
            ))
        else:
            findings.append(Finding(
                check="layout-version", where=where,
                message=(
                    f"packed layout for {protocol} changed and the version "
                    f"was bumped ({want_version!r} -> {got_version!r}) but "
                    f"the goldens are stale: re-record via `python -m "
                    f"paxos_tpu audit --record-goldens` (changed field(s): "
                    f"[{', '.join(changed)}])"
                ),
            ))
    elif got_version != want_version:
        findings.append(Finding(
            check="layout-version", where=where,
            message=(
                f"layout version for {protocol} bumped "
                f"({want_version!r} -> {got_version!r}) with an unchanged "
                f"table: re-record goldens (the config fingerprint folds "
                f"the version, so every recorded campaign re-seeds)"
            ),
        ))
    return findings


# Where each protocol declares its layout + read/write-set tables — audit
# findings name the file so the fix needs no grepping.
_STATE_FILES = {
    "paxos": "paxos_tpu/core/state.py",
    "multipaxos": "paxos_tpu/core/mp_state.py",
    "fastpaxos": "paxos_tpu/core/fp_state.py",
    "raftcore": "paxos_tpu/core/raft_state.py",
    "synchpaxos": "paxos_tpu/core/sp_state.py",
}


def _written_leaf_paths(protocol: str, cfg: SimConfig) -> set:
    """Dotted paths of state leaves the fused tick actually writes.

    Traces the counter tick body (the exact program the Pallas kernel
    lowers) with state as the ONLY free input; a leaf is unwritten iff its
    output var is literally its input var (the tracer passed it through
    untouched), written otherwise.
    """
    import jax.numpy as jnp

    from paxos_tpu.kernels.counter_prng import mix
    from paxos_tpu.kernels.fused_tick import fused_fns
    from paxos_tpu.utils import bitops

    apply_fn, mask_fn, _ = fused_fns(protocol)
    state = init_state(cfg)
    plan = init_plan(cfg)

    def body(st):
        tick_seed = mix(jnp.int32(cfg.seed), st.tick, jnp.int32(0))
        return apply_fn(st, mask_fn(cfg.fault, tick_seed, st), plan, cfg.fault)

    jaxpr = jax.make_jaxpr(body)(state).jaxpr
    paths = bitops.leaf_paths(state)
    written = set()
    for i, (iv, ov) in enumerate(zip(jaxpr.invars, jaxpr.outvars)):
        if ov is not iv:
            written.add(paths[i])
    return written


def audit_write_set(protocol: str) -> list:
    """Always-on: the fused tick must write INSIDE its declared write-set.

    The delta codec (``bitops.Codec.pack_delta``) re-encodes only the
    declared ``*_TICK_WRITES`` leaves and carries everything else through
    the fori_loop unchanged — so a transition that starts writing an
    undeclared leaf would have that write silently DROPPED by the packed
    engine while the XLA engine applies it.  This audit catches the drift
    at trace time and names the leaf and the declaration file.

    Audited over the ``default`` and ``stale`` cells: together they cover
    every always-on leaf plus the snapshot shadows; the telemetry /
    coverage / exposure planes are declared as whole-subtree globs, so
    their leaves cannot drift outside the set.
    """
    from paxos_tpu.analysis import trace as trace_mod
    from paxos_tpu.utils import bitops

    findings = []
    _, writes_decl = bitops.protocol_rw(protocol)
    for config_name in ("default", "stale"):
        cfg = trace_mod.build_config(protocol, config_name)
        where = f"{protocol}/{config_name}"
        for path in sorted(_written_leaf_paths(protocol, cfg)):
            if not bitops.path_matches(path, writes_decl):
                findings.append(Finding(
                    check="write-set", where=where,
                    message=(
                        f"fused tick for {where} writes state leaf "
                        f"'{path}' OUTSIDE the declared write-set: the "
                        f"delta codec would silently drop this write on "
                        f"the packed engine — add '{path}' to the "
                        f"*_TICK_WRITES table in {_STATE_FILES[protocol]}"
                    ),
                ))
    return findings


def _count_min_eqns(jaxpr) -> int:
    """Total ``min`` primitives in a (possibly nested) jaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n = 0
    for eq in jaxpr.eqns:
        if eq.primitive.name == "min":
            n += 1
        for p in eq.params.values():
            if hasattr(p, "jaxpr") or hasattr(p, "eqns"):
                n += _count_min_eqns(p)
            elif isinstance(p, (list, tuple)):
                n += sum(
                    _count_min_eqns(q)
                    for q in p
                    if hasattr(q, "jaxpr") or hasattr(q, "eqns")
                )
    return n


def audit_clamp_hoist(protocol: str) -> list:
    """Always-on: the ballot clamp must be ABSENT from the per-tick jaxpr.

    The hoisted clamp (``fused_tick._saturate_ballots`` at chunk entry /
    exit) is only a win if the default per-tick program really lost its
    saturation ``min``; this audits the traced tick rather than eyeballing
    it, by diffing the hoisted trace against the ``clamp_per_tick=True``
    fallback — the fallback must carry exactly one extra ``min``.
    """
    import jax.numpy as jnp

    from paxos_tpu.analysis import trace as trace_mod
    from paxos_tpu.kernels.fused_tick import packed_fns
    from paxos_tpu.utils import bitops

    cfg = trace_mod.build_config(protocol, "default")
    state = init_state(cfg)
    plan = init_plan(cfg)
    codec = bitops.codec_for(protocol, state)
    pst = bitops.pack_state(codec, state)
    counts = {}
    for per_tick in (False, True):
        apply_fn, _, _ = packed_fns(protocol, clamp_per_tick=per_tick)

        def body(p):
            return apply_fn(p, jnp.int32(1), plan, cfg.fault)

        counts[per_tick] = _count_min_eqns(jax.make_jaxpr(body)(pst))
    if counts[True] != counts[False] + 1:
        return [Finding(
            check="clamp-hoist", where=f"{protocol}/default",
            message=(
                f"per-tick packed jaxpr for {protocol} does not show the "
                f"hoisted ballot clamp: expected the clamp_per_tick=True "
                f"fallback to carry exactly one extra `min` eqn, got "
                f"{counts[False]} (hoisted) vs {counts[True]} (fallback) — "
                f"the clamp leaked back into the tick body "
                f"(kernels/fused_tick.packed_fns) or the fallback lost it"
            ),
        )]
    return []


def record_goldens(matrix) -> dict:
    """Compute fresh goldens for ``matrix`` = [(protocol, config_name, cfg)].

    Returns ``{"treedef": {...}, "config": {...}, "layout": {...},
    "eqns": {...}}`` with stringified keys, ready to paste into
    :mod:`paxos_tpu.analysis.goldens`.
    """
    from paxos_tpu.analysis import flow as flow_mod
    from paxos_tpu.analysis import trace as trace_mod
    from paxos_tpu.utils import bitops

    tree, conf, layout, eqns = {}, {}, {}, {}
    for protocol, config_name, cfg in matrix:
        key = (protocol, config_name)
        tree[key] = treedef_fingerprint(init_state(cfg))
        conf[key] = cfg.fingerprint()
        layout[protocol] = {
            "version": bitops.layout_version(protocol),
            "fields": bitops.layout_fields(protocol),
        }
        eqns[key] = {
            "xla": flow_mod.count_eqns(
                trace_mod.trace_xla_step(protocol, cfg)
            ),
            "ctr": flow_mod.count_eqns(
                trace_mod.trace_counter_tick(protocol, cfg)
            ),
        }
    return {"treedef": tree, "config": conf, "layout": layout, "eqns": eqns}
