"""Trace protocol programs to closed jaxprs across the audit config matrix.

Sizes are deliberately tiny (tracing cost only — nothing executes) but the
*knob* combinations mirror the real evaluation configs: stream topology
depends on fault/telemetry knobs, never on lane count, so a 64-lane trace
proves the same stream discipline as a 1M-lane campaign.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from paxos_tpu.core.telemetry import TelemetryConfig
from paxos_tpu.faults.injector import FaultPlan
from paxos_tpu.harness.config import (
    SimConfig,
    config_corrupt,
    config_delay_chaos,
    config_gray_chaos,
    config_stale,
)
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state
from paxos_tpu.kernels.counter_prng import mix
from paxos_tpu.kernels.fused_tick import fused_fns

PROTOCOLS = ("paxos", "multipaxos", "fastpaxos", "raftcore", "synchpaxos")

_AUDIT_N_INST = 64
_AUDIT_SEED = 3


def _small(cfg: SimConfig, protocol: str) -> SimConfig:
    return dataclasses.replace(
        cfg, protocol=protocol, n_inst=_AUDIT_N_INST, seed=_AUDIT_SEED
    )


def _default(protocol: str) -> SimConfig:
    return _small(SimConfig(), protocol)


def _gray(protocol: str) -> SimConfig:
    return _small(config_gray_chaos(), protocol)


def _corrupt(protocol: str) -> SimConfig:
    return _small(config_corrupt(), protocol)


def _stale(protocol: str) -> SimConfig:
    return _small(config_stale(), protocol)


def _delay(protocol: str) -> SimConfig:
    return _small(config_delay_chaos(), protocol)


def _telemetry(protocol: str) -> SimConfig:
    return dataclasses.replace(
        _default(protocol),
        telemetry=TelemetryConfig(counters=True, ring_depth=4, hist_bins=8),
    )


def _coverage(protocol: str) -> SimConfig:
    from paxos_tpu.obs.coverage import CoverageConfig

    return dataclasses.replace(
        _default(protocol), coverage=CoverageConfig(words=8)
    )


def _exposure(protocol: str) -> SimConfig:
    from paxos_tpu.obs.exposure import ExposureConfig

    # Gray-chaos base on purpose: exposure's per-class arms only trace
    # when their fault knobs are lit, so auditing it over the default
    # (no-fault) config would prove parity of an empty hook.
    return dataclasses.replace(
        _gray(protocol), exposure=ExposureConfig(counters=True)
    )


def _margin(protocol: str) -> SimConfig:
    from paxos_tpu.obs.margin import MarginConfig

    return dataclasses.replace(
        _default(protocol), margin=MarginConfig(counters=True)
    )


def _workload(protocol: str) -> SimConfig:
    from paxos_tpu.workload.generator import WorkloadConfig

    # "mixed" on purpose: all three arrival-class arms must trace (a
    # single-class cell would audit a partially-dead threshold select).
    return dataclasses.replace(
        _default(protocol),
        workload=WorkloadConfig(mix="mixed", slo_p99_ticks=64),
    )


CONFIG_MATRIX: dict[str, Callable[[str], SimConfig]] = {
    "default": _default,
    "gray-chaos": _gray,
    "corrupt": _corrupt,
    "stale": _stale,
    "delay-chaos": _delay,
    "telemetry": _telemetry,
    "coverage": _coverage,
    "exposure": _exposure,
    "margin": _margin,
    "workload": _workload,
}


def build_config(protocol: str, config_name: str) -> SimConfig:
    return CONFIG_MATRIX[config_name](protocol)


def trace_xla_step(protocol: str, cfg: SimConfig):
    """Closed jaxpr of one XLA-engine protocol step (state, key, plan free)."""
    step = get_step_fn(protocol)
    state = init_state(cfg)
    plan = init_plan(cfg)

    def body(st, key, pl):
        return step(st, key, pl, cfg.fault)

    return jax.make_jaxpr(body)(state, base_key(cfg), plan)


def trace_counter_tick(protocol: str, cfg: SimConfig):
    """Closed jaxpr of one fused-engine tick body (reference schedule).

    Mirrors ``kernels.fused_tick.reference_chunk``'s loop body exactly:
    per-tick seed from ``mix(seed, tick, block)``, then the protocol's
    counter-PRNG mask sampler + transition.  This is the same program the
    Pallas kernel lowers, so the stream ids recovered here are the fused
    engine's stream ids.
    """
    apply_fn, mask_fn, _ = fused_fns(protocol)
    state = init_state(cfg)
    plan = init_plan(cfg)

    def body(st, seed, pl):
        tick_seed = mix(seed, st.tick, jnp.int32(0))
        return apply_fn(st, mask_fn(cfg.fault, tick_seed, st), pl, cfg.fault)

    return jax.make_jaxpr(body)(state, jnp.int32(cfg.seed), plan)


def trace_plan_sample(cfg: SimConfig):
    """Closed jaxpr of the fault-plan sampler (the harness's plan domain)."""

    def body(key):
        return FaultPlan.sample(
            key, cfg.fault, cfg.n_inst, cfg.n_acc, cfg.n_prop
        )

    return jax.make_jaxpr(body)(jax.random.PRNGKey(0))
