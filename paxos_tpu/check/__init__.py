"""On-device safety and liveness checking."""
