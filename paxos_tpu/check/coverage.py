"""Measure fuzzer schedule coverage against the exhaustive space (VERDICT r3 #3).

The README's adversarial-power claim — batched mask-driven delivery explores
the same interleaving space a one-message-at-a-time model checker enumerates
— was an argument (commutative folds) plus falsifiability spot checks.  This
module turns it into a NUMBER: project every fuzz lane's post-tick state
into the bounded model's canonical encoding and report what fraction of the
exhaustively-enumerated space the fuzzer actually occupies, plus the dual
soundness check (every in-bounds fuzz state MUST be a reachable model
state — an out-of-space state would mean the engines and the model disagree
about Paxos itself).

Three state sets at the same (n_prop, n_acc, max_round) bounds:

- ``S_multi`` — the classic checker's space (multiset network: messages in
  flight forever until delivered; loss = "never scheduled").
- ``S_slot`` — the same transition system under the TPU transport's
  fixed-slot buffers (``check_exhaustive(slot_net=True)``): one in-flight
  message per (kind, src, dst) edge, sends overwrite.  This is the space
  the batched fuzzer can in principle reach, so ``S_multi - S_slot`` is the
  EXACT transport-excluded remainder (computed, not heuristically guessed).
- ``V`` — states the fuzzer's lanes occupy at tick boundaries, projected
  through :func:`project_lane` + :func:`canon`.

All three are quotiented by the SAME projection ``canon``: phase-dead
bookkeeping (``heard`` after DONE, the phase-1 ``best_*`` accumulators
after phase 1, ``prop_val`` before phase 2) is zeroed, because batch reply
folds legitimately accumulate beyond the quorum point where the
single-delivery model stops (the values differ; the protocol behavior does
not — the extra entries are never read).  Soundness of the quotient: every
zeroed field is write-only until a phase transition resets it, so two
states equal under ``canon`` have ``canon``-equal successor sets.

Probe fault model: selection entropy + ``p_idle`` (acceptor stalls) +
``p_hold`` (reply delays) + timeouts + ``p_dup`` (round-5, VERDICT r4
weak#2: a consumed message re-offers in its slot; redelivery is
idempotent by protocol design, and the projection drops already-folded
copies — an ACCEPT the acceptor already holds verbatim, a reply whose
voter bit is already in ``heard`` — so dup profiles exercise the dup
mask plumbing under the membership check without leaving the model
space).  ``p_drop`` stays 0 BY CONSTRUCTION: the bounded model
represents loss as "never delivered" (the message stays in flight), so a
send-time drop would make the lane's network observably thinner than any
model state and the membership check meaningless.  Nothing is lost:
every drop-prefix execution is already in the space as a delay-forever
schedule.

Reference parity: the reference has no analog (SURVEY.md §5 [B] — its tests
are example runs); this is the TPU twin's own-verification tier.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from paxos_tpu.cpu_ref.exhaustive import (
    DONE,
    P1,
    P2,
    check_exhaustive,
    _gc,
)
from paxos_tpu.cpu_ref.exhaustive import (
    ACCEPT as M_ACCEPT,
    ACCEPTED as M_ACCEPTED,
    PREPARE as M_PREPARE,
    PROMISE as M_PROMISE,
)
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig

# MsgBuf kind indices (core.messages): requests / replies families.
_REQ_PREPARE, _REQ_ACCEPT = 0, 1
_REP_PROMISE, _REP_ACCEPTED = 0, 1
_MAX_PROPS = 8  # core.ballot.MAX_PROPOSERS — ballot_round divisor


def canon(state):
    """Quotient a model/projected state by phase-dead bookkeeping (see
    module docstring for the soundness argument)."""
    accs, props, net, voters = state
    props2 = tuple(
        (
            ph,
            rnd,
            heard if ph != DONE else 0,
            bb if ph == P1 else 0,
            bv if ph == P1 else 0,
            pv if ph != P1 else 0,
            dec,
        )
        for (ph, rnd, heard, bb, bv, pv, dec) in props
    )
    return (accs, props2, net, voters)


def project_lane(h, i: int, n_prop: int, n_acc: int):
    """One fuzz lane's host-side ``PaxosState`` -> canonical model state.

    ``h`` is a ``jax.device_get`` of the full batched state; ``i`` the lane.
    The lane's fixed-slot buffers reassemble into the model's message
    tuples, the learner table into the voters table, and the role arrays
    into the model's role tuples; the model's own GC then collapses
    dead-letter messages exactly as the checker's successor function does.
    """
    acc, pro, lrn = h.acceptor, h.proposer, h.learner
    accs = tuple(
        (
            int(acc.promised[a, i]),
            int(acc.acc_bal[a, i]),
            int(acc.acc_val[a, i]),
        )
        for a in range(n_acc)
    )
    props = tuple(
        (
            int(pro.phase[p, i]),
            (int(pro.bal[p, i]) - 1) // _MAX_PROPS,
            int(pro.heard[p, i]),
            int(pro.best_bal[p, i]),
            int(pro.best_val[p, i]),
            int(pro.prop_val[p, i]),
            int(pro.decided_val[p, i]),
        )
        for p in range(n_prop)
    )
    net = []
    req, rep = h.requests, h.replies
    for p in range(n_prop):
        p_phase = int(pro.phase[p, i])
        p_bal = int(pro.bal[p, i])
        p_heard = int(pro.heard[p, i])
        for a in range(n_acc):
            if req.present[_REQ_PREPARE, p, a, i]:
                net.append((
                    M_PREPARE, p, a,
                    int(req.bal[_REQ_PREPARE, p, a, i]),
                    int(req.v1[_REQ_PREPARE, p, a, i]),
                    int(req.v2[_REQ_PREPARE, p, a, i]),
                ))
            if req.present[_REQ_ACCEPT, p, a, i]:
                b = int(req.bal[_REQ_ACCEPT, p, a, i])
                v = int(req.v1[_REQ_ACCEPT, p, a, i])
                # Idempotent redelivery (dup-enabled profiles: a consumed
                # request can STAY in its slot): the acceptor already
                # accepted exactly (b, v), so delivery is a no-op modulo
                # re-emitting the identical ACCEPTED — drop.  Without dup
                # the rule never fires (consumed requests leave the slot).
                if not (
                    accs[a][0] >= b and accs[a][1] == b and accs[a][2] == v
                ):
                    net.append((
                        M_ACCEPT, p, a, b, v,
                        int(req.v2[_REQ_ACCEPT, p, a, i]),
                    ))
            if rep.present[_REP_PROMISE, p, a, i]:  # src = acceptor, dst = p
                b = int(rep.bal[_REP_PROMISE, p, a, i])
                # Idempotent echo (dup): the promise's voter bit is already
                # folded into this candidacy's heard mask — re-folding is a
                # no-op (bit OR; the best_* max re-fold of an identical
                # payload is inert too).
                if not (
                    p_phase == P1 and b == p_bal and (p_heard >> a) & 1
                ):
                    net.append((
                        M_PROMISE, a, p, b,
                        int(rep.v1[_REP_PROMISE, p, a, i]),
                        int(rep.v2[_REP_PROMISE, p, a, i]),
                    ))
            if rep.present[_REP_ACCEPTED, p, a, i]:
                b = int(rep.bal[_REP_ACCEPTED, p, a, i])
                if not (
                    p_phase == P2 and b == p_bal and (p_heard >> a) & 1
                ):
                    net.append((
                        M_ACCEPTED, a, p, b,
                        int(rep.v1[_REP_ACCEPTED, p, a, i]),
                        int(rep.v2[_REP_ACCEPTED, p, a, i]),
                    ))
    k_rows = lrn.lt_bal.shape[0]
    voters = tuple(sorted(
        (
            (int(lrn.lt_bal[k, i]), int(lrn.lt_val[k, i])),
            int(lrn.lt_mask[k, i]),
        )
        for k in range(k_rows)
        if lrn.lt_bal[k, i] > 0
    ))
    state = (accs, props, tuple(sorted(net)), voters)
    return canon(_gc(state))


def probe_config(
    n_inst: int,
    seed: int,
    n_prop: int = 2,
    n_acc: int = 3,
    p_idle: float = 0.25,
    p_hold: float = 0.25,
    timeout: int = 2,
    backoff_max: int = 3,
    p_dup: float = 0.0,
) -> SimConfig:
    """The coverage probe's fuzz config (delay/reorder/duplication
    adversary, no loss)."""
    return SimConfig(
        n_inst=n_inst,
        n_prop=n_prop,
        n_acc=n_acc,
        k_slots=8,  # >= distinct in-bounds ballots: the table never evicts
        seed=seed,
        protocol="paxos",
        fault=FaultConfig(
            p_idle=p_idle, p_hold=p_hold,
            timeout=timeout, backoff_max=backoff_max, p_dup=p_dup,
        ),
    )


# The default adversary portfolio, rotated across seeds: tick-boundary
# sampling only OBSERVES states at batch edges, so delay-heavy adversaries
# (most ticks deliver <= 1 message — the lane single-steps the model) expose
# the transient states that balanced adversaries batch over, while
# balanced/retry-heavy mixes reach the deep-retry corners faster.  Measured
# at (2x3, (1,0)): the delay-heavy profile alone covers ~2x the states of
# the balanced one at equal samples; the portfolio beats either.
#
# Profiles 6-8 are the round-5 TARGETED additions (VERDICT r4 #1), designed
# from the residue analysis of the round-4 run (`residue_analysis`): the
# uncovered states shared early retries (a proposer back in P1 while its
# round-0 traffic is still in flight — needs a FAST timeout, the old
# portfolio's minimum was 4+backoff) and near-full in-flight buffers (many
# undelivered sends — needs EXTREME hold/idle so emissions pile up while
# little delivers).  Changing the portfolio changes which profile a given
# seed index draws; COVERAGE*.json artifacts record the probe version they
# were measured under.
PORTFOLIO = (
    {"p_idle": 0.7, "p_hold": 0.7, "timeout": 8, "backoff_max": 8},
    {"p_idle": 0.5, "p_hold": 0.5, "timeout": 4, "backoff_max": 6},
    {"p_idle": 0.25, "p_hold": 0.25, "timeout": 4, "backoff_max": 6},
    {"p_idle": 0.6, "p_hold": 0.3, "timeout": 6, "backoff_max": 4},
    {"p_idle": 0.3, "p_hold": 0.6, "timeout": 6, "backoff_max": 4},
    {"p_idle": 0.75, "p_hold": 0.75, "timeout": 12, "backoff_max": 4},
    # Early-retry corners: expire almost immediately, tiny backoff.
    {"p_idle": 0.5, "p_hold": 0.5, "timeout": 1, "backoff_max": 2},
    {"p_idle": 0.7, "p_hold": 0.3, "timeout": 2, "backoff_max": 2},
    # Pile-up corners: deliver almost nothing for long stretches.
    {"p_idle": 0.85, "p_hold": 0.85, "timeout": 6, "backoff_max": 10},
    # Duplication (VERDICT r4 weak#2): consumed messages re-offer with
    # probability p_dup, exercising the dup mask plumbing under the
    # membership check — redeliveries are idempotent, and the projection
    # drops already-folded copies (see project_lane), so dup adds no new
    # model states, only new PATHS through them.
    {"p_idle": 0.4, "p_hold": 0.4, "timeout": 4, "backoff_max": 6,
     "p_dup": 0.4},
)


def state_features(s) -> dict:
    """Coarse features of a canonical model state, for residue analysis."""
    accs, props, net, voters = s
    kinds = [0, 0, 0, 0]
    for m in net:
        kinds[m[0]] += 1
    return {
        "net_size": len(net),
        "kinds": tuple(kinds),  # (PREPARE, PROMISE, ACCEPT, ACCEPTED) counts
        "phases": tuple(pr[0] for pr in props),
        "max_rnd": max(pr[1] for pr in props),
        "decided": _decided(s),
        "n_voter_rows": len(voters),
    }


def residue_analysis(space: set, visited: set, top: int = 12) -> dict:
    """What do the UNREACHED states (``space - visited``) share?

    Histograms the residue by coarse features and contrasts each against
    the same histogram over the covered set — the design input for
    targeted adversary profiles (VERDICT r4 #1: "inspect ``slot -
    visited`` and target what they share").
    """
    residue = space - visited
    covered = space & visited

    def hist(states, key):
        h: dict = {}
        for s in states:
            k = key(state_features(s))
            h[k] = h.get(k, 0) + 1
        return dict(sorted(h.items(), key=lambda kv: -kv[1])[:top])

    def block(key):
        return {
            "residue": {str(k): v for k, v in hist(residue, key).items()},
            "covered": {str(k): v for k, v in hist(covered, key).items()},
        }

    return {
        "residue_size": len(residue),
        "covered_size": len(covered),
        "by_net_size": block(lambda f: f["net_size"]),
        "by_phases": block(lambda f: f["phases"]),
        "by_max_rnd": block(lambda f: f["max_rnd"]),
        "by_kinds": block(lambda f: f["kinds"]),
        "decided_share": {
            "residue": round(
                sum(1 for s in residue if _decided(s)) / max(len(residue), 1), 4
            ),
            "covered": round(
                sum(1 for s in covered if _decided(s)) / max(len(covered), 1), 4
            ),
        },
    }


def _decided(state) -> bool:
    return any(pr[0] == DONE for pr in state[1])


def _lane_matrix(cols, n_inst: int) -> np.ndarray:
    """Stack per-lane state columns into an (I, F) int32 matrix.

    Row i is a byte-exact fingerprint of everything the projection reads
    for lane i — two lanes with equal rows project to the SAME canonical
    state, so the probe only runs the (Python, slow) projection once per
    distinct row and serves repeats from a cache.  At 3.65M samples over
    ~7k distinct states this is a ~100x probe speedup, which is what
    makes plateau-length campaigns (VERDICT r4 #1) tractable.
    """
    return np.ascontiguousarray(np.concatenate(
        [np.asarray(c).astype(np.int32).reshape(-1, n_inst) for c in cols],
        axis=0,
    ).T)


def _paxos_lane_cols(h):
    acc, pro, lrn = h.acceptor, h.proposer, h.learner
    req, rep = h.requests, h.replies
    return (
        acc.promised, acc.acc_bal, acc.acc_val,
        pro.phase, pro.bal, pro.heard, pro.best_bal, pro.best_val,
        pro.prop_val, pro.decided_val,
        req.present, req.bal, req.v1, req.v2,
        rep.present, rep.bal, rep.v1, rep.v2,
        lrn.lt_bal, lrn.lt_val, lrn.lt_mask,
    )


def probe_lanes(
    cfgs, step, lane_cols, project, in_bounds, n_inst: int, ticks: int, say,
) -> dict:
    """The shared lane-sampling driver for every protocol's coverage probe.

    Runs each config for ``ticks`` single-tick chunks, fingerprints every
    in-bounds lane per tick (:func:`_lane_matrix` over ``lane_cols(h)``),
    projects each DISTINCT raw row once (``project(h, i)`` -> canonical
    state, or ``None`` for protocol-specific nonconforming transients,
    which are excluded and counted), and counts canonical-state ENTRIES
    (a lane leaving one canonical state for another = one detection) —
    the abundance statistics the Chao1 estimator feeds on.
    """
    import jax

    from paxos_tpu.harness.run import (
        base_key, init_plan, init_state, run_chunk,
    )

    counts: dict = {}
    samples = detections = nonconforming = deeper = 0
    growth = []
    # One cache across every config: projections depend only on the
    # fingerprinted lane bytes, so distinct states common to many seeds
    # project exactly once.
    proj_cache: dict = {}  # raw lane bytes -> canonical state (or None)
    _MISS = object()
    for cfg in cfgs:
        state = init_state(cfg)
        plan = init_plan(cfg)
        key = base_key(cfg)
        prev: list = [None] * n_inst  # per-lane previous raw bytes
        for t in range(ticks + 1):
            if t > 0:
                state = run_chunk(state, key, plan, cfg.fault, 1, step)
            h = jax.device_get(state)
            in_b = in_bounds(h)
            # A lane whose table evicted has an incomplete voters
            # projection forever after (evictions are monotone) — exclude
            # it.  Only lanes far past the ballot bounds can evict
            # (k_slots exceeds the in-bounds distinct-pair count), so this
            # never drops an in-bounds-reachable state.
            evicted = np.asarray(h.learner.evictions) > 0
            assert not (in_b & evicted).any(), (
                "in-bounds lane evicted: k_slots below the in-bounds "
                "distinct-ballot count — raise it"
            )
            deeper += int((~in_b).sum())
            mat = _lane_matrix(lane_cols(h), n_inst)
            for i in np.nonzero(in_b)[0]:
                raw = mat[i].tobytes()
                st = proj_cache.get(raw, _MISS)
                if st is _MISS:
                    st = project(h, int(i))
                    proj_cache[raw] = st
                if st is None:  # nonconforming transient: excluded
                    nonconforming += 1
                    prev[i] = raw
                    continue
                samples += 1
                if raw == prev[i]:
                    continue  # same dwell: not a new detection
                # Raw rows can differ while projecting to the same
                # canonical state (dead-field churn): a detection is a
                # CANONICAL-state entry.
                if prev[i] is None or proj_cache.get(prev[i]) != st:
                    counts[st] = counts.get(st, 0) + 1
                    detections += 1
                prev[i] = raw
        growth.append(len(counts))
        say(f"seed {cfg.seed}: |visited|={len(counts)} "
            f"({samples} samples, {nonconforming} nonconforming, "
            f"{deeper} deeper)")
    return {
        "counts": counts,
        "samples": samples,
        "detections": detections,
        "nonconforming": nonconforming,
        "deeper": deeper,
        "growth": growth,
    }


def chao1_estimate(counts: dict, detections: int) -> dict:
    """Chao1 asymptote + Good-Turing sample coverage over DETECTION counts
    (state entries, not per-tick dwell — see :func:`probe_lanes`)."""
    f1 = sum(1 for c in counts.values() if c == 1)
    f2 = sum(1 for c in counts.values() if c == 2)
    visited = len(counts)
    chao1 = (
        visited + f1 * f1 / (2 * f2) if f2 else visited + f1 * (f1 - 1) / 2
    )
    return {
        "singletons": f1,
        "doubletons": f2,
        "chao1": round(chao1, 1),
        "good_turing_sample_coverage": round(
            1.0 - f1 / max(detections, 1), 6
        ),
    }


def category_block(space: set, visited: set, pred) -> dict:
    """Coverage of a predicate-defined state class within ``space``."""
    space_c = sum(1 for s in space if pred(s))
    vis_c = sum(1 for s in visited if s in space and pred(s))
    return {
        "space": space_c,
        "visited": vis_c,
        "coverage": round(vis_c / max(space_c, 1), 6),
    }


def coverage_probe(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    n_inst: int = 2048,
    ticks: int = 48,
    seeds: int = 4,
    seed0: int = 0,
    max_states: int = 50_000_000,
    log=None,
    probe_cfg_kw: Optional[dict] = None,
    analyze_residue: bool = False,
) -> dict[str, Any]:
    """Run the probe; returns the coverage report (see module docstring).

    ``out_of_space`` MUST be 0 — a nonzero count is a soundness finding
    (an in-bounds fuzz state the bounded model cannot reach), not a
    statistic; callers should treat it like a safety violation.

    ``probe_cfg_kw=None`` rotates the :data:`PORTFOLIO` of adversary
    profiles across seeds; pass a dict to pin one profile for every seed.
    The report carries a per-seed ``growth`` curve (|visited| after each
    seed) so the seed-starvation trend is visible, and category coverage
    for the two state classes that matter most: DECIDED states (a proposer
    reached a decision — the consequential corner agreement is checked in)
    and QUIET states (network drained — the configurations every real
    execution passes through).
    """
    from paxos_tpu.harness.run import get_step_fn

    say = log or (lambda s: None)
    mr = (max_round,) * n_prop if isinstance(max_round, int) else tuple(max_round)

    say("enumerating multiset space ...")
    multi: set = set()
    r_multi = check_exhaustive(
        n_prop, n_acc, mr, max_states, visit=lambda s: multi.add(canon(s))
    )
    say(f"multiset: {r_multi.states} raw, {len(multi)} canonical")
    say("enumerating slot-transport space ...")
    slot: set = set()
    r_slot = check_exhaustive(
        n_prop, n_acc, mr, max_states, slot_net=True,
        visit=lambda s: slot.add(canon(s)),
    )
    say(f"slot: {r_slot.states} raw, {len(slot)} canonical")

    bounds = np.asarray(mr)[:, None]

    def in_bounds(h):
        rnds = (np.asarray(h.proposer.bal) - 1) // _MAX_PROPS  # (P, I)
        return (rnds <= bounds).all(axis=0)

    cfgs = []
    for s_idx in range(seeds):
        kw = probe_cfg_kw
        if kw is None:
            kw = PORTFOLIO[s_idx % len(PORTFOLIO)]
        cfgs.append(probe_config(n_inst, seed0 + s_idx, n_prop, n_acc, **kw))
    run_stats = probe_lanes(
        cfgs, get_step_fn("paxos"), _paxos_lane_cols,
        lambda h, i: project_lane(h, i, n_prop, n_acc),
        in_bounds, n_inst, ticks, say,
    )
    counts = run_stats["counts"]

    visited = set(counts)
    out_of_space = visited - slot
    in_slot = len(visited) - len(out_of_space)
    in_multi = len(visited & multi)

    extra: dict[str, Any] = {}
    if analyze_residue:
        extra["residue"] = residue_analysis(slot, visited)
    # Chao1 (chao1_estimate) reads: the estimator bounds what THIS sampling
    # process would reach at infinite samples, not the space — chao1 <<
    # |slot| means the residue needs schedules the process cannot produce
    # (observation-structural); chao1 ~ |slot| means merely seed-starved.
    chao = chao1_estimate(counts, run_stats["detections"])
    return extra | {
        "metric": "fuzz-coverage",
        "bounds": {"n_prop": n_prop, "n_acc": n_acc, "max_round": list(mr)},
        "space_multiset_raw": r_multi.states,
        "space_multiset": len(multi),
        "space_slot_raw": r_slot.states,
        "space_slot": len(slot),
        # The exact transport quotient: states only an unbounded-multiset
        # network can reach (>= 2 same-edge messages in flight and their
        # downstream consequences).
        "transport_excluded": len(multi - slot),
        "slot_only": len(slot - multi),
        "visited": len(visited),
        "visited_in_slot": in_slot,
        "visited_in_multiset": in_multi,
        "coverage_slot": round(in_slot / max(len(slot), 1), 6),
        "coverage_multiset": round(in_multi / max(len(multi), 1), 6),
        "out_of_space": len(out_of_space),  # MUST be 0 (soundness)
        "out_of_space_sample": sorted(out_of_space)[:3],
        "decided_states": category_block(slot, visited, _decided),
        "quiet_states": category_block(slot, visited, lambda s: not s[2]),
        "growth": run_stats["growth"],
        "samples": run_stats["samples"],
        "detections": run_stats["detections"],
        "deeper_than_bounds_samples": run_stats["deeper"],
        "chao1_vs_slot": round(chao["chao1"] / max(len(slot), 1), 4),
        "n_inst": n_inst,
        "ticks": ticks,
        "seeds": seeds,
    } | chao


def sketch_crosscheck(
    n_inst: int = 512,
    ticks: int = 32,
    seeds: int = 2,
    seed0: int = 0,
    # Calibration wants m comfortably above k*n (probe-bounds campaigns
    # visit ~1e4 distinct raw states): an over-full sketch saturates and
    # honestly reports est_states=None, which is a finding about the
    # sketch SIZE, not the estimator.  2048 words = 64 Ki bits.
    words: int = 2048,
    probe_cfg_kw: Optional[dict] = None,
    log=None,
) -> dict[str, Any]:
    """Calibrate the on-device Bloom sketch against exact digest counts.

    Runs probe-bounds campaigns with the coverage plane ON and, in
    lockstep, collects the EXACT set of per-lane post-tick digests
    host-side (the same ``obs.coverage.lane_digest`` the in-tick observe
    folds into the sketch).  Three claims come back as report fields:

    - ``union_matches_host_mirror``: the device union bitmap equals the
      pure-Python mirror rebuilt from the exact digest set — the sketch
      IS the Bloom filter of the digests, bit for bit, not merely an
      approximation of one;
    - ``estimate_within_bound``: ``bloom_estimate`` of the union fill
      recovers the exact distinct-digest count within ``bloom_bound``
      (z=4) — the calibration the sketch's state-count gauge rests on;
    - the raw counts, so COVERAGE.json records the measurement.

    Scale note: the exact oracle here is the distinct-DIGEST count, i.e.
    distinct raw post-tick lane states up to 32-bit digest collisions.
    ``coverage_probe``'s ``visited`` counts CANONICAL model states (raw
    rows that project equal are merged), so the two are cross-referenced,
    not equal; the CLI's ``--exact`` mode records both side by side.
    """
    import dataclasses as _dc

    import jax

    from paxos_tpu.harness.run import (
        base_key, get_step_fn, init_plan, init_state, run_chunk,
    )
    from paxos_tpu.obs.coverage import (
        K_HASHES,
        CoverageConfig,
        bloom_bound,
        bloom_estimate,
        coverage_report,
        digest_tree,
        host_sketch_positions,
        lane_digest,
    )

    say = log or (lambda s: None)
    step = get_step_fn("paxos")
    m = 32 * words
    exact_digests: set = set()
    union = 0  # OR of per-campaign union bitmaps (Python big-int)
    for s_idx in range(seeds):
        kw = probe_cfg_kw
        if kw is None:
            kw = PORTFOLIO[s_idx % len(PORTFOLIO)]
        cfg = _dc.replace(
            probe_config(n_inst, seed0 + s_idx, **kw),
            coverage=CoverageConfig(words=words),
        )
        state = init_state(cfg)
        plan = init_plan(cfg)
        key = base_key(cfg)
        for _ in range(ticks):
            # 1-tick chunks so every post-tick state the sketch observed
            # is also observed exactly, host-side.
            state = run_chunk(state, key, plan, cfg.fault, 1, step)
            dig = np.asarray(jax.device_get(lane_digest(digest_tree(state))))
            exact_digests.update(int(v) & 0xFFFFFFFF for v in dig)
        rep = coverage_report(state.coverage)
        union |= int(rep["union_hex"], 16)
        say(f"seed {cfg.seed}: |digests|={len(exact_digests)}, "
            f"union bits={bin(union).count('1')}")
    mirror = 0
    for pos in host_sketch_positions(exact_digests, words):
        mirror |= 1 << pos
    bits_set = bin(union).count("1")
    n = len(exact_digests)
    est = bloom_estimate(m, K_HASHES, bits_set)
    bound = bloom_bound(m, K_HASHES, n)
    return {
        "metric": "sketch-crosscheck",
        "words": words,
        "bits_total": m,
        "hashes": K_HASHES,
        "exact_digests": n,
        "sketch_bits_set": bits_set,
        "sketch_est_states": None if est is None else round(est, 1),
        "bloom_bound": round(bound, 1),
        "estimate_within_bound": est is not None and abs(est - n) <= bound,
        "union_matches_host_mirror": union == mirror,
        "n_inst": n_inst,
        "ticks": ticks,
        "seeds": seeds,
    }
