"""Liveness statistics: decided-by-tick curves and stuck-instance detection.

Reference parity (SURVEY.md §3.3 `check/liveness`, §6.5): the reference's
liveness story is "the master blocks on `expect` until the decision arrives"
[CH]; at batch scale that becomes distributional statistics computed
on-device from `LearnerState.chosen_tick`:

- ``decided_by(k)``: fraction of instances whose value was chosen by tick k
  (the decided-by-round-k statistic of SURVEY.md §6).
- ``chosen_tick_histogram``: decision-latency distribution over instances.
- ``stuck_mask``: instances still undecided after a tick budget — under a
  fair scheduler these indicate livelock (e.g. dueling proposers without
  backoff), the classic Paxos liveness failure (FLP-adjacent), which the
  fuzzer is meant to surface, not hide.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.core.state import LearnerState


def decided_by(learner: LearnerState, k) -> jnp.ndarray:
    """Fraction of instances chosen at tick <= k (scalar float32)."""
    ok = learner.chosen & (learner.chosen_tick <= k)
    return ok.mean(dtype=jnp.float32)


def chosen_tick_histogram(
    learner: LearnerState, n_bins: int, bin_width: int
) -> jnp.ndarray:
    """(n_bins,) int32 histogram of decision ticks; undecided in the last bin."""
    t = jnp.where(learner.chosen, learner.chosen_tick, jnp.iinfo(jnp.int32).max)
    binned = jnp.clip(t // bin_width, 0, n_bins - 1)
    return jnp.zeros((n_bins,), jnp.int32).at[binned].add(1)


def stuck_mask(learner: LearnerState, budget_ticks: int, now) -> jnp.ndarray:
    """(I,) bool: still undecided although ``budget_ticks`` have elapsed."""
    return ~learner.chosen & (jnp.asarray(now) >= budget_ticks)


def liveness_report(
    learner: LearnerState, now: int, n_points: int = 8, n_bins: int = 16
) -> dict:
    """The liveness block of a run report (SURVEY.md §6.5).

    Host-side dict of plain Python values: ``decided_by_curve`` —
    ``n_points`` (tick, fraction) pairs evenly spaced to ``now``;
    ``chosen_tick_hist`` — ``n_bins`` decision-latency counts (undecided
    lanes in the last bin, ``hist_bin_width`` ticks per bin); ``stuck_lanes``
    — lanes (slot-lanes for Multi-Paxos) still undecided at ``now``.  A
    livelock regression (dueling proposers without backoff) shows up as a
    flattening curve + growing ``stuck_lanes``, not as a silent slowdown.

    Shape-polymorphic over single-decree ``(I,)`` and Multi-Paxos ``(L, I)``
    learners: curve/histogram count slot-lanes in the latter.
    """
    import jax

    now = max(int(now), 1)
    ticks = [max(1, (now * (i + 1)) // n_points) for i in range(n_points)]
    # Width chosen so every decided tick (<= now-1) lands in bins
    # 0..n_bins-2: the last bin holds ONLY undecided lanes, so
    # hist[-1] is exactly the livelock count, never late deciders.
    bin_width = max(1, -(-now // (n_bins - 1)))
    curve = [decided_by(learner, k) for k in ticks]
    hist = chosen_tick_histogram(learner, n_bins, bin_width)
    stuck = stuck_mask(learner, now, now).sum()
    curve, hist, stuck = jax.device_get((curve, hist, stuck))
    return {
        "decided_by_curve": [
            (k, round(float(f), 6)) for k, f in zip(ticks, curve)
        ],
        "chosen_tick_hist": [int(c) for c in hist],
        "hist_bin_width": bin_width,
        "stuck_lanes": int(stuck),
    }
