"""Liveness statistics: decided-by-tick curves and stuck-instance detection.

Reference parity (SURVEY.md §3.3 `check/liveness`, §6.5): the reference's
liveness story is "the master blocks on `expect` until the decision arrives"
[CH]; at batch scale that becomes distributional statistics computed
on-device from `LearnerState.chosen_tick`:

- ``decided_by(k)``: fraction of instances whose value was chosen by tick k
  (the decided-by-round-k statistic of SURVEY.md §6).
- ``chosen_tick_histogram``: decision-latency distribution over instances.
- ``stuck_mask``: instances still undecided after a tick budget — under a
  fair scheduler these indicate livelock (e.g. dueling proposers without
  backoff), the classic Paxos liveness failure (FLP-adjacent), which the
  fuzzer is meant to surface, not hide.

Long-log Multi-Paxos (SURVEY.md §6.7): the learner holds only the residual
window — compacted slots (decided by definition) have left it, and window
rows whose global index ``base + slot >= log_total`` can never be decided.
Every function here accepts an optional ``valid`` mask so those
never-decidable tail rows are excluded from denominators, histograms, and
stuck counts instead of being misreported as livelocked
(``window_valid_mask`` builds the mask; ``liveness_report`` wires it).
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.core.state import LearnerState


def window_valid_mask(chosen_shape, base, log_total: int):
    """(L, I) bool: window rows whose global slot index is a real log slot.

    ``base`` is the per-instance count of compacted (decided) slots; row
    ``l`` of instance ``i`` holds global slot ``base[i] + l``, which exists
    only while it is ``< log_total``.
    """
    sl = jnp.arange(chosen_shape[0], dtype=jnp.int32)[:, None]
    return (base[None, :] + sl) < log_total


def decided_by(learner: LearnerState, k, valid=None) -> jnp.ndarray:
    """Fraction of (valid) instances chosen at tick <= k (scalar float32)."""
    ok = learner.chosen & (learner.chosen_tick <= k)
    if valid is None:
        return ok.mean(dtype=jnp.float32)
    return (ok & valid).sum(dtype=jnp.float32) / jnp.maximum(
        valid.sum(dtype=jnp.float32), 1.0
    )


def chosen_tick_histogram(
    learner: LearnerState, n_bins: int, bin_width: int, valid=None
) -> jnp.ndarray:
    """(n_bins,) int32 histogram of decision ticks; undecided in the last bin.

    With ``valid``, never-decidable rows are dropped entirely (they belong
    to no bin — neither decided nor livelocked).
    """
    t = jnp.where(learner.chosen, learner.chosen_tick, jnp.iinfo(jnp.int32).max)
    binned = jnp.clip(t // bin_width, 0, n_bins - 1)
    w = 1 if valid is None else valid.astype(jnp.int32)
    return jnp.zeros((n_bins,), jnp.int32).at[binned].add(w)


def stuck_mask(learner: LearnerState, budget_ticks: int, now, valid=None):
    """bool mask: still undecided although ``budget_ticks`` have elapsed."""
    stuck = ~learner.chosen & (jnp.asarray(now) >= budget_ticks)
    return stuck if valid is None else stuck & valid


def liveness_report(
    learner: LearnerState,
    now: int,
    n_points: int = 8,
    n_bins: int = 16,
    base=None,
    log_total: int = 0,
) -> dict:
    """The liveness block of a run report (SURVEY.md §6.5).

    Host-side dict of plain Python values: ``decided_by_curve`` —
    ``n_points`` (tick, fraction) pairs evenly spaced to ``now``;
    ``chosen_tick_hist`` — ``n_bins`` decision-latency counts (undecided
    lanes in the last bin, ``hist_bin_width`` ticks per bin); ``stuck_lanes``
    — lanes (slot-lanes for Multi-Paxos) still undecided at ``now``.  A
    livelock regression (dueling proposers without backoff) shows up as a
    flattening curve + growing ``stuck_lanes``, not as a silent slowdown.

    Shape-polymorphic over single-decree ``(I,)`` and Multi-Paxos ``(L, I)``
    learners: curve/histogram count slot-lanes in the latter.

    Long-log runs (``log_total > 0`` with per-instance ``base``): all
    statistics are WINDOW-RELATIVE — compacted slots (decided, but gone
    from the learner) are reported separately as ``slots_compacted``, and
    window rows past the end of the log are masked out rather than counted
    as stuck (the masking leg of `check/liveness` — see module docstring).
    """
    import jax

    now = max(int(now), 1)
    ticks = [max(1, (now * (i + 1)) // n_points) for i in range(n_points)]
    # Width chosen so every decided tick (<= now-1) lands in bins
    # 0..n_bins-2: the last bin holds ONLY undecided lanes, so
    # hist[-1] is exactly the livelock count, never late deciders.
    bin_width = max(1, -(-now // (n_bins - 1)))
    valid = None
    if log_total > 0 and base is not None:
        valid = window_valid_mask(learner.chosen.shape, base, log_total)
    curve = [decided_by(learner, k, valid) for k in ticks]
    hist = chosen_tick_histogram(learner, n_bins, bin_width, valid)
    stuck = stuck_mask(learner, now, now, valid).sum()
    curve, hist, stuck = jax.device_get((curve, hist, stuck))
    out = {
        "decided_by_curve": [
            (k, round(float(f), 6)) for k, f in zip(ticks, curve)
        ],
        "chosen_tick_hist": [int(c) for c in hist],
        "hist_bin_width": bin_width,
        "stuck_lanes": int(stuck),
    }
    if valid is not None:
        out["liveness_window_relative"] = True
        out["slots_compacted"] = int(jax.device_get(base.sum()))
    return out
