"""Liveness statistics: decided-by-tick curves and stuck-instance detection.

Reference parity (SURVEY.md §3.3 `check/liveness`, §6.5): the reference's
liveness story is "the master blocks on `expect` until the decision arrives"
[CH]; at batch scale that becomes distributional statistics computed
on-device from `LearnerState.chosen_tick`:

- ``decided_by(k)``: fraction of instances whose value was chosen by tick k
  (the decided-by-round-k statistic of SURVEY.md §6).
- ``chosen_tick_histogram``: decision-latency distribution over instances.
- ``stuck_mask``: instances still undecided after a tick budget — under a
  fair scheduler these indicate livelock (e.g. dueling proposers without
  backoff), the classic Paxos liveness failure (FLP-adjacent), which the
  fuzzer is meant to surface, not hide.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.core.state import LearnerState


def decided_by(learner: LearnerState, k) -> jnp.ndarray:
    """Fraction of instances chosen at tick <= k (scalar float32)."""
    ok = learner.chosen & (learner.chosen_tick <= k)
    return ok.mean(dtype=jnp.float32)


def chosen_tick_histogram(
    learner: LearnerState, n_bins: int, bin_width: int
) -> jnp.ndarray:
    """(n_bins,) int32 histogram of decision ticks; undecided in the last bin."""
    t = jnp.where(learner.chosen, learner.chosen_tick, jnp.iinfo(jnp.int32).max)
    binned = jnp.clip(t // bin_width, 0, n_bins - 1)
    return jnp.zeros((n_bins,), jnp.int32).at[binned].add(1)


def stuck_mask(learner: LearnerState, budget_ticks: int, now) -> jnp.ndarray:
    """(I,) bool: still undecided although ``budget_ticks`` have elapsed."""
    return ~learner.chosen & (jnp.asarray(now) >= budget_ticks)
