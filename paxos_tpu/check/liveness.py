"""Liveness statistics: decided-by-tick curves and stuck-instance detection.

Reference parity (SURVEY.md §3.3 `check/liveness`, §6.5): the reference's
liveness story is "the master blocks on `expect` until the decision arrives"
[CH]; at batch scale that becomes distributional statistics computed
on-device from `LearnerState.chosen_tick`:

- ``decided_by(k)``: fraction of instances whose value was chosen by tick k
  (the decided-by-round-k statistic of SURVEY.md §6).
- ``chosen_tick_histogram``: decision-latency distribution over instances.
- ``stuck_mask``: instances still undecided after a tick budget — under a
  fair scheduler these indicate livelock (e.g. dueling proposers without
  backoff), the classic Paxos liveness failure (FLP-adjacent), which the
  fuzzer is meant to surface, not hide.

Long-log Multi-Paxos (SURVEY.md §6.7): the learner holds only the residual
window — compacted slots (decided by definition) have left it, and window
rows whose global index ``base + slot >= log_total`` can never be decided.
Every function here accepts an optional ``valid`` mask so those
never-decidable tail rows are excluded from denominators, histograms, and
stuck counts instead of being misreported as livelocked
(``window_valid_mask`` builds the mask; ``liveness_report`` wires it).
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.core.state import LearnerState


def window_valid_mask(chosen_shape, base, log_total: int):
    """(L, I) bool: window rows whose global slot index is a real log slot.

    ``base`` is the per-instance count of compacted (decided) slots; row
    ``l`` of instance ``i`` holds global slot ``base[i] + l``, which exists
    only while it is ``< log_total``.
    """
    sl = jnp.arange(chosen_shape[0], dtype=jnp.int32)[:, None]
    return (base[None, :] + sl) < log_total


def decided_by(learner: LearnerState, k, valid=None) -> jnp.ndarray:
    """Fraction of (valid) instances chosen at tick <= k (scalar float32)."""
    ok = learner.chosen & (learner.chosen_tick <= k)
    if valid is None:
        return ok.mean(dtype=jnp.float32)
    return (ok & valid).sum(dtype=jnp.float32) / jnp.maximum(
        valid.sum(dtype=jnp.float32), 1.0
    )


def chosen_tick_histogram(
    learner: LearnerState, n_bins: int, bin_width: int, valid=None
) -> jnp.ndarray:
    """(n_bins,) int32 histogram of decision ticks; undecided in the last bin.

    With ``valid``, never-decidable rows are dropped entirely (they belong
    to no bin — neither decided nor livelocked).
    """
    t = jnp.where(learner.chosen, learner.chosen_tick, jnp.iinfo(jnp.int32).max)
    binned = jnp.clip(t // bin_width, 0, n_bins - 1)
    w = 1 if valid is None else valid.astype(jnp.int32)
    return jnp.zeros((n_bins,), jnp.int32).at[binned].add(w)


def stuck_mask(learner: LearnerState, budget_ticks: int, now, valid=None):
    """bool mask: still undecided although ``budget_ticks`` have elapsed."""
    stuck = ~learner.chosen & (jnp.asarray(now) >= budget_ticks)
    return stuck if valid is None else stuck & valid


def liveness_device(
    learner: LearnerState,
    now,
    n_points: int = 8,
    n_bins: int = 16,
    base=None,
    log_total: int = 0,
) -> dict:
    """Device half of :func:`liveness_report`: all statistics as a pytree of
    small device arrays, no host transfer.

    ``now`` may be a device scalar (e.g. ``state.tick``) — the curve's tick
    points and the histogram bin width are computed ON DEVICE with the same
    integer arithmetic the host formulas used (``jnp`` floor division
    rounds toward -inf exactly like Python's), so building the report needs
    no host round-trip at all.  Pair with :func:`liveness_host`, or embed
    in ``harness.run.summarize_device``'s composite pytree.
    """
    now = jnp.maximum(jnp.asarray(now, jnp.int32), 1)
    idx = jnp.arange(1, n_points + 1, dtype=jnp.int32)
    ticks = jnp.maximum(1, (now * idx) // n_points)
    # Width chosen so every decided tick (<= now-1) lands in bins
    # 0..n_bins-2: the last bin holds ONLY undecided lanes, so hist[-1] is
    # exactly the livelock count, never late deciders.
    bin_width = jnp.maximum(1, -((-now) // (n_bins - 1)))
    valid = None
    if log_total > 0 and base is not None:
        valid = window_valid_mask(learner.chosen.shape, base, log_total)
    # One decided_by reduction per point (same accumulation as the serial
    # path — a batched reduce could reassociate float sums at huge sizes).
    curve = jnp.stack([decided_by(learner, ticks[i], valid)
                       for i in range(n_points)])
    dev = {
        "ticks": ticks,
        "curve": curve,
        "hist": chosen_tick_histogram(learner, n_bins, bin_width, valid),
        "bin_width": bin_width,
        "stuck": stuck_mask(learner, now, now, valid).sum(),
    }
    if valid is not None:
        dev["slots_compacted"] = base.sum()
    return dev


def liveness_host(host: dict) -> dict:
    """Format a ``device_get``'d :func:`liveness_device` pytree."""
    out = {
        "decided_by_curve": [
            (int(k), round(float(f), 6))
            for k, f in zip(host["ticks"], host["curve"])
        ],
        "chosen_tick_hist": [int(c) for c in host["hist"]],
        "hist_bin_width": int(host["bin_width"]),
        "stuck_lanes": int(host["stuck"]),
    }
    if "slots_compacted" in host:
        out["liveness_window_relative"] = True
        out["slots_compacted"] = int(host["slots_compacted"])
    return out


def liveness_report(
    learner: LearnerState,
    now: int,
    n_points: int = 8,
    n_bins: int = 16,
    base=None,
    log_total: int = 0,
) -> dict:
    """The liveness block of a run report (SURVEY.md §6.5).

    Host-side dict of plain Python values: ``decided_by_curve`` —
    ``n_points`` (tick, fraction) pairs evenly spaced to ``now``;
    ``chosen_tick_hist`` — ``n_bins`` decision-latency counts (undecided
    lanes in the last bin, ``hist_bin_width`` ticks per bin); ``stuck_lanes``
    — lanes (slot-lanes for Multi-Paxos) still undecided at ``now``.  A
    livelock regression (dueling proposers without backoff) shows up as a
    flattening curve + growing ``stuck_lanes``, not as a silent slowdown.

    Shape-polymorphic over single-decree ``(I,)`` and Multi-Paxos ``(L, I)``
    learners: curve/histogram count slot-lanes in the latter.

    Long-log runs (``log_total > 0`` with per-instance ``base``): all
    statistics are WINDOW-RELATIVE — compacted slots (decided, but gone
    from the learner) are reported separately as ``slots_compacted``, and
    window rows past the end of the log are masked out rather than counted
    as stuck (the masking leg of `check/liveness` — see module docstring).
    """
    import jax

    dev = liveness_device(learner, now, n_points, n_bins, base, log_total)
    return liveness_host(jax.device_get(dev))
