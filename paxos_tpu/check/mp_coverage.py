"""Multi-Paxos fuzz-coverage against the exhaustive space (VERDICT r4 #3).

``check/coverage.py`` measures classic-Paxos fuzz occupancy of the bounded
model's state space; this sibling lifts the measurement to MULTI-PAXOS —
log state, whole-log recovery, elections — so the README's "the engines
and the checker agree about the protocol" claim is a two-protocol
measurement, not a one-protocol fact quoted as a framework property.

Same three state sets, at shared (n_prop, n_acc, log_len, max_round)
bounds, all quotiented by the same ``canon_mp``:

- ``S_multi`` — ``cpu_ref.mp_exhaustive``'s multiset-network space;
- ``S_slot`` — the same transition system under the TPU transport's
  fixed-slot buffers (``check_mp_exhaustive(slot_net=True)``; the MP
  state's request/promise/accepted buffers are exactly one slot per
  (kind, src, dst) edge), so ``S_multi - S_slot`` is the EXACT
  transport-excluded remainder;
- ``V`` — the fuzz lanes' tick-boundary states through
  :func:`project_mp_lane`.

**Ballot alignment**: the kernel's first election runs at packed round 0
(``bal = make_ballot(ballot_round(0) + 1 = 0, pid)``) while the model's
first challenge runs at round 1 (``_timeout`` increments from the initial
0), so the projection shifts every nonzero kernel ballot up one round
(+MAX_PROPOSERS).  The shift is order-preserving, so folds and GC agree.

**The canon_mp quotient** (applied to BOTH the enumerated spaces and the
projections; every quotiented field is write-only until a phase
transition resets it, except ``recov`` — see below):

- ``heard`` zeroed outside CANDIDATE/LEAD; ``commit_idx`` zeroed outside
  LEAD; ``dec`` zeroed everywhere (write-only bookkeeping in the model:
  transitions never read it).
- ``recov`` zeroed EVERYWHERE — a deliberate coarsening, not a dead-field
  erasure.  Batched promise folds legitimately accumulate past the
  model's at-quorum stop (three same-tick promises fold three payloads
  where the single-delivery model stops at quorum and GCs the third),
  and unlike classic Paxos' phase-1 ``best_*`` accumulators the MP
  recovery array stays LIVE into LEAD (each slot advance reads it), so
  the exact values are not comparable state-by-state.  Nothing is
  hidden from the metric: recovery's downstream effect — the values
  actually driven — is fully visible through the ACCEPT traffic,
  acceptor logs, and vote rows, and the fold's CONTENT is verified
  tick-exactly by the differential interpreter and exhaustively by the
  checker's own safety leg.
- vote rows of a CHOSEN slot collapse to one ``((slot, -1, value), -1)``
  marker: the kernel's learner suppresses re-confirmation votes after
  choice (table-pressure control) while the model records them at every
  ballot; votes are write-only w.r.t. transitions, so the collapse is a
  sound quotient and keeps the decided corner first-class.

**Projection-only reductions** (kernel-transient structure the model
never produces; each drops a message whose delivery is a no-op modulo
idempotent re-emission):

- an ACCEPT(b, s, v) to an acceptor whose log already holds (b, v) at
  slot s (the leader re-broadcasts its current slot every tick;
  re-accepting is idempotent);
- an ACCEPTED(b, s, v) whose voter bit is already folded into the
  addressee's ``heard`` for its current slot (the re-broadcast's echo).

**Exclusions** (counted, not silently dropped): lanes where any proposer
sits in FOLLOW with a nonzero ballot — the kernel's failed-candidacy /
demotion transient (``cand_fail``/``demote`` zero ``heard`` and fall
back to FOLLOW; the model has no corresponding action, and the
promises the failed candidacy consumed are unrecoverable from the
state).  Such lanes re-conform at their next election, so the exclusion
is transient; the report carries the excluded-sample count.

Probe fault model: selection entropy + ``p_idle`` + ``p_hold`` +
election timing (lease/jitter/backoff draws) + ``p_dup`` (request
re-offers — idempotent by design; the projection reductions above absorb
them); ``p_drop`` stays 0 by construction (loss = delay forever, as in
the classic probe).

Reference parity: the reference has no analog (SURVEY.md §5 [B]); this
is the TPU twin's own-verification tier.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from paxos_tpu.check.coverage import (
    category_block,
    chao1_estimate,
    probe_lanes,
)
from paxos_tpu.core.mp_state import BV_SHIFT
from paxos_tpu.cpu_ref.mp_exhaustive import (
    ACCEPT as M_ACCEPT,
    ACCEPTED as M_ACCEPTED,
    CAND,
    DONE,
    FOLLOW,
    LEAD,
    PREPARE as M_PREPARE,
    PROMISE as M_PROMISE,
    _gc,
    check_mp_exhaustive,
)
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig

_MAX_PROPS = 8
_REQ_PREPARE, _REQ_ACCEPT = 0, 1


def _shift(bal: int) -> int:
    """Kernel ballot -> model ballot (one round up; 0 stays NIL)."""
    return bal + _MAX_PROPS if bal > 0 else 0


def _unpack_bv(bv: int) -> tuple:
    """Packed kernel (ballot, value) pair -> model (ballot, value) with the
    round alignment applied — the ONE place the unpack + shift rule lives
    (acceptor logs, promise payloads, and vote rows all ride through it)."""
    return (
        (bv >> BV_SHIFT) + _MAX_PROPS if bv > 0 else 0,
        bv & ((1 << BV_SHIFT) - 1),
    )


def canon_mp(state, quorum: int):
    """Quotient a model/projected MP state (see module docstring)."""
    accs, props, net, votes = state
    log_len = len(accs[0][1])
    zero_recov = ((0, 0),) * log_len
    zero_dec = (0,) * log_len
    props2 = tuple(
        (
            ph,
            rnd,
            heard if ph in (CAND, LEAD) else 0,
            zero_recov,
            ci if ph == LEAD else 0,
            zero_dec,
        )
        for (ph, rnd, heard, recov, ci, dec) in props
    )
    chosen = {}
    for (s, b, v), m in votes:
        if bin(m).count("1") >= quorum:
            chosen[s] = v
    votes2 = tuple(sorted(
        [((s, b, v), m) for (s, b, v), m in votes if s not in chosen]
        + [((s, -1, v), -1) for s, v in chosen.items()]
    ))
    return (accs, props2, net, votes2)


def project_mp_lane(h, i: int, n_prop: int, n_acc: int, log_len: int):
    """One fuzz lane's host-side ``MultiPaxosState`` -> canonical model
    state, or ``None`` when the lane is in a nonconforming transient
    (a failed-candidacy FOLLOW; see module docstring)."""
    acc, pro = h.acceptor, h.proposer
    lrn = h.learner

    props = []
    for p in range(n_prop):
        bal = int(pro.bal[p, i])
        phase = int(pro.phase[p, i])
        ci = int(pro.commit_idx[p, i])
        if phase == FOLLOW and bal > 0:
            return None  # failed-candidacy / demotion transient
        rnd = 0 if bal == 0 else (bal - 1) // _MAX_PROPS + 1
        if phase == LEAD and ci >= log_len:
            phase = DONE  # the model's terminal leader
        props.append((
            phase,
            rnd,
            int(pro.heard[p, i]),
            ((0, 0),) * log_len,  # recov: quotiented (canon_mp zeroes too)
            ci,
            (0,) * log_len,
        ))
    props = tuple(props)

    accs = []
    for a in range(n_acc):
        log = tuple(
            _unpack_bv(int(acc.log[a, s, i])) for s in range(log_len)
        )
        accs.append((_shift(int(acc.promised[a, i])), log))
    accs = tuple(accs)

    def lead_slot(p):
        # The addressee's live (ballot, slot) pair, for the idempotent-
        # ACCEPTED reduction.
        return (
            int(pro.phase[p, i]) == LEAD,
            _shift(int(pro.bal[p, i])),
            int(pro.commit_idx[p, i]),
            int(pro.heard[p, i]),
        )

    net = []
    req, prom, accd = h.requests, h.promises, h.accepted
    for p in range(n_prop):
        for a in range(n_acc):
            if req.present[_REQ_PREPARE, p, a, i]:
                net.append((
                    M_PREPARE, p, a,
                    _shift(int(req.bal[_REQ_PREPARE, p, a, i])), 0, 0, (),
                ))
            if req.present[_REQ_ACCEPT, p, a, i]:
                b = _shift(int(req.bal[_REQ_ACCEPT, p, a, i]))
                v = int(req.v1[_REQ_ACCEPT, p, a, i])
                s = int(req.v2[_REQ_ACCEPT, p, a, i])
                # Idempotent re-broadcast: already accepted verbatim.
                if not (accs[a][0] >= b and accs[a][1][s] == (b, v)):
                    net.append((M_ACCEPT, p, a, b, s, v, ()))
            if prom.present[p, a, i]:
                payload = tuple(
                    _unpack_bv(int(prom.p_bv[p, a, s, i]))
                    for s in range(log_len)
                )
                net.append((
                    M_PROMISE, a, p, _shift(int(prom.bal[p, a, i])),
                    0, 0, payload,
                ))
            if accd.present[p, a, i]:
                b = _shift(int(accd.bal[p, a, i]))
                s = int(accd.slot[p, a, i])
                v = int(accd.val[p, a, i])
                is_lead, pbal, pci, pheard = lead_slot(p)
                # Idempotent echo: the voter bit is already folded.
                if not (
                    is_lead and b == pbal and s == pci
                    and (pheard >> a) & 1
                ):
                    net.append((M_ACCEPTED, a, p, b, s, v, ()))

    k_rows = lrn.lt_bv.shape[1]
    votes: dict = {}
    for s in range(log_len):
        for k in range(k_rows):
            bv = int(lrn.lt_bv[s, k, i])
            if bv > 0:
                key = (s, *_unpack_bv(bv))
                votes[key] = votes.get(key, 0) | int(lrn.lt_mask[s, k, i])
    votes = tuple(sorted(votes.items()))

    quorum = n_acc // 2 + 1
    state = (accs, props, tuple(sorted(net)), votes)
    return canon_mp(_gc(state, log_len), quorum)


def probe_mp_config(
    n_inst: int,
    seed: int,
    n_prop: int = 2,
    n_acc: int = 3,
    log_len: int = 2,
    p_idle: float = 0.25,
    p_hold: float = 0.25,
    lease_len: int = 6,
    timeout: int = 12,
    backoff_max: int = 3,
    p_dup: float = 0.0,
) -> SimConfig:
    """The MP coverage probe's fuzz config (delay/reorder, no loss).

    ``timeout`` (the candidacy-failure clock) defaults HIGH relative to
    the classic probe: a failed candidacy throws the lane into the
    nonconforming FOLLOW transient (excluded samples), so giving
    candidacies room to complete keeps sample efficiency up.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=n_prop,
        n_acc=n_acc,
        log_len=log_len,
        k_slots=8,
        seed=seed,
        protocol="multipaxos",
        fault=FaultConfig(
            p_idle=p_idle, p_hold=p_hold, lease_len=lease_len,
            timeout=timeout, backoff_max=backoff_max, p_dup=p_dup,
        ),
    )


MP_PORTFOLIO = (
    {"p_idle": 0.25, "p_hold": 0.25, "lease_len": 6},
    {"p_idle": 0.55, "p_hold": 0.55, "lease_len": 8},
    {"p_idle": 0.4, "p_hold": 0.1, "lease_len": 4},
    {"p_idle": 0.1, "p_hold": 0.4, "lease_len": 10},
    {"p_idle": 0.7, "p_hold": 0.7, "lease_len": 12, "timeout": 20},
    # Duplication (VERDICT r4 weak#2): MP requests re-offer after
    # consumption; the projection's idempotent-ACCEPT drop and the model
    # GC's stale-PREPARE rule absorb the redeliveries.
    {"p_idle": 0.3, "p_hold": 0.3, "lease_len": 6, "p_dup": 0.4},
)


def _mp_decided(state) -> bool:
    return any(pr[0] == DONE for pr in state[1])


def _mp_lane_cols(h):
    """Everything ``project_mp_lane`` reads (recov_bv excluded: quotiented
    away, never read by the projection)."""
    acc, pro, lrn = h.acceptor, h.proposer, h.learner
    req, prom, accd = h.requests, h.promises, h.accepted
    return (
        acc.promised, acc.log,
        pro.bal, pro.phase, pro.heard, pro.commit_idx,
        req.present, req.bal, req.v1, req.v2,
        prom.present, prom.bal, prom.p_bv,
        accd.present, accd.bal, accd.slot, accd.val,
        lrn.lt_bv, lrn.lt_mask,
    )


def mp_coverage_probe(
    n_prop: int = 2,
    n_acc: int = 3,
    log_len: int = 2,
    max_round: "int | tuple[int, ...]" = (1, 1),
    n_inst: int = 2048,
    ticks: int = 64,
    seeds: int = 6,  # one full MP_PORTFOLIO rotation (incl. the dup profile)
    seed0: int = 0,
    max_states: int = 50_000_000,
    log=None,
    probe_cfg_kw: Optional[dict] = None,
) -> dict[str, Any]:
    """Run the MP probe; returns the coverage report.

    ``out_of_space`` MUST be 0 — a nonzero count means a fuzz-lane state
    the bounded MP model cannot reach (treat like a safety violation).
    """
    from paxos_tpu.harness.run import get_step_fn

    say = log or (lambda s: None)
    mr = (max_round,) * n_prop if isinstance(max_round, int) else tuple(max_round)

    say("enumerating MP multiset space ...")
    multi: set = set()
    quorum = n_acc // 2 + 1
    r_multi = check_mp_exhaustive(
        n_prop, n_acc, log_len, mr, max_states,
        visit=lambda s: multi.add(canon_mp(s, quorum)),
    )
    say(f"multiset: {r_multi.states} raw, {len(multi)} canonical")
    say("enumerating MP slot-transport space ...")
    slot: set = set()
    r_slot = check_mp_exhaustive(
        n_prop, n_acc, log_len, mr, max_states, slot_net=True,
        visit=lambda s: slot.add(canon_mp(s, quorum)),
    )
    say(f"slot: {r_slot.states} raw, {len(slot)} canonical")

    bounds = np.asarray(mr)[:, None]

    def in_bounds(h):
        bal = np.asarray(h.proposer.bal)  # (P, I)
        rnds = np.where(bal > 0, (bal - 1) // _MAX_PROPS + 1, 0)
        return (rnds <= bounds).all(axis=0)

    cfgs = []
    for s_idx in range(seeds):
        kw = probe_cfg_kw
        if kw is None:
            kw = MP_PORTFOLIO[s_idx % len(MP_PORTFOLIO)]
        cfgs.append(probe_mp_config(
            n_inst, seed0 + s_idx, n_prop, n_acc, log_len, **kw
        ))
    run_stats = probe_lanes(
        cfgs, get_step_fn("multipaxos"), _mp_lane_cols,
        lambda h, i: project_mp_lane(h, i, n_prop, n_acc, log_len),
        in_bounds, n_inst, ticks, say,
    )
    counts = run_stats["counts"]

    visited = set(counts)
    out_of_space = visited - slot
    in_slot = len(visited) - len(out_of_space)
    in_multi = len(visited & multi)
    chao = chao1_estimate(counts, run_stats["detections"])

    return {
        "metric": "mp-fuzz-coverage",
        "bounds": {
            "n_prop": n_prop, "n_acc": n_acc, "log_len": log_len,
            "max_round": list(mr),
        },
        "space_multiset_raw": r_multi.states,
        "space_multiset": len(multi),
        "space_slot_raw": r_slot.states,
        "space_slot": len(slot),
        "transport_excluded": len(multi - slot),
        "slot_only": len(slot - multi),
        "visited": len(visited),
        "visited_in_slot": in_slot,
        "visited_in_multiset": in_multi,
        "coverage_slot": round(in_slot / max(len(slot), 1), 6),
        "coverage_multiset": round(in_multi / max(len(multi), 1), 6),
        "out_of_space": len(out_of_space),  # MUST be 0 (soundness)
        "out_of_space_sample": sorted(out_of_space)[:3],
        "decided_states": category_block(slot, visited, _mp_decided),
        "quiet_states": category_block(slot, visited, lambda s: not s[2]),
        "growth": run_stats["growth"],
        "samples": run_stats["samples"],
        "detections": run_stats["detections"],
        "nonconforming_samples": run_stats["nonconforming"],
        "deeper_than_bounds_samples": run_stats["deeper"],
        "chao1_vs_slot": round(chao["chao1"] / max(len(slot), 1), 4),
        "n_inst": n_inst,
        "ticks": ticks,
        "seeds": seeds,
    } | chao
