"""Per-slot safety checking for Multi-Paxos logs.

The same agreement oracle as :mod:`paxos_tpu.check.safety`, lifted to a log
axis: every (instance, slot) pair is its own consensus instance, tracked by
a K-row (ballot, value) -> voter-bitmask table.  Accept events carry a slot
index; the fold is an unrolled loop over the (small) acceptors axis with a
one-hot scatter over slots — fixed shapes, no gathers with dynamic extents.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.core.mp_state import MPLearnerState
from paxos_tpu.utils.bitops import popcount


def mp_learner_observe(
    learner: MPLearnerState,
    ev_flag: jnp.ndarray,  # (I, A) bool — acceptor a accepted this tick
    ev_bal: jnp.ndarray,  # (I, A) int32
    ev_slot: jnp.ndarray,  # (I, A) int32 log slot index
    ev_val: jnp.ndarray,  # (I, A) int32
    tick: jnp.ndarray,
    quorum: int,
) -> MPLearnerState:
    n_acc = ev_flag.shape[1]
    n_slots = learner.lt_bal.shape[1]
    k = learner.lt_bal.shape[2]
    lt_bal, lt_val, lt_mask = learner.lt_bal, learner.lt_val, learner.lt_mask
    evictions = learner.evictions

    pre_chosen_rows = popcount(lt_mask) >= quorum  # (I, L, K)

    for a in range(n_acc):
        b, s, v = ev_bal[:, a], ev_slot[:, a], ev_val[:, a]
        f = ev_flag[:, a] & (b > 0)
        oh_slot = jax.nn.one_hot(s, n_slots, dtype=jnp.bool_)  # (I, L)

        # Re-confirmations of an already-chosen value carry no violation
        # potential (agreement compares against chosen_val; the same value
        # cannot disagree) — skip them to keep table pressure (evictions)
        # proportional to genuinely competing proposals.
        ch_s = jnp.take_along_axis(learner.chosen, s[:, None], axis=1)[:, 0]
        cv_s = jnp.take_along_axis(learner.chosen_val, s[:, None], axis=1)[:, 0]
        f = f & ~(ch_s & (v == cv_s))

        match = (
            (lt_bal == b[:, None, None])
            & (lt_val == v[:, None, None])
            & oh_slot[:, :, None]
            & f[:, None, None]
        )  # (I, L, K)
        any_match = match.any(axis=(1, 2))  # (I,)

        # Candidate insertion row: the min-ballot row of the event's slot.
        row_bal = jnp.take_along_axis(
            lt_bal, jnp.broadcast_to(s[:, None, None], (s.shape[0], 1, k)), axis=1
        )[:, 0, :]  # (I, K)
        min_row = jnp.argmin(row_bal, axis=-1)  # (I,)
        min_bal = jnp.take_along_axis(row_bal, min_row[:, None], axis=-1)[:, 0]
        can_insert = (min_bal == 0) | (b > min_bal)
        do_insert = f & ~any_match & can_insert
        missed = f & ~any_match & ~can_insert
        bit = jnp.asarray(1 << a, jnp.int32)

        lt_mask = jnp.where(match, lt_mask | bit, lt_mask)
        ins = (
            oh_slot[:, :, None]
            & jax.nn.one_hot(min_row, k, dtype=jnp.bool_)[:, None, :]
            & do_insert[:, None, None]
        )
        lt_bal = jnp.where(ins, b[:, None, None], lt_bal)
        lt_val = jnp.where(ins, v[:, None, None], lt_val)
        lt_mask = jnp.where(ins, bit, lt_mask)
        evictions = (
            evictions
            + missed.astype(jnp.int32)
            + (do_insert & (min_bal != 0)).astype(jnp.int32)
        )

    chosen_rows = popcount(lt_mask) >= quorum  # (I, L, K)
    newly = chosen_rows & ~pre_chosen_rows
    any_new = newly.any(axis=-1)  # (I, L)

    first_idx = jnp.argmax(newly, axis=-1)  # (I, L)
    first_val = jnp.take_along_axis(lt_val, first_idx[..., None], axis=-1)[..., 0]

    chosen_val = jnp.where(
        learner.chosen, learner.chosen_val, jnp.where(any_new, first_val, 0)
    )
    chosen = learner.chosen | any_new
    chosen_tick = jnp.where(
        learner.chosen, learner.chosen_tick, jnp.where(any_new, tick, -1)
    )

    viol = (
        (newly & (lt_val != chosen_val[..., None]) & chosen[..., None])
        .sum(axis=(1, 2), dtype=jnp.int32)
    )

    return learner.replace(
        lt_bal=lt_bal,
        lt_val=lt_val,
        lt_mask=lt_mask,
        chosen=chosen,
        chosen_val=chosen_val,
        chosen_tick=chosen_tick,
        violations=learner.violations + viol,
        evictions=evictions,
    )
