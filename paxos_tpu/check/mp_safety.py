"""Per-slot safety checking for Multi-Paxos logs.

The same agreement oracle as :mod:`paxos_tpu.check.safety`, lifted to a log
axis: every (instance, slot) pair is its own consensus instance, tracked by
a K-row (ballot, value) -> voter-bitmask table.  Accept events carry a slot
index; the fold is an unrolled loop over the (small) acceptors axis with
one-hot slot masks — fixed shapes, instance-minor layout (L, K, I), no
gathers with dynamic extents.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.check.safety import first_true
from paxos_tpu.core.mp_state import MPLearnerState
from paxos_tpu.utils.bitops import popcount


def mp_learner_observe(
    learner: MPLearnerState,
    ev_flag: jnp.ndarray,  # (A, I) bool — acceptor a accepted this tick
    ev_bal: jnp.ndarray,  # (A, I) int32
    ev_slot: jnp.ndarray,  # (A, I) int32 log slot index
    ev_val: jnp.ndarray,  # (A, I) int32
    tick: jnp.ndarray,
    quorum: int,
) -> MPLearnerState:
    n_acc = ev_flag.shape[0]
    n_slots, k, _ = learner.lt_bal.shape
    lt_bal, lt_val, lt_mask = learner.lt_bal, learner.lt_val, learner.lt_mask
    evictions = learner.evictions
    slot_ids = jnp.arange(n_slots, dtype=jnp.int32)[:, None]  # (L, 1)

    pre_chosen_rows = popcount(lt_mask) >= quorum  # (L, K, I)

    for a in range(n_acc):
        b, s, v = ev_bal[a], ev_slot[a], ev_val[a]  # (I,)
        f = ev_flag[a] & (b > 0)
        oh_slot = s[None] == slot_ids  # (L, I)

        # Re-confirmations of an already-chosen value carry no violation
        # potential (agreement compares against chosen_val; the same value
        # cannot disagree) — skip them to keep table pressure (evictions)
        # proportional to genuinely competing proposals.
        ch_s = (learner.chosen & oh_slot).any(axis=0)  # (I,)
        cv_s = jnp.where(oh_slot, learner.chosen_val, 0).sum(axis=0)  # (I,)
        f = f & ~(ch_s & (v == cv_s))

        # GATHER the event slot's K rows to (K, I), decide there, then make
        # one (L, K, I) write pass per field.  Bit-identical to the direct
        # (L, K, I) fold (the gathered rows ARE the target slot's rows —
        # other slots can't match through the one-hot), but the wide table
        # is touched ~9x per acceptor instead of ~14x; measured via
        # scripts/ablate_fused.py, the learner is the fused MP tick's
        # dominant component (58% at the r3 shapes), so these passes are
        # the throughput.
        ohk = oh_slot[:, None]  # (L, 1, I)
        row_bal = jnp.where(ohk, lt_bal, 0).sum(axis=0)  # (K, I)
        row_val = jnp.where(ohk, lt_val, 0).sum(axis=0)  # (K, I)

        match_row = (row_bal == b[None]) & (row_val == v[None]) & f[None]
        any_match = match_row.any(axis=0)  # (I,)

        # Candidate insertion row: the min-ballot row of the event's slot.
        min_bal = row_bal.min(axis=0)  # (I,)
        ins_row = first_true(row_bal == min_bal[None], axis=0)  # (K, I)
        can_insert = (min_bal == 0) | (b > min_bal)
        do_insert = f & ~any_match & can_insert
        missed = f & ~any_match & ~can_insert
        bit = jnp.asarray(1 << a, jnp.int32)

        match = ohk & match_row[None]  # (L, K, I)
        ins = ohk & (ins_row & do_insert[None])[None]  # (L, K, I)
        lt_mask = jnp.where(
            ins, bit, jnp.where(match, lt_mask | bit, lt_mask)
        )
        lt_bal = jnp.where(ins, b[None, None], lt_bal)
        lt_val = jnp.where(ins, v[None, None], lt_val)
        evictions = (
            evictions
            + missed.astype(jnp.int32)
            + (do_insert & (min_bal != 0)).astype(jnp.int32)
        )

    chosen_rows = popcount(lt_mask) >= quorum  # (L, K, I)
    newly = chosen_rows & ~pre_chosen_rows
    any_new = newly.any(axis=1)  # (L, I)

    first_val = jnp.where(first_true(newly, axis=1), lt_val, 0).sum(axis=1)  # (L, I)

    chosen_val = jnp.where(
        learner.chosen, learner.chosen_val, jnp.where(any_new, first_val, 0)
    )
    chosen = learner.chosen | any_new
    chosen_tick = jnp.where(
        learner.chosen, learner.chosen_tick, jnp.where(any_new, tick, -1)
    )

    viol = (
        (newly & (lt_val != chosen_val[:, None]) & chosen[:, None])
        .sum(axis=(0, 1), dtype=jnp.int32)
    )

    return learner.replace(
        lt_bal=lt_bal,
        lt_val=lt_val,
        lt_mask=lt_mask,
        chosen=chosen,
        chosen_val=chosen_val,
        chosen_tick=chosen_tick,
        violations=learner.violations + viol,
        evictions=evictions,
    )
