"""Per-slot safety checking for Multi-Paxos logs.

The same agreement oracle as :mod:`paxos_tpu.check.safety`, lifted to a log
axis: every (instance, slot) pair is its own consensus instance, tracked by
a K-row table per slot.  Accept events carry a slot index; the fold is an
unrolled loop over the (small) acceptors axis with one-hot slot masks —
fixed shapes, instance-minor layout (L, K, I), no gathers with dynamic
extents.

Rows store PACKED (ballot, value) pairs (``core.mp_state.pack_bv``: one
int32, ballot in the high bits) next to the voter bitmask: the roofline
work (BASELINE.md utilization table) showed the wide passes here are the
fused MP tick's dominant cost (58% by ablation pre-packing), and packing
halves both the row compares (one ``lt_bv`` probe instead of bal + val)
and the insert writes.  The eviction victim is the row with the minimum
packed pair — i.e. the minimum ballot, tie-broken by value, where the old
code broke ties by row order; either policy is sound (eviction choice is
checker bookkeeping, counted either way) and the scalar interpreter
mirrors this one exactly.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.check.safety import first_true
from paxos_tpu.core.mp_state import MPLearnerState, bv_bal, bv_val, pack_bv
from paxos_tpu.utils.bitops import popcount


def mp_learner_observe(
    learner: MPLearnerState,
    ev_flag: jnp.ndarray,  # (A, I) bool — acceptor a accepted this tick
    ev_bal: jnp.ndarray,  # (A, I) int32
    ev_slot: jnp.ndarray,  # (A, I) int32 log slot index
    ev_val: jnp.ndarray,  # (A, I) int32
    tick: jnp.ndarray,
    quorum: int,
) -> MPLearnerState:
    n_acc = ev_flag.shape[0]
    n_slots, k, n_inst = learner.lt_bv.shape
    evictions = learner.evictions
    slot_ids = jnp.arange(n_slots, dtype=jnp.int32)[:, None]  # (L, 1)

    # The fold runs on the table viewed as (L*K, I): every wide pass is then
    # a full-tile (8, 128)-aligned elementwise op over the same two arrays,
    # where both the original direct (L, K, I) fold and the gathered (K, I)
    # formulation spend their time in half-empty (K=4) sublane tiles and
    # mixed-rank broadcasts (measured: the learner was 61% of the fused MP
    # tick even with packed rows).  The flat view is layout-free (instances
    # stay minor) and each row's slot is a static iota — the one-hot becomes
    # a direct compare, no broadcast.
    lk = n_slots * k
    lt_bv = learner.lt_bv.reshape(lk, n_inst)
    lt_mask = learner.lt_mask.reshape(lk, n_inst)
    row_slot = (jnp.arange(lk, dtype=jnp.int32) // k)[:, None]  # (LK, 1)

    pre_chosen_rows = popcount(lt_mask) >= quorum  # (LK, I)

    for a in range(n_acc):
        b, s, v = ev_bal[a], ev_slot[a], ev_val[a]  # (I,)
        bv = pack_bv(b, v)
        # Out-of-window slots must not reach the fold: with no matching
        # one-hot row, min_bv would read 0x7FFFFFFF and the event would be
        # miscounted as an eviction ("missed").  Senders currently clamp
        # (ci = min(commit_idx, n_slots - 1)), so this is a belt against a
        # future unclamped sender, not a reachable path today.
        f = ev_flag[a] & (b > 0) & (s >= 0) & (s < n_slots)
        oh_slot = s[None] == slot_ids  # (L, I)

        # Re-confirmations of an already-chosen value carry no violation
        # potential (agreement compares against chosen_val; the same value
        # cannot disagree) — skip them to keep table pressure (evictions)
        # proportional to genuinely competing proposals.
        ch_s = (learner.chosen & oh_slot).any(axis=0)  # (I,)
        cv_s = jnp.where(oh_slot, learner.chosen_val, 0).sum(axis=0)  # (I,)
        f = f & ~(ch_s & (v == cv_s))

        oh_row = s[None] == row_slot  # (LK, I)
        match = oh_row & (lt_bv == bv[None]) & f[None]
        any_match = match.any(axis=0)  # (I,)

        # Candidate insertion row: the min-packed (= min-ballot, value
        # tiebreak) row of the event's slot; 0 = an empty row.
        masked = jnp.where(oh_row, lt_bv, jnp.int32(0x7FFFFFFF))
        min_bv = masked.min(axis=0)  # (I,)
        can_insert = (min_bv == 0) | (b > bv_bal(min_bv))
        do_insert = f & ~any_match & can_insert
        missed = f & ~any_match & ~can_insert
        bit = jnp.asarray(1 << a, jnp.int32)

        ins = first_true(
            oh_row & (lt_bv == min_bv[None]), axis=0
        ) & do_insert[None]  # (LK, I): first min-packed row of the slot
        lt_mask = jnp.where(
            ins, bit, jnp.where(match, lt_mask | bit, lt_mask)
        )
        lt_bv = jnp.where(ins, bv[None], lt_bv)
        evictions = (
            evictions
            + missed.astype(jnp.int32)
            + (do_insert & (min_bv != 0)).astype(jnp.int32)
        )

    lt_bv = lt_bv.reshape(n_slots, k, n_inst)
    lt_mask = lt_mask.reshape(n_slots, k, n_inst)
    pre_chosen_rows = pre_chosen_rows.reshape(n_slots, k, n_inst)
    chosen_rows = popcount(lt_mask) >= quorum  # (L, K, I)
    newly = chosen_rows & ~pre_chosen_rows
    any_new = newly.any(axis=1)  # (L, I)

    lt_v = bv_val(lt_bv)  # (L, K, I): one unpack pass shared below
    first_val = jnp.where(first_true(newly, axis=1), lt_v, 0).sum(axis=1)  # (L, I)

    chosen_val = jnp.where(
        learner.chosen, learner.chosen_val, jnp.where(any_new, first_val, 0)
    )
    chosen = learner.chosen | any_new
    chosen_tick = jnp.where(
        learner.chosen, learner.chosen_tick, jnp.where(any_new, tick, -1)
    )

    viol = (
        (newly & (lt_v != chosen_val[:, None]) & chosen[:, None])
        .sum(axis=(0, 1), dtype=jnp.int32)
    )

    return learner.replace(
        lt_bv=lt_bv,
        lt_mask=lt_mask,
        chosen=chosen,
        chosen_val=chosen_val,
        chosen_tick=chosen_tick,
        violations=learner.violations + viol,
        evictions=evictions,
    )


def mp_margin_observe(
    margin,
    pre: MPLearnerState,
    post: MPLearnerState,
    promised: jnp.ndarray,  # (A, I) int32 promise fence
    acc_bal: jnp.ndarray,  # (A, I) int32 max accepted ballot over the log
    honest: jnp.ndarray,  # (A, I) bool
    quorum: int,
):
    """Multi-Paxos margin fold: :func:`paxos_tpu.check.safety.margin_observe`
    lifted to the (L, K, I) table — per-slot rivals and decide edges,
    per-lane running minima (see ``obs.margin`` for counter semantics).
    """
    from paxos_tpu.obs.margin import SENTINEL

    bal = bv_bal(post.lt_bv)  # (L, K, I)
    val = bv_val(post.lt_bv)
    votes = popcount(post.lt_mask)
    live = post.lt_bv > 0

    # Quorum slack: best competing row across every decided slot.
    competing = (
        live & post.chosen[:, None] & (val != post.chosen_val[:, None])
    )
    slack = jnp.maximum(quorum - votes, 0)
    tick_slack = jnp.where(competing, slack, SENTINEL).min(axis=(0, 1))  # (I,)
    qslack_min = jnp.minimum(margin.qslack_min, tick_slack)

    # Near-split contention: any slot with >= 2 distinct hot values.
    hot = live & (votes >= quorum - 1)
    vmin = jnp.where(hot, val, SENTINEL).min(axis=1)  # (L, I)
    vmax = jnp.where(hot, val, 0).max(axis=1)
    near = (
        (hot.sum(axis=1, dtype=jnp.int32) >= 2) & (vmin != vmax)
    ).any(axis=0)
    near_split = margin.near_split + near.astype(jnp.int32)

    # Ballot-race margin on slots deciding this tick.
    decided_now = post.chosen & ~pre.chosen  # (L, I)
    win_rows = (votes >= quorum) & live & (val == post.chosen_val[:, None])
    win_bal = jnp.where(win_rows, bal, 0).max(axis=1)  # (L, I)
    rival_bal = jnp.where(live & ~win_rows, bal, 0).max(axis=1)
    gap = jnp.maximum(win_bal - rival_bal, 0)
    tick_gap = jnp.where(decided_now & (rival_bal > 0), gap, SENTINEL).min(
        axis=0
    )
    bal_gap_min = jnp.minimum(margin.bal_gap_min, tick_gap)

    # Checker headroom: one promise fence covers the whole log, so the
    # slack partner is the acceptor's highest accepted ballot.
    pslack = jnp.where(
        honest & (acc_bal > 0), promised - acc_bal, SENTINEL
    ).min(axis=0)  # (I,)
    promise_slack_min = jnp.minimum(margin.promise_slack_min, pslack)

    return margin.replace(
        qslack_min=qslack_min,
        near_split=near_split,
        bal_gap_min=bal_gap_min,
        promise_slack_min=promise_slack_min,
    )
