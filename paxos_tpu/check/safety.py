"""Vectorized safety checking — the learner as invariant oracle.

Reference parity (SURVEY.md §3.1 "Learner process" [B][P] and §5.2): the
reference learner counts Accepted(b, v) per ballot and declares the value
chosen on a majority.  Here the learner is *omniscient* (it observes every
accept event on-device, un-droppable — the checker should not miss
violations because the network was lossy) and doubles as the safety oracle:

- **Agreement**: at most one value is ever chosen per instance.  Tracked by
  the bounded (ballot, value) -> voter-bitmask table in
  :class:`~paxos_tpu.core.state.LearnerState`; a second distinct chosen value
  increments ``violations``.  Keying the table by the *(b, v) pair* (not just
  b) means Byzantine equivocation — the same ballot accepted with two values
  (config 4) — shows up as two competing table rows and is caught by the same
  majority test, with no special case.
- **Acceptor-local invariants** (:func:`acceptor_invariants`): promises are
  monotone and accepted ballots never exceed the promise — checked per tick
  against the pre-tick state, honest acceptors only (equivocators violate by
  design).

Completeness bound: the table holds K pairs, evicting the lowest ballot;
``evictions`` counts both evictions and rejected inserts.  A run with
``evictions == 0`` (all tests and all BASELINE configs) has a *complete*
checker: no accept event escaped quorum accounting.

Layout: tables are (K, I) — instance-minor like everything else — so the
table fold is pure elementwise work plus tiny cross-sublane reductions over
K; slot argmins become min+cumsum first-slot masks, never gathers.
"""

from __future__ import annotations

import jax.numpy as jnp

from paxos_tpu.core.state import AcceptorState, LearnerState
from paxos_tpu.utils.bitops import popcount


def first_true(mask: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Boolean mask selecting the first True along ``axis`` (all-False-safe).

    Positions are unique, so "first" is an exact min-of-masked-iota plus an
    equality — full-shape elementwise ops and one small reduce, with no
    slicing/stacking/cumsum (this function is traced inside the fused Pallas
    engine, where those fail to lower).
    """
    import jax

    idx = jax.lax.broadcasted_iota(jnp.int32, mask.shape, axis)
    none = jnp.int32(mask.shape[axis])  # > every real index
    masked = jnp.where(mask, idx, none)
    first = masked.min(axis=axis, keepdims=True)
    return mask & (masked == first)


def learner_observe(
    learner: LearnerState,
    ev_flag: jnp.ndarray,  # (A, I) bool: acceptor a accepted something this tick
    ev_bal: jnp.ndarray,  # (A, I) int32
    ev_val: jnp.ndarray,  # (A, I) int32
    tick: jnp.ndarray,  # () int32
    quorum: int,
    fast_quorum: int | None = None,
) -> LearnerState:
    """Fold this tick's accept events into the learner table; update chosen/violations.

    With ``fast_quorum`` set (Fast Paxos), ballots of round 0 — the fast
    round — need ``fast_quorum`` voters to be chosen; classic rounds (>= 1)
    need ``quorum``.  Per-slot thresholds are recomputed from the table's
    ballots, so one table serves both round kinds.
    """
    n_acc = ev_flag.shape[0]
    lt_bal, lt_val, lt_mask = learner.lt_bal, learner.lt_val, learner.lt_mask
    evictions = learner.evictions

    def slot_quorum(bal: jnp.ndarray) -> jnp.ndarray | int:
        if fast_quorum is None:
            return quorum
        from paxos_tpu.core.ballot import ballot_round

        return jnp.where(ballot_round(bal) == 0, fast_quorum, quorum)

    pre_chosen_slots = popcount(lt_mask) >= slot_quorum(lt_bal)  # (K, I)

    # At most one accept event per acceptor per tick (one-message-per-actor
    # scheduling), so an unrolled sequential fold over the small acceptors
    # axis is exact: a second acceptor hitting a just-inserted pair matches it.
    for a in range(n_acc):
        b, v, f = ev_bal[a], ev_val[a], ev_flag[a]  # (I,)
        f = f & (b > 0)
        match = (lt_bal == b[None]) & (lt_val == v[None]) & (b[None] > 0)  # (K, I)
        any_match = match.any(axis=0)  # (I,)
        min_bal = lt_bal.min(axis=0)  # (I,); empty slots (bal 0) win first
        ins_slot = first_true(lt_bal == min_bal[None], axis=0)  # (K, I)
        can_insert = (min_bal == 0) | (b > min_bal)
        do_insert = f & ~any_match & can_insert
        missed = f & ~any_match & ~can_insert
        bit = jnp.asarray(1 << a, jnp.int32)

        lt_mask = jnp.where(match & f[None], lt_mask | bit, lt_mask)
        ins = ins_slot & do_insert[None]
        lt_bal = jnp.where(ins, b[None], lt_bal)
        lt_val = jnp.where(ins, v[None], lt_val)
        lt_mask = jnp.where(ins, bit, lt_mask)
        evictions = (
            evictions
            + missed.astype(jnp.int32)
            + (do_insert & (min_bal != 0)).astype(jnp.int32)
        )

    chosen_slots = popcount(lt_mask) >= slot_quorum(lt_bal)  # (K, I)
    newly_chosen = chosen_slots & ~pre_chosen_slots
    any_new = newly_chosen.any(axis=0)  # (I,)

    # First newly chosen value (slot order is arbitrary but deterministic).
    first_val = jnp.where(first_true(newly_chosen, axis=0), lt_val, 0).sum(axis=0)

    chosen_val = jnp.where(
        learner.chosen, learner.chosen_val, jnp.where(any_new, first_val, 0)
    )
    chosen = learner.chosen | any_new
    chosen_tick = jnp.where(
        learner.chosen, learner.chosen_tick, jnp.where(any_new, tick, -1)
    )

    # Agreement: every newly chosen slot must carry THE chosen value.
    viol = (newly_chosen & (lt_val != chosen_val[None]) & chosen[None]).sum(
        axis=0, dtype=jnp.int32
    )

    return learner.replace(
        lt_bal=lt_bal,
        lt_val=lt_val,
        lt_mask=lt_mask,
        chosen=chosen,
        chosen_val=chosen_val,
        chosen_tick=chosen_tick,
        violations=learner.violations + viol,
        evictions=evictions,
    )


def margin_observe(
    margin,
    pre: LearnerState,
    post: LearnerState,
    promised: jnp.ndarray,  # (A, I) int32 promise fence (Raft: voted)
    acc_bal: jnp.ndarray,  # (A, I) int32 accepted ballot (Raft: ent_term)
    honest: jnp.ndarray,  # (A, I) bool — equivocators violate by design
    quorum: int,
    fast_quorum: int | None = None,
):
    """Fold one tick's distance-to-violation signals into the margin sketch.

    Reads the post-:func:`learner_observe` table (``post``) plus the
    pre-tick learner (``pre``, for decide edges) and the post-tick
    acceptor fence — signals the tick already produced, no PRNG, so the
    plane rides the default-off-is-free contract (see ``obs.margin`` for
    counter semantics).  ``margin`` is an ``obs.margin.MarginState``.
    """
    from paxos_tpu.obs.margin import SENTINEL

    lt_bal, lt_val, lt_mask = post.lt_bal, post.lt_val, post.lt_mask
    votes = popcount(lt_mask)  # (K, I)
    if fast_quorum is None:
        sq = jnp.full(lt_bal.shape, quorum, jnp.int32)
    else:
        from paxos_tpu.core.ballot import ballot_round

        sq = jnp.where(ballot_round(lt_bal) == 0, fast_quorum, quorum)
    live = lt_bal > 0  # (K, I)

    # Quorum slack: the best competing row — a live pair on a decided
    # instance carrying a value that is NOT the chosen one.  Slack 0 means
    # the rival reached quorum: the agreement violation fired this tick.
    competing = live & post.chosen[None] & (lt_val != post.chosen_val[None])
    slack = jnp.maximum(sq - votes, 0)
    tick_slack = jnp.where(competing, slack, SENTINEL).min(axis=0)  # (I,)
    qslack_min = jnp.minimum(margin.qslack_min, tick_slack)

    # Near-split contention: >= 2 live rows with distinct values each
    # within one accept of quorum on the same instance this tick.
    hot = live & (votes >= sq - 1)
    vmin = jnp.where(hot, lt_val, SENTINEL).min(axis=0)
    vmax = jnp.where(hot, lt_val, 0).max(axis=0)
    near = (hot.sum(axis=0, dtype=jnp.int32) >= 2) & (vmin != vmax)
    near_split = margin.near_split + near.astype(jnp.int32)

    # Ballot-race margin, taken on the decide tick: winning-row ballot vs
    # the best rival row still in the table.  Unopposed decides (no live
    # rival) record nothing.
    decided_now = post.chosen & ~pre.chosen  # (I,)
    win_rows = (votes >= sq) & live & (lt_val == post.chosen_val[None])
    win_bal = jnp.where(win_rows, lt_bal, 0).max(axis=0)  # (I,)
    rival_bal = jnp.where(live & ~win_rows, lt_bal, 0).max(axis=0)
    gap = jnp.maximum(win_bal - rival_bal, 0)
    tick_gap = jnp.where(decided_now & (rival_bal > 0), gap, SENTINEL)
    bal_gap_min = jnp.minimum(margin.bal_gap_min, tick_gap)

    # Checker headroom on the acceptance bound: promised - acc_bal over
    # honest acceptors holding a live accepted pair.  0 = accepts landing
    # exactly at the fence; negative is already an invariant violation.
    pslack = jnp.where(
        honest & (acc_bal > 0), promised - acc_bal, SENTINEL
    ).min(axis=0)  # (I,)
    promise_slack_min = jnp.minimum(margin.promise_slack_min, pslack)

    return margin.replace(
        qslack_min=qslack_min,
        near_split=near_split,
        bal_gap_min=bal_gap_min,
        promise_slack_min=promise_slack_min,
    )


def acceptor_invariants(
    old: AcceptorState, new: AcceptorState, honest: jnp.ndarray
) -> jnp.ndarray:
    """(I,) int32 count of per-tick acceptor-local invariant breaks (honest lanes).

    - promise monotonicity: ``promised`` never decreases;
    - acceptance bound: ``acc_bal <= promised`` after every transition;
    - accepted pair consistency: a nil ballot never carries a value.
    """
    mono = new.promised < old.promised
    bound = new.acc_bal > new.promised
    nilpair = (new.acc_bal == 0) & (new.acc_val != 0)
    bad = (mono | bound | nilpair) & honest
    return bad.sum(axis=0, dtype=jnp.int32)


def raft_voter_invariants(old, new, honest: jnp.ndarray) -> jnp.ndarray:
    """(I,) int32 count of per-tick Raft voter invariant breaks (honest lanes).

    Over :class:`~paxos_tpu.core.raft_state.VoterState` transitions:

    - vote-fence monotonicity: ``voted`` never decreases;
    - entry bound: a stored entry's term never exceeds the vote fence
      (appends raise ``voted`` to the entry's term);
    - entry-term monotonicity: overwrites only by equal-or-higher terms;
    - nil pair: an empty entry (term 0) never carries a value.
    """
    mono = new.voted < old.voted
    bound = new.ent_term > new.voted
    ent_mono = new.ent_term < old.ent_term
    nilpair = (new.ent_term == 0) & (new.ent_val != 0)
    bad = (mono | bound | ent_mono | nilpair) & honest
    return bad.sum(axis=0, dtype=jnp.int32)
