"""Core array encodings: ballots, role state, message buffers."""
