"""Packed ballot numbers.

Reference parity (SURVEY.md §3.1 "Ballot numbers" [P]): the reference's
proposer-unique, totally ordered ballots — classically ``(round, proposerId)``
with lexicographic order — become a single int32 so that ballot comparison is
integer comparison, the form the TPU's vector units and the quorum kernel
want.  Encoding::

    ballot = round * MAX_PROPOSERS + proposer_id + 1      (NIL = 0)

``MAX_PROPOSERS`` is a power of two so pack/unpack are shifts.  With int32
this supports rounds up to 2**27 — far beyond any fuzzing schedule (ticks per
run are bounded by the scan length).

All functions are shape-polymorphic and jit-safe: they operate elementwise on
arrays of any shape.
"""

from __future__ import annotations

import jax.numpy as jnp

# Power of two so round/owner unpack compiles to shifts/ands.
MAX_PROPOSERS = 8
NIL = 0  # "no ballot" — smaller than every real ballot.


def make_ballot(rnd, proposer_id):
    """Pack (round, proposer_id) into an ordered int32 ballot.

    Lexicographic (round, proposer_id) order is preserved; every real ballot
    compares greater than NIL.
    """
    rnd = jnp.asarray(rnd, jnp.int32)
    proposer_id = jnp.asarray(proposer_id, jnp.int32)
    return rnd * MAX_PROPOSERS + proposer_id + 1


def ballot_round(bal):
    """Round component of a packed ballot (NIL maps to round -1... safe)."""
    bal = jnp.asarray(bal, jnp.int32)
    return (bal - 1) // MAX_PROPOSERS


def ballot_owner(bal):
    """Proposer id that owns this ballot. Only meaningful for bal != NIL."""
    bal = jnp.asarray(bal, jnp.int32)
    return (bal - 1) % MAX_PROPOSERS
