"""Fast Paxos state — classic state plus per-value fast-round vote masks.

Reference parity (SURVEY.md §3.3 `protocols/fastpaxos`, BASELINE config 5):
the reference framework's pluggable-protocol story (the same actor runtime
running different role loops) becomes a second step function over a state
pytree that shares :class:`~paxos_tpu.core.state.AcceptorState`,
:class:`~paxos_tpu.core.state.LearnerState` and the
:class:`~paxos_tpu.core.messages.MsgBuf` wire format with single-decree
Paxos, so the identical fault plan drives both (the config-5 sweep).

Fast Paxos (Lamport, 2006) specifics carried per proposer lane:

- the **fast round** is round 0, ballot ``make_ballot(0, 0)`` shared by all
  proposers: everyone broadcasts ``Accept(fast_bal, own_val)`` immediately,
  skipping phase 1; a value is chosen when a **fast quorum** (ceil(3n/4))
  of acceptors votes for it.
- on collision/loss, proposers fall back to **classic recovery** rounds
  (>= 1) with majority quorums; phase-1 value selection needs, per value,
  *which acceptors* reported it at the highest accepted ballot seen — the
  ``rep_mask`` bitmask table replacing classic Paxos' single (best_bal,
  best_val) running max.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import ACCEPT, MsgBuf
from paxos_tpu.core.state import AcceptorState, LearnerState
from paxos_tpu.core.telemetry import TelemetryState
from paxos_tpu.obs.coverage import CoverageState
from paxos_tpu.obs.exposure import FaultExposure
from paxos_tpu.obs.margin import MarginState
from paxos_tpu.workload.generator import WloadState

# Proposer phases (P1/P2/DONE match core.state so summarize() is shared).
P1 = 0  # classic recovery: prepare sent, collecting promises
P2 = 1  # classic recovery: accept sent, collecting accepted
DONE = 2  # observed a quorum of Accepted for its ballot
FAST = 3  # fast round: Accept(fast_bal, own_val) sent, collecting accepted

# Value encoding: proposer p proposes VALUE_BASE + p (see ProposerState.init).
VALUE_BASE = 100


def fast_ballot() -> jnp.ndarray:
    """The shared round-0 ballot every proposer's fast Accept carries."""
    return make_ballot(0, 0)


@struct.dataclass
class FastProposerState:
    bal: jnp.ndarray  # (P, I) int32 current ballot (fast_ballot() in FAST)
    phase: jnp.ndarray  # (P, I) int32 in {P1, P2, DONE, FAST}
    own_val: jnp.ndarray  # (P, I) int32 value this proposer wants
    prop_val: jnp.ndarray  # (P, I) int32 value sent in classic phase 2
    heard: jnp.ndarray  # (P, I) int32 acceptor bitmask for current phase
    best_bal: jnp.ndarray  # (P, I) int32 highest prev-accepted ballot seen in P1
    rep_mask: jnp.ndarray  # (P, V, I) int32: acceptors reporting value v at best_bal
    timer: jnp.ndarray  # (P, I) int32 ticks since phase start (<0: backoff)
    decided_val: jnp.ndarray  # (P, I) int32 value this proposer saw decided

    @classmethod
    def init(cls, n_inst: int, n_prop: int) -> "FastProposerState":
        def z():
            return jnp.zeros((n_prop, n_inst), jnp.int32)

        pid = jnp.broadcast_to(
            jnp.arange(n_prop, dtype=jnp.int32)[:, None], (n_prop, n_inst)
        )
        return cls(
            bal=jnp.broadcast_to(fast_ballot(), (n_prop, n_inst)),
            phase=jnp.full((n_prop, n_inst), FAST, jnp.int32),
            own_val=pid + VALUE_BASE,
            prop_val=z(),
            heard=z(),
            best_bal=z(),
            rep_mask=jnp.zeros((n_prop, n_prop, n_inst), jnp.int32),
            timer=z(),
            decided_val=z(),
        )


@struct.dataclass
class FastPaxosState:
    """Full simulator state for Fast Paxos: one pytree, scanned and sharded."""

    acceptor: AcceptorState
    proposer: FastProposerState
    learner: LearnerState
    requests: MsgBuf  # proposer -> acceptor (PREPARE / ACCEPT)
    replies: MsgBuf  # acceptor -> proposer (PROMISE / ACCEPTED)
    tick: jnp.ndarray  # () int32
    # Flight recorder / telemetry (core.telemetry): None when disabled.
    telemetry: Optional[TelemetryState] = None
    # Coverage sketch (obs.coverage): None when disabled, same contract.
    coverage: Optional[CoverageState] = None
    # Fault-exposure counters (obs.exposure): None when disabled, same contract.
    exposure: Optional[FaultExposure] = None
    # Near-miss safety-margin sketch (obs.margin): None when disabled, same contract.
    margin: Optional[MarginState] = None
    # Client-workload queue (workload.generator): None when disabled, same
    # contract; carried by the fused engine's passthrough codec (no
    # layout-table entry — see core/state.py).
    wload: Optional[WloadState] = None

    @classmethod
    def init(
        cls,
        n_inst: int,
        n_prop: int,
        n_acc: int,
        k: int = 8,
        stale: bool = False,
        delay: bool = False,
    ) -> "FastPaxosState":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.utils.bitops import MAX_ACCEPTORS

        if not 1 <= n_prop <= MAX_PROPOSERS:
            raise ValueError(
                f"n_prop={n_prop} exceeds ballot packing capacity {MAX_PROPOSERS}"
            )
        if not 1 <= n_acc <= MAX_ACCEPTORS:
            raise ValueError(
                f"n_acc={n_acc} exceeds voter bitmask capacity {MAX_ACCEPTORS}"
            )
        proposer = FastProposerState.init(n_inst, n_prop)
        # The fast round is in flight at tick 0: every proposer's
        # Accept(fast_bal, own_val) broadcast occupies its ACCEPT slots.
        requests = MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay)
        shape = (n_prop, n_acc, n_inst)
        requests = requests.replace(
            bal=requests.bal.at[ACCEPT].set(
                jnp.broadcast_to(proposer.bal[:, None], shape)
            ),
            v1=requests.v1.at[ACCEPT].set(
                jnp.broadcast_to(proposer.own_val[:, None], shape)
            ),
            present=requests.present.at[ACCEPT].set(True),
        )
        return cls(
            acceptor=AcceptorState.init(n_inst, n_acc, stale=stale),
            proposer=proposer,
            learner=LearnerState.init(n_inst, k),
            requests=requests,
            replies=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            tick=jnp.zeros((), jnp.int32),
        )


# ---------------------------------------------------------------------------
# Packed lane-state layout (utils/bitops) — see core/state.py for the width
# rationale; Fast Paxos shares the classic widths.  phase needs 2 bits for
# FAST=3.  decided_val has no 12-bit partner leaf (best_val is replaced by
# rep_mask here), so it passes through — the layout rule bans single-field
# words.  rep_mask is a (P, V, I) vote bitmask and passes through.  Bump the
# version with ANY table edit.

from paxos_tpu.utils.bitops import F, Word, Zero  # noqa: E402

# v4: the optional bounded-delay ``until`` stamps (core/messages.py) joined
# the message buffers — full int32 tick stamps, passed through unpacked
# like rep_mask (no packing partner at 32 bits).
FP_LAYOUT_VERSION = "fastpaxos-packed-v4"
FP_LAYOUT = (
    Word("req", F("requests.bal", 15), F("requests.v1", 12),
         F("requests.present", 1, bool_=True)),
    Zero("requests.v2", like="req"),
    Word("rep", F("replies.bal", 15), F("replies.v2", 12),
         F("replies.present", 1, bool_=True)),
    Word("acc", F("acceptor.promised", 15), F("acceptor.acc_bal", 15)),
    Word("snap_acc", F("acceptor.snap_promised", 15),
         F("acceptor.snap_bal", 15), optional=True),
    # 17-bit proposer.bal: 2 headroom bits over the 15-bit report threshold
    # so the chunk-boundary-only ballot clamp (fused_tick) cannot wrap
    # mid-chunk — see core/state.py.
    Word("prop0", F("proposer.bal", 17), F("proposer.phase", 2),
         F("proposer.timer", 13, signed=True)),
    Word("prop1", F("proposer.own_val", 12), F("proposer.prop_val", 12)),
    Word("prop2", F("proposer.heard", 16), F("proposer.best_bal", 15)),
    Word("lt", F("learner.lt_bal", 15), F("learner.lt_val", 12),
         F("learner.lt_mask", "n_acc")),
    Word("chosen", F("learner.chosen", 1, bool_=True),
         F("learner.chosen_val", 12),
         F("learner.chosen_tick", 19, signed=True)),
)
FP_LAYOUT_DIMS = {"n_acc": ("acceptor.promised", 0)}

# Tick read/write-set declarations (delta codec + write-set audit — see the
# read/write-set section of utils/bitops.py).  As in classic paxos, the tick
# writes everything except proposer.own_val (the fixed fast-round candidate
# value, assigned at init and only ever read).
FP_TICK_READS = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)
FP_TICK_WRITES = (
    "acceptor.*",
    "proposer.bal", "proposer.phase", "proposer.timer", "proposer.prop_val",
    "proposer.heard", "proposer.best_bal", "proposer.rep_mask",
    "proposer.decided_val",
    "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)

# Registered fault-injection sites for the dataflow auditor
# (analysis/flow.py): site name -> fault channels it may absorb; see
# core/state.py for the registration contract.
FP_FAULT_SITES = {
    "equivocate": ("equiv",),
    "flaky": ("flaky",),
    "skew": ("skew",),
    "delay": ("delay",),
}
