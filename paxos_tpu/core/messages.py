"""Fixed-slot message buffers — the vectorized ``PaxosMessage`` wire format.

Reference parity (SURVEY.md §3.1 "PaxosMessage ADT" [B]): the reference's
ADT — Prepare(ballot) / Promise(ballot, maybe (ballot,value)) /
Accept(ballot, value) / Accepted(ballot, value) — becomes struct-of-arrays
device buffers with one slot per directed (proposer, acceptor) edge and
message kind.  A slot is a bounded, overwriting channel: sending while an
older message of the same kind is still in flight overwrites it (the network
is allowed to drop, so this loses no adversarial power — SURVEY.md §8.4.2's
"fixed-shape message plumbing" requirement).

Two buffer families, each with a ``kind`` axis of size 2:

- requests, proposer→acceptor:  kind 0 = PREPARE(bal), kind 1 = ACCEPT(bal,val)
- replies,  acceptor→proposer:  kind 0 = PROMISE(bal, prev_bal, prev_val),
                                kind 1 = ACCEPTED(bal, val)

Array shape is ``(2, n_prop, n_acc, instances)`` throughout; int32 payloads,
bool presence.  Asynchrony (delay, reordering, duplication, loss) is realized
by the transport's per-tick masks over these slots, not by queues — see
``paxos_tpu.transport.inmemory_tpu``.

Layout note (TPU): ``instances`` is the LAST axis of every array in the
framework.  The minor (lane) dimension of a TPU vector register holds 128
elements; with the huge instances axis minor, every elementwise op runs at
full lane occupancy, where an ``(I, ..., 5)`` layout would waste 123/128
lanes (measured ~9x step-time difference at 1M instances).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

# Request kinds (proposer -> acceptor)
PREPARE = 0
ACCEPT = 1
# Reply kinds (acceptor -> proposer)
PROMISE = 0
ACCEPTED = 1


@struct.dataclass
class MsgBuf:
    """In-flight messages for one direction, all instances at once.

    ``bal``/``v1``/``v2`` are int32 payload lanes whose meaning depends on
    the kind (see module docstring); ``present`` marks occupied slots.
    """

    bal: jnp.ndarray  # (2, P, A, I) int32
    v1: jnp.ndarray  # (2, P, A, I) int32
    v2: jnp.ndarray  # (2, P, A, I) int32
    present: jnp.ndarray  # (2, P, A, I) bool
    # Bounded-delay stamp (``FaultConfig.p_delay``): a slot is deliverable
    # only once ``tick >= until``.  None (pruned leaf) when delay is off —
    # the buffer is then structurally identical to pre-delay builds.
    until: Optional[jnp.ndarray] = None  # (2, P, A, I) int32

    @classmethod
    def empty(
        cls, n_inst: int, n_prop: int, n_acc: int, delay: bool = False
    ) -> "MsgBuf":
        shape = (2, n_prop, n_acc, n_inst)
        # Fresh buffer per field: aliased leaves break buffer donation.
        return cls(
            bal=jnp.zeros(shape, jnp.int32),
            v1=jnp.zeros(shape, jnp.int32),
            v2=jnp.zeros(shape, jnp.int32),
            present=jnp.zeros(shape, jnp.bool_),
            until=jnp.zeros(shape, jnp.int32) if delay else None,
        )
