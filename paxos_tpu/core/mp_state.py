"""Struct-of-arrays state for Multi-Paxos log replication (BASELINE config 3).

Reference parity: the reference implements single-decree Paxos only
(SURVEY.md §1 [B]); Multi-Paxos is part of the north-star config set
(BASELINE.json configs[2]).  Design per SURVEY.md §6.7/§8.4.6: the log is a
statically-bounded per-instance array axis ``L`` (no dynamic shapes on TPU);
long-log scaling comes from chunked scans, not unbounded arrays.

Protocol shape: classic Multi-Paxos with a distinguished leader.

- Phase 1 (leader election) covers the WHOLE log: one ``Prepare(b)``; the
  ``Promise(b)`` reply carries the acceptor's accepted (ballot, value) pair
  for every slot (the new leader's recovery information).
- The leader then drives phase 2 slot-by-slot (pipeline width 1): it
  re-proposes from slot 0 upward, adopting the highest accepted value per
  slot — re-confirming already-chosen slots is safe (it adopts the chosen
  value) and costs at most L extra rounds per leadership change.
- Leases are failure-detection-by-progress: followers watch the instance's
  chosen count; no new slot chosen for ``lease_len`` ticks means the leader
  is presumed dead and a follower runs phase 1 with a higher ballot.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import MsgBuf
from paxos_tpu.core.telemetry import TelemetryState
from paxos_tpu.obs.coverage import CoverageState
from paxos_tpu.obs.exposure import FaultExposure
from paxos_tpu.obs.margin import MarginState
from paxos_tpu.workload.generator import WloadState

# Proposer phases
FOLLOW = 0  # passive: watching progress, lease ticking
CANDIDATE = 1  # phase-1 outstanding
LEAD = 2  # distinguished leader, driving slots

# ---- Packed (ballot, value) pairs -------------------------------------------
#
# Every slot-indexed (ballot, value) pair in the Multi-Paxos state rides in
# ONE int32: ``bal << 16 | val``.  The round-4 roofline (BASELINE.md
# utilization table) located the fused MP throughput gap in the wide-table
# passes over exactly these arrays — the (L, K, I) learner rows, the
# (P, A, L, I) promise payloads, the (A, L, I) acceptor log — and packing
# halves both their VMEM footprint and the number of gather/write passes
# per tick.  It also strengthens the recovery fold: the per-slot "highest
# accepted ballot, its value" max-trick over two arrays becomes ONE lexical
# max over packed pairs (bal in the high bits dominates; at equal ballot
# the values agree — one value per (slot, ballot), equivocator payloads
# zeroed — so the value tiebreak never changes the outcome).
#
# Bit budget: ``val`` is ``(pid + 1) * 1000 + global_slot`` <= 8*1000 + 255
# < 2^16 (``own_slot_value``; MAX_PROPOSERS = 8, log_total <= 256) and
# ``bal = rnd * 8 + pid + 1`` needs rnd <= 4094 to stay under 2^15 —
# elections cost at least a lease period (~24 ticks), so even a 4096-tick
# campaign peaks near rnd ~ 170.  Packed pairs are non-negative int32s, so
# integer compares order them lexicographically by (bal, val) and 0 is
# still the NIL sentinel.
#
# The helpers work on Python ints too — the scalar interpreter
# (cpu_ref/interp.py) uses THESE functions, so the packed layout cannot
# drift between the kernels and the differential oracle.

BV_SHIFT = 16
BV_VAL_MASK = (1 << BV_SHIFT) - 1


def pack_bv(bal, val):
    """One int32 per (ballot, value) pair; 0 stays the NIL sentinel."""
    return (bal << BV_SHIFT) | val


def bv_bal(bv):
    return bv >> BV_SHIFT


def bv_val(bv):
    return bv & BV_VAL_MASK


@struct.dataclass
class MPAcceptorState:
    promised: jnp.ndarray  # (A, I) int32 — one promise covers every slot
    log: jnp.ndarray  # (A, L, I) int32 packed accepted (ballot, value) per slot
    # Stale-snapshot shadows (FaultConfig.stale_k); None when the knob is off.
    snap_promised: Optional[jnp.ndarray] = None  # (A, I) int32
    snap_log: Optional[jnp.ndarray] = None  # (A, L, I) int32

    @classmethod
    def init(
        cls, n_inst: int, n_acc: int, log_len: int, stale: bool = False
    ) -> "MPAcceptorState":
        return cls(
            promised=jnp.zeros((n_acc, n_inst), jnp.int32),
            log=jnp.zeros((n_acc, log_len, n_inst), jnp.int32),
            snap_promised=(
                jnp.zeros((n_acc, n_inst), jnp.int32) if stale else None
            ),
            snap_log=(
                jnp.zeros((n_acc, log_len, n_inst), jnp.int32)
                if stale
                else None
            ),
        )


@struct.dataclass
class MPProposerState:
    bal: jnp.ndarray  # (P, I) int32 current ballot
    phase: jnp.ndarray  # (P, I) int32 in {FOLLOW, CANDIDATE, LEAD}
    heard: jnp.ndarray  # (P, I) int32 acceptor bitmask (phase-1 or current slot)
    commit_idx: jnp.ndarray  # (P, I) int32 next slot this leader drives
    recov_bv: jnp.ndarray  # (P, L, I) int32 packed highest accepted (bal, val) per slot
    lease_timer: jnp.ndarray  # (P, I) int32 ticks since observed progress
    last_chosen_count: jnp.ndarray  # (P, I) int32 chosen slots last observed
    candidate_timer: jnp.ndarray  # (P, I) int32 ticks spent as candidate

    @classmethod
    def init(
        cls, n_inst: int, n_prop: int, log_len: int, lease_init: int = 0
    ) -> "MPProposerState":
        def z():
            return jnp.zeros((n_prop, n_inst), jnp.int32)

        return cls(
            bal=z(),  # NIL: nobody has a ballot until first election
            phase=z(),  # FOLLOW
            heard=z(),
            commit_idx=z(),
            recov_bv=jnp.zeros((n_prop, log_len, n_inst), jnp.int32),
            # Head start: the first election should not wait a full lease.
            lease_timer=jnp.full((n_prop, n_inst), lease_init, jnp.int32),
            last_chosen_count=z(),
            candidate_timer=z(),
        )


@struct.dataclass
class MPLearnerState:
    """Per-(instance, slot) chosen tracking + agreement checking.

    K rows of (ballot, value) -> voter bitmask per slot (K small: honest
    Multi-Paxos uses few ballots per slot; evictions are counted).
    """

    lt_bv: jnp.ndarray  # (L, K, I) int32 packed (ballot, value) per row
    lt_mask: jnp.ndarray  # (L, K, I) int32
    chosen: jnp.ndarray  # (L, I) bool
    chosen_val: jnp.ndarray  # (L, I) int32
    chosen_tick: jnp.ndarray  # (L, I) int32 (-1 if not chosen)
    violations: jnp.ndarray  # (I,) int32
    evictions: jnp.ndarray  # (I,) int32

    @classmethod
    def init(cls, n_inst: int, log_len: int, k: int = 4) -> "MPLearnerState":
        def zk():
            return jnp.zeros((log_len, k, n_inst), jnp.int32)

        return cls(
            lt_bv=zk(),
            lt_mask=zk(),
            chosen=jnp.zeros((log_len, n_inst), jnp.bool_),
            chosen_val=jnp.zeros((log_len, n_inst), jnp.int32),
            chosen_tick=jnp.full((log_len, n_inst), -1, jnp.int32),
            violations=jnp.zeros((n_inst,), jnp.int32),
            evictions=jnp.zeros((n_inst,), jnp.int32),
        )


@struct.dataclass
class PromiseBuf:
    """Promise replies with full-log recovery payload: one slot per (p, a) edge."""

    present: jnp.ndarray  # (P, A, I) bool
    bal: jnp.ndarray  # (P, A, I) int32 — the promised ballot
    p_bv: jnp.ndarray  # (P, A, L, I) int32 — packed accepted (bal, val) per slot
    # Bounded-delay delivery stamp (FaultConfig.p_delay): first tick the
    # slot may be consumed; 0 = deliverable immediately.  None (pruned)
    # when delay is off — see core/messages.MsgBuf.until.
    until: Optional[jnp.ndarray] = None  # (P, A, I) int32

    @classmethod
    def empty(
        cls, n_inst: int, n_prop: int, n_acc: int, log_len: int,
        delay: bool = False,
    ) -> "PromiseBuf":
        return cls(
            present=jnp.zeros((n_prop, n_acc, n_inst), jnp.bool_),
            bal=jnp.zeros((n_prop, n_acc, n_inst), jnp.int32),
            p_bv=jnp.zeros((n_prop, n_acc, log_len, n_inst), jnp.int32),
            until=(
                jnp.zeros((n_prop, n_acc, n_inst), jnp.int32)
                if delay
                else None
            ),
        )


@struct.dataclass
class AcceptedBuf:
    """Accepted replies: (ballot, slot, value) per (p, a) edge."""

    present: jnp.ndarray  # (P, A, I) bool
    bal: jnp.ndarray  # (P, A, I) int32
    slot: jnp.ndarray  # (P, A, I) int32
    val: jnp.ndarray  # (P, A, I) int32
    # Bounded-delay delivery stamp; None (pruned) when delay is off.
    until: Optional[jnp.ndarray] = None  # (P, A, I) int32

    @classmethod
    def empty(
        cls, n_inst: int, n_prop: int, n_acc: int, delay: bool = False
    ) -> "AcceptedBuf":
        return cls(
            present=jnp.zeros((n_prop, n_acc, n_inst), jnp.bool_),
            bal=jnp.zeros((n_prop, n_acc, n_inst), jnp.int32),
            slot=jnp.zeros((n_prop, n_acc, n_inst), jnp.int32),
            val=jnp.zeros((n_prop, n_acc, n_inst), jnp.int32),
            until=(
                jnp.zeros((n_prop, n_acc, n_inst), jnp.int32)
                if delay
                else None
            ),
        )


@struct.dataclass
class MultiPaxosState:
    """Full Multi-Paxos simulator state: one pytree, scanned and sharded."""

    acceptor: MPAcceptorState
    proposer: MPProposerState
    learner: MPLearnerState
    requests: MsgBuf  # p->a: kind 0 PREPARE(bal), kind 1 ACCEPT(bal, val, slot)
    promises: PromiseBuf  # a->p
    accepted: AcceptedBuf  # a->p
    tick: jnp.ndarray  # () int32
    # (I,) int32: global log index of window slot 0 — the count of
    # decided-prefix slots compacted out so far (0 in plain mode).  Message
    # slots stay window-relative; values/termination use base + slot.
    base: jnp.ndarray
    # Flight recorder / telemetry (core.telemetry): None when disabled.
    telemetry: Optional[TelemetryState] = None
    # Coverage sketch (obs.coverage): None when disabled, same contract.
    coverage: Optional[CoverageState] = None
    # Fault-exposure counters (obs.exposure): None when disabled, same contract.
    exposure: Optional[FaultExposure] = None
    # Near-miss safety-margin sketch (obs.margin): None when disabled, same contract.
    margin: Optional[MarginState] = None
    # Client-workload queue (workload.generator): None when disabled, same
    # contract; carried by the fused engine's passthrough codec (no
    # layout-table entry — see core/state.py).
    wload: Optional[WloadState] = None

    @classmethod
    def init(
        cls,
        n_inst: int,
        n_prop: int,
        n_acc: int,
        log_len: int = 8,
        k: int = 4,
        lease_init: int = 0,
        stale: bool = False,
        delay: bool = False,
    ) -> "MultiPaxosState":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.utils.bitops import MAX_ACCEPTORS

        if not 1 <= n_prop <= MAX_PROPOSERS:
            raise ValueError(f"n_prop={n_prop} exceeds {MAX_PROPOSERS}")
        if not 1 <= n_acc <= MAX_ACCEPTORS:
            raise ValueError(f"n_acc={n_acc} exceeds {MAX_ACCEPTORS}")
        return cls(
            acceptor=MPAcceptorState.init(n_inst, n_acc, log_len, stale=stale),
            proposer=MPProposerState.init(n_inst, n_prop, log_len, lease_init),
            learner=MPLearnerState.init(n_inst, log_len, k),
            requests=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            promises=PromiseBuf.empty(n_inst, n_prop, n_acc, log_len,
                                      delay=delay),
            accepted=AcceptedBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            tick=jnp.zeros((), jnp.int32),
            base=jnp.zeros((n_inst,), jnp.int32),
        )

    @property
    def log_len(self) -> int:
        return self.acceptor.log.shape[1]


# ---------------------------------------------------------------------------
# Packed lane-state layout (utils/bitops).  Multi-Paxos width rationale:
#
# - Proposer ballots stay <= 2^11 - 1, the 11-bit field capacity: the fused
#   engine saturates there instead of wrapping (fused_tick._saturate_ballots)
#   and the report-time max_ballot guard in harness/run.py condemns any
#   campaign that reaches it (tighter than the 2^15 pack_bv budget);
#   message-buffer ballot fields get 12 bits because PREPARE corruption
#   bumps msg_bal by 1, which can land exactly on 2^11.
# - Values are own_slot_value(pid, slot) < 2^13 (config-time guard in
#   init_state; corrupt flips ^64 stay in range).
# - (bal << 16 | val) log pairs transcode to dense 11+13 = 24-bit entries and
#   pack 4 entries -> 3 words along the slot axis (Stream): acceptor.log,
#   promises.p_bv, proposer.recov_bv, snap_log.  Log ballots are ACCEPT
#   ballots (never corrupt-bumped), so 11 bits suffice.
# - commit_idx <= n_slots < 64 (config-time log_len guard); candidate_timer
#   resets on election success/failure so it stays <= timeout+1 < 2^12.
# - lease_timer passes through: once the log is full nothing resets it, so
#   it grows without bound.  requests.v2 and accepted.slot pass through:
#   compact_mp_body shifts them unconditionally (present or not), so
#   non-present slots drift negative without bound.  acceptor.promised /
#   snap_promised pass through (no same-shape partner when stale is off).
#
# Bump the version with ANY table edit — the audit's layout goldens fail
# otherwise (analysis/structure.py).

from paxos_tpu.utils.bitops import F, Stream, Word  # noqa: E402

# v4: the optional bounded-delay ``until`` stamps joined all three message
# buffers (requests / promises / accepted) — full int32 tick stamps,
# passed through unpacked.
MP_LAYOUT_VERSION = "multipaxos-packed-v4"
MP_LAYOUT = (
    Word("req", F("requests.bal", 12), F("requests.v1", 13),
         F("requests.present", 1, bool_=True)),
    Word("prom", F("promises.bal", 12), F("promises.present", 1, bool_=True)),
    Stream("prom_bv", "promises.p_bv", bal_bits=11, val_bits=13),
    Word("accd", F("accepted.bal", 12), F("accepted.val", 13),
         F("accepted.present", 1, bool_=True)),
    Stream("acc_log", "acceptor.log", bal_bits=11, val_bits=13),
    Stream("snap_log", "acceptor.snap_log", bal_bits=11, val_bits=13,
           optional=True),
    # proposer.bal gets 1 headroom bit over the 11-bit report threshold
    # ((1 << 11) - 1, hardcoded in harness/run.summarize_device): ballots
    # are clamped at chunk boundaries only (fused_tick), so the field must
    # absorb chunk_ticks * BALLOT_GROWTH_PER_TICK of un-clamped monotone
    # growth mid-chunk; chunks too long for one bit fall back to the
    # per-tick clamp.
    Word("prop0", F("proposer.bal", 12), F("proposer.phase", 2),
         F("proposer.commit_idx", 6), F("proposer.candidate_timer", 12)),
    Word("prop1", F("proposer.heard", 16),
         F("proposer.last_chosen_count", 16)),
    Stream("recov", "proposer.recov_bv", bal_bits=11, val_bits=13),
    Word("lt", F("learner.lt_bv", 24, bv=(11, 13)),
         F("learner.lt_mask", "n_acc")),
    Word("chosen", F("learner.chosen", 1, bool_=True),
         F("learner.chosen_val", 13),
         F("learner.chosen_tick", 18, signed=True)),
)
MP_LAYOUT_DIMS = {"n_acc": ("acceptor.promised", 0)}

# Tick read/write-set declarations (delta codec + write-set audit — see the
# read/write-set section of utils/bitops.py).  The tick reads every leaf;
# it writes everything except ``base`` (the compacted-prefix origin, bumped
# only by the host-side compaction path, never by the in-trace tick).
MP_TICK_READS = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "promises.*",
    "accepted.*", "base",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)
MP_TICK_WRITES = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "promises.*",
    "accepted.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)

# Registered fault-injection sites for the dataflow auditor
# (analysis/flow.py): site name -> fault channels it may absorb; see
# core/state.py for the registration contract.
MP_FAULT_SITES = {
    "equivocate": ("equiv",),
    "flaky": ("flaky",),
    "skew": ("skew",),
    "delay": ("delay",),
}
