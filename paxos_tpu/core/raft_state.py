"""Raft-core state — voters with single-slot logs, candidates with terms.

Reference parity (SURVEY.md §3.3 `protocols/raftcore`, BASELINE config 5):
the cross-protocol sweep runs Raft's *vote kernel* — leader election with
the log-comparison election restriction, then append/ack replication of one
log entry — through the same scheduler/transport/fault machinery as Paxos,
over the same (instances, proposers, acceptors) topology: proposer lanes
are candidates/leaders, acceptor lanes are voters that also store the
replicated entry.

Terms are packed ballots (:mod:`paxos_tpu.core.ballot`): proposer-unique
and totally ordered, so "at most one vote per term" becomes "grant only
ballots strictly above the last granted one" with no extra votedFor cell.
Entry terms reuse the same encoding, making Raft's up-to-date comparison
(``candidate_last_term >= voter_entry_term`` in the single-slot case) an
integer compare — the same compare unit the quorum kernel runs on.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import MsgBuf
from paxos_tpu.core.state import LearnerState
from paxos_tpu.core.telemetry import TelemetryState
from paxos_tpu.obs.coverage import CoverageState
from paxos_tpu.obs.exposure import FaultExposure
from paxos_tpu.obs.margin import MarginState
from paxos_tpu.workload.generator import WloadState

# Candidate phases (values match core.state.P1/P2/DONE so summarize() and
# liveness stats are shared across protocols).
CAND = 0  # soliciting votes (RequestVote broadcast out)
LEAD = 1  # elected; appending the entry (AppendEntries broadcast out)
DONE = 2  # observed a majority of acks: entry committed

# Request kinds (candidate -> voter)
REQVOTE = 0  # bal=candidate term, v1=candidate's entry term (0 = empty log)
APPEND = 1  # bal=leader term, v1=entry value
# Reply kinds (voter -> candidate)
VOTE = 0  # bal=requested term, v1=(payload_term << 1) | granted, v2=entry val
ACK = 1  # bal=leader term, v1=entry value

VALUE_BASE = 100  # candidate p proposes VALUE_BASE + p when its log is empty


@struct.dataclass
class VoterState:
    """(A, I) per-voter durable state.

    ``voted`` is the Paxos-promise-shaped cell: the highest term this voter
    has either granted a vote to or accepted an append from.  Raising it on
    append (not just on grant) is what fences stale leaders, mirroring
    Raft's currentTerm update on AppendEntries.
    """

    voted: jnp.ndarray  # (A, I) int32 packed term; 0 = none yet
    ent_term: jnp.ndarray  # (A, I) int32 packed term of stored entry; 0 = empty
    ent_val: jnp.ndarray  # (A, I) int32 stored entry value
    # Stale-snapshot shadows (FaultConfig.stale_k); None when the knob is off.
    snap_voted: Optional[jnp.ndarray] = None  # (A, I) int32
    snap_term: Optional[jnp.ndarray] = None  # (A, I) int32
    snap_val: Optional[jnp.ndarray] = None  # (A, I) int32

    @classmethod
    def init(cls, n_inst: int, n_acc: int, stale: bool = False) -> "VoterState":
        def z():
            return jnp.zeros((n_acc, n_inst), jnp.int32)

        return cls(
            voted=z(),
            ent_term=z(),
            ent_val=z(),
            snap_voted=z() if stale else None,
            snap_term=z() if stale else None,
            snap_val=z() if stale else None,
        )


@struct.dataclass
class CandidateState:
    bal: jnp.ndarray  # (P, I) int32 current term (packed ballot)
    phase: jnp.ndarray  # (P, I) int32 in {CAND, LEAD, DONE}
    own_val: jnp.ndarray  # (P, I) int32 value proposed if log empty
    prop_val: jnp.ndarray  # (P, I) int32 value being appended while LEAD
    heard: jnp.ndarray  # (P, I) int32 voter bitmask (grants in CAND, acks in LEAD)
    ent_term: jnp.ndarray  # (P, I) int32 candidate's own log entry term
    ent_val: jnp.ndarray  # (P, I) int32 candidate's own log entry value
    timer: jnp.ndarray  # (P, I) int32 ticks since phase start (<0: backoff)
    decided_val: jnp.ndarray  # (P, I) int32 value this candidate saw committed

    @classmethod
    def init(cls, n_inst: int, n_prop: int) -> "CandidateState":
        def z():
            return jnp.zeros((n_prop, n_inst), jnp.int32)

        pid = jnp.broadcast_to(
            jnp.arange(n_prop, dtype=jnp.int32)[:, None], (n_prop, n_inst)
        )
        return cls(
            bal=make_ballot(jnp.zeros_like(pid), pid),
            phase=z(),  # CAND
            own_val=pid + VALUE_BASE,
            prop_val=z(),
            heard=z(),
            ent_term=z(),
            ent_val=z(),
            timer=z(),
            decided_val=z(),
        )


@struct.dataclass
class RaftState:
    """Full simulator state for Raft-core: one pytree, scanned and sharded."""

    acceptor: VoterState  # named `acceptor` so sharding/summaries are uniform
    proposer: CandidateState  # likewise
    learner: LearnerState
    requests: MsgBuf  # candidate -> voter (REQVOTE / APPEND)
    replies: MsgBuf  # voter -> candidate (VOTE / ACK)
    tick: jnp.ndarray  # () int32
    # Flight recorder / telemetry (core.telemetry): None when disabled.
    telemetry: Optional[TelemetryState] = None
    # Coverage sketch (obs.coverage): None when disabled, same contract.
    coverage: Optional[CoverageState] = None
    # Fault-exposure counters (obs.exposure): None when disabled, same contract.
    exposure: Optional[FaultExposure] = None
    # Near-miss safety-margin sketch (obs.margin): None when disabled, same contract.
    margin: Optional[MarginState] = None
    # Client-workload queue (workload.generator): None when disabled, same
    # contract; carried by the fused engine's passthrough codec (no
    # layout-table entry — see core/state.py).
    wload: Optional[WloadState] = None

    @classmethod
    def init(
        cls,
        n_inst: int,
        n_prop: int,
        n_acc: int,
        k: int = 8,
        stale: bool = False,
        delay: bool = False,
    ) -> "RaftState":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.utils.bitops import MAX_ACCEPTORS

        if not 1 <= n_prop <= MAX_PROPOSERS:
            raise ValueError(
                f"n_prop={n_prop} exceeds ballot packing capacity {MAX_PROPOSERS}"
            )
        if not 1 <= n_acc <= MAX_ACCEPTORS:
            raise ValueError(
                f"n_acc={n_acc} exceeds voter bitmask capacity {MAX_ACCEPTORS}"
            )
        proposer = CandidateState.init(n_inst, n_prop)
        # Every candidate opens with a RequestVote broadcast in flight.
        requests = MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay)
        shape = (n_prop, n_acc, n_inst)
        requests = requests.replace(
            bal=requests.bal.at[REQVOTE].set(
                jnp.broadcast_to(proposer.bal[:, None], shape)
            ),
            present=requests.present.at[REQVOTE].set(True),
        )
        return cls(
            acceptor=VoterState.init(n_inst, n_acc, stale=stale),
            proposer=proposer,
            learner=LearnerState.init(n_inst, k),
            requests=requests,
            replies=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            tick=jnp.zeros((), jnp.int32),
        )


# ---------------------------------------------------------------------------
# Packed lane-state layout (utils/bitops) — see core/state.py for the width
# rationale.  Raft-specific widths: requests.v1 carries 15-bit terms
# (REQVOTE ships ent_term_c) as well as 12-bit values (APPEND ships
# prop_val), so it gets 15 bits; replies.v1 carries VOTE's term*2+grant
# (16 bits) and ACK's value, so it passes through.  ent_term is a ballot
# (elected leaders adopt cand.bal), hence 15 bits.  requests.v2 is
# identically 0 (APPEND and REQVOTE both send v2=0).  Bump the version with
# ANY table edit.

from paxos_tpu.utils.bitops import F, Word, Zero  # noqa: E402

# v4: the optional bounded-delay ``until`` stamps (core/messages.py) joined
# the message buffers — full int32 tick stamps, passed through unpacked.
RAFT_LAYOUT_VERSION = "raftcore-packed-v4"
RAFT_LAYOUT = (
    Word("req", F("requests.bal", 15), F("requests.v1", 15),
         F("requests.present", 1, bool_=True)),
    Zero("requests.v2", like="req"),
    Word("rep", F("replies.bal", 15), F("replies.v2", 12),
         F("replies.present", 1, bool_=True)),
    Word("acc", F("acceptor.voted", 15), F("acceptor.ent_term", 15)),
    Word("snap_acc", F("acceptor.snap_voted", 15),
         F("acceptor.snap_term", 15), optional=True),
    # 17-bit proposer.bal (term): 2 headroom bits over the 15-bit report
    # threshold so the chunk-boundary-only ballot clamp (fused_tick) cannot
    # wrap mid-chunk — see core/state.py.
    Word("prop0", F("proposer.bal", 17), F("proposer.phase", 2),
         F("proposer.timer", 13, signed=True)),
    Word("prop1", F("proposer.own_val", 12), F("proposer.prop_val", 12)),
    Word("prop2", F("proposer.heard", 16), F("proposer.ent_term", 15)),
    Word("prop3", F("proposer.ent_val", 12), F("proposer.decided_val", 12)),
    Word("lt", F("learner.lt_bal", 15), F("learner.lt_val", 12),
         F("learner.lt_mask", "n_acc")),
    Word("chosen", F("learner.chosen", 1, bool_=True),
         F("learner.chosen_val", 12),
         F("learner.chosen_tick", 19, signed=True)),
)
RAFT_LAYOUT_DIMS = {"n_acc": ("acceptor.voted", 0)}

# Tick read/write-set declarations (delta codec + write-set audit — see the
# read/write-set section of utils/bitops.py).  The tick writes everything
# except proposer.own_val (the candidate's fixed value, only ever read).
RAFT_TICK_READS = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)
RAFT_TICK_WRITES = (
    "acceptor.*",
    "proposer.bal", "proposer.phase", "proposer.timer", "proposer.prop_val",
    "proposer.heard", "proposer.ent_term", "proposer.ent_val",
    "proposer.decided_val",
    "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)

# Registered fault-injection sites for the dataflow auditor
# (analysis/flow.py): site name -> fault channels it may absorb; see
# core/state.py for the registration contract.
RAFT_FAULT_SITES = {
    "equivocate": ("equiv",),
    "flaky": ("flaky",),
    "skew": ("skew",),
    "delay": ("delay",),
}
