"""SynchPaxos state — classic Paxos roles plus a synchrony-exploiting leader.

SynchPaxos (after the bounded-delay SMR line of arXiv:2507.12792) is the
fifth protocol of the sweep: it EXPLOITS the bounded-delay fault dimension
(``FaultConfig.p_delay`` / ``delta``) instead of merely tolerating it.

Protocol shape, built so safety never depends on the synchrony bet:

- **Fast path (round 0)**: a designated leader (proposer 0) owns the unique
  round-0 ballot ``sync_ballot() = make_ballot(0, 0)`` and broadcasts
  ``Accept(sync_bal, own_val)`` at tick 0, skipping phase 1.  It decides
  when a **majority** of Accepted arrives while its timer is still inside
  the synchrony window ``delta`` — one round trip when the network honors
  the bound.  Because round 0 has a single owner, a majority quorum at that
  ballot is just classic phase 2: the delta guard is a liveness/latency
  bet, never a safety assumption.
- **Classic fallback**: the leader abandons the fast attempt when its timer
  exceeds ``delta`` (followers wait out the normal ``timeout``), then runs
  ordinary Paxos rounds (>= 1) with phase-1 recovery — which adopts the
  round-0 value if any acceptor reports it, so a late fast quorum can never
  contradict a fallback decision.
- **Followers** start passive in P1 with nothing in flight: their first
  send is a classic PREPARE after ``timeout`` ticks of no progress.  No
  follower ever emits a round-0 message, preserving round-0's single owner.

``FaultConfig.sp_unsafe_fast`` is the planted delay-unsafe bug: the leader
commits its fast value on the FIRST Accepted heard, without the delta
window or the quorum — the bogus "one ack within the window implies
everyone got it" synchrony shortcut.  Under delta-violating delays (plus
loss) the checker must flag it (proposer/learner disagreement).

The state pytree reuses the classic single-decree role dataclasses
(:class:`~paxos_tpu.core.state.AcceptorState` /
:class:`~paxos_tpu.core.state.ProposerState` /
:class:`~paxos_tpu.core.state.LearnerState` and the
:class:`~paxos_tpu.core.messages.MsgBuf` wire format) — only the init
differs, so the identical fault plan drives SynchPaxos alongside the other
four protocols.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import MsgBuf
from paxos_tpu.core.state import (
    DONE,
    P1,
    P2,
    AcceptorState,
    LearnerState,
    ProposerState,
)
from paxos_tpu.core.telemetry import TelemetryState
from paxos_tpu.obs.coverage import CoverageState
from paxos_tpu.obs.exposure import FaultExposure
from paxos_tpu.obs.margin import MarginState
from paxos_tpu.workload.generator import WloadState

# Proposer phases: P1/P2/DONE match core.state so summarize() is shared;
# FAST is the leader's round-0 window (fits the layout's 2-bit phase field,
# same budget as fastpaxos' FAST).
FAST = 3

# Value encoding: proposer p proposes VALUE_BASE + p (ProposerState.init).
VALUE_BASE = 100


def sync_ballot() -> jnp.ndarray:
    """The leader-owned round-0 ballot of the fast path."""
    return make_ballot(0, 0)


@struct.dataclass
class SynchPaxosState:
    """Full simulator state for SynchPaxos: one pytree, scanned and sharded."""

    acceptor: AcceptorState
    proposer: ProposerState
    learner: LearnerState
    requests: MsgBuf  # proposer -> acceptor (PREPARE / ACCEPT)
    replies: MsgBuf  # acceptor -> proposer (PROMISE / ACCEPTED)
    tick: jnp.ndarray  # () int32
    # Flight recorder / telemetry (core.telemetry): None when disabled.
    telemetry: Optional[TelemetryState] = None
    # Coverage sketch (obs.coverage): None when disabled, same contract.
    coverage: Optional[CoverageState] = None
    # Fault-exposure counters (obs.exposure): None when disabled, same contract.
    exposure: Optional[FaultExposure] = None
    # Near-miss safety-margin sketch (obs.margin): None when disabled, same contract.
    margin: Optional[MarginState] = None
    # Client-workload queue (workload.generator): None when disabled, same
    # contract; carried by the fused engine's passthrough codec (no
    # layout-table entry — see core/state.py).
    wload: Optional[WloadState] = None

    @classmethod
    def init(
        cls,
        n_inst: int,
        n_prop: int,
        n_acc: int,
        k: int = 8,
        stale: bool = False,
        delay: bool = False,
    ) -> "SynchPaxosState":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.utils.bitops import MAX_ACCEPTORS

        if not 1 <= n_prop <= MAX_PROPOSERS:
            raise ValueError(
                f"n_prop={n_prop} exceeds ballot packing capacity {MAX_PROPOSERS}"
            )
        if not 1 <= n_acc <= MAX_ACCEPTORS:
            raise ValueError(
                f"n_acc={n_acc} exceeds voter bitmask capacity {MAX_ACCEPTORS}"
            )
        proposer = ProposerState.init(n_inst, n_prop)
        # Leader lane (proposer 0) opens in FAST; the tick function emits its
        # round-0 Accept broadcast at timer == 0 THROUGH the faulty network
        # (drop/flaky/delay apply — pre-seeding the buffer here would make
        # the fast round immune to loss).  Followers idle in P1 with nothing
        # in flight: their first emit is the post-timeout classic PREPARE.
        # ProposerState.init already gives row 0 bal == make_ballot(0, 0).
        leader = (
            jnp.arange(n_prop, dtype=jnp.int32)[:, None] == 0
        )  # (P, 1) broadcast against (P, I)
        proposer = proposer.replace(
            phase=jnp.broadcast_to(
                jnp.where(leader, FAST, P1).astype(jnp.int32),
                (n_prop, n_inst),
            ),
        )
        return cls(
            acceptor=AcceptorState.init(n_inst, n_acc, stale=stale),
            proposer=proposer,
            learner=LearnerState.init(n_inst, k),
            requests=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            replies=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            tick=jnp.zeros((), jnp.int32),
        )


# ---------------------------------------------------------------------------
# Packed lane-state layout (utils/bitops) — SynchPaxos shares the classic
# single-decree widths verbatim (see core/state.py for the rationale); the
# 2-bit phase field already covers FAST = 3.  Bump the version with ANY
# table edit.

from paxos_tpu.utils.bitops import F, Word, Zero  # noqa: E402

# v1: born after the bounded-delay plane, so the optional ``until`` stamps
# (full int32 passthrough lanes) are part of the base layout contract.
SP_LAYOUT_VERSION = "synchpaxos-packed-v1"
SP_LAYOUT = (
    Word("req", F("requests.bal", 15), F("requests.v1", 12),
         F("requests.present", 1, bool_=True)),
    Zero("requests.v2", like="req"),
    Word("rep", F("replies.bal", 15), F("replies.v2", 12),
         F("replies.present", 1, bool_=True)),
    Word("acc", F("acceptor.promised", 15), F("acceptor.acc_bal", 15)),
    Word("snap_acc", F("acceptor.snap_promised", 15),
         F("acceptor.snap_bal", 15), optional=True),
    # 17-bit proposer.bal: 2 headroom bits over the 15-bit report threshold
    # so the chunk-boundary-only ballot clamp (fused_tick) cannot wrap
    # mid-chunk — see core/state.py.
    Word("prop0", F("proposer.bal", 17), F("proposer.phase", 2),
         F("proposer.timer", 13, signed=True)),
    Word("prop1", F("proposer.own_val", 12), F("proposer.prop_val", 12)),
    Word("prop2", F("proposer.heard", 16), F("proposer.best_bal", 15)),
    Word("prop3", F("proposer.best_val", 12), F("proposer.decided_val", 12)),
    Word("lt", F("learner.lt_bal", 15), F("learner.lt_val", 12),
         F("learner.lt_mask", "n_acc")),
    Word("chosen", F("learner.chosen", 1, bool_=True),
         F("learner.chosen_val", 12),
         F("learner.chosen_tick", 19, signed=True)),
)
SP_LAYOUT_DIMS = {"n_acc": ("acceptor.promised", 0)}

# Tick read/write-set declarations (delta codec + write-set audit — see the
# read/write-set section of utils/bitops.py).  Identical to classic paxos:
# the tick writes everything except proposer.own_val.
SP_TICK_READS = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)
SP_TICK_WRITES = (
    "acceptor.*",
    "proposer.bal", "proposer.phase", "proposer.timer", "proposer.prop_val",
    "proposer.heard", "proposer.best_bal", "proposer.best_val",
    "proposer.decided_val",
    "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)

# Registered fault-injection sites for the dataflow auditor
# (analysis/flow.py): site name -> fault channels it may absorb; see
# core/state.py for the registration contract.
SP_FAULT_SITES = {
    "equivocate": ("equiv",),
    "flaky": ("flaky",),
    "skew": ("skew",),
    "delay": ("delay",),
}
