"""Struct-of-arrays role state — the vectorized actor heap.

Reference parity (SURVEY.md §3.1 [B][P]): each Cloud Haskell role process's
loop-carried state becomes a field of a batched dataclass over the
``instances`` axis:

- Acceptor process state (``promisedBallot``, ``acceptedBallot``,
  ``acceptedValue``) -> :class:`AcceptorState`, shape ``(A, I)``.
- Proposer process state (current ballot, phase, collected promises, the
  value to propose, retry timer) -> :class:`ProposerState`, shape ``(P, I)``.
- Learner process state (per-ballot Accepted counts) -> :class:`LearnerState`,
  a bounded top-K table of (ballot, value) -> acceptor-bitmask, shape
  ``(K, I)`` — the on-device twin of the learner's quorum counting, and the
  substrate of the safety checker (``paxos_tpu.check.safety``).

Everything is int32/bool; NIL ballots/values are 0.  All dataclasses are
immutable flax pytrees, so the whole simulator state is one pytree that
``lax.scan`` carries and ``pjit`` shards on its trailing ``instances`` axis
(instance-minor layout — see ``core.messages`` for why).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import MsgBuf
from paxos_tpu.core.telemetry import TelemetryState
from paxos_tpu.obs.coverage import CoverageState
from paxos_tpu.obs.exposure import FaultExposure
from paxos_tpu.obs.margin import MarginState
from paxos_tpu.workload.generator import WloadState

# Proposer phases
P1 = 0  # prepare sent, collecting promises
P2 = 1  # accept sent, collecting accepted
DONE = 2  # proposer observed a quorum of Accepted for its ballot


@struct.dataclass
class AcceptorState:
    promised: jnp.ndarray  # (A, I) int32 ballot; highest ballot promised
    acc_bal: jnp.ndarray  # (A, I) int32 ballot of last accepted proposal
    acc_val: jnp.ndarray  # (A, I) int32 value of last accepted proposal
    # Stale-snapshot shadows (FaultConfig.stale_k bug injection): the
    # durable image a recovering acceptor rolls back to.  None (pruned from
    # the pytree) unless the knob is on — default states keep their
    # pre-gray structure.
    snap_promised: Optional[jnp.ndarray] = None  # (A, I) int32
    snap_bal: Optional[jnp.ndarray] = None  # (A, I) int32
    snap_val: Optional[jnp.ndarray] = None  # (A, I) int32

    @classmethod
    def init(cls, n_inst: int, n_acc: int, stale: bool = False) -> "AcceptorState":
        # Fresh buffer per field: aliased leaves break buffer donation.
        def z():
            return jnp.zeros((n_acc, n_inst), jnp.int32)

        return cls(
            promised=z(),
            acc_bal=z(),
            acc_val=z(),
            snap_promised=z() if stale else None,
            snap_bal=z() if stale else None,
            snap_val=z() if stale else None,
        )


@struct.dataclass
class ProposerState:
    bal: jnp.ndarray  # (P, I) int32 current ballot
    phase: jnp.ndarray  # (P, I) int32 in {P1, P2, DONE}
    own_val: jnp.ndarray  # (P, I) int32 value this proposer wants
    prop_val: jnp.ndarray  # (P, I) int32 value sent in phase 2 (else NIL)
    heard: jnp.ndarray  # (P, I) int32 acceptor bitmask for current phase
    best_bal: jnp.ndarray  # (P, I) int32 highest prev-accepted ballot seen
    best_val: jnp.ndarray  # (P, I) int32 its value
    timer: jnp.ndarray  # (P, I) int32 ticks since phase start (can be <0: backoff)
    decided_val: jnp.ndarray  # (P, I) int32 value this proposer saw decided

    @classmethod
    def init(cls, n_inst: int, n_prop: int) -> "ProposerState":
        def z():
            return jnp.zeros((n_prop, n_inst), jnp.int32)

        pid = jnp.broadcast_to(
            jnp.arange(n_prop, dtype=jnp.int32)[:, None], (n_prop, n_inst)
        )
        return cls(
            bal=make_ballot(jnp.zeros_like(pid), pid),  # all start at round 0
            phase=z(),  # P1
            own_val=pid + 100,  # distinct per proposer so duels are observable
            prop_val=z(),
            heard=z(),
            best_bal=z(),
            best_val=z(),
            timer=z(),
            decided_val=z(),
        )


@struct.dataclass
class LearnerState:
    """Bounded per-instance table of (ballot, value) -> acceptor bitmask.

    The learner counts Accepted(b, v) events per distinct (b, v) pair; a pair
    whose bitmask reaches a majority is *chosen*.  K slots, evicting the
    smallest ballot when full (evictions counted — a nonzero count means the
    checker's completeness bound was hit, which adversarial configs keep at 0).
    """

    lt_bal: jnp.ndarray  # (K, I) int32
    lt_val: jnp.ndarray  # (K, I) int32
    lt_mask: jnp.ndarray  # (K, I) int32 acceptor bitmask
    chosen: jnp.ndarray  # (I,) bool: some value has been chosen
    chosen_val: jnp.ndarray  # (I,) int32: the first chosen value
    chosen_tick: jnp.ndarray  # (I,) int32: tick of first choice (-1 if none)
    violations: jnp.ndarray  # (I,) int32: safety violations observed
    evictions: jnp.ndarray  # (I,) int32: table evictions (completeness bound)

    @classmethod
    def init(cls, n_inst: int, k: int = 8) -> "LearnerState":
        def zk():
            return jnp.zeros((k, n_inst), jnp.int32)

        def zi():
            return jnp.zeros((n_inst,), jnp.int32)

        return cls(
            lt_bal=zk(),
            lt_val=zk(),
            lt_mask=zk(),
            chosen=jnp.zeros((n_inst,), jnp.bool_),
            chosen_val=zi(),
            chosen_tick=jnp.full((n_inst,), -1, jnp.int32),
            violations=zi(),
            evictions=zi(),
        )


@struct.dataclass
class PaxosState:
    """Full simulator state for single-decree Paxos: one pytree, scanned and sharded."""

    acceptor: AcceptorState
    proposer: ProposerState
    learner: LearnerState
    requests: MsgBuf  # proposer -> acceptor (PREPARE / ACCEPT)
    replies: MsgBuf  # acceptor -> proposer (PROMISE / ACCEPTED)
    tick: jnp.ndarray  # () int32 global tick counter
    # Flight recorder / telemetry (core.telemetry): None when disabled —
    # pruned from the pytree, so default states keep the pre-telemetry
    # structure (same contract as the snap_* gray fields above).
    telemetry: Optional[TelemetryState] = None
    # Coverage sketch (obs.coverage): None when disabled, same contract.
    coverage: Optional[CoverageState] = None
    # Fault-exposure counters (obs.exposure): None when disabled, same contract.
    exposure: Optional[FaultExposure] = None
    # Near-miss safety-margin sketch (obs.margin): None when disabled, same contract.
    margin: Optional[MarginState] = None
    # Client-workload queue (workload.generator): None when disabled, same
    # contract.  Deliberately NOT declared in the tick read/write tables
    # below — all leaves are non-scalar trailing-I int32, so the fused
    # engine's passthrough codec (utils/bitops) carries them without any
    # layout-table edit, keeping the packed LAYOUT goldens byte-identical.
    wload: Optional[WloadState] = None

    @classmethod
    def init(
        cls,
        n_inst: int,
        n_prop: int,
        n_acc: int,
        k: int = 8,
        stale: bool = False,
        delay: bool = False,
    ) -> "PaxosState":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.utils.bitops import MAX_ACCEPTORS

        if not 1 <= n_prop <= MAX_PROPOSERS:
            raise ValueError(
                f"n_prop={n_prop} exceeds ballot packing capacity {MAX_PROPOSERS}"
            )
        if not 1 <= n_acc <= MAX_ACCEPTORS:
            raise ValueError(
                f"n_acc={n_acc} exceeds voter bitmask capacity {MAX_ACCEPTORS}"
            )
        proposer = ProposerState.init(n_inst, n_prop)
        # Every proposer opens with a phase-1 broadcast: PREPARE(bal) to all
        # acceptors is in flight at tick 0 (the reference's `forM_ pids $
        # send (Prepare b)` before the first `receiveWait` — SURVEY.md §4.2).
        requests = MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay)
        prep_bal = jnp.broadcast_to(
            proposer.bal[:, None, :], (n_prop, n_acc, n_inst)
        )
        requests = requests.replace(
            bal=requests.bal.at[0].set(prep_bal),  # kind 0 == PREPARE
            present=requests.present.at[0].set(True),
        )
        return cls(
            acceptor=AcceptorState.init(n_inst, n_acc, stale=stale),
            proposer=proposer,
            learner=LearnerState.init(n_inst, k),
            requests=requests,
            replies=MsgBuf.empty(n_inst, n_prop, n_acc, delay=delay),
            tick=jnp.zeros((), jnp.int32),
        )

    @property
    def n_inst(self) -> int:
        return self.acceptor.promised.shape[1]

    @property
    def n_acc(self) -> int:
        return self.acceptor.promised.shape[0]

    @property
    def n_prop(self) -> int:
        return self.proposer.bal.shape[0]


# ---------------------------------------------------------------------------
# Packed lane-state layout (utils/bitops): how the fused engine fuses these
# leaves into dense 32-bit VMEM words.  Field widths come from protocol
# invariants — ballots are make_ballot(rnd, pid) = rnd*8+pid+1 < 2^15
# (report-time max_ballot guard in harness/run.py), values are
# pid+VALUE_BASE or adopted values < 2^12 (corrupt flips ^64 stay in range),
# timers stay within ±(timeout+1 / backoff_max*backoff_skew) < 2^12
# (config-time guard), chosen_tick < 2^18 ticks per campaign.  requests.v2
# is identically 0 (ACCEPT/PREPARE both send v2=0; the transport only ever
# overwrites payloads with sends), so it stores nothing.  Unlisted leaves
# (acc_val / snap_val / replies.v1 / violations / evictions / telemetry)
# pass through as full int32 lanes: replies.v1 carries 15-bit promise
# ballots AND 12-bit accepted values depending on kind, so packing it would
# save nothing safe.  Bump the version with ANY table edit — the audit's
# layout goldens fail otherwise (analysis/structure.py).

from paxos_tpu.utils.bitops import F, Word, Zero  # noqa: E402

# v4: the bounded-delay ``until`` stamps joined the message buffers
# (requests.until / replies.until, present only under p_delay).  They pass
# through as full int32 lanes — a delivery tick needs the whole campaign
# tick range, so packing saves nothing safe.
PAXOS_LAYOUT_VERSION = "paxos-packed-v4"
PAXOS_LAYOUT = (
    Word("req", F("requests.bal", 15), F("requests.v1", 12),
         F("requests.present", 1, bool_=True)),
    Zero("requests.v2", like="req"),
    Word("rep", F("replies.bal", 15), F("replies.v2", 12),
         F("replies.present", 1, bool_=True)),
    Word("acc", F("acceptor.promised", 15), F("acceptor.acc_bal", 15)),
    Word("snap_acc", F("acceptor.snap_promised", 15),
         F("acceptor.snap_bal", 15), optional=True),
    # proposer.bal gets 2 headroom bits over the 15-bit report threshold
    # ((1 << 15) - 1, hardcoded in harness/run.summarize_device): the fused
    # engine clamps ballots at chunk *boundaries* only (fused_tick), so the
    # field must absorb up to chunk_ticks * BALLOT_GROWTH_PER_TICK of
    # un-clamped monotone growth mid-chunk without wrapping.
    Word("prop0", F("proposer.bal", 17), F("proposer.phase", 2),
         F("proposer.timer", 13, signed=True)),
    Word("prop1", F("proposer.own_val", 12), F("proposer.prop_val", 12)),
    Word("prop2", F("proposer.heard", 16), F("proposer.best_bal", 15)),
    Word("prop3", F("proposer.best_val", 12), F("proposer.decided_val", 12)),
    Word("lt", F("learner.lt_bal", 15), F("learner.lt_val", 12),
         F("learner.lt_mask", "n_acc")),
    Word("chosen", F("learner.chosen", 1, bool_=True),
         F("learner.chosen_val", 12),
         F("learner.chosen_tick", 19, signed=True)),
)
PAXOS_LAYOUT_DIMS = {"n_acc": ("acceptor.promised", 0)}

# Tick read/write-set declarations (delta codec + write-set audit — see the
# read/write-set section of utils/bitops.py).  The tick reads every leaf;
# it writes everything except proposer.own_val (each proposer's fixed
# candidate value, assigned at init and only ever read).  Globs cover the
# optional planes (snap_* gray shadows under acceptor.*, telemetry /
# coverage / exposure) so one declaration serves every config shape.
PAXOS_TICK_READS = (
    "acceptor.*", "proposer.*", "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)
PAXOS_TICK_WRITES = (
    "acceptor.*",
    "proposer.bal", "proposer.phase", "proposer.timer", "proposer.prop_val",
    "proposer.heard", "proposer.best_bal", "proposer.best_val",
    "proposer.decided_val",
    "learner.*", "requests.*", "replies.*",
    "telemetry.*", "coverage.*", "exposure.*", "margin.*", "tick",
)

# Registered fault-injection sites for the dataflow auditor
# (analysis/flow.py): site name (as tagged by ``faults.injector.fault_site``
# in protocols/paxos.py) -> fault channels the site may absorb.  The
# injector's own window queries (alive / prop_alive / recovering / link_ok)
# are registered globally in ``faults.injector.INJECTOR_FAULT_SITES``.
PAXOS_FAULT_SITES = {
    "equivocate": ("equiv",),
    "flaky": ("flaky",),
    "skew": ("skew",),
    "delay": ("delay",),
}
