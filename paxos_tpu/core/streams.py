"""Central PRNG stream registry — every stream id and fold_in constant.

Determinism in this codebase rests on stream *discipline*: the XLA engine
derives every tick's randomness from a fixed-width ``jax.random.split`` plus
gray-only ``fold_in`` constants, and the fused engines index the counter
PRNG (``kernels/counter_prng``) by small integer stream ids.  PR 1's
contract — gray-failure draws live on streams disjoint from the pre-gray
protocol draws, so default-config schedules stay bit-identical — was
enforced only by comments and golden digests.  This module makes the
allocation itself a checked artifact:

- **Counter stream families** (:class:`StreamFamily`): the single-decree
  family (paxos / fastpaxos / raftcore share one mask sampler) and the
  multipaxos family each map mask names to counter-PRNG stream ids, with a
  ``gray_base`` splitting protocol streams (below) from gray streams (at or
  above).  ``validate()`` rejects collisions and range breaches at import.
- **fold_in domains**: the root domain (``PRNGKey(seed)`` → step/plan
  keys), the tick domain (gray draws inside ``sample_masks``), and the plan
  domain (gray fields of ``FaultPlan.sample``).  Constants in different
  domains fold different keys, so equal values across domains are fine;
  within a domain each constant is unique and gray constants sit at or
  above :data:`GRAY_FOLD_BASE`.

The jaxpr-level auditor (``paxos_tpu/analysis``) recovers every
``fold_in``/``random_bits``/counter-stream draw from traced step functions
and checks them against THIS registry — an unregistered constant, a
collision, or a gray draw in a default-config trace fails the audit
(``paxos_tpu audit``; tests/test_audit.py).

Numbering is historical and frozen: the multipaxos family's ``BACKOFF``
stream is 10 (it predates the gray layer), so that family's gray streams
start at 11 while the single-decree family's start at 10.  Renumbering
would silently change every recorded schedule digest — the registry
records reality; the auditor keeps reality consistent.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax

__all__ = [
    "StreamFamily",
    "SINGLE_DECREE",
    "MULTI_PAXOS",
    "FAMILIES",
    "family_of",
    "ROOT_STEP",
    "ROOT_PLAN",
    "ROOT_WLOAD",
    "GRAY_FOLD_BASE",
    "TICK_FOLDS",
    "PLAN_FOLDS",
    "tick_key",
    "root_step_key",
    "root_plan_key",
    "root_wload_key",
    "tick_fold",
    "plan_fold",
]


@dataclasses.dataclass(frozen=True)
class StreamFamily:
    """One counter-PRNG stream allocation (one mask-sampler lineage).

    ``streams`` maps mask names to ``kernels/counter_prng`` stream ids;
    ``gray`` names the streams drawn only when a gray-failure knob is on,
    and ``wload`` the streams drawn only when the client-workload plane is
    on (``harness.config.WorkloadConfig``).  Invariant (checked by
    :meth:`validate`): protocol streams are all ``< gray_base`` and
    gray/wload streams all ``>= gray_base``, so a default-config trace
    containing any stream ``>= gray_base`` is a determinism bug by
    construction.
    """

    name: str
    streams: Mapping[str, int]
    gray: frozenset
    gray_base: int
    wload: frozenset = frozenset()

    def validate(self) -> None:
        ids = list(self.streams.values())
        if len(ids) != len(set(ids)):
            dup = {
                i: sorted(n for n, v in self.streams.items() if v == i)
                for i in set(ids)
                if ids.count(i) > 1
            }
            raise ValueError(
                f"stream family {self.name!r}: duplicate stream ids {dup}"
            )
        unknown = (self.gray | self.wload) - set(self.streams)
        if unknown:
            raise ValueError(
                f"stream family {self.name!r}: gray/wload names "
                f"{sorted(unknown)} not in the stream table"
            )
        overlap = self.gray & self.wload
        if overlap:
            raise ValueError(
                f"stream family {self.name!r}: streams {sorted(overlap)} "
                "claimed by both gray and wload"
            )
        for mask, sid in self.streams.items():
            if sid < 0:
                raise ValueError(
                    f"stream family {self.name!r}: negative id {mask}={sid}"
                )
            if mask in (self.gray | self.wload) and sid < self.gray_base:
                raise ValueError(
                    f"stream family {self.name!r}: gated stream {mask}={sid} "
                    f"below gray_base={self.gray_base}"
                )
            if (
                mask not in self.gray
                and mask not in self.wload
                and sid >= self.gray_base
            ):
                raise ValueError(
                    f"stream family {self.name!r}: protocol stream "
                    f"{mask}={sid} at or above gray_base={self.gray_base}"
                )

    def by_id(self) -> dict:
        """id -> mask name (validated: injective)."""
        return {sid: mask for mask, sid in self.streams.items()}

    def gray_ids(self) -> frozenset:
        return frozenset(self.streams[m] for m in self.gray)

    def wload_ids(self) -> frozenset:
        return frozenset(self.streams[m] for m in self.wload)


# The single-decree family: paxos, fastpaxos and raftcore all draw their
# masks through protocols.paxos.sample_masks / counter_masks (identical
# shapes), so they share one allocation.
SINGLE_DECREE = StreamFamily(
    name="single-decree",
    streams=dict(
        SEL=0,  # request-selection entropy
        BUSY=1,  # acceptor idling (p_idle)
        DELIVER=2,  # reply holding (p_hold)
        DUP_REQ=3,  # request duplication (p_dup, uniform)
        DUP_REP=4,  # reply duplication (p_dup, uniform)
        KEEP_PROM=5,  # PROMISE-class drop (p_drop, uniform)
        KEEP_ACCD=6,  # ACCEPTED-class drop
        KEEP_P1=7,  # PREPARE-class drop
        KEEP_P2=8,  # ACCEPT-class drop
        BACKOFF=9,  # proposer retry backoff
        LINK_BITS=10,  # per-link loss raw bits (p_flaky)
        DUP_BITS=11,  # per-link duplication raw bits (p_flaky + dup)
        CORRUPT=12,  # in-flight corruption mask (p_corrupt)
        DELAY_BITS=13,  # per-edge delay decision raw bits (p_delay)
        LAT_BITS=14,  # per-edge sampled latency raw bits (delay_max)
        ARRIVAL=15,  # client-arrival raw bits (workload plane)
    ),
    gray=frozenset(
        {"LINK_BITS", "DUP_BITS", "CORRUPT", "DELAY_BITS", "LAT_BITS"}
    ),
    gray_base=10,
    wload=frozenset({"ARRIVAL"}),
)

# The multipaxos family: BACKOFF landed on 10 before the gray layer
# existed, so gray streams start at 11 (frozen by the PR 1/PR 3 golden
# digests — see the module docstring).
MULTI_PAXOS = StreamFamily(
    name="multipaxos",
    streams=dict(
        SEL=0,
        BUSY=1,
        DUP_REQ=2,
        PROM_DELIVER=3,  # promise holding (p_hold)
        ACCD_DELIVER=4,  # accepted holding (p_hold)
        KEEP_PROM=5,
        KEEP_ACCD=6,
        KEEP_PREP=7,
        KEEP_ACC=8,
        JITTER=9,  # election-threshold jitter
        BACKOFF=10,  # post-failure retreat
        LINK_BITS=11,
        DUP_BITS=12,
        CORRUPT=13,
        DELAY_BITS=14,  # per-edge delay decision raw bits (p_delay)
        LAT_BITS=15,  # per-edge sampled latency raw bits (delay_max)
        ARRIVAL=16,  # client-arrival raw bits (workload plane)
    ),
    gray=frozenset(
        {"LINK_BITS", "DUP_BITS", "CORRUPT", "DELAY_BITS", "LAT_BITS"}
    ),
    gray_base=11,
    wload=frozenset({"ARRIVAL"}),
)

FAMILIES = {f.name: f for f in (SINGLE_DECREE, MULTI_PAXOS)}

_FAMILY_OF_PROTOCOL = {
    "paxos": SINGLE_DECREE,
    "fastpaxos": SINGLE_DECREE,
    "raftcore": SINGLE_DECREE,
    "synchpaxos": SINGLE_DECREE,
    "multipaxos": MULTI_PAXOS,
}


def family_of(protocol: str) -> StreamFamily:
    """The counter-stream family a protocol's mask sampler draws from."""
    try:
        return _FAMILY_OF_PROTOCOL[protocol]
    except KeyError:
        raise ValueError(f"unknown protocol: {protocol!r}") from None


# --- fold_in domains (XLA engine, jax.random keys) ---

# Root domain: fold_in(PRNGKey(seed), c) — the top-level lineages.
ROOT_STEP = 0  # per-tick mask stream (harness.run.base_key)
ROOT_PLAN = 1  # fault-plan sampling (harness.run.init_plan)
ROOT_WLOAD = 2  # workload-plan sampling (workload.generator.sample_plan)

# Gray fold_in constants sit at or above this in the tick and plan domains,
# keeping them visibly disjoint from the split-derived pre-gray draws.
GRAY_FOLD_BASE = 100

# Tick domain: fold_in(tick_key, c) inside sample_masks — gray draws only
# (the pre-gray draws come from the fixed-width split, never fold_in).
TICK_FOLDS = dict(
    LINK_BITS=100,  # per-link loss raw bits (p_flaky)
    DUP_BITS=101,  # per-link duplication raw bits
    CORRUPT=102,  # in-flight corruption mask (p_corrupt)
    DELAY_BITS=103,  # per-edge delay decision raw bits (p_delay)
    LAT_BITS=104,  # per-edge sampled latency raw bits (delay_max)
    ARRIVAL_BITS=105,  # client-arrival raw bits (workload plane)
)

# Plan domain: fold_in(plan_key, c) inside FaultPlan.sample — gray fields
# only (pre-gray plan draws come from the 5-way split).
PLAN_FOLDS = dict(
    PART_DIR=101,  # one-way cut? (p_asym)
    CUT_REQ=102,  # which direction a one-way cut blocks
    FLAKY=103,  # which links are flaky (p_flaky)
    FLAKY_DROP=104,  # per-flaky-link drop rate
    FLAKY_DUP=105,  # per-flaky-link dup rate
    PTIMEOUT=106,  # per-proposer timeout skew (timeout_skew)
    PBOFF=107,  # per-proposer backoff multiplier (backoff_skew)
    LINK_DELAY=108,  # per-link latency cap (p_delay + delay_max)
)


def _validate_folds(domain_name: str, folds: Mapping[str, int]) -> None:
    vals = list(folds.values())
    if len(vals) != len(set(vals)):
        dup = sorted(v for v in set(vals) if vals.count(v) > 1)
        raise ValueError(f"{domain_name} fold domain: duplicate consts {dup}")
    low = [f"{k}={v}" for k, v in folds.items() if v < GRAY_FOLD_BASE]
    if low:
        raise ValueError(
            f"{domain_name} fold domain: gray consts below "
            f"GRAY_FOLD_BASE={GRAY_FOLD_BASE}: {low}"
        )


SINGLE_DECREE.validate()
MULTI_PAXOS.validate()
_validate_folds("tick", TICK_FOLDS)
_validate_folds("plan", PLAN_FOLDS)


def tick_key(base_key: jax.Array, tick) -> jax.Array:
    """The per-tick mask key: depends only on (seed, tick), so
    checkpoint/resume and pipelined dispatch replay bit-exactly."""
    return jax.random.fold_in(base_key, tick)


def root_step_key(seed: int) -> jax.Array:
    """The step-key lineage root (fold const :data:`ROOT_STEP`)."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), ROOT_STEP)


def root_plan_key(seed: int) -> jax.Array:
    """The plan-sampling lineage root (fold const :data:`ROOT_PLAN`)."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), ROOT_PLAN)


def root_wload_key(seed: int) -> jax.Array:
    """The workload-plan lineage root (fold const :data:`ROOT_WLOAD`).

    Folded only when the workload plane is on — a default config must
    never touch this lineage (the step/plan lineages stay bit-identical
    either way because fold_in lineages are independent).
    """
    return jax.random.fold_in(jax.random.PRNGKey(seed), ROOT_WLOAD)


def tick_fold(key: jax.Array, name: str) -> jax.Array:
    """A registered gray fold of the tick key (``sample_masks``)."""
    return jax.random.fold_in(key, TICK_FOLDS[name])


def plan_fold(key: jax.Array, name: str) -> jax.Array:
    """A registered gray fold of the plan key (``FaultPlan.sample``)."""
    return jax.random.fold_in(key, PLAN_FOLDS[name])
