"""On-device flight recorder + consensus telemetry (default OFF, off is free).

Model-checking practice treats the counterexample *trace* as the product,
not just the verdict, and hardware-consensus designs keep event accounting
on the fast path so telemetry costs nothing when idle (PAPERS.md: Spin
Paxos traces, NetPaxos).  This module is that pattern for the fuzzing
engines:

- :class:`TelemetryState` — per-lane device arrays: an event-kind counter
  matrix, a packed-int32 event ring buffer (the flight recorder), and a
  ticks-to-decide latency histogram.  Every leaf is int32 with trailing
  ``instances`` axis, so the fused Pallas engine's generic pytree
  flattening (``kernels/fused_tick``) carries it with ZERO kernel changes,
  and ``pjit`` shards it with the rest of the state.
- :func:`record` — the in-tick update.  Pure elementwise/iota-masked
  ``where`` ops (no scatter, no unsigned math: Mosaic-clean) and **no PRNG
  draws**: everything is computed from signals the tick already produced,
  so enabling telemetry cannot perturb a schedule.
- Host-side decoding (:func:`decode_lane`, :func:`counter_totals`,
  :func:`hist_totals`) — turns device arrays into human-readable
  timelines; ``harness/shrink.py`` attaches these to violation repros.

Default-off is free: ``SimConfig.telemetry`` defaults to the disabled
:class:`TelemetryConfig`, the ``telemetry`` leaf of every protocol state is
then ``None`` (pruned from the pytree), and schedule streams are
bit-identical to a build without this module (tests/test_telemetry.py
reuses the tests/test_gray.py golden digests).

By design this module draws NO randomness — it owns no stream id and no
fold constant in ``core.streams``, and the static auditor
(``paxos_tpu/analysis``) holds it to that: a telemetry-on trace must have
the exact same PRNG-equation multiset as a default trace
(``prng_audit.audit_telemetry_parity``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

# Event kinds: bit i of a ring word's high half, and row i of the counter
# matrix.  Shared across all four protocols (raft maps votes/acks onto
# promise/accept; elections onto leader).
EVENTS = (
    "promise",  # phase-1 promise recorded (raft: vote granted)
    "accept",  # phase-2 accept recorded (raft: append acked)
    "decide",  # lane (multi-paxos: slot) newly chose a value
    "conflict",  # safety checker recorded a violation
    "leader",  # leader/ballot change (phase-1 won, election, demotion)
    "timeout",  # proposer phase timer expired (retry with higher ballot)
    "drop",  # message dropped by the fault layer
    "dup",  # duplicate delivery (message processed again)
    "corrupt",  # in-flight payload corruption applied
    "part_cut",  # partition window opened on this lane
    "part_heal",  # partition window closed on this lane
    "recover",  # crashed node recovered
)
N_EVENTS = len(EVENTS)

# Ring word layout: (event bitmask << EVENT_SHIFT) | (tick & TICK_MASK).
# 16 tick bits wrap at 65536 ticks — campaigns run in chunks far shorter
# than that, and the decoder only needs ordering within the ring window.
EVENT_SHIFT = 16
TICK_MASK = (1 << EVENT_SHIFT) - 1

# Latency histogram: bucket = min(decide_tick // HIST_TICKS_PER_BIN, B-1);
# the last bucket is the overflow bucket.
HIST_TICKS_PER_BIN = 8


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Static telemetry knobs (frozen: rides ``SimConfig`` into jit).

    All default OFF.  Any knob on allocates the counter matrix; the ring
    and histogram are gated individually.
    """

    counters: bool = False  # per-lane event-kind counters
    ring_depth: int = 0  # flight-recorder entries per lane (0 = off)
    hist_bins: int = 0  # ticks-to-decide histogram bins (0 = off)

    def enabled(self) -> bool:
        return self.counters or self.ring_depth > 0 or self.hist_bins > 0


@struct.dataclass
class TelemetryState:
    """Per-lane telemetry arrays (all int32, instance-minor).

    Rides as an ``Optional`` leaf of every protocol state: ``None`` when
    disabled (pruned from the pytree — the default-off-is-free contract),
    never containing scalar leaves (the fused engine's ``_split_tick``
    expects exactly one scalar in the whole state: the tick).
    """

    counters: jnp.ndarray  # (E, I) int32 — per event kind, per lane
    ring: Optional[jnp.ndarray] = None  # (D, I) int32 packed event words
    cursor: Optional[jnp.ndarray] = None  # (I,) int32 next slot in [0, D)
    seq: Optional[jnp.ndarray] = None  # (I,) int32 words ever written
    hist: Optional[jnp.ndarray] = None  # (B, I) int32 decide-latency bins

    @classmethod
    def init(cls, n_inst: int, tcfg: TelemetryConfig) -> "TelemetryState":
        def zi():
            return jnp.zeros((n_inst,), jnp.int32)

        ring_on = tcfg.ring_depth > 0
        return cls(
            counters=jnp.zeros((N_EVENTS, n_inst), jnp.int32),
            ring=(
                jnp.zeros((tcfg.ring_depth, n_inst), jnp.int32)
                if ring_on
                else None
            ),
            cursor=zi() if ring_on else None,
            seq=zi() if ring_on else None,
            hist=(
                jnp.zeros((tcfg.hist_bins, n_inst), jnp.int32)
                if tcfg.hist_bins > 0
                else None
            ),
        )


def lane_count(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce any leading axes of a bool/int event signal to (I,) int32."""
    x = x.astype(jnp.int32)
    if x.ndim > 1:
        x = jnp.sum(x, axis=tuple(range(x.ndim - 1)))
    return x


def record(
    tel: TelemetryState,
    tick: jnp.ndarray,
    *,
    promise=None,
    accept=None,
    decide=None,
    conflict=None,
    leader=None,
    timeout=None,
    drop=None,
    dup=None,
    corrupt=None,
    part_cut=None,
    part_heal=None,
    recover=None,
) -> TelemetryState:
    """One tick's telemetry update (pure, PRNG-free, Mosaic-clean).

    Each keyword is ``None`` (event not applicable / its fault knob off —
    skipped at trace time) or a bool/int32 array whose trailing axis is
    ``instances``; leading axes are summed into a per-lane count.

    Counters: per-kind elementwise adds (iota row select — no scatter).
    Ring: at most one packed word per (lane, tick) — the OR of the tick's
    event bits — appended with an iota-vs-cursor masked ``where``.
    Histogram: ``decide`` counts land in bucket ``tick // HIST_TICKS_PER_BIN``
    (clamped to the overflow bucket).
    """
    counts = (promise, accept, decide, conflict, leader, timeout, drop, dup,
              corrupt, part_cut, part_heal, recover)
    n_inst = tel.counters.shape[-1]

    row = jax.lax.broadcasted_iota(jnp.int32, tel.counters.shape, 0)
    inc = jnp.zeros_like(tel.counters)
    word_bits = jnp.zeros((n_inst,), jnp.int32)
    for e, c in enumerate(counts):
        if c is None:
            continue
        c = lane_count(c)
        inc = inc + jnp.where(row == e, c[None], 0)
        word_bits = word_bits | jnp.where(c > 0, jnp.int32(1 << e), 0)
    tel = tel.replace(counters=tel.counters + inc)

    if tel.ring is not None:
        depth = tel.ring.shape[0]
        has = word_bits != 0
        word = (word_bits << EVENT_SHIFT) | (tick & TICK_MASK)
        rows_d = jax.lax.broadcasted_iota(jnp.int32, tel.ring.shape, 0)
        hit = (rows_d == tel.cursor[None]) & has[None]
        step = has.astype(jnp.int32)
        nxt = tel.cursor + step
        tel = tel.replace(
            ring=jnp.where(hit, word[None], tel.ring),
            cursor=jnp.where(nxt >= depth, 0, nxt),
            seq=tel.seq + step,
        )

    if tel.hist is not None and decide is not None:
        bins = tel.hist.shape[0]
        bucket = jnp.minimum(tick // HIST_TICKS_PER_BIN, bins - 1)
        rows_b = jax.lax.broadcasted_iota(jnp.int32, tel.hist.shape, 0)
        tel = tel.replace(
            hist=tel.hist + jnp.where(rows_b == bucket, lane_count(decide)[None], 0)
        )
    return tel


def fault_lane_events(plan, cfg, tick):
    """Per-lane fault-plan edge events, shared by all four protocols.

    Returns kwargs for :func:`record` (``part_cut`` / ``part_heal`` /
    ``recover``), each ``None`` when its fault knob is off (no work traced).
    """
    out = {"part_cut": None, "part_heal": None, "recover": None}
    if cfg.p_part > 0.0:
        out["part_cut"] = plan.part_start == tick
        out["part_heal"] = plan.part_end == tick
    rec = None
    if cfg.p_crash > 0.0:
        rec = lane_count(plan.crash_end == tick)
    if cfg.p_crash_prop > 0.0:
        prec = lane_count(plan.pcrash_end == tick)
        rec = prec if rec is None else rec + prec
    out["recover"] = rec
    return out


# ---------------------------------------------------------------------------
# Host-side decoding (numpy-friendly: call on device_get'd arrays).


def decode_word(word: int) -> dict:
    """One packed ring word -> {"tick": int, "events": [names]}."""
    word = int(word)
    bits = (word >> EVENT_SHIFT) & ((1 << N_EVENTS) - 1)
    return {
        "tick": word & TICK_MASK,
        "events": [EVENTS[i] for i in range(N_EVENTS) if (bits >> i) & 1],
    }


def decode_lane(tel: TelemetryState, lane: int) -> list:
    """The lane's recorded event window, oldest first (empty if no ring)."""
    if tel.ring is None:
        return []
    ring = jax.device_get(tel.ring[:, lane])
    cursor = int(jax.device_get(tel.cursor[lane]))
    seq = int(jax.device_get(tel.seq[lane]))
    depth = ring.shape[0]
    if seq <= depth:
        words = ring[:seq]
    else:  # wrapped: oldest entry sits at the write cursor
        words = list(ring[cursor:]) + list(ring[:cursor])
    return [decode_word(w) for w in words]


def counter_totals(tel: TelemetryState) -> dict:
    """Whole-campaign event counts, summed over lanes: {name: int}."""
    totals = jax.device_get(tel.counters.sum(axis=-1))
    return {name: int(v) for name, v in zip(EVENTS, totals)}


def hist_saturation(counts: list) -> dict:
    """Overflow accounting for a decoded decide-latency histogram.

    The device update clamps ``decide_tick // HIST_TICKS_PER_BIN`` into the
    last bin, so that bin is a catch-all: any count there means latencies
    at or past ``(bins - 1) * HIST_TICKS_PER_BIN`` ticks were folded
    together and the in-range bins under-describe the tail.  Returns
    ``{"overflow": <last-bin count>, "saturated": <bool>}`` (zeros/False
    for an empty or single-bin histogram, where no in-range bins exist to
    be misread).
    """
    if len(counts) < 2:
        return {"overflow": 0, "saturated": False}
    overflow = int(counts[-1])
    return {"overflow": overflow, "saturated": overflow > 0}


def hist_totals(tel: TelemetryState, with_saturation: bool = False):
    """Decide-latency histogram summed over lanes (len = hist_bins).

    With ``with_saturation`` returns ``(counts, hist_saturation(counts))``
    so callers surfacing the histogram can flag a clipped tail instead of
    silently reporting the overflow bucket as a real latency bin.
    """
    counts = (
        []
        if tel.hist is None
        else [int(v) for v in jax.device_get(tel.hist.sum(axis=-1))]
    )
    if with_saturation:
        return counts, hist_saturation(counts)
    return counts


def telemetry_device(tel: TelemetryState) -> dict:
    """Device half of :func:`telemetry_report`: reductions only, no transfer.

    Returns a dict of small device arrays suitable for embedding in a
    composite report pytree (``harness.run.summarize_device``) so one
    ``jax.device_get`` — or one async transfer — covers the whole report.
    """
    dev = {"counters": tel.counters.sum(axis=-1)}
    if tel.hist is not None:
        dev["hist"] = tel.hist.sum(axis=-1)
    if tel.seq is not None:
        dev["seq"] = tel.seq.sum()
    return dev


def telemetry_host(host: dict) -> dict:
    """Format a ``device_get``'d :func:`telemetry_device` pytree."""
    report = {
        "counters": {name: int(v) for name, v in zip(EVENTS, host["counters"])}
    }
    if "hist" in host:
        report["hist"] = [int(v) for v in host["hist"]]
        report["hist_ticks_per_bin"] = HIST_TICKS_PER_BIN
        sat = hist_saturation(report["hist"])
        report["hist_overflow"] = sat["overflow"]
        report["hist_saturated"] = sat["saturated"]
    if "seq" in host:
        report["events_recorded"] = int(host["seq"])
    return report


def telemetry_report(tel: TelemetryState) -> dict:
    """Host-readable per-chunk telemetry summary (for MetricsLog / stats)."""
    return telemetry_host(jax.device_get(telemetry_device(tel)))
