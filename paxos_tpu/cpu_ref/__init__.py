"""CPU golden models for differential testing."""
