"""Bounded exhaustive model checking of single-decree Paxos.

The fuzzer explores interleavings statistically at millions/sec; this module
explores them EXHAUSTIVELY for small bounded instances (the Spin/TLA recipe
— cf. "Model Checking Paxos in Spin", arXiv:1408.5962 in PAPERS.md): every
reachable state of an asynchronous schedule space is enumerated and the
agreement/validity invariants are asserted in each one.

Model: the same protocol the batched kernels implement (and the same the
Python golden model runs), as a pure transition system over immutable
tuples:

- **State** = (acceptors, proposers, network multiset, voters table).
- **Actions** = deliver any in-flight message (consuming it), or time out a
  live proposer onto its next ballot (bounded by ``max_round``).  Message
  LOSS needs no separate action for safety: a lost message is one that is
  never selected before the run ends, and every such prefix is explored.
  Duplication is covered by the fuzzer (idempotence known-answer tests);
  modeling it here would only blow up the bounded space.

Because every action either consumes a message or spends a bounded timeout,
the schedule space is a finite DAG; memoized DFS visits each reachable
state once.  A violation raises with the full action trace — a
counterexample schedule, Spin-style.

This is the third leg of the verification tripod (SURVEY.md §5.2):
randomized at scale (the TPU fuzzer), differential (golden model + native
C++ oracle), exhaustive at small bounds (this).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Message kinds
PREPARE, PROMISE, ACCEPT, ACCEPTED = 0, 1, 2, 3
# Proposer phases
P1, P2, DONE = 0, 1, 2


def make_ballot(rnd: int, pid: int, max_props: int = 8) -> int:
    return rnd * max_props + pid + 1


# A message: (kind, src, dst, bal, v1, v2).  src/dst are role-local indices
# (proposer index for requests' src, acceptor index for replies' src).
Msg = tuple[int, int, int, int, int, int]
# An acceptor: (promised, acc_bal, acc_val).
Acc = tuple[int, int, int]
# A proposer: (phase, rnd, heard_bitmask, best_bal, best_val, prop_val,
#              decided_val).
Prop = tuple[int, int, int, int, int, int, int]
# Full state: (accs, props, net, voters) with net a sorted tuple (multiset)
# and voters a sorted tuple of ((bal, val), acceptor_bitmask).
State = tuple[tuple[Acc, ...], tuple[Prop, ...], tuple[Msg, ...], tuple]


@dataclasses.dataclass
class CheckResult:
    states: int  # distinct states visited
    decided_states: int  # states where some proposer reached DONE
    chosen_values: set  # every value ever chosen anywhere in the space
    counterexample: Optional[list]  # action trace to a violation (None = ok)
    # Liveness leg (None when not requested): the max fair-completion length
    # over ALL reachable states — from every one of them, the deterministic
    # fair schedule decided within this many actions.
    max_completion: Optional[int] = None


class LivenessViolation(AssertionError):
    """A reachable state from which the fair completion schedule never
    decides — a mechanized livelock/deadlock counterexample (a lasso when
    the completion revisits a state, a bound overrun when ballots grow
    forever).  Carries the reach trace and the completion trace."""


def make_liveness_checker(fair_next, is_decided, bound: int):
    """The mechanized liveness leg shared by all four checkers (VERDICT r3 #2).

    Safety asks "is any reachable state WRONG"; this asks "is any reachable
    state a TRAP".  The property is bounded fair liveness: from EVERY
    reachable state, the deterministic *fair completion schedule* — deliver
    the least in-flight message until the network drains, then let the
    designated (highest-ballot live) proposer time out, repeat — reaches a
    decision within ``bound`` actions.  That schedule is exactly the
    partial-synchrony assumption under which Paxos-family liveness holds
    (fair delivery, eventually one distinguished retrier); FLP says no
    asynchronous consensus can be live under ALL schedules, so a fair
    completion is the strongest property that can hold.

    ``fair_next(state) -> (action, next_state)`` must be DETERMINISTIC:
    completion paths then form a functional graph, so memoizing
    steps-to-decision makes the whole leg near-linear in reachable states
    (shared suffixes are walked once).  Two failure shapes raise
    :class:`LivenessViolation` with full traces:

    - **lasso**: the completion path revisits a state — a true livelock
      cycle (e.g. retry-without-ballot-increase re-collects denials
      forever);
    - **bound overrun**: no repeat but no decision within ``bound`` (e.g.
      a livelock whose ballots grow forever, so no state ever repeats).

    Returns ``(check, stats)``; call ``check(state, reach_trace)`` on every
    reachable state; ``stats["max_completion"]`` is the reported maximum.
    """
    memo: dict = {}
    stats = {"max_completion": 0, "states_checked": 0}

    def check(state, trace) -> None:
        stats["states_checked"] += 1
        path_states: list = []
        path_actions: list = []
        pos: dict = {}
        s = state
        while True:
            if s in memo:
                tail = memo[s]
                break
            if is_decided(s):
                tail = 0
                break
            if s in pos:
                k = pos[s]
                raise LivenessViolation(
                    f"liveness violated (LASSO): fair completion revisits a "
                    f"state after {len(path_actions)} steps; reach trace="
                    f"{list(trace)}; completion prefix="
                    f"{path_actions[:k]}; cycle={path_actions[k:]}"
                )
            pos[s] = len(path_states)
            path_states.append(s)
            action, s = fair_next(s)
            path_actions.append(action)
            if len(path_actions) > bound:
                raise LivenessViolation(
                    f"liveness violated (BOUND): no decision within {bound} "
                    f"fair actions and no state repeat (ballots growing?); "
                    f"reach trace={list(trace)}; completion head="
                    f"{path_actions[:40]}"
                )
        total = tail + len(path_states)
        if total > bound:
            raise LivenessViolation(
                f"liveness violated (BOUND): fair completion needs {total} "
                f"actions > bound {bound}; reach trace={list(trace)}; "
                f"completion head={path_actions[:40]}"
            )
        for i, st in enumerate(path_states):
            memo[st] = total - i
        if total > stats["max_completion"]:
            stats["max_completion"] = total

    return check, stats


def make_fair_completion(deliver_first, timeout_designated, done_phase: int):
    """The ONE fair-completion schedule policy, shared by all four protocol
    checkers (so a policy change cannot silently diverge per protocol):

    - network nonempty -> deliver the least in-flight message
      (``deliver_first(state) -> (action, next_state)``);
    - network drained, nobody decided -> the DESIGNATED proposer retries:
      the live one holding the highest current ballot (the
      partial-synchrony "distinguished leader"), via
      ``timeout_designated(state, p) -> next_state``.

    Relies on the layout contract every checker already satisfies:
    ``state[1]`` is the proposer/candidate tuple with ``pr[0]`` = phase and
    ``pr[1]`` = round, ``state[2]`` is the network; ``done_phase`` is the
    protocol's terminal phase constant.  Returns ``(fair_next,
    is_decided)`` for :func:`make_liveness_checker`.
    """

    def fair_next(state):
        if state[2]:
            return deliver_first(state)
        props = state[1]
        p = max(
            (q for q in range(len(props)) if props[q][0] != done_phase),
            key=lambda q: make_ballot(props[q][1], q),
        )
        return ("t", p), timeout_designated(state, p)

    def is_decided(state) -> bool:
        return any(pr[0] == done_phase for pr in state[1])

    return fair_next, is_decided


def explore(init, successors, check_state, max_states: int) -> int:
    """Memoized DFS over a finite action DAG — the shared search driver.

    ``successors(state)`` yields ``(action, next_state)`` pairs;
    ``check_state(state, trace)`` asserts the invariants (raising
    ``AssertionError`` with the Spin-style action trace) and accumulates
    stats via closure.  Traces are tuples shared by prefix, so storing one
    per stack entry is O(depth), not O(depth^2).  Returns the number of
    distinct states visited; raises ``RuntimeError`` past ``max_states``.
    """
    stack = [(init, ())]
    visited = set()
    while stack:
        state, trace = stack.pop()
        if state in visited:
            continue
        visited.add(state)
        if len(visited) > max_states:
            raise RuntimeError(
                f"state space exceeds max_states={max_states}; tighten bounds"
            )
        check_state(state, trace)
        for action, nxt in successors(state):
            stack.append((nxt, trace + (action,)))
    return len(visited)


def _init_state(n_prop: int, n_acc: int) -> State:
    accs = tuple((0, 0, 0) for _ in range(n_acc))
    props = tuple(
        (P1, 0, 0, 0, 0, 0, 0) for _ in range(n_prop)
    )
    net = tuple(
        sorted(
            (PREPARE, p, a, make_ballot(0, p), 0, 0)
            for p in range(n_prop)
            for a in range(n_acc)
        )
    )
    return (accs, props, net, ())


def _merge(net: tuple, out: list, slot_net: bool) -> tuple:
    """Add emitted messages to the in-flight set.

    ``slot_net=False``: the classic multiset union (a message in flight
    forever unless delivered — loss is "never scheduled").  ``slot_net=True``
    models the TPU transport's fixed-slot buffers instead: one in-flight
    message per (kind, src, dst) edge, a new send OVERWRITING the old (the
    ``core.messages`` bounded-channel semantics).  The slot-quotiented
    reachable set is exactly what the batched fuzzer can in principle
    reach, which is what makes fuzz coverage measurable against it
    (``check/coverage.py``).
    """
    if not slot_net:
        return tuple(sorted(net + tuple(out)))
    d = {(m[0], m[1], m[2]): m for m in net}
    for m in out:
        d[(m[0], m[1], m[2])] = m
    return tuple(sorted(d.values()))


def _own_val(pid: int) -> int:
    return 100 + pid


def _chosen(voters: tuple, quorum: int) -> set:
    return {bv[1] for bv, mask in voters if bin(mask).count("1") >= quorum}


def _record_vote(voters: tuple, a: int, bal: int, val: int) -> tuple:
    d = dict(voters)
    d[(bal, val)] = d.get((bal, val), 0) | (1 << a)
    return tuple(sorted(d.items()))


def _deliver(
    state: State, i: int, quorum: int, n_acc: int, unsafe_accept: bool = False,
    slot_net: bool = False,
) -> State:
    """Deliver (and consume) in-flight message ``i``; pure.

    ``unsafe_accept=True`` injects the classic bug (accept below the
    promise) — the checker must then find a counterexample schedule.
    ``slot_net`` selects the fixed-slot transport merge (:func:`_merge`).
    """
    accs, props, net, voters = state
    kind, src, dst, bal, v1, v2 = net[i]
    net = net[:i] + net[i + 1 :]
    out: list[Msg] = []

    if kind == PREPARE:
        promised, abal, aval = accs[dst]
        if bal > promised:
            accs = accs[:dst] + ((bal, abal, aval),) + accs[dst + 1 :]
            out.append((PROMISE, dst, src, bal, abal, aval))
    elif kind == ACCEPT:
        promised, abal, aval = accs[dst]
        if unsafe_accept or bal >= promised:
            accs = accs[:dst] + ((bal, bal, v1),) + accs[dst + 1 :]
            voters = _record_vote(voters, dst, bal, v1)
            out.append((ACCEPTED, dst, src, bal, v1, 0))
    elif kind == PROMISE:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase == P1 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if v1 > bb:
                bb, bv = v1, v2
            if bin(heard).count("1") >= quorum:
                pv = bv if bb > 0 else _own_val(dst)
                phase, heard = P2, 0
                out.extend(
                    (ACCEPT, dst, a, bal, pv, 0) for a in range(n_acc)
                )
            props = props[:dst] + ((phase, rnd, heard, bb, bv, pv, dec),) + props[dst + 1 :]
    elif kind == ACCEPTED:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase == P2 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if bin(heard).count("1") >= quorum:
                phase, dec = DONE, pv
            props = props[:dst] + ((phase, rnd, heard, bb, bv, pv, dec),) + props[dst + 1 :]

    return (accs, props, _merge(net, out, slot_net), voters)


def _timeout(
    state: State, p: int, n_acc: int, bump: bool = True, slot_net: bool = False
) -> State:
    """Proposer ``p`` abandons its ballot and retries one round higher.

    ``bump=False`` is the injected LIVENESS bug (retry without ballot
    increase): the retry's PREPAREs sit at or below every promise the first
    attempt extracted, so they GC away and the proposer re-collects nothing
    — the mechanized-liveness leg must find the lasso."""
    accs, props, net, voters = state
    phase, rnd, heard, bb, bv, pv, dec = props[p]
    if bump:
        rnd += 1
    bal = make_ballot(rnd, p)
    props = props[:p] + ((P1, rnd, 0, 0, 0, 0, dec),) + props[p + 1 :]
    out = [(PREPARE, p, a, bal, 0, 0) for a in range(n_acc)]
    return (accs, props, _merge(net, out, slot_net), voters)


def _gc(state: State, unsafe_accept: bool = False, dedup: bool = False) -> State:
    """Drop in-flight messages whose delivery is provably a no-op.

    ``dedup=True`` (the ``livelock_bug`` legs) additionally collapses the
    in-flight multiset to a SET: with retries frozen at a fixed ballot the
    message universe is finite, but each retry re-emits identical PREPAREs,
    so the multiset — and with it the state space — would grow without
    bound.  Identical messages are indistinguishable to every transition
    (delivering either copy is the same successor), so the collapse only
    removes duplicate-count bookkeeping; every lasso it finds is a real
    schedule.

    Sound state-space reduction: delivering such a message changes nothing
    but the network multiset, so its removal commutes with every other
    action and preserves the reachable set of (acceptor, proposer, voters)
    configurations — while collapsing the dead-letter orderings that
    otherwise dominate the bounded space.

    - replies (PROMISE/ACCEPTED) to a proposer that is DONE, past phase 1
      (for PROMISE), or on a different ballot (ballots only increase);
    - PREPARE at or below the acceptor's promise, ACCEPT below it.
    """
    accs, props, net, voters = state
    keep = []
    for m in net:
        kind, src, dst, bal, v1, v2 = m
        if kind == PREPARE:
            # The prune relies on promised-ballot monotonicity, which the
            # injected accept-below-promise bug breaks (a stale ACCEPT can
            # LOWER the promise, reviving this PREPARE) — keep it then.
            if bal <= accs[dst][0] and not unsafe_accept:
                continue
        elif kind == ACCEPT:
            # Under the injected accept-below-promise bug a stale ACCEPT is
            # NOT a no-op — it is the bug — so it must stay deliverable.
            if bal < accs[dst][0] and not unsafe_accept:
                continue
        else:
            phase, rnd = props[dst][0], props[dst][1]
            if phase == DONE or bal != make_ballot(rnd, dst):
                continue
            if kind == PROMISE and phase != P1:
                continue
            # ACCEPTED while still in P1 cannot exist for the CURRENT
            # ballot (its phase 2 has not begun), so this only drops
            # replies that can never be consumed.
            if kind == ACCEPTED and phase != P2:
                continue
        keep.append(m)
    if dedup:
        keep = sorted(set(keep))
    return (accs, props, tuple(keep), voters)


def check_exhaustive(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 5_000_000,
    unsafe_accept: bool = False,
    liveness_bound: "int | None" = None,
    livelock_bug: bool = False,
    visit=None,
    slot_net: bool = False,
) -> CheckResult:
    """Exhaustively explore every schedule; assert agreement + validity.

    ``max_round`` bounds retries — an int applies to every proposer, a tuple
    gives per-proposer bounds (asymmetric bounds keep the space tractable:
    the killer interleavings need only ONE proposer to preempt the other).
    Raises ``AssertionError`` with the counterexample trace on a violation;
    ``RuntimeError`` if the bounded space exceeds ``max_states`` (tighten
    the bounds).

    ``liveness_bound`` arms the mechanized liveness leg
    (:func:`make_liveness_checker`): from every reachable state the fair
    completion schedule must decide within that many actions (completion
    timeouts are NOT bounded by ``max_round`` — the property is "finitely
    many extra fair retries always decide", and bounding them would
    manufacture fake traps at the exploration edge).  ``livelock_bug``
    injects retry-without-ballot-increase into BOTH the explored timeouts
    and the completion schedule; the leg must then produce a lasso
    counterexample (tests/test_exhaustive.py asserts both directions).

    ``visit`` (optional callable) receives every reachable state once —
    the coverage probe's hook (``check/coverage.py``).  ``slot_net=True``
    explores under the fixed-slot transport (:func:`_merge`): the quotient
    of the schedule space the batched fuzzer's overwriting message buffers
    can reach.
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    own_vals = {_own_val(p) for p in range(n_prop)}
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state: State, trace: tuple) -> None:
        accs, props, net, voters = state
        chosen = _chosen(voters, quorum)
        stats["chosen_all"] |= chosen
        decided = {pr[6] for pr in props if pr[0] == DONE}
        if decided:
            stats["decided_states"] += 1
        # ---- Invariants, checked in EVERY reachable state ----
        ok = (
            len(chosen) <= 1  # agreement
            and chosen <= own_vals  # validity
            and decided <= chosen  # a decided proposer's value was chosen
        )
        if not ok:
            raise AssertionError(
                f"invariant violated: chosen={chosen} decided={decided} "
                f"after trace={list(trace)}"
            )

    live_check, live_stats = (None, None)
    if liveness_bound is not None:
        fair_next, is_decided = make_fair_completion(
            lambda s: (("d", s[2][0]), _gc(
                _deliver(s, 0, quorum, n_acc, unsafe_accept, slot_net),
                unsafe_accept, dedup=livelock_bug,
            )),
            lambda s, p: _gc(
                _timeout(s, p, n_acc, bump=not livelock_bug,
                         slot_net=slot_net),
                unsafe_accept, dedup=livelock_bug,
            ),
            done_phase=DONE,
        )
        live_check, live_stats = make_liveness_checker(
            fair_next, is_decided, liveness_bound
        )

    def check_both(state: State, trace: tuple) -> None:
        check_state(state, trace)
        if visit is not None:
            visit(state)
        if live_check is not None:
            live_check(state, trace)

    def successors(state: State):
        # GC'd: dead-letter orderings collapse.
        accs, props, net, voters = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, quorum, n_acc, unsafe_accept, slot_net),
                unsafe_accept, dedup=livelock_bug,
            )
        for p in range(n_prop):
            if props[p][0] != DONE and props[p][1] < max_round[p]:
                yield ("t", p), _gc(
                    _timeout(state, p, n_acc, bump=not livelock_bug,
                             slot_net=slot_net),
                    unsafe_accept, dedup=livelock_bug,
                )

    states = explore(_init_state(n_prop, n_acc), successors, check_both, max_states)
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
        max_completion=None if live_stats is None else live_stats["max_completion"],
    )
