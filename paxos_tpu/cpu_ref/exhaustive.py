"""Bounded exhaustive model checking of single-decree Paxos.

The fuzzer explores interleavings statistically at millions/sec; this module
explores them EXHAUSTIVELY for small bounded instances (the Spin/TLA recipe
— cf. "Model Checking Paxos in Spin", arXiv:1408.5962 in PAPERS.md): every
reachable state of an asynchronous schedule space is enumerated and the
agreement/validity invariants are asserted in each one.

Model: the same protocol the batched kernels implement (and the same the
Python golden model runs), as a pure transition system over immutable
tuples:

- **State** = (acceptors, proposers, network multiset, voters table).
- **Actions** = deliver any in-flight message (consuming it), or time out a
  live proposer onto its next ballot (bounded by ``max_round``).  Message
  LOSS needs no separate action for safety: a lost message is one that is
  never selected before the run ends, and every such prefix is explored.
  Duplication is covered by the fuzzer (idempotence known-answer tests);
  modeling it here would only blow up the bounded space.

Because every action either consumes a message or spends a bounded timeout,
the schedule space is a finite DAG; memoized DFS visits each reachable
state once.  A violation raises with the full action trace — a
counterexample schedule, Spin-style.

This is the third leg of the verification tripod (SURVEY.md §5.2):
randomized at scale (the TPU fuzzer), differential (golden model + native
C++ oracle), exhaustive at small bounds (this).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Message kinds
PREPARE, PROMISE, ACCEPT, ACCEPTED = 0, 1, 2, 3
# Proposer phases
P1, P2, DONE = 0, 1, 2


def make_ballot(rnd: int, pid: int, max_props: int = 8) -> int:
    return rnd * max_props + pid + 1


# A message: (kind, src, dst, bal, v1, v2).  src/dst are role-local indices
# (proposer index for requests' src, acceptor index for replies' src).
Msg = tuple[int, int, int, int, int, int]
# An acceptor: (promised, acc_bal, acc_val).
Acc = tuple[int, int, int]
# A proposer: (phase, rnd, heard_bitmask, best_bal, best_val, prop_val,
#              decided_val).
Prop = tuple[int, int, int, int, int, int, int]
# Full state: (accs, props, net, voters) with net a sorted tuple (multiset)
# and voters a sorted tuple of ((bal, val), acceptor_bitmask).
State = tuple[tuple[Acc, ...], tuple[Prop, ...], tuple[Msg, ...], tuple]


@dataclasses.dataclass
class CheckResult:
    states: int  # distinct states visited
    decided_states: int  # states where some proposer reached DONE
    chosen_values: set  # every value ever chosen anywhere in the space
    counterexample: Optional[list]  # action trace to a violation (None = ok)


def explore(init, successors, check_state, max_states: int) -> int:
    """Memoized DFS over a finite action DAG — the shared search driver.

    ``successors(state)`` yields ``(action, next_state)`` pairs;
    ``check_state(state, trace)`` asserts the invariants (raising
    ``AssertionError`` with the Spin-style action trace) and accumulates
    stats via closure.  Traces are tuples shared by prefix, so storing one
    per stack entry is O(depth), not O(depth^2).  Returns the number of
    distinct states visited; raises ``RuntimeError`` past ``max_states``.
    """
    stack = [(init, ())]
    visited = set()
    while stack:
        state, trace = stack.pop()
        if state in visited:
            continue
        visited.add(state)
        if len(visited) > max_states:
            raise RuntimeError(
                f"state space exceeds max_states={max_states}; tighten bounds"
            )
        check_state(state, trace)
        for action, nxt in successors(state):
            stack.append((nxt, trace + (action,)))
    return len(visited)


def _init_state(n_prop: int, n_acc: int) -> State:
    accs = tuple((0, 0, 0) for _ in range(n_acc))
    props = tuple(
        (P1, 0, 0, 0, 0, 0, 0) for _ in range(n_prop)
    )
    net = tuple(
        sorted(
            (PREPARE, p, a, make_ballot(0, p), 0, 0)
            for p in range(n_prop)
            for a in range(n_acc)
        )
    )
    return (accs, props, net, ())


def _own_val(pid: int) -> int:
    return 100 + pid


def _chosen(voters: tuple, quorum: int) -> set:
    return {bv[1] for bv, mask in voters if bin(mask).count("1") >= quorum}


def _record_vote(voters: tuple, a: int, bal: int, val: int) -> tuple:
    d = dict(voters)
    d[(bal, val)] = d.get((bal, val), 0) | (1 << a)
    return tuple(sorted(d.items()))


def _deliver(
    state: State, i: int, quorum: int, n_acc: int, unsafe_accept: bool = False
) -> State:
    """Deliver (and consume) in-flight message ``i``; pure.

    ``unsafe_accept=True`` injects the classic bug (accept below the
    promise) — the checker must then find a counterexample schedule.
    """
    accs, props, net, voters = state
    kind, src, dst, bal, v1, v2 = net[i]
    net = net[:i] + net[i + 1 :]
    out: list[Msg] = []

    if kind == PREPARE:
        promised, abal, aval = accs[dst]
        if bal > promised:
            accs = accs[:dst] + ((bal, abal, aval),) + accs[dst + 1 :]
            out.append((PROMISE, dst, src, bal, abal, aval))
    elif kind == ACCEPT:
        promised, abal, aval = accs[dst]
        if unsafe_accept or bal >= promised:
            accs = accs[:dst] + ((bal, bal, v1),) + accs[dst + 1 :]
            voters = _record_vote(voters, dst, bal, v1)
            out.append((ACCEPTED, dst, src, bal, v1, 0))
    elif kind == PROMISE:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase == P1 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if v1 > bb:
                bb, bv = v1, v2
            if bin(heard).count("1") >= quorum:
                pv = bv if bb > 0 else _own_val(dst)
                phase, heard = P2, 0
                out.extend(
                    (ACCEPT, dst, a, bal, pv, 0) for a in range(n_acc)
                )
            props = props[:dst] + ((phase, rnd, heard, bb, bv, pv, dec),) + props[dst + 1 :]
    elif kind == ACCEPTED:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase == P2 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if bin(heard).count("1") >= quorum:
                phase, dec = DONE, pv
            props = props[:dst] + ((phase, rnd, heard, bb, bv, pv, dec),) + props[dst + 1 :]

    return (accs, props, tuple(sorted(net + tuple(out))), voters)


def _timeout(state: State, p: int, n_acc: int) -> State:
    """Proposer ``p`` abandons its ballot and retries one round higher."""
    accs, props, net, voters = state
    phase, rnd, heard, bb, bv, pv, dec = props[p]
    rnd += 1
    bal = make_ballot(rnd, p)
    props = props[:p] + ((P1, rnd, 0, 0, 0, 0, dec),) + props[p + 1 :]
    out = tuple((PREPARE, p, a, bal, 0, 0) for a in range(n_acc))
    return (accs, props, tuple(sorted(net + out)), voters)


def _gc(state: State, unsafe_accept: bool = False) -> State:
    """Drop in-flight messages whose delivery is provably a no-op.

    Sound state-space reduction: delivering such a message changes nothing
    but the network multiset, so its removal commutes with every other
    action and preserves the reachable set of (acceptor, proposer, voters)
    configurations — while collapsing the dead-letter orderings that
    otherwise dominate the bounded space.

    - replies (PROMISE/ACCEPTED) to a proposer that is DONE, past phase 1
      (for PROMISE), or on a different ballot (ballots only increase);
    - PREPARE at or below the acceptor's promise, ACCEPT below it.
    """
    accs, props, net, voters = state
    keep = []
    for m in net:
        kind, src, dst, bal, v1, v2 = m
        if kind == PREPARE:
            # The prune relies on promised-ballot monotonicity, which the
            # injected accept-below-promise bug breaks (a stale ACCEPT can
            # LOWER the promise, reviving this PREPARE) — keep it then.
            if bal <= accs[dst][0] and not unsafe_accept:
                continue
        elif kind == ACCEPT:
            # Under the injected accept-below-promise bug a stale ACCEPT is
            # NOT a no-op — it is the bug — so it must stay deliverable.
            if bal < accs[dst][0] and not unsafe_accept:
                continue
        else:
            phase, rnd = props[dst][0], props[dst][1]
            if phase == DONE or bal != make_ballot(rnd, dst):
                continue
            if kind == PROMISE and phase != P1:
                continue
            # ACCEPTED while still in P1 cannot exist for the CURRENT
            # ballot (its phase 2 has not begun), so this only drops
            # replies that can never be consumed.
            if kind == ACCEPTED and phase != P2:
                continue
        keep.append(m)
    return (accs, props, tuple(keep), voters)


def check_exhaustive(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 5_000_000,
    unsafe_accept: bool = False,
) -> CheckResult:
    """Exhaustively explore every schedule; assert agreement + validity.

    ``max_round`` bounds retries — an int applies to every proposer, a tuple
    gives per-proposer bounds (asymmetric bounds keep the space tractable:
    the killer interleavings need only ONE proposer to preempt the other).
    Raises ``AssertionError`` with the counterexample trace on a violation;
    ``RuntimeError`` if the bounded space exceeds ``max_states`` (tighten
    the bounds).
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    own_vals = {_own_val(p) for p in range(n_prop)}
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state: State, trace: tuple) -> None:
        accs, props, net, voters = state
        chosen = _chosen(voters, quorum)
        stats["chosen_all"] |= chosen
        decided = {pr[6] for pr in props if pr[0] == DONE}
        if decided:
            stats["decided_states"] += 1
        # ---- Invariants, checked in EVERY reachable state ----
        ok = (
            len(chosen) <= 1  # agreement
            and chosen <= own_vals  # validity
            and decided <= chosen  # a decided proposer's value was chosen
        )
        if not ok:
            raise AssertionError(
                f"invariant violated: chosen={chosen} decided={decided} "
                f"after trace={list(trace)}"
            )

    def successors(state: State):
        # GC'd: dead-letter orderings collapse.
        accs, props, net, voters = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, quorum, n_acc, unsafe_accept), unsafe_accept
            )
        for p in range(n_prop):
            if props[p][0] != DONE and props[p][1] < max_round[p]:
                yield ("t", p), _gc(_timeout(state, p, n_acc), unsafe_accept)

    states = explore(_init_state(n_prop, n_acc), successors, check_state, max_states)
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
    )
