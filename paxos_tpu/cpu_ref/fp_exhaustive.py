"""Bounded exhaustive model checking of Fast Paxos (round-1 verdict #3).

`cpu_ref/exhaustive.py` enumerates every schedule of single-decree Paxos;
this sibling does the same for **Fast Paxos** — the repo's subtlest logic
(`protocols/fastpaxos.py`): the shared fast round, the
vote-at-most-once-per-ballot rule, and coordinated recovery's *choosable*
rule.  Until now these were verified only by randomized fuzzing plus
hand-picked cases; here every reachable state of a small bounded instance
is visited and agreement/validity asserted in each.

Model (mirroring the kernel's semantics, not its vectorized form):

- Round 0 is the **fast round** with the shared ballot ``make_ballot(0, 0)``
  (`core/fp_state.py` `fast_ballot`): every proposer's
  ``Accept(fast_bal, own_val)`` broadcast is in flight initially.
- An acceptor votes at most once per ballot: it accepts ``(b, v)`` iff
  ``b >= promised`` and (``b > acc_bal`` or the identical pair — idempotent
  re-accept).
- A timed-out proposer starts a **classic round** ``>= 1``: phase-1
  PREPAREs, promises carrying the pre-update ``(acc_bal, acc_val)``, and on
  a q1 quorum the coordinated-recovery pick: value ``v`` is *choosable* at
  the highest reported ballot ``k`` (when ``k`` is the fast round) iff its
  reporters plus the unheard acceptors could still contain a fast quorum —
  ``count(v) + (n - heard) >= q_fast``.  A choosable value MUST be adopted;
  if none is, the proposer's own value is safe.  At a classic ``k`` the
  (unique) reported value is adopted.
- A value is **chosen** when a ``(bal, v)`` row has a fast quorum of votes
  (round-0 ballot) or a classic q2 quorum (rounds >= 1) — the same
  per-round-kind threshold `check/safety.learner_observe` applies.

``adopt_any=True`` injects the classic wrong-recovery bug: skip the
choosable filter and adopt any reported value (lowest value id).  The
checker must then find a counterexample — e.g. with 5 acceptors, recovery
hearing {v1 x 1, v2 x 2} must adopt the still-choosable v2; adopting v1
lets v1 be chosen classically while the two unheard acceptors complete
v2's fast quorum.  That this trace is found (and none exists under the
correct rule) is exactly what tests/test_exhaustive.py asserts.

Same soundness notes as the paxos checker: message loss = never-delivered
(every prefix explored), duplication left to the fuzzer, GC'd no-op
deliveries collapse dead-letter orderings.
"""

from __future__ import annotations

from paxos_tpu.cpu_ref.exhaustive import (
    CheckResult,
    explore,
    make_ballot,
    make_fair_completion,
    make_liveness_checker,
)

# Message kinds (same encoding as the paxos checker).
PREPARE, PROMISE, ACCEPT, ACCEPTED = 0, 1, 2, 3
# Proposer phases (core/fp_state.py).
P1, P2, DONE, FAST = 0, 1, 2, 3

FAST_BAL = make_ballot(0, 0)  # shared fast ballot (fp_state.fast_ballot)


def _round(bal: int, max_props: int = 8) -> int:
    return (bal - 1) // max_props


def _fast_quorum(n_acc: int) -> int:
    return -((-3 * n_acc) // 4)  # ceil(3n/4)


def _own_val(pid: int) -> int:
    return 100 + pid


# An acceptor: (promised, acc_bal, acc_val).
# A proposer: (phase, rnd, heard_mask, best_bal, rep_masks, prop_val,
#              decided_val) — rep_masks is a tuple of per-value-id acceptor
#              bitmasks at best_bal (protocols/fastpaxos.py's rep_mask fold).
# State: (accs, props, net, voters); net a sorted tuple (multiset); voters a
# sorted tuple of ((bal, val), acceptor_bitmask) — the learner's vote table.


def _init_state(n_prop: int, n_acc: int):
    accs = tuple((0, 0, 0) for _ in range(n_acc))
    props = tuple(
        (FAST, 0, 0, 0, (0,) * n_prop, _own_val(p), 0) for p in range(n_prop)
    )
    net = tuple(
        sorted(
            (ACCEPT, p, a, FAST_BAL, _own_val(p), 0)
            for p in range(n_prop)
            for a in range(n_acc)
        )
    )
    return (accs, props, net, ())


def _record_vote(voters: tuple, a: int, bal: int, val: int) -> tuple:
    d = dict(voters)
    d[(bal, val)] = d.get((bal, val), 0) | (1 << a)
    return tuple(sorted(d.items()))


def _chosen(voters: tuple, q2: int, fquorum: int) -> set:
    return {
        bv[1]
        for bv, mask in voters
        if bin(mask).count("1") >= (fquorum if _round(bv[0]) == 0 else q2)
    }


def _recovery_pick(
    pid: int,
    n_prop: int,
    n_acc: int,
    heard: int,
    best_bal: int,
    rep_masks: tuple,
    fquorum: int,
    adopt_any: bool,
) -> int:
    """The coordinated-recovery value pick at q1 completion (kernel's rule)."""
    if best_bal == 0:
        return _own_val(pid)
    if adopt_any:  # BUG INJECTION: ignore choosability entirely
        return next(
            (_own_val(v) for v in range(n_prop) if rep_masks[v]), _own_val(pid)
        )
    if _round(best_bal) == 0:  # recovering a fast round
        unheard = n_acc - bin(heard).count("1")
        choosable = [
            rep_masks[v] != 0
            and bin(rep_masks[v]).count("1") + unheard >= fquorum
            for v in range(n_prop)
        ]
        return next(
            (_own_val(v) for v in range(n_prop) if choosable[v]),
            _own_val(pid),
        )
    # Classic round: its unique owner proposed exactly one value.
    return next(
        (_own_val(v) for v in range(n_prop) if rep_masks[v]), _own_val(pid)
    )


def _deliver(
    state,
    i: int,
    n_prop: int,
    n_acc: int,
    q1: int,
    q2: int,
    fquorum: int,
    adopt_any: bool,
):
    """Deliver (and consume) in-flight message ``i``; pure."""
    accs, props, net, voters = state
    kind, src, dst, bal, v1, v2 = net[i]
    net = net[:i] + net[i + 1 :]
    out = []

    if kind == PREPARE:
        promised, abal, aval = accs[dst]
        if bal > promised:
            accs = accs[:dst] + ((bal, abal, aval),) + accs[dst + 1 :]
            out.append((PROMISE, dst, src, bal, abal, aval))
    elif kind == ACCEPT:
        promised, abal, aval = accs[dst]
        # Vote at most once per ballot (the fast-round rule).
        revote = bal > abal or (bal == abal and v1 == aval)
        if bal >= promised and revote:
            accs = accs[:dst] + ((max(promised, bal), bal, v1),) + accs[dst + 1 :]
            voters = _record_vote(voters, dst, bal, v1)
            out.append((ACCEPTED, dst, src, bal, v1, 0))
    elif kind == PROMISE:
        phase, rnd, heard, bb, masks, pv, dec = props[dst]
        if phase == P1 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if v1 > 0 and 0 <= v2 - 100 < n_prop:
                vid = v2 - 100
                if v1 > bb:
                    bb, masks = v1, (0,) * n_prop
                if v1 == bb:
                    masks = masks[:vid] + (masks[vid] | (1 << src),) + masks[vid + 1 :]
            if bin(heard).count("1") >= q1:
                pv = _recovery_pick(
                    dst, n_prop, n_acc, heard, bb, masks, fquorum, adopt_any
                )
                phase, heard = P2, 0
                out.extend((ACCEPT, dst, a, bal, pv, 0) for a in range(n_acc))
            props = props[:dst] + ((phase, rnd, heard, bb, masks, pv, dec),) + props[dst + 1 :]
    elif kind == ACCEPTED:
        phase, rnd, heard, bb, masks, pv, dec = props[dst]
        fast_ok = phase == FAST and bal == FAST_BAL
        p2_ok = phase == P2 and bal == make_ballot(rnd, dst)
        if fast_ok or p2_ok:
            heard |= 1 << src
            need = fquorum if fast_ok else q2
            if bin(heard).count("1") >= need:
                phase, dec = DONE, pv
            props = props[:dst] + ((phase, rnd, heard, bb, masks, pv, dec),) + props[dst + 1 :]

    return (accs, props, tuple(sorted(net + tuple(out))), voters)


def _timeout(state, p: int, n_prop: int, n_acc: int, bump: bool = True):
    """Proposer ``p`` abandons its round and starts the next classic one.

    ``bump=False`` is the injected liveness bug, Fast Paxos' OWN livelock
    shape: on timeout the proposer RETRIES THE FAST ROUND (re-broadcasts
    its value at the shared fast ballot) instead of escalating to a classic
    recovery round.  After a collision the vote-at-most-once-per-ballot
    rule makes every re-broadcast a no-op or an idempotent re-vote, so the
    collided tally never changes and nobody ever reaches the fast quorum —
    the mechanized-liveness leg must find the lasso (retry -> idempotent
    replies -> drained net -> identical state)."""
    accs, props, net, voters = state
    phase, rnd, heard, bb, masks, pv, dec = props[p]
    if not bump:
        props = props[:p] + (
            (FAST, 0, 0, 0, (0,) * n_prop, _own_val(p), dec),
        ) + props[p + 1 :]
        out = tuple(
            (ACCEPT, p, a, FAST_BAL, _own_val(p), 0) for a in range(n_acc)
        )
        return (accs, props, tuple(sorted(net + out)), voters)
    rnd += 1
    bal = make_ballot(rnd, p)
    props = props[:p] + ((P1, rnd, 0, 0, (0,) * n_prop, pv, dec),) + props[p + 1 :]
    out = tuple((PREPARE, p, a, bal, 0, 0) for a in range(n_acc))
    return (accs, props, tuple(sorted(net + out)), voters)


def _gc(state, n_prop: int, dedup: bool = False):
    """Drop in-flight messages whose delivery is provably a no-op.

    Unlike the paxos checker, no prune here depends on a rule the injected
    bug (``adopt_any`` — a PROPOSER pick) could break: acceptor monotonicity
    holds in both modes, so the same reductions are sound for both.
    ``dedup`` collapses the multiset to a set in the ``livelock_bug`` leg
    (see exhaustive._gc: frozen ballots make re-emitted retries identical,
    and without the collapse the multiset grows without bound).
    """
    accs, props, net, voters = state
    keep = []
    for m in net:
        kind, src, dst, bal, v1, v2 = m
        if kind == PREPARE:
            if bal <= accs[dst][0]:
                continue
        elif kind == ACCEPT:
            promised, abal, aval = accs[dst]
            revote = bal > abal or (bal == abal and v1 == aval)
            if bal < promised or not revote:
                continue
        else:
            phase, rnd = props[dst][0], props[dst][1]
            if phase == DONE:
                continue
            if kind == PROMISE and (phase != P1 or bal != make_ballot(rnd, dst)):
                continue
            if kind == ACCEPTED:
                fast_ok = phase == FAST and bal == FAST_BAL
                p2_ok = phase == P2 and bal == make_ballot(rnd, dst)
                if not (fast_ok or p2_ok):
                    continue
        keep.append(m)
    if dedup:
        keep = sorted(set(keep))
    return (accs, props, tuple(keep), voters)


def check_fp_exhaustive(
    n_prop: int = 2,
    n_acc: int = 5,
    max_round: "int | tuple[int, ...]" = (1, 0),
    max_states: int = 5_000_000,
    adopt_any: bool = False,
    q1: int = 0,
    q2: int = 0,
    q_fast: int = 0,
    liveness_bound: "int | None" = None,
    livelock_bug: bool = False,
) -> CheckResult:
    """Exhaustively explore every Fast-Paxos schedule at small bounds.

    Defaults: 2 proposers x 5 acceptors (5 is the smallest count where the
    choosable rule is load-bearing: with 3, nothing reported by a majority
    recovery can ever still reach the fast quorum of 3), proposer 0 allowed
    one classic recovery round, proposer 1 fast-only.  ``q1``/``q2``/
    ``q_fast`` = 0 use the classic majority / ceil(3n/4) defaults (nonzero
    values model Fast Flexible Paxos quorums).  Raises ``AssertionError``
    with the counterexample trace on an agreement/validity violation.
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    q1 = q1 or quorum
    q2 = q2 or quorum
    fquorum = q_fast or _fast_quorum(n_acc)
    own_vals = {_own_val(p) for p in range(n_prop)}
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state, trace) -> None:
        accs, props, net, voters = state
        chosen = _chosen(voters, q2, fquorum)
        stats["chosen_all"] |= chosen
        decided = {pr[6] for pr in props if pr[0] == DONE}
        if decided:
            stats["decided_states"] += 1
        ok = (
            len(chosen) <= 1  # agreement
            and chosen <= own_vals  # validity
            and decided <= chosen  # a decided proposer's value was chosen
        )
        if not ok:
            raise AssertionError(
                f"invariant violated: chosen={chosen} decided={decided} "
                f"after trace={list(trace)}"
            )

    live_check, live_stats = (None, None)
    if liveness_bound is not None:
        fair_next, is_decided = make_fair_completion(
            lambda s: (("d", s[2][0]), _gc(
                _deliver(s, 0, n_prop, n_acc, q1, q2, fquorum, adopt_any),
                n_prop, dedup=livelock_bug,
            )),
            lambda s, p: _gc(
                _timeout(s, p, n_prop, n_acc, bump=not livelock_bug),
                n_prop, dedup=livelock_bug,
            ),
            done_phase=DONE,
        )
        live_check, live_stats = make_liveness_checker(
            fair_next, is_decided, liveness_bound
        )

    def check_both(state, trace) -> None:
        check_state(state, trace)
        if live_check is not None:
            live_check(state, trace)

    def successors(state):
        accs, props, net, voters = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, n_prop, n_acc, q1, q2, fquorum, adopt_any),
                n_prop, dedup=livelock_bug,
            )
        for p in range(n_prop):
            if props[p][0] != DONE and props[p][1] < max_round[p]:
                yield ("t", p), _gc(
                    _timeout(state, p, n_prop, n_acc, bump=not livelock_bug),
                    n_prop, dedup=livelock_bug,
                )

    states = explore(_init_state(n_prop, n_acc), successors, check_both, max_states)
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
        max_completion=None if live_stats is None else live_stats["max_completion"],
    )
