"""Golden model: event-driven single-instance Paxos in pure Python.

Reference parity (SURVEY.md §5.2.1): an independently written, readable
implementation of the same protocol the batched kernels implement — the
Proposer/Acceptor/Learner roles as objects, the network as an explicit
multiset of in-flight messages, and the asynchronous scheduler as a seeded
random choice of which enabled event fires next (deliver some message, or
fire a proposer timeout).  This mirrors the reference's actor semantics
(unordered selective receive from mailboxes [CH]) without any array tricks,
so the batched simulator's behavior can be checked against it property-wise:
both must satisfy agreement + validity on every seed, and both must decide
under fair scheduling.

The safety oracle here recomputes *chosen* from the full accept-event
history (no bounded table) — strictly more complete than the device checker,
which the tests exploit to validate the device checker's bounds.
"""

from __future__ import annotations

import dataclasses
import random
from collections import defaultdict
from typing import Optional

# Message kinds
PREPARE, PROMISE, ACCEPT, ACCEPTED = "prepare", "promise", "accept", "accepted"


def make_ballot(rnd: int, pid: int, max_props: int = 8) -> int:
    return rnd * max_props + pid + 1


@dataclasses.dataclass
class Msg:
    kind: str
    src: int  # proposer id for requests, acceptor id for replies
    dst: int
    bal: int
    val: int = 0
    prev_bal: int = 0
    prev_val: int = 0


class Acceptor:
    def __init__(self) -> None:
        self.promised = 0
        self.acc_bal = 0
        self.acc_val = 0

    def on_prepare(self, m: Msg) -> Optional[Msg]:
        if m.bal > self.promised:
            self.promised = m.bal
            return Msg(PROMISE, m.dst, m.src, m.bal,
                       prev_bal=self.acc_bal, prev_val=self.acc_val)
        return None

    def on_accept(self, m: Msg) -> Optional[Msg]:
        if m.bal >= self.promised:
            self.promised = max(self.promised, m.bal)
            self.acc_bal, self.acc_val = m.bal, m.val
            return Msg(ACCEPTED, m.dst, m.src, m.bal, val=m.val)
        return None


class Proposer:
    P1, P2, DONE = 0, 1, 2

    def __init__(self, pid: int, own_val: int, n_acc: int) -> None:
        self.pid = pid
        self.own_val = own_val
        self.n_acc = n_acc
        self.rnd = 0
        self.bal = make_ballot(0, pid)
        self.phase = self.P1
        self.heard: set[int] = set()
        self.best = (0, 0)
        self.prop_val = 0
        self.decided_val: Optional[int] = None

    @property
    def quorum(self) -> int:
        return self.n_acc // 2 + 1

    def broadcast(self, kind: str, **kw) -> list[Msg]:
        return [Msg(kind, self.pid, a, self.bal, **kw) for a in range(self.n_acc)]

    def start(self) -> list[Msg]:
        return self.broadcast(PREPARE)

    def on_promise(self, m: Msg) -> list[Msg]:
        if self.phase != self.P1 or m.bal != self.bal:
            return []
        self.heard.add(m.src)
        if m.prev_bal > self.best[0]:
            self.best = (m.prev_bal, m.prev_val)
        if len(self.heard) >= self.quorum:
            self.phase = self.P2
            self.heard = set()
            self.prop_val = self.best[1] if self.best[0] > 0 else self.own_val
            return self.broadcast(ACCEPT, val=self.prop_val)
        return []

    def on_accepted(self, m: Msg) -> list[Msg]:
        if self.phase != self.P2 or m.bal != self.bal:
            return []
        self.heard.add(m.src)
        if len(self.heard) >= self.quorum:
            self.phase = self.DONE
            self.decided_val = self.prop_val
        return []

    def on_timeout(self) -> list[Msg]:
        if self.phase == self.DONE:
            return []
        self.rnd += 1
        self.bal = make_ballot(self.rnd, self.pid)
        self.phase = self.P1
        self.heard = set()
        self.best = (0, 0)
        return self.broadcast(PREPARE)


@dataclasses.dataclass
class GoldenReport:
    decided: bool
    chosen_values: set[int]
    agreement_ok: bool
    validity_ok: bool
    steps: int


def run_golden(
    seed: int,
    n_prop: int = 2,
    n_acc: int = 3,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.05,
    max_steps: int = 20_000,
) -> GoldenReport:
    """Run one instance to decision under a seeded adversarial scheduler."""
    rng = random.Random(seed)
    acceptors = [Acceptor() for _ in range(n_acc)]
    proposers = [Proposer(p, 100 + p, n_acc) for p in range(n_prop)]
    own_vals = {p.own_val for p in proposers}
    network: list[Msg] = []
    accept_events: list[tuple[int, int, int]] = []  # (acceptor, bal, val)

    for p in proposers:
        network.extend(p.start())

    def dispatch(m: Msg) -> None:
        out: list[Msg] = []
        if m.kind == PREPARE:
            r = acceptors[m.dst].on_prepare(m)
            out = [r] if r else []
        elif m.kind == ACCEPT:
            r = acceptors[m.dst].on_accept(m)
            if r:
                accept_events.append((m.dst, m.bal, m.val))
                out = [r]
        elif m.kind == PROMISE:
            out = proposers[m.dst].on_promise(m)
        elif m.kind == ACCEPTED:
            out = proposers[m.dst].on_accepted(m)
        for o in out:
            if rng.random() >= p_drop:
                network.append(o)

    steps = 0
    while steps < max_steps and not all(p.phase == p.DONE for p in proposers):
        steps += 1
        # Enabled events: deliver any in-flight message, or any live timeout.
        if network and rng.random() >= timeout_weight:
            i = rng.randrange(len(network))
            m = network[i] if rng.random() < p_dup else network.pop(i)
            dispatch(m)
        else:
            live = [p for p in proposers if p.phase != p.DONE]
            if not live:
                break
            for m in rng.choice(live).on_timeout():
                if rng.random() >= p_drop:
                    network.append(m)

    # Omniscient oracle: chosen = any (b, v) accepted by a majority, over history.
    voters: dict[tuple[int, int], set[int]] = defaultdict(set)
    for a, b, v in accept_events:
        voters[(b, v)].add(a)
    quorum = n_acc // 2 + 1
    chosen = {v for (b, v), accs in voters.items() if len(accs) >= quorum}
    decided_vals = {p.decided_val for p in proposers if p.decided_val is not None}
    return GoldenReport(
        decided=all(p.phase == p.DONE for p in proposers),
        chosen_values=chosen,
        agreement_ok=len(chosen) <= 1 and all(v in chosen for v in decided_vals),
        validity_ok=chosen <= own_vals,
        steps=steps,
    )
