"""Schedule-exact host interpreter — the differential oracle for the kernels.

SURVEY.md §5.2.1 promises "single-lane slices of the batched kernels, same
seeds => identical decisions".  This module goes further: a pure-Python,
per-lane, *scalar* re-implementation of every protocol's tick semantics that
consumes the SAME pre-sampled ``TickMasks``/``MPTickMasks`` and ``FaultPlan``
(sliced to one lane) as the JAX kernels, so the whole per-tick state — not
just decisions — must match lane-for-lane, tick-for-tick
(tests/test_differential.py).

Why this exists (round-1 verdict, "Missing #2"): the property tests and the
fused-vs-XLA bit-exactness check validate invariants and the *lowering*, but
a mask-plumbing bug that silently weakens adversarial coverage — a drop mask
wired to the wrong message kind, a selection bias, a fault consumed by the
wrong role — would pass all of them.  An independent interpreter written in
a different style (scalar loops over one lane, no arrays) diverges on the
first tick any mask is consumed differently, which turns "the schedule space
we think we explore" into a checked property.

Style contract: everything here is deliberately UN-vectorized — Python ints,
lists, explicit loops — and written from the protocol semantics, not by
transcribing the jnp expressions.  Where the kernels have known
representation quirks (int32 wraparound scores, max-trick value ride-alongs,
sentinel guards), those are semantics and are reproduced, with comments.

State/mask/plan representation: nested dicts mirroring the flax dataclass
field names, with the instances axis sliced away (see :func:`lane_of`), so a
test can assert ``interp_state == lane_of(jax_state, lane)`` wholesale.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

INT32_MIN = -(1 << 31)
NEVER = (1 << 31) - 1  # faults.injector.NEVER
MAX_PROPOSERS = 8  # core.ballot.MAX_PROPOSERS

# Phases (core.state / core.fp_state / core.raft_state / core.mp_state).
P1, P2, DONE, FAST = 0, 1, 2, 3
CAND, LEAD_R = 0, 1  # raft candidate phases (DONE shared)
FOLLOW, CANDIDATE, LEAD = 0, 1, 2  # multipaxos proposer phases
VALUE_BASE = 100


def _i32(x: int) -> int:
    """Interpret a Python int's low 32 bits as a signed int32."""
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x >= (1 << 31) else x


def _popcount(x: int) -> int:
    return bin(x & 0xFFFFFFFF).count("1")


def _majority(n_acc: int) -> int:
    return n_acc // 2 + 1


def _fast_quorum(n_acc: int) -> int:
    return -((-3 * n_acc) // 4)


def _make_ballot(rnd: int, pid: int) -> int:
    return rnd * MAX_PROPOSERS + pid + 1


def _ballot_round(bal: int) -> int:
    return (bal - 1) // MAX_PROPOSERS  # floor division, matching jnp int32


def lane_of(tree: Any, lane: int) -> Any:
    """Convert a flax-struct pytree to nested plain-Python data for ONE lane.

    Every array leaf's trailing axis is ``instances`` (the framework's
    instance-minor layout); scalars (``tick``) pass through.  ``None``
    (disabled masks) stays ``None``.
    """
    import dataclasses

    import numpy as np

    if tree is None:
        return None
    if dataclasses.is_dataclass(tree):
        return {
            f.name: lane_of(getattr(tree, f.name), lane)
            for f in dataclasses.fields(tree)
        }
    a = np.asarray(tree)
    if a.ndim == 0:
        return a.item()
    return a[..., lane].tolist()


def _select_one(
    present: list, score_bits: list, n_prop: int
) -> Optional[tuple]:
    """The transport's per-acceptor request pick: (kind, proposer) or None.

    Max over int32 scores whose low bits are the slot id (distinct per
    fiber); a winning score equal to the INT32_MIN absent-sentinel idles the
    acceptor (the kernels' ``fiber_max > neg_inf`` guard).
    """
    nbits = max((2 * n_prop - 1).bit_length(), 1)
    himask = (~((1 << nbits) - 1)) & 0xFFFFFFFF
    best, best_score = None, None
    for k in range(2):
        for p in range(n_prop):
            if not present[k][p]:
                continue
            score = _i32((score_bits[k][p] & himask) | (k * n_prop + p))
            if best_score is None or score > best_score:
                best_score, best = score, (k, p)
    if best is None or best_score == INT32_MIN:
        return None
    return best


def _alive(plan: dict, a: int, tick: int) -> bool:
    return not (plan["crash_start"][a] <= tick < plan["crash_end"][a])


def _prop_alive(plan: dict, p: int, tick: int) -> bool:
    return not (plan["pcrash_start"][p] <= tick < plan["pcrash_end"][p])


def _link_ok(plan: dict, p: int, a: int, tick: int) -> bool:
    cut = plan["part_start"] <= tick < plan["part_end"]
    return plan["pside"][p] == plan["aside"][a] or not cut


def _mask3(m: Optional[list], k: int, p: int, a: int, default: bool = True) -> bool:
    """Read an optional (2, P, A) mask; None means the fault is disabled."""
    return default if m is None else bool(m[k][p][a])


def _mask2(m: Optional[list], p: int, a: int, default: bool = True) -> bool:
    """Read an optional (P, A) mask; None means the fault is disabled."""
    return default if m is None else bool(m[p][a])


def _learner_fold(
    lrn: dict,
    events: list,  # per acceptor: (flag, bal, val)
    tick: int,
    quorum: int,
    fquorum: Optional[int] = None,
) -> None:
    """check.safety.learner_observe, scalar: bounded (b, v) -> bitmask table.

    Sequential fold over acceptors (at most one accept event each per tick);
    eviction = displacing a live row (min-ballot policy) or failing to
    insert; with ``fquorum``, round-0 ballots use the fast threshold.
    """
    K = len(lrn["lt_bal"])

    def thr(bal: int) -> int:
        if fquorum is None:
            return quorum
        return fquorum if _ballot_round(bal) == 0 else quorum

    pre = [
        _popcount(lrn["lt_mask"][k]) >= thr(lrn["lt_bal"][k]) for k in range(K)
    ]
    for a, (flag, b, v) in enumerate(events):
        f = flag and b > 0
        if not f:
            continue
        match = [
            lrn["lt_bal"][k] == b and lrn["lt_val"][k] == v for k in range(K)
        ]
        if any(match):
            for k in range(K):
                if match[k]:
                    lrn["lt_mask"][k] |= 1 << a
            continue
        min_bal = min(lrn["lt_bal"])
        if min_bal == 0 or b > min_bal:
            k = lrn["lt_bal"].index(min_bal)  # first min row
            lrn["lt_bal"][k], lrn["lt_val"][k], lrn["lt_mask"][k] = b, v, 1 << a
            if min_bal != 0:
                lrn["evictions"] += 1
        else:
            lrn["evictions"] += 1
    post = [
        _popcount(lrn["lt_mask"][k]) >= thr(lrn["lt_bal"][k]) for k in range(K)
    ]
    newly = [post[k] and not pre[k] for k in range(K)]
    if not lrn["chosen"] and any(newly):
        first = next(k for k in range(K) if newly[k])
        lrn["chosen"] = True
        lrn["chosen_val"] = lrn["lt_val"][first]
        lrn["chosen_tick"] = tick
    if lrn["chosen"]:
        lrn["violations"] += sum(
            1 for k in range(K) if newly[k] and lrn["lt_val"][k] != lrn["chosen_val"]
        )


def _consume(buf: dict, taken, stay, n_prop: int, n_acc: int) -> None:
    """transport.consume: clear processed slots unless duplicated."""
    for k in range(2):
        for p in range(n_prop):
            for a in range(n_acc):
                if taken[k][p][a] and not _mask3(stay, k, p, a, default=False):
                    buf["present"][k][p][a] = False


def _send(
    buf: dict, kind: int, p: int, a: int, keep: Optional[list],
    bal: int, v1: int, v2: int,
) -> None:
    """transport.send for one edge: overwrite the slot unless send-dropped."""
    if not _mask2(keep, p, a):
        return
    buf["bal"][kind][p][a] = bal
    buf["v1"][kind][p][a] = v1
    buf["v2"][kind][p][a] = v2
    buf["present"][kind][p][a] = True


def _link_fn(plan: dict, tick: int, cfg):
    """(p, a) -> partition-respecting reachability (constant True w/o faults)."""
    if cfg.p_part > 0.0:
        return lambda p, a: _link_ok(plan, p, a, tick)
    return lambda p, a: True


def _deliver_replies(st: dict, m: dict, link, P: int, A: int) -> tuple:
    """Reply delivery decided on the pre-tick buffer; delivered slots clear
    (minus duplicates) before the acceptors write new replies.

    Returns ``(pre_rep, delivered)`` — the pre-tick snapshot and the
    (2, P, A) delivery decision the proposer half-tick folds over.
    """
    pre_rep = copy.deepcopy(st["replies"])
    delivered = [
        [
            [
                pre_rep["present"][k][p][a]
                and _mask3(m["deliver"], k, p, a)
                and link(p, a)
                for a in range(A)
            ]
            for p in range(P)
        ]
        for k in range(2)
    ]
    _consume(st["replies"], delivered, m["dup_rep"], P, A)
    return pre_rep, delivered


def _select_requests(
    st: dict, m: dict, plan: dict, tick: int, P: int, A: int, link
) -> tuple:
    """Per-acceptor transport pick + gating, then consume selected slots.

    Returns ``(pre_req, picks)``: the pre-tick request snapshot and the
    ``(a, kind, proposer)`` triples that survive the busy/alive/link gates —
    the at-most-one request each live acceptor processes this tick.
    Consuming before the acceptor bodies run is equivalent to the kernels'
    post-loop consume: the bodies read only ``pre_req`` and write only reply
    buffers.
    """
    pre_req = copy.deepcopy(st["requests"])
    sel = [[[False] * A for _ in range(P)] for _ in range(2)]
    picks = []
    for a in range(A):
        pick = _select_one(
            [[pre_req["present"][k][p][a] for p in range(P)] for k in range(2)],
            [[m["sel_score"][k][p][a] for p in range(P)] for k in range(2)],
            P,
        )
        if pick is None:
            continue
        k, p = pick
        busy_ok = m["busy"] is None or bool(m["busy"][0][0][a])
        if not (busy_ok and _alive(plan, a, tick) and link(p, a)):
            continue
        sel[k][p][a] = True
        picks.append((a, k, p))
    _consume(st["requests"], sel, m["dup_req"], P, A)
    return pre_req, picks


# ---------------------------------------------------------------------------
# Single-decree Paxos (protocols/paxos.apply_tick)
# ---------------------------------------------------------------------------


def paxos_tick(st: dict, m: dict, plan: dict, cfg) -> None:
    """One lane, one tick of single-decree Paxos, in place.

    ``st``/``m``/``plan`` are :func:`lane_of` slices of the PaxosState,
    TickMasks, and FaultPlan handed to ``protocols.paxos.apply_tick``;
    ``cfg`` is the (static) FaultConfig.
    """
    A = len(st["acceptor"]["promised"])
    P = len(st["proposer"]["bal"])
    quorum = _majority(A)
    q1 = cfg.q1 or quorum
    q2 = cfg.q2 or quorum
    tick = st["tick"]
    acc, prop, lrn = st["acceptor"], st["proposer"], st["learner"]

    if cfg.amnesia:
        for a in range(A):
            if plan["crash_end"][a] == tick:
                acc["promised"][a] = acc["acc_bal"][a] = acc["acc_val"][a] = 0
    acc_pre = copy.deepcopy(acc)

    link = _link_fn(plan, tick, cfg)
    pre_rep, delivered = _deliver_replies(st, m, link, P, A)

    # ---- Acceptor half-tick: select and process at most one request ----
    pre_req, picks = _select_requests(st, m, plan, tick, P, A, link)
    ok_acc = [False] * A
    ev_bal = [0] * A
    ev_val = [0] * A
    for a, k, p in picks:
        eq = bool(plan["equivocate"][a])
        bal = pre_req["bal"][k][p][a]
        val = pre_req["v1"][k][p][a]
        if k == 0:  # PREPARE(bal)
            honest_ok = not eq and bal > acc["promised"][a]
            if honest_ok or eq:
                # Promise reply carries the PRE-update accepted pair;
                # equivocators "promise" anything and hide theirs.
                _send(
                    st["replies"], 0, p, a, m["keep_prom"], bal,
                    0 if eq else acc["acc_bal"][a],
                    0 if eq else acc["acc_val"][a],
                )
            if honest_ok:
                acc["promised"][a] = bal
        else:  # ACCEPT(bal, val)
            honest_ok = not eq and bal >= acc["promised"][a]
            if honest_ok:
                acc["promised"][a] = max(acc["promised"][a], bal)
            if honest_ok or eq:
                acc["acc_bal"][a], acc["acc_val"][a] = bal, val
                ok_acc[a], ev_bal[a], ev_val[a] = True, bal, val
                _send(st["replies"], 1, p, a, m["keep_accd"], bal, val, 0)

    # ---- Learner / safety checker ----
    _learner_fold(lrn, list(zip(ok_acc, ev_bal, ev_val)), tick, q2)
    for a in range(A):
        if plan["equivocate"][a]:
            continue
        if (
            acc["promised"][a] < acc_pre["promised"][a]
            or acc["acc_bal"][a] > acc["promised"][a]
            or (acc["acc_bal"][a] == 0 and acc["acc_val"][a] != 0)
        ):
            lrn["violations"] += 1

    # ---- Proposer half-tick: fold all delivered replies ----
    for p in range(P):
        bal = prop["bal"][p]
        phase = prop["phase"][p]
        heard = prop["heard"][p]
        for a in range(A):
            if delivered[0][p][a] and pre_rep["bal"][0][p][a] == bal and phase == P1:
                heard |= 1 << a
            if delivered[1][p][a] and pre_rep["bal"][1][p][a] == bal and phase == P2:
                heard |= 1 << a
        # Highest prev-accepted pair among valid promises (max-trick: among
        # slots at the max ballot, take the max value — raw v2 of slots
        # whose prev ballot ties cand_bal, which for cand_bal == 0 includes
        # stale payloads, exactly like the kernel; harmless since a zero
        # cand_bal never upgrades).
        prev = [
            pre_rep["v1"][0][p][a]
            if (delivered[0][p][a] and pre_rep["bal"][0][p][a] == bal and phase == P1)
            else 0
            for a in range(A)
        ]
        cand_bal = max(prev)
        cand_val = max(
            pre_rep["v2"][0][p][a] if prev[a] == cand_bal else 0 for a in range(A)
        )
        if cand_bal > prop["best_bal"][p]:
            prop["best_bal"][p] = cand_bal
            prop["best_val"][p] = cand_val

        p1_done = phase == P1 and _popcount(heard) >= q1
        p2_done = phase == P2 and _popcount(heard) >= q2
        timer = prop["timer"][p] if phase == DONE else prop["timer"][p] + 1
        expired = phase != DONE and not p1_done and not p2_done and timer > cfg.timeout

        if p1_done:
            phase = P2
            prop["prop_val"][p] = (
                prop["best_val"][p] if prop["best_bal"][p] > 0 else prop["own_val"][p]
            )
            heard = 0
            timer = 0
        elif p2_done:
            prop["decided_val"][p] = prop["prop_val"][p]
            phase = DONE
        elif expired:
            phase = P1
            new_bal = _make_ballot(_ballot_round(bal) + 1, p)
            heard = 0
            prop["best_bal"][p] = prop["best_val"][p] = 0
            timer = -m["backoff"][p]
            for a in range(A):
                _send(st["requests"], 0, p, a, m["keep_p1"], new_bal, 0, 0)
            prop["bal"][p] = new_bal
        if p1_done:  # ACCEPT broadcast at the (unchanged) ballot
            for a in range(A):
                _send(
                    st["requests"], 1, p, a, m["keep_p2"],
                    bal, prop["prop_val"][p], 0,
                )
        prop["phase"][p] = phase
        prop["heard"][p] = heard
        prop["timer"][p] = timer

    st["tick"] = tick + 1


# ---------------------------------------------------------------------------
# Fast Paxos (protocols/fastpaxos.apply_tick_fast)
# ---------------------------------------------------------------------------


def fastpaxos_tick(st: dict, m: dict, plan: dict, cfg) -> None:
    """One lane, one tick of Fast Paxos (fast round + coordinated recovery)."""
    A = len(st["acceptor"]["promised"])
    P = len(st["proposer"]["bal"])
    quorum = _majority(A)
    q1 = cfg.q1 or quorum
    q2 = cfg.q2 or quorum
    fquorum = cfg.q_fast or _fast_quorum(A)
    tick = st["tick"]
    acc, prop, lrn = st["acceptor"], st["proposer"], st["learner"]

    if cfg.amnesia:
        for a in range(A):
            if plan["crash_end"][a] == tick:
                acc["promised"][a] = acc["acc_bal"][a] = acc["acc_val"][a] = 0
    acc_pre = copy.deepcopy(acc)

    link = _link_fn(plan, tick, cfg)
    pre_rep, delivered = _deliver_replies(st, m, link, P, A)

    pre_req, picks = _select_requests(st, m, plan, tick, P, A, link)
    ok_acc = [False] * A
    ev_bal = [0] * A
    ev_val = [0] * A
    for a, k, p in picks:
        eq = bool(plan["equivocate"][a])
        bal = pre_req["bal"][k][p][a]
        val = pre_req["v1"][k][p][a]
        if k == 0:  # PREPARE
            honest_ok = not eq and bal > acc["promised"][a]
            if honest_ok or eq:
                _send(
                    st["replies"], 0, p, a, m["keep_prom"], bal,
                    0 if eq else acc["acc_bal"][a],
                    0 if eq else acc["acc_val"][a],
                )
            if honest_ok:
                acc["promised"][a] = bal
        else:  # ACCEPT — vote at most once per ballot (fast-round rule)
            revote = bal > acc["acc_bal"][a] or (
                bal == acc["acc_bal"][a] and val == acc["acc_val"][a]
            )
            honest_ok = not eq and bal >= acc["promised"][a] and revote
            if honest_ok:
                acc["promised"][a] = max(acc["promised"][a], bal)
            if honest_ok or eq:
                acc["acc_bal"][a], acc["acc_val"][a] = bal, val
                ok_acc[a], ev_bal[a], ev_val[a] = True, bal, val
                _send(st["replies"], 1, p, a, m["keep_accd"], bal, val, 0)

    _learner_fold(
        lrn, list(zip(ok_acc, ev_bal, ev_val)), tick, q2, fquorum=fquorum
    )
    for a in range(A):
        if plan["equivocate"][a]:
            continue
        if (
            acc["promised"][a] < acc_pre["promised"][a]
            or acc["acc_bal"][a] > acc["promised"][a]
            or (acc["acc_bal"][a] == 0 and acc["acc_val"][a] != 0)
        ):
            lrn["violations"] += 1

    # ---- Proposer half-tick ----
    for p in range(P):
        bal = prop["bal"][p]
        phase = prop["phase"][p]
        heard = prop["heard"][p]
        for a in range(A):
            if delivered[0][p][a] and pre_rep["bal"][0][p][a] == bal and phase == P1:
                heard |= 1 << a
            if (
                delivered[1][p][a]
                and pre_rep["bal"][1][p][a] == bal
                and phase in (P2, FAST)
            ):
                heard |= 1 << a
        # Recovery fold: per-value voter bitmask at the highest reported
        # ballot, sequential over acceptors (matching the kernel's fold).
        for a in range(A):
            pb = pre_rep["v1"][0][p][a]
            pv = pre_rep["v2"][0][p][a]
            valid = (
                delivered[0][p][a]
                and pre_rep["bal"][0][p][a] == bal
                and phase == P1
                and pb > 0
                and VALUE_BASE <= pv < VALUE_BASE + P
            )
            if not valid:
                continue
            vid = pv - VALUE_BASE
            if pb > prop["best_bal"][p]:
                for v in range(P):
                    prop["rep_mask"][p][v] = 0
                prop["best_bal"][p] = pb
            if pb == prop["best_bal"][p]:
                prop["rep_mask"][p][vid] |= 1 << a

        fast_done = phase == FAST and _popcount(heard) >= fquorum
        p1_done = phase == P1 and _popcount(heard) >= q1
        p2_done = phase == P2 and _popcount(heard) >= q2

        # Coordinated recovery: v choosable at fast round k iff its
        # reporters plus the unheard acceptors could contain a fast quorum.
        unheard = A - _popcount(heard)
        choosable = [
            prop["rep_mask"][p][v] != 0
            and _popcount(prop["rep_mask"][p][v]) + unheard >= fquorum
            for v in range(P)
        ]
        pick_fast = next(
            (v + VALUE_BASE for v in range(P) if choosable[v]), VALUE_BASE
        )
        pick_classic = next(
            (v + VALUE_BASE for v in range(P) if prop["rep_mask"][p][v] != 0),
            VALUE_BASE,
        )
        if prop["best_bal"][p] > 0:
            if _ballot_round(prop["best_bal"][p]) == 0:  # k is the fast round
                v_recover = pick_fast if any(choosable) else prop["own_val"][p]
            else:  # k classic: its unique owner proposed one value
                v_recover = pick_classic
        else:
            v_recover = prop["own_val"][p]

        timer = prop["timer"][p] if phase == DONE else prop["timer"][p] + 1
        expired = (
            phase != DONE
            and not (p1_done or p2_done or fast_done)
            and timer > cfg.timeout
        )

        if p1_done:
            phase = P2
            prop["prop_val"][p] = v_recover
            heard = 0
            timer = 0
        elif p2_done or fast_done:
            prop["decided_val"][p] = (
                prop["own_val"][p] if fast_done else prop["prop_val"][p]
            )
            phase = DONE
        elif expired:
            phase = P1
            new_bal = _make_ballot(_ballot_round(bal) + 1, p)
            heard = 0
            prop["best_bal"][p] = 0
            for v in range(P):
                prop["rep_mask"][p][v] = 0
            timer = -m["backoff"][p]
            for a in range(A):
                _send(st["requests"], 0, p, a, m["keep_p1"], new_bal, 0, 0)
            prop["bal"][p] = new_bal
        if p1_done:
            for a in range(A):
                _send(
                    st["requests"], 1, p, a, m["keep_p2"],
                    bal, prop["prop_val"][p], 0,
                )
        prop["phase"][p] = phase
        prop["heard"][p] = heard
        prop["timer"][p] = timer

    st["tick"] = tick + 1


# ---------------------------------------------------------------------------
# Raft-core (protocols/raftcore.apply_tick_raft)
# ---------------------------------------------------------------------------


def raftcore_tick(st: dict, m: dict, plan: dict, cfg) -> None:
    """One lane, one tick of Raft-core: election restriction + append/ack."""
    A = len(st["acceptor"]["voted"])
    P = len(st["proposer"]["bal"])
    quorum = _majority(A)
    tick = st["tick"]
    voter, cand, lrn = st["acceptor"], st["proposer"], st["learner"]

    if cfg.amnesia:
        for a in range(A):
            if plan["crash_end"][a] == tick:
                voter["voted"][a] = voter["ent_term"][a] = voter["ent_val"][a] = 0
    voter_pre = copy.deepcopy(voter)

    link = _link_fn(plan, tick, cfg)
    pre_rep, delivered = _deliver_replies(st, m, link, P, A)

    pre_req, picks = _select_requests(st, m, plan, tick, P, A, link)
    ok_ap = [False] * A
    ev_bal = [0] * A
    ev_val = [0] * A
    for a, k, p in picks:
        eq = bool(plan["equivocate"][a])
        term = pre_req["bal"][k][p][a]
        v1 = pre_req["v1"][k][p][a]
        if k == 0:  # REQVOTE(term, cand_last): one vote per term + restriction
            grant_h = (
                not eq and term > voter["voted"][a] and v1 >= voter["ent_term"][a]
            )
            grant = grant_h or eq
            # Reply to every solicitor, grant or denial, with the voter's
            # pre-update entry: v1 = (entry_term << 1) | granted.
            pt = 0 if eq else voter["ent_term"][a]
            pv = 0 if eq else voter["ent_val"][a]
            _send(
                st["replies"], 0, p, a, m["keep_prom"], term,
                pt * 2 + (1 if grant else 0), pv,
            )
            if grant_h:
                voter["voted"][a] = term
        else:  # APPEND(term, value)
            ok_h = not eq and term >= voter["voted"][a]
            if ok_h:
                voter["voted"][a] = max(voter["voted"][a], term)
            if ok_h or eq:
                voter["ent_term"][a], voter["ent_val"][a] = term, v1
                ok_ap[a], ev_bal[a], ev_val[a] = True, term, v1
                _send(st["replies"], 1, p, a, m["keep_accd"], term, v1, 0)

    _learner_fold(lrn, list(zip(ok_ap, ev_bal, ev_val)), tick, quorum)
    for a in range(A):
        if plan["equivocate"][a]:
            continue
        if (
            voter["voted"][a] < voter_pre["voted"][a]
            or voter["ent_term"][a] > voter["voted"][a]
            or voter["ent_term"][a] < voter_pre["ent_term"][a]
            or (voter["ent_term"][a] == 0 and voter["ent_val"][a] != 0)
        ):
            lrn["violations"] += 1

    # ---- Candidate half-tick ----
    for p in range(P):
        bal = cand["bal"][p]
        phase = cand["phase"][p]
        heard = cand["heard"][p]
        for a in range(A):
            vote_ok = (
                delivered[0][p][a]
                and pre_rep["bal"][0][p][a] == bal
                and phase == CAND
            )
            if vote_ok and pre_rep["v1"][0][p][a] % 2 == 1:
                heard |= 1 << a
            if (
                delivered[1][p][a]
                and pre_rep["bal"][1][p][a] == bal
                and phase == LEAD_R
            ):
                heard |= 1 << a
        # Adopt the highest-term entry among vote replies (grant or denial):
        # max term, then max value among term-tied slots (kernel max-trick —
        # for cand_t == 0 the value max runs over all vote_ok slots, which
        # only matters when it never upgrades).
        terms = [
            pre_rep["v1"][0][p][a] // 2
            if (
                delivered[0][p][a]
                and pre_rep["bal"][0][p][a] == bal
                and phase == CAND
            )
            else 0
            for a in range(A)
        ]
        cand_t = max(terms)
        cand_v = max(
            (
                pre_rep["v2"][0][p][a]
                if (
                    terms[a] == cand_t
                    and delivered[0][p][a]
                    and pre_rep["bal"][0][p][a] == bal
                    and phase == CAND
                )
                else 0
            )
            for a in range(A)
        )
        if cand_t > cand["ent_term"][p]:
            cand["ent_term"][p] = cand_t
            cand["ent_val"][p] = cand_v

        elected = phase == CAND and _popcount(heard) >= quorum
        committed = phase == LEAD_R and _popcount(heard) >= quorum
        timer = cand["timer"][p] if phase == DONE else cand["timer"][p] + 1
        expired = (
            phase != DONE and not elected and not committed and timer > cfg.timeout
        )

        if elected:
            v_lead = (
                cand["ent_val"][p] if cand["ent_term"][p] > 0 else cand["own_val"][p]
            )
            phase = LEAD_R
            cand["prop_val"][p] = v_lead
            cand["ent_term"][p] = bal  # records its proposal at its own term
            cand["ent_val"][p] = v_lead
            heard = 0
            timer = 0
        elif committed:
            cand["decided_val"][p] = cand["prop_val"][p]
            phase = DONE
        elif expired:
            phase = CAND
            new_bal = _make_ballot(_ballot_round(bal) + 1, p)
            heard = 0
            timer = -m["backoff"][p]
            cand["bal"][p] = new_bal
            bal = new_bal
            for a in range(A):
                _send(
                    st["requests"], 0, p, a, m["keep_p1"],
                    bal, cand["ent_term"][p], 0,
                )
        if phase == LEAD_R:  # leaders re-broadcast AppendEntries every tick
            for a in range(A):
                _send(
                    st["requests"], 1, p, a, m["keep_p2"],
                    bal, cand["prop_val"][p], 0,
                )
        cand["phase"][p] = phase
        cand["heard"][p] = heard
        cand["timer"][p] = timer

    st["tick"] = tick + 1


# ---------------------------------------------------------------------------
# Multi-Paxos (protocols/multipaxos.apply_tick_mp)
# ---------------------------------------------------------------------------


def _mp_learner_fold(
    lrn: dict,
    events: list,  # per acceptor: (flag, bal, slot, val)
    tick: int,
    quorum: int,
) -> None:
    """check.mp_safety.mp_learner_observe, scalar: per-slot packed tables.

    Rows are packed (ballot, value) pairs (``core.mp_state.pack_bv`` — the
    SAME helper the kernels use, so the layout cannot drift); the eviction
    victim is the min-packed row (min ballot, value tiebreak), mirroring
    the kernel's ``row_bv.min`` policy.
    """
    from paxos_tpu.core.mp_state import bv_bal, bv_val, pack_bv

    L = len(lrn["lt_bv"])
    K = len(lrn["lt_bv"][0])
    pre_chosen = copy.deepcopy(lrn["chosen"])  # events all see pre-tick chosen
    pre_val = copy.deepcopy(lrn["chosen_val"])
    pre = [
        [_popcount(lrn["lt_mask"][s][k]) >= quorum for k in range(K)]
        for s in range(L)
    ]
    for a, (flag, b, s, v) in enumerate(events):
        f = flag and b > 0
        if not f or not (0 <= s < L):
            continue
        # Re-confirmations of an already-chosen value are skipped (they
        # cannot disagree; keeps eviction pressure meaningful).
        if pre_chosen[s] and v == pre_val[s]:
            continue
        row_bv = lrn["lt_bv"][s]
        bv = pack_bv(b, v)
        match = [row_bv[k] == bv for k in range(K)]
        if any(match):
            for k in range(K):
                if match[k]:
                    lrn["lt_mask"][s][k] |= 1 << a
            continue
        min_bv = min(row_bv)
        if min_bv == 0 or b > bv_bal(min_bv):
            k = row_bv.index(min_bv)
            row_bv[k] = bv
            lrn["lt_mask"][s][k] = 1 << a
            if min_bv != 0:
                lrn["evictions"] += 1
        else:
            lrn["evictions"] += 1
    for s in range(L):
        newly = [
            _popcount(lrn["lt_mask"][s][k]) >= quorum and not pre[s][k]
            for k in range(K)
        ]
        if not lrn["chosen"][s] and any(newly):
            first = next(k for k in range(K) if newly[k])
            lrn["chosen"][s] = True
            lrn["chosen_val"][s] = bv_val(lrn["lt_bv"][s][first])
            lrn["chosen_tick"][s] = tick
        if lrn["chosen"][s]:
            lrn["violations"] += sum(
                1
                for k in range(K)
                if newly[k]
                and bv_val(lrn["lt_bv"][s][k]) != lrn["chosen_val"][s]
            )


def multipaxos_tick(st: dict, m: dict, plan: dict, cfg) -> None:
    """One lane, one tick of Multi-Paxos: whole-log phase 1, slot-wise phase 2,
    progress leases, leader crash windows.

    ``m`` is a :func:`lane_of` slice of ``MPTickMasks`` (note the per-kind
    reply delivery masks and the jitter draw, absent from paxos' masks).
    """
    from paxos_tpu.core.mp_state import bv_bal, bv_val, pack_bv

    A = len(st["acceptor"]["promised"])
    P = len(st["proposer"]["bal"])
    L = len(st["acceptor"]["log"][0])
    quorum = _majority(A)
    tick = st["tick"]
    acc, prop, lrn = st["acceptor"], st["proposer"], st["learner"]

    if cfg.amnesia:
        for a in range(A):
            if plan["crash_end"][a] == tick:
                acc["promised"][a] = 0
                for s in range(L):
                    acc["log"][a][s] = 0

    link = _link_fn(plan, tick, cfg)

    # Reply delivery (promises and accepteds are separate buffers here).
    pre_prom = copy.deepcopy(st["promises"])
    pre_accd = copy.deepcopy(st["accepted"])
    prom_del = [
        [
            pre_prom["present"][p][a]
            and _mask2(m["prom_deliver"], p, a)
            and link(p, a)
            for a in range(A)
        ]
        for p in range(P)
    ]
    accd_del = [
        [
            pre_accd["present"][p][a]
            and _mask2(m["accd_deliver"], p, a)
            and link(p, a)
            for a in range(A)
        ]
        for p in range(P)
    ]
    for p in range(P):
        for a in range(A):
            if prom_del[p][a]:
                st["promises"]["present"][p][a] = False
            if accd_del[p][a]:
                st["accepted"]["present"][p][a] = False

    # ---- Acceptor half-tick ----
    pre_req, picks = _select_requests(st, m, plan, tick, P, A, link)
    events = [(False, 0, 0, 0)] * A
    for a, k, p in picks:
        eq = bool(plan["equivocate"][a])
        bal = pre_req["bal"][k][p][a]
        val = pre_req["v1"][k][p][a]
        slot = pre_req["v2"][k][p][a]
        if k == 0:  # PREPARE(bal) covering the whole log
            honest_ok = not eq and bal > acc["promised"][a]
            if (honest_ok or eq) and _mask2(m["keep_prom"], p, a):
                st["promises"]["present"][p][a] = True
                st["promises"]["bal"][p][a] = bal
                for s in range(L):  # full-log recovery payload (pre-update)
                    st["promises"]["p_bv"][p][a][s] = (
                        0 if eq else acc["log"][a][s]
                    )
            if honest_ok:
                acc["promised"][a] = bal
        else:  # ACCEPT(bal, val, slot)
            honest_ok = not eq and bal >= acc["promised"][a]
            if honest_ok:
                acc["promised"][a] = max(acc["promised"][a], bal)
            if honest_ok or eq:
                if 0 <= slot < L:
                    acc["log"][a][slot] = pack_bv(bal, val)
                events[a] = (True, bal, slot, val)
                if _mask2(m["keep_accd"], p, a):
                    st["accepted"]["present"][p][a] = True
                    st["accepted"]["bal"][p][a] = bal
                    st["accepted"]["slot"][p][a] = slot
                    st["accepted"]["val"][p][a] = val

    # ---- Learner / checker (chosen count feeds the leases, post-update) ----
    _mp_learner_fold(lrn, events, tick, quorum)
    chosen_count = sum(1 for s in range(L) if lrn["chosen"][s])

    # ---- Proposer half-tick ----
    for p in range(P):
        bal = prop["bal"][p]
        phase = prop["phase"][p]
        heard = prop["heard"][p]
        p_up = _prop_alive(plan, p, tick)
        for a in range(A):
            if (
                prom_del[p][a]
                and pre_prom["bal"][p][a] == bal
                and phase == CANDIDATE
            ):
                heard |= 1 << a
        # Whole-log recovery: per-slot max over valid promises.  Packed
        # pairs order lexicographically by (ballot, value) — one max, no
        # value ride-along (mirrors apply_tick_mp's jnp.maximum fold).
        for s in range(L):
            cand_bv = max(
                (
                    pre_prom["p_bv"][p][a][s]
                    if (
                        prom_del[p][a]
                        and pre_prom["bal"][p][a] == bal
                        and phase == CANDIDATE
                    )
                    else 0
                )
                for a in range(A)
            )
            prop["recov_bv"][p][s] = max(prop["recov_bv"][p][s], cand_bv)
        for a in range(A):
            if (
                accd_del[p][a]
                and pre_accd["bal"][p][a] == bal
                and pre_accd["slot"][p][a] == prop["commit_idx"][p]
                and phase == LEAD
            ):
                heard |= 1 << a

        p1_done = phase == CANDIDATE and _popcount(heard) >= quorum
        slot_done = (
            phase == LEAD
            and _popcount(heard) >= quorum
            and prop["commit_idx"][p] < L
        )

        # Progress lease: chosen-count progress resets suspicion.
        if chosen_count > prop["last_chosen_count"][p]:
            lease_timer = 0
        else:
            lease_timer = prop["lease_timer"][p] + 1
        prop["last_chosen_count"][p] = max(
            prop["last_chosen_count"][p], chosen_count
        )
        log_full = chosen_count >= L
        if cfg.log_total:
            log_full = log_full or st["base"] + chosen_count >= cfg.log_total
        lease_out = lease_timer > cfg.lease_len

        start_elec = (
            phase == FOLLOW
            and p_up
            and not log_full
            and lease_timer > cfg.lease_len + p * 3 + m["jitter"][p]
        )
        candidate_timer = (
            prop["candidate_timer"][p] + 1 if phase == CANDIDATE else 0
        )
        cand_fail = (
            phase == CANDIDATE and candidate_timer > cfg.timeout and not p1_done
        )
        demote = phase == LEAD and lease_out and not slot_done and not log_full

        new_phase = phase
        if start_elec:
            new_phase = CANDIDATE
        if p1_done:
            new_phase = LEAD
        if cand_fail or demote:
            new_phase = FOLLOW
        if not p_up:
            new_phase = FOLLOW

        if start_elec:
            bal = _make_ballot(_ballot_round(bal) + 1, p)
            prop["bal"][p] = bal
            for s in range(L):
                prop["recov_bv"][p][s] = 0
        if p1_done:
            prop["commit_idx"][p] = 0
        if slot_done:
            prop["commit_idx"][p] += 1
        if p1_done or slot_done or start_elec or cand_fail or demote:
            heard = 0
        if start_elec or p1_done or slot_done:
            lease_timer = 0
        if cand_fail or demote:
            lease_timer = cfg.lease_len - m["backoff"][p]
        if start_elec:
            candidate_timer = 0

        # Emits.
        if start_elec and p_up:
            for a in range(A):
                _send(st["requests"], 0, p, a, m["keep_prep"], bal, 0, 0)
        ci = min(prop["commit_idx"][p], L - 1)
        drive = new_phase == LEAD and p_up and prop["commit_idx"][p] < L
        if cfg.log_total:
            drive = drive and st["base"] + prop["commit_idx"][p] < cfg.log_total
        if drive:
            rbv = prop["recov_bv"][p][ci]
            # Command payloads are keyed by GLOBAL slot (base + ci).
            pval = bv_val(rbv) if rbv > 0 else (p + 1) * 1000 + st["base"] + ci
            for a in range(A):
                _send(st["requests"], 1, p, a, m["keep_acc"], bal, pval, ci)

        prop["phase"][p] = new_phase
        prop["heard"][p] = heard
        prop["lease_timer"][p] = lease_timer
        prop["candidate_timer"][p] = candidate_timer

    st["tick"] = tick + 1


def multipaxos_compact_lane(st: dict) -> tuple:
    """Scalar mirror of ``protocols.multipaxos.compact_mp`` for ONE lane.

    Shifts the contiguous chosen prefix out of every slot-indexed list,
    re-bases in-flight ACCEPT slots (dropping those below the new window),
    and advances ``base``.  Returns ``(shift, evicted_vals)`` so the
    differential harness can compare against the kernel's outputs.
    """
    lrn, prop, acc = st["learner"], st["proposer"], st["acceptor"]
    L = len(lrn["chosen"])
    A = len(acc["promised"])
    P = len(prop["bal"])
    shift = 0
    while shift < L and lrn["chosen"][shift]:
        shift += 1
    evicted = list(lrn["chosen_val"][:shift]) + [0] * (L - shift)

    def sh(lst, fill=0):
        return lst[shift:] + [fill] * shift

    for a in range(A):
        acc["log"][a] = sh(acc["log"][a])
    for p in range(P):
        prop["recov_bv"][p] = sh(prop["recov_bv"][p])
        # Mirror of compact_mp: a leader whose driven slot was compacted
        # under it re-collects votes for the (different) slot it clamps to.
        if prop["phase"][p] == LEAD and shift > prop["commit_idx"][p]:
            prop["heard"][p] = 0
        prop["commit_idx"][p] = max(prop["commit_idx"][p] - shift, 0)
        prop["last_chosen_count"][p] = max(
            prop["last_chosen_count"][p] - shift, 0
        )
    for key in ("lt_bv", "lt_mask"):
        # Fresh row lists (a shared fill list would alias mutations).
        lrn[key] = lrn[key][shift:] + [
            [0] * len(lrn[key][0]) for _ in range(shift)
        ]
    lrn["chosen"] = sh(lrn["chosen"], fill=False)
    lrn["chosen_val"] = sh(lrn["chosen_val"])
    lrn["chosen_tick"] = sh(lrn["chosen_tick"], fill=-1)
    req = st["requests"]
    for p in range(P):
        for a in range(A):
            s = req["v2"][1][p][a] - shift  # kind 1 = ACCEPT carries the slot
            req["v2"][1][p][a] = s
            if s < 0:
                req["present"][1][p][a] = False
            ab = st["accepted"]
            s2 = ab["slot"][p][a] - shift
            ab["slot"][p][a] = s2
            if s2 < 0:
                ab["present"][p][a] = False
            # In-flight promises drop on any nonzero shift (compact_mp
            # clears them instead of shifting their payloads).
            if shift:
                st["promises"]["present"][p][a] = False
    st["base"] += shift
    return shift, evicted


INTERP_TICKS = {
    "paxos": paxos_tick,
    "fastpaxos": fastpaxos_tick,
    "raftcore": raftcore_tick,
    "multipaxos": multipaxos_tick,
}
