"""Bounded exhaustive model checking of Multi-Paxos log replication.

Completes the model-checker matrix (`exhaustive.py` classic Paxos,
`fp_exhaustive.py` Fast Paxos, `raft_exhaustive.py` Raft-core): every
schedule of a small bounded instance of `protocols/multipaxos.py`'s
semantics — whole-log phase 1 (promises carry the acceptor's full
accepted log), slot-by-slot phase 2 from slot 0 with per-slot max-ballot
recovery, one promise covering every slot — with per-slot
agreement/validity asserted in every reachable state.

The lease machinery is deliberately absent: leases only decide WHEN a
follower challenges the leader, and safety must hold for ANY challenge
schedule, which is exactly what the nondeterministic timeout action
explores (the same abstraction the C++ oracle `native/paxos_oracle.cc`
mp::Sim uses — this checker is its exhaustive counterpart).

``no_recovery=True`` injects the classic Multi-Paxos bug: a new leader
skips the promise-payload fold and drives its OWN values from slot 0.
The checker must then find a counterexample — a second leader at a
higher ballot overwrites an already-chosen slot — while the correct
recovery rule keeps the whole bounded space clean (re-confirming a
chosen slot re-chooses the same value).

Same soundness notes as the siblings: loss = never-delivered (every
prefix explored), duplication left to the fuzzer, GC'd no-op deliveries
collapse dead-letter orderings.
"""

from __future__ import annotations

from paxos_tpu.cpu_ref.exhaustive import (
    CheckResult,
    explore,
    make_ballot,
    make_fair_completion,
    make_liveness_checker,
)

# Message kinds.
PREPARE, PROMISE, ACCEPT, ACCEPTED = 0, 1, 2, 3
# Proposer phases (core/mp_state.py: FOLLOW, CANDIDATE, LEAD + terminal).
FOLLOW, CAND, LEAD, DONE = 0, 1, 2, 3


def own_slot_value(pid: int, slot: int) -> int:
    return (pid + 1) * 1000 + slot  # multipaxos.own_slot_value


# An acceptor: (promised, log) with log an L-tuple of (bal, val).
# A proposer: (phase, rnd, heard_mask, recov, commit_idx, decided) with
#   recov an L-tuple of (bal, val) and decided an L-tuple of values.
# Messages are 7-tuples (kind, src, dst, bal, slot, val, payload):
#   PREPARE:  slot/val/payload unused
#   PROMISE:  payload = the acceptor's full pre-promise log (L-tuple)
#   ACCEPT:   (slot, val) the driven slot; payload unused
#   ACCEPTED: (slot, val) echoed; payload unused
# Votes: sorted tuple of ((slot, bal, val), acceptor_bitmask).


def _init_state(n_prop: int, n_acc: int, log_len: int):
    accs = tuple((0, ((0, 0),) * log_len) for _ in range(n_acc))
    props = tuple(
        (FOLLOW, 0, 0, ((0, 0),) * log_len, 0, (0,) * log_len)
        for _ in range(n_prop)
    )
    return (accs, props, (), ())


def _record(votes: tuple, a: int, slot: int, bal: int, val: int) -> tuple:
    d = dict(votes)
    d[(slot, bal, val)] = d.get((slot, bal, val), 0) | (1 << a)
    return tuple(sorted(d.items()))


def _chosen_per_slot(votes: tuple, quorum: int, log_len: int) -> list:
    out = [set() for _ in range(log_len)]
    for (slot, bal, val), mask in votes:
        if bin(mask).count("1") >= quorum:
            out[slot].add(val)
    return out


def _merge(net: tuple, out, slot_net: bool) -> tuple:
    """Add emitted messages to the in-flight set.

    ``slot_net=True`` models the TPU transport's fixed-slot buffers (one
    in-flight message per (kind, src, dst) edge, a new send OVERWRITING
    the old — ``core.messages`` semantics; the MP state's request /
    promise / accepted buffers are exactly one slot per (kind, p, a)).
    The slot-quotiented reachable set is what the batched fuzzer can in
    principle occupy — the denominator of ``check/mp_coverage.py``.
    """
    if not slot_net:
        return tuple(sorted(net + tuple(out)))
    d = {(m[0], m[1], m[2]): m for m in net}
    for m in out:
        d[(m[0], m[1], m[2])] = m
    return tuple(sorted(d.values()))


def _drive(p: int, prop, log_len: int, n_acc: int, no_recovery: bool):
    """The leader's ACCEPT broadcast for its current slot (or DONE)."""
    phase, rnd, heard, recov, ci, dec = prop
    if ci >= log_len:
        return (DONE, rnd, 0, recov, ci, dec), ()
    rb, rv = recov[ci]
    val = own_slot_value(p, ci) if (no_recovery or rb == 0) else rv
    bal = make_ballot(rnd, p)
    out = tuple(
        (ACCEPT, p, a, bal, ci, val, ()) for a in range(n_acc)
    )
    return (LEAD, rnd, 0, recov, ci, dec), out


def _deliver(
    state,
    i: int,
    n_acc: int,
    log_len: int,
    quorum: int,
    no_recovery: bool,
    slot_net: bool = False,
):
    accs, props, net, votes = state
    kind, src, dst, bal, slot, val, payload = net[i]
    net = net[:i] + net[i + 1 :]
    out = []

    if kind == PREPARE:
        promised, log = accs[dst]
        if bal > promised:
            accs = accs[:dst] + ((bal, log),) + accs[dst + 1 :]
            out.append((PROMISE, dst, src, bal, 0, 0, log))
    elif kind == ACCEPT:
        promised, log = accs[dst]
        if bal >= promised:
            log = log[:slot] + ((bal, val),) + log[slot + 1 :]
            accs = accs[:dst] + ((max(promised, bal), log),) + accs[dst + 1 :]
            votes = _record(votes, dst, slot, bal, val)
            out.append((ACCEPTED, dst, src, bal, slot, val, ()))
    elif kind == PROMISE:
        prop = props[dst]
        phase, rnd, heard, recov, ci, dec = prop
        if phase == CAND and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if not no_recovery:
                # Whole-log recovery: per-slot max-ballot fold.
                recov = tuple(
                    max(recov[s], payload[s]) for s in range(log_len)
                )
            if bin(heard).count("1") >= quorum:
                newp, emits = _drive(
                    dst, (LEAD, rnd, 0, recov, 0, dec), log_len, n_acc,
                    no_recovery,
                )
                props = props[:dst] + (newp,) + props[dst + 1 :]
                out.extend(emits)
            else:
                props = props[:dst] + ((phase, rnd, heard, recov, ci, dec),) + props[dst + 1 :]
    elif kind == ACCEPTED:
        prop = props[dst]
        phase, rnd, heard, recov, ci, dec = prop
        if phase == LEAD and bal == make_ballot(rnd, dst) and slot == ci:
            heard |= 1 << src
            if bin(heard).count("1") >= quorum:
                dec = dec[:ci] + (val,) + dec[ci + 1 :]
                newp, emits = _drive(
                    dst, (LEAD, rnd, 0, recov, ci + 1, dec), log_len, n_acc,
                    no_recovery,
                )
                props = props[:dst] + (newp,) + props[dst + 1 :]
                out.extend(emits)
            else:
                props = props[:dst] + ((phase, rnd, heard, recov, ci, dec),) + props[dst + 1 :]

    return (accs, props, _merge(net, out, slot_net), votes)


def _timeout(
    state, p: int, n_acc: int, log_len: int, bump: bool = True,
    slot_net: bool = False,
):
    """Proposer ``p`` challenges for leadership at its next ballot (the
    lease-expiry surrogate: any challenge schedule must be safe).

    ``bump=False`` is the injected liveness bug (a leadership challenge
    that does NOT raise the ballot): once any acceptor has promised above
    the frozen ballot, the challenge PREPAREs GC away and the challenger
    re-collects nothing — the mechanized-liveness leg must find the
    lasso."""
    accs, props, net, votes = state
    phase, rnd, heard, recov, ci, dec = props[p]
    if bump:
        rnd += 1
    bal = make_ballot(rnd, p)
    props = props[:p] + ((CAND, rnd, 0, ((0, 0),) * log_len, 0, dec),) + props[p + 1 :]
    out = tuple((PREPARE, p, a, bal, 0, 0, ()) for a in range(n_acc))
    return (accs, props, _merge(net, out, slot_net), votes)


def _gc(state, log_len: int, dedup: bool = False):
    """Drop provably-no-op messages; ``dedup`` collapses the multiset to a
    set in the ``livelock_bug`` leg (see exhaustive._gc: frozen ballots
    make re-emitted challenges identical, and without the collapse the
    multiset grows without bound)."""
    accs, props, net, votes = state
    keep = []
    for m in net:
        kind, src, dst, bal, slot, val, payload = m
        if kind == PREPARE:
            if bal <= accs[dst][0]:
                continue
        elif kind == ACCEPT:
            if bal < accs[dst][0]:
                continue
        else:
            phase, rnd = props[dst][0], props[dst][1]
            if phase == DONE or bal != make_ballot(rnd, dst):
                continue
            if kind == PROMISE and phase != CAND:
                continue
            if kind == ACCEPTED and (
                phase != LEAD or slot != props[dst][4]
            ):
                continue
        keep.append(m)
    if dedup:
        keep = sorted(set(keep))
    return (accs, props, tuple(keep), votes)


def check_mp_exhaustive(
    n_prop: int = 2,
    n_acc: int = 3,
    log_len: int = 2,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 5_000_000,
    no_recovery: bool = False,
    liveness_bound: "int | None" = None,
    livelock_bug: bool = False,
    visit=None,
    slot_net: bool = False,
) -> CheckResult:
    """Exhaustively explore every Multi-Paxos schedule at small bounds.

    ``visit`` (optional callable) receives every reachable state once —
    the MP coverage probe's hook (``check/mp_coverage.py``).
    ``slot_net=True`` explores under the fixed-slot transport
    (:func:`_merge`): the quotient of the schedule space the batched
    fuzzer's overwriting message buffers can reach.

    ``decided_states`` counts states where some proposer replicated the
    FULL log; ``chosen_values`` is the union over slots.

    ``liveness_bound`` arms the mechanized liveness leg
    (exhaustive.make_liveness_checker): from every reachable state the
    fair completion — drain, then the highest-ballot live proposer
    challenges for leadership at the NEXT ballot — fully replicates some
    leader's log within the bound.  Multi-Paxos exercises the timeout arm
    from the very first state: the initial network is EMPTY (leadership
    challenges create all traffic), so completion is election-driven, not
    just drain-driven.  ``livelock_bug`` freezes the challenge ballot and
    the leg must produce a lasso counterexample.
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state, trace) -> None:
        accs, props, net, votes = state
        per_slot = _chosen_per_slot(votes, quorum, log_len)
        for s, vals in enumerate(per_slot):
            stats["chosen_all"] |= vals
            ok = len(vals) <= 1 and all(
                v % 1000 == s and 1 <= v // 1000 <= n_prop for v in vals
            )
            if not ok:
                raise AssertionError(
                    f"invariant violated: slot {s} chosen={vals} "
                    f"after trace={list(trace)}"
                )
        if any(prop[0] == DONE for prop in props):
            stats["decided_states"] += 1  # per STATE, as documented
        for prop in props:
            if prop[0] != DONE:
                continue
            for s in range(log_len):
                if not (per_slot[s] == {prop[5][s]}):
                    raise AssertionError(
                        f"invariant violated: DONE log {prop[5]} vs "
                        f"chosen {per_slot} after trace={list(trace)}"
                    )

    live_check, live_stats = (None, None)
    if liveness_bound is not None:
        fair_next, is_decided = make_fair_completion(
            lambda s: (("d", s[2][0]), _gc(
                _deliver(s, 0, n_acc, log_len, quorum, no_recovery,
                         slot_net),
                log_len, dedup=livelock_bug,
            )),
            lambda s, p: _gc(
                _timeout(s, p, n_acc, log_len, bump=not livelock_bug,
                         slot_net=slot_net),
                log_len, dedup=livelock_bug,
            ),
            done_phase=DONE,
        )
        live_check, live_stats = make_liveness_checker(
            fair_next, is_decided, liveness_bound
        )

    def check_both(state, trace) -> None:
        check_state(state, trace)
        if visit is not None:
            visit(state)
        if live_check is not None:
            live_check(state, trace)

    def successors(state):
        accs, props, net, votes = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, n_acc, log_len, quorum, no_recovery,
                         slot_net),
                log_len, dedup=livelock_bug,
            )
        for p in range(n_prop):
            if props[p][0] != DONE and props[p][1] < max_round[p]:
                yield ("t", p), _gc(
                    _timeout(state, p, n_acc, log_len, bump=not livelock_bug,
                             slot_net=slot_net),
                    log_len, dedup=livelock_bug,
                )

    states = explore(
        _init_state(n_prop, n_acc, log_len), successors, check_both, max_states
    )
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
        max_completion=None if live_stats is None else live_stats["max_completion"],
    )
