"""ctypes binding for the native C++ differential oracle.

Reference parity (SURVEY.md §3.1 native-code note): the framework's native
tier — ``native/paxos_oracle.cc`` — compiled on demand with the system
toolchain (no pip deps) and loaded via ctypes.  Used by the differential
tests to triangulate the JAX kernels against an implementation that shares
no code, no RNG, and no language with them, and to measure the CPU-reference
throughput row of BASELINE.md.
"""

from __future__ import annotations

import ctypes
import dataclasses
import pathlib
import subprocess
import tempfile

import numpy as np

_SRC = pathlib.Path(__file__).resolve().parents[2] / "native" / "paxos_oracle.cc"
_LIB: ctypes.CDLL | None = None


def _build() -> pathlib.Path:
    """Compile the oracle into a cached shared library; rebuild on source change."""
    # Repo-local, user-private cache: a fixed world-shared /tmp path could be
    # pre-created (or pre-populated with a matching .so) by another local user.
    cache = _SRC.parent / ".build"
    cache.mkdir(exist_ok=True, mode=0o700)
    lib = cache / f"libpaxos_oracle_{_SRC.stat().st_mtime_ns}.so"
    if not lib.exists():
        # Compile to a unique temp name, then atomically rename: a killed or
        # racing build can never leave a truncated .so at the final path.
        with tempfile.NamedTemporaryFile(
            dir=cache, suffix=".so.tmp", delete=False
        ) as tmp:
            tmp_path = pathlib.Path(tmp.name)
        proc = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(tmp_path), str(_SRC)],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            tmp_path.unlink(missing_ok=True)
            raise RuntimeError(f"g++ failed building {_SRC}:\n{proc.stderr}")
        tmp_path.replace(lib)
    return lib


def _load() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        lib = ctypes.CDLL(str(_build()))
        lib.run_batch.argtypes = [
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.run_batch.restype = None
        lib.bench_steps.argtypes = lib.run_batch.argtypes[:-1]
        lib.bench_steps.restype = ctypes.c_int64
        lib.mp_run_batch.argtypes = [
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mp_run_batch.restype = None
        lib.fp_run_batch.argtypes = [
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fp_run_batch.restype = None
        lib.raft_run_batch.argtypes = [
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.raft_run_batch.restype = None
        lib.explore_paxos.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.explore_paxos.restype = None
        lib.explore_multipaxos.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.explore_multipaxos.restype = None
        lib.explore_fastpaxos.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.explore_fastpaxos.restype = None
        lib.explore_raftcore.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.explore_raftcore.restype = None
        _LIB = lib
    return _LIB


def _check_topology(n_prop: int, n_acc: int) -> None:
    # Mirrors the C++ side's packing limits: voter sets live in uint32
    # bitmasks and ballots pack (round, pid) with kMaxProposers matching the
    # JAX kernels' single source of truth (tests assert the parity).
    from paxos_tpu.core.ballot import MAX_PROPOSERS

    if not 1 <= n_prop <= MAX_PROPOSERS:
        raise ValueError(
            f"n_prop={n_prop} outside oracle ballot capacity [1, {MAX_PROPOSERS}]"
        )
    if not 1 <= n_acc <= 32:
        raise ValueError(f"n_acc={n_acc} outside oracle bitmask capacity [1, 32]")


@dataclasses.dataclass(frozen=True)
class OracleBatch:
    """Per-run results over a seed range, as numpy arrays of shape (n_runs,)."""

    decided: np.ndarray
    agreement_ok: np.ndarray
    validity_ok: np.ndarray
    n_chosen: np.ndarray
    steps: np.ndarray


def run_native_batch(
    seed0: int,
    n_runs: int,
    n_prop: int = 2,
    n_acc: int = 3,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.05,
    max_steps: int = 20_000,
) -> OracleBatch:
    """Fuzz ``n_runs`` independent single-decree instances in native code."""
    _check_topology(n_prop, n_acc)
    lib = _load()
    out = np.empty((n_runs, 5), dtype=np.int32)
    lib.run_batch(
        seed0, n_runs, n_prop, n_acc, p_drop, p_dup, timeout_weight, max_steps,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return OracleBatch(
        decided=out[:, 0].astype(bool),
        agreement_ok=out[:, 1].astype(bool),
        validity_ok=out[:, 2].astype(bool),
        n_chosen=out[:, 3],
        steps=out[:, 4],
    )


def run_native_mp_batch(
    seed0: int,
    n_runs: int,
    n_prop: int = 2,
    n_acc: int = 3,
    log_len: int = 4,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.05,
    max_steps: int = 60_000,
) -> OracleBatch:
    """Fuzz ``n_runs`` independent Multi-Paxos instances in native code.

    Second oracle protocol (round-1 verdict #9): whole-log phase 1,
    slot-by-slot phase 2, leader preemption by random challenge — the same
    semantics as ``protocols/multipaxos.py`` under an event-driven
    scheduler.  ``n_chosen`` reports chosen SLOTS; ``agreement_ok`` covers
    per-slot agreement AND every finished proposer's decided log matching
    the chosen values.
    """
    _check_topology(n_prop, n_acc)
    if not 1 <= log_len <= 32:
        raise ValueError(f"log_len={log_len} outside oracle capacity [1, 32]")
    lib = _load()
    out = np.empty((n_runs, 5), dtype=np.int32)
    lib.mp_run_batch(
        seed0, n_runs, n_prop, n_acc, log_len, p_drop, p_dup, timeout_weight,
        max_steps, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return OracleBatch(
        decided=out[:, 0].astype(bool),
        agreement_ok=out[:, 1].astype(bool),
        validity_ok=out[:, 2].astype(bool),
        n_chosen=out[:, 3],
        steps=out[:, 4],
    )


def run_native_fp_batch(
    seed0: int,
    n_runs: int,
    n_prop: int = 2,
    n_acc: int = 5,
    q1: int = 0,
    q2: int = 0,
    q_fast: int = 0,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.0,
    max_steps: int = 40_000,
) -> OracleBatch:
    """Fuzz ``n_runs`` independent Fast Paxos instances in native code.

    Third oracle protocol (round-2 verdict #5): shared round-0 fast ballot,
    vote-at-most-once acceptors, fast-quorum choice, and the coordinated-
    recovery choosable rule in classic rounds — the same semantics as
    ``protocols/fastpaxos.py`` under an event-driven scheduler.  The choice
    threshold is per-round-kind (``q_fast`` at round 0, ``q2`` classically);
    ``q1``/``q2``/``q_fast`` of 0 select the classic defaults (majority /
    majority / ceil(3n/4)).  Unsafe FFP triples are supported and MUST make
    the oracle report agreement violations (the falsifiability leg).
    """
    _check_topology(n_prop, n_acc)
    for name, q in (("q1", q1), ("q2", q2), ("q_fast", q_fast)):
        if not 0 <= q <= n_acc:
            raise ValueError(f"{name}={q} outside [0, n_acc={n_acc}]")
    lib = _load()
    out = np.empty((n_runs, 5), dtype=np.int32)
    lib.fp_run_batch(
        seed0, n_runs, n_prop, n_acc, q1, q2, q_fast, p_drop, p_dup,
        timeout_weight, max_steps,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return OracleBatch(
        decided=out[:, 0].astype(bool),
        agreement_ok=out[:, 1].astype(bool),
        validity_ok=out[:, 2].astype(bool),
        n_chosen=out[:, 3],
        steps=out[:, 4],
    )


def run_native_raft_batch(
    seed0: int,
    n_runs: int,
    n_prop: int = 2,
    n_acc: int = 3,
    no_restriction: bool = False,
    no_adoption: bool = False,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.05,
    max_steps: int = 40_000,
) -> OracleBatch:
    """Fuzz ``n_runs`` independent Raft-core instances in native code.

    Fourth oracle protocol — the native matrix is square: election
    restriction, one-vote-per-term fencing, entry adoption from vote
    replies, and majority-ack commit, the same semantics as
    ``protocols/raftcore.py`` under an event-driven scheduler.
    ``no_restriction``/``no_adoption`` each disable one safety leg; the
    exhaustive checker proved either alone suffices and both off violates,
    and this oracle must reproduce that result under its event-driven
    scheduler (tests/test_native_oracle.py).
    """
    _check_topology(n_prop, n_acc)
    lib = _load()
    out = np.empty((n_runs, 5), dtype=np.int32)
    lib.raft_run_batch(
        seed0, n_runs, n_prop, n_acc, int(no_restriction), int(no_adoption),
        p_drop, p_dup, timeout_weight, max_steps,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return OracleBatch(
        decided=out[:, 0].astype(bool),
        agreement_ok=out[:, 1].astype(bool),
        validity_ok=out[:, 2].astype(bool),
        n_chosen=out[:, 3],
        steps=out[:, 4],
    )


def bench_native_steps(
    seed0: int,
    n_runs: int,
    n_prop: int = 1,
    n_acc: int = 3,
    p_drop: float = 0.0,
    p_dup: float = 0.0,
    timeout_weight: float = 0.05,
    max_steps: int = 20_000,
) -> int:
    """Total scheduler events processed (CPU-reference throughput numerator)."""
    _check_topology(n_prop, n_acc)
    return int(_load().bench_steps(
        seed0, n_runs, n_prop, n_acc, p_drop, p_dup, timeout_weight, max_steps
    ))


def main() -> None:
    """Reproduce the BASELINE.md CPU-reference row:

        python -m paxos_tpu.cpu_ref.native
    """
    import json
    import time

    run_native_batch(0, 10)  # warm the build
    t0 = time.perf_counter()
    n_runs = 200_000
    total = bench_native_steps(0, n_runs, n_prop=1, n_acc=3)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "cpu-ref config1 (1 proposer, 3 acceptors, no faults)",
        "events_per_sec": round(total / dt, 1),
        "decisions_per_sec": round(n_runs / dt, 1),
        "events": total,
        "seconds": round(dt, 3),
    }))


if __name__ == "__main__":
    main()


@dataclasses.dataclass(frozen=True)
class NativeExploreResult:
    """Result of the native bounded exhaustive explorer (classic Paxos).

    Field-compatible with the cross-validated subset of
    ``cpu_ref.exhaustive.CheckResult``: ``states`` and ``decided_states``
    must match the Python checker EXACTLY at shared bounds
    (tests/test_native_oracle.py asserts it); ``chosen_values`` is the
    union over the whole space.  ``violation`` reports existence only —
    counterexample TRACES are the Python checker's job (same bounds, same
    reachable set, full action trace).
    """

    states: int
    decided_states: int
    violation: bool
    chosen_values: set
    peak_frontier: int


def explore_native(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 2_000_000_000,
    unsafe_accept: bool = False,
    progress_every: int = 0,
) -> NativeExploreResult:
    """Exhaustively enumerate every schedule of bounded classic Paxos in
    native code — the same transition system as
    ``cpu_ref.exhaustive.check_exhaustive`` (same GC reductions, same
    actions), ~100-150x faster (measured: the (2,1)-retry 5.8M-state space
    is ~25 min in Python, 10 s native), which is what moves the deepest
    recorded bounds an order of magnitude (VERDICT r3 #4).

    State identity is a 128-bit fingerprint of the canonical serialization
    (collision expectation N^2/2^129 — immaterial below ~1e12 states, and
    a collision can only undercount by one state, never fabricate a
    violation); the small-bound counts cross-validate exactly against the
    Python set-based checker.

    Raises ``AssertionError`` on an invariant violation (existence — run
    the Python checker at the same bounds for the trace) and
    ``RuntimeError`` past ``max_states``, mirroring check_exhaustive.
    """
    max_round = _norm_max_round(max_round, n_prop)
    if not 1 <= n_acc <= 8:
        raise ValueError(f"explorer n_acc={n_acc} outside [1, 8]")
    lib = _load()
    mr = (ctypes.c_int32 * n_prop)(*max_round)
    out = (ctypes.c_int64 * 6)()
    lib.explore_paxos(
        n_prop, n_acc, mr, max_states, int(unsafe_accept), progress_every, out
    )
    return _decode_explore_out(
        out, max_states, "paxos", _own_vals_decoder(n_prop)
    )


def explore_mp_native(
    n_prop: int = 2,
    n_acc: int = 3,
    log_len: int = 2,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 2_000_000_000,
    no_recovery: bool = False,
    progress_every: int = 0,
) -> NativeExploreResult:
    """Exhaustively enumerate every schedule of bounded MULTI-PAXOS in
    native code — the same transition system as
    ``cpu_ref.mp_exhaustive.check_mp_exhaustive`` (whole-log phase 1,
    slot-by-slot phase 2, per-slot max-ballot recovery, same GC), state
    counts cross-validated EXACTLY at shared bounds
    (tests/test_native_oracle.py).  Values ride internally as compact
    order-isomorphic ids; ``chosen_values`` decodes them back to
    ``own_slot_value`` form.

    Raises ``AssertionError`` on an invariant violation (existence — the
    Python checker at the same bounds yields the trace) and
    ``RuntimeError`` past ``max_states``.
    """
    max_round = _norm_max_round(max_round, n_prop)
    if not 1 <= n_prop <= 3:
        raise ValueError(f"mp explorer n_prop={n_prop} outside [1, 3]")
    if not 1 <= n_acc <= 8:
        raise ValueError(f"mp explorer n_acc={n_acc} outside [1, 8]")
    if not 1 <= log_len <= 4:
        raise ValueError(f"mp explorer log_len={log_len} outside [1, 4]")
    lib = _load()
    mr = (ctypes.c_int32 * n_prop)(*max_round)
    out = (ctypes.c_int64 * 6)()
    lib.explore_multipaxos(
        n_prop, n_acc, log_len, mr, max_states, int(no_recovery),
        progress_every, out,
    )
    return _decode_explore_out(
        out, max_states, "mp",
        # Compact order-isomorphic ids back to own_slot_value form.
        lambda mask: {
            (vid // log_len + 1) * 1000 + (vid % log_len)
            for vid in range(n_prop * log_len)
            if mask & (1 << vid)
        },
    )


def _decode_explore_out(out, max_states: int, what: str, decode_chosen):
    """Shared result decoding for every native explorer (out[0..5] ABI)."""
    states, decided, violation, status, chosen_mask, peak = (
        out[0], out[1], out[2], out[3], out[4], out[5],
    )
    if status == -1:
        raise ValueError(f"invalid {what} explorer topology (C-side check)")
    if status == 2:
        raise RuntimeError(
            f"state space exceeds max_states={max_states}; tighten bounds"
        )
    if violation:
        raise AssertionError(
            f"invariant violated after {states} states (native explorer "
            f"reports existence; rerun the Python checker at the same "
            f"bounds for the counterexample trace)"
        )
    return NativeExploreResult(
        states=int(states),
        decided_states=int(decided),
        violation=False,
        chosen_values=decode_chosen(int(chosen_mask)),
        peak_frontier=int(peak),
    )


def _own_vals_decoder(n_prop: int):
    """Chosen-bitmask decoder for single-decree protocols (bit v = 100+v)."""
    return lambda mask: {100 + v for v in range(n_prop) if mask & (1 << v)}


def _norm_max_round(max_round, n_prop: int):
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    if not 1 <= n_prop <= 4:
        raise ValueError(f"explorer n_prop={n_prop} outside [1, 4]")
    if any(not 0 <= r <= 29 for r in max_round):
        raise ValueError("explorer max_round outside [0, 29] (uint8 ballots)")
    return max_round


def explore_fp_native(
    n_prop: int = 2,
    n_acc: int = 5,
    max_round: "int | tuple[int, ...]" = (1, 0),
    max_states: int = 2_000_000_000,
    q1: int = 0,
    q2: int = 0,
    q_fast: int = 0,
    adopt_any: bool = False,
    progress_every: int = 0,
) -> NativeExploreResult:
    """Exhaustively enumerate every schedule of bounded FAST PAXOS in
    native code — the same transition system as
    ``cpu_ref.fp_exhaustive.check_fp_exhaustive`` (shared fast ballot,
    vote-at-most-once acceptors, choosable-rule recovery, same GC), state
    counts cross-validated EXACTLY at shared bounds
    (tests/test_native_oracle.py: 4,013,181 at 2x5, retries (1, 0)).
    ``q1``/``q2``/``q_fast`` of 0 select the classic defaults; unsafe FFP
    triples and ``adopt_any`` are falsifiability legs (must raise
    ``AssertionError``).  ``RuntimeError`` past ``max_states``.
    """
    max_round = _norm_max_round(max_round, n_prop)
    if not 1 <= n_acc <= 8:
        raise ValueError(f"fp explorer n_acc={n_acc} outside [1, 8]")
    for name, q in (("q1", q1), ("q2", q2), ("q_fast", q_fast)):
        if not 0 <= q <= n_acc:
            raise ValueError(f"{name}={q} outside [0, n_acc={n_acc}]")
    lib = _load()
    mr = (ctypes.c_int32 * n_prop)(*max_round)
    out = (ctypes.c_int64 * 6)()
    lib.explore_fastpaxos(
        n_prop, n_acc, q1, q2, q_fast, mr, max_states, int(adopt_any),
        progress_every, out,
    )
    return _decode_explore_out(out, max_states, "fp", _own_vals_decoder(n_prop))


def explore_raft_native(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 2_000_000_000,
    no_restriction: bool = False,
    no_adoption: bool = False,
    progress_every: int = 0,
) -> NativeExploreResult:
    """Exhaustively enumerate every schedule of bounded RAFT-CORE in native
    code — the same transition system as
    ``cpu_ref.raft_exhaustive.check_raft_exhaustive`` (election
    restriction, one-vote-per-term, adoption from grants AND denials, same
    GC), state counts cross-validated EXACTLY at shared bounds
    (tests/test_native_oracle.py: 1,233,894 at 2x3, symmetric retry).
    ``no_restriction``/``no_adoption`` disable one safety leg each —
    either alone stays clean, both off must raise ``AssertionError`` (the
    Python decomposition, reproduced natively).  ``RuntimeError`` past
    ``max_states``.
    """
    max_round = _norm_max_round(max_round, n_prop)
    if not 1 <= n_acc <= 8:
        raise ValueError(f"raft explorer n_acc={n_acc} outside [1, 8]")
    lib = _load()
    mr = (ctypes.c_int32 * n_prop)(*max_round)
    out = (ctypes.c_int64 * 6)()
    lib.explore_raftcore(
        n_prop, n_acc, mr, max_states, int(no_restriction), int(no_adoption),
        progress_every, out,
    )
    return _decode_explore_out(
        out, max_states, "raft", _own_vals_decoder(n_prop)
    )
