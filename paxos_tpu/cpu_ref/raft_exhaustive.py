"""Bounded exhaustive model checking of the Raft-core vote kernel.

Third member of the model-checker family (`exhaustive.py` classic Paxos,
`fp_exhaustive.py` Fast Paxos): every schedule of a small bounded
instance of `protocols/raftcore.py`'s semantics — election restriction,
one-vote-per-term, entry adoption from vote replies (grants AND denials),
heartbeat append/ack commit — with agreement/validity asserted in every
reachable state.

The kernel's docstring argues safety rests on two mechanisms:

1. **election restriction** — a voter grants only candidates whose last
   entry is at least as up to date (``cand_last >= ent_term``);
2. **adoption** — a candidate adopts the highest-term entry carried by
   ANY vote reply before proposing.

This checker makes that argument mechanical: ``no_restriction`` and
``no_adoption`` disable each leg independently.  Either leg ALONE keeps
the bounded space clean (restriction blocks stale candidates outright —
real Raft's design; adoption recovers the committed value Paxos-style
even when stale candidates win), while disabling BOTH yields a
counterexample trace (a stale candidate wins with an empty log and
commits a second value over the first) — asserted by
tests/test_exhaustive.py.

Same soundness notes as the siblings: loss = never-delivered (every
prefix explored), duplication left to the fuzzer, GC'd no-op deliveries
collapse dead-letter orderings.
"""

from __future__ import annotations

from paxos_tpu.cpu_ref.exhaustive import (
    CheckResult,
    _chosen,
    _own_val,
    _record_vote as _record,
    explore,
    make_ballot,
    make_fair_completion,
    make_liveness_checker,
)

# Message kinds.
REQVOTE, VOTE, APPEND, ACK = 0, 1, 2, 3
# Candidate phases (core/raft_state.py: CAND, LEAD, DONE).
CAND, LEAD, DONE = 0, 1, 2

# A voter: (voted, ent_term, ent_val).
# A candidate: (phase, rnd, heard_mask, ent_term, ent_val, prop_val, decided).
# Messages are uniform hashable 7-tuples (kind, src, dst, term, x, y, z):
#   REQVOTE: x = cand_last (sender's entry term);          y, z unused
#   VOTE:    x = granted (0/1), y = pre-update ent_term, z = ent_val
#   APPEND:  x = value;                                    y, z unused
#   ACK:     x, y, z unused


def _init_state(n_prop: int, n_acc: int):
    voters = tuple((0, 0, 0) for _ in range(n_acc))
    cands = tuple((CAND, 0, 0, 0, 0, 0, 0) for p in range(n_prop))
    net = tuple(
        sorted(
            (REQVOTE, p, a, make_ballot(0, p), 0, 0, 0)
            for p in range(n_prop)
            for a in range(n_acc)
        )
    )
    return (voters, cands, net, ())


def _deliver(
    state,
    i: int,
    n_acc: int,
    quorum: int,
    no_restriction: bool,
    no_adoption: bool,
):
    voters, cands, net, events = state
    kind, src, dst, term, x, y, z = net[i]
    net = net[:i] + net[i + 1 :]
    out = []

    if kind == REQVOTE:
        voted, et, ev = voters[dst]
        grant = term > voted and (no_restriction or x >= et)
        if grant:
            voters = voters[:dst] + ((term, et, ev),) + voters[dst + 1 :]
        # Reply to every solicitor — grant or denial — with the pre-update
        # entry (the kernel's gossip channel candidates adopt from).
        out.append((VOTE, dst, src, term, 1 if grant else 0, et, ev))
    elif kind == VOTE:
        phase, rnd, heard, et, ev, pv, dec = cands[dst]
        if phase == CAND and term == make_ballot(rnd, dst):
            if x:
                heard |= 1 << src
            if not no_adoption and y > et:
                et, ev = y, z
            if bin(heard).count("1") >= quorum:
                pv = ev if et > 0 else _own_val(dst)
                phase, heard = LEAD, 0
                et, ev = term, pv  # records its proposal at its own term
                out.extend(
                    (APPEND, dst, a, term, pv, 0, 0) for a in range(n_acc)
                )
            cands = cands[:dst] + ((phase, rnd, heard, et, ev, pv, dec),) + cands[dst + 1 :]
    elif kind == APPEND:
        voted, et, ev = voters[dst]
        if term >= voted:
            voters = voters[:dst] + ((max(voted, term), term, x),) + voters[dst + 1 :]
            events = _record(events, dst, term, x)
            out.append((ACK, dst, src, term, 0, 0, 0))
    elif kind == ACK:
        phase, rnd, heard, et, ev, pv, dec = cands[dst]
        if phase == LEAD and term == make_ballot(rnd, dst):
            heard |= 1 << src
            if bin(heard).count("1") >= quorum:
                phase, dec = DONE, pv
            cands = cands[:dst] + ((phase, rnd, heard, et, ev, pv, dec),) + cands[dst + 1 :]

    return (voters, cands, tuple(sorted(net + tuple(out))), events)


def _timeout(state, p: int, n_acc: int, bump: bool = True):
    """Candidate ``p`` abandons its term and runs at the next one.

    The adopted entry PERSISTS across retries (matching the kernel: the
    expired branch resets ballot/heard only) — it is the candidate's log.

    ``bump=False`` is the injected liveness bug (re-election WITHOUT a term
    increase): every voter already spent its one vote for this term, so the
    re-run collects only denials, forever — the mechanized-liveness leg
    must find the lasso.  This is exactly the hazard Raft's randomized
    election timeouts + term bump exist to prevent."""
    voters, cands, net, events = state
    phase, rnd, heard, et, ev, pv, dec = cands[p]
    if bump:
        rnd += 1
    bal = make_ballot(rnd, p)
    cands = cands[:p] + ((CAND, rnd, 0, et, ev, pv, dec),) + cands[p + 1 :]
    out = tuple((REQVOTE, p, a, bal, et, 0, 0) for a in range(n_acc))
    return (voters, cands, tuple(sorted(net + out)), events)


def _gc(state, dedup: bool = False):
    """Drop provably-no-op messages.  Conservative: a REQVOTE below the
    voter's term is kept only while its denial reply could still matter.
    ``dedup`` collapses the multiset to a set in the ``livelock_bug`` leg
    (see exhaustive._gc: frozen terms make re-emitted REQVOTEs identical,
    and without the collapse the multiset grows without bound)."""
    voters, cands, net, events = state
    keep = []
    for m in net:
        kind, src, dst, term, x, y, z = m
        if kind == REQVOTE:
            # No grant possible AND the reply would be ignored => no-op.
            phase, rnd = cands[src][0], cands[src][1]
            reply_dead = phase != CAND or term != make_ballot(rnd, src)
            if term <= voters[dst][0] and reply_dead:
                continue
        elif kind == VOTE:
            phase, rnd = cands[dst][0], cands[dst][1]
            if phase != CAND or term != make_ballot(rnd, dst):
                continue
        elif kind == APPEND:
            if term < voters[dst][0]:
                continue
        else:  # ACK
            phase, rnd = cands[dst][0], cands[dst][1]
            if phase != LEAD or term != make_ballot(rnd, dst):
                continue
        keep.append(m)
    if dedup:
        keep = sorted(set(keep))
    return (voters, cands, tuple(keep), events)


def check_raft_exhaustive(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = (1, 0),
    max_states: int = 5_000_000,
    no_restriction: bool = False,
    no_adoption: bool = False,
    liveness_bound: "int | None" = None,
    livelock_bug: bool = False,
) -> CheckResult:
    """Exhaustively explore every Raft-core schedule at small bounds.

    ``liveness_bound`` arms the mechanized liveness leg
    (exhaustive.make_liveness_checker): from every reachable state, the
    fair completion (drain, then the highest-term live candidate re-runs
    at the NEXT term) elects a leader and commits within the bound.
    ``livelock_bug`` removes the term bump from re-election — the classic
    split-vote livelock Raft's design calls out — and the leg must then
    produce a lasso counterexample (every voter's one vote for the term is
    spent, so re-runs collect only denials).
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    own_vals = {_own_val(p) for p in range(n_prop)}
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state, trace) -> None:
        voters, cands, net, events = state
        chosen = _chosen(events, quorum)
        stats["chosen_all"] |= chosen
        decided = {c[6] for c in cands if c[0] == DONE}
        if decided:
            stats["decided_states"] += 1
        ok = (
            len(chosen) <= 1  # agreement (distinct committed values)
            and chosen <= own_vals  # validity
            and decided <= chosen  # a finished leader's value was committed
        )
        if not ok:
            raise AssertionError(
                f"invariant violated: chosen={chosen} decided={decided} "
                f"after trace={list(trace)}"
            )

    live_check, live_stats = (None, None)
    if liveness_bound is not None:
        fair_next, is_decided = make_fair_completion(
            lambda s: (("d", s[2][0]), _gc(
                _deliver(s, 0, n_acc, quorum, no_restriction, no_adoption),
                dedup=livelock_bug,
            )),
            lambda s, p: _gc(
                _timeout(s, p, n_acc, bump=not livelock_bug),
                dedup=livelock_bug,
            ),
            done_phase=DONE,
        )
        live_check, live_stats = make_liveness_checker(
            fair_next, is_decided, liveness_bound
        )

    def check_both(state, trace) -> None:
        check_state(state, trace)
        if live_check is not None:
            live_check(state, trace)

    def successors(state):
        voters, cands, net, events = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, n_acc, quorum, no_restriction, no_adoption),
                dedup=livelock_bug,
            )
        for p in range(n_prop):
            if cands[p][0] != DONE and cands[p][1] < max_round[p]:
                yield ("t", p), _gc(
                    _timeout(state, p, n_acc, bump=not livelock_bug),
                    dedup=livelock_bug,
                )

    states = explore(_init_state(n_prop, n_acc), successors, check_both, max_states)
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
        max_completion=None if live_stats is None else live_stats["max_completion"],
    )
