"""Bounded exhaustive model checking of SynchPaxos (bounded-delay fast path).

`cpu_ref/exhaustive.py` enumerates every schedule of single-decree Paxos;
this sibling does the same for **SynchPaxos** (`protocols/synchpaxos.py`,
after the bounded-delay SMR line of arXiv:2507.12792): the leader-owned
round-0 fast path plus the classic-ballot fallback.

The model deliberately has NO timer and NO delta: an exhaustive schedule
space already contains every delay pattern (a delayed message is one
scheduled late; an infinitely-delayed one is never scheduled), so proving
the invariants over ALL schedules proves exactly the claim the protocol
makes — the synchrony window delta is a liveness/latency bet and safety
never depends on it.  Concretely:

- **Fast path**: proposer 0 owns the unique round-0 ballot
  ``sync_ballot = make_ballot(0, 0)`` and its ``Accept(sync_bal, own_val)``
  broadcast is in flight initially.  It decides on a **majority** of
  Accepted at that ballot — round 0 has a single owner, so this is just
  classic phase 2 and must be safe under every schedule.
- **Fallback**: a timeout moves the leader (or activates a follower) onto
  a classic round >= 1 through ordinary phase-1 recovery, which adopts any
  reported round-0 value — a late fast quorum can never contradict it.
  Followers start passive: their PREPAREs enter the net only via timeout,
  preserving round 0's single owner.
- **Planted bug** (``unsafe_fast=True``): the leader decides on the FIRST
  Accepted heard — the "one ack implies synchrony held" shortcut with no
  quorum.  The checker must find a counterexample schedule (a decided
  value that is not chosen, or two chosen values after recovery commits a
  different value classically); tests/test_exhaustive.py asserts both
  directions.

Same soundness notes as the paxos checker: message loss = never-delivered
(every prefix explored), duplication left to the fuzzer, GC'd no-op
deliveries collapse dead-letter orderings.
"""

from __future__ import annotations

from paxos_tpu.cpu_ref.exhaustive import (
    CheckResult,
    explore,
    make_ballot,
    make_fair_completion,
    make_liveness_checker,
)

# Message kinds (same encoding as the paxos checker).
PREPARE, PROMISE, ACCEPT, ACCEPTED = 0, 1, 2, 3
# Proposer phases (core/sp_state.py).
P1, P2, DONE, FAST = 0, 1, 2, 3

SYNC_BAL = make_ballot(0, 0)  # leader-owned round-0 ballot (sp_state.sync_ballot)


def _own_val(pid: int) -> int:
    return 100 + pid


# An acceptor: (promised, acc_bal, acc_val).
# A proposer: (phase, rnd, heard_mask, best_bal, best_val, prop_val,
#              decided_val) — the classic paxos tuple; the leader starts in
#              FAST with prop_val pre-bound to its own value.
# State: (accs, props, net, voters); net a sorted tuple (multiset); voters a
# sorted tuple of ((bal, val), acceptor_bitmask) — the learner's vote table.


def _init_state(n_prop: int, n_acc: int):
    accs = tuple((0, 0, 0) for _ in range(n_acc))
    props = ((FAST, 0, 0, 0, 0, _own_val(0), 0),) + tuple(
        (P1, 0, 0, 0, 0, 0, 0) for _ in range(1, n_prop)
    )
    # Only the leader's fast broadcast is in flight: followers activate via
    # timeout, so round 0 keeps its single owner.
    net = tuple(
        sorted((ACCEPT, 0, a, SYNC_BAL, _own_val(0), 0) for a in range(n_acc))
    )
    return (accs, props, net, ())


def _merge(net: tuple, out: list) -> tuple:
    return tuple(sorted(net + tuple(out)))


def _chosen(voters: tuple, quorum: int) -> set:
    return {bv[1] for bv, mask in voters if bin(mask).count("1") >= quorum}


def _record_vote(voters: tuple, a: int, bal: int, val: int) -> tuple:
    d = dict(voters)
    d[(bal, val)] = d.get((bal, val), 0) | (1 << a)
    return tuple(sorted(d.items()))


def _deliver(state, i: int, quorum: int, n_acc: int, unsafe_fast: bool):
    """Deliver (and consume) in-flight message ``i``; pure."""
    accs, props, net, voters = state
    kind, src, dst, bal, v1, v2 = net[i]
    net = net[:i] + net[i + 1 :]
    out = []

    if kind == PREPARE:
        promised, abal, aval = accs[dst]
        if bal > promised:
            accs = accs[:dst] + ((bal, abal, aval),) + accs[dst + 1 :]
            out.append((PROMISE, dst, src, bal, abal, aval))
    elif kind == ACCEPT:
        promised, abal, aval = accs[dst]
        if bal >= promised:
            accs = accs[:dst] + ((bal, bal, v1),) + accs[dst + 1 :]
            voters = _record_vote(voters, dst, bal, v1)
            out.append((ACCEPTED, dst, src, bal, v1, 0))
    elif kind == PROMISE:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase == P1 and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            if v1 > bb:
                bb, bv = v1, v2
            if bin(heard).count("1") >= quorum:
                pv = bv if bb > 0 else _own_val(dst)
                phase, heard = P2, 0
                out.extend(
                    (ACCEPT, dst, a, bal, pv, 0) for a in range(n_acc)
                )
            props = (
                props[:dst]
                + ((phase, rnd, heard, bb, bv, pv, dec),)
                + props[dst + 1 :]
            )
    elif kind == ACCEPTED:
        phase, rnd, heard, bb, bv, pv, dec = props[dst]
        if phase in (P2, FAST) and bal == make_ballot(rnd, dst):
            heard |= 1 << src
            votes = bin(heard).count("1")
            # The honest fast decide IS a classic phase-2 quorum at the
            # single-owner round-0 ballot; the planted bug decides the fast
            # round on the first ack, no quorum.
            need = 1 if (unsafe_fast and phase == FAST) else quorum
            if votes >= need:
                phase, dec = DONE, pv
            props = (
                props[:dst]
                + ((phase, rnd, heard, bb, bv, pv, dec),)
                + props[dst + 1 :]
            )

    return (accs, props, _merge(net, out), voters)


def _timeout(state, p: int, n_acc: int):
    """Proposer ``p`` abandons its attempt (the leader its FAST round) and
    retries one classic round higher — the delta-expiry fallback and the
    follower activation collapse to the same action here."""
    accs, props, net, voters = state
    phase, rnd, heard, bb, bv, pv, dec = props[p]
    rnd += 1
    bal = make_ballot(rnd, p)
    props = props[:p] + ((P1, rnd, 0, 0, 0, 0, dec),) + props[p + 1 :]
    out = [(PREPARE, p, a, bal, 0, 0) for a in range(n_acc)]
    return (accs, props, _merge(net, out), voters)


def _gc(state):
    """Drop in-flight messages whose delivery is provably a no-op (same
    soundness argument as the paxos checker's ``_gc``; ACCEPTED stays
    deliverable to a FAST-phase leader)."""
    accs, props, net, voters = state
    keep = []
    for m in net:
        kind, src, dst, bal, v1, v2 = m
        if kind == PREPARE:
            if bal <= accs[dst][0]:
                continue
        elif kind == ACCEPT:
            if bal < accs[dst][0]:
                continue
        else:
            phase, rnd = props[dst][0], props[dst][1]
            if phase == DONE or bal != make_ballot(rnd, dst):
                continue
            if kind == PROMISE and phase != P1:
                continue
            if kind == ACCEPTED and phase not in (P2, FAST):
                continue
        keep.append(m)
    return (accs, props, tuple(keep), voters)


def check_sp_exhaustive(
    n_prop: int = 2,
    n_acc: int = 3,
    max_round: "int | tuple[int, ...]" = 1,
    max_states: int = 5_000_000,
    unsafe_fast: bool = False,
    liveness_bound: "int | None" = None,
) -> CheckResult:
    """Exhaustively explore every SynchPaxos schedule; assert agreement +
    validity + decided-implies-chosen in every reachable state.

    ``unsafe_fast=True`` injects the delay-unsafe fast commit; the checker
    must then raise ``AssertionError`` with a counterexample trace.
    ``liveness_bound`` arms the shared mechanized-liveness leg: from every
    reachable state the fair completion schedule (deliver-all, then let the
    designated proposer retry) must decide within the bound.
    """
    if n_prop > 8:
        raise ValueError("n_prop > 8 collides packed ballots (make_ballot)")
    if isinstance(max_round, int):
        max_round = (max_round,) * n_prop
    if len(max_round) != n_prop:
        raise ValueError(
            f"max_round has {len(max_round)} bounds for n_prop={n_prop}"
        )
    quorum = n_acc // 2 + 1
    own_vals = {_own_val(p) for p in range(n_prop)}
    stats = {"decided_states": 0, "chosen_all": set()}

    def check_state(state, trace) -> None:
        accs, props, net, voters = state
        chosen = _chosen(voters, quorum)
        stats["chosen_all"] |= chosen
        decided = {pr[6] for pr in props if pr[0] == DONE}
        if decided:
            stats["decided_states"] += 1
        ok = (
            len(chosen) <= 1  # agreement
            and chosen <= own_vals  # validity
            and decided <= chosen  # a decided proposer's value was chosen
        )
        if not ok:
            raise AssertionError(
                f"invariant violated: chosen={chosen} decided={decided} "
                f"after trace={list(trace)}"
            )

    live_check, live_stats = (None, None)
    if liveness_bound is not None:
        fair_next, is_decided = make_fair_completion(
            lambda s: (
                ("d", s[2][0]),
                _gc(_deliver(s, 0, quorum, n_acc, unsafe_fast)),
            ),
            lambda s, p: _gc(_timeout(s, p, n_acc)),
            done_phase=DONE,
        )
        live_check, live_stats = make_liveness_checker(
            fair_next, is_decided, liveness_bound
        )

    def check_both(state, trace) -> None:
        check_state(state, trace)
        if live_check is not None:
            live_check(state, trace)

    def successors(state):
        accs, props, net, voters = state
        for i in range(len(net)):
            yield ("d", net[i]), _gc(
                _deliver(state, i, quorum, n_acc, unsafe_fast)
            )
        for p in range(n_prop):
            if props[p][0] != DONE and props[p][1] < max_round[p]:
                yield ("t", p), _gc(_timeout(state, p, n_acc))

    states = explore(
        _init_state(n_prop, n_acc), successors, check_both, max_states
    )
    return CheckResult(
        states=states,
        decided_states=stats["decided_states"],
        chosen_values=stats["chosen_all"],
        counterexample=None,
        max_completion=(
            None if live_stats is None else live_stats["max_completion"]
        ),
    )
