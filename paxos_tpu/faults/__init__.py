"""Fault injection: PRNG-mask twins of real crashes and lossy networks."""

from paxos_tpu.faults.injector import FaultConfig, FaultPlan  # noqa: F401
