"""Fault injection — declarative crash schedules and per-tick chaos masks.

Reference parity (SURVEY.md §4.4, §6.3): the reference gets failure semantics
from the actor runtime — monitors/links deliver ``ProcessMonitorNotification``
when a process or node dies, and fault *injection* means actually killing OS
processes [CH].  Here both collapse into data:

- **Static plan** (:class:`FaultPlan`): per-(acceptor, instance) crash windows
  and Byzantine-equivocation flags, sampled once per run from a PRNG key.
  "Failure detection" needs no detector — the quorum kernel simply sees fewer
  live votes (SURVEY.md §4.4).
- **Dynamic masks** (:class:`FaultConfig` probabilities, sampled per tick
  inside the step): send-time message drop, duplication (a processed message
  stays in flight and is processed again), acceptor idling and reply holding
  (both of which realize unbounded delay and reordering under the synchronous
  round model — SURVEY.md §8.1's "adversarial delivery mask").

Crashed acceptors stop processing but *keep their state* across recovery —
Paxos' durable-storage assumption.  Amnesia on recovery (a real-world bug the
checker should catch) is a separate switch, as is equivocation (config 4).

Gray failures (PR 1) extend the plan beyond symmetric, clean faults:
one-way partition cuts (``p_asym``), per-link Bernoulli loss/duplication
rate matrices (``p_flaky``), in-flight payload corruption (``p_corrupt``,
bug injection the checker must flag), per-proposer timeout/backoff skew
(``timeout_skew``/``backoff_skew``), and stale-snapshot recovery
(``stale_k`` — amnesia generalized to "roll back to the last snapshot").
Every gray knob defaults OFF, and every gray plan field is ``None`` when
its knob is off — the pruned pytree and the untouched PRNG stream keep
default-config schedules bit-identical to pre-gray builds
(tests/test_gray.py golden digests).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.core import streams as streams_mod

NEVER = jnp.iinfo(jnp.int32).max

# ---------------------------------------------------------------------------
# Registered injection sites (PR 14 dataflow auditor).  ``fault_site(name)``
# is a zero-op ``jax.named_scope`` whose tag lands in every enclosed eqn's
# name stack, marking the *only* regions where ``FaultPlan`` leaves may touch
# protocol state.  The taint pass (analysis/flow.py) strips the matching
# fault channel's labels inside a registered site and reports any plan leaf
# that reaches protocol state elsewhere.  Metadata only: schedules stay
# bit-identical (goldens pin this).
_SITE_TAG = "__fault_site__"

# Sites owned by the injector itself: the plan-window queries every protocol
# consumes.  name -> fault channels the site is allowed to absorb.
INJECTOR_FAULT_SITES = {
    "alive": ("crash",),
    "prop_alive": ("crash",),
    "recovering": ("crash",),
    "link_ok": ("partition",),
}


def fault_site(name: str):
    """Scope marking a registered fault-injection site named ``name``.

    The name must be registered either in :data:`INJECTOR_FAULT_SITES` or in
    the owning protocol's ``*_FAULT_SITES`` table (core/*state.py) — the flow
    auditor reports unregistered site tags as findings.
    """
    return jax.named_scope(_SITE_TAG + name)

# Per-link Bernoulli rates are stored as uint32 thresholds in int32 bit
# patterns (Mosaic has no uint32 vectors): P(bits < t) = rate for uniform
# bits, compared with the same sign-flip trick as counter_prng.bern.
_TWO32 = float(1 << 32)


def rate_threshold(rate: jnp.ndarray) -> jnp.ndarray:
    """uint32 threshold (as int32 bit pattern) with P(bits < t) ~= rate.

    float32 quantizes the rate to ~2^-24 — far finer than any fuzzing
    config needs.  ``rate >= 1`` saturates near-certain (misses w.p.
    ~2^-24); per-link rates are chaos knobs, not exactness contracts.
    """
    t = jnp.clip(jnp.asarray(rate, jnp.float32), 0.0, 1.0) * _TWO32
    t = jnp.minimum(t, jnp.float32(_TWO32 - 256.0))  # stay uint32-convertible
    return jax.lax.bitcast_convert_type(t.astype(jnp.uint32), jnp.int32)


def bits_below(bits: jnp.ndarray, threshold: jnp.ndarray) -> jnp.ndarray:
    """True where uint32(bits) < uint32(threshold), both int32 bit patterns.

    Sign-flip unsigned compare (Mosaic-safe, same trick as
    ``counter_prng.bern``); works in both engines.
    """
    sign = jnp.int32(-(1 << 31))
    return (bits ^ sign) < (threshold ^ sign)


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Static (trace-time) fault probabilities and protocol timing knobs.

    Hashable and frozen so it can be a static argument to ``jax.jit``.
    """

    # Network chaos (per message / per tick)
    p_drop: float = 0.0  # send-time message loss
    p_dup: float = 0.0  # processed message remains in flight (duplicate)
    p_idle: float = 0.0  # acceptor processes nothing this tick
    p_hold: float = 0.0  # a deliverable reply stays in flight this tick
    # Crash schedule (sampled once per run)
    p_crash: float = 0.0  # per (instance, acceptor): crashes at some point
    p_crash_prop: float = 0.0  # per (instance, proposer): crashes (leader crash)
    crash_max_start: int = 32  # crash start ~ U[0, crash_max_start)
    crash_max_len: int = 16  # window length ~ U[1, crash_max_len]
    crash_forever: bool = False  # never recover instead
    amnesia: bool = False  # (bug injection) lose acceptor state on recovery
    # Network partition (sampled once per run): within a per-instance window
    # the nodes are split into two sides; messages crossing the cut stall
    # in flight (delivery blocked, nothing lost) until the partition heals.
    p_part: float = 0.0  # per instance: a partition episode occurs
    part_max_start: int = 32  # episode start ~ U[0, part_max_start)
    part_max_len: int = 16  # episode length ~ U[1, part_max_len]
    # Byzantine (config 4)
    p_equiv: float = 0.0  # per (instance, acceptor): equivocates forever
    # --- Gray failures (all default OFF; default-off streams bit-identical) ---
    # Asymmetric partitions: a partitioned instance's cut is one-way with
    # probability p_asym — either requests P->A stall while replies flow, or
    # the reverse (the classic one-way link that livelocks naive proposers).
    p_asym: float = 0.0
    # Per-link flaky loss/duplication: each (proposer, acceptor, instance)
    # link is flaky with probability p_flaky; a flaky link's drop rate is
    # ~ U[0, flaky_drop] and its dup rate ~ U[0, flaky_dup], while healthy
    # links keep the uniform p_drop/p_dup — the single global rate is the
    # p_flaky = 0 special case of the same masks.
    p_flaky: float = 0.0
    flaky_drop: float = 0.5
    flaky_dup: float = 0.0
    # (bug injection) In-flight payload corruption: with probability
    # p_corrupt per delivered request, perturb the value of an ACCEPT-class
    # message and the ballot of a PREPARE-class one.  The safety checker
    # MUST flag campaigns run with this on (like unsafe quorums, this
    # validates the checker, not the protocol).
    p_corrupt: float = 0.0
    # Per-proposer timer skew: proposer timeouts get ~ U[0, timeout_skew]
    # extra ticks and backoffs a ~ U[1, backoff_skew] multiplier, so retry
    # storms and dueling-proposer races become schedulable.
    timeout_skew: int = 0
    backoff_skew: int = 0
    # (bug injection) Stale-snapshot recovery: amnesia generalized — a
    # recovering acceptor restores the snapshot taken at the last multiple
    # of stale_k ticks (up to stale_k ticks of accepted state silently
    # lost) instead of losing everything.  0 = off.
    stale_k: int = 0
    # Bounded-delay channel: slow links (each link is slow with probability
    # p_delay, sampled once per run into ``FaultPlan.link_delay``) delay
    # each message send with per-tick probability p_delay by a latency
    # ~ U[1, cap] extra ticks, cap ~ U[1, delay_max] per link.  Delayed
    # messages stay in flight (``until`` stamps on the message buffers) and
    # compose with drop/dup/partition — a delayed message that lands in a
    # cut stalls until the heal releases it (delivery masks AND).
    p_delay: float = 0.0
    delay_max: int = 4  # per-link latency cap ~ U[1, delay_max] ticks
    # Synchrony window Δ (protocols/synchpaxos): the leader's one-round
    # fast path may decide only while its round-trips arrived within delta
    # ticks; past the window it falls back to classic ballots.
    delta: int = 4
    # (bug injection) SynchPaxos fast-path commit WITHOUT the Δ guard: the
    # leader keeps deciding on fast votes after the synchrony window
    # expired, when a classic ballot may already have chosen a different
    # value.  The safety checker must flag campaigns run with this on.
    sp_unsafe_fast: bool = False
    # Proposer timing
    timeout: int = 10  # ticks in a phase before retrying with higher ballot
    backoff_max: int = 8  # retry backoff ~ U[0, backoff_max) extra ticks
    # Ballot-selection strategy (arxiv 2006.01885): a retrying proposer
    # advances its ballot round by ballot_stride instead of 1.  Strides
    # spread contending proposers across rounds, trading per-retry ballot
    # burn for fewer dueling collisions; 1 is the classic consecutive
    # strategy (bit-identical to pre-knob builds).
    ballot_stride: int = 1
    # Flexible Paxos (protocols/paxos + fastpaxos): phase-1 / phase-2 quorum
    # sizes.  0 means the classic majority.  Safe iff q1 + q2 > n_acc —
    # running an unsafe pair is a supported bug-injection mode the checker
    # must catch.
    q1: int = 0
    q2: int = 0
    # Fast Flexible Paxos (protocols/fastpaxos): fast-round quorum size.
    # 0 means the classic ceil(3n/4).  Safe iff ALSO q1 + 2*q_fast > 2*n_acc
    # (a phase-1 quorum must see a majority of any two fast quorums'
    # intersection); unsafe triples are bug-injection modes.
    q_fast: int = 0
    # Multi-Paxos leader lease (ticks without chosen-count progress before
    # followers suspect the leader / a leader demotes itself)
    lease_len: int = 24
    # Multi-Paxos long-log mode (SURVEY.md §6.7): total GLOBAL log length to
    # replicate through the sliding window of ``SimConfig.log_len`` slots
    # (decided prefixes compact out at chunk boundaries —
    # ``protocols.multipaxos.compact_mp``).  0 = plain bounded-log mode
    # (window IS the whole log; bit-identical to the pre-long-log build).
    log_total: int = 0


def links_dup(cfg: FaultConfig) -> bool:
    """Per-link duplication is live: flaky links exist and some dup rate > 0."""
    return cfg.p_flaky > 0.0 and (cfg.p_dup > 0.0 or cfg.flaky_dup > 0.0)


def exposure_lit(cfg: FaultConfig) -> dict:
    """Which exposure classes (``obs.exposure.CLASSES``) this config lights.

    The knob->class mapping the exposure plane accounts against: a class is
    "lit" when at least one knob that can produce its fault events is on.
    A lit class with a zero effective count after a campaign is "vacuous
    chaos" — randomness burned without ever touching the protocol — which
    soak and the ``exposure`` subcommand flag loudly.
    """
    return {
        "drop": cfg.p_drop > 0.0
        or (cfg.p_flaky > 0.0 and cfg.flaky_drop > 0.0),
        "dup": cfg.p_dup > 0.0 or links_dup(cfg),
        "corrupt": cfg.p_corrupt > 0.0,
        "partition": cfg.p_part > 0.0,
        "timeout": cfg.timeout_skew > 0,
        "stale": cfg.stale_k > 0,
        "delay": cfg.p_delay > 0.0,
    }


@struct.dataclass
class FaultPlan:
    """Per-run static fault schedule (device arrays, shard with the state)."""

    crash_start: jnp.ndarray  # (A, I) int32 tick; NEVER if no crash
    crash_end: jnp.ndarray  # (A, I) int32 tick; NEVER if crash is permanent
    equivocate: jnp.ndarray  # (A, I) bool
    pcrash_start: jnp.ndarray  # (P, I) int32 — proposer (leader) crash window
    pcrash_end: jnp.ndarray  # (P, I) int32
    part_start: jnp.ndarray  # (I,) int32 — partition window; NEVER if none
    part_end: jnp.ndarray  # (I,) int32
    aside: jnp.ndarray  # (A, I) bool — acceptor's side of the cut
    pside: jnp.ndarray  # (P, I) bool — proposer's side of the cut
    # Gray-failure fields — None (pruned from the pytree) when the owning
    # knob is off, so default plans keep their pre-gray structure and the
    # fused engine's VMEM footprint.
    part_dir: Optional[jnp.ndarray] = None  # (I,) int32: 0 = two-way cut,
    #   1 = only requests P->A cut, 2 = only replies A->P cut (p_asym)
    link_drop: Optional[jnp.ndarray] = None  # (P, A, I) int32 — per-link
    #   drop-rate uint32 threshold (bit pattern; p_flaky)
    link_dup: Optional[jnp.ndarray] = None  # (P, A, I) int32 — dup threshold
    ptimeout: Optional[jnp.ndarray] = None  # (P, I) int32 extra timeout ticks
    pboff: Optional[jnp.ndarray] = None  # (P, I) int32 backoff multiplier >= 1
    link_delay: Optional[jnp.ndarray] = None  # (P, A, I) int32 — per-link
    #   latency cap in ticks; 0 = the link never delays (p_delay)

    @classmethod
    def none(
        cls,
        n_inst: int,
        n_acc: int,
        n_prop: int = 1,
        cfg: "FaultConfig | None" = None,
    ) -> "FaultPlan":
        """The fault-free plan.

        With ``cfg`` given, gray fields gated on by its knobs are present
        but benign (no per-link variation, no skew) so the pytree structure
        matches ``sample(cfg)`` — checkpoint restore templates need this.
        """
        cfg = cfg or FaultConfig()
        edge = (n_prop, n_acc, n_inst)
        return cls(
            crash_start=jnp.full((n_acc, n_inst), NEVER, jnp.int32),
            crash_end=jnp.full((n_acc, n_inst), NEVER, jnp.int32),
            equivocate=jnp.zeros((n_acc, n_inst), jnp.bool_),
            pcrash_start=jnp.full((n_prop, n_inst), NEVER, jnp.int32),
            pcrash_end=jnp.full((n_prop, n_inst), NEVER, jnp.int32),
            part_start=jnp.full((n_inst,), NEVER, jnp.int32),
            part_end=jnp.full((n_inst,), NEVER, jnp.int32),
            aside=jnp.zeros((n_acc, n_inst), jnp.bool_),
            pside=jnp.zeros((n_prop, n_inst), jnp.bool_),
            part_dir=(
                jnp.zeros((n_inst,), jnp.int32) if cfg.p_asym > 0.0 else None
            ),
            link_drop=(
                jnp.broadcast_to(rate_threshold(cfg.p_drop), edge)
                if cfg.p_flaky > 0.0
                else None
            ),
            link_dup=(
                jnp.broadcast_to(rate_threshold(cfg.p_dup), edge)
                if links_dup(cfg)
                else None
            ),
            ptimeout=(
                jnp.zeros((n_prop, n_inst), jnp.int32)
                if cfg.timeout_skew > 0
                else None
            ),
            pboff=(
                jnp.ones((n_prop, n_inst), jnp.int32)
                if cfg.backoff_skew > 1
                else None
            ),
            link_delay=(
                jnp.zeros(edge, jnp.int32) if cfg.p_delay > 0.0 else None
            ),
        )

    @classmethod
    def sample(
        cls,
        key: jax.Array,
        cfg: FaultConfig,
        n_inst: int,
        n_acc: int,
        n_prop: int = 1,
    ) -> "FaultPlan":
        k_crash, k_eq, kp, k_part, k_side = jax.random.split(key, 5)

        def windows(k, shape, p):
            k1, k2, k3 = jax.random.split(k, 3)
            crashes = jax.random.uniform(k1, shape) < p
            start = jax.random.randint(k2, shape, 0, max(cfg.crash_max_start, 1))
            length = jax.random.randint(k3, shape, 1, max(cfg.crash_max_len, 1) + 1)
            c_start = jnp.where(crashes, start, NEVER)
            c_end = jnp.where(
                crashes & (not cfg.crash_forever),
                # Guard overflow: NEVER + length would wrap.
                jnp.minimum(start + length, NEVER - 1),
                NEVER,
            )
            return c_start, c_end

        crash_start, crash_end = windows(k_crash, (n_acc, n_inst), cfg.p_crash)
        pcrash_start, pcrash_end = windows(kp, (n_prop, n_inst), cfg.p_crash_prop)
        equivocate = jax.random.uniform(k_eq, (n_acc, n_inst)) < cfg.p_equiv

        kp1, kp2, kp3 = jax.random.split(k_part, 3)
        parts = jax.random.uniform(kp1, (n_inst,)) < cfg.p_part
        pstart = jax.random.randint(kp2, (n_inst,), 0, max(cfg.part_max_start, 1))
        plen = jax.random.randint(kp3, (n_inst,), 1, max(cfg.part_max_len, 1) + 1)
        part_start = jnp.where(parts, pstart, NEVER)
        part_end = jnp.where(parts, jnp.minimum(pstart + plen, NEVER - 1), NEVER)
        ka, kpr = jax.random.split(k_side)
        aside = jax.random.uniform(ka, (n_acc, n_inst)) < 0.5
        pside = jax.random.uniform(kpr, (n_prop, n_inst)) < 0.5

        # Gray fields draw from fold_in-derived keys (NOT extra splits of
        # ``key``) so the pre-gray streams above stay bit-identical; the
        # fold constants are registered in core.streams.PLAN_FOLDS and
        # checked against traced plans by the jaxpr auditor.
        part_dir = None
        if cfg.p_asym > 0.0:
            one_way = (
                jax.random.uniform(
                    streams_mod.plan_fold(key, "PART_DIR"), (n_inst,)
                )
                < cfg.p_asym
            )
            cut_req = jax.random.bernoulli(
                streams_mod.plan_fold(key, "CUT_REQ"), 0.5, (n_inst,)
            )
            part_dir = jnp.where(
                one_way, jnp.where(cut_req, 1, 2), 0
            ).astype(jnp.int32)

        link_drop = link_dup = None
        if cfg.p_flaky > 0.0:
            edge = (n_prop, n_acc, n_inst)
            flaky = (
                jax.random.uniform(streams_mod.plan_fold(key, "FLAKY"), edge)
                < cfg.p_flaky
            )
            drop_rate = jnp.where(
                flaky,
                jax.random.uniform(
                    streams_mod.plan_fold(key, "FLAKY_DROP"), edge
                )
                * cfg.flaky_drop,
                cfg.p_drop,
            )
            link_drop = rate_threshold(drop_rate)
            if links_dup(cfg):
                dup_rate = jnp.where(
                    flaky,
                    jax.random.uniform(
                        streams_mod.plan_fold(key, "FLAKY_DUP"), edge
                    )
                    * cfg.flaky_dup,
                    cfg.p_dup,
                )
                link_dup = rate_threshold(dup_rate)

        ptimeout = None
        if cfg.timeout_skew > 0:
            ptimeout = jax.random.randint(
                streams_mod.plan_fold(key, "PTIMEOUT"),
                (n_prop, n_inst),
                0,
                cfg.timeout_skew + 1,
            )
        pboff = None
        if cfg.backoff_skew > 1:
            pboff = jax.random.randint(
                streams_mod.plan_fold(key, "PBOFF"),
                (n_prop, n_inst),
                1,
                cfg.backoff_skew + 1,
            )

        link_delay = None
        if cfg.p_delay > 0.0:
            edge = (n_prop, n_acc, n_inst)
            kd_slow, kd_cap = jax.random.split(
                streams_mod.plan_fold(key, "LINK_DELAY")
            )
            slow = jax.random.uniform(kd_slow, edge) < cfg.p_delay
            cap = jax.random.randint(
                kd_cap, edge, 1, max(cfg.delay_max, 1) + 1
            )
            link_delay = jnp.where(slow, cap, 0).astype(jnp.int32)

        return cls(
            crash_start=crash_start,
            crash_end=crash_end,
            equivocate=equivocate,
            pcrash_start=pcrash_start,
            pcrash_end=pcrash_end,
            part_start=part_start,
            part_end=part_end,
            aside=aside,
            pside=pside,
            part_dir=part_dir,
            link_drop=link_drop,
            link_dup=link_dup,
            ptimeout=ptimeout,
            pboff=pboff,
            link_delay=link_delay,
        )

    def alive(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(A, I) bool: acceptor is up at ``tick``."""
        with fault_site("alive"):
            return ~((self.crash_start <= tick) & (tick < self.crash_end))

    def link_ok(
        self, tick: jnp.ndarray, direction: "str | None" = None
    ) -> jnp.ndarray:
        """(P, A, I) bool: the proposer<->acceptor link delivers at ``tick``.

        False only inside the instance's partition window for pairs on
        opposite sides of the cut; in-flight messages are not dropped, they
        stall until the partition heals (delivery masks AND with this).

        ``direction`` selects the traffic direction for asymmetric cuts:
        ``"req"`` (proposer->acceptor requests) or ``"rep"``
        (acceptor->proposer replies).  With ``part_dir`` sampled, a one-way
        cut blocks only its direction — ``part_dir == 1`` cuts requests,
        ``part_dir == 2`` cuts replies, 0 cuts both.  ``direction=None``
        (or no ``part_dir`` in the plan) is the symmetric two-way view.
        """
        with fault_site("link_ok"):
            cut = (self.part_start <= tick) & (tick < self.part_end)  # (I,)
            if direction is not None and self.part_dir is not None:
                spares = jnp.int32(2 if direction == "req" else 1)
                cut = cut & (self.part_dir != spares)
            same = self.pside[:, None] == self.aside[None]  # (P, A, I)
            return same | ~cut[None, None]

    def prop_alive(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(P, I) bool: proposer is up at ``tick``."""
        with fault_site("prop_alive"):
            return ~((self.pcrash_start <= tick) & (tick < self.pcrash_end))

    def recovering(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(A, I) bool: acceptor comes back up exactly at ``tick`` (for amnesia)."""
        with fault_site("recovering"):
            return self.crash_end == tick


# ---------------------------------------------------------------------------
# Atom codec — JSON-stable (de)serialization of a FaultPlan at the atom
# granularity the shrinker minimizes at (harness/shrink.py), shared by
# shrink (atom enumeration + replayable repros), replay, and the fuzz
# mutator (paxos_tpu/fuzz/mutate.py).  An "atom" is one independently
# removable fault: a crash window, an equivocation flag, a partition
# episode (with its sides and direction), one flaky link's (drop, dup)
# thresholds, one proposer's (timeout, backoff) skew, or one slow link's
# delay cap.
#
# Stability contract: atoms are plain dicts of ints/lists (thresholds in
# uint32 value form, never int32 bit patterns), canonically ordered by
# ``atom_key``, so ``json.dumps(atoms, sort_keys=True)`` is a stable wire
# format across platforms and sessions.  ``atoms_to_plan(plan_to_atoms(p,
# cfg), ..., cfg)`` reproduces ``p`` bit-exactly on every schedule-relevant
# field; ``aside``/``pside``/``part_dir`` are reproduced only in lanes
# with a partition atom (outside a partition window the sides and the cut
# direction are dead inputs — ``link_ok`` returns all-True regardless — so
# sampled values in windowless lanes are deliberately not serialized;
# tests/test_fuzz.py pins both the exact-field round-trip and the
# ``link_ok`` equivalence that justifies the exception).

_ATOM_KIND_ORDER = {"crash": 0, "equiv": 1, "partition": 2, "flaky": 3,
                    "skew": 4, "delay": 5, "wload": 6}


def _u32(x) -> int:
    """int32 bit pattern -> uint32 value (the JSON threshold form)."""
    return int(x) & 0xFFFFFFFF


def _thr32(rate: float) -> int:
    """Host-side ``rate_threshold`` in uint32 value form."""
    return _u32(jax.device_get(rate_threshold(rate)))


def atom_key(atom: dict) -> tuple:
    """Canonical sort key: lane-major, then kind, then sub-targeting."""
    return (
        int(atom["lane"]),
        _ATOM_KIND_ORDER[atom["kind"]],
        str(atom.get("role", "")),
        int(atom.get("idx", atom.get("prop", 0))),
        int(atom.get("acc", 0)),
    )


def canonical_atoms(atoms: list) -> list:
    """Atoms sorted by :func:`atom_key` (the JSON-stable order)."""
    return sorted(atoms, key=atom_key)


def atom_label(atom: dict) -> str:
    """The shrinker's human-readable name for an atom."""
    kind = atom["kind"]
    if kind == "crash":
        return f"crash[{atom['role']}={atom['idx']}]"
    if kind == "equiv":
        return f"equiv[acceptor={atom['idx']}]"
    if kind == "partition":
        return "asym-partition" if atom.get("dir", 0) else "partition"
    if kind == "flaky":
        return f"flaky[link=({atom['prop']},{atom['acc']})]"
    if kind == "skew":
        return f"skew[proposer={atom['prop']}]"
    if kind == "delay":
        return (
            f"delay[link=({atom['prop']},{atom['acc']}),cap={atom['cap']}]"
        )
    if kind == "wload":
        return f"wload[mix={atom['mix']},rate={atom['rate']}]"
    raise ValueError(f"unknown atom kind: {kind!r}")


def plan_to_atoms(
    plan: "FaultPlan", cfg: "FaultConfig | None" = None
) -> list:
    """Serialize ``plan`` to its canonical atom list.

    ``cfg`` supplies the healthy-link baselines: a sampled plan's healthy
    links carry exactly ``rate_threshold(cfg.p_drop/p_dup)`` (see
    ``FaultPlan.sample``), so with ``cfg`` given only genuinely flaky
    links become atoms.  Without ``cfg`` the baseline is 0 — any nonzero
    gray value is an atom, which is what the shrinker's liveness test
    wants for its lane-isolated plans.
    """
    import numpy as np

    host = jax.device_get(plan)
    atoms: list = []
    drop_base = _thr32(cfg.p_drop) if cfg is not None else 0
    dup_base = _thr32(cfg.p_dup) if cfg is not None else 0

    cs = np.asarray(host.crash_start)
    for a, i in zip(*np.nonzero(cs != NEVER)):
        atoms.append({
            "kind": "crash", "role": "acceptor", "idx": int(a),
            "lane": int(i), "start": int(cs[a, i]),
            "end": int(np.asarray(host.crash_end)[a, i]),
        })
    ps = np.asarray(host.pcrash_start)
    for p, i in zip(*np.nonzero(ps != NEVER)):
        atoms.append({
            "kind": "crash", "role": "proposer", "idx": int(p),
            "lane": int(i), "start": int(ps[p, i]),
            "end": int(np.asarray(host.pcrash_end)[p, i]),
        })
    eq = np.asarray(host.equivocate)
    for a, i in zip(*np.nonzero(eq)):
        atoms.append({"kind": "equiv", "idx": int(a), "lane": int(i)})
    pst = np.asarray(host.part_start)
    aside = np.asarray(host.aside)
    pside = np.asarray(host.pside)
    pdir = (
        np.asarray(host.part_dir) if host.part_dir is not None else None
    )
    for (i,) in zip(*np.nonzero(pst != NEVER)):
        atoms.append({
            "kind": "partition", "lane": int(i), "start": int(pst[i]),
            "end": int(np.asarray(host.part_end)[i]),
            "dir": int(pdir[i]) if pdir is not None else 0,
            "aside": [int(b) for b in aside[:, i]],
            "pside": [int(b) for b in pside[:, i]],
        })
    if host.link_drop is not None:
        ld = np.asarray(host.link_drop).astype(np.int64) & 0xFFFFFFFF
        lu = (
            np.asarray(host.link_dup).astype(np.int64) & 0xFFFFFFFF
            if host.link_dup is not None
            else None
        )
        dev = ld != drop_base
        if lu is not None:
            dev = dev | (lu != dup_base)
        for p, a, i in zip(*np.nonzero(dev)):
            atoms.append({
                "kind": "flaky", "prop": int(p), "acc": int(a),
                "lane": int(i), "drop": int(ld[p, a, i]),
                "dup": int(lu[p, a, i]) if lu is not None else None,
            })
    if host.ptimeout is not None or host.pboff is not None:
        pt = (
            np.asarray(host.ptimeout) if host.ptimeout is not None else None
        )
        pb = np.asarray(host.pboff) if host.pboff is not None else None
        shape = pt.shape if pt is not None else pb.shape
        for p in range(shape[0]):
            for i in range(shape[1]):
                t = int(pt[p, i]) if pt is not None else 0
                b = int(pb[p, i]) if pb is not None else 1
                if t != 0 or b != 1:
                    atoms.append({
                        "kind": "skew", "prop": int(p), "lane": int(i),
                        "timeout": t, "boff": b,
                    })
    if host.link_delay is not None:
        lde = np.asarray(host.link_delay)
        for p, a, i in zip(*np.nonzero(lde > 0)):
            atoms.append({
                "kind": "delay", "prop": int(p), "acc": int(a),
                "lane": int(i), "cap": int(lde[p, a, i]),
            })
    return canonical_atoms(atoms)


def atoms_to_plan(
    atoms: list,
    n_inst: int,
    n_acc: int,
    n_prop: int = 1,
    cfg: "FaultConfig | None" = None,
) -> "FaultPlan":
    """Build a FaultPlan from an atom list (the codec's decode direction).

    Starts from ``FaultPlan.none(cfg=cfg)`` — so the pytree STRUCTURE
    matches what ``sample(cfg)`` would produce and healthy links carry the
    cfg baselines — then applies each atom.  Gray fields an atom needs
    that the cfg doesn't gate on are materialized at their benign
    baseline; note the step functions only CONSULT gray fields when the
    matching cfg knob is lit (see protocols/*.py), so callers running a
    mutated plan must light the knobs its atoms need (the fuzz scheduler's
    ``campaign_config`` does exactly this).
    """
    import numpy as np

    cfg = cfg or FaultConfig()
    base = jax.device_get(FaultPlan.none(n_inst, n_acc, n_prop, cfg))
    fields = {
        k: (np.array(v) if v is not None else None)
        for k, v in dataclasses.asdict(base).items()
    }
    drop_base = _thr32(cfg.p_drop)
    dup_base = _thr32(cfg.p_dup)
    edge = (n_prop, n_acc, n_inst)

    def need(name, fill):
        if fields[name] is None:
            fields[name] = fill()
        return fields[name]

    for atom in atoms:
        kind = atom["kind"]
        lane = int(atom["lane"])
        if kind == "crash":
            pre = "crash" if atom["role"] == "acceptor" else "pcrash"
            fields[f"{pre}_start"][atom["idx"], lane] = atom["start"]
            fields[f"{pre}_end"][atom["idx"], lane] = atom["end"]
        elif kind == "equiv":
            fields["equivocate"][atom["idx"], lane] = True
        elif kind == "partition":
            fields["part_start"][lane] = atom["start"]
            fields["part_end"][lane] = atom["end"]
            fields["aside"][:, lane] = [bool(b) for b in atom["aside"]]
            fields["pside"][:, lane] = [bool(b) for b in atom["pside"]]
            if atom.get("dir", 0):
                need(
                    "part_dir",
                    lambda: np.zeros((n_inst,), np.int32),
                )[lane] = atom["dir"]
        elif kind == "flaky":
            ld = need(
                "link_drop",
                lambda: np.full(
                    edge, np.uint32(drop_base).astype(np.int32), np.int32
                ),
            )
            ld[atom["prop"], atom["acc"], lane] = np.uint32(
                atom["drop"]
            ).astype(np.int32)
            if atom.get("dup") is not None:
                lu = need(
                    "link_dup",
                    lambda: np.full(
                        edge, np.uint32(dup_base).astype(np.int32), np.int32
                    ),
                )
                lu[atom["prop"], atom["acc"], lane] = np.uint32(
                    atom["dup"]
                ).astype(np.int32)
        elif kind == "skew":
            if atom.get("timeout", 0) or fields["ptimeout"] is not None:
                need(
                    "ptimeout",
                    lambda: np.zeros((n_prop, n_inst), np.int32),
                )[atom["prop"], lane] = atom.get("timeout", 0)
            if atom.get("boff", 1) != 1 or fields["pboff"] is not None:
                need(
                    "pboff",
                    lambda: np.ones((n_prop, n_inst), np.int32),
                )[atom["prop"], lane] = atom.get("boff", 1)
        elif kind == "delay":
            need(
                "link_delay",
                lambda: np.zeros(edge, np.int32),
            )[atom["prop"], atom["acc"], lane] = int(atom["cap"])
        elif kind == "wload":
            # Config-level, not plan-level: the open-loop client workload
            # rides SimConfig.workload, which the fuzz scheduler's
            # campaign_config lights from this atom (workload.generator).
            # Nothing to write into the plan.
            pass
        else:
            raise ValueError(f"unknown atom kind: {kind!r}")
    return FaultPlan(**{
        k: (jnp.asarray(v) if v is not None else None)
        for k, v in fields.items()
    })
