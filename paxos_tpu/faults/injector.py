"""Fault injection — declarative crash schedules and per-tick chaos masks.

Reference parity (SURVEY.md §4.4, §6.3): the reference gets failure semantics
from the actor runtime — monitors/links deliver ``ProcessMonitorNotification``
when a process or node dies, and fault *injection* means actually killing OS
processes [CH].  Here both collapse into data:

- **Static plan** (:class:`FaultPlan`): per-(acceptor, instance) crash windows
  and Byzantine-equivocation flags, sampled once per run from a PRNG key.
  "Failure detection" needs no detector — the quorum kernel simply sees fewer
  live votes (SURVEY.md §4.4).
- **Dynamic masks** (:class:`FaultConfig` probabilities, sampled per tick
  inside the step): send-time message drop, duplication (a processed message
  stays in flight and is processed again), acceptor idling and reply holding
  (both of which realize unbounded delay and reordering under the synchronous
  round model — SURVEY.md §8.1's "adversarial delivery mask").

Crashed acceptors stop processing but *keep their state* across recovery —
Paxos' durable-storage assumption.  Amnesia on recovery (a real-world bug the
checker should catch) is a separate switch, as is equivocation (config 4).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

NEVER = jnp.iinfo(jnp.int32).max


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Static (trace-time) fault probabilities and protocol timing knobs.

    Hashable and frozen so it can be a static argument to ``jax.jit``.
    """

    # Network chaos (per message / per tick)
    p_drop: float = 0.0  # send-time message loss
    p_dup: float = 0.0  # processed message remains in flight (duplicate)
    p_idle: float = 0.0  # acceptor processes nothing this tick
    p_hold: float = 0.0  # a deliverable reply stays in flight this tick
    # Crash schedule (sampled once per run)
    p_crash: float = 0.0  # per (instance, acceptor): crashes at some point
    p_crash_prop: float = 0.0  # per (instance, proposer): crashes (leader crash)
    crash_max_start: int = 32  # crash start ~ U[0, crash_max_start)
    crash_max_len: int = 16  # window length ~ U[1, crash_max_len]
    crash_forever: bool = False  # never recover instead
    amnesia: bool = False  # (bug injection) lose acceptor state on recovery
    # Network partition (sampled once per run): within a per-instance window
    # the nodes are split into two sides; messages crossing the cut stall
    # in flight (delivery blocked, nothing lost) until the partition heals.
    p_part: float = 0.0  # per instance: a partition episode occurs
    part_max_start: int = 32  # episode start ~ U[0, part_max_start)
    part_max_len: int = 16  # episode length ~ U[1, part_max_len]
    # Byzantine (config 4)
    p_equiv: float = 0.0  # per (instance, acceptor): equivocates forever
    # Proposer timing
    timeout: int = 10  # ticks in a phase before retrying with higher ballot
    backoff_max: int = 8  # retry backoff ~ U[0, backoff_max) extra ticks
    # Flexible Paxos (protocols/paxos + fastpaxos): phase-1 / phase-2 quorum
    # sizes.  0 means the classic majority.  Safe iff q1 + q2 > n_acc —
    # running an unsafe pair is a supported bug-injection mode the checker
    # must catch.
    q1: int = 0
    q2: int = 0
    # Fast Flexible Paxos (protocols/fastpaxos): fast-round quorum size.
    # 0 means the classic ceil(3n/4).  Safe iff ALSO q1 + 2*q_fast > 2*n_acc
    # (a phase-1 quorum must see a majority of any two fast quorums'
    # intersection); unsafe triples are bug-injection modes.
    q_fast: int = 0
    # Multi-Paxos leader lease (ticks without chosen-count progress before
    # followers suspect the leader / a leader demotes itself)
    lease_len: int = 24
    # Multi-Paxos long-log mode (SURVEY.md §6.7): total GLOBAL log length to
    # replicate through the sliding window of ``SimConfig.log_len`` slots
    # (decided prefixes compact out at chunk boundaries —
    # ``protocols.multipaxos.compact_mp``).  0 = plain bounded-log mode
    # (window IS the whole log; bit-identical to the pre-long-log build).
    log_total: int = 0


@struct.dataclass
class FaultPlan:
    """Per-run static fault schedule (device arrays, shard with the state)."""

    crash_start: jnp.ndarray  # (A, I) int32 tick; NEVER if no crash
    crash_end: jnp.ndarray  # (A, I) int32 tick; NEVER if crash is permanent
    equivocate: jnp.ndarray  # (A, I) bool
    pcrash_start: jnp.ndarray  # (P, I) int32 — proposer (leader) crash window
    pcrash_end: jnp.ndarray  # (P, I) int32
    part_start: jnp.ndarray  # (I,) int32 — partition window; NEVER if none
    part_end: jnp.ndarray  # (I,) int32
    aside: jnp.ndarray  # (A, I) bool — acceptor's side of the cut
    pside: jnp.ndarray  # (P, I) bool — proposer's side of the cut

    @classmethod
    def none(cls, n_inst: int, n_acc: int, n_prop: int = 1) -> "FaultPlan":
        return cls(
            crash_start=jnp.full((n_acc, n_inst), NEVER, jnp.int32),
            crash_end=jnp.full((n_acc, n_inst), NEVER, jnp.int32),
            equivocate=jnp.zeros((n_acc, n_inst), jnp.bool_),
            pcrash_start=jnp.full((n_prop, n_inst), NEVER, jnp.int32),
            pcrash_end=jnp.full((n_prop, n_inst), NEVER, jnp.int32),
            part_start=jnp.full((n_inst,), NEVER, jnp.int32),
            part_end=jnp.full((n_inst,), NEVER, jnp.int32),
            aside=jnp.zeros((n_acc, n_inst), jnp.bool_),
            pside=jnp.zeros((n_prop, n_inst), jnp.bool_),
        )

    @classmethod
    def sample(
        cls,
        key: jax.Array,
        cfg: FaultConfig,
        n_inst: int,
        n_acc: int,
        n_prop: int = 1,
    ) -> "FaultPlan":
        k_crash, k_eq, kp, k_part, k_side = jax.random.split(key, 5)

        def windows(k, shape, p):
            k1, k2, k3 = jax.random.split(k, 3)
            crashes = jax.random.uniform(k1, shape) < p
            start = jax.random.randint(k2, shape, 0, max(cfg.crash_max_start, 1))
            length = jax.random.randint(k3, shape, 1, max(cfg.crash_max_len, 1) + 1)
            c_start = jnp.where(crashes, start, NEVER)
            c_end = jnp.where(
                crashes & (not cfg.crash_forever),
                # Guard overflow: NEVER + length would wrap.
                jnp.minimum(start + length, NEVER - 1),
                NEVER,
            )
            return c_start, c_end

        crash_start, crash_end = windows(k_crash, (n_acc, n_inst), cfg.p_crash)
        pcrash_start, pcrash_end = windows(kp, (n_prop, n_inst), cfg.p_crash_prop)
        equivocate = jax.random.uniform(k_eq, (n_acc, n_inst)) < cfg.p_equiv

        kp1, kp2, kp3 = jax.random.split(k_part, 3)
        parts = jax.random.uniform(kp1, (n_inst,)) < cfg.p_part
        pstart = jax.random.randint(kp2, (n_inst,), 0, max(cfg.part_max_start, 1))
        plen = jax.random.randint(kp3, (n_inst,), 1, max(cfg.part_max_len, 1) + 1)
        part_start = jnp.where(parts, pstart, NEVER)
        part_end = jnp.where(parts, jnp.minimum(pstart + plen, NEVER - 1), NEVER)
        ka, kpr = jax.random.split(k_side)
        aside = jax.random.uniform(ka, (n_acc, n_inst)) < 0.5
        pside = jax.random.uniform(kpr, (n_prop, n_inst)) < 0.5
        return cls(
            crash_start=crash_start,
            crash_end=crash_end,
            equivocate=equivocate,
            pcrash_start=pcrash_start,
            pcrash_end=pcrash_end,
            part_start=part_start,
            part_end=part_end,
            aside=aside,
            pside=pside,
        )

    def alive(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(A, I) bool: acceptor is up at ``tick``."""
        return ~((self.crash_start <= tick) & (tick < self.crash_end))

    def link_ok(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(P, A, I) bool: the proposer<->acceptor link delivers at ``tick``.

        False only inside the instance's partition window for pairs on
        opposite sides of the cut; in-flight messages are not dropped, they
        stall until the partition heals (delivery masks AND with this).
        """
        cut = (self.part_start <= tick) & (tick < self.part_end)  # (I,)
        same = self.pside[:, None] == self.aside[None]  # (P, A, I)
        return same | ~cut[None, None]

    def prop_alive(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(P, I) bool: proposer is up at ``tick``."""
        return ~((self.pcrash_start <= tick) & (tick < self.pcrash_end))

    def recovering(self, tick: jnp.ndarray) -> jnp.ndarray:
        """(A, I) bool: acceptor comes back up exactly at ``tick`` (for amnesia)."""
        return self.crash_end == tick
