"""Fault-tolerant sharded fuzzing fleet (ROADMAP item 3).

A long-lived fuzzing service built from the pieces the repo already
trusts: the shared soak worker loop executes campaigns (`harness.soak`),
the corpus journal and coverage union are wall-clock-free and mergeable
(`fuzz.corpus`, `obs.coverage.union_hex`), and campaigns are
deterministic in (config, seed, plan) — so worker loss is recoverable by
EXACT REPLAY, and the whole fleet's output is byte-identical to an
uninterrupted run's.  Three layers:

- ``queue``: a durable file-backed campaign queue — atomic-rename
  enqueue/claim, lease-based ownership with heartbeat renewal, expired-
  lease reclaim so a dead worker's campaign is re-dispatched.
- ``worker``: one worker process — claims campaign records, runs them
  through ``soak()`` with a per-record campaign source, journals
  per-seed progress crash-safely, and resumes a reclaimed record from
  its last durable line.
- ``coordinator``: spawns/monitors N workers, reclaims expired leases,
  respawns the dead, merges shard corpora and coverage (ordered by
  record, so the merge is schedule-independent), dedups repros, and
  gates the run through ``bench-compare``.  ``--chaos`` SIGKILLs workers
  on a seeded schedule — the fleet's own fault injection.
"""
