"""Fleet coordinator — plan, spawn, monitor, reclaim, merge, gate.

The coordinator turns a campaign budget into durable queue records,
spawns N worker subprocesses (`paxos_tpu fleet-worker`), and runs the
monitor loop: reclaim expired leases (a dead worker's record goes back
to pending with ``attempt + 1``), respawn dead workers while work
remains, and — under ``--chaos`` — SIGKILL workers on a seeded schedule
drawn from the same pure-integer stream family as every other schedule
in the repo (`fuzz.mutate.SplitMix64`).

The merge is where the determinism contract pays off: shard results are
combined in CANONICAL RECORD ORDER (never completion order), coverage
unions OR together (`obs.coverage.union_hex` is a mergeable Bloom
sketch), corpus journals replay-append with dedup by (seed,
atoms_digest) (`fuzz.corpus.merge_journals`), and shrunk repros dedup by
(config_fingerprint, seed).  Campaigns are deterministic in (config,
seed, plan), so however many workers died and however leases bounced,
the merged journal digest and union_hex are byte-identical to an
uninterrupted run's — chaos mode exists to keep proving that.

``bench-compare`` runs as the fleet's continuous regression gate
(`obs.perf.compare_benches` against the committed baseline), so a fleet
that finishes its budget on a slowed-down build still fails loudly.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from typing import Any, Callable, Optional

from paxos_tpu.fleet.queue import CampaignQueue
from paxos_tpu.fuzz.mutate import SplitMix64
from paxos_tpu.harness.retry import run_with_retries

# Chaos kill-schedule stream fold (registry idiom: a fixed lane no other
# stream uses).
_CHAOS_FOLD = 0xC4A5


def plan_records(
    *,
    mode: str,
    config: str,
    n_inst: Optional[int],
    fault: "list[str]",
    seed: int,
    records: int,
    seeds_per_record: int,
    ticks_per_seed: int,
    chunk: int,
    coverage_words: int,
    engine: str = "xla",
    seed_stride: int = 10_000,
    rng_seed: int = 0,
    campaigns_per_record: int = 8,
    seed_entries: int = 2,
    mutations: int = 2,
    energy_max: int = 4,
    workload: "Optional[str]" = None,
    workload_rate: float = 0.05,
    slo_p99: int = 0,
) -> "list[dict]":
    """Partition a fleet budget into campaign records.

    Soak mode: record ``i`` owns the contiguous seed range
    ``[seed + i*seeds_per_record, ...)`` — together exactly the rotating
    seed schedule one big soak would run.  Fuzz mode: record ``i`` is an
    independent guided-fuzzing shard rooted at ``seed + i*seed_stride``
    (disjoint seed spaces) with mutation stream ``rng_seed + i`` —
    shards explore independently and the corpora merge.
    """
    out = []
    for i in range(records):
        rec: dict = {
            "campaign": i,
            "mode": mode,
            "config": config,
            "n_inst": n_inst,
            "fault": list(fault),
            "ticks_per_seed": ticks_per_seed,
            "chunk": chunk,
            "coverage_words": coverage_words,
            "engine": engine,
            "attempt": 0,
        }
        if workload:
            # Client-workload plane per record: every shard runs the same
            # mix, so per-seed slo_p99_ticks gauges land in the sampled
            # series and the slo_degradation trend detector covers the
            # fleet.
            rec |= {
                "workload": workload,
                "workload_rate": workload_rate,
                "slo_p99": slo_p99,
            }
        if mode == "fuzz":
            rec |= {
                "seed": seed + i * seed_stride,
                "rng_seed": rng_seed + i,
                "campaigns": campaigns_per_record,
                "seed_entries": seed_entries,
                "mutations": mutations,
                "energy_max": energy_max,
            }
        else:
            rec |= {
                "seed": seed + i * seeds_per_record,
                "seeds": seeds_per_record,
            }
        out.append(rec)
    return out


def chaos_kill_ordinals(
    chaos_seed: int, kills: int, n_records: int
) -> "set[int]":
    """Which claim events (by observation ordinal) get a SIGKILL.

    Drawn from the registered pure-integer stream — same seed, same
    schedule, every run.  Determinism of the MERGED RESULT never depends
    on which claims these ordinals land on (that varies with worker
    interleaving); the seeded schedule makes chaos runs repeatable in
    *shape*, and the recovery contract makes them identical in *output*.
    """
    stream = SplitMix64(chaos_seed).fork(_CHAOS_FOLD)
    out: "set[int]" = set()
    want = min(kills, n_records)
    while len(out) < want:
        out.add(stream.below(n_records))
    return out


def merge_results(results: "list[dict]") -> dict:
    """Merge shard results in canonical record order (see module doc)."""
    from paxos_tpu.fuzz.corpus import merge_journals

    ordered = sorted(results, key=lambda r: r["campaign"])
    union = 0
    bits_total = 0
    rounds = 0
    seeds = 0
    resumed = 0
    violations = 0
    torn_tails = 0
    retried = 0
    violating: "list[int]" = []
    journals = []
    repros: "dict[tuple, dict]" = {}
    repro_dups = 0
    for r in ordered:
        union |= int(r.get("union_hex", "0"), 16)
        bits_total = max(bits_total, int(r.get("bits_total", 0)))
        rounds += int(r.get("rounds", 0))
        seeds += int(r.get("seeds", 0))
        resumed += int(r.get("resumed_seeds", 0))
        violations += int(r.get("violations", 0))
        violating += list(r.get("violating_seeds", []))
        torn_tails += int(bool(r.get("torn_tail")))
        retried += int(r.get("attempt", 0))
        if r.get("journal") is not None:
            journals.append(r["journal"])
        repro = r.get("repro")
        if repro is not None:
            key = (repro.get("config_fingerprint"), repro.get("seed"))
            if key in repros:
                repro_dups += 1
            else:
                repros[key] = repro
    out: dict = {
        "records": len(ordered),
        "rounds": rounds,
        "seeds": seeds,
        "resumed_seeds": resumed,
        "violations": violations,
        "violating_seeds": sorted(violating),
        "union_hex": f"{union:x}",
        "coverage": {
            "bits_set": bin(union).count("1"),
            "bits_total": bits_total,
            "saturation": round(
                bin(union).count("1") / max(bits_total, 1), 6
            ),
            "union_hex": f"{union:x}",
        },
        "torn_tails": torn_tails,
        "campaigns_retried": retried,
        "repros": sorted(
            repros.values(),
            key=lambda x: (x.get("config_fingerprint") or "",
                           x.get("seed", 0)),
        ),
        "repro_dedup": repro_dups,
        "merge_dedup": 0,
    }
    if journals:
        merged = merge_journals(journals)
        out["journal_digest"] = merged["digest"]
        out["journal_entries"] = merged["entries"]
        out["merge_dedup"] = merged["dedup"]
        out["journal_events"] = merged["events"]
    return out


def bench_gate(
    baseline: str,
    fresh: Optional[str] = None,
    tolerance: float = 0.10,
    noise_k: float = 3.0,
) -> dict:
    """The fleet's continuous regression gate: compare_benches on the
    committed baseline (fresh=None is the self-compare sanity leg, which
    must pass — same contract as ``bench-compare`` without ``--fresh``)."""
    from paxos_tpu.obs import perf as perf_mod

    def load(path):
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, list) else [data]

    try:
        base_rows = load(baseline)
        fresh_rows = base_rows if fresh is None else load(fresh)
    except (OSError, json.JSONDecodeError) as e:
        return {"ok": False, "error": str(e)}
    result = perf_mod.compare_benches(
        base_rows, fresh_rows, tolerance=tolerance, noise_k=noise_k
    )
    return {
        "ok": bool(result["compared"]) and not result["regressions"],
        "compared": result["compared"],
        "regressions": result["regressions"],
        "baseline": baseline,
        "fresh": fresh or baseline,
    }


def _spawn_worker(
    root, worker_id: str, args_ns, say
) -> subprocess.Popen:
    """One worker subprocess, dispatched through the shared retry policy
    (a transient fork/pipe failure must not kill the whole fleet run)."""
    cmd = [
        sys.executable, "-m", "paxos_tpu",
        "--platform", getattr(args_ns, "platform", "default"),
        "fleet-worker",
        "--dir", str(root),
        "--worker-id", worker_id,
        "--lease-s", str(args_ns.lease_s),
        "--poll-s", str(args_ns.poll_s),
        "--hold-s", str(args_ns.hold_s),
        "--sample-every", str(getattr(args_ns, "sample_every", 0)),
    ]
    proc, _ = run_with_retries(
        lambda: subprocess.Popen(cmd, stdout=subprocess.DEVNULL),
        say, retries=2, backoff_s=0.2, retry_on=(OSError,),
        describe="worker dispatch error",
    )
    say(f"spawned {worker_id} (pid {proc.pid})")
    return proc


def run_fleet(
    records: "list[dict]",
    root,
    args_ns,
    *,
    log: Optional[Callable[[str], None]] = None,
    on_tick: Optional[Callable[[dict], None]] = None,
) -> "tuple[dict, int]":
    """Run one fleet to completion; returns (report, exit_code).

    Exit codes mirror the CLI family: 0 clean, 1 operational failure
    (budget not completed before ``--timeout-s``, unusable bench gate
    inputs), 2 safety violations or a bench regression.
    """
    say = log or (lambda s: None)
    q = CampaignQueue(root)
    for rec in records:
        q.enqueue(rec)
    n_records = len(records)
    n_workers = int(args_ns.workers)

    from paxos_tpu.parallel.mesh import partition_devices

    device_plan = [len(s) for s in partition_devices(n_workers)]

    chaos = bool(getattr(args_ns, "chaos", False))
    kill_set = (
        chaos_kill_ordinals(
            int(args_ns.chaos_seed), int(args_ns.chaos_kills), n_records
        )
        if chaos else set()
    )
    if chaos:
        say(f"chaos: kill schedule (claim ordinals) = {sorted(kill_set)}")

    procs: "dict[str, subprocess.Popen]" = {}
    spawned = 0
    t0 = time.time()
    # The unified-timeline capture: coordinator-observed fleet events
    # (spawn/claim/SIGKILL/reclaim/lease-renew/respawn), lease-held
    # windows as spans, and ~1 Hz gauge snapshots.  Pure host-side list
    # appends; obs.export.fleet_chrome_trace renders it.
    timeline: dict = {"t0": t0, "instants": [], "spans": [], "gauges": []}
    open_spans: "dict[tuple, dict]" = {}
    lease_expiry: "dict[tuple, float]" = {}

    def instant(name: str, worker=None, **args) -> None:
        ev: dict = {"t": time.time(), "name": name}
        if worker is not None:
            ev["worker"] = worker
        if args:
            ev["args"] = args
        timeline["instants"].append(ev)

    def spawn(tag: str) -> None:
        nonlocal spawned
        wid = f"w{spawned}{tag}"
        procs[wid] = _spawn_worker(root, wid, args_ns, say)
        spawned += 1
        instant("respawn" if tag else "spawn", worker=wid)

    for _ in range(n_workers):
        spawn("")

    deadline = t0 + float(args_ns.timeout_s)
    claims_seen: "set[tuple]" = set()
    kills_done = 0
    workers_killed: "set[str]" = set()
    leases_reclaimed = 0
    leases_expired = 0
    leases_held_peak = 0
    workers_dead = 0
    last_emit = 0.0

    def gauges() -> dict:
        alive = sum(1 for p in procs.values() if p.poll() is None)
        return {
            "workers": n_workers,
            "workers_alive": alive,
            "workers_dead": workers_dead,
            "workers_spawned": spawned,
            "queue_depth": q.pending_count(),
            "records_total": n_records,
            "records_done": q.done_count(),
            "leases_held_peak": leases_held_peak,
            "leases_expired": leases_expired,
            "leases_reclaimed": leases_reclaimed,
        }

    completed = False
    while time.time() < deadline:
        if q.done_count() >= n_records:
            completed = True
            break
        now = time.time()
        # 1. Chaos: watch for new claims; kill on the seeded ordinals.
        leases = q.leases()
        leases_held_peak = max(leases_held_peak, len(leases))
        for key in [k for k in open_spans if k[0] not in leases]:
            # Lease gone (completed or reclaimed) — close its span.
            open_spans.pop(key)["t_end"] = now
            lease_expiry.pop(key, None)
        for rec_id in sorted(leases):
            lease = leases[rec_id]
            key = (rec_id, lease.get("worker"), lease.get("attempt", 0))
            expires = float(lease.get("expires", 0.0))
            if key in claims_seen:
                if expires > lease_expiry.get(key, expires):
                    instant("lease_renew", worker=key[1], record=rec_id)
                lease_expiry[key] = expires
                continue
            ordinal = len(claims_seen)
            claims_seen.add(key)
            lease_expiry[key] = expires
            wid = lease.get("worker")
            instant("claim", worker=wid, record=rec_id,
                    attempt=key[2], ordinal=ordinal)
            span = {
                "worker": wid, "record": rec_id, "attempt": key[2],
                "t_start": now, "t_end": None,
            }
            open_spans[key] = span
            timeline["spans"].append(span)
            if (chaos and ordinal in kill_set
                    and kills_done < int(args_ns.chaos_kills)
                    and wid in procs and procs[wid].poll() is None):
                say(f"chaos: SIGKILL {wid} (claim #{ordinal} = {rec_id})")
                procs[wid].kill()
                workers_killed.add(wid)
                kills_done += 1
                instant("sigkill", worker=wid, record=rec_id,
                        ordinal=ordinal)
        # 2. Reclaim expired leases (the recovery path).
        reclaimed = q.reclaim_expired(now)
        if reclaimed:
            leases_expired += len(reclaimed)
            leases_reclaimed += len(reclaimed)
            say(f"reclaimed expired leases: {', '.join(reclaimed)}")
            for rec_id in reclaimed:
                instant("reclaim", record=rec_id)
        # 3. Respawn dead workers while work remains.
        for wid, proc in list(procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del procs[wid]
            if rc != 0 or wid in workers_killed:
                workers_dead += 1
            if (q.pending_count() + q.claimed_count()) > 0:
                say(f"worker {wid} exited (rc {rc}) with work remaining; "
                    "respawning")
                spawn("r")
        if now - last_emit >= 1.0:
            last_emit = now
            g = gauges()
            timeline["gauges"].append({"t": now, "gauges": g})
            if on_tick is not None:
                on_tick(g)
        time.sleep(float(args_ns.poll_s))
    else:
        completed = q.done_count() >= n_records

    # Drain: workers exit on their own once the queue is empty.
    for wid, proc in procs.items():
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            say(f"worker {wid} did not exit; terminating")
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()

    t_end = time.time()
    for span in open_spans.values():
        span["t_end"] = t_end
    timeline["gauges"].append({"t": t_end, "gauges": gauges()})

    results = q.results()
    merged = merge_results(list(results.values())) if results else {}
    fleet_stats = gauges() | {
        "campaigns_retried": merged.get("campaigns_retried", 0),
        "merge_dedup": merged.get("merge_dedup", 0),
        "torn_tails": (
            merged.get("torn_tails", 0) + q.torn_records
        ),
        "resumed_seeds": merged.get("resumed_seeds", 0),
    }
    report: dict = {
        "metric": "fleet",
        "mode": records[0]["mode"] if records else "soak",
        "completed": completed,
        "device_plan": device_plan,
        "fleet": fleet_stats,
        "seconds": round(time.time() - t0, 2),
    }
    # The merged journal events are working data for tests/tools, not
    # report noise — summarize in the report, keep digests.
    merged_public = {
        k: v for k, v in merged.items() if k != "journal_events"
    }
    report |= merged_public
    if chaos:
        report["chaos"] = {
            "kills_planned": sorted(kill_set),
            "kills_done": kills_done,
            "workers_killed": sorted(workers_killed),
            "chaos_seed": int(args_ns.chaos_seed),
        }
    # Per-worker drill-down: what each worker id actually delivered.
    report["workers"] = worker_stats(list(results.values()))

    rc = 0
    if not completed:
        say(f"fleet incomplete: {q.done_count()}/{n_records} records done "
            f"at timeout")
        rc = 1
    if merged.get("violations"):
        rc = 2

    # Observatory: merge per-worker time-series journals into the
    # canonical fleet series and run the trend gate over the raw rows.
    # Auto-armed — if no worker journaled (sampling off), nothing runs.
    raw_rows = _collect_series(q, say)
    if raw_rows:
        from paxos_tpu.obs.timeseries import (
            compare_series,
            merge_series,
            write_series,
        )

        merged_series = merge_series([raw_rows])
        series_path = q.root / "merged_series.jsonl"
        write_series(series_path, merged_series)
        report["series"] = {
            "samples": merged_series["samples"],
            "dedup": merged_series["dedup"],
            "digest": merged_series["digest"],
            "workers": merged_series["workers"],
            "path": str(series_path),
        }
        gate = compare_series(raw_rows)
        report["series_gate"] = gate
        if not gate["ok"]:
            for f in gate["findings"]:
                say(f"trend gate: {f['kind']} — worker {f['worker']} "
                    f"record {f['record']}")
            rc = max(rc, 2)

    # Corpus lineage roll-up (fuzz mode: the merged journal exists).
    if merged.get("journal_events"):
        from paxos_tpu.fuzz.lineage import build_lineage, lineage_summary

        report["lineage"] = lineage_summary(
            build_lineage(merged["journal_events"])
        )

    corpus_out = getattr(args_ns, "corpus_out", None)
    if corpus_out and merged.get("journal_events") is not None:
        _write_journal(corpus_out, merged["journal_events"],
                       merged["journal_digest"])
        report["corpus_out"] = str(corpus_out)
        say(f"merged corpus journal -> {corpus_out}")

    timeline_out = getattr(args_ns, "timeline", None)
    if timeline_out:
        from paxos_tpu.obs.export import fleet_chrome_trace

        trace = fleet_chrome_trace(timeline, raw_rows, meta={
            "metric": "fleet", "records": n_records,
            "workers": n_workers, "chaos": chaos,
        })
        with open(timeline_out, "w") as fh:
            json.dump(trace, fh)
        report["timeline"] = {
            "path": str(timeline_out),
            "events": len(trace["traceEvents"]),
        }
        say(f"fleet timeline -> {timeline_out}")

    baseline = getattr(args_ns, "bench_baseline", None)
    if baseline:
        gate = bench_gate(baseline)
        report["bench_gate"] = gate
        if "error" in gate:
            rc = max(rc, 1)
        elif not gate["ok"]:
            say("bench gate: regression against the committed baseline")
            rc = max(rc, 2)
    return report, rc


def worker_stats(results: "list[dict]") -> dict:
    """Aggregate shard results by the worker that completed them."""
    out: "dict[str, dict]" = {}
    for r in sorted(results, key=lambda r: r.get("campaign", 0)):
        w = str(r.get("worker", "?"))
        s = out.setdefault(w, {
            "records": 0, "seeds": 0, "rounds": 0, "violations": 0,
            "resumed_seeds": 0,
        })
        s["records"] += 1
        s["seeds"] += int(r.get("seeds", 0))
        s["rounds"] += int(r.get("rounds", 0))
        s["violations"] += int(r.get("violations", 0))
        s["resumed_seeds"] += int(r.get("resumed_seeds", 0))
    return dict(sorted(out.items()))


def _collect_series(q: CampaignQueue, say) -> "list[dict]":
    """Load every worker time-series journal under the queue root.

    Sorted filename order (deterministic), torn tails tolerated per the
    journal contract, unreadable journals skipped loudly — observability
    must never take the fleet down with it.
    """
    from paxos_tpu.obs.timeseries import load_series

    rows: "list[dict]" = []
    for path in sorted((q.root / "series").glob("*.jsonl")):
        try:
            loaded = load_series(path)
        except (OSError, ValueError) as e:
            say(f"series journal {path.name} unreadable ({e}); skipping")
            continue
        if loaded["torn_tail"]:
            say(f"series journal {path.name}: torn tail dropped")
        rows.extend(loaded["rows"])
    return rows


def _write_journal(path, events: "list[dict]", digest: str) -> None:
    """Write the merged corpus journal (digest line last) atomically."""
    import os

    from paxos_tpu.fuzz.corpus import event_line

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for e in events:
            f.write(event_line(e) + "\n")
        f.write(event_line({"event": "digest", "sha256": digest}) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
