"""Durable file-backed campaign queue with lease-based claims.

The queue is a directory state machine — every transition is one atomic
``rename`` on the same filesystem, so any observer (worker, coordinator,
a human with ``ls``) sees each record in exactly one state:

    pending/c00003.json    enqueued, claimable
    claimed/c00003.json    owned by a worker (leases/c00003.json says who)
    done/c00003.json       completed (results/c00003.json has the shard
                           result, progress/c00003.jsonl the seed journal)

``series/<worker>.jsonl`` sits beside the record states: one append-only
metrics time-series journal per worker process (``obs.timeseries``),
keyed by worker rather than record because it spans every record the
worker runs.

Ownership is a LEASE, not a lock: a claim writes ``{worker, expires,
attempt}`` and the worker must renew before ``expires`` (a heartbeat
thread in ``fleet.worker``).  A worker that dies — SIGKILL, OOM,
preemption — simply stops renewing; the coordinator's
:meth:`CampaignQueue.reclaim_expired` moves the record back to pending
with ``attempt + 1`` and someone else re-runs it.  Campaigns are
deterministic in (config, seed, plan), so the re-run produces the same
bytes the dead worker would have — recovery is exact replay, which is
what lets the fleet promise a merged output byte-identical to an
uninterrupted run's.

Every time-dependent method takes ``now`` EXPLICITLY (callers pass
``time.time()``): lease logic has no hidden clock, so tests drive the
whole expiry/reclaim state machine with plain floats.  File reads
tolerate torn JSON (a crash mid-enqueue) by quarantining, mirroring the
corpus journal's torn-tail contract.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Optional

from paxos_tpu.harness.retry import run_with_retries


class LeaseLost(RuntimeError):
    """The caller's lease no longer exists or belongs to someone else —
    the record was reclaimed out from under a worker presumed dead.  The
    worker must abandon the record (its replacement owns it now)."""


_DIRS = ("pending", "claimed", "done", "leases", "results", "progress",
         "series", "tmp")


class CampaignQueue:
    """One fleet's queue rooted at a directory (see module docstring)."""

    def __init__(self, root, io_retries: int = 2,
                 io_backoff_s: float = 0.05) -> None:
        self.root = pathlib.Path(root)
        self.io_retries = io_retries
        self.io_backoff_s = io_backoff_s
        self.torn_records = 0  # unreadable record files quarantined
        self._tmp_seq = 0
        for d in _DIRS:
            (self.root / d).mkdir(parents=True, exist_ok=True)

    # -- paths -----------------------------------------------------------
    def _p(self, state: str, rec_id: str) -> pathlib.Path:
        return self.root / state / f"{rec_id}.json"

    def progress_path(self, rec_id: str) -> pathlib.Path:
        return self.root / "progress" / f"{rec_id}.jsonl"

    def series_path(self, worker_id: str) -> pathlib.Path:
        """Per-WORKER metrics time-series journal (one per process
        lifetime, append-only — see ``obs.timeseries``)."""
        return self.root / "series" / f"{worker_id}.jsonl"

    # -- primitives ------------------------------------------------------
    def _write(self, payload: dict, dest: pathlib.Path) -> None:
        """Atomic durable write: temp file + fsync + rename, retried on
        transient filesystem errors through the shared retry policy."""
        self._tmp_seq += 1
        tmp = self.root / "tmp" / f"{dest.name}.{os.getpid()}.{self._tmp_seq}"

        def attempt():
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True,
                          separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dest)

        run_with_retries(
            attempt, lambda s: None, retries=self.io_retries,
            backoff_s=self.io_backoff_s, retry_on=(OSError,),
            describe="queue write error",
        )

    def _read(self, path: pathlib.Path) -> Optional[dict]:
        """None on missing or torn (a crash mid-enqueue) — never raises
        on content."""
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError:
            return None

    # -- lifecycle -------------------------------------------------------
    def enqueue(self, record: dict) -> str:
        """Durably publish one campaign record; returns its id.

        The id is the zero-padded campaign ordinal so every directory
        listing is already in canonical merge order.
        """
        rec_id = f"c{int(record['campaign']):05d}"
        self._write(record, self._p("pending", rec_id))
        return rec_id

    def claim(self, worker: str, now: float,
              lease_s: float) -> "Optional[tuple[str, dict]]":
        """Claim the first pending record; None when nothing is claimable.

        The claim IS the rename pending -> claimed: losers of a race get
        ``FileNotFoundError`` and move on.  The winner then writes its
        lease.  (A crash between the two leaves a claimed record with no
        lease — ``reclaim_expired`` treats that as already expired.)
        """
        for path in sorted((self.root / "pending").glob("*.json")):
            rec_id = path.stem
            dest = self._p("claimed", rec_id)
            try:
                os.rename(path, dest)
            except FileNotFoundError:
                continue  # another worker won this record
            record = self._read(dest)
            if record is None:
                # Torn enqueue: quarantine rather than crash-loop every
                # future claimer on the same bytes.
                self.torn_records += 1
                os.replace(dest, self.root / "tmp" / f"{rec_id}.torn")
                continue
            self._write(
                {"worker": worker, "expires": now + lease_s,
                 "attempt": int(record.get("attempt", 0))},
                self._p("leases", rec_id),
            )
            return rec_id, record
        return None

    def renew(self, rec_id: str, worker: str, now: float,
              lease_s: float) -> None:
        """Heartbeat: extend the caller's lease; LeaseLost if reclaimed."""
        lease = self._read(self._p("leases", rec_id))
        if lease is None or lease.get("worker") != worker:
            owner = "gone" if lease is None else (
                f"owned by {lease.get('worker')}"
            )
            raise LeaseLost(f"{rec_id}: lease {owner}")
        self._write(
            dict(lease, expires=now + lease_s), self._p("leases", rec_id)
        )

    def complete(self, rec_id: str, worker: str, result: dict) -> None:
        """Publish the shard result and retire the record.

        Result first (atomic), then the record moves claimed -> done,
        then the lease goes away — so ``done`` implies the result file
        exists, and a crash anywhere in between is recovered by reclaim
        + re-run (the re-run rewrites the identical result).
        """
        lease = self._read(self._p("leases", rec_id))
        if lease is None or lease.get("worker") != worker:
            raise LeaseLost(f"{rec_id}: completed after lease loss")
        self._write(result, self._p("results", rec_id))
        os.replace(self._p("claimed", rec_id), self._p("done", rec_id))
        try:
            os.unlink(self._p("leases", rec_id))
        except FileNotFoundError:
            pass

    def reclaim_expired(self, now: float) -> "list[str]":
        """Move every claimed record whose lease is missing or expired
        back to pending with ``attempt + 1``; returns the reclaimed ids.

        Coordinator-only by design: one reclaimer means a slow-but-alive
        worker is told exactly once (its next ``renew`` raises
        :class:`LeaseLost`) instead of racing N peers.  Write-then-unlink
        ordering: a crash mid-reclaim can duplicate the record across
        pending and claimed, never lose it — the next claim's rename
        simply overwrites the orphan.
        """
        out: list[str] = []
        for path in sorted((self.root / "claimed").glob("*.json")):
            rec_id = path.stem
            lease = self._read(self._p("leases", rec_id))
            if lease is not None and lease.get("expires", 0) > now:
                continue
            record = self._read(path)
            if record is None:
                self.torn_records += 1
                os.replace(path, self.root / "tmp" / f"{rec_id}.torn")
                continue
            record["attempt"] = int(record.get("attempt", 0)) + 1
            self._write(record, self._p("pending", rec_id))
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            try:
                os.unlink(self._p("leases", rec_id))
            except FileNotFoundError:
                pass
            out.append(rec_id)
        return out

    # -- queries ---------------------------------------------------------
    def _count(self, state: str) -> int:
        return len(list((self.root / state).glob("*.json")))

    def pending_count(self) -> int:
        return self._count("pending")

    def claimed_count(self) -> int:
        return self._count("claimed")

    def done_count(self) -> int:
        return self._count("done")

    def leases(self) -> "dict[str, dict]":
        """Current leases by record id (the coordinator's claim watch)."""
        out = {}
        for path in sorted((self.root / "leases").glob("*.json")):
            lease = self._read(path)
            if lease is not None:
                out[path.stem] = lease
        return out

    def results(self) -> "dict[str, dict]":
        """Shard results of DONE records, by record id, canonical order."""
        out = {}
        for path in sorted((self.root / "done").glob("*.json")):
            res = self._read(self._p("results", path.stem))
            if res is not None:
                out[path.stem] = res
        return out

    def record(self, rec_id: str) -> Optional[dict]:
        """The record dict wherever it currently lives (else None)."""
        for state in ("pending", "claimed", "done"):
            rec = self._read(self._p(state, rec_id))
            if rec is not None:
                return rec
        return None
