"""One fleet worker — claim, execute, journal, resume.

A worker is the shared soak loop (`harness.soak`) wrapped in lease
discipline.  It claims campaign RECORDS (a batch of campaigns: soak mode
runs ``seeds`` rotating seeds, fuzz mode runs one whole ``GuidedSource``
budget), heartbeats its lease from a background thread so a minutes-long
XLA compile can't starve the renewal, and writes two crash-safe
artifacts per record:

- ``progress/<id>.jsonl`` — one `fuzz.corpus.append_event` line per
  finalized seed (union_hex, violations), headed by the record's
  schedule-stream lineage (`harness.checkpoint.stream_id`).  A reclaimed
  soak record RESUMES seed-granular from the last durable line; the
  header guard (`checkpoint.check_stream`) discards progress written
  under a different stream instead of silently splicing two schedules.
- ``results/<id>.json`` — the shard result, written atomically by
  ``queue.complete``.

Recovery semantics by mode: soak records resume seed-granular (per-seed
coverage unions OR back together — the Bloom union is associative);
fuzz records are ATOMIC units — the guided feedback loop is sequential,
and re-running it from scratch is a byte-exact replay (the corpus
journal is wall-clock-free), so deterministic replay IS the recovery.
Either way the merged fleet output is byte-identical to an uninterrupted
run's.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Optional

from paxos_tpu.fleet.queue import CampaignQueue, LeaseLost
from paxos_tpu.harness.retry import run_with_retries


class WorkerPreempted(RuntimeError):
    """Raised by the in-process preemption hook (``stop_after_seeds``):
    the deterministic stand-in for SIGKILL that tier-1 recovery tests
    use — progress up to the hook is durable, nothing after it exists,
    exactly the state a killed worker leaves behind."""


# -- config reconstruction -----------------------------------------------

def build_cfg(record: dict):
    """Reconstruct the campaign config a record describes.

    Records carry the CLI vocabulary (config name + n_inst + fault
    override strings + seed), not a serialized config — the same
    reconstruction path ``cmd_soak``/``cmd_fuzz`` use, so a record is
    replayable by hand from its JSON.  Coverage is always on: the union
    sketch is what makes shard results mergeable.
    """
    from paxos_tpu.harness.cli import CONFIGS
    from paxos_tpu.harness.config import apply_fault_overrides
    from paxos_tpu.obs.coverage import CoverageConfig

    kw: dict = {"seed": int(record["seed"])}
    if record.get("n_inst"):
        kw["n_inst"] = int(record["n_inst"])
    cfg = CONFIGS[record["config"]](**kw)
    cfg = apply_fault_overrides(cfg, list(record.get("fault", [])))
    cfg = dataclasses.replace(
        cfg, coverage=CoverageConfig(
            words=int(record.get("coverage_words", 64))
        )
    )
    if record.get("workload"):
        from paxos_tpu.workload.generator import WorkloadConfig

        cfg = dataclasses.replace(cfg, workload=WorkloadConfig(
            mix=str(record["workload"]),
            rate=float(record.get("workload_rate", WorkloadConfig().rate)),
            slo_p99_ticks=int(record.get("slo_p99", 0)),
        ))
    return cfg


# -- per-record campaign source ------------------------------------------

class SeedListSource:
    """Campaign source over an explicit seed list — the fleet's
    resumable unit.  A reclaimed record re-runs ONLY the seeds missing
    from its progress journal; ``on_report`` fires per finalized
    campaign with the full report (union_hex included), which is where
    the progress line and the lease heartbeat happen."""

    def __init__(self, cfg, seeds: "list[int]",
                 on_report: Optional[Callable] = None) -> None:
        self.cfg = cfg
        self._seeds = list(seeds)
        self._i = 0
        self.on_report = on_report

    def next_campaign(self):
        from paxos_tpu.harness.soak import CampaignSpec

        if self._i >= len(self._seeds):
            return None
        spec = CampaignSpec(
            cfg=dataclasses.replace(self.cfg, seed=self._seeds[self._i])
        )
        self._i += 1
        return spec

    def feedback(self, spec, report, seed_rec) -> None:
        if self.on_report is not None:
            self.on_report(spec, report, seed_rec)


# -- progress journal ----------------------------------------------------

def _load_progress(path, stream: dict, fingerprint: str, say) -> dict:
    """Recover a record's durable per-seed progress.

    Tolerates a torn tail (`corpus.load_journal`); refuses — by
    discarding, recovery must recover — progress whose header stream or
    config fingerprint differs from the resuming record's
    (`checkpoint.check_stream` decides stream compatibility).
    Returns ``{"seeds": {seed: line}, "union": int, "violations": int,
    "violating": [...], "torn_tail": bool}``.
    """
    from paxos_tpu.fuzz.corpus import load_journal

    out = {"seeds": {}, "union": 0, "violations": 0, "violating": [],
           "torn_tail": False}
    try:
        loaded = load_journal(path)
    except FileNotFoundError:
        return out
    except ValueError as e:
        say(f"progress journal unreadable ({e}); re-running the record")
        return out
    out["torn_tail"] = loaded["torn_tail"]
    events = loaded["events"]
    if not events:
        return out
    header = events[0] if events[0].get("event") == "header" else None
    if header is not None:
        from paxos_tpu.harness.checkpoint import check_stream

        try:
            check_stream(header.get("stream"), stream, str(path))
        except ValueError:
            say("progress journal was written under a different schedule "
                "stream; discarding it and re-running the record")
            return dict(out, seeds={}, union=0, violations=0, violating=[])
        if header.get("fingerprint") not in (None, fingerprint):
            say("progress journal belongs to a different config "
                "fingerprint; discarding it")
            return dict(out, seeds={}, union=0, violations=0, violating=[])
    for e in events:
        if e.get("event") != "seed":
            continue
        out["seeds"][int(e["seed"])] = e
        out["union"] |= int(e.get("union_hex", "0"), 16)
        v = int(e.get("violations", 0))
        out["violations"] += v
        if v:
            out["violating"].append(int(e["seed"]))
    return out


# -- record execution ----------------------------------------------------

def run_record(
    queue: CampaignQueue,
    rec_id: str,
    record: dict,
    worker_id: str,
    *,
    log: Optional[Callable[[str], None]] = None,
    heartbeat: Optional[Callable[[], None]] = None,
    stop_after_seeds: Optional[int] = None,
    sampler=None,
) -> dict:
    """Execute one claimed record to a shard result (see module docstring).

    ``stop_after_seeds`` is the deterministic in-process preemption hook:
    after that many progress lines land durably, :class:`WorkerPreempted`
    raises — the record is left exactly as a SIGKILL would leave it.

    ``sampler`` is an ``obs.timeseries.SeriesSampler`` (or None = off);
    every finalized campaign writes one time-series row on an injected
    logical clock — the seed index for soak records, the campaign ordinal
    for fuzz records — BEFORE the progress line lands, so a crash between
    the two re-runs the seed and re-emits a byte-identical sample that
    merge dedup absorbs (the reverse order would lose the clock forever
    and break the chaos byte-identity contract).  The deterministic
    gauges (``worker_union_bits`` / ``worker_violations`` /
    ``worker_seeds`` / ``worker_rounds``) are cumulative per-record state
    seeded from resumed progress, so a resumed record samples exactly the
    values its uninterrupted twin would have at the same clock.
    """
    from paxos_tpu.fuzz.corpus import append_event
    from paxos_tpu.harness.checkpoint import stream_id
    from paxos_tpu.harness.soak import soak

    say = log or (lambda s: None)
    cfg = build_cfg(record)
    engine = record.get("engine", "xla")
    mode = record.get("mode", "soak")
    ticks = int(record["ticks_per_seed"])
    chunk = int(record["chunk"])
    stream = stream_id(cfg, engine)
    fingerprint = cfg.fingerprint()
    prog_path = queue.progress_path(rec_id)
    progress = _load_progress(prog_path, stream, fingerprint, say)
    if progress["torn_tail"]:
        say(f"{rec_id}: torn tail in progress journal (crash mid-append); "
            "resuming from the last durable line")

    base = {
        "record": rec_id,
        "campaign": int(record["campaign"]),
        "mode": mode,
        "worker": worker_id,
        "attempt": int(record.get("attempt", 0)),
        "engine": engine,
        "stream": stream,
        "config_fingerprint": fingerprint,
        "torn_tail": progress["torn_tail"],
    }

    prog_fh = open(prog_path, "a")
    try:
        if not progress["seeds"]:
            append_event(prog_fh, {
                "event": "header", "record": rec_id, "stream": stream,
                "fingerprint": fingerprint,
                "attempt": int(record.get("attempt", 0)),
            })
        emitted = {"n": 0}
        # Sampling context, configured per mode below: clock_of maps a
        # finalized campaign to its logical clock; cum is deterministic
        # cumulative per-record state (resume-seeded for soak).
        sample_ctx: dict = {
            "clock_of": None,
            "cum": {"union": 0, "violations": 0, "seeds": 0, "rounds": 0},
        }
        reg = None
        if sampler is not None:
            from paxos_tpu.harness.metrics import MetricsRegistry

            reg = MetricsRegistry()

        def on_report(spec, report, seed_rec):
            cov = report.get("coverage") or {}
            if reg is not None and sample_ctx["clock_of"] is not None:
                cum = sample_ctx["cum"]
                cum["union"] |= int(cov.get("union_hex", "0"), 16)
                cum["violations"] += int(report["violations"])
                cum["seeds"] += 1
                cum["rounds"] += spec.cfg.n_inst * ticks
                reg.gauge("worker_union_bits",
                          bin(cum["union"]).count("1"))
                reg.gauge("worker_violations", cum["violations"])
                reg.gauge("worker_seeds", cum["seeds"])
                reg.gauge("worker_rounds", cum["rounds"])
                # Workload-on records ride their campaign p99 into the
                # series so compare_series's slo_degradation detector
                # covers the fleet for free; a deterministic function of
                # (record, clock) like every other gauge.  Unserved
                # campaigns (-1) export nothing, mirroring ingest_slo.
                slo = report.get("slo")
                if slo is not None and slo["p99_ticks"] >= 0:
                    reg.gauge("slo_p99_ticks", slo["p99_ticks"])
                    reg.gauge("slo_queue_depth", slo["queue_depth"])
                sampler.sample(
                    record=rec_id,
                    attempt=int(record.get("attempt", 0)),
                    clock=sample_ctx["clock_of"](spec),
                    registry=reg,
                    wall={
                        "t": round(time.time(), 3),
                        "rps": seed_rec.get("rounds_per_sec"),
                    },
                )
            append_event(prog_fh, {
                "event": "seed", "seed": spec.cfg.seed,
                "union_hex": cov.get("union_hex", "0"),
                "violations": int(report["violations"]),
                "rounds": spec.cfg.n_inst * ticks,
            })
            if heartbeat is not None:
                heartbeat()
            emitted["n"] += 1
            if (stop_after_seeds is not None
                    and emitted["n"] >= stop_after_seeds):
                raise WorkerPreempted(
                    f"{rec_id}: preempted after {emitted['n']} seeds"
                )

        if mode == "fuzz":
            # Atomic unit: deterministic full replay IS the recovery —
            # the guided feedback loop is sequential, so a half-run
            # corpus can't be spliced; prior progress only tells us the
            # dead worker got partway.  The per-seed progress lines
            # still land (lease heartbeats + post-mortem visibility).
            from paxos_tpu.fuzz.schedule import FuzzParams, GuidedSource

            source = GuidedSource(
                cfg,
                FuzzParams(
                    campaigns=int(record["campaigns"]),
                    seed_entries=int(record.get("seed_entries", 2)),
                    mutations=int(record.get("mutations", 2)),
                    energy_max=int(record.get("energy_max", 4)),
                    rng_seed=int(record["rng_seed"]),
                ),
                ticks_per_seed=ticks,
                log=say,
            )
            inner = source.feedback

            def fuzz_feedback(spec, report, seed_rec):
                inner(spec, report, seed_rec)
                on_report(spec, report, seed_rec)

            source.feedback = fuzz_feedback
            # Fuzz clock = campaign ordinal within the (atomic) record;
            # a replayed attempt restarts at 0 and re-emits identical
            # rows, which merge dedup collapses.
            sample_ctx["clock_of"] = lambda spec: emitted["n"]
            report = soak(
                source.cfg,
                target_rounds=(
                    int(record["campaigns"]) * cfg.n_inst * ticks
                ),
                ticks_per_seed=ticks, chunk=chunk, engine=engine,
                log=say, campaigns=source,
            )
            union = int(
                (report.get("coverage") or {}).get("union_hex", "0"), 16
            )
            result = base | {
                "seeds": report["seeds"],
                "resumed_seeds": 0,
                "rounds": report["rounds"],
                "violations": report["violations"],
                "violating_seeds": report["violating_seeds"],
                "union_hex": f"{union:x}",
                "bits_total": 32 * cfg.coverage.words,
                "journal": source.corpus.events(),
                "journal_digest": source.corpus.digest(),
            }
            if report["violations"] and source.violating:
                result["repro"] = _shrink_repro(
                    source, ticks, chunk, engine, say
                )
            return result

        # Soak mode: seed-granular resume.
        first = int(record["seed"])
        all_seeds = [first + i for i in range(int(record["seeds"]))]
        remaining = [s for s in all_seeds if s not in progress["seeds"]]
        resumed = len(all_seeds) - len(remaining)
        if resumed:
            say(f"{rec_id}: resuming — {resumed}/{len(all_seeds)} seeds "
                "already durable in the progress journal")
        union = progress["union"]
        violations = progress["violations"]
        violating = list(progress["violating"])
        seeds_run = 0
        # Soak clock = seed index in the record's full seed list; the
        # cumulative gauges start from the resumed progress so clock k
        # always carries the union of seeds 0..k.
        sample_ctx["clock_of"] = (
            lambda spec: all_seeds.index(spec.cfg.seed)
        )
        sample_ctx["cum"] = {
            "union": progress["union"],
            "violations": progress["violations"],
            "seeds": resumed,
            "rounds": resumed * cfg.n_inst * ticks,
        }
        if remaining:
            source = SeedListSource(cfg, remaining, on_report=on_report)
            report = soak(
                cfg, target_rounds=0, ticks_per_seed=ticks, chunk=chunk,
                engine=engine, log=say, campaigns=source,
            )
            union |= int(
                (report.get("coverage") or {}).get("union_hex", "0"), 16
            )
            violations += report["violations"]
            violating += report["violating_seeds"]
            seeds_run = report["seeds"]
        return base | {
            "seeds": resumed + seeds_run,
            "resumed_seeds": resumed,
            "rounds": len(all_seeds) * cfg.n_inst * ticks,
            "violations": violations,
            "violating_seeds": sorted(violating),
            "union_hex": f"{union:x}",
            "bits_total": 32 * cfg.coverage.words,
        }
    finally:
        prog_fh.close()


def _shrink_repro(source, ticks: int, chunk: int, engine: str, say) -> dict:
    """Shrink the shard's first violating campaign (deterministic pick,
    like ``cmd_fuzz``) so the coordinator can dedup repros globally."""
    from paxos_tpu.harness.shrink import (
        exposure_annotation,
        margin_annotation,
        replay,
        shrink,
    )

    vcfg, vplan, eid = source.violating[0]
    say(f"violation in corpus entry {eid} (seed {vcfg.seed}); shrinking")
    result = shrink(
        vcfg, max_ticks=ticks, chunk=chunk, engine=engine, log=say,
        plan=vplan,
    )
    repro = {
        "entry": eid,
        "config_fingerprint": vcfg.fingerprint(),
        "seed": vcfg.seed,
    }
    if result is not None:
        repro |= {
            "replays": replay(vcfg, result),
            **result.to_json(),
            "margin": margin_annotation(vcfg, result),
            "exposure": exposure_annotation(vcfg, result),
        }
    return repro


# -- worker main loop ----------------------------------------------------

def work_loop(
    root,
    worker_id: str,
    *,
    lease_s: float = 15.0,
    poll_s: float = 0.5,
    hold_s: float = 0.0,
    log: Optional[Callable[[str], None]] = None,
    stop_after_seeds: Optional[int] = None,
    now_fn: Callable[[], float] = time.time,
    sample_every: int = 0,
) -> dict:
    """Claim-execute-complete until the queue drains; returns loop stats.

    ``sample_every`` > 0 turns on the metrics time-series: one
    ``obs.timeseries.SeriesSampler`` per worker process appending to
    ``series/<worker>.jsonl`` at that logical-clock cadence.  Off (the
    default) opens no file and writes nothing — default-off-is-free.

    The lease heartbeat runs in a DAEMON THREAD renewing every
    ``lease_s / 5`` — pure host I/O, nothing schedule-relevant — so a
    long XLA compile inside the first campaign cannot let the lease
    lapse.  Renewals go through the shared retry policy (transient
    filesystem errors); :class:`LeaseLost` is never retried — it means
    the coordinator declared this worker dead, and the only correct move
    is to abandon the record mid-flight and claim fresh work.

    ``hold_s`` pauses between claim and execution — the chaos window the
    coordinator's seeded SIGKILL schedule aims at.  The loop exits when
    pending AND claimed are both empty (other workers' in-flight records
    might yet be reclaimed, so a worker lingers while any claim exists).
    """
    say = log or (lambda s: None)
    q = CampaignQueue(root)
    stats = {"worker": worker_id, "records_done": 0, "leases_lost": 0}
    sampler = None
    series_fh = None
    if int(sample_every) > 0:
        from paxos_tpu.obs.timeseries import SeriesSampler

        series_fh = open(q.series_path(worker_id), "a")
        sampler = SeriesSampler(series_fh, worker_id,
                                every=int(sample_every))
    try:
        return _work_loop(
            q, worker_id, stats, say, sampler,
            lease_s=lease_s, poll_s=poll_s, hold_s=hold_s,
            stop_after_seeds=stop_after_seeds, now_fn=now_fn,
        )
    finally:
        if sampler is not None:
            stats["samples"] = sampler.samples
        if series_fh is not None:
            series_fh.close()


def _work_loop(
    q: CampaignQueue,
    worker_id: str,
    stats: dict,
    say,
    sampler,
    *,
    lease_s: float,
    poll_s: float,
    hold_s: float,
    stop_after_seeds: Optional[int],
    now_fn: Callable[[], float],
) -> dict:
    while True:
        claim = run_with_retries(
            lambda: q.claim(worker_id, now_fn(), lease_s),
            say, retries=2, backoff_s=poll_s, retry_on=(OSError,),
            describe="queue claim error",
        )[0]
        if claim is None:
            if q.pending_count() == 0 and q.claimed_count() == 0:
                return stats
            time.sleep(poll_s)
            continue
        rec_id, record = claim
        say(f"{worker_id}: claimed {rec_id} "
            f"(attempt {record.get('attempt', 0)})")
        stop = threading.Event()
        hb_state: dict = {"lost": None}

        def renew_once():
            q.renew(rec_id, worker_id, now_fn(), lease_s)

        def heartbeat():
            run_with_retries(
                renew_once, say, retries=2, backoff_s=0.05,
                retry_on=(OSError,), describe="lease renewal error",
            )

        def hb_loop():
            while not stop.wait(lease_s / 5.0):
                try:
                    heartbeat()
                except LeaseLost as e:
                    hb_state["lost"] = e
                    return

        thread = threading.Thread(target=hb_loop, daemon=True)
        thread.start()
        try:
            if hold_s:
                time.sleep(hold_s)  # chaos window
            result = run_record(
                q, rec_id, record, worker_id, log=say,
                heartbeat=heartbeat, stop_after_seeds=stop_after_seeds,
                sampler=sampler,
            )
            if hb_state["lost"] is not None:
                raise hb_state["lost"]
            q.complete(rec_id, worker_id, result)
            stats["records_done"] += 1
            say(f"{worker_id}: completed {rec_id}")
        except LeaseLost:
            stats["leases_lost"] += 1
            say(f"{worker_id}: lost lease on {rec_id}; abandoning it "
                "(its replacement owns the record now)")
        finally:
            stop.set()
            thread.join(timeout=2.0)
