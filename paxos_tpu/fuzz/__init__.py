"""Feedback-directed fuzzing — coverage-guided, exposure-weighted scheduling.

The fuzzer closes the loop the observer planes opened: coverage ``new_bits``
(obs.coverage, PR 8) says whether a campaign visited novel protocol states,
fault exposure (obs.exposure, PR 9) says whether its chaos actually touched
the protocol, and near-miss margins (obs.margin, PR 12) say how close it came
to a violation.  ``fuzz.corpus`` folds the three into one fitness number per
corpus entry, ``fuzz.mutate`` grows new entries by deterministic atom-level
mutations (the shrink machinery run in reverse), and ``fuzz.schedule`` assigns
energy AFL-style and drives the campaigns through the same soak worker loop
plain ``soak`` uses.

The fuzzer only chooses WHICH campaigns run, never how a campaign executes:
every device schedule for a given (config, seed, plan) is bit-identical to
the unguided build, and with fuzzing disabled nothing here is imported.
"""

from paxos_tpu.fuzz.corpus import (
    Corpus,
    CorpusEntry,
    atoms_digest,
    entry_classes,
    exposure_weight,
    fitness,
    margin_boost,
)
from paxos_tpu.fuzz.lineage import (
    build_lineage,
    lineage_summary,
    op_attribution,
    render_op_table,
    render_tree,
)
from paxos_tpu.fuzz.mutate import MUTATION_OPS, SplitMix64, mutate
from paxos_tpu.fuzz.schedule import FuzzParams, GuidedSource, campaign_config

__all__ = [
    "Corpus",
    "CorpusEntry",
    "atoms_digest",
    "entry_classes",
    "exposure_weight",
    "fitness",
    "margin_boost",
    "MUTATION_OPS",
    "SplitMix64",
    "mutate",
    "FuzzParams",
    "GuidedSource",
    "campaign_config",
    "build_lineage",
    "lineage_summary",
    "op_attribution",
    "render_op_table",
    "render_tree",
]
