"""Corpus — fitness-ranked (seed, atom-list) entries and their journal.

A corpus entry is everything needed to re-run its campaign exactly: the
campaign seed, the fault-plan atom list (the ``faults.injector`` codec —
JSON-stable, canonically ordered), any fault-knob overrides the mutator
applied, and the campaign config fingerprint recorded at dispatch.  Fitness
folds the three observer planes into one number:

    fitness = new_bits * exposure_weight * margin_boost

- ``new_bits`` — union bits this entry's campaign contributed against the
  soak loop's cross-seed Bloom union (obs.coverage): the novelty signal.
- ``exposure_weight`` — the mean effective/injected fraction over the fault
  classes this entry's atoms light (obs.exposure).  An entry whose lit
  classes are ALL vacuous (zero effective events) weighs 0 — vacuous chaos
  earns no energy, however many bits its baseline dynamics set.  An entry
  with no gray atoms (crash/equiv only, or none) weighs 1: those faults are
  applied unconditionally and need no exposure defense.
- ``margin_boost`` — 1 + 1/(1 + min_quorum_slack) in (1, 2]: campaigns that
  came within a vote of a safety violation (obs.margin) are worth mutating
  harder even when they soaked clean.

The journal is an append-only JSONL event stream (``add`` / ``feedback`` /
``retire``) with NO wall-clock fields, so two runs of the same fuzz command
produce byte-identical journals — ``digest()`` is the replay-determinism
pin the FUZZ_SMOKE gate compares.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Optional

# Atom kind -> exposure classes (obs.exposure.CLASSES) its fault events land
# in — the same map harness/shrink.py uses for repro annotation.  Crash and
# equivocation atoms are deliberately absent: they are applied
# unconditionally by every step function (no gating knob, no exposure
# counter), so they cannot be vacuous.
ATOM_CLASSES = {
    "partition": ("partition",),
    "flaky": ("drop", "dup"),
    "skew": ("timeout",),
    "delay": ("delay",),
}


def atoms_digest(atoms: list) -> str:
    """sha256 of the canonical JSON wire form of an atom list."""
    from paxos_tpu.faults.injector import canonical_atoms

    wire = json.dumps(
        canonical_atoms(atoms), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(wire.encode()).hexdigest()


def entry_classes(atoms: list) -> set:
    """The exposure classes an atom list's gray atoms light."""
    out: set = set()
    for atom in atoms:
        out.update(ATOM_CLASSES.get(atom["kind"], ()))
    return out


def exposure_weight(atoms: list, classes: Optional[dict]) -> float:
    """Mean effective/injected fraction over the entry's lit classes.

    0.0 when every lit class has zero effective events (vacuous chaos);
    1.0 when the entry lights no gray class (nothing to defend) or when
    the exposure plane was off (``classes`` is None — no evidence either
    way, so novelty alone decides).
    """
    lit = sorted(entry_classes(atoms))
    if not lit or classes is None:
        return 1.0
    if all(classes.get(n, {}).get("effective", 0) == 0 for n in lit):
        return 0.0
    fracs = []
    for n in lit:
        row = classes.get(n, {})
        inj = row.get("injected", 0)
        fracs.append(min(1.0, row.get("effective", 0) / inj) if inj else 0.0)
    return sum(fracs) / len(fracs)


def margin_boost(min_quorum_slack: Optional[int]) -> float:
    """1 + 1/(1 + slack) in (1, 2]; 1.0 when the margin plane saw nothing."""
    if min_quorum_slack is None:
        return 1.0
    return 1.0 + 1.0 / (1.0 + max(int(min_quorum_slack), 0))


def fitness(
    new_bits: int,
    atoms: list,
    classes: Optional[dict],
    min_quorum_slack: Optional[int],
) -> float:
    """The corpus fitness formula (see module docstring)."""
    return round(
        new_bits * exposure_weight(atoms, classes)
        * margin_boost(min_quorum_slack),
        6,
    )


@dataclasses.dataclass
class CorpusEntry:
    """One schedulable campaign: identity, recipe, and measured feedback."""

    entry_id: int
    seed: int
    atoms: list
    knobs: dict  # fault-knob overrides the mutator applied (e.g. p_corrupt)
    parent: Optional[int] = None
    ops: tuple = ()  # mutation op names that produced this entry
    root: bool = False  # root entries run the config's own sampled plan
    # Measured feedback (None until the entry's campaign finalizes).
    fingerprint: Optional[str] = None
    new_bits: Optional[int] = None
    effective: Optional[dict] = None
    min_quorum_slack: Optional[int] = None
    violations: int = 0
    fitness: float = 0.0
    # Plateau bookkeeping: consecutive low-yield child campaigns.
    stale: int = 0
    retired: bool = False

    @property
    def executed(self) -> bool:
        return self.new_bits is not None


def event_line(event: dict) -> str:
    """One canonical journal line: sorted-key compact JSON."""
    return json.dumps(event, sort_keys=True, separators=(",", ":"))


def append_event(fh, event: dict) -> None:
    """Crash-safe journal append: ONE ``write`` of the full line
    (newline included), then flush + fsync.

    A single write means a crash can only ever truncate the FINAL line —
    never interleave two — and the fsync means every line before it is
    durable before the next event exists.  :func:`load_journal` completes
    the contract by treating an unterminated tail as torn, not corrupt.
    """
    fh.write(event_line(event) + "\n")
    fh.flush()
    os.fsync(fh.fileno())


def load_journal(path: Any) -> dict:
    """Read a journal JSONL back, tolerating a torn final line.

    A crash mid-append (SIGKILL, power loss) leaves at most one
    truncated line at the END of the file — the append discipline above
    guarantees it.  That tail is dropped and REPORTED (``torn_tail``)
    instead of raising: recovery replays from the last durable event.  A
    malformed line anywhere else is real corruption and still raises.

    Returns ``{"events", "digest", "torn_tail"}`` — ``digest`` is the
    value of a trailing ``{"event": "digest"}`` line when present (the
    ``write_journal`` format), separated out of ``events``.
    """
    with open(path, "r") as f:
        text = f.read()
    lines = text.split("\n")
    torn = False
    if lines and lines[-1] == "":
        lines.pop()  # clean newline-terminated tail
    elif lines and lines[-1] != "":
        # No terminating newline: the final append was cut mid-write.
        # Even a tail that parses as JSON is dropped — completeness is
        # "newline landed", not "the prefix happened to parse".
        lines.pop()
        torn = True
    events: list[dict] = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError as e:
            if i == len(lines) - 1:
                # Torn tail variant: the line's bytes were cut but the
                # newline of a later flush survived is impossible under
                # the single-write discipline, yet a crashed PRE-fix
                # writer could leave this — tolerate the final line only.
                torn = True
                break
            raise ValueError(
                f"corpus journal {path}: malformed line {i + 1} (not the "
                f"tail — real corruption, not a torn append): {e}"
            ) from e
    digest = None
    if events and events[-1].get("event") == "digest":
        digest = events.pop()["sha256"]
    return {"events": events, "digest": digest, "torn_tail": torn}


def merge_journals(streams: "list[list[dict]]") -> dict:
    """Replay-append shard journals into one merged journal.

    The fleet merge: shard event streams are appended IN THE GIVEN ORDER
    (the coordinator passes campaign-record order, never worker
    completion order), entries dedup by their campaign identity
    ``(seed, atoms_digest)``, and entry ids are remapped densely.  A
    duplicate entry's ``feedback``/``retire`` events are dropped —
    campaigns are deterministic in (config, seed, plan), so the
    surviving copy's measurements are the same bytes.  Children of a
    deduped parent re-parent onto the surviving id.  Because the input
    order is canonical and every event is wall-clock-free, the merged
    digest is byte-identical however the shards were actually scheduled,
    interrupted, or recovered — the determinism pin extends through the
    merge.

    Returns ``{"events", "lines", "digest", "entries", "dedup"}``.
    """
    merged: list[dict] = []
    # (seed, atoms_digest) -> surviving merged id
    seen: dict[tuple, int] = {}
    dedup = 0
    next_id = 0
    for events in streams:
        # original id -> (merged id, was_duplicate)
        idmap: dict[int, tuple] = {}
        for e in events:
            kind = e.get("event")
            if kind == "add":
                key = (e["seed"], e.get("atoms_digest")
                       or atoms_digest(e["atoms"]))
                if key in seen:
                    idmap[e["id"]] = (seen[key], True)
                    dedup += 1
                    continue
                new = dict(e)
                new["id"] = next_id
                parent = e.get("parent")
                if parent is not None and parent in idmap:
                    new["parent"] = idmap[parent][0]
                seen[key] = next_id
                idmap[e["id"]] = (next_id, False)
                next_id += 1
                merged.append(new)
            elif kind in ("feedback", "retire"):
                mapped = idmap.get(e["id"])
                if mapped is None or mapped[1]:
                    continue  # event of a deduped (or foreign) entry
                merged.append(dict(e, id=mapped[0]))
            # Unknown kinds (a future journal schema) are dropped rather
            # than merged under stale ids.
    h = hashlib.sha256()
    lines = [event_line(e) for e in merged]
    for line in lines:
        h.update(line.encode())
        h.update(b"\n")
    return {
        "events": merged,
        "lines": lines,
        "digest": h.hexdigest(),
        "entries": next_id,
        "dedup": dedup,
    }


class Corpus:
    """Entry store + the append-only JSONL journal of every corpus event.

    With ``journal_path`` the journal is ALSO written through to disk as
    it happens — each event one crash-safe :func:`append_event` — so a
    SIGKILLed fuzzing worker loses at most the event being written, and
    :func:`load_journal` recovers everything before it.  The in-memory
    journal (and so ``digest()``) is unchanged either way.
    """

    def __init__(self, journal_path: Optional[Any] = None) -> None:
        self.entries: list[CorpusEntry] = []
        self._events: list[dict] = []
        self._fh = open(journal_path, "a") if journal_path else None

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def events(self) -> list[dict]:
        """The journal as events (a copy) — the fleet's merge input."""
        return list(self._events)

    # -- construction ----------------------------------------------------
    def add(
        self,
        seed: int,
        atoms: list,
        knobs: Optional[dict] = None,
        parent: Optional[int] = None,
        ops: tuple = (),
        root: bool = False,
    ) -> CorpusEntry:
        entry = CorpusEntry(
            entry_id=len(self.entries), seed=int(seed), atoms=list(atoms),
            knobs=dict(knobs or {}), parent=parent, ops=tuple(ops), root=root,
        )
        self.entries.append(entry)
        self._emit({
            "event": "add", "id": entry.entry_id, "seed": entry.seed,
            "parent": entry.parent, "ops": list(entry.ops),
            "root": entry.root, "knobs": entry.knobs, "atoms": entry.atoms,
            "atoms_digest": atoms_digest(entry.atoms),
        })
        return entry

    # -- feedback --------------------------------------------------------
    def record(
        self,
        entry: CorpusEntry,
        *,
        new_bits: int,
        classes: Optional[dict],
        min_quorum_slack: Optional[int],
        fingerprint: Optional[str],
        violations: int,
    ) -> float:
        """Fold one finalized campaign's measurements into its entry."""
        entry.new_bits = int(new_bits)
        entry.effective = (
            {n: row["effective"] for n, row in classes.items()}
            if classes is not None
            else None
        )
        entry.min_quorum_slack = min_quorum_slack
        entry.fingerprint = fingerprint
        entry.violations = int(violations)
        entry.fitness = fitness(
            entry.new_bits, entry.atoms, classes, min_quorum_slack
        )
        self._emit({
            "event": "feedback", "id": entry.entry_id,
            "fingerprint": fingerprint, "new_bits": entry.new_bits,
            "effective": entry.effective,
            "min_quorum_slack": min_quorum_slack,
            "violations": entry.violations, "fitness": entry.fitness,
        })
        return entry.fitness

    def retire(self, entry: CorpusEntry, reason: str) -> None:
        if entry.retired:
            return
        entry.retired = True
        self._emit({
            "event": "retire", "id": entry.entry_id, "reason": reason,
        })

    # -- queries ---------------------------------------------------------
    def get(self, entry_id: int) -> CorpusEntry:
        return self.entries[entry_id]

    def alive(self) -> list[CorpusEntry]:
        """Executed, unretired entries — the mutation parent pool."""
        return [e for e in self.entries if e.executed and not e.retired]

    # -- journal ---------------------------------------------------------
    def _emit(self, event: dict) -> None:
        self._events.append(event)
        if self._fh is not None:
            append_event(self._fh, event)

    def journal_lines(self) -> list[str]:
        """Canonical JSONL: one sorted-key compact line per event, in
        emission order — byte-stable across runs and platforms (no
        wall-clock, no floats beyond the rounded fitness)."""
        return [event_line(e) for e in self._events]

    def digest(self) -> str:
        """sha256 over the journal — the replay-determinism pin."""
        h = hashlib.sha256()
        for line in self.journal_lines():
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    def write_journal(self, path: Any) -> str:
        """Write the journal JSONL (digest line last); returns the digest.

        Written to a sibling temp file and renamed into place, so a crash
        mid-write can never leave a half journal under the final name —
        the whole-file twin of the :func:`append_event` discipline.
        """
        digest = self.digest()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for line in self.journal_lines():
                f.write(line + "\n")
            f.write(event_line({"event": "digest", "sha256": digest}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return digest
