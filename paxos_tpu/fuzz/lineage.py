"""Corpus lineage — the mutation family tree and per-op payoff attribution.

The corpus journal already records everything genealogy needs: every
``add`` event carries its parent link, the mutation ops that produced the
entry, and the canonical ``atoms_digest``; every ``feedback`` event
carries the measured payoff (coverage ``new_bits``, per-class effective
exposure, margin slack, violations, fitness).  PR 16's merge even
re-parents deduped entries.  What was never built is the READ side: this
module reconstructs the family tree from any journal (live worker, merged
fleet, ``--corpus-out`` artifact) and answers the question the energy
scheduler's design begs — *which of the 14 registered mutation ops
actually pay?*

Attribution formula: each executed entry's measured feedback is credited
to the ops that produced it, **split equally** across the entry's op
chain (exact ``fractions.Fraction`` arithmetic, so the per-op columns sum
to the journal's recorded feedback totals *exactly* — no double counting,
no rounding drift).  Root entries carry no ops and credit the pseudo-op
``root``: the baseline the mutations are measured against.
``margin_tightened`` credits an entry whose ``min_quorum_slack`` is
strictly tighter than its parent's (or which is contested at all, for a
root) — the near-miss payoff the fitness boost rewards.

Pure host-side decode over journal events: no device ops, no PRNG, no
clock — importable and runnable anywhere a journal file exists.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Iterable

ROOT_OP = "root"


def build_lineage(events: "Iterable[dict]") -> dict:
    """Reconstruct the family tree from corpus journal events.

    Tolerates merged journals (dense re-mapped ids, re-parented
    children) and partial ones (entries with no feedback yet).  Unknown
    event kinds are ignored, matching the merge's forward-compat rule.

    Returns ``{"nodes", "roots", "order", "depth_max"}`` — ``nodes``
    maps id -> node dict (children list included), ``roots`` is the list
    of parentless ids in id order, ``order`` every id in add order.
    """
    nodes: "dict[int, dict]" = {}
    order: "list[int]" = []
    for e in events:
        kind = e.get("event")
        if kind == "add":
            nid = int(e["id"])
            node = {
                "id": nid,
                "seed": e.get("seed"),
                "parent": e.get("parent"),
                "ops": tuple(e.get("ops") or ()),
                "root": bool(e.get("root")),
                "atoms_digest": e.get("atoms_digest"),
                "children": [],
                "executed": False,
                "new_bits": None,
                "effective": None,
                "min_quorum_slack": None,
                "violations": 0,
                "fitness": 0.0,
                "retired": None,
            }
            nodes[nid] = node
            order.append(nid)
            parent = e.get("parent")
            if parent is not None and int(parent) in nodes:
                nodes[int(parent)]["children"].append(nid)
        elif kind == "feedback":
            node = nodes.get(int(e["id"]))
            if node is None:
                continue
            node["executed"] = True
            node["new_bits"] = int(e.get("new_bits", 0))
            node["effective"] = e.get("effective")
            node["min_quorum_slack"] = e.get("min_quorum_slack")
            node["violations"] = int(e.get("violations", 0))
            node["fitness"] = float(e.get("fitness", 0.0))
        elif kind == "retire":
            node = nodes.get(int(e["id"]))
            if node is not None:
                node["retired"] = e.get("reason", "?")
    depth: "dict[int, int]" = {}
    for nid in order:  # parents precede children in add order
        parent = nodes[nid]["parent"]
        depth[nid] = (
            0 if parent is None or int(parent) not in depth
            else depth[int(parent)] + 1
        )
        nodes[nid]["depth"] = depth[nid]
    return {
        "nodes": nodes,
        "roots": [n for n in order if nodes[n]["parent"] is None],
        "order": order,
        "depth_max": max(depth.values(), default=0),
    }


def margin_tightened(node: dict, nodes: "dict[int, dict]") -> bool:
    """Did this entry tighten the near-miss margin vs its parent?

    Contested at all (slack not None) counts for a parentless entry;
    a child must be STRICTLY tighter than its parent (an uncontested
    parent tightens on any contested child).
    """
    slack = node.get("min_quorum_slack")
    if slack is None:
        return False
    parent = node.get("parent")
    if parent is None or int(parent) not in nodes:
        return True
    pslack = nodes[int(parent)].get("min_quorum_slack")
    return pslack is None or int(slack) < int(pslack)


def _effective_sum(node: dict) -> int:
    eff = node.get("effective")
    return sum(int(v) for v in eff.values()) if isinstance(eff, dict) else 0


def op_attribution(lineage: dict) -> dict:
    """Per-mutation-op payoff table + exact journal feedback totals.

    ``totals`` counts every executed entry ONCE (it equals independent
    sums over the journal's feedback events — the cross-check the tests
    pin); ``ops`` maps op name -> equally-split credit whose columns sum
    back to ``totals`` exactly (Fraction arithmetic internally, floats
    rounded to 6 on the way out).
    """
    nodes = lineage["nodes"]
    cols = ("campaigns", "new_bits", "effective", "violations",
            "margin_tightened", "fitness")
    acc: "dict[str, dict[str, Fraction]]" = {}
    totals_f = {c: Fraction(0) for c in cols}
    for nid in lineage["order"]:
        node = nodes[nid]
        if not node["executed"]:
            continue
        row = {
            "campaigns": Fraction(1),
            "new_bits": Fraction(int(node["new_bits"] or 0)),
            "effective": Fraction(_effective_sum(node)),
            "violations": Fraction(int(node["violations"])),
            "margin_tightened": Fraction(
                int(margin_tightened(node, nodes))
            ),
            "fitness": Fraction(node["fitness"]).limit_denominator(10**9),
        }
        ops = list(node["ops"]) or [ROOT_OP]
        share = Fraction(1, len(ops))
        for op in ops:
            dst = acc.setdefault(op, {c: Fraction(0) for c in cols})
            for c in cols:
                dst[c] += row[c] * share
        for c in cols:
            totals_f[c] += row[c]
    totals = {
        c: (float(v) if c == "fitness" else int(v))
        for c, v in totals_f.items()
    }
    ops_out = {
        op: {c: round(float(v), 6) for c, v in sorted(vals.items())}
        for op, vals in acc.items()
    }
    return {"ops": ops_out, "totals": totals, "_exact": acc,
            "_exact_totals": totals_f}


def lineage_summary(lineage: dict) -> dict:
    """The gauge-ready roll-up (``lineage_*`` metrics vocabulary)."""
    nodes = list(lineage["nodes"].values())
    return {
        "entries": len(nodes),
        "roots": len(lineage["roots"]),
        "executed": sum(1 for n in nodes if n["executed"]),
        "retired": sum(1 for n in nodes if n["retired"]),
        "depth_max": lineage["depth_max"],
        "best_fitness": max((n["fitness"] for n in nodes), default=0.0),
    }


def render_tree(lineage: dict) -> str:
    """ASCII family tree in add order — the ``paxos_tpu lineage`` view."""
    nodes = lineage["nodes"]
    out: "list[str]" = []

    def fmt(node: dict) -> str:
        bits = (
            f" bits={node['new_bits']}" if node["executed"] else " (pending)"
        )
        ops = ",".join(node["ops"]) if node["ops"] else ROOT_OP
        extra = ""
        if node["min_quorum_slack"] is not None:
            extra += f" slack={node['min_quorum_slack']}"
        if node["violations"]:
            extra += f" VIOLATIONS={node['violations']}"
        if node["retired"]:
            extra += f" [retired: {node['retired']}]"
        return (
            f"#{node['id']} seed={node['seed']} ops={ops}"
            f" fit={node['fitness']}{bits}{extra}"
        )

    def walk(nid: int, prefix: str, last: bool, top: bool) -> None:
        node = nodes[nid]
        if top:
            out.append(fmt(node))
            child_prefix = ""
        else:
            branch = "`-- " if last else "|-- "
            out.append(prefix + branch + fmt(node))
            child_prefix = prefix + ("    " if last else "|   ")
        kids = node["children"]
        for i, kid in enumerate(kids):
            walk(kid, child_prefix, i == len(kids) - 1, False)

    for rid in lineage["roots"]:
        walk(rid, "", True, True)
    return "\n".join(out)


def render_op_table(attribution: dict) -> str:
    """Per-op payoff table, best-paying ops first."""
    header = (
        f"{'op':<20}{'campaigns':>10}{'new_bits':>10}{'effective':>11}"
        f"{'violations':>12}{'tightened':>11}{'fitness':>10}"
    )
    lines = [header]
    rows = sorted(
        attribution["ops"].items(),
        key=lambda kv: (-kv[1]["new_bits"], kv[0]),
    )
    for op, row in rows:
        lines.append(
            f"{op:<20}{row['campaigns']:>10g}{row['new_bits']:>10g}"
            f"{row['effective']:>11g}{row['violations']:>12g}"
            f"{row['margin_tightened']:>11g}{row['fitness']:>10g}"
        )
    t = attribution["totals"]
    lines.append(
        f"{'TOTAL':<20}{t['campaigns']:>10g}{t['new_bits']:>10g}"
        f"{t['effective']:>11g}{t['violations']:>12g}"
        f"{t['margin_tightened']:>11g}{t['fitness']:>10g}"
    )
    return "\n".join(lines)
