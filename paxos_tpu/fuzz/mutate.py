"""Deterministic, stream-registered fault-plan mutations.

The mutator is the shrink machinery run in reverse: where
``harness/shrink.py`` removes atoms one at a time to minimize a failing
plan, ``mutate`` adds, removes, retargets, and widens atoms at the SAME
granularity — the ``faults.injector`` codec — plus two knob-level ops
(corruption rate when the base config lights it, and ballot-pressure
timing) that perturb the campaign config rather than the plan.

Determinism contract (pinned by tests/test_fuzz.py against a golden
digest): mutations draw from a pure-Python splitmix64 stream — integer
arithmetic only, no platform floats, no ``random`` module, no wall clock —
so the same (rng seed, corpus entry) yields the identical mutation
sequence on every run and platform.  Ops are STREAM-REGISTERED like the
device PRNG streams in ``core/streams.py``: each op owns a stable integer
id, every op application forks the entry stream by that id, and the
registry refuses duplicate ids or names at import time — adding an op
never perturbs the draws of existing ones beyond the op-selection draw.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

_MASK64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15
# Per-link rates are uint32 thresholds (faults.injector.rate_threshold);
# the mutator draws them on a 1/16 grid — coarse is fine for chaos knobs,
# and integer grid points keep the wire form platform-independent.
_THR_STEP = (1 << 32) // 16


class SplitMix64:
    """splitmix64 — the integer-only host PRNG behind every mutation draw."""

    def __init__(self, seed: int) -> None:
        self.state = seed & _MASK64

    def next_u64(self) -> int:
        self.state = (self.state + _GOLDEN) & _MASK64
        z = self.state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
        return z ^ (z >> 31)

    def below(self, n: int) -> int:
        """Uniform int in [0, n).  Modulo bias at 64 bits is ~2^-59 per
        draw — irrelevant for mutation choice, and bit-stable everywhere."""
        return self.next_u64() % max(int(n), 1)

    def fork(self, fold: int) -> "SplitMix64":
        """An independent child stream keyed by ``fold`` (an op id) —
        consumes one parent draw, so sibling forks never collide."""
        return SplitMix64(self.next_u64() ^ ((fold * _GOLDEN) & _MASK64))


def entry_stream(rng_seed: int, entry_id: int) -> SplitMix64:
    """The registered mutation stream for one (rng seed, corpus entry)."""
    return SplitMix64(((rng_seed & _MASK64) * _GOLDEN ^ entry_id) & _MASK64)


@dataclasses.dataclass(frozen=True)
class Dims:
    """Targeting bounds for mutation draws (shapes + tick budget)."""

    n_inst: int
    n_acc: int
    n_prop: int
    max_tick: int


def _window(rng: SplitMix64, dims: Dims) -> tuple[int, int]:
    start = rng.below(max(dims.max_tick - 1, 1))
    length = 1 + rng.below(max(dims.max_tick // 4, 2))
    return start, min(start + length, dims.max_tick)


# --- atom-level ops (the shrinker's vocabulary, in reverse) ---------------


def _add_acceptor_crash(rng, atoms, knobs, dims) -> Optional[str]:
    start, end = _window(rng, dims)
    atoms.append({
        "kind": "crash", "role": "acceptor", "idx": rng.below(dims.n_acc),
        "lane": rng.below(dims.n_inst), "start": start, "end": end,
    })
    return "add-acceptor-crash"


def _add_proposer_crash(rng, atoms, knobs, dims) -> Optional[str]:
    start, end = _window(rng, dims)
    atoms.append({
        "kind": "crash", "role": "proposer", "idx": rng.below(dims.n_prop),
        "lane": rng.below(dims.n_inst), "start": start, "end": end,
    })
    return "add-proposer-crash"


def _add_equiv(rng, atoms, knobs, dims) -> Optional[str]:
    atoms.append({
        "kind": "equiv", "idx": rng.below(dims.n_acc),
        "lane": rng.below(dims.n_inst),
    })
    return "add-equiv"


def _partition(rng, atoms, dims, direction: int) -> None:
    start, end = _window(rng, dims)
    # Sides must actually split the acceptors or the cut is a no-op: put
    # one drawn acceptor alone on side A, the rest on side B.
    alone = rng.below(dims.n_acc)
    atoms.append({
        "kind": "partition", "lane": rng.below(dims.n_inst),
        "start": start, "end": end, "dir": direction,
        "aside": [1 if a == alone else 0 for a in range(dims.n_acc)],
        "pside": [rng.below(2) for _ in range(dims.n_prop)],
    })


def _add_partition(rng, atoms, knobs, dims) -> Optional[str]:
    _partition(rng, atoms, dims, 0)
    return "add-partition"


def _add_asym_partition(rng, atoms, knobs, dims) -> Optional[str]:
    _partition(rng, atoms, dims, 1 + rng.below(2))
    return "add-asym-partition"


def _add_flaky(rng, atoms, knobs, dims) -> Optional[str]:
    atoms.append({
        "kind": "flaky", "prop": rng.below(dims.n_prop),
        "acc": rng.below(dims.n_acc), "lane": rng.below(dims.n_inst),
        "drop": (1 + rng.below(15)) * _THR_STEP,  # rate in [1/16, 15/16]
        "dup": rng.below(9) * _THR_STEP,  # rate in [0, 8/16]
    })
    return "add-flaky"


def _add_skew(rng, atoms, knobs, dims) -> Optional[str]:
    atoms.append({
        "kind": "skew", "prop": rng.below(dims.n_prop),
        "lane": rng.below(dims.n_inst), "timeout": 1 + rng.below(8),
        "boff": 2 + rng.below(3),
    })
    return "add-skew"


def _add_delay(rng, atoms, knobs, dims) -> Optional[str]:
    # A slow link: per-link latency cap on a 1..8 grid.  campaign_config
    # lights p_delay so the plan field is consulted; whether the latencies
    # breach the SynchPaxos window Delta is the protocol's problem — that
    # boundary is exactly what the fuzzer is probing.
    atoms.append({
        "kind": "delay", "prop": rng.below(dims.n_prop),
        "acc": rng.below(dims.n_acc), "lane": rng.below(dims.n_inst),
        "cap": 1 + rng.below(8),
    })
    return "add-delay"


def _remove_atom(rng, atoms, knobs, dims) -> Optional[str]:
    if not atoms:
        return None
    atoms.pop(rng.below(len(atoms)))
    return "remove-atom"


def _retarget_lane(rng, atoms, knobs, dims) -> Optional[str]:
    if not atoms:
        return None
    atoms[rng.below(len(atoms))]["lane"] = rng.below(dims.n_inst)
    return "retarget-lane"


def _widen_window(rng, atoms, knobs, dims) -> Optional[str]:
    windowed = [a for a in atoms if "start" in a]
    if not windowed:
        return None
    atom = windowed[rng.below(len(windowed))]
    atom["end"] = min(
        atom["end"] + 1 + rng.below(max(dims.max_tick // 2, 2)),
        dims.max_tick,
    )
    return "widen-window"


# --- knob-level ops (campaign-config pressure, not plan atoms) ------------


def _ballot_pressure(rng, atoms, knobs, dims) -> Optional[str]:
    # Shorter timeouts and tighter backoff = more dueling ballots per tick
    # budget (the known high-yield dimension; see README).  Campaign-config
    # knobs, so this chooses a different campaign, never a different
    # execution of the same one.
    knobs["timeout"] = 2 + rng.below(10)
    knobs["backoff_max"] = 1 + rng.below(8)
    return "ballot-pressure"


def _scale_corrupt(rng, atoms, knobs, dims, base_corrupt=0.0) -> Optional[str]:
    # Only meaningful when the BASE config already lights the corruption
    # bug injection — the fuzzer must not silently turn a chaos soak into
    # a checker-validation run.  Rates live on a 1/32 grid (exact binary
    # floats, platform-stable).
    if base_corrupt <= 0.0:
        return None
    knobs["p_corrupt"] = (1 + rng.below(32)) / 32.0
    return "scale-corrupt"


def _set_workload(rng, atoms, knobs, dims) -> Optional[str]:
    # Config-level atom, not a plan field: ``campaign_config`` lights
    # ``SimConfig.workload`` from it and ``atoms_to_plan`` skips the kind.
    # Open-loop traffic changes which lanes have retirable client work, so
    # it is a campaign dimension exactly like a chaos knob — and because
    # the plane is an extra state leaf, entries with a wload atom compile
    # a separate executable (one per workload shape, shared across seeds).
    # Rates ride the 1/16 uint32 grid; ``atom_key`` ignores the payload,
    # so dedup keeps one workload per campaign (last write wins).
    mixes = ("poisson", "bursty", "diurnal", "mixed")
    atoms.append({
        "kind": "wload", "lane": 0,
        "mix": mixes[rng.below(len(mixes))],
        "rate": (1 + rng.below(8)) * _THR_STEP,  # rate in [1/16, 8/16]
    })
    return "set-workload"


def _ballot_stride(rng, atoms, knobs, dims) -> Optional[str]:
    # Coprime ballot strides (arXiv:2006.01885): proposers advance rounds
    # by a stride > 1 on retry, de-synchronizing dueling ballots the way
    # randomized backoff would — but deterministically, so the campaign
    # stays replayable.  Odd strides only: round numbers then never
    # re-collide mod a power-of-two backoff horizon.
    knobs["ballot_stride"] = 1 + 2 * rng.below(4)  # 1, 3, 5, 7
    return "ballot-stride"


@dataclasses.dataclass(frozen=True)
class MutationOp:
    """One registered mutation: stable stream id, name, and the op."""

    op_id: int
    name: str
    fn: Callable


def _register(*ops: MutationOp) -> tuple[MutationOp, ...]:
    ids = [op.op_id for op in ops]
    names = [op.name for op in ops]
    if len(set(ids)) != len(ids) or len(set(names)) != len(names):
        raise AssertionError(f"duplicate mutation op id/name: {ids} {names}")
    if any(i <= 0 for i in ids):
        raise AssertionError("mutation op ids must be positive")
    return tuple(ops)


# Append-only: op ids are part of the determinism contract (they key the
# stream forks), so never renumber or reuse one — retire by leaving a gap.
MUTATION_OPS = _register(
    MutationOp(1, "add-acceptor-crash", _add_acceptor_crash),
    MutationOp(2, "add-proposer-crash", _add_proposer_crash),
    MutationOp(3, "add-equiv", _add_equiv),
    MutationOp(4, "add-partition", _add_partition),
    MutationOp(5, "add-asym-partition", _add_asym_partition),
    MutationOp(6, "add-flaky", _add_flaky),
    MutationOp(7, "add-skew", _add_skew),
    MutationOp(8, "remove-atom", _remove_atom),
    MutationOp(9, "retarget-lane", _retarget_lane),
    MutationOp(10, "widen-window", _widen_window),
    MutationOp(11, "ballot-pressure", _ballot_pressure),
    MutationOp(12, "scale-corrupt", _scale_corrupt),
    MutationOp(13, "add-delay", _add_delay),
    MutationOp(14, "ballot-stride", _ballot_stride),
    MutationOp(15, "set-workload", _set_workload),
)


def _dedup(atoms: list) -> list:
    """Canonical order with one atom per targeting key (last write wins —
    matching ``atoms_to_plan``'s apply order semantics)."""
    from paxos_tpu.faults.injector import atom_key, canonical_atoms

    by_key = {atom_key(a): a for a in atoms}
    return canonical_atoms(list(by_key.values()))


def mutate(
    rng: SplitMix64,
    atoms: list,
    knobs: dict,
    dims: Dims,
    n_ops: int = 2,
    base_corrupt: float = 0.0,
) -> tuple[list, dict, tuple]:
    """Apply ``n_ops`` drawn mutations; returns (atoms', knobs', op names).

    Inputs are never modified.  Each application draws the op uniformly,
    then scans forward (registry order) past inapplicable ops — e.g.
    ``remove-atom`` on an empty list — so a draw always lands somewhere
    and the op count is exact.  Every op runs on its own ``fork(op_id)``
    stream: its internal draws cannot shift any other op's.
    """
    atoms = [dict(a) for a in atoms]
    knobs = dict(knobs)
    applied: list[str] = []
    for _ in range(max(int(n_ops), 1)):
        pick = rng.below(len(MUTATION_OPS))
        for step in range(len(MUTATION_OPS)):
            op = MUTATION_OPS[(pick + step) % len(MUTATION_OPS)]
            op_rng = rng.fork(op.op_id)
            if op.fn is _scale_corrupt:
                desc = op.fn(op_rng, atoms, knobs, dims,
                             base_corrupt=base_corrupt)
            else:
                desc = op.fn(op_rng, atoms, knobs, dims)
            if desc is not None:
                applied.append(desc)
                break
    return _dedup(atoms), knobs, tuple(applied)
