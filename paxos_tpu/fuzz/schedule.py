"""AFL-style energy scheduling over the shared soak worker loop.

``GuidedSource`` is a campaign source in the :class:`harness.soak`
protocol (``next_campaign`` / ``feedback``): plain ``soak`` and
``paxos_tpu fuzz`` execute campaigns through the SAME worker loop — the
fuzzer only decides WHICH (config, seed, plan) triples run, never how one
executes, so every device schedule stays bit-identical to the unguided
build for the same triple.

Energy policy (AFL-style): after each corpus refill, an executed entry
with fitness f gets ``clamp(round(f / mean_fitness), 1, energy_max)``
child campaigns, scheduled fitness-descending.  Entries whose lit fault
classes are all vacuous (zero effective events — ``fuzz.corpus``) are
retired immediately with zero energy; entries whose children stop buying
union bits are retired by the same ``plateau_seeds``/``plateau_min_new``
detection the soak loop applies to its cross-seed curve.

``campaign_config`` is the knob-lighting step: gray plan fields are only
CONSULTED when the matching ``FaultConfig`` knob is on (see
``protocols/*.py``), so a mutated plan's partition/flaky/skew atoms would
be silently inert without it.  It lights exactly the knobs the entry's
atoms need (crash/equiv need none — they apply unconditionally) and
applies the mutator's knob overrides; the resulting config fingerprint is
recorded per entry in the corpus journal.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from paxos_tpu.fuzz.corpus import Corpus, CorpusEntry, exposure_weight
from paxos_tpu.fuzz.mutate import Dims, entry_stream, mutate
from paxos_tpu.harness.config import SimConfig

# Campaign-config knobs the mutator may override (fuzz.mutate's knob ops).
# A whitelist, not a convention: an atom-level concern leaking into knobs
# would silently bypass the codec's round-trip guarantees.
KNOB_WHITELIST = ("timeout", "backoff_max", "p_corrupt", "ballot_stride")


@dataclasses.dataclass(frozen=True)
class FuzzParams:
    """Scheduler policy — all deterministic, all journal-visible."""

    campaigns: int = 32  # total campaign budget (the uniform-soak unit)
    seed_entries: int = 2  # root entries: base seed, seed+1, ...
    mutations: int = 2  # atom mutations per child entry
    energy_max: int = 4  # per-refill cap on campaigns per entry
    plateau_seeds: int = 3  # retire a parent after K low-yield children
    plateau_min_new: int = 1  # ...each adding fewer union bits than this
    rng_seed: int = 0  # mutation stream root (independent of cfg.seed)


def campaign_config(
    base_cfg: SimConfig, seed: int, atoms: list, knobs: dict
) -> SimConfig:
    """The concrete campaign config for one corpus entry.

    Lights the fault knobs the entry's atoms need (never dims one the base
    config already lit) and applies the whitelisted knob overrides.  The
    returned config is what fingerprints, compiles, and runs — entries
    with the same knob needs share one compiled executable across the
    whole fuzz run (plans are traced values, never compile keys).
    """
    f = base_cfg.fault
    rep: dict = {}
    kinds = {a["kind"] for a in atoms}
    if "partition" in kinds and f.p_part <= 0.0:
        rep["p_part"] = 0.5
    if any(
        a["kind"] == "partition" and a.get("dir", 0) for a in atoms
    ) and f.p_asym <= 0.0:
        rep["p_asym"] = 0.5
    if "flaky" in kinds:
        if f.p_flaky <= 0.0:
            rep["p_flaky"] = 0.5
        if any(a.get("dup") for a in atoms if a["kind"] == "flaky") and not (
            f.p_dup > 0.0 or f.flaky_dup > 0.0
        ):
            rep["flaky_dup"] = 0.5
    delays = [a for a in atoms if a["kind"] == "delay"]
    if delays:
        # The per-link caps live in plan.link_delay, which the step only
        # consults when p_delay lights the channel; the per-tick latency
        # draw is U[1, delay_max] clamped to the link cap, so delay_max
        # must cover the largest atom cap for it to be reachable.
        if f.p_delay <= 0.0:
            rep["p_delay"] = 0.5
        cmax = max(a["cap"] for a in delays)
        if cmax > f.delay_max:
            rep["delay_max"] = cmax
    skews = [a for a in atoms if a["kind"] == "skew"]
    if skews:
        tmax = max(a.get("timeout", 0) for a in skews)
        if tmax > 0:
            rep["timeout_skew"] = max(f.timeout_skew, tmax)
        bmax = max(a.get("boff", 1) for a in skews)
        if bmax > 1:
            rep["backoff_skew"] = max(f.backoff_skew, bmax)
    for k, v in knobs.items():
        if k not in KNOB_WHITELIST:
            raise ValueError(f"non-whitelisted fuzz knob: {k!r}")
        rep[k] = v
    fault = dataclasses.replace(f, **rep) if rep else f
    out = dataclasses.replace(base_cfg, seed=int(seed), fault=fault)
    wls = [a for a in atoms if a["kind"] == "wload"]
    if wls:
        # Config-level lighting, same doctrine as the fault knobs: the
        # atom decides the arrival shape, the base config keeps its other
        # workload knobs (queue_cap, SLO target, ...).  The rate rides the
        # mutator's uint32 grid — /2^32 is an exact binary float, so the
        # fingerprint is platform-stable.  atoms_to_plan skips the kind.
        from paxos_tpu.workload.generator import WorkloadConfig

        wl = wls[-1]
        out = dataclasses.replace(
            out,
            workload=dataclasses.replace(
                base_cfg.workload or WorkloadConfig(),
                mix=wl["mix"],
                rate=wl["rate"] / float(1 << 32),
            ),
        )
    return out


class GuidedSource:
    """Corpus-driven campaign source for the soak worker loop."""

    def __init__(
        self,
        cfg: SimConfig,
        params: Optional[FuzzParams] = None,
        ticks_per_seed: int = 256,
        log=None,
    ) -> None:
        from paxos_tpu.obs.exposure import ExposureConfig
        from paxos_tpu.obs.margin import MarginConfig

        if cfg.coverage is None:
            raise ValueError(
                "GuidedSource needs cfg.coverage on — new_bits IS the "
                "fitness signal (pass a CoverageConfig)"
            )
        # Exposure and margin are forced on: the energy policy is defined
        # in terms of effective-exposure weight and near-miss boost, and
        # both planes are schedule-identical either way.
        if cfg.exposure is None:
            cfg = dataclasses.replace(cfg, exposure=ExposureConfig(counters=True))
        if cfg.margin is None:
            cfg = dataclasses.replace(cfg, margin=MarginConfig(counters=True))
        self.cfg = cfg
        self.params = params or FuzzParams()
        self.ticks_per_seed = int(ticks_per_seed)
        self.say = log or (lambda s: None)
        self.dims = Dims(
            n_inst=cfg.n_inst, n_acc=cfg.n_acc, n_prop=cfg.n_prop,
            max_tick=self.ticks_per_seed,
        )
        self.corpus = Corpus()
        self.scheduled = 0
        self.finalized = 0
        # (cfg, plan, entry_id) of violating campaigns — the shrink queue.
        self.violating: list[tuple] = []
        self._queue: list[int] = []  # entry ids with energy multiplicity
        self._children: dict[int, int] = {}  # parent id -> children spawned
        self._roots_pending: list[int] = []
        from paxos_tpu.faults.injector import plan_to_atoms
        from paxos_tpu.harness.run import init_plan

        for i in range(max(self.params.seed_entries, 1)):
            scfg = dataclasses.replace(cfg, seed=cfg.seed + i)
            # Root entries record the config's OWN sampled plan as atoms
            # (the mutation substrate) but dispatch with plan=None, so a
            # root campaign is bit-identical to the plain-soak campaign
            # for the same seed.
            atoms = plan_to_atoms(init_plan(scfg), cfg.fault)
            entry = self.corpus.add(seed=scfg.seed, atoms=atoms, root=True)
            self._roots_pending.append(entry.entry_id)

    # -- campaign source protocol ---------------------------------------
    def next_campaign(self):
        from paxos_tpu.harness.soak import CampaignSpec

        if self.scheduled >= self.params.campaigns:
            return None
        self.scheduled += 1
        if self._roots_pending:
            entry = self.corpus.get(self._roots_pending.pop(0))
        else:
            parent = self._next_parent()
            entry = self._spawn_child(parent)
        ccfg = campaign_config(
            self.cfg, entry.seed, entry.atoms, entry.knobs
        )
        plan = None
        if not entry.root:
            from paxos_tpu.faults.injector import atoms_to_plan

            plan = atoms_to_plan(
                entry.atoms, self.cfg.n_inst, self.cfg.n_acc,
                self.cfg.n_prop, cfg=ccfg.fault,
            )
        return CampaignSpec(
            cfg=ccfg, plan=plan, meta={"entry_id": entry.entry_id}
        )

    def feedback(self, spec, report, seed_rec) -> None:
        entry = self.corpus.get(spec.meta["entry_id"])
        exp = report.get("exposure")
        classes = exp.get("classes") if isinstance(exp, dict) else None
        fit = self.corpus.record(
            entry,
            new_bits=seed_rec.get("new_bits", 0),
            classes=classes,
            min_quorum_slack=seed_rec.get("min_quorum_slack"),
            fingerprint=spec.cfg.fingerprint(),
            violations=report["violations"],
        )
        self.finalized += 1
        if report["violations"]:
            self.violating.append((spec.cfg, spec.plan, entry.entry_id))
        if classes is not None and exposure_weight(entry.atoms, classes) == 0.0:
            # Zero energy, permanently: the entry's chaos never touched
            # the protocol, so whatever bits it set are baseline dynamics
            # any entry would have bought.
            self.corpus.retire(entry, "vacuous")
            self.say(f"entry {entry.entry_id}: vacuous (retired)")
        if entry.parent is not None:
            parent = self.corpus.get(entry.parent)
            if seed_rec.get("new_bits", 0) < self.params.plateau_min_new:
                parent.stale += 1
                if parent.stale >= self.params.plateau_seeds:
                    self.corpus.retire(parent, "plateau")
                    self.say(
                        f"entry {parent.entry_id}: plateaued after "
                        f"{parent.stale} low-yield children (retired)"
                    )
            else:
                parent.stale = 0

    # -- energy ----------------------------------------------------------
    def _spawn_child(self, parent: CorpusEntry) -> CorpusEntry:
        child_idx = self._children.get(parent.entry_id, 0)
        self._children[parent.entry_id] = child_idx + 1
        # Stream discipline: one registered stream per (rng seed, parent
        # entry), forked per child — reordering campaigns never changes
        # what mutations a given (parent, child_idx) pair draws.
        rng = entry_stream(
            self.params.rng_seed, parent.entry_id
        ).fork(child_idx)
        atoms, knobs, ops = mutate(
            rng, parent.atoms, parent.knobs, self.dims,
            n_ops=self.params.mutations,
            base_corrupt=self.cfg.fault.p_corrupt,
        )
        return self.corpus.add(
            seed=parent.seed, atoms=atoms, knobs=knobs,
            parent=parent.entry_id, ops=ops,
        )

    def _refill(self) -> None:
        pool = [e for e in self.corpus.alive() if e.fitness > 0]
        if pool:
            mean = sum(e.fitness for e in pool) / len(pool)
            queue: list[int] = []
            for e in sorted(pool, key=lambda e: (-e.fitness, e.entry_id)):
                energy = max(
                    1,
                    min(self.params.energy_max, round(e.fitness / mean)),
                )
                queue.extend([e.entry_id] * energy)
            self._queue = queue
            return
        # Nothing fit yet (all campaigns plateaued at zero new bits):
        # keep exploring round-robin over whatever is not retired — the
        # vacuous and plateaued stay excluded via the retired flag.
        fallback = [e for e in self.corpus.entries if not e.retired]
        self._queue = [e.entry_id for e in fallback]

    def _next_parent(self) -> CorpusEntry:
        for _ in range(2):
            while self._queue:
                e = self.corpus.get(self._queue.pop(0))
                if not e.retired:
                    return e
            self._refill()
        # Everything retired: deterministic last resort, lowest id.
        return self.corpus.entries[0]

    # -- reporting -------------------------------------------------------
    def summary(self) -> dict[str, Any]:
        entries = self.corpus.entries
        return {
            "campaigns": self.finalized,
            "entries": len(entries),
            "roots": sum(1 for e in entries if e.root),
            "executed": sum(1 for e in entries if e.executed),
            "retired": sum(1 for e in entries if e.retired),
            "best_fitness": max((e.fitness for e in entries), default=0.0),
            "journal_digest": self.corpus.digest(),
        }
