"""Host-side harness: configs, the scan driver, metrics, checkpointing."""

from paxos_tpu.harness.config import SimConfig  # noqa: F401
from paxos_tpu.harness.run import run, summarize  # noqa: F401
