"""Checkpoint / resume — elastic recovery for the simulator itself.

Reference parity (SURVEY.md §6.4): the reference has no checkpointing
(single-decree Paxos decides and exits; acceptor state is in-memory [?]);
the TPU twin needs it because long fuzzing campaigns outlive TPU
preemptions.  The full simulator state (one pytree: role arrays, message
buffers, learner/checker accumulators, tick counter) plus the fault plan is
saved at chunk boundaries; because per-tick PRNG keys are derived as
``fold_in(base_key, tick)``, a resumed run replays the exact key stream and
is bit-identical to an uninterrupted one (test: tests/test_checkpoint.py).

Uses Orbax (the standard JAX checkpointing library); state arrays are
restored host-side and can be re-sharded onto any mesh afterwards, so a run
checkpointed on N chips can resume on M.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from paxos_tpu.core.state import PaxosState
from paxos_tpu.faults.injector import FaultPlan
from paxos_tpu.harness.config import SimConfig

# On-disk snapshot schema.  Bumped whenever the state/plan pytree changes
# shape or structure (axis order, new FaultPlan fields, ...); restore()
# refuses snapshots from a different schema with a clear message instead of
# a deep orbax structure error.
LAYOUT_VERSION = "instance-minor-v5"  # v5: packed (bal, val) pairs in MP arrays


def save(
    path: str | pathlib.Path,
    state: PaxosState,
    plan: FaultPlan,
    cfg: SimConfig,
) -> None:
    """Write a complete, resumable snapshot to ``path`` (a directory)."""
    path = pathlib.Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            path,
            {
                "state": jax.device_get(state),
                "plan": jax.device_get(plan),
            },
            force=True,
        )
    meta = dataclasses.asdict(cfg) | {"layout_version": LAYOUT_VERSION}
    (path / "simconfig.json").write_text(json.dumps(meta))


def restore(
    path: str | pathlib.Path,
) -> tuple[PaxosState, FaultPlan, SimConfig]:
    """Read a snapshot back; arrays land on the default device, unsharded."""
    path = pathlib.Path(path).absolute()
    raw = json.loads((path / "simconfig.json").read_text())
    found = raw.pop("layout_version", "pre-instance-minor")
    if found != LAYOUT_VERSION:
        raise ValueError(
            f"checkpoint at {path} uses array-layout schema {found!r}, this "
            f"build expects {LAYOUT_VERSION!r}; re-run the campaign from "
            "scratch (state array axis order changed)"
        )
    fault = raw.pop("fault")
    from paxos_tpu.faults.injector import FaultConfig

    cfg = SimConfig(**raw, fault=FaultConfig(**fault))

    # Restore against concrete templates so pytree structure (dataclasses,
    # not dicts) and dtypes come back exactly.
    from paxos_tpu.harness.run import init_state

    template = {
        "state": jax.device_get(init_state(cfg)),
        "plan": jax.device_get(FaultPlan.none(cfg.n_inst, cfg.n_acc, cfg.n_prop)),
    }
    with ocp.PyTreeCheckpointer() as ckptr:
        out = ckptr.restore(path, item=template)
    return out["state"], out["plan"], cfg
