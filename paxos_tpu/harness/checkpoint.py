"""Checkpoint / resume — elastic recovery for the simulator itself.

Reference parity (SURVEY.md §6.4): the reference has no checkpointing
(single-decree Paxos decides and exits; acceptor state is in-memory [?]);
the TPU twin needs it because long fuzzing campaigns outlive TPU
preemptions.  The full simulator state (one pytree: role arrays, message
buffers, learner/checker accumulators, tick counter) plus the fault plan is
saved at chunk boundaries; because per-tick PRNG keys are derived as
``fold_in(base_key, tick)``, a resumed run replays the exact key stream and
is bit-identical to an uninterrupted one (test: tests/test_checkpoint.py).

Uses Orbax (the standard JAX checkpointing library); state arrays are
restored host-side and can be re-sharded onto any mesh afterwards, so a run
checkpointed on N chips can resume on M.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from paxos_tpu.core.state import PaxosState
from paxos_tpu.faults.injector import FaultPlan
from paxos_tpu.harness.config import SimConfig

# On-disk snapshot schema.  Bumped whenever the state/plan pytree changes
# shape or structure (axis order, new FaultPlan fields, ...); restore()
# refuses snapshots from a different schema with a clear message instead of
# a deep orbax structure error.
#
# Migration note (ADVICE r4): v4 -> v5 repacked the MP log_bal/log_val
# arrays into packed (ballot, value) pairs AND the MP fused block default
# changed 256 -> 128 (a fresh schedule lineage), so pre-round-4 MP
# snapshots are deliberately stranded — a mechanical repack shim would
# restore the ARRAYS but silently resume a DIFFERENT schedule under the
# new block default, which is exactly the corruption the stream guard
# below exists to prevent.  Re-run stranded campaigns from scratch.
LAYOUT_VERSION = "instance-minor-v5"  # v5: packed (bal, val) pairs in MP arrays


def stream_id(cfg: SimConfig, engine: str, block: Optional[int] = None) -> dict:
    """The schedule-stream lineage of a campaign (VERDICT r4 weak#3).

    Fused streams are keyed per (seed, tick, BLOCK) — resuming a
    checkpoint under a different effective block replays a different
    schedule with the same seed, silently.  This records everything the
    stream identity depends on: the engine, the EFFECTIVE fused block
    (protocol default resolved at save time, so a later default change
    cannot reinterpret it), and the counter-PRNG scheme version.
    """
    if engine == "fused":
        if block is None:
            from paxos_tpu.kernels.fused_tick import fused_fns

            block = fused_fns(cfg.protocol)[2]
        # Fused masks come from the on-core splitmix counter-PRNG.
        prng = "splitmix-counter-v1"
    else:
        # XLA-engine masks come from jax.random under the ACTIVE impl
        # (bench.py switches to rbg; the CLI default is threefry) — part
        # of the stream identity, so record it.
        import jax

        block = None
        prng = f"jax.random-{jax.config.jax_default_prng_impl}"
    return {"engine": engine, "block": block, "prng_scheme": prng}


def check_stream(
    saved_stream: Optional[dict], want: dict, where: str
) -> None:
    """Refuse a resume whose schedule-stream lineage changed.

    The shared guard behind every resume path — checkpoint
    :func:`restore` and the fleet's per-record progress journals: a
    recorded stream that differs from the resuming one means the SAME
    seed would replay a DIFFERENT schedule (engine switch, fused-block
    default change, PRNG impl change), which silently corrupts the
    determinism contract.  ``None`` (pre-stream metadata) warns and
    proceeds; a mismatch raises.
    """
    if saved_stream is None:
        import warnings

        warnings.warn(
            f"{where} predates stream metadata: cannot verify the resume "
            f"replays the saved schedule (resuming as {want})",
            stacklevel=3,
        )
    elif saved_stream != want:
        raise ValueError(
            f"{where} was written by stream {saved_stream} but this "
            f"resume would run stream {want}: same seed, DIFFERENT "
            "schedule.  Pass the saved engine/block explicitly (e.g. "
            "--block) or re-run from scratch."
        )


def save(
    path: str | pathlib.Path,
    state: PaxosState,
    plan: FaultPlan,
    cfg: SimConfig,
    engine: Optional[str] = None,
    block: Optional[int] = None,
) -> None:
    """Write a complete, resumable snapshot to ``path`` (a directory).

    ``engine``/``block`` record the saving campaign's stream lineage
    (:func:`stream_id`) so a resume under a different engine or fused
    block — a silently different schedule — can be refused.
    """
    path = pathlib.Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            path,
            {
                "state": jax.device_get(state),
                "plan": jax.device_get(plan),
            },
            force=True,
        )
    meta = dataclasses.asdict(cfg) | {"layout_version": LAYOUT_VERSION}
    if engine is not None:
        meta["stream"] = stream_id(cfg, engine, block)
    (path / "simconfig.json").write_text(json.dumps(meta))


def restore(
    path: str | pathlib.Path,
    engine: Optional[str] = None,
    block: Optional[int] = None,
) -> tuple[PaxosState, FaultPlan, SimConfig]:
    """Read a snapshot back; arrays land on the default device, unsharded.

    When ``engine`` is given, the resuming campaign's stream lineage is
    checked against the one recorded at save time: a mismatch (e.g. an MP
    checkpoint saved under the pre-round-4 block=256 default resumed under
    the 128 default) raises instead of silently replaying a different
    schedule.  Snapshots without stream metadata warn and proceed.
    """
    path = pathlib.Path(path).absolute()
    raw = json.loads((path / "simconfig.json").read_text())
    found = raw.pop("layout_version", "pre-instance-minor")
    if found != LAYOUT_VERSION:
        raise ValueError(
            f"checkpoint at {path} uses array-layout schema {found!r}, this "
            f"build expects {LAYOUT_VERSION!r}; re-run the campaign from "
            "scratch (state array axis order changed)"
        )
    saved_stream = raw.pop("stream", None)
    fault = raw.pop("fault")
    # Tolerate snapshots predating an observer plane (no key for
    # telemetry / coverage / exposure / margin / workload): default off.
    tel = raw.pop("telemetry", None)
    cov = raw.pop("coverage", None)
    exp = raw.pop("exposure", None)
    mar = raw.pop("margin", None)
    wl = raw.pop("workload", None)
    from paxos_tpu.core.telemetry import TelemetryConfig
    from paxos_tpu.faults.injector import FaultConfig
    from paxos_tpu.obs.coverage import CoverageConfig
    from paxos_tpu.obs.exposure import ExposureConfig
    from paxos_tpu.obs.margin import MarginConfig
    from paxos_tpu.workload.generator import WorkloadConfig

    cfg = SimConfig(
        **raw,
        fault=FaultConfig(**fault),
        telemetry=TelemetryConfig(**tel) if tel else TelemetryConfig(),
        coverage=CoverageConfig(**cov) if cov else CoverageConfig(),
        exposure=ExposureConfig(**exp) if exp else ExposureConfig(),
        margin=MarginConfig(**mar) if mar else MarginConfig(),
        workload=WorkloadConfig(**wl) if wl else WorkloadConfig(),
    )

    if engine is not None:
        check_stream(
            saved_stream, stream_id(cfg, engine, block),
            f"checkpoint at {path}",
        )

    # Restore against concrete templates so pytree structure (dataclasses,
    # not dicts) and dtypes come back exactly.
    from paxos_tpu.harness.run import init_state

    template = {
        "state": jax.device_get(init_state(cfg)),
        # cfg-aware: the template must carry the gray-failure plan fields
        # (part_dir, link_drop, ...) exactly when the config's knobs do.
        "plan": jax.device_get(
            FaultPlan.none(cfg.n_inst, cfg.n_acc, cfg.n_prop, cfg=cfg.fault)
        ),
    }
    with ocp.PyTreeCheckpointer() as ckptr:
        out = ckptr.restore(path, item=template)
    return out["state"], out["plan"], cfg
