"""CLI — the master/slave command line's TPU twin.

Reference parity (SURVEY.md §4.1, §6.6): where the reference's `main` parses
``master|slave host port`` and boots SimpleLocalnet [CH], this CLI picks a
named BASELINE config, scales it, runs the scan loop with optional mesh
sharding, JSONL metrics, and periodic checkpoints, and prints the final
report as JSON — the batch analog of "print the decided value".

    python -m paxos_tpu run --config config2 --n-inst 65536 --ticks 400
    python -m paxos_tpu run --config config4 --log metrics.jsonl
    python -m paxos_tpu run --resume ckpt_dir --ticks 200
    python -m paxos_tpu sweep --n-inst 65536 --ticks 1024
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from paxos_tpu.harness import config as config_mod
from paxos_tpu.harness.config import SimConfig

def _sweep_member(protocol):
    """One protocol's member of the config-5 sweep as a standalone config,
    so run/soak/shrink can target the fastpaxos/raftcore kernels directly
    (the `sweep` subcommand runs all three under identical masks)."""

    def make(**kw):
        return next(
            c for c in config_mod.config5_sweep(**kw) if c.protocol == protocol
        )

    return make


CONFIGS = {
    "config1": config_mod.config1_no_faults,
    "config2": config_mod.config2_dueling_drop,
    "config3": config_mod.config3_multipaxos,
    "config3long": config_mod.config3_long,
    "config4": config_mod.config4_byzantine,
    "config5-fastpaxos": _sweep_member("fastpaxos"),
    "config5-raftcore": _sweep_member("raftcore"),
    "partition": config_mod.config_partition,
    # Gray failures: chaos (must soak clean) vs bug injections (checker
    # must flag) — see README "Fault model".
    "gray-chaos": config_mod.config_gray_chaos,
    "corrupt": config_mod.config_corrupt,
    "stale": config_mod.config_stale,
    # Bounded-delay chaos on SynchPaxos: latencies within the synchrony
    # window Delta, so the fast path stays live AND safe (must soak clean
    # with a nonzero fast-path rate); pass violate_delta=True (scripts/
    # delay.sh) for the latency>Delta regime the fallback must absorb.
    "delay-chaos": config_mod.config_delay_chaos,
    # Flexible Paxos: safe (4+2 > 5) and deliberately unsafe (2+2 <= 5)
    # quorum pairs; the unsafe one exists to prove the checker catches it.
    "flex-safe": lambda **kw: config_mod.config_flex(4, 2, **kw),
    "flex-unsafe": lambda **kw: config_mod.config_flex(2, 2, **kw),
    # Fast Flexible Paxos (arXiv:2008.02671): classic q1/q2 + fast quorum.
    # Safe: 4+2>5 and 4+2*4>10.  Unsafe: classically fine (3+3>5) but the
    # fast condition fails (3+2*3 <= 10) — isolates the q_fast path.
    "ffp-safe": lambda **kw: config_mod.config_ffp(4, 2, 4, **kw),
    "ffp-unsafe": lambda **kw: config_mod.config_ffp(3, 3, 3, **kw),
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="paxos_tpu")
    p.add_argument(
        "--platform",
        choices=["default", "cpu"],
        default="default",
        help="force the JAX backend (cpu = run without an accelerator)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="run a fuzzing campaign")
    r.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    r.add_argument(
        "--engine",
        choices=["xla", "fused"],
        default="xla",
        help="fused = whole-chunk Pallas kernel (TPU; works with --shard)",
    )
    r.add_argument("--n-inst", type=int, default=None, help="override instance count")
    r.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable), e.g. "
        "--fault p_corrupt=0.1 --fault timeout_skew=4; incompatible with "
        "--resume (the checkpoint's fault config is part of its stream)",
    )
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--ticks", type=int, default=256, help="total scheduler ticks")
    r.add_argument("--chunk", type=int, default=64, help="ticks per device dispatch")
    r.add_argument(
        "--pipeline-depth", type=int, default=None, metavar="K",
        help="dispatch pipeline (harness.pipeline): group up to K chunks "
        "per device dispatch, with termination probed via an async "
        "on-device done-flag (default 4; 1 = the serial per-chunk loop). "
        "Schedules are bit-identical at any depth.  Auto-degrades to 1 "
        "under --shard/--events/--checkpoint-every (they need per-chunk "
        "host work); incompatible with --resume (same rule as --record)",
    )
    r.add_argument("--until-all-chosen", action="store_true")
    r.add_argument("--shard", action="store_true", help="shard over all devices")
    r.add_argument("--log", default=None, help="JSONL metrics path")
    r.add_argument("--checkpoint-dir", default=None)
    r.add_argument("--checkpoint-every", type=int, default=0, help="ticks (0=off)")
    r.add_argument("--resume", default=None, help="checkpoint dir to resume from")
    r.add_argument(
        "--block", type=int, default=None,
        help="fused block size override (stream-relevant: fused schedules "
        "key on (seed, tick, block)); --resume verifies it against the "
        "block recorded in the checkpoint",
    )
    r.add_argument("--trace", default=None, help="jax.profiler trace logdir")
    r.add_argument(
        "--liveness",
        action="store_true",
        help="append decided-by curve / latency histogram / stuck-lane "
        "count to the final report (check/liveness)",
    )
    r.add_argument(
        "--events",
        action="store_true",
        help="per-chunk protocol event dump to stderr, routed through the "
        "metrics registry (and into --log when set); debug; slows the loop",
    )
    r.add_argument(
        "--telemetry", action="store_true",
        help="on-device protocol event counters, read back per chunk "
        "(core.telemetry; default off — off is free and schedule-identical)",
    )
    r.add_argument(
        "--record", type=int, default=0, metavar="DEPTH",
        help="on-device flight-recorder ring: DEPTH packed event words per "
        "lane (implies --telemetry); decode with core.telemetry.decode_lane",
    )
    r.add_argument(
        "--hist-bins", type=int, default=0, metavar="N",
        help="on-device ticks-to-decide histogram with N fixed-width bins "
        "(implies --telemetry)",
    )
    r.add_argument(
        "--span-trace", default=None, metavar="PATH",
        help="write the host loop's wall-clock spans (dispatches, probes, "
        "checkpoint writes) as a Chrome/Perfetto trace to PATH; for the "
        "unified device+host view use the `trace` subcommand",
    )
    r.add_argument(
        "--coverage", action="store_true",
        help="on-device coverage sketch: hash every lane's post-tick state "
        "into a per-lane Bloom bitmap (obs.coverage; default off — off is "
        "free and schedule-identical)",
    )
    r.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (m = 32*W Bloom bits; "
        "power of two; only read with --coverage)",
    )
    r.add_argument(
        "--exposure", action="store_true",
        help="on-device fault-exposure counters: per-lane injected-vs-"
        "effective tallies per fault class (obs.exposure; default off — "
        "off is free and schedule-identical)",
    )
    r.add_argument(
        "--margin", action="store_true",
        help="on-device near-miss safety-margin counters: per-lane distance "
        "to violation (quorum slack, near-split ticks, ballot-race gap, "
        "promise headroom; obs.margin; default off — off is free and "
        "schedule-identical)",
    )
    r.add_argument(
        "--workload", choices=["poisson", "bursty", "diurnal", "mixed"],
        default=None, metavar="MIX",
        help="open-loop client arrivals per proposer lane with on-device "
        "queue accounting and end-to-end latency histograms "
        "(workload.generator + obs.slo; default off — off is free and "
        "schedule-identical)",
    )
    r.add_argument(
        "--workload-rate", type=float, default=0.05, metavar="P",
        help="per-tick arrival probability per lane (only read with "
        "--workload; bursty/diurnal peaks use 10x via burst_rate)",
    )
    r.add_argument(
        "--slo-p99", type=int, default=0, metavar="T",
        help="configured p99 SLO in ticks, exported as the "
        "slo_target_p99_ticks gauge (only read with --workload; 0 = no "
        "SLO configured)",
    )
    r.add_argument(
        "--perf", action="store_true",
        help="host-side performance plane (obs.perf): rounds/sec, pipeline "
        "occupancy, chunk-latency percentiles, compile-vs-steady split in "
        "the final report and metrics stream (default off — zero device "
        "ops, schedule-identical either way)",
    )

    s = sub.add_parser(
        "sweep",
        help="config 5: Paxos vs Fast-Paxos vs Raft-core, identical fault masks",
    )
    s.add_argument("--n-inst", type=int, default=65_536)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--ticks", type=int, default=1024, help="max ticks per protocol")
    s.add_argument("--chunk", type=int, default=64)
    s.add_argument("--log", default=None, help="JSONL metrics path")

    so = sub.add_parser(
        "soak",
        help="rotate seeds until N instance-rounds accumulate; tally violations",
    )
    so.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    so.add_argument("--engine", choices=["xla", "fused"], default="fused")
    so.add_argument("--n-inst", type=int, default=None)
    so.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    so.add_argument("--seed", type=int, default=0)
    so.add_argument("--target-rounds", type=float, default=1e9)
    so.add_argument("--ticks-per-seed", type=int, default=256)
    so.add_argument("--chunk", type=int, default=64)
    so.add_argument(
        "--pipeline-depth", type=int, default=4, metavar="K",
        help="campaign overlap (harness.pipeline): dispatch seed N+1's "
        "campaign while seed N executes on-device and read reports from "
        "async transfers, with K chunks grouped per dispatch (default 4; "
        "1 = the serial campaign loop; the tally is identical either way)",
    )
    so.add_argument("--log", default=None, help="JSONL metrics path")
    so.add_argument(
        "--min-replication", type=float, default=None,
        help="long-log configs: fail (exit 3) if any campaign replicates "
        "fewer slots per lane-tick than this; defaults to 0.7x the recorded "
        "rate for known long-log configs (config.REPLICATION_RATES), 'off' "
        "for ad-hoc ones; pass 0 to disable",
    )
    so.add_argument(
        "--span-trace", default=None, metavar="PATH",
        help="write the campaign loop's wall-clock spans (per-seed dispatch "
        "and finalize, retry backoffs) as a Chrome/Perfetto trace to PATH",
    )
    so.add_argument(
        "--coverage", action="store_true",
        help="on-device coverage sketch per campaign, merged across seeds "
        "(Bloom unions OR): the report gains the cross-seed coverage "
        "curve and a plateau flag (obs.coverage)",
    )
    so.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (only read with "
        "--coverage)",
    )
    so.add_argument(
        "--plateau-seeds", type=int, default=3, metavar="K",
        help="flag a coverage plateau after K consecutive seeds each "
        "contribute fewer than --plateau-min-new new union bits",
    )
    so.add_argument(
        "--plateau-min-new", type=int, default=1, metavar="B",
        help="new-union-bits threshold a seed must reach to reset the "
        "plateau counter",
    )
    so.add_argument(
        "--plateau-stop", action="store_true",
        help="end the soak at the plateau instead of only reporting it "
        "(the tally keeps every finalized seed)",
    )
    so.add_argument(
        "--exposure", action="store_true",
        help="on-device fault-exposure counters per campaign, summed "
        "across seeds: the report gains per-class injected-vs-effective "
        "totals and a vacuous-chaos flag for lit knobs that never touched "
        "the protocol (obs.exposure)",
    )
    so.add_argument(
        "--margin", action="store_true",
        help="on-device near-miss margin counters per campaign: the report "
        "gains cross-seed minima and a per-seed near-miss ranking — which "
        "seeds came closest to a violation (obs.margin)",
    )
    so.add_argument(
        "--workload", choices=["poisson", "bursty", "diurnal", "mixed"],
        default=None, metavar="MIX",
        help="open-loop client workload per campaign: the report gains the "
        "cross-seed client-latency tally (summed histograms, recomputed "
        "percentiles) and per-seed slo_p99_ticks trend (workload.generator "
        "+ obs.slo; default off — off is free and schedule-identical)",
    )
    so.add_argument(
        "--workload-rate", type=float, default=0.05, metavar="P",
        help="per-tick arrival probability per lane (only read with "
        "--workload)",
    )
    so.add_argument(
        "--slo-p99", type=int, default=0, metavar="T",
        help="configured p99 SLO in ticks, exported as the "
        "slo_target_p99_ticks gauge (only read with --workload)",
    )
    so.add_argument(
        "--perf", action="store_true",
        help="host-side performance plane (obs.perf) over the campaign "
        "loop: cumulative/windowed rounds/sec, occupancy, and dispatch "
        "latency percentiles in the soak report and metrics stream "
        "(default off; the per-seed throughput trend is recorded always)",
    )

    fz = sub.add_parser(
        "fuzz",
        help="feedback-directed fuzzing: corpus-driven campaigns, coverage-"
        "guided and exposure-weighted, through the soak worker loop",
    )
    fz.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    fz.add_argument(
        "--engine", choices=["xla", "fused"], default="xla",
        help="defaults to xla (the fuzzer's feedback loop is CPU-friendly "
        "at small batches); fused needs a TPU, like soak",
    )
    fz.add_argument("--n-inst", type=int, default=None)
    fz.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob on the BASE config (repeatable); "
        "mutated entries light additional knobs per their atoms",
    )
    fz.add_argument("--seed", type=int, default=0, help="first root entry seed")
    fz.add_argument(
        "--rng-seed", type=int, default=0,
        help="mutation stream root (fuzz.mutate; independent of --seed so "
        "the same corpus can be re-mutated differently)",
    )
    fz.add_argument(
        "--campaigns", type=int, default=32,
        help="total campaign budget — the unit a uniform soak comparison "
        "must match (one campaign = one (config, seed, plan) run)",
    )
    fz.add_argument("--ticks-per-seed", type=int, default=256)
    fz.add_argument("--chunk", type=int, default=64)
    fz.add_argument(
        "--pipeline-depth", type=int, default=1, metavar="K",
        help="campaign overlap (soak's pipelining); default 1 so energy "
        "decisions always see the previous campaign's feedback",
    )
    fz.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="coverage sketch size in int32 words per lane (the plane is "
        "always on under fuzz — new_bits IS the fitness signal)",
    )
    fz.add_argument(
        "--seed-entries", type=int, default=2,
        help="root corpus entries (base seed upward), run unmutated first",
    )
    fz.add_argument(
        "--mutations", type=int, default=2,
        help="atom mutations per child entry (fuzz.mutate ops)",
    )
    fz.add_argument(
        "--energy-max", type=int, default=4,
        help="per-refill cap on child campaigns per corpus entry",
    )
    fz.add_argument(
        "--plateau-seeds", type=int, default=3, metavar="K",
        help="retire a corpus entry after K consecutive low-yield children "
        "(same detection as soak's cross-seed plateau)",
    )
    fz.add_argument(
        "--plateau-min-new", type=int, default=1, metavar="B",
        help="new-union-bits threshold a child must reach to reset its "
        "parent's plateau counter",
    )
    fz.add_argument(
        "--corpus-out", default=None, metavar="PATH",
        help="write the corpus journal (JSONL, wall-clock-free, digest "
        "line last) — two runs of the same command produce byte-identical "
        "journals, the replay-determinism pin",
    )
    fz.add_argument("--log", default=None, help="JSONL metrics path")

    fl = sub.add_parser(
        "fleet",
        help="fault-tolerant sharded fuzzing fleet: durable campaign "
        "queue, lease-based worker recovery, merged corpus + coverage "
        "(fleet.coordinator)",
    )
    fl.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    fl.add_argument(
        "--engine", choices=["xla", "fused"], default="xla",
        help="engine each worker campaign runs under (recorded in every "
        "queue record's stream lineage)",
    )
    fl.add_argument("--n-inst", type=int, default=None)
    fl.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob on the base config (repeatable)",
    )
    fl.add_argument(
        "--mode", choices=["soak", "fuzz"], default="soak",
        help="what each record runs: a rotating-seed soak shard or an "
        "independent guided-fuzzing shard whose corpora merge",
    )
    fl.add_argument(
        "--dir", required=True, metavar="PATH",
        help="queue root directory (pending/claimed/done/leases/results/"
        "progress) — durable across coordinator restarts",
    )
    fl.add_argument("--workers", type=int, default=2)
    fl.add_argument(
        "--records", type=int, default=4,
        help="campaign records to enqueue (the re-dispatch granularity)",
    )
    fl.add_argument(
        "--seeds-per-record", type=int, default=4,
        help="soak mode: rotating seeds per record — together the records "
        "cover exactly the seed schedule one big soak would run",
    )
    fl.add_argument("--seed", type=int, default=0)
    fl.add_argument(
        "--seed-stride", type=int, default=10_000,
        help="fuzz mode: seed-space stride between records (disjoint "
        "root-seed ranges per shard)",
    )
    fl.add_argument("--rng-seed", type=int, default=0)
    fl.add_argument(
        "--campaigns-per-record", type=int, default=8,
        help="fuzz mode: guided campaign budget per record",
    )
    fl.add_argument("--seed-entries", type=int, default=2)
    fl.add_argument("--mutations", type=int, default=2)
    fl.add_argument("--energy-max", type=int, default=4)
    fl.add_argument("--ticks-per-seed", type=int, default=256)
    fl.add_argument("--chunk", type=int, default=64)
    fl.add_argument("--coverage-words", type=int, default=64, metavar="W")
    fl.add_argument(
        "--workload", choices=["poisson", "bursty", "diurnal", "mixed"],
        default=None,
        help="light the client-workload plane on every record: per-seed "
        "slo_p99_ticks gauges ride the sampled series, so the "
        "slo_degradation trend detector covers the fleet",
    )
    fl.add_argument(
        "--workload-rate", type=float, default=0.05, metavar="P",
        help="base per-tick arrival probability (only read with "
        "--workload)",
    )
    fl.add_argument(
        "--slo-p99", type=int, default=0, metavar="T",
        help="p99 SLO in ticks recorded in each record's workload config "
        "(only read with --workload; 0 = report only)",
    )
    fl.add_argument(
        "--lease-s", type=float, default=15.0,
        help="lease duration; a worker silent this long is presumed dead "
        "and its record re-dispatched (workers heartbeat at lease/5)",
    )
    fl.add_argument("--poll-s", type=float, default=0.5)
    fl.add_argument(
        "--timeout-s", type=float, default=1800.0,
        help="wall-clock bound on the whole fleet run (exit 1 if the "
        "budget is not completed)",
    )
    fl.add_argument(
        "--chaos", action="store_true",
        help="SIGKILL workers mid-campaign on a seeded schedule, then "
        "recover — the fleet's own fault injection; the merged output "
        "must be byte-identical to an uninterrupted run's",
    )
    fl.add_argument("--chaos-kills", type=int, default=1)
    fl.add_argument("--chaos-seed", type=int, default=0)
    fl.add_argument(
        "--hold-s", type=float, default=0.0,
        help="worker pause between claim and execution — the window the "
        "chaos kill schedule aims at (test/chaos knob)",
    )
    fl.add_argument(
        "--bench-baseline", default=None, metavar="PATH",
        help="run bench-compare against this committed artifact as the "
        "fleet's continuous regression gate (exit 2 on regression)",
    )
    fl.add_argument(
        "--sample-every", type=int, default=0, metavar="N",
        help="fleet observatory: each worker samples its gauges into a "
        "crash-safe time-series journal every N logical-clock ticks "
        "(seed index / campaign ordinal); the coordinator merges the "
        "journals canonically and runs the trend gate (exit 2 on "
        "discovery stall / rps degradation / heartbeat gaps). 0 = off "
        "(no journal, nothing written)",
    )
    fl.add_argument(
        "--timeline", default=None, metavar="PATH",
        help="write the unified fleet timeline (Chrome trace JSON, "
        "Perfetto-loadable): a track per worker with claim/SIGKILL/"
        "reclaim/lease events and record spans, per-worker coverage and "
        "rounds/sec counter tracks, fleet-aggregate counters",
    )
    fl.add_argument(
        "--corpus-out", default=None, metavar="PATH",
        help="fuzz mode: write the merged corpus journal (JSONL, digest "
        "line last) — the artifact `paxos_tpu lineage` reads",
    )
    fl.add_argument("--log", default=None, help="JSONL metrics path")

    fw = sub.add_parser(
        "fleet-worker",
        help="internal: one fleet worker process (spawned by `fleet`; "
        "usable standalone against any queue directory)",
    )
    fw.add_argument("--dir", required=True)
    fw.add_argument("--worker-id", required=True)
    fw.add_argument("--lease-s", type=float, default=15.0)
    fw.add_argument("--poll-s", type=float, default=0.5)
    fw.add_argument("--hold-s", type=float, default=0.0)
    fw.add_argument("--sample-every", type=int, default=0)

    ln = sub.add_parser(
        "lineage",
        help="corpus lineage: reconstruct the mutation family tree from "
        "a corpus journal and attribute payoff to each mutation op "
        "(fuzz.lineage)",
    )
    ln.add_argument(
        "journal", metavar="JOURNAL",
        help="corpus journal path (fuzz --corpus-out, fleet --corpus-out, "
        "or a worker's raw journal)",
    )
    ln.add_argument(
        "--tree", action="store_true",
        help="render the ASCII family tree (default shows the per-op "
        "payoff table only)",
    )
    ln.add_argument(
        "--json", action="store_true",
        help="machine-readable: summary + per-op attribution + totals",
    )
    ln.add_argument("--log", default=None, help="JSONL metrics path")

    k = sub.add_parser(
        "shrink",
        help="delta-debug a violating config's fault plan to a minimal repro",
    )
    k.add_argument("--config", choices=sorted(CONFIGS), default="config4")
    k.add_argument(
        "--engine",
        choices=["xla", "fused"],
        default="fused",
        help="stream the violation was observed under; defaults to fused to "
        "match soak's default (seeds from `soak` replay only under the "
        "same engine's stream)",
    )
    k.add_argument(
        "--block", type=int, default=None,
        help="fused block size of the observing run, when it differed from "
        "the protocol default (e.g. a sharded run clamped it)",
    )
    k.add_argument("--n-inst", type=int, default=None)
    k.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable); must "
        "match the observing run's overrides (plan sampling keys on them)",
    )
    k.add_argument("--seed", type=int, default=0)
    k.add_argument("--ticks", type=int, default=512, help="violation search budget")
    k.add_argument(
        "--chunk", type=int, default=64,
        help="chunk of the observing run (default matches run/soak's 64; "
        "schedule-relevant for long-log configs — compaction fires at "
        "chunk boundaries, so a mismatched chunk explores a different "
        "schedule and can miss the violation)",
    )
    k.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="also write the victim lane's reconstructed round spans as a "
        "Chrome/Perfetto trace to PATH (the repro JSON carries the same "
        "spans either way)",
    )

    tr = sub.add_parser(
        "trace",
        help="run a campaign with the flight recorder on and export a "
        "Perfetto/Chrome trace: per-lane ballot-round spans, fault "
        "instants, and the host dispatch loop on its own track",
    )
    tr.add_argument("--config", choices=sorted(CONFIGS), default="corrupt")
    tr.add_argument("--engine", choices=["xla", "fused"], default="xla")
    tr.add_argument("--n-inst", type=int, default=None)
    tr.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--ticks", type=int, default=256)
    tr.add_argument("--chunk", type=int, default=64)
    tr.add_argument(
        "--pipeline-depth", type=int, default=4, metavar="K",
        help="dispatch grouping for the traced loop (the host track shows "
        "the grouped dispatches; 1 = serial per-chunk loop)",
    )
    tr.add_argument(
        "--lanes", type=int, default=8, metavar="N",
        help="how many lanes to decode into round spans (violating lanes "
        "are picked first, then lane 0 upward)",
    )
    tr.add_argument(
        "--out", default="trace.json",
        help="Chrome trace-event JSON output path (load in ui.perfetto.dev "
        "or chrome://tracing)",
    )
    tr.add_argument(
        "--spans-out", default=None, metavar="PATH",
        help="also write the reconstructed spans as compact JSONL "
        "(one span per line; the programmatic-diff format)",
    )
    tr.add_argument("--log", default=None, help="JSONL metrics path")
    tr.add_argument(
        "--coverage", action="store_true",
        help="also sample the coverage sketch at every chunk boundary and "
        "draw it as a Perfetto counter track (obs.coverage; forces the "
        "serial per-chunk loop)",
    )
    tr.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (only read with "
        "--coverage)",
    )
    tr.add_argument(
        "--exposure", action="store_true",
        help="also sample the fault-exposure counters at every chunk "
        "boundary and draw one Perfetto counter track per fault class "
        "(obs.exposure; forces the serial per-chunk loop)",
    )
    tr.add_argument(
        "--margin", action="store_true",
        help="also sample the near-miss margin counters at every chunk "
        "boundary and draw min_quorum_slack / near_miss_lanes Perfetto "
        "counter tracks (obs.margin; forces the serial per-chunk loop)",
    )
    tr.add_argument(
        "--workload", choices=["poisson", "bursty", "diurnal", "mixed"],
        default=None, metavar="MIX",
        help="also run the open-loop client workload and draw "
        "slo_p99_ticks / queue_depth Perfetto counter tracks "
        "(workload.generator + obs.slo; forces the serial per-chunk loop; "
        "default off — off is free and schedule-identical)",
    )
    tr.add_argument(
        "--workload-rate", type=float, default=0.05, metavar="P",
        help="per-tick arrival probability per lane (only read with "
        "--workload)",
    )

    st = sub.add_parser(
        "stats",
        help="summarize a JSONL metrics stream written by run/soak --log",
    )
    st.add_argument(
        "path", nargs="?", default=None,
        help="JSONL metrics file (omit when using --fleet-root)",
    )
    st.add_argument(
        "--prometheus", action="store_true",
        help="print the Prometheus text exposition instead of a JSON summary",
    )
    st.add_argument(
        "--fleet-root", default=None, metavar="DIR",
        help="fleet observatory mode: read the time-series journals under "
        "a fleet queue root (series/*.jsonl), rendering per-worker "
        "last-sample rows + the fleet aggregate; with --follow, tails "
        "them until the coordinator's merged_series.jsonl lands",
    )
    st.add_argument(
        "--series-gate", action="store_true",
        help="with --fleet-root: run the trend gate (obs.timeseries."
        "compare_series) over the collected rows and exit 2 on findings",
    )
    st.add_argument(
        "--follow", action="store_true",
        help="tail the stream: re-render the summary every --interval "
        "seconds as new records land (watch a running soak from a second "
        "terminal); stops when a 'final' record arrives",
    )
    st.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="seconds between --follow re-renders (default 2)",
    )
    st.add_argument(
        "--max-renders", type=int, default=0, metavar="N",
        help="with --follow: stop after N renders even without a 'final' "
        "record (0 = unbounded; the scriptable exit hatch)",
    )

    bc = sub.add_parser(
        "bench-compare",
        help="diff a fresh bench.py --record file against committed history "
        "and gate on regression (exit 2) with a noise-aware tolerance",
    )
    bc.add_argument(
        "--baseline", default="BENCH_SWEEP.json", metavar="PATH",
        help="committed bench artifact (a JSON list of rows; default "
        "BENCH_SWEEP.json)",
    )
    bc.add_argument(
        "--fresh", default=None, metavar="PATH",
        help="fresh bench.py --record output to judge; omitted = compare "
        "the baseline against itself (the CI sanity check: must exit 0)",
    )
    bc.add_argument(
        "--tolerance", type=float, default=0.10, metavar="T",
        help="minimum allowed relative drop before a case regresses "
        "(default 0.10); widened per case to noise-k x the baseline's own "
        "sample CV — see obs.perf.compare_benches",
    )
    bc.add_argument(
        "--noise-k", type=float, default=3.0, metavar="K",
        help="noise multiplier on the baseline coefficient of variation "
        "(default 3.0)",
    )

    c = sub.add_parser(
        "check",
        help="bounded exhaustive model check: every schedule of a small instance",
    )
    c.add_argument("--n-prop", type=int, default=2)
    c.add_argument("--n-acc", type=int, default=3)
    c.add_argument(
        "--max-round", type=int, nargs="+", default=[1],
        help="retry bound; one value for all proposers or one per proposer",
    )
    c.add_argument("--max-states", type=int, default=5_000_000)
    c.add_argument(
        "--unsafe-accept", action="store_true",
        help="inject the accept-below-promise bug (must find a counterexample)",
    )
    c.add_argument(
        "--protocol",
        choices=["paxos", "multipaxos", "fastpaxos", "raftcore", "synchpaxos"],
        default="paxos",
        help="which protocol's bounded model to enumerate",
    )
    c.add_argument(
        "--log-len", type=int, default=2,
        help="multipaxos only: bounded log length per instance",
    )
    c.add_argument(
        "--no-recovery", action="store_true",
        help="multipaxos only: inject the skipped-promise-fold bug (a new "
        "leader drives its own values from slot 0; must find a "
        "counterexample)",
    )
    c.add_argument(
        "--adopt-any", action="store_true",
        help="fastpaxos only: inject the wrong-recovery bug (adopt any "
        "reported value instead of the choosable rule)",
    )
    c.add_argument(
        "--q1", type=int, default=0,
        help="fastpaxos only: FFP phase-1 quorum (0 = majority)",
    )
    c.add_argument(
        "--q2", type=int, default=0,
        help="fastpaxos only: FFP phase-2 quorum (0 = majority)",
    )
    c.add_argument(
        "--q-fast", type=int, default=0,
        help="fastpaxos only: FFP fast quorum (0 = ceil(3n/4))",
    )
    c.add_argument(
        "--unsafe-fast", action="store_true",
        help="synchpaxos only: inject the delay-unsafe fast commit (decide "
        "the fast round on the FIRST ack, no quorum — the 'one ack implies "
        "synchrony held' shortcut; must find a counterexample)",
    )
    c.add_argument(
        "--no-restriction", action="store_true",
        help="raftcore only: disable the election restriction (one of the "
        "two safety legs; clean alone, violates with --no-adoption)",
    )
    c.add_argument(
        "--no-adoption", action="store_true",
        help="raftcore only: candidates ignore vote-reply entries (the "
        "other safety leg; clean alone, violates with --no-restriction)",
    )
    c.add_argument(
        "--liveness-bound", type=int, default=None, metavar="N",
        help="arm the mechanized liveness leg: from EVERY reachable state, "
        "the deterministic fair completion schedule must decide within N "
        "actions (reports the max actually needed); any protocol",
    )
    c.add_argument(
        "--native", action="store_true",
        help="run the native (C++) explorer — same transition system and "
        "GC as the Python checker for all four protocols, ~20-150x "
        "faster, counts cross-validated bit-for-bit; traces and the "
        "liveness leg stay Python-side",
    )
    c.add_argument(
        "--progress-every", type=int, default=0, metavar="N",
        help="native explorer: print a stderr progress line every N states",
    )
    c.add_argument(
        "--livelock-bug", action="store_true",
        help="inject the protocol's livelock bug (paxos/multipaxos: retry "
        "without ballot increase; raftcore: re-election without term bump; "
        "fastpaxos: retry the fast round instead of classic recovery) — "
        "--liveness-bound must then find a lasso counterexample",
    )

    a = sub.add_parser(
        "audit",
        help="static determinism audit: trace every protocol x config cell "
        "and check PRNG streams, purity, and (optionally) pytree structure "
        "against the core.streams registry — nothing executes",
    )
    a.add_argument(
        "--protocol", action="append", dest="protocols", metavar="NAME",
        choices=["paxos", "multipaxos", "fastpaxos", "raftcore", "synchpaxos"],
        help="restrict to one protocol (repeatable; default: all five)",
    )
    a.add_argument(
        "--config", action="append", dest="configs", metavar="NAME",
        choices=["default", "gray-chaos", "corrupt", "stale", "delay-chaos",
                 "telemetry", "coverage", "exposure", "margin", "workload"],
        help="restrict to one audit config (repeatable; default: all ten)",
    )
    a.add_argument(
        "--structure", action="store_true",
        help="also run the default-off leaf checks and the golden "
        "treedef/config-fingerprint diffs (release gate; default off)",
    )
    a.add_argument(
        "--no-lint", action="store_true",
        help="skip the AST host-entropy lint over the traced packages",
    )
    a.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full report as JSON instead of the text summary",
    )
    a.add_argument(
        "--record-goldens", action="store_true",
        help="print a fresh goldens table (paste into analysis/goldens.py "
        "after an intentional structure change) instead of auditing",
    )

    cv = sub.add_parser(
        "coverage",
        help="coverage plane: run a campaign with the on-device Bloom "
        "sketch and print the coverage curve; --exact instead runs the "
        "exhaustive probe (check/coverage) plus the sketch-vs-exact "
        "calibration cross-check",
    )
    cv.add_argument(
        "--exact", action="store_true",
        help="exact probe mode (CPU): enumerate the bounded schedule "
        "space, measure fuzz occupancy, and cross-check the sketch "
        "estimator against the exact visited set",
    )
    # Sketch-campaign mode knobs (any config, any scale).
    cv.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    cv.add_argument("--engine", choices=["xla", "fused"], default="xla")
    cv.add_argument("--n-inst", type=int, default=None,
                    help="instance count (default: config default; "
                    "--exact default 4096)")
    cv.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    cv.add_argument("--seed", type=int, default=0)
    cv.add_argument("--ticks", type=int, default=None,
                    help="total ticks (default 256; --exact default 48)")
    cv.add_argument("--chunk", type=int, default=64)
    cv.add_argument(
        "--words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (m = 32*W Bloom bits; "
        "power of two)",
    )
    cv.add_argument("--log", default=None, help="JSONL metrics path")
    # Exact-probe mode knobs (scripts/coverage_probe.py, folded in).
    cv.add_argument("--n-prop", type=int, default=2)
    cv.add_argument("--n-acc", type=int, default=3)
    cv.add_argument(
        "--max-round", type=int, nargs="+", default=[1, 0],
        help="--exact: retry bounds (one per proposer, or one for all)",
    )
    cv.add_argument("--seeds", type=int, default=12,
                    help="--exact: probe campaigns to rotate through")
    cv.add_argument("--seed0", type=int, default=0)
    cv.add_argument("--max-states", type=int, default=50_000_000)
    cv.add_argument("--record", default=None, metavar="PATH",
                    help="--exact: also write the report JSON to PATH")
    cv.add_argument(
        "--analyze-residue", action="store_true",
        help="--exact: append residue_analysis (what the UNREACHED states "
        "share) to the report",
    )
    cv.add_argument(
        "--profile", type=int, default=None,
        help="--exact: pin ONE portfolio profile index for every seed "
        "(default: rotate the full portfolio)",
    )
    cv.add_argument(
        "--no-crosscheck", action="store_true",
        help="--exact: skip the sketch-vs-exact calibration pass",
    )

    ex = sub.add_parser(
        "exposure",
        help="fault-exposure plane: run a campaign with the injected-vs-"
        "effective counters on and print the per-class exposure matrix "
        "plus the chunk-granular attribution table (which classes were "
        "live while coverage grew / violations fired)",
    )
    ex.add_argument("--config", choices=sorted(CONFIGS), default="gray-chaos")
    ex.add_argument("--engine", choices=["xla", "fused"], default="xla")
    ex.add_argument("--n-inst", type=int, default=None)
    ex.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    ex.add_argument("--seed", type=int, default=0)
    ex.add_argument("--ticks", type=int, default=256)
    ex.add_argument("--chunk", type=int, default=64)
    ex.add_argument(
        "--coverage", action="store_true",
        help="also run the coverage sketch so the attribution table can "
        "credit new bits to the fault classes live in each chunk",
    )
    ex.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (only read with "
        "--coverage)",
    )
    ex.add_argument("--log", default=None, help="JSONL metrics path")
    ex.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full report as JSON instead of the text tables",
    )

    mg = sub.add_parser(
        "margin",
        help="near-miss margin plane: run a campaign with the distance-to-"
        "violation counters on and print the per-chunk min-slack curve, "
        "the tightest-lane ranking, and the correlation table against "
        "coverage growth and effective faults (obs.margin)",
    )
    mg.add_argument("--config", choices=sorted(CONFIGS), default="corrupt")
    mg.add_argument("--engine", choices=["xla", "fused"], default="xla")
    mg.add_argument("--n-inst", type=int, default=None)
    mg.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    mg.add_argument("--seed", type=int, default=0)
    mg.add_argument("--ticks", type=int, default=256)
    mg.add_argument("--chunk", type=int, default=64)
    mg.add_argument(
        "--coverage", action="store_true",
        help="also run the coverage sketch so the correlation table can "
        "join tightening chunks against new union bits",
    )
    mg.add_argument(
        "--coverage-words", type=int, default=64, metavar="W",
        help="sketch size in int32 words per lane (only read with "
        "--coverage)",
    )
    mg.add_argument(
        "--exposure", action="store_true",
        help="also run the fault-exposure counters so the correlation "
        "table can join tightening chunks against effective-fault deltas",
    )
    mg.add_argument(
        "--lanes", type=int, default=8, metavar="N",
        help="how many tightest lanes to rank in the report",
    )
    mg.add_argument("--log", default=None, help="JSONL metrics path")
    mg.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full report as JSON instead of the text tables",
    )

    sl = sub.add_parser(
        "slo",
        help="client-workload SLO plane: sweep offered load over a "
        "campaign, print the per-class client-latency table and the "
        "goodput-vs-offered curve, locate the overload knee, and gate "
        "the configured p99 SLO (exit 2 on breach; obs.slo)",
    )
    sl.add_argument("--config", choices=sorted(CONFIGS), default="config2")
    sl.add_argument("--engine", choices=["xla", "fused"], default="xla")
    sl.add_argument("--n-inst", type=int, default=None)
    sl.add_argument(
        "--fault", action="append", default=[], metavar="KEY=VALUE",
        help="override any FaultConfig knob by name (repeatable)",
    )
    sl.add_argument("--seed", type=int, default=0)
    sl.add_argument("--ticks", type=int, default=256)
    sl.add_argument("--chunk", type=int, default=64)
    sl.add_argument(
        "--mix", choices=["poisson", "bursty", "diurnal", "mixed"],
        default="mixed",
        help="arrival-class mix for every sweep point (mixed = lanes "
        "sample their class from the workload stream)",
    )
    sl.add_argument(
        "--rate", type=float, default=0.05, metavar="P",
        help="base per-tick arrival probability at sweep scale 1.0",
    )
    sl.add_argument(
        "--sweep", type=float, nargs="+", metavar="S",
        default=[0.25, 0.5, 1.0, 2.0, 4.0],
        help="offered-load scale factors: one campaign per factor at "
        "rate*S (clamped to 1.0), the goodput curve's x axis",
    )
    sl.add_argument(
        "--knee-floor", type=float, default=0.9, metavar="F",
        help="overload knee = first sweep point with done/offered < F",
    )
    sl.add_argument(
        "--slo-p99", type=int, default=0, metavar="T",
        help="p99 SLO in ticks, gated at sweep scale 1.0: any class "
        "whose served p99 exceeds T exits 2 (0 = report only)",
    )
    sl.add_argument("--log", default=None, help="JSONL metrics path")
    sl.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full report as JSON instead of the text tables",
    )
    return p


def _telemetry_from_args(args: argparse.Namespace):
    """The run subcommand's telemetry knobs as a TelemetryConfig (or None)."""
    if not (args.telemetry or args.record or args.hist_bins):
        return None
    from paxos_tpu.core.telemetry import TelemetryConfig

    # --record / --hist-bins imply counters: the ring and histogram are
    # refinements of the same recorder, not independent devices.
    return TelemetryConfig(
        counters=True, ring_depth=args.record, hist_bins=args.hist_bins
    )


def _coverage_from_args(args: argparse.Namespace, words_attr: str = "coverage_words"):
    """The --coverage knobs as a CoverageConfig (or None when off)."""
    if not getattr(args, "coverage", False):
        return None
    from paxos_tpu.obs.coverage import CoverageConfig

    try:
        return CoverageConfig(words=getattr(args, words_attr))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(1)


def _exposure_from_args(args: argparse.Namespace):
    """The --exposure flag as an ExposureConfig (or None when off)."""
    if not getattr(args, "exposure", False):
        return None
    from paxos_tpu.obs.exposure import ExposureConfig

    return ExposureConfig(counters=True)


def _margin_from_args(args: argparse.Namespace):
    """The --margin flag as a MarginConfig (or None when off)."""
    if not getattr(args, "margin", False):
        return None
    from paxos_tpu.obs.margin import MarginConfig

    return MarginConfig(counters=True)


def _workload_from_args(args: argparse.Namespace):
    """The --workload knobs as a WorkloadConfig (or None when off)."""
    mix = getattr(args, "workload", None)
    if not mix:
        return None
    from paxos_tpu.workload.generator import WorkloadConfig

    wl = WorkloadConfig(
        mix=mix,
        rate=args.workload_rate,
        slo_p99_ticks=getattr(args, "slo_p99", 0),
    )
    try:
        wl.validate()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(1)
    return wl


def _warn_checker_incomplete(report: dict) -> None:
    """Loud stderr warning when the safety oracle lost rows (satellite:
    an eviction means a violation could have been MISSED, so a clean
    violations=0 from this campaign is weaker than it looks)."""
    ev = report.get("evictions", 0)
    if ev:
        print(f"warning: learner table evicted {ev} row(s) — the safety "
              "checker is INCOMPLETE for this campaign (a quorum on an "
              "evicted (ballot, value) row would not have been flagged); "
              "treat violations=0 as unverified, raise the table capacity "
              "or shorten the campaign", file=sys.stderr)


def cmd_run(args: argparse.Namespace) -> int:
    from paxos_tpu.harness.metrics import MetricsLog

    if args.checkpoint_every and not args.checkpoint_dir:
        print("error: --checkpoint-every requires --checkpoint-dir", file=sys.stderr)
        return 1

    # Context-managed so the JSONL stream closes on EVERY exit path —
    # early-return errors, MeasurementCorrupted unwinds, and violations.
    with MetricsLog(args.log) as log:
        return _cmd_run_logged(args, log)


def _cmd_run_logged(args: argparse.Namespace, log) -> int:
    import dataclasses

    import jax

    from paxos_tpu.harness import checkpoint as ckpt
    from paxos_tpu.harness import trace as trace_mod
    from paxos_tpu.harness.metrics import MetricsRegistry
    from paxos_tpu.harness.run import (
        MeasurementCorrupted,
        init_plan,
        init_state,
        make_advance,
        make_longlog,
        summarize,
    )
    from paxos_tpu.parallel.mesh import make_mesh, shard_pytree

    # Dispatch-pipeline depth (harness.pipeline).  An explicit
    # --pipeline-depth is refused with --resume (same rule as --record: a
    # resumed campaign keeps the serial per-chunk cadence its checkpoint
    # lineage was recorded under); otherwise the depth defaults to 4 and
    # auto-degrades to 1 for consumers that need per-chunk host work
    # (--shard, --events, --checkpoint-every) or a resumed campaign.
    if args.pipeline_depth is not None and args.resume:
        print("error: --pipeline-depth cannot be combined with --resume "
              "(resumed campaigns keep the serial per-chunk loop their "
              "checkpoint cadence was recorded under; same rule as "
              "--record)", file=sys.stderr)
        return 1
    try:
        depth = config_mod.validate_pipeline_depth(
            4 if args.pipeline_depth is None else args.pipeline_depth
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    serial_needs = [
        flag for flag, on in (
            ("--resume", bool(args.resume)),
            ("--shard", args.shard),
            ("--events", args.events),
            ("--checkpoint-every", bool(args.checkpoint_every)),
        ) if on
    ]
    if depth > 1 and serial_needs:
        # Always say so (satellite of the silent-degrade bug): an operator
        # reading throughput off a run that quietly fell back to depth 1
        # would compare serial numbers against pipelined expectations.
        print(f"warning: {', '.join(serial_needs)} needs per-chunk host "
              f"work; pipeline depth {depth} degraded to 1 "
              f"({'explicit' if args.pipeline_depth is not None else 'default'}"
              " --pipeline-depth overridden)", file=sys.stderr)
        depth = 1

    tel_cfg = _telemetry_from_args(args)
    cov_cfg = _coverage_from_args(args)
    expo_cfg = _exposure_from_args(args)
    mar_cfg = _margin_from_args(args)
    wl_cfg = _workload_from_args(args)
    registry = MetricsRegistry()
    registry.gauge("pipeline_depth_effective", depth)
    # Host span recorder (--span-trace / --perf): the CLI owns the wall
    # clock and injects it — the obs package itself stays clock-free
    # (purity audit).  The perf plane is derived entirely from these spans.
    recorder = None
    if args.span_trace or args.perf:
        import time

        from paxos_tpu.obs.host_spans import HostSpanRecorder

        recorder = HostSpanRecorder(time.perf_counter)
    if args.resume:
        if args.fault:
            print("error: --fault cannot be combined with --resume (the "
                  "checkpoint's fault config is part of its schedule "
                  "stream)", file=sys.stderr)
            return 1
        if tel_cfg is not None:
            print("error: --telemetry/--record/--hist-bins cannot be "
                  "combined with --resume (the recorder's arrays are part "
                  "of the checkpointed state structure)", file=sys.stderr)
            return 1
        if cov_cfg is not None:
            print("error: --coverage cannot be combined with --resume (the "
                  "sketch's arrays are part of the checkpointed state "
                  "structure; same rule as --telemetry)", file=sys.stderr)
            return 1
        if expo_cfg is not None:
            print("error: --exposure cannot be combined with --resume (the "
                  "counters' arrays are part of the checkpointed state "
                  "structure; same rule as --telemetry)", file=sys.stderr)
            return 1
        if mar_cfg is not None:
            print("error: --margin cannot be combined with --resume (the "
                  "counters' arrays are part of the checkpointed state "
                  "structure; same rule as --telemetry)", file=sys.stderr)
            return 1
        if wl_cfg is not None:
            print("error: --workload cannot be combined with --resume (the "
                  "queue's arrays are part of the checkpointed state "
                  "structure; same rule as --telemetry)", file=sys.stderr)
            return 1
        # Stream-lineage guard (VERDICT r4 weak#3): refuse to resume under
        # a different engine/block than the one that wrote the snapshot.
        state, plan, cfg = ckpt.restore(
            args.resume, engine=args.engine, block=args.block
        )
        log.emit("resume", path=args.resume, tick=int(state.tick))
    else:
        kw = {"seed": args.seed}
        if args.n_inst:
            kw["n_inst"] = args.n_inst
        cfg = CONFIGS[args.config](**kw)
        try:
            cfg = config_mod.apply_fault_overrides(cfg, args.fault)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if tel_cfg is not None:
            cfg = dataclasses.replace(cfg, telemetry=tel_cfg)
        if cov_cfg is not None:
            cfg = dataclasses.replace(cfg, coverage=cov_cfg)
        if expo_cfg is not None:
            cfg = dataclasses.replace(cfg, exposure=expo_cfg)
        if mar_cfg is not None:
            cfg = dataclasses.replace(cfg, margin=mar_cfg)
        if wl_cfg is not None:
            cfg = dataclasses.replace(cfg, workload=wl_cfg)
        state, plan = init_state(cfg), init_plan(cfg)

    if args.shard:
        mesh = make_mesh()
        state = shard_pytree(state, mesh, cfg.n_inst)
        plan = shard_pytree(plan, mesh, cfg.n_inst)
        log.emit("mesh", devices=len(mesh.devices))

    ll = make_longlog(cfg)
    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "off-TPU only the Pallas interpreter can replay the fused "
              "stream (shrink uses it for repro) — far too slow for "
              "campaigns; use --engine xla",
              file=sys.stderr)
        return 1
    # ONE dispatch for every engine x sharding x long-log combination
    # (make_advance; the XLA engine ignores the mesh — sharded inputs
    # alone drive pjit).
    advance = make_advance(
        cfg, plan, args.engine, block=args.block, compact=bool(ll),
        mesh=mesh if (args.shard and args.engine == "fused") else None,
    )

    log.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
             n_inst=cfg.n_inst, protocol=cfg.protocol, engine=args.engine)

    def observe(**kw):
        # The ballot-overflow guard (harness.run.summarize) raises
        # MeasurementCorrupted when a campaign's measurements stop being
        # trustworthy — surface that as a structured CLI failure (logged,
        # clean message, exit 1), not a raw traceback.  Infrastructure
        # RuntimeErrors (XLA OOMs etc.) keep their tracebacks.
        try:
            return summarize(state, log_total=cfg.fault.log_total, **kw)
        except MeasurementCorrupted as e:
            log.emit("error", message=str(e), tick=int(state.tick))
            print(f"error: {e}", file=sys.stderr)
            raise SystemExit(1)

    from paxos_tpu.obs.host_spans import ensure_recorder

    sp = ensure_recorder(recorder)
    done, since_ckpt = 0, 0
    if depth > 1:
        # Pipelined loop: grouped dispatches, async done-flag probe, and
        # light per-dispatch chunk records (the full report — including
        # telemetry totals, which accumulate on-device — lands in `final`).
        from paxos_tpu.harness.pipeline import pipelined_run
        from paxos_tpu.harness.run import all_chosen_flag, make_advance_grouped

        advance_g = make_advance_grouped(
            cfg, plan, args.engine, block=args.block, compact=bool(ll)
        )
        done_fn = None
        if args.until_all_chosen:
            done_fn = ll.done_flag if ll else all_chosen_flag
        with trace_mod.profile(args.trace):
            state, done, _ = pipelined_run(
                state, advance_g, budget=args.ticks, chunk=args.chunk,
                depth=depth, done_fn=done_fn, spans=recorder,
                on_dispatch=lambda t: log.emit(
                    "chunk", ticks=t, pipelined=True
                ),
            )
    else:
        with trace_mod.profile(args.trace):
            while done < args.ticks:
                n = min(args.chunk, args.ticks - done)
                with sp.span("dispatch", tick_start=done, ticks=n, groups=1):
                    state = advance(state, n)
                done += n
                since_ckpt += n
                with sp.span("report", tick=done):
                    rep = observe()
                log.emit("chunk", **rep)
                if "telemetry" in rep:
                    registry.ingest(rep["telemetry"])
                if "coverage" in rep:
                    registry.ingest_coverage(rep["coverage"])
                if "exposure" in rep:
                    registry.ingest_exposure(rep["exposure"])
                if "margin" in rep:
                    registry.ingest_margin(
                        rep["margin"], rep.get("checker_complete")
                    )
                if "slo" in rep:
                    registry.ingest_slo(
                        rep["slo"], cfg.workload.slo_p99_ticks
                    )
                if args.events:
                    # Registry-routed (and into the JSONL stream), with the
                    # historical stderr line kept for eyeball debugging.
                    rec = trace_mod.event_dump(
                        state, stream=sys.stderr, registry=registry
                    )
                    log.emit("events", **rec)
                if args.checkpoint_every and since_ckpt >= args.checkpoint_every:
                    with sp.span("checkpoint", tick=done):
                        ckpt.save(args.checkpoint_dir, state, plan, cfg,
                                  engine=args.engine, block=args.block)
                    log.emit("checkpoint", path=args.checkpoint_dir,
                             tick=int(state.tick))
                    since_ckpt = 0
                # Exact check (a float32 mean can round to != 1.0 at huge
                # scales).
                if args.until_all_chosen:
                    if (ll.done(state) if ll
                            else bool(state.learner.chosen.all())):
                        break

    # The final readback is where async dispatch catches up with the host;
    # spanned so the perf plane's wall clock covers real device completion.
    with sp.span("report", tick=done):
        report = observe(liveness=args.liveness)
    report["config_fingerprint"] = cfg.fingerprint()
    # EFFECTIVE depth, always: the requested depth may have been degraded
    # above, and a silent fallback must not be invisible in the report.
    report["pipeline_depth"] = depth
    if args.checkpoint_dir:
        ckpt.save(args.checkpoint_dir, state, plan, cfg,
                  engine=args.engine, block=args.block)
        log.emit("checkpoint", path=args.checkpoint_dir, tick=int(state.tick))
    if "telemetry" in report:
        registry.ingest(report["telemetry"])
    if "coverage" in report:
        registry.ingest_coverage(report["coverage"])
    if "exposure" in report:
        from paxos_tpu.faults.injector import exposure_lit
        from paxos_tpu.obs.exposure import annotate_lit

        report["exposure"] = annotate_lit(report["exposure"], cfg.fault)
        registry.ingest_exposure(
            report["exposure"], lit=exposure_lit(cfg.fault)
        )
    if "margin" in report:
        registry.ingest_margin(
            report["margin"], report.get("checker_complete")
        )
    if "slo" in report:
        registry.ingest_slo(report["slo"], cfg.workload.slo_p99_ticks)
    _warn_checker_incomplete(report)
    if args.perf:
        from paxos_tpu.obs import perf as perf_mod

        perf = perf_mod.perf_summary(recorder, cfg.n_inst)
        if args.engine == "fused" and "dispatches" in perf:
            from paxos_tpu.harness.checkpoint import stream_id
            from paxos_tpu.utils import bitops

            sid = stream_id(cfg, args.engine, block=args.block)
            vmem = perf_mod.vmem_gauges(
                bitops.codec_for(cfg.protocol, state).bytes_per_lane(state),
                sid.get("block"),
            )
            if vmem:
                perf["vmem"] = vmem
        report["perf"] = perf
        registry.ingest_perf(perf)
    if args.span_trace:
        from paxos_tpu.obs.export import write_chrome_trace

        write_chrome_trace(
            args.span_trace, {}, host=recorder,
            meta={"config": args.config, "engine": args.engine},
        )
        log.emit("span_trace", path=args.span_trace,
                 host_spans=len(recorder.spans))
    snap = registry.snapshot()
    if snap["counters"] or snap["histograms"] or snap.get("gauges"):
        log.emit("metrics", **snap)
    log.emit("final", **report)
    print(json.dumps(report))
    return 0 if report["violations"] == 0 else 2


def cmd_sweep(args: argparse.Namespace) -> int:
    """Run the three vote kernels on the same fault schedule; print one JSON
    comparison (the sweep analog of 'print the decided value')."""
    from paxos_tpu.harness import config as cfg_mod
    from paxos_tpu.harness.metrics import MetricsLog
    from paxos_tpu.harness.run import run

    with MetricsLog(args.log) as log:
        results = {}
        worst = 0
        for cfg in cfg_mod.config5_sweep(n_inst=args.n_inst, seed=args.seed):
            rep = run(
                cfg,
                until_all_chosen=True,
                max_ticks=args.ticks,
                chunk=args.chunk,
            )
            log.emit("protocol", protocol=cfg.protocol, **rep)
            results[cfg.protocol] = rep
            worst = max(worst, rep["violations"])

        def liveness_key(p: str):
            # More decided instances wins; among equals, earlier decisions
            # win.  An undecided protocol reports mean_choose_tick -1.0 —
            # rank it last.
            rep = results[p]
            mean = rep["mean_choose_tick"]
            return (-rep["chosen_frac"], mean if mean >= 0 else float("inf"))

        out = {
            "sweep": "config5",
            "n_inst": args.n_inst,
            "seed": args.seed,
            "protocols": results,
            "liveness_rank": sorted(results, key=liveness_key),
        }
        log.emit("final", **out)
    print(json.dumps(out))
    return 0 if worst == 0 else 2


def cmd_soak(args: argparse.Namespace) -> int:
    """Accumulate instance-rounds across rotating seeds; exit 2 on violations."""
    import jax

    from paxos_tpu.harness.soak import soak

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused needs a TPU (the off-TPU interpreter is "
              "far too slow for soak campaigns); use --engine xla",
              file=sys.stderr)
        return 1
    try:
        depth = config_mod.validate_pipeline_depth(args.pipeline_depth)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    cov_cfg = _coverage_from_args(args)
    if cov_cfg is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, coverage=cov_cfg)
    expo_cfg = _exposure_from_args(args)
    if expo_cfg is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, exposure=expo_cfg)
    mar_cfg = _margin_from_args(args)
    if mar_cfg is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, margin=mar_cfg)
    wl_cfg = _workload_from_args(args)
    if wl_cfg is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, workload=wl_cfg)
    band = args.min_replication
    if band is None:
        rec = config_mod.REPLICATION_RATES.get(args.config)
        if rec is not None and args.ticks_per_seed < 256:
            # The recorded rate is steady-state; short budgets spend most
            # ticks on warmup (election + first-decide latency), so no
            # defensible default band exists — report the rate ungated.
            rec = None
        if rec is not None:
            # The recorded rate is slots/lane-tick while the log lasts, but
            # two mathematical ceilings cap what a HEALTHY run can achieve:
            # a budget long enough to finish the whole log caps it at
            # log_total/ticks_per_seed, and compaction only advancing `base`
            # at chunk boundaries caps it at window/chunk.  Gate at 0.7x
            # (the perf gate's band discipline) of the lowest of the three,
            # else a fully-replicated or coarse-chunk soak would fail while
            # perfectly healthy.
            cap = min(
                cfg.fault.log_total / args.ticks_per_seed,
                cfg.log_len / args.chunk,
            )
            band = round(0.7 * min(rec, cap), 6)
    elif band and not (cfg.protocol == "multipaxos" and cfg.fault.log_total):
        # An explicit band on a config that never reports slots_replicated
        # would be silently inert (the gate never evaluates) — refuse.
        print(f"error: --min-replication needs a long-log config "
              f"(got {args.config}, which reports no replication rate)",
              file=sys.stderr)
        return 1
    from paxos_tpu.harness.metrics import MetricsLog

    recorder = None
    if args.span_trace or args.perf:
        import time

        from paxos_tpu.obs.host_spans import HostSpanRecorder

        recorder = HostSpanRecorder(time.perf_counter)
    with MetricsLog(args.log) as mlog:
        mlog.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
                  n_inst=cfg.n_inst, protocol=cfg.protocol, engine=args.engine)
        report = soak(
            cfg,
            target_rounds=args.target_rounds,
            ticks_per_seed=args.ticks_per_seed,
            chunk=args.chunk,
            engine=args.engine,
            log=lambda s: print(f"# {s}", file=sys.stderr),
            min_slots_per_lane_tick=band or None,
            pipeline_depth=depth,
            spans=recorder,
            plateau_seeds=args.plateau_seeds,
            plateau_min_new=args.plateau_min_new,
            plateau_stop=args.plateau_stop,
            # Per-seed throughput trend, streamed as it lands so `stats
            # --follow` over this JSONL shows the live cadence.
            on_seed=lambda rec: mlog.emit("seed", **rec),
        )
        report["config"] = args.config
        if args.perf:
            from paxos_tpu.obs import perf as perf_mod

            report["perf"] = perf_mod.perf_summary(recorder, cfg.n_inst)
        if ("coverage" in report or "exposure" in report
                or "margin" in report or "slo" in report or args.perf):
            # Cross-seed coverage/exposure/margin/perf as gauges, so `stats
            # --prometheus` over this JSONL stream exposes the curve's
            # endpoint, the plateau, per-class exposure totals, the
            # near-miss minima, and the campaign-loop throughput/occupancy.
            from paxos_tpu.harness.metrics import MetricsRegistry

            registry = MetricsRegistry()
            if "coverage" in report:
                registry.ingest_coverage(report["coverage"])
                registry.gauge(
                    "coverage_plateau", float(report["coverage"]["plateau"])
                )
            if "exposure" in report:
                from paxos_tpu.faults.injector import exposure_lit

                registry.ingest_exposure(
                    report["exposure"], lit=exposure_lit(cfg.fault)
                )
            if "margin" in report:
                registry.ingest_margin(
                    report["margin"], report.get("checker_complete")
                )
            if "slo" in report:
                registry.ingest_slo(
                    report["slo"], cfg.workload.slo_p99_ticks
                )
            if args.perf:
                registry.ingest_perf(report["perf"])
            mlog.emit("metrics", **registry.snapshot())
        if args.span_trace:
            from paxos_tpu.obs.export import write_chrome_trace

            write_chrome_trace(
                args.span_trace, {}, host=recorder,
                meta={"config": args.config, "engine": args.engine},
            )
            mlog.emit("span_trace", path=args.span_trace,
                      host_spans=len(recorder.spans))
        if report["violations"]:
            # emit() flushes per record, so the violation tally is durable
            # in the JSONL stream even if the process dies right after.
            mlog.emit("violation", violations=report["violations"],
                      violating_seeds=report.get("violating_seeds"))
        _warn_checker_incomplete(report)
        mlog.emit("final", **report)
    print(json.dumps(report))
    if report["violations"]:
        return 2
    if "measurement_corrupted" in report:
        # A seed's measurements went untrustworthy (ballot overflow): the
        # tally above covers only the seeds BEFORE it — fail, don't let a
        # truncated soak read as a completed one.
        print(f"error: seed {report['measurement_corrupted']} corrupted its "
              "measurements (see stderr); tally truncated", file=sys.stderr)
        return 1
    if not report.get("replication_ok", True):
        return 3
    return 0


def cmd_fuzz(args: argparse.Namespace) -> int:
    """Corpus-driven guided campaigns; exit 2 on violations (repro shrunk).

    Drives ``fuzz.schedule.GuidedSource`` through the same soak worker
    loop as ``cmd_soak`` — one code path, two campaign sources.  On any
    safety violation the violating campaign's plan is delta-debugged to a
    minimal repro (``harness.shrink`` with the explicit plan) and the
    repro rides the report margin- and exposure-annotated, exactly like a
    ``shrink`` invocation would print.
    """
    import dataclasses

    import jax

    from paxos_tpu.fuzz.schedule import FuzzParams, GuidedSource
    from paxos_tpu.harness.soak import soak

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused needs a TPU (the off-TPU interpreter is "
              "far too slow for fuzz campaigns); use --engine xla",
              file=sys.stderr)
        return 1
    try:
        depth = config_mod.validate_pipeline_depth(args.pipeline_depth)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    from paxos_tpu.obs.coverage import CoverageConfig

    try:
        cfg = dataclasses.replace(
            cfg, coverage=CoverageConfig(words=args.coverage_words)
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    say = lambda s: print(f"# {s}", file=sys.stderr)  # noqa: E731
    source = GuidedSource(
        cfg,
        FuzzParams(
            campaigns=args.campaigns,
            seed_entries=args.seed_entries,
            mutations=args.mutations,
            energy_max=args.energy_max,
            plateau_seeds=args.plateau_seeds,
            plateau_min_new=args.plateau_min_new,
            rng_seed=args.rng_seed,
        ),
        ticks_per_seed=args.ticks_per_seed,
        log=say,
    )
    from paxos_tpu.harness.metrics import MetricsLog

    with MetricsLog(args.log) as mlog:
        mlog.emit("start", config=args.config, mode="fuzz",
                  fingerprint=source.cfg.fingerprint(), n_inst=cfg.n_inst,
                  protocol=cfg.protocol, engine=args.engine,
                  campaigns=args.campaigns, rng_seed=args.rng_seed)
        report = soak(
            source.cfg,
            target_rounds=args.campaigns * cfg.n_inst * args.ticks_per_seed,
            ticks_per_seed=args.ticks_per_seed,
            chunk=args.chunk,
            engine=args.engine,
            log=say,
            pipeline_depth=depth,
            plateau_seeds=args.plateau_seeds,
            plateau_min_new=args.plateau_min_new,
            on_seed=lambda rec: mlog.emit("seed", **rec),
            campaigns=source,
        )
        report["config"] = args.config
        report["fuzz"] = source.summary()
        if args.corpus_out:
            digest = source.corpus.write_journal(args.corpus_out)
            say(f"corpus journal: {args.corpus_out} (sha256 {digest[:16]})")
        if "coverage" in report or "exposure" in report or "margin" in report:
            from paxos_tpu.harness.metrics import MetricsRegistry

            registry = MetricsRegistry()
            if "coverage" in report:
                registry.ingest_coverage(report["coverage"])
                registry.gauge(
                    "coverage_plateau", float(report["coverage"]["plateau"])
                )
            if "exposure" in report:
                from paxos_tpu.faults.injector import exposure_lit

                registry.ingest_exposure(
                    report["exposure"], lit=exposure_lit(source.cfg.fault)
                )
            if "margin" in report:
                registry.ingest_margin(
                    report["margin"], report.get("checker_complete")
                )
            mlog.emit("metrics", **registry.snapshot())
        if report["violations"] and source.violating:
            # Shrink the FIRST violating campaign (deterministic pick) to
            # a minimal margin- and exposure-annotated repro — the fuzzer
            # must hand back something replayable, not just a tally.
            from paxos_tpu.harness.shrink import (
                exposure_annotation,
                margin_annotation,
                replay,
                shrink,
            )

            vcfg, vplan, eid = source.violating[0]
            say(f"violation in corpus entry {eid} (seed {vcfg.seed}); "
                "shrinking its plan")
            result = shrink(
                vcfg, max_ticks=args.ticks_per_seed, chunk=args.chunk,
                engine=args.engine, log=say, plan=vplan,
            )
            if result is not None:
                report["repro"] = {
                    "entry": eid,
                    "config_fingerprint": vcfg.fingerprint(),
                    "seed": vcfg.seed,
                    "replays": replay(vcfg, result),
                    **result.to_json(),
                    "margin": margin_annotation(vcfg, result),
                    "exposure": exposure_annotation(vcfg, result),
                }
            mlog.emit("violation", violations=report["violations"],
                      violating_seeds=report.get("violating_seeds"),
                      entry=eid)
        _warn_checker_incomplete(report)
        mlog.emit("final", **report)
    print(json.dumps(report))
    if report["violations"]:
        return 2
    if "measurement_corrupted" in report:
        print(f"error: seed {report['measurement_corrupted']} corrupted its "
              "measurements (see stderr); tally truncated", file=sys.stderr)
        return 1
    return 0


def cmd_fleet(args: argparse.Namespace) -> int:
    """Fault-tolerant sharded fleet over the durable campaign queue.

    Plans the budget into records, spawns ``--workers`` subprocesses
    (``fleet-worker``), monitors leases (reclaiming a dead worker's
    record so it re-dispatches), merges shard corpora/coverage in
    canonical record order, and optionally gates through bench-compare.
    Exit 0 clean, 1 operational failure (budget incomplete at
    ``--timeout-s``), 2 safety violations or bench regression.
    """
    from paxos_tpu.fleet import coordinator

    say = lambda s: print(f"# {s}", file=sys.stderr)  # noqa: E731
    # Fail-fast on an unbuildable record BEFORE enqueueing anything: the
    # same reconstruction every worker will do.
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    try:
        cfg = config_mod.apply_fault_overrides(
            CONFIGS[args.config](**kw), args.fault
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    records = coordinator.plan_records(
        mode=args.mode, config=args.config, n_inst=args.n_inst,
        fault=args.fault, seed=args.seed, records=args.records,
        seeds_per_record=args.seeds_per_record,
        ticks_per_seed=args.ticks_per_seed, chunk=args.chunk,
        coverage_words=args.coverage_words, engine=args.engine,
        seed_stride=args.seed_stride, rng_seed=args.rng_seed,
        campaigns_per_record=args.campaigns_per_record,
        seed_entries=args.seed_entries, mutations=args.mutations,
        energy_max=args.energy_max, workload=args.workload,
        workload_rate=args.workload_rate, slo_p99=args.slo_p99,
    )
    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry

    with MetricsLog(args.log) as mlog:
        mlog.emit("start", mode="fleet", config=args.config,
                  fingerprint=cfg.fingerprint(), workers=args.workers,
                  records=len(records), engine=args.engine,
                  chaos=bool(args.chaos))
        report, rc = coordinator.run_fleet(
            records, args.dir, args, log=say,
            on_tick=lambda g: mlog.emit("fleet", fleet=g),
        )
        registry = MetricsRegistry()
        registry.ingest_fleet(report["fleet"])
        # Per-worker drill-down as labeled series beside the aggregate
        # (the collision fix: N workers = N series, not one overwrite).
        for wid, block in (report.get("workers") or {}).items():
            registry.ingest_fleet(block, worker=wid)
        if report.get("lineage"):
            registry.ingest_lineage(report["lineage"])
        mlog.emit("metrics", **registry.snapshot())
        mlog.emit("final", **report)
    print(json.dumps(report))
    return rc


def cmd_fleet_worker(args: argparse.Namespace) -> int:
    """One fleet worker: claim records from ``--dir`` until it drains."""
    from paxos_tpu.fleet.worker import work_loop

    say = lambda s: print(f"# {s}", file=sys.stderr)  # noqa: E731
    stats = work_loop(
        args.dir, args.worker_id, lease_s=args.lease_s,
        poll_s=args.poll_s, hold_s=args.hold_s, log=say,
        sample_every=getattr(args, "sample_every", 0),
    )
    print(json.dumps(stats))
    return 0


def cmd_lineage(args: argparse.Namespace) -> int:
    """Corpus lineage: family tree + per-op payoff from a journal.

    Exit 0 on a readable journal, 1 on an unreadable one; a torn tail is
    tolerated (reported on stderr) per the journal contract.
    """
    from paxos_tpu.fuzz.corpus import load_journal
    from paxos_tpu.fuzz.lineage import (
        build_lineage,
        lineage_summary,
        op_attribution,
        render_op_table,
        render_tree,
    )
    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry

    try:
        loaded = load_journal(args.journal)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if loaded["torn_tail"]:
        print("# torn tail dropped (crash mid-append)", file=sys.stderr)
    lineage = build_lineage(loaded["events"])
    summary = lineage_summary(lineage)
    attribution = op_attribution(lineage)
    with MetricsLog(args.log) as mlog:
        registry = MetricsRegistry()
        registry.ingest_lineage(summary, attribution["ops"])
        mlog.emit("metrics", **registry.snapshot())
        mlog.emit("final", metric="lineage", summary=summary,
                  ops=attribution["ops"], totals=attribution["totals"])
    if args.json:
        print(json.dumps({
            "metric": "lineage", "summary": summary,
            "ops": attribution["ops"], "totals": attribution["totals"],
        }))
        return 0
    print(f"# entries={summary['entries']} roots={summary['roots']} "
          f"executed={summary['executed']} retired={summary['retired']} "
          f"depth_max={summary['depth_max']} "
          f"best_fitness={summary['best_fitness']}")
    if args.tree:
        print(render_tree(lineage))
        print()
    print(render_op_table(attribution))
    return 0


def cmd_audit(args: argparse.Namespace) -> int:
    """Static determinism audit: exit 0 clean, 2 on findings."""
    from paxos_tpu.analysis import run_audit
    from paxos_tpu.analysis import trace as trace_mod
    from paxos_tpu.analysis.structure import record_goldens

    if args.record_goldens:
        matrix = [
            (p, c, trace_mod.build_config(p, c))
            for p in (args.protocols or trace_mod.PROTOCOLS)
            for c in (args.configs or trace_mod.CONFIG_MATRIX)
        ]
        g = record_goldens(matrix)
        for kind in ("treedef", "config"):
            print(f"{kind.upper()}_GOLDENS = {{")
            for (p, c), v in g[kind].items():
                print(f'    ("{p}", "{c}"): "{v}",')
            print("}")
        print("LAYOUT_GOLDENS = {")
        for p, rec in g["layout"].items():
            print(f'    "{p}": {{')
            print(f'        "version": "{rec["version"]}",')
            print('        "fields": {')
            for path, desc in sorted(rec["fields"].items()):
                print(f'            "{path}":')
                print(f'                "{desc}",')
            print("        },")
            print("    },")
        print("}")
        print("EQN_GOLDENS: dict = {")
        for (p, c), v in g["eqns"].items():
            print(
                f'    ("{p}", "{c}"): '
                f'{{"xla": {v["xla"]}, "ctr": {v["ctr"]}}},'
            )
        print("}")
        return 0
    report = run_audit(
        protocols=args.protocols,
        configs=args.configs,
        structure=args.structure,
        lint=not args.no_lint,
    )
    print(report.to_json() if args.as_json else report.summary())
    return 0 if report.ok else 2


def _stats_read(path) -> "tuple[list, int]":
    """Parse a JSONL metrics file; returns (records, malformed_lines)."""
    records, malformed = [], 0
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            malformed += 1
    return records, malformed


def _stats_render(
    records: list, malformed: int, path, prometheus: bool
) -> "tuple[str, bool]":
    """One summary render; returns (text, saw_final_record)."""
    from paxos_tpu.harness.metrics import MetricsRegistry

    registry = MetricsRegistry()
    kinds: dict[str, int] = {}
    final = None
    last_tel = None
    last_agg = None
    last_cov = None
    last_exp = None
    last_margin = None
    last_checker = None
    last_perf = None
    last_seed = None
    last_fleet = None
    for rec in records:
        kind = rec.get("event", "?")
        kinds[kind] = kinds.get(kind, 0) + 1
        registry.inc("log_records_total", record=kind)
        # Perf-plane summaries ride the final report (run/soak/trace
        # --perf); the last one wins like every cumulative plane.
        perf = rec.get("perf")
        if isinstance(perf, dict) and "dispatches" in perf:
            last_perf = perf
        if kind == "seed":  # soak per-seed throughput trend
            last_seed = rec
        # Device telemetry is cumulative; the LAST report is the campaign
        # total, whether it rode a chunk record or the final one.
        if isinstance(rec.get("telemetry"), dict):
            last_tel = rec["telemetry"]
        # Same for the coverage sketch: the union only grows, so the last
        # report carries the campaign's (or soak's cross-seed) coverage.
        cov = rec.get("coverage")
        if isinstance(cov, dict) and "bits_set" in cov:
            last_cov = cov
        # Exposure counters only grow too; last report = campaign totals.
        exp = rec.get("exposure")
        if isinstance(exp, dict) and "classes" in exp:
            last_exp = exp
        # Margin minima only tighten; last report = campaign-wide minima.
        mar = rec.get("margin")
        if isinstance(mar, dict) and "min_quorum_slack" in mar:
            last_margin = mar
        if "checker_complete" in rec:
            last_checker = rec["checker_complete"]
        # Fleet gauges ride periodic "fleet" records and the final fleet
        # report; coordinator-side observations, last one wins.
        flt = rec.get("fleet")
        if isinstance(flt, dict) and "records_total" in flt:
            last_fleet = flt
        # Span-trace aggregates (`trace` subcommand) are whole-campaign
        # summaries; the last record wins for the same reason.
        if kind == "spans" and isinstance(rec.get("aggregates"), dict):
            last_agg = rec["aggregates"]
        if kind == "final":
            final = rec
    if last_tel is not None:
        registry.ingest(last_tel)
    if last_cov is not None:
        registry.ingest_coverage(last_cov)
        if "plateau" in last_cov:
            registry.gauge("coverage_plateau", float(last_cov["plateau"]))
    if last_exp is not None:
        # A report that passed through annotate_lit carries its lit list;
        # rebuild the lit map from it (stats has no FaultConfig in hand).
        registry.ingest_exposure(
            last_exp, lit={n: True for n in last_exp.get("lit", [])}
        )
    if last_margin is not None or last_checker is not None:
        registry.ingest_margin(last_margin or {}, last_checker)
    if last_agg is not None:
        registry.ingest_span_aggregates(last_agg)
    if last_perf is not None:
        registry.ingest_perf(last_perf)
    if last_seed is not None:
        registry.gauge(
            "perf_seed_rounds_per_sec", last_seed.get("rounds_per_sec", 0)
        )
    if last_fleet is not None:
        registry.ingest_fleet(last_fleet)

    saw_final = final is not None
    if prometheus:
        return registry.to_prometheus().rstrip("\n"), saw_final

    out: dict = {
        "path": str(path),
        "records": dict(sorted(kinds.items())),
        "malformed_lines": malformed,
    }
    chunks = [r for r in records if r.get("event") == "chunk"]
    if chunks:
        out["chunks"] = len(chunks)
        last = chunks[-1]
        out["last_tick"] = last.get("ticks")
        out["wall_s"] = last.get("t_wall")
    if final is not None:
        out["final"] = {
            k: final[k]
            for k in (
                "ticks", "chosen_frac", "decided_frac", "violations",
                "evictions", "engine", "config_fingerprint",
            )
            if k in final
        }
    if last_tel is not None:
        out["telemetry"] = last_tel
        if last_tel.get("hist"):
            from paxos_tpu.core.telemetry import hist_saturation

            # Recompute (rather than trust the record) so logs written
            # before the overflow flag existed still get the verdict.
            out["hist_saturation"] = hist_saturation(last_tel["hist"])
    if last_cov is not None:
        out["coverage"] = last_cov
    if last_exp is not None:
        out["exposure"] = last_exp
    if last_margin is not None:
        out["margin"] = last_margin
    if last_checker is not None:
        out["checker_complete"] = last_checker
    if last_agg is not None:
        out["span_aggregates"] = last_agg
    if last_perf is not None:
        out["perf"] = last_perf
    if last_fleet is not None:
        out["fleet"] = last_fleet
    if last_seed is not None:
        # Observer-plane enrichments (new_bits / effective / min quorum
        # slack) ride the seed events when soak runs with those planes on
        # — corpus fitness is reconstructable from this stream alone.
        out["last_seed"] = {
            k: last_seed[k]
            for k in (
                "seed", "wall_s", "rounds", "rounds_per_sec",
                "new_bits", "effective", "min_quorum_slack",
            )
            if k in last_seed
        }
    return json.dumps(out), saw_final


def _devnull_stdout() -> None:
    """Point the stdout fd at devnull after a BrokenPipeError.

    The buffered writer may still hold bytes the reader will never take;
    without this the interpreter's exit-time flush re-raises EPIPE and
    turns a clean exit into status 120.
    """
    import os

    os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


def cmd_stats(args: argparse.Namespace) -> int:
    """Summarize a JSONL metrics stream; optionally as Prometheus text.

    ``--follow`` tails the file: re-parse and re-render every
    ``--interval`` seconds (the writer flushes per record, so new seeds
    and chunks appear as they land), stopping when a ``final`` record
    arrives or after ``--max-renders`` renders.  A missing file is waited
    for rather than an error — the natural race when the watcher starts
    before the soak opens its log.

    A closed stdout (``stats ... | head``, ``| grep -q``) ends the
    command cleanly instead of tracebacking — the reader deciding it has
    seen enough is a normal way for a tailing pipeline to stop.

    ``--fleet-root`` switches the source to a fleet queue root's
    time-series journals (``series/*.jsonl``): per-worker last-sample
    rows plus a fleet aggregate, the same follow/interval machinery
    (tailing stops when the coordinator's ``merged_series.jsonl``
    lands), and optionally the trend gate (``--series-gate``, exit 2 on
    findings).
    """
    import pathlib

    if args.fleet_root:
        return _stats_fleet(args, pathlib.Path(args.fleet_root))
    if args.path is None:
        print("error: a metrics file path is required without "
              "--fleet-root", file=sys.stderr)
        return 1
    path = pathlib.Path(args.path)
    if not args.follow:
        if not path.exists():
            print(f"error: no metrics file at {path}", file=sys.stderr)
            return 1
        records, malformed = _stats_read(path)
        if not records:
            print(f"error: {path} holds no JSONL records", file=sys.stderr)
            return 1
        text, _ = _stats_render(records, malformed, path, args.prometheus)
        try:
            print(text, flush=True)
        except BrokenPipeError:
            _devnull_stdout()
        return 0

    import time

    renders = 0
    while True:
        records, malformed = (
            _stats_read(path) if path.exists() else ([], 0)
        )
        if records:
            text, saw_final = _stats_render(
                records, malformed, path, args.prometheus
            )
            try:
                print(text, flush=True)
            except BrokenPipeError:
                _devnull_stdout()
                return 0
            renders += 1
            if saw_final:
                return 0
        if args.max_renders and renders >= args.max_renders:
            return 0
        time.sleep(max(args.interval, 0.05))


def _stats_fleet_rows(root) -> "list[dict]":
    """Collect every sample row under a fleet root (torn tails dropped
    per the journal contract, unreadable journals skipped)."""
    from paxos_tpu.obs.timeseries import load_series

    rows: "list[dict]" = []
    for p in sorted((root / "series").glob("*.jsonl")):
        try:
            rows.extend(load_series(p)["rows"])
        except (OSError, ValueError):
            continue
    return rows


def _stats_fleet_render(rows: "list[dict]", root,
                        prometheus: bool) -> str:
    """Per-worker last-sample rows + the fleet aggregate."""
    last: "dict[str, dict]" = {}
    counts: "dict[str, int]" = {}
    for r in rows:
        w = str(r.get("worker", "?"))
        counts[w] = counts.get(w, 0) + 1
        prev = last.get(w)
        if prev is None or int(r.get("seq", 0)) >= int(prev.get("seq", 0)):
            last[w] = r
    agg = {"workers": len(last), "samples": len(rows),
           "seeds": 0, "rounds": 0, "violations": 0}
    for w, r in last.items():
        g = r.get("gauges", {})
        agg["seeds"] += int(g.get("worker_seeds", 0))
        agg["rounds"] += int(g.get("worker_rounds", 0))
        agg["violations"] += int(g.get("worker_violations", 0))
    if prometheus:
        from paxos_tpu.harness.metrics import MetricsRegistry

        registry = MetricsRegistry()
        for name in ("workers", "samples", "seeds", "rounds",
                     "violations"):
            registry.gauge(f"fleet_series_{name}", agg[name])
        for w, r in sorted(last.items()):
            for name, v in sorted(r.get("gauges", {}).items()):
                if isinstance(v, (int, float)):
                    registry.gauge(name, v, worker=w)
        return registry.to_prometheus()
    return json.dumps({
        "metric": "fleet_series",
        "root": str(root),
        "fleet": agg,
        "workers": {
            w: {
                "samples": counts[w],
                "record": r.get("record"),
                "clock": r.get("clock"),
                "seq": r.get("seq"),
                "gauges": r.get("gauges", {}),
            }
            for w, r in sorted(last.items())
        },
    })


def _stats_fleet(args: argparse.Namespace, root) -> int:
    """The ``stats --fleet-root`` observatory view (see cmd_stats)."""
    import time

    renders = 0
    while True:
        rows = _stats_fleet_rows(root)
        done = (root / "merged_series.jsonl").exists()
        if rows:
            try:
                print(_stats_fleet_render(rows, root, args.prometheus),
                      flush=True)
            except BrokenPipeError:
                _devnull_stdout()
                return 0
            renders += 1
        elif not args.follow:
            print(f"error: no time-series journals under {root}/series "
                  "(was the fleet run with --sample-every?)",
                  file=sys.stderr)
            return 1
        if (not args.follow or done
                or (args.max_renders and renders >= args.max_renders)):
            break
        time.sleep(max(args.interval, 0.05))
    if args.series_gate:
        from paxos_tpu.obs.timeseries import compare_series

        gate = compare_series(_stats_fleet_rows(root))
        print(json.dumps({"metric": "series_gate", **gate}))
        if not gate["ok"]:
            for f in gate["findings"]:
                print(f"# trend gate: {f['kind']} — worker "
                      f"{f['worker']} record {f['record']}",
                      file=sys.stderr)
            return 2
    return 0


def cmd_bench_compare(args: argparse.Namespace) -> int:
    """Regression-gate a fresh bench run against committed history.

    Exit 0 = every overlapping case within tolerance, 2 = regression
    beyond the noise-aware band, 1 = unusable inputs (missing files,
    schema-less rows, zero overlapping cases — a vacuous pass must not
    gate CI).  See ``obs.perf.compare_benches`` for the tolerance model.
    """
    import pathlib

    from paxos_tpu.obs import perf as perf_mod

    def load_rows(path_str: str) -> "Optional[list]":
        path = pathlib.Path(path_str)
        if not path.exists():
            print(f"error: no bench artifact at {path}", file=sys.stderr)
            return None
        try:
            data = json.loads(path.read_text())
        except json.JSONDecodeError as e:
            print(f"error: {path}: {e}", file=sys.stderr)
            return None
        rows = data if isinstance(data, list) else [data]
        if not all(isinstance(r, dict) for r in rows) or not rows:
            print(f"error: {path} is not a list of bench rows",
                  file=sys.stderr)
            return None
        return rows

    baseline = load_rows(args.baseline)
    if baseline is None:
        return 1
    fresh = baseline if args.fresh is None else load_rows(args.fresh)
    if fresh is None:
        return 1
    # Schema-gate fresh rows that claim the schema; pre-schema baselines
    # (older BENCH_SWEEP.json) are grandfathered via throughput_runs.
    bad = 0
    for row in fresh:
        if "schema" in row:
            for err in perf_mod.validate_bench_row(row):
                print(f"error: fresh row "
                      f"{row.get('case', row.get('protocol'))}: {err}",
                      file=sys.stderr)
                bad += 1
    if bad:
        return 1
    result = perf_mod.compare_benches(
        baseline, fresh, tolerance=args.tolerance, noise_k=args.noise_k
    )
    result["baseline"] = args.baseline
    result["fresh"] = args.fresh or args.baseline
    print(json.dumps(result))
    if not result["compared"]:
        print("error: no overlapping (case, engine, platform) rows — "
              "nothing was actually compared", file=sys.stderr)
        return 1
    if result["regressions"]:
        for r in result["regressions"]:
            print(f"REGRESSION: {r['case']} [{r['engine']}/{r['platform']}] "
                  f"{r['fresh_best']:.3g} vs baseline median "
                  f"{r['baseline_median']:.3g} "
                  f"(ratio {r['ratio']}, allowed drop {r['allowed_drop']})",
                  file=sys.stderr)
        return 2
    return 0


def cmd_check(args: argparse.Namespace) -> int:
    """Exhaustively model-check a bounded instance; print the space summary."""
    mr = args.max_round[0] if len(args.max_round) == 1 else tuple(args.max_round)
    # Reject flags that the selected protocol's model would silently ignore —
    # a user probing an unsafe FFP quorum without --protocol fastpaxos must
    # get an error, not a misleading "ok" from the classic checker.
    if args.protocol != "paxos" and args.unsafe_accept:
        print("error: --unsafe-accept applies to --protocol paxos only",
              file=sys.stderr)
        return 1
    if args.protocol != "fastpaxos" and (
        args.adopt_any or args.q1 or args.q2 or args.q_fast
    ):
        print("error: --adopt-any/--q1/--q2/--q-fast require "
              "--protocol fastpaxos", file=sys.stderr)
        return 1
    if args.protocol != "raftcore" and (args.no_restriction or args.no_adoption):
        print("error: --no-restriction/--no-adoption require "
              "--protocol raftcore", file=sys.stderr)
        return 1
    if args.protocol != "synchpaxos" and args.unsafe_fast:
        print("error: --unsafe-fast applies to --protocol synchpaxos only",
              file=sys.stderr)
        return 1
    if args.protocol == "synchpaxos" and (args.native or args.livelock_bug):
        print("error: --native/--livelock-bug not yet wired for "
              "--protocol synchpaxos", file=sys.stderr)
        return 1
    if args.protocol != "multipaxos" and (args.no_recovery or args.log_len != 2):
        print("error: --no-recovery/--log-len require --protocol multipaxos",
              file=sys.stderr)
        return 1
    if args.livelock_bug and args.liveness_bound is None:
        print("error: --livelock-bug needs --liveness-bound (the liveness "
              "leg is what detects it)", file=sys.stderr)
        return 1
    if args.native and args.liveness_bound is not None:
        print("error: --native excludes --liveness-bound (liveness and "
              "traces are Python-side)", file=sys.stderr)
        return 1
    try:
        if args.native:
            # ONE native dispatch + result block for the full explorer
            # matrix (all four protocols as of round 5).
            if args.protocol == "multipaxos":
                from paxos_tpu.cpu_ref.native import explore_mp_native

                nr = explore_mp_native(
                    n_prop=args.n_prop,
                    n_acc=args.n_acc,
                    log_len=args.log_len,
                    max_round=mr,
                    max_states=args.max_states,
                    no_recovery=args.no_recovery,
                    progress_every=args.progress_every,
                )
            elif args.protocol == "fastpaxos":
                from paxos_tpu.cpu_ref.native import explore_fp_native

                nr = explore_fp_native(
                    n_prop=args.n_prop,
                    n_acc=args.n_acc,
                    max_round=mr,
                    max_states=args.max_states,
                    q1=args.q1,
                    q2=args.q2,
                    q_fast=args.q_fast,
                    adopt_any=args.adopt_any,
                    progress_every=args.progress_every,
                )
            elif args.protocol == "raftcore":
                from paxos_tpu.cpu_ref.native import explore_raft_native

                nr = explore_raft_native(
                    n_prop=args.n_prop,
                    n_acc=args.n_acc,
                    max_round=mr,
                    max_states=args.max_states,
                    no_restriction=args.no_restriction,
                    no_adoption=args.no_adoption,
                    progress_every=args.progress_every,
                )
            else:
                from paxos_tpu.cpu_ref.native import explore_native

                nr = explore_native(
                    n_prop=args.n_prop,
                    n_acc=args.n_acc,
                    max_round=mr,
                    max_states=args.max_states,
                    unsafe_accept=args.unsafe_accept,
                    progress_every=args.progress_every,
                )
            print(json.dumps({
                "ok": True,
                "states": nr.states,
                "decided_states": nr.decided_states,
                "chosen_values": sorted(nr.chosen_values),
                "native": True,
                "peak_frontier": nr.peak_frontier,
            }))
            return 0
        if args.protocol == "multipaxos":
            from paxos_tpu.cpu_ref.mp_exhaustive import check_mp_exhaustive

            r = check_mp_exhaustive(
                n_prop=args.n_prop,
                n_acc=args.n_acc,
                log_len=args.log_len,
                max_round=mr,
                max_states=args.max_states,
                no_recovery=args.no_recovery,
                liveness_bound=args.liveness_bound,
                livelock_bug=args.livelock_bug,
            )
        elif args.protocol == "raftcore":
            from paxos_tpu.cpu_ref.raft_exhaustive import check_raft_exhaustive

            r = check_raft_exhaustive(
                n_prop=args.n_prop,
                n_acc=args.n_acc,
                max_round=mr,
                max_states=args.max_states,
                no_restriction=args.no_restriction,
                no_adoption=args.no_adoption,
                liveness_bound=args.liveness_bound,
                livelock_bug=args.livelock_bug,
            )
        elif args.protocol == "synchpaxos":
            from paxos_tpu.cpu_ref.sp_exhaustive import check_sp_exhaustive

            r = check_sp_exhaustive(
                n_prop=args.n_prop,
                n_acc=args.n_acc,
                max_round=mr,
                max_states=args.max_states,
                unsafe_fast=args.unsafe_fast,
                liveness_bound=args.liveness_bound,
            )
        elif args.protocol == "fastpaxos":
            from paxos_tpu.cpu_ref.fp_exhaustive import check_fp_exhaustive

            r = check_fp_exhaustive(
                n_prop=args.n_prop,
                n_acc=args.n_acc,
                max_round=mr,
                max_states=args.max_states,
                adopt_any=args.adopt_any,
                q1=args.q1,
                q2=args.q2,
                q_fast=args.q_fast,
                liveness_bound=args.liveness_bound,
                livelock_bug=args.livelock_bug,
            )
        else:
            from paxos_tpu.cpu_ref.exhaustive import check_exhaustive

            r = check_exhaustive(
                n_prop=args.n_prop,
                n_acc=args.n_acc,
                max_round=mr,
                max_states=args.max_states,
                unsafe_accept=args.unsafe_accept,
                liveness_bound=args.liveness_bound,
                livelock_bug=args.livelock_bug,
            )
    except AssertionError as e:
        print(json.dumps({"ok": False, "counterexample": str(e)}))
        return 2
    except (RuntimeError, ValueError) as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 3
    out = {
        "ok": True,
        "states": r.states,
        "decided_states": r.decided_states,
        "chosen_values": sorted(r.chosen_values),
    }
    if r.max_completion is not None:
        out["max_completion"] = r.max_completion
    print(json.dumps(out))
    return 0


def cmd_shrink(args: argparse.Namespace) -> int:
    """Minimize a failing fault schedule and print the repro as JSON."""
    from paxos_tpu.harness.shrink import replay, shrink

    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    result = shrink(
        cfg, max_ticks=args.ticks, chunk=args.chunk, engine=args.engine,
        block=args.block,
        log=lambda s: print(f"# {s}", file=sys.stderr),
    )
    if result is None:
        print(json.dumps({"config": args.config, "violation": False}))
        return 0
    out = {
        "config": args.config,
        "violation": True,
        "config_fingerprint": cfg.fingerprint(),
        "seed": args.seed,
        "replays": replay(cfg, result),
        **result.to_json(),
    }
    if args.trace_out and result.spans is not None:
        from paxos_tpu.obs.export import write_chrome_trace

        write_chrome_trace(
            args.trace_out, {result.lane: result.spans},
            meta={"config": args.config, "repro": "shrink",
                  "lane": result.lane, "ticks": result.ticks},
        )
        print(f"# trace: {args.trace_out}", file=sys.stderr)
    print(json.dumps(out))
    return 2


def cmd_trace(args: argparse.Namespace) -> int:
    """Causal round tracing: run a recorded campaign, export the unified
    device+host Perfetto timeline, and print the span summary as JSON."""
    import time

    import jax

    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry
    from paxos_tpu.obs.capture import capture_round_trace
    from paxos_tpu.obs.export import spans_jsonl, write_chrome_trace
    from paxos_tpu.obs.host_spans import HostSpanRecorder

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "use --engine xla", file=sys.stderr)
        return 1
    try:
        depth = config_mod.validate_pipeline_depth(args.pipeline_depth)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    # The CLI owns the wall clock and injects it; the obs package itself
    # never touches `time` (purity-audit scope).
    recorder = HostSpanRecorder(time.perf_counter)
    with MetricsLog(args.log) as log:
        log.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
                 n_inst=cfg.n_inst, protocol=cfg.protocol, engine=args.engine)
        cap = capture_round_trace(
            cfg, ticks=args.ticks, chunk=args.chunk, engine=args.engine,
            depth=depth, max_lanes=args.lanes, recorder=recorder,
            coverage=_coverage_from_args(args),
            exposure=_exposure_from_args(args),
            margin=_margin_from_args(args),
            workload=_workload_from_args(args),
        )
        # Perf plane (obs.perf): host throughput/occupancy as counter
        # tracks on the same unified timeline — free here, the recorder
        # already watched every dispatch.
        from paxos_tpu.obs import perf as perf_mod

        counters = dict(cap.counters or {})
        counters.update(perf_mod.perf_counter_tracks(recorder, cfg.n_inst))
        perf = perf_mod.perf_summary(recorder, cfg.n_inst)
        write_chrome_trace(
            args.out, cap.spans, host=recorder,
            meta={"config": args.config, "engine": args.engine,
                  "seed": args.seed, "ticks": args.ticks,
                  "fingerprint": cfg.fingerprint()},
            counters=counters or None,
        )
        if args.spans_out:
            with open(args.spans_out, "w") as fh:
                fh.write(spans_jsonl(
                    s for lane in cap.lanes for s in cap.spans[lane]
                ))
        registry = MetricsRegistry()
        log.emit("report", **cap.report)
        if "telemetry" in cap.report:
            registry.ingest(cap.report["telemetry"])
        if "coverage" in cap.report:
            registry.ingest_coverage(cap.report["coverage"])
        if "exposure" in cap.report:
            from paxos_tpu.faults.injector import exposure_lit

            registry.ingest_exposure(
                cap.report["exposure"], lit=exposure_lit(cfg.fault)
            )
        if "margin" in cap.report:
            registry.ingest_margin(
                cap.report["margin"], cap.report.get("checker_complete")
            )
        if "slo" in cap.report:
            registry.ingest_slo(cap.report["slo"])
        registry.ingest_span_aggregates(cap.aggregates)
        registry.ingest_perf(perf)
        log.emit("spans", lanes=cap.lanes, aggregates=cap.aggregates)
        log.emit("metrics", **registry.snapshot())
        summary = {
            "trace": args.out,
            "config": args.config,
            "engine": args.engine,
            "ticks": args.ticks,
            "lanes": cap.lanes,
            "violations": cap.report.get("violations"),
            "host_spans": len(recorder.spans),
            "perf": perf,
            **cap.aggregates,
        }
        if args.spans_out:
            summary["spans_jsonl"] = args.spans_out
        log.emit("final", **summary)
    print(json.dumps(summary))
    return 0


def cmd_coverage(args: argparse.Namespace) -> int:
    """Coverage plane: sketch campaign (default) or exact probe (--exact)."""
    if args.exact:
        return _cmd_coverage_exact(args)
    import dataclasses

    import jax

    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry
    from paxos_tpu.harness.run import (
        init_plan, init_state, make_advance, make_longlog, summarize,
    )
    from paxos_tpu.obs.coverage import CoverageConfig

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "use --engine xla", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
        cov_cfg = CoverageConfig(words=args.words)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    cfg = dataclasses.replace(cfg, coverage=cov_cfg)
    ticks = 256 if args.ticks is None else args.ticks

    registry = MetricsRegistry()
    with MetricsLog(args.log) as log:
        log.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
                 n_inst=cfg.n_inst, protocol=cfg.protocol,
                 engine=args.engine, coverage_words=args.words)
        state, plan = init_state(cfg), init_plan(cfg)
        advance = make_advance(
            cfg, plan, args.engine, compact=bool(make_longlog(cfg))
        )
        # Serial per-chunk loop: the per-chunk summarize IS the coverage
        # curve sampler (the sketch reduces at the summarize boundary).
        curve: list = []
        done = 0
        prev_bits = 0
        while done < ticks:
            n = min(args.chunk, ticks - done)
            state = advance(state, n)
            done += n
            rep = summarize(state, log_total=cfg.fault.log_total)
            cov = rep["coverage"]
            registry.ingest_coverage(cov)
            curve.append({
                "tick": done,
                "bits_set": cov["bits_set"],
                "new_bits": cov["bits_set"] - prev_bits,
                "est_states": cov["est_states"],
            })
            prev_bits = cov["bits_set"]
            log.emit("chunk", ticks=done, coverage=cov)
        final = summarize(state, log_total=cfg.fault.log_total)
        out = {
            "metric": "coverage",
            "config": args.config,
            "engine": args.engine,
            "n_inst": cfg.n_inst,
            "ticks": ticks,
            "chunk": args.chunk,
            "violations": final["violations"],
            "coverage": final["coverage"],
            "curve": curve,
            "config_fingerprint": cfg.fingerprint(),
        }
        log.emit("metrics", **registry.snapshot())
        log.emit("final", **out)
    print(json.dumps(out))
    return 0 if final["violations"] == 0 else 2


def _cmd_coverage_exact(args: argparse.Namespace) -> int:
    """Exact probe + sketch calibration (scripts/coverage_probe.py, folded
    into the CLI; the script remains as a thin wrapper)."""
    import jax

    # The probe is a CPU tool regardless of --platform.
    jax.config.update("jax_platforms", "cpu")

    from paxos_tpu.check.coverage import (
        PORTFOLIO, coverage_probe, sketch_crosscheck,
    )

    if args.profile is not None and not 0 <= args.profile < len(PORTFOLIO):
        print(f"error: --profile must be in [0, {len(PORTFOLIO) - 1}]",
              file=sys.stderr)
        return 1
    say = lambda s: print(f"# {s}", file=sys.stderr)
    mr = args.max_round[0] if len(args.max_round) == 1 else tuple(args.max_round)
    n_inst = args.n_inst or 4096
    ticks = 48 if args.ticks is None else args.ticks
    probe_cfg_kw = None if args.profile is None else PORTFOLIO[args.profile]
    out = coverage_probe(
        n_prop=args.n_prop,
        n_acc=args.n_acc,
        max_round=mr,
        n_inst=n_inst,
        ticks=ticks,
        seeds=args.seeds,
        seed0=args.seed0,
        max_states=args.max_states,
        log=say,
        probe_cfg_kw=probe_cfg_kw,
        analyze_residue=args.analyze_residue,
    )
    if not args.no_crosscheck:
        # Calibrate the on-device sketch at the same bounds/adversaries
        # (smaller campaigns: the crosscheck re-reads every tick's digests
        # host-side, so probe-scale lanes would dominate the runtime).
        out["sketch_crosscheck"] = sketch_crosscheck(
            n_inst=min(n_inst, 512),
            ticks=min(ticks, 32),
            seeds=min(args.seeds, 2),
            seed0=args.seed0,
            probe_cfg_kw=probe_cfg_kw,
            log=say,
        )
    sample = out.pop("out_of_space_sample")
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=1)
    if out["out_of_space"]:
        print(f"# SOUNDNESS FAILURE — sample state: {sample[0]}",
              file=sys.stderr)
        return 2
    cross = out.get("sketch_crosscheck")
    if cross is not None and not (
        cross["union_matches_host_mirror"] and cross["estimate_within_bound"]
    ):
        print("# SKETCH CALIBRATION FAILURE — see sketch_crosscheck",
              file=sys.stderr)
        return 2
    return 0


def cmd_exposure(args: argparse.Namespace) -> int:
    """Fault-exposure plane: run a campaign with the injected-vs-effective
    counters on; print the per-class exposure matrix and the chunk-granular
    attribution table (obs.exposure)."""
    import dataclasses

    import jax

    from paxos_tpu.faults.injector import exposure_lit
    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry
    from paxos_tpu.harness.run import (
        init_plan, init_state, make_advance, make_longlog, summarize,
    )
    from paxos_tpu.obs.exposure import (
        CLASSES, ExposureConfig, annotate_lit, attribution, effective_delta,
    )

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "use --engine xla", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    cfg = dataclasses.replace(cfg, exposure=ExposureConfig(counters=True))
    cov_cfg = _coverage_from_args(args)
    if cov_cfg is not None:
        cfg = dataclasses.replace(cfg, coverage=cov_cfg)

    registry = MetricsRegistry()
    with MetricsLog(args.log) as log:
        log.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
                 n_inst=cfg.n_inst, protocol=cfg.protocol, engine=args.engine)
        state, plan = init_state(cfg), init_plan(cfg)
        advance = make_advance(
            cfg, plan, args.engine, compact=bool(make_longlog(cfg))
        )
        # Serial per-chunk loop: each chunk's summarize yields the exposure
        # deltas (and coverage new-bits / violation deltas) the attribution
        # table joins on — the counters themselves only grow on-device.
        chunks: list = []
        prev_exp = None
        prev_bits = 0
        prev_viol = 0
        done = 0
        while done < args.ticks:
            n = min(args.chunk, args.ticks - done)
            state = advance(state, n)
            done += n
            rep = summarize(state, log_total=cfg.fault.log_total)
            exp = rep["exposure"]
            ch = {
                "tick": done,
                "effective_delta": effective_delta(prev_exp, exp),
                "violations_delta": rep["violations"] - prev_viol,
            }
            if "coverage" in rep:
                ch["new_bits"] = rep["coverage"]["bits_set"] - prev_bits
                prev_bits = rep["coverage"]["bits_set"]
            prev_exp, prev_viol = exp, rep["violations"]
            chunks.append(ch)
            registry.ingest_exposure(exp)
            log.emit("chunk", ticks=done, exposure=exp)
        final = summarize(state, log_total=cfg.fault.log_total)
        matrix = annotate_lit(final["exposure"], cfg.fault)
        registry.ingest_exposure(matrix, lit=exposure_lit(cfg.fault))
        table = attribution(chunks)
        out = {
            "metric": "exposure",
            "config": args.config,
            "engine": args.engine,
            "n_inst": cfg.n_inst,
            "ticks": args.ticks,
            "chunk": args.chunk,
            "violations": final["violations"],
            "exposure": matrix,
            "attribution": table,
            "config_fingerprint": cfg.fingerprint(),
        }
        if "coverage" in final:
            out["coverage"] = final["coverage"]
        log.emit("metrics", **registry.snapshot())
        log.emit("final", **out)
    if args.as_json:
        print(json.dumps(out))
    else:
        lit = set(matrix["lit"])
        print(f"# exposure matrix  config={args.config} "
              f"n_inst={cfg.n_inst} ticks={args.ticks} engine={args.engine}")
        print(f"{'class':<12}{'lit':>4}{'injected':>12}{'effective':>12}"
              f"{'lanes_exposed':>15}")
        for name in CLASSES:
            row = matrix["classes"][name]
            print(f"{name:<12}{'yes' if name in lit else 'no':>4}"
                  f"{row['injected']:>12}{row['effective']:>12}"
                  f"{row['lanes_exposed']:>15}")
        print(f"# vacuous: {', '.join(matrix['vacuous']) or 'none'}")
        print("# attribution (chunk-granular co-occurrence, not causality)")
        print(f"{'class':<12}{'chunks_active':>14}{'effective':>12}"
              f"{'new_bits':>10}{'violations':>12}")
        for name in CLASSES:
            row = table[name]
            print(f"{name:<12}{row['chunks_active']:>14}"
                  f"{row['effective']:>12}{row['new_bits']:>10}"
                  f"{row['violations']:>12}")
    return 0 if final["violations"] == 0 else 2


def cmd_margin(args: argparse.Namespace) -> int:
    """Near-miss margin plane: run a campaign with the distance-to-violation
    counters on; print the per-chunk min-slack curve, the tightest-lane
    ranking, and the margin-vs-progress correlation table (obs.margin)."""
    import dataclasses

    import jax

    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry
    from paxos_tpu.harness.run import (
        init_plan, init_state, make_advance, make_longlog, summarize,
    )
    from paxos_tpu.obs.margin import MarginConfig, correlation, lane_ranking

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "use --engine xla", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    cfg = CONFIGS[args.config](**kw)
    try:
        cfg = config_mod.apply_fault_overrides(cfg, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    cfg = dataclasses.replace(cfg, margin=MarginConfig(counters=True))
    cov_cfg = _coverage_from_args(args)
    if cov_cfg is not None:
        cfg = dataclasses.replace(cfg, coverage=cov_cfg)
    expo_cfg = _exposure_from_args(args)
    if expo_cfg is not None:
        cfg = dataclasses.replace(cfg, exposure=expo_cfg)

    registry = MetricsRegistry()
    with MetricsLog(args.log) as log:
        log.emit("start", config=args.config, fingerprint=cfg.fingerprint(),
                 n_inst=cfg.n_inst, protocol=cfg.protocol, engine=args.engine)
        state, plan = init_state(cfg), init_plan(cfg)
        advance = make_advance(
            cfg, plan, args.engine, compact=bool(make_longlog(cfg))
        )
        # Serial per-chunk loop: each chunk's summarize samples the running
        # minima, so the curve shows WHEN the campaign got close — the
        # counters themselves only tighten on-device.
        chunks: list = []
        prev_min = None  # None = uncontested so far
        prev_near = 0
        prev_bits = 0
        prev_viol = 0
        prev_exp = None
        done = 0
        while done < args.ticks:
            n = min(args.chunk, args.ticks - done)
            state = advance(state, n)
            done += n
            rep = summarize(state, log_total=cfg.fault.log_total)
            mar = rep["margin"]
            cur_min = mar["min_quorum_slack"]
            tightened = (
                (cur_min is not None and (prev_min is None or cur_min < prev_min))
                or mar["near_miss_lanes"] > prev_near
            )
            ch = {
                "tick": done,
                "min_quorum_slack": cur_min,
                "near_miss_lanes": mar["near_miss_lanes"],
                "zero_slack_lanes": mar["zero_slack_lanes"],
                "near_split_ticks": mar["near_split_ticks"],
                "violations_delta": rep["violations"] - prev_viol,
                "tightened": tightened,
            }
            if "coverage" in rep:
                ch["new_bits"] = rep["coverage"]["bits_set"] - prev_bits
                prev_bits = rep["coverage"]["bits_set"]
            if "exposure" in rep:
                from paxos_tpu.obs.exposure import effective_delta

                ch["effective_total"] = sum(
                    effective_delta(prev_exp, rep["exposure"]).values()
                )
                prev_exp = rep["exposure"]
            prev_min, prev_near = cur_min, mar["near_miss_lanes"]
            prev_viol = rep["violations"]
            chunks.append(ch)
            registry.ingest_margin(mar, rep.get("checker_complete"))
            log.emit("chunk", ticks=done, margin=mar)
        final_rep = summarize(state, log_total=cfg.fault.log_total)
        table = correlation(chunks)
        ranking = lane_ranking(state.margin, top=args.lanes)
        out = {
            "metric": "margin",
            "config": args.config,
            "engine": args.engine,
            "n_inst": cfg.n_inst,
            "ticks": args.ticks,
            "chunk": args.chunk,
            "violations": final_rep["violations"],
            "checker_complete": final_rep["checker_complete"],
            "margin": final_rep["margin"],
            "curve": chunks,
            "lane_ranking": ranking,
            "correlation": table,
            "config_fingerprint": cfg.fingerprint(),
        }
        if "coverage" in final_rep:
            out["coverage"] = final_rep["coverage"]
        if "exposure" in final_rep:
            out["exposure"] = final_rep["exposure"]
        registry.ingest_margin(
            final_rep["margin"], final_rep["checker_complete"]
        )
        log.emit("metrics", **registry.snapshot())
        log.emit("final", **out)
        _warn_checker_incomplete(final_rep)
    if args.as_json:
        print(json.dumps(out))
    else:
        m = final_rep["margin"]
        fmt = lambda v: "-" if v is None else v
        print(f"# margin plane  config={args.config} n_inst={cfg.n_inst} "
              f"ticks={args.ticks} engine={args.engine}")
        print(f"# min_quorum_slack={fmt(m['min_quorum_slack'])} "
              f"(0 = a violation fired, 1 = one accept short)  "
              f"min_ballot_gap={fmt(m['min_ballot_gap'])}  "
              f"min_promise_slack={fmt(m['min_promise_slack'])}")
        print(f"# near_miss_lanes={m['near_miss_lanes']}  "
              f"zero_slack_lanes={m['zero_slack_lanes']}  "
              f"contested_lanes={m['contested_lanes']}  "
              f"near_split_ticks={m['near_split_ticks']}  "
              f"checker_complete={out['checker_complete']}")
        print("# min-slack curve (per chunk)")
        print(f"{'tick':>6}{'min_slack':>11}{'near_miss':>11}"
              f"{'zero_slack':>12}{'viol_delta':>12}{'tightened':>11}")
        for ch in chunks:
            print(f"{ch['tick']:>6}{fmt(ch['min_quorum_slack']):>11}"
                  f"{ch['near_miss_lanes']:>11}{ch['zero_slack_lanes']:>12}"
                  f"{ch['violations_delta']:>12}"
                  f"{'yes' if ch['tightened'] else 'no':>11}")
        print("# tightest lanes")
        for row in ranking:
            print(f"#   lane {row['lane']:>6}  "
                  f"min_quorum_slack={fmt(row['min_quorum_slack'])}  "
                  f"near_split_ticks={row['near_split_ticks']}")
        print("# correlation (chunk-granular co-occurrence, not causality)")
        print(f"{'margin':<12}{'chunks':>8}{'new_bits':>10}"
              f"{'effective':>11}{'violations':>12}")
        for key in ("tightened", "flat"):
            row = table[key]
            print(f"{key:<12}{row['chunks']:>8}{row['new_bits']:>10}"
                  f"{row['effective']:>11}{row['violations']:>12}")
    return 0 if final_rep["violations"] == 0 else 2


def cmd_slo(args: argparse.Namespace) -> int:
    """Client-workload SLO plane: one campaign per offered-load scale,
    per-class latency table, goodput curve, overload knee, p99 gate."""
    import dataclasses

    import jax

    from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry
    from paxos_tpu.harness.run import run
    from paxos_tpu.obs.slo import overload_knee, slo_breach
    from paxos_tpu.workload.generator import WorkloadConfig

    if args.engine == "fused" and jax.devices()[0].platform != "tpu":
        print("error: --engine fused compiles Mosaic kernels (TPU only); "
              "use --engine xla", file=sys.stderr)
        return 1
    kw = {"seed": args.seed}
    if args.n_inst:
        kw["n_inst"] = args.n_inst
    base = CONFIGS[args.config](**kw)
    try:
        base = config_mod.apply_fault_overrides(base, args.fault)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    registry = MetricsRegistry()
    points: list = []
    at_one: Optional[dict] = None
    with MetricsLog(args.log) as log:
        log.emit("start", config=args.config, n_inst=base.n_inst,
                 protocol=base.protocol, engine=args.engine)
        for scale in args.sweep:
            wl = WorkloadConfig(
                mix=args.mix,
                rate=min(1.0, args.rate * scale),
                burst_rate=min(1.0, WorkloadConfig().burst_rate * scale),
                slo_p99_ticks=args.slo_p99,
            )
            try:
                wl.validate()
            except ValueError as e:
                print(f"error: sweep scale {scale}: {e}", file=sys.stderr)
                return 1
            cfg = dataclasses.replace(base, workload=wl)
            rep = run(cfg, total_ticks=args.ticks, chunk=args.chunk,
                      engine=args.engine)
            slo = rep["slo"]
            pt = {
                "rate_scale": scale,
                "rate": wl.rate,
                "offered": slo["offered"],
                "done": slo["done"],
                "shed": slo["shed"],
                "goodput": slo["goodput"],
                "queue_depth": slo["queue_depth"],
                "depth_peak": slo["depth_peak"],
                "p99_ticks": slo["p99_ticks"],
                "violations": rep["violations"],
                "classes": slo["classes"],
            }
            points.append(pt)
            log.emit("sweep_point", **{
                k: v for k, v in pt.items() if k != "classes"
            })
            if scale == 1.0:
                at_one = slo
                registry.ingest_slo(slo, args.slo_p99)
        # Gate at scale 1.0 (the configured operating point); a sweep
        # without it gates on the first swept point instead.
        gate = at_one or {"classes": points[0]["classes"]}
        breaches = slo_breach(gate, args.slo_p99)
        knee = overload_knee(points, floor=args.knee_floor)
        out = {
            "metric": "slo",
            "config": args.config,
            "engine": args.engine,
            "n_inst": base.n_inst,
            "ticks": args.ticks,
            "mix": args.mix,
            "slo_p99_ticks": args.slo_p99,
            "sweep": points,
            "overload_knee": knee,
            "breaches": breaches,
        }
        snap = registry.snapshot()
        if snap.get("gauges"):
            log.emit("metrics", **snap)
        log.emit("final", **{k: v for k, v in out.items() if k != "sweep"})
    if args.as_json:
        print(json.dumps(out))
    else:
        print(f"# slo plane  config={args.config} n_inst={base.n_inst} "
              f"ticks={args.ticks} mix={args.mix} engine={args.engine}")
        print(f"{'scale':>7}{'rate':>9}{'offered':>10}{'done':>10}"
              f"{'shed':>8}{'goodput':>9}{'p99':>6}{'depth_pk':>10}")
        for pt in points:
            print(f"{pt['rate_scale']:>7}{pt['rate']:>9.4f}"
                  f"{pt['offered']:>10}{pt['done']:>10}{pt['shed']:>8}"
                  f"{pt['goodput']:>9.3f}{pt['p99_ticks']:>6}"
                  f"{pt['depth_peak']:>10}")
        if knee is not None:
            print(f"# overload knee: scale {knee['rate_scale']} "
                  f"(goodput {knee['goodput']:.3f} < {args.knee_floor})")
        else:
            print(f"# no overload knee inside the swept range "
                  f"(goodput >= {args.knee_floor} everywhere)")
        if at_one is not None:
            print("# per-class latency at scale 1.0 (ticks, queue-delay "
                  "inclusive)")
            print(f"{'class':<10}{'lanes':>7}{'offered':>9}{'done':>8}"
                  f"{'goodput':>9}{'p50':>6}{'p95':>6}{'p99':>6}")
            fmt = lambda v: "-" if v < 0 else v
            for name, row in at_one["classes"].items():
                print(f"{name:<10}{row['lanes']:>7}{row['offered']:>9}"
                      f"{row['done']:>8}{row['goodput']:>9.3f}"
                      f"{fmt(row['p50_ticks']):>6}{fmt(row['p95_ticks']):>6}"
                      f"{fmt(row['p99_ticks']):>6}")
        if breaches:
            print(f"# SLO BREACH: p99 > {args.slo_p99} ticks for "
                  f"{', '.join(breaches)}")
    return 2 if breaches else 0


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform == "cpu":
        # Must happen before any backend use; an env var alone does not stick
        # because the image's sitecustomize pins the platform list.
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.cmd == "run":
        return cmd_run(args)
    if args.cmd == "sweep":
        return cmd_sweep(args)
    if args.cmd == "soak":
        return cmd_soak(args)
    if args.cmd == "fuzz":
        return cmd_fuzz(args)
    if args.cmd == "fleet":
        return cmd_fleet(args)
    if args.cmd == "fleet-worker":
        return cmd_fleet_worker(args)
    if args.cmd == "lineage":
        return cmd_lineage(args)
    if args.cmd == "shrink":
        return cmd_shrink(args)
    if args.cmd == "check":
        return cmd_check(args)
    if args.cmd == "stats":
        return cmd_stats(args)
    if args.cmd == "bench-compare":
        return cmd_bench_compare(args)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "audit":
        return cmd_audit(args)
    if args.cmd == "coverage":
        return cmd_coverage(args)
    if args.cmd == "exposure":
        return cmd_exposure(args)
    if args.cmd == "slo":
        return cmd_slo(args)
    if args.cmd == "margin":
        return cmd_margin(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
