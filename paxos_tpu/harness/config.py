"""Typed run configs — the deployment/discovery layer's TPU twin.

Reference parity (SURVEY.md §6.6): the reference configures runs with
SimpleLocalnet positional CLI args (``master|slave host port``) [CH].  Here a
run is a frozen, hashable dataclass (so it can ride into ``jax.jit`` as a
static argument) and each BASELINE.json evaluation config has a named
constructor.  ``fingerprint()`` lands in benchmark reports so numbers are
attributable to exact configurations.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from paxos_tpu.core.telemetry import TelemetryConfig
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.obs.coverage import CoverageConfig
from paxos_tpu.obs.exposure import ExposureConfig
from paxos_tpu.obs.margin import MarginConfig
from paxos_tpu.workload.generator import WorkloadConfig


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One fuzzing run: protocol, topology, scale, faults, timing."""

    n_inst: int = 1024
    n_prop: int = 1
    n_acc: int = 3
    k_slots: int = 8  # learner-table capacity
    log_len: int = 8  # Multi-Paxos replicated-log length
    seed: int = 0
    protocol: str = "paxos"
    fault: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    # Flight recorder / telemetry (core.telemetry) — default OFF, and off
    # is free: the state's telemetry leaf prunes to None and schedules are
    # bit-identical (tests/test_telemetry.py).
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    # On-device coverage sketch (obs.coverage) — same default-off contract:
    # the state's coverage leaf prunes to None and digest hashing draws no
    # PRNG, so schedules are bit-identical (tests/test_coverage.py).
    coverage: CoverageConfig = dataclasses.field(
        default_factory=CoverageConfig
    )
    # Fault-exposure accounting (obs.exposure) — same default-off contract:
    # the state's exposure leaf prunes to None and the counters draw no
    # PRNG, so schedules are bit-identical (tests/test_exposure.py).
    exposure: ExposureConfig = dataclasses.field(
        default_factory=ExposureConfig
    )
    # Near-miss safety-margin sketch (obs.margin) — same default-off
    # contract: the state's margin leaf prunes to None and the fold draws
    # no PRNG, so schedules are bit-identical (tests/test_margin.py).
    margin: MarginConfig = dataclasses.field(default_factory=MarginConfig)
    # Open-loop client workload (workload.generator) — same default-off
    # contract: the state's wload leaf prunes to None and no arrival PRNG
    # is drawn, so schedules are bit-identical (tests/test_workload.py).
    workload: WorkloadConfig = dataclasses.field(
        default_factory=WorkloadConfig
    )

    def fingerprint(self) -> str:
        d = dataclasses.asdict(self)
        # Telemetry never changes a schedule; with it disabled (the default)
        # drop it from the fingerprint so recorded artifacts (BENCH_SWEEP,
        # checkpoints) from pre-telemetry builds keep matching.
        if d["telemetry"] == dataclasses.asdict(TelemetryConfig()):
            del d["telemetry"]
        # Coverage is an observer under the same contract: disabled (the
        # default) drops out so pre-coverage fingerprints keep matching.
        if d["coverage"] == dataclasses.asdict(CoverageConfig()):
            del d["coverage"]
        # Exposure too: disabled (the default) drops out so pre-exposure
        # fingerprints keep matching.
        if d["exposure"] == dataclasses.asdict(ExposureConfig()):
            del d["exposure"]
        # Margin too: disabled (the default) drops out so pre-margin
        # fingerprints keep matching.
        if d["margin"] == dataclasses.asdict(MarginConfig()):
            del d["margin"]
        # Workload too: disabled (the default) drops out so pre-workload
        # fingerprints keep matching.
        if d["workload"] == dataclasses.asdict(WorkloadConfig()):
            del d["workload"]
        # The packed lane-state layout version (core/*_state.py) is part of
        # the on-device representation: a layout change invalidates every
        # checkpoint recorded under the old bit positions, so it must
        # re-key fingerprint-addressed artifacts.  The audit's
        # layout-version guard ensures the version actually moves when the
        # table does.  Lazy import: bitops pulls in jax.numpy.
        from paxos_tpu.utils.bitops import layout_version

        d["layout_version"] = layout_version(self.protocol)
        blob = json.dumps(d, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def validate_pipeline_depth(depth) -> int:
    """Validate a dispatch-pipeline depth (``harness.pipeline``) up front.

    Depth is a HOST-LOOP knob, deliberately not a ``SimConfig`` field: it
    regroups the same chunk sequence into fewer device dispatches without
    changing a single tick, so it must never enter fingerprints, stream
    ids, or checkpoints.  Validated here (the config layer) so every
    entry point — ``run()``, ``soak()``, the CLI, bench — rejects a bad
    depth before any device work.
    """
    if isinstance(depth, bool) or not isinstance(depth, int):
        raise ValueError(
            f"pipeline depth must be an integer >= 1, got {depth!r}"
        )
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1, got {depth}")
    return depth


# --- BASELINE.json evaluation configs (BASELINE.md "Evaluation configs") ---


def config1_no_faults(n_inst: int = 1024, seed: int = 0) -> SimConfig:
    """Config 1: single-decree, 3 acceptors, 1 proposer, no faults."""
    return SimConfig(n_inst=n_inst, n_prop=1, n_acc=3, seed=seed)


def config2_dueling_drop(n_inst: int = 131_072, seed: int = 0) -> SimConfig:
    """Config 2: 5 acceptors, 2 dueling proposers, 10% message drop.

    Default batch is the power-of-two at the spec's "100k" scale (2^17):
    TPU lane tiling needs 128-divisible blocks, and the literal 100,000
    (2^5 x 5^5) admits none — the fused engine would reject it.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(p_drop=0.1, p_idle=0.2, p_hold=0.2),
    )


def config3_multipaxos(n_inst: int = 1_048_576, seed: int = 0) -> SimConfig:
    """Config 3: Multi-Paxos log replication, leader lease + leader crash.

    Default batch is the power-of-two at the spec's "1M" scale (2^20):
    the literal 1,000,000 (2^6 x 5^6) admits no 128-divisible block, so
    the fused engine would reject it (see ``fused_tick.fit_block``).
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        log_len=8,
        k_slots=4,  # per-slot table rows; plenty with re-confirmation suppression
        seed=seed,
        protocol="multipaxos",
        fault=FaultConfig(
            p_drop=0.05,
            p_idle=0.1,
            p_hold=0.1,
            p_crash=0.1,
            p_crash_prop=0.4,  # leader crash is the config's point
            crash_max_start=150,
            crash_max_len=40,
            lease_len=24,
        ),
    )


def config3_long(
    n_inst: int = 262_144,
    seed: int = 0,
    log_total: int = 256,
    window: int = 16,
) -> SimConfig:
    """Config 3-long: Multi-Paxos over a LONG log through a sliding window.

    SURVEY.md §6.7's claim made concrete: ``log_total`` slots are replicated
    per instance while HBM holds only the ``window``-slot working set —
    decided prefixes compact out at chunk boundaries
    (``protocols.multipaxos.compact_mp``).  Same fault family as config 3;
    crash windows spread over the (much longer) expected run.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        log_len=window,
        k_slots=4,
        seed=seed,
        protocol="multipaxos",
        fault=FaultConfig(
            p_drop=0.05,
            p_idle=0.1,
            p_hold=0.1,
            p_crash=0.1,
            p_crash_prop=0.4,
            crash_max_start=2000,
            crash_max_len=60,
            lease_len=24,
            log_total=log_total,
        ),
    )


# Recorded long-log replication rate (slots replicated per lane-tick) at the
# soak operating point (ticks_per_seed=512, chunk=64, fused engine, 1M
# instances): BASELINE.md's config3long soak replicates decided_frac 0.498
# of a 256-slot log in a 512-tick budget -> 0.249 slots/lane-tick.  The soak
# CLI gates long-log campaigns at 0.7x this (VERDICT r3 #8) — the same band
# discipline as the perf-regression gate — so a replication slowdown fails
# the soak loudly instead of drifting a statistic.  The rate is per-lane, so
# it holds across instance counts; re-record if the config's fault mix or
# the soak cadence changes.
REPLICATION_RATES = {"config3long": 0.249}


def config4_byzantine(n_inst: int = 4096, seed: int = 0) -> SimConfig:
    """Config 4: acceptor equivocation (double-promise) to validate the checker."""
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, p_equiv=0.25),
    )


def config_partition(n_inst: int = 65_536, seed: int = 0) -> SimConfig:
    """Network partitions: per-instance bipartition windows + drop + duels.

    Messages crossing the cut stall until the partition heals
    (``FaultPlan.link_ok``); safety must hold throughout and liveness must
    resume after healing.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(
            p_drop=0.05,
            p_idle=0.1,
            p_hold=0.1,
            p_part=0.8,
            part_max_start=40,
            part_max_len=30,
        ),
    )


def config_gray_chaos(n_inst: int = 65_536, seed: int = 0) -> SimConfig:
    """Gray-failure chaos: asymmetric cuts, flaky links, skewed timers.

    Every gray knob that is CHAOS (schedule-space enrichment, not a bug)
    at once: one-way partitions (``p_asym``), per-link Bernoulli loss and
    duplication rate matrices (``p_flaky``/``flaky_drop``/``flaky_dup``),
    and per-proposer timeout/backoff skew.  Safety must hold at any soak
    length; liveness must survive the heal.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(
            p_idle=0.1,
            p_hold=0.1,
            p_dup=0.05,
            p_part=0.5,
            part_max_start=40,
            part_max_len=30,
            p_asym=0.7,
            p_flaky=0.4,
            flaky_drop=0.4,
            flaky_dup=0.2,
            timeout_skew=6,
            backoff_skew=3,
        ),
    )


def config_delay_chaos(
    n_inst: int = 4096, seed: int = 0, violate_delta: bool = False
) -> SimConfig:
    """Bounded-delay chaos: per-link latency queues under loss (chaos, not
    a bug — delay alone can neither lose nor duplicate a message).

    Most sends take an extra 1..``delay_max`` ticks, capped per link by the
    plan's sampled ``link_delay`` matrix.  The default cell keeps latencies
    inside the synchrony window ``delta`` often enough that SynchPaxos'
    fast path still lands (nonzero fast-path decide rate); the
    ``violate_delta`` cell caps the window BELOW the sampled latencies —
    the synchrony bet loses, the honest protocol must fall back with zero
    safety violations (and the ``sp_unsafe_fast`` planted bug becomes
    catchable).
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        protocol="synchpaxos",
        fault=FaultConfig(
            p_drop=0.1,
            p_idle=0.1,
            p_delay=0.8 if violate_delta else 0.4,
            delay_max=8 if violate_delta else 2,
            delta=4 if violate_delta else 6,
            timeout=8,
        ),
    )


def config_corrupt(n_inst: int = 4096, seed: int = 0) -> SimConfig:
    """Message corruption bug injection: in-flight payload bit flips.

    ACCEPT values flip bits and PREPARE ballots bump between send and
    process (``p_corrupt``) — acceptors vote for values nobody proposed,
    which the agreement checker MUST flag (within a 256-tick campaign at
    this rate/scale; tests/test_gray.py pins it).
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(
            p_drop=0.1, p_idle=0.2, p_hold=0.2, p_corrupt=0.2, timeout=6
        ),
    )


def config_stale(n_inst: int = 4096, seed: int = 0) -> SimConfig:
    """Stale-snapshot recovery bug injection (amnesia generalized).

    Crashed acceptors recover to their durable image as of the last
    multiple of ``stale_k`` ticks — up to ``stale_k`` ticks of promises
    and accepts silently lost; the checker must flag the consequences.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(
            p_drop=0.1,
            p_idle=0.1,
            p_hold=0.1,
            timeout=6,
            stale_k=8,
            p_crash=0.4,
            crash_max_start=60,
            crash_max_len=20,
        ),
    )


def apply_fault_overrides(cfg: SimConfig, overrides) -> SimConfig:
    """Apply generic ``key=value`` fault-knob overrides to a config.

    The CLI's ``--fault`` escape hatch: any :class:`FaultConfig` field by
    name, value coerced to the field's current type (bool fields accept
    true/false/1/0).  Unknown keys raise ``ValueError`` listing the valid
    knobs, so a typo'd knob fails loudly instead of silently fuzzing the
    wrong space.
    """
    if not overrides:
        return cfg
    valid = {f.name for f in dataclasses.fields(FaultConfig)}
    patch = {}
    for item in overrides:
        key, sep, raw = item.partition("=")
        if not sep:
            raise ValueError(f"fault override must be key=value, got {item!r}")
        if key not in valid:
            raise ValueError(
                f"unknown fault knob {key!r}; valid: {', '.join(sorted(valid))}"
            )
        cur = getattr(cfg.fault, key)
        if isinstance(cur, bool):
            if raw.lower() not in {"true", "false", "1", "0"}:
                raise ValueError(f"{key} is a flag; use {key}=true/false")
            val: object = raw.lower() in {"true", "1"}
        elif isinstance(cur, int):
            val = int(raw)
        elif isinstance(cur, float):
            val = float(raw)
        else:
            val = raw
        patch[key] = val
    return dataclasses.replace(
        cfg, fault=dataclasses.replace(cfg.fault, **patch)
    )


def config_flex(
    q1: int, q2: int, n_inst: int = 16_384, seed: int = 0
) -> SimConfig:
    """Flexible Paxos: explicit phase-1/phase-2 quorums over 5 acceptors.

    Safe iff ``q1 + q2 > 5``; an unsafe pair is a supported bug-injection
    mode that must light up the safety checker (grid quorums, FPaxos).
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, q1=q1, q2=q2),
    )


def config_ffp(
    q1: int, q2: int, q_fast: int, n_inst: int = 16_384, seed: int = 0
) -> SimConfig:
    """Fast Flexible Paxos: explicit classic + fast quorums over 5 acceptors.

    Safe iff ``q1 + q2 > 5`` and ``q1 + 2*q_fast > 10`` (arXiv:2008.02671's
    relaxed intersection conditions); an unsafe triple is a supported
    bug-injection mode that must light up the safety checker.
    """
    return SimConfig(
        n_inst=n_inst,
        n_prop=2,
        n_acc=5,
        seed=seed,
        protocol="fastpaxos",
        fault=FaultConfig(
            p_idle=0.2, p_hold=0.2, p_drop=0.1, q1=q1, q2=q2, q_fast=q_fast
        ),
    )


def config5_sweep(n_inst: int = 65_536, seed: int = 0) -> tuple[SimConfig, ...]:
    """Config 5: Paxos vs Fast-Paxos vs Raft-core under identical fault masks."""
    fault = FaultConfig(p_drop=0.1, p_idle=0.2, p_hold=0.2)
    return tuple(
        SimConfig(n_inst=n_inst, n_prop=2, n_acc=5, seed=seed, protocol=p, fault=fault)
        for p in ("paxos", "fastpaxos", "raftcore")
    )
