"""Observability: structured JSONL metrics and profiler scopes.

Reference parity (SURVEY.md §6.1, §6.5): the reference's observability is
stdout printing plus the distributed-process Mx tracing bus (per-event hooks
on send/receive/spawn) [CH].  The TPU twin keeps all counters on-device
(they live inside `LearnerState` and are reduced in `summarize`) and, on the
host side, appends one JSON object per chunk to a JSONL stream — the
structured twin of the Mx trace log.  `trace_scope` wraps phases in
`jax.profiler.TraceAnnotation` so device profiles show deliver/vote/emit
sections by name.
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import time
from typing import Any, Iterator, Optional, TextIO

import jax


class MetricsLog:
    """Append-only JSONL metrics stream with a wall-clock and tick context."""

    def __init__(self, path: "str | pathlib.Path | None" = None) -> None:
        self._fh: Optional[TextIO] = None
        if path is not None:
            p = pathlib.Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._fh = p.open("a")
        self._t0 = time.monotonic()

    def emit(self, event: str, **fields: Any) -> dict[str, Any]:
        rec = {"event": event, "t_wall": round(time.monotonic() - self._t0, 4)}
        rec.update(fields)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@contextlib.contextmanager
def trace_scope(name: str) -> Iterator[None]:
    """Named region in device profiles (no-op overhead when not profiling)."""
    with jax.profiler.TraceAnnotation(name):
        yield
