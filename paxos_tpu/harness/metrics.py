"""Observability: structured JSONL metrics and profiler scopes.

Reference parity (SURVEY.md §6.1, §6.5): the reference's observability is
stdout printing plus the distributed-process Mx tracing bus (per-event hooks
on send/receive/spawn) [CH].  The TPU twin keeps all counters on-device
(they live inside `LearnerState` and are reduced in `summarize`) and, on the
host side, appends one JSON object per chunk to a JSONL stream — the
structured twin of the Mx trace log.  `trace_scope` wraps phases in
`jax.profiler.TraceAnnotation` so device profiles show deliver/vote/emit
sections by name.
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import time
from typing import Any, Iterator, Optional, TextIO

import jax


class MetricsLog:
    """Append-only JSONL metrics stream with a wall-clock and tick context.

    Usable as a context manager; the CLI paths enter it with ``with`` so the
    stream is closed on EVERY exit path (early-return errors included) —
    before, violation runs could leave the file handle dangling.
    """

    def __init__(self, path: "str | pathlib.Path | None" = None) -> None:
        self._fh: Optional[TextIO] = None
        self._closed = False
        if path is not None:
            p = pathlib.Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._fh = p.open("a")
        self._t0 = time.monotonic()

    def __enter__(self) -> "MetricsLog":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def emit(self, event: str, **fields: Any) -> dict[str, Any]:
        if self._closed:
            raise ValueError("emit() on a closed MetricsLog")
        rec = {"event": event, "t_wall": round(time.monotonic() - self._t0, 4)}
        rec.update(fields)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        return rec

    def close(self) -> None:
        self._closed = True
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _escape_label_value(v: Any) -> str:
    """Escape a label value per the Prometheus text exposition format.

    Backslash first (so the other escapes' own backslashes survive), then
    double-quote and newline — an unescaped value containing any of these
    silently truncates or splits the sample line at scrape time.
    """
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class MetricsRegistry:
    """Host-side metrics registry: named counters + fixed-bin histograms.

    The host half of the flight-recorder pipeline (core.telemetry holds the
    device half): per-chunk telemetry reports fold in via :meth:`ingest`,
    ad-hoc host counters via :meth:`inc`, and the whole registry exports as
    a JSONL snapshot record (:meth:`emit`) or Prometheus text exposition
    (:meth:`to_prometheus`) for scrape-style consumers.  Counters carry
    optional labels (rendered Prometheus-style); histograms are fixed-width
    tick bins, matching the on-device layout, merged elementwise.
    """

    def __init__(self, namespace: str = "paxos_tpu") -> None:
        self.namespace = namespace
        # name -> {labels-tuple -> value}; labels-tuple is sorted (k, v) pairs.
        self._counters: dict[str, dict[tuple, float]] = {}
        # name -> {"counts": list[int], "bin_width": int}
        self._hists: dict[str, dict[str, Any]] = {}
        # name -> {labels-tuple -> value}; last-write-wins point-in-time values.
        self._gauges: dict[str, dict[tuple, float]] = {}
        # bits_set at the previous ingest_coverage (per-chunk delta base).
        self._cov_prev_bits: Optional[float] = None

    def inc(self, name: str, value: float = 1, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        series = self._counters.setdefault(name, {})
        series[key] = series.get(key, 0) + value

    def gauge(self, name: str, value: float, **labels: str) -> None:
        """Set a point-in-time value (overwrite, not accumulate)."""
        key = tuple(sorted(labels.items()))
        self._gauges.setdefault(name, {})[key] = value

    def observe_hist(
        self, name: str, counts: "list[int]", bin_width: int
    ) -> None:
        """Merge a fixed-bin histogram (elementwise add; widths must agree)."""
        hist = self._hists.get(name)
        if hist is None:
            self._hists[name] = {"counts": list(counts), "bin_width": bin_width}
            return
        if hist["bin_width"] != bin_width or len(hist["counts"]) != len(counts):
            raise ValueError(
                f"histogram {name!r} layout changed mid-stream: "
                f"{len(hist['counts'])}x{hist['bin_width']} vs "
                f"{len(counts)}x{bin_width}"
            )
        hist["counts"] = [a + b for a, b in zip(hist["counts"], counts)]

    def ingest(self, report: dict[str, Any]) -> None:
        """Fold one ``core.telemetry.telemetry_report`` dict into the registry.

        Telemetry counters are CUMULATIVE on-device, so ingest overwrites
        rather than adds (the last chunk's report is the campaign total);
        same for the latency histogram.
        """
        for event, total in report.get("counters", {}).items():
            series = self._counters.setdefault("events_total", {})
            series[(("event", event),)] = total
        hist = report.get("hist")
        if hist is not None:
            self._hists["ticks_to_decide"] = {
                "counts": list(hist),
                "bin_width": report.get("hist_ticks_per_bin", 1),
            }
        if "hist_overflow" in report:
            self.gauge("hist_overflow_decides", report["hist_overflow"])

    def ingest_coverage(self, cov: dict[str, Any]) -> None:
        """Fold one ``obs.coverage.coverage_host`` dict into the registry.

        Coverage counts are cumulative (the union sketch only grows), so
        they land as gauges; ``coverage_new_per_chunk`` is the delta of
        ``bits_set`` since the previous ingest — the live coverage-curve
        slope a scraper alerts on when exploration plateaus.
        """
        bits = cov["bits_set"]
        prev = self._cov_prev_bits
        self._cov_prev_bits = bits
        self.gauge("coverage_bits_set", bits)
        self.gauge("coverage_bits_total", cov["bits_total"])
        self.gauge("coverage_saturation", cov["saturation"])
        self.gauge(
            "coverage_new_per_chunk", bits - prev if prev is not None else bits
        )
        if cov.get("est_states") is not None:
            self.gauge("coverage_est_states", cov["est_states"])

    def ingest_exposure(
        self, exp: dict[str, Any], lit: "Optional[dict[str, bool]]" = None
    ) -> None:
        """Fold one ``obs.exposure.exposure_host`` dict into the registry.

        Exposure counters are cumulative on-device (the leaf only grows),
        so per-class injected/effective/lanes_exposed land as gauges keyed
        by a ``class`` label.  With ``lit`` (the ``faults.injector.
        exposure_lit`` map) given, every LIT class also gets a
        ``fault_vacuous{class=...}`` gauge — 1.0 when its effective count
        is still zero, the "vacuous chaos" alert a scraper pages on.
        """
        for name, row in exp["classes"].items():
            kw = {"class": name}
            self.gauge("exposure_injected", row["injected"], **kw)
            self.gauge("exposure_effective", row["effective"], **kw)
            self.gauge("exposure_lanes_exposed", row["lanes_exposed"], **kw)
        if lit:
            for name, on in lit.items():
                if on:
                    vacuous = exp["classes"][name]["effective"] == 0
                    self.gauge(
                        "fault_vacuous", float(vacuous), **{"class": name}
                    )

    def ingest_margin(
        self, margin: dict[str, Any], checker_complete: "Optional[bool]" = None
    ) -> None:
        """Fold one ``obs.margin.margin_host`` dict into the registry.

        Margin counters are running minima / cumulative tallies on-device,
        so they land as gauges (overwrite — the last chunk's report is the
        campaign-to-date value).  The ``min_*`` keys arrive as ``None``
        while uncontested (the sentinel never folded); an uncontested
        minimum is simply not exported rather than faked as a number, so a
        scraper alerting on ``margin_min_quorum_slack <= 1`` only fires on
        lanes that were actually contested.  ``checker_complete`` (the
        evictions-free bit from ``summarize``) rides along as a 0/1 gauge —
        0 means the safety oracle may have missed a violation.
        """
        for name, v in margin.items():
            # Numeric keys only: soak's cross-seed block carries list-valued
            # extras (the per-seed near-miss ranking) that are report-only.
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(f"margin_{name}", v)
        if checker_complete is not None:
            self.gauge("checker_complete", float(checker_complete))

    def ingest_slo(
        self, slo: dict[str, Any], slo_p99_ticks: "Optional[int]" = None
    ) -> None:
        """Fold one ``obs.slo.slo_host`` dict into the registry.

        Workload counters are cumulative on-device (the queue plane only
        accumulates), so everything lands as gauges under an ``slo_``
        prefix — the namespace stays disjoint from every other plane
        (tests/test_metrics.py pins the prefix partition).  Per-class
        offered/done/shed/goodput and latency quantiles become series
        labelled by ``class`` (quantiles additionally by ``quantile``,
        the summary idiom); unserved classes export no quantiles rather
        than a faked -1, so a scraper alerting on ``slo_latency_ticks``
        only sees real traffic.  ``slo_p99_ticks`` (the configured SLO)
        rides along so dashboards can draw the breach line.
        """
        for name, row in slo["classes"].items():
            kw = {"class": name}
            self.gauge("slo_offered", row["offered"], **kw)
            self.gauge("slo_done", row["done"], **kw)
            self.gauge("slo_shed", row["shed"], **kw)
            self.gauge("slo_goodput", row["goodput"], **kw)
            self.gauge("slo_lanes", row["lanes"], **kw)
            for q in ("p50", "p95", "p99"):
                v = row[f"{q}_ticks"]
                if v >= 0:
                    self.gauge(
                        "slo_latency_ticks", v, quantile=q, **kw
                    )
        for name in ("offered", "done", "shed", "goodput",
                     "queue_depth", "depth_peak"):
            self.gauge(f"slo_{name}", slo[name])
        if slo["p99_ticks"] >= 0:
            self.gauge("slo_p99_ticks", slo["p99_ticks"])
        if slo_p99_ticks is not None and slo_p99_ticks > 0:
            self.gauge("slo_target_p99_ticks", slo_p99_ticks)

    def ingest_span_aggregates(self, agg: dict[str, Any]) -> None:
        """Fold ``obs.spans.span_aggregates`` output into gauges.

        Span aggregates are whole-campaign summaries (not deltas), so they
        land as gauges; quantiles become one ``round_latency_ticks`` series
        labelled by quantile, matching Prometheus summary idiom.
        """
        for q in ("p50", "p95", "p99"):
            v = agg.get(f"round_latency_{q}")
            if v is not None and v >= 0:
                self.gauge("round_latency_ticks", v, quantile=q)
        for name in (
            "rounds_total",
            "rounds_decided",
            "rounds_preempted",
            "preemption_depth_max",
            "faults_per_decided_round",
        ):
            if agg.get(name) is not None:
                self.gauge(name, agg[name])

    def ingest_perf(self, perf: dict[str, Any]) -> None:
        """Fold an ``obs.perf.perf_summary`` dict into the registry.

        Every perf value is a point-in-time host-side measurement, so they
        all land as gauges under a ``perf_`` prefix — the prefix keeps the
        plane's namespace disjoint from the telemetry/coverage/exposure
        planes, so one shared registry never collides.  Chunk-latency
        quantiles become one ``perf_chunk_latency_us`` series labelled by
        quantile (the same summary idiom as ``round_latency_ticks``);
        the optional ``vmem``/``roofline`` sub-dicts flatten in under the
        same prefix.
        """
        for name in (
            "dispatches",
            "chunks",
            "rounds_total",
            "rounds_per_sec",
            "rounds_per_sec_steady",
            "rounds_per_sec_windowed",
            "occupancy",
            "compile_s",
            "wall_s",
            "dispatch_enqueue_s",
            "probe_wait_s",
        ):
            v = perf.get(name)
            if v is not None:
                self.gauge(f"perf_{name}", v)
        lat = perf.get("chunk_latency_us") or {}
        for q in ("p50", "p95", "p99"):
            if lat.get(q) is not None:
                self.gauge("perf_chunk_latency_us", lat[q], quantile=q)
        for sub in ("vmem", "roofline"):
            for name, v in (perf.get(sub) or {}).items():
                self.gauge(f"perf_{name}", v)

    def ingest_fleet(
        self, fleet: dict[str, Any], worker: Optional[str] = None
    ) -> None:
        """Fold a fleet coordinator gauges block into the registry.

        Every value is a point-in-time coordinator-side observation of
        the queue/lease state machine (``fleet.coordinator``), so they
        all land as gauges under a ``fleet_`` prefix — the plane's
        namespace stays disjoint like every other ingest.  The keys a
        scraper alerts on: ``fleet_leases_reclaimed`` climbing means
        workers are dying (each reclaim is one recovered campaign), and
        ``fleet_queue_depth`` stuck nonzero with ``fleet_workers_alive``
        at zero means the fleet stalled.

        ``worker`` adds a label dimension: per-worker blocks (the
        coordinator's ``report["workers"]``) land as
        ``fleet_<name>{worker=...}`` series beside — never overwriting —
        the unlabeled fleet-aggregate gauges.  Gauge keys include sorted
        labels, so N workers are N distinct series (the PR 16 collision,
        where the last-ingested block won, cannot recur).
        """
        for name in (
            "workers",
            "workers_alive",
            "workers_dead",
            "workers_spawned",
            "queue_depth",
            "records_total",
            "records_done",
            "leases_held_peak",
            "leases_expired",
            "leases_reclaimed",
            "campaigns_retried",
            "merge_dedup",
            "torn_tails",
            "resumed_seeds",
            "records",
            "seeds",
            "rounds",
            "violations",
        ):
            v = fleet.get(name)
            if v is None:
                continue
            if worker is not None:
                self.gauge(f"fleet_{name}", v, worker=str(worker))
            else:
                self.gauge(f"fleet_{name}", v)

    def ingest_lineage(self, summary: dict[str, Any],
                       ops: Optional[dict[str, Any]] = None) -> None:
        """Fold a ``fuzz.lineage`` roll-up into ``lineage_*`` gauges.

        ``ops`` (the ``op_attribution`` per-op table) lands as
        ``lineage_op_<column>{op=...}`` labeled series — one series per
        mutation op, the per-op payoff a scraper can rank.
        """
        for name in ("entries", "roots", "executed", "retired",
                     "depth_max", "best_fitness"):
            v = summary.get(name)
            if v is not None:
                self.gauge(f"lineage_{name}", v)
        for op, row in sorted((ops or {}).items()):
            for col in ("campaigns", "new_bits", "effective",
                        "violations", "margin_tightened", "fitness"):
                v = row.get(col)
                if v is not None:
                    self.gauge(f"lineage_op_{col}", v, op=str(op))

    def snapshot(self) -> dict[str, Any]:
        """One JSON-ready dict of everything in the registry."""
        counters: dict[str, Any] = {}
        for name, series in sorted(self._counters.items()):
            for key, value in sorted(series.items()):
                label = ",".join(f"{k}={v}" for k, v in key)
                counters[f"{name}{{{label}}}" if label else name] = value
        hists = {
            name: {"counts": h["counts"], "bin_width": h["bin_width"]}
            for name, h in sorted(self._hists.items())
        }
        gauges: dict[str, Any] = {}
        for name, series in sorted(self._gauges.items()):
            for key, value in sorted(series.items()):
                label = ",".join(f"{k}={v}" for k, v in key)
                gauges[f"{name}{{{label}}}" if label else name] = value
        snap: dict[str, Any] = {"counters": counters, "histograms": hists}
        if gauges:
            snap["gauges"] = gauges
        return snap

    def emit(self, log: MetricsLog, event: str = "metrics") -> dict[str, Any]:
        """Write the current snapshot as one JSONL record to ``log``."""
        return log.emit(event, **self.snapshot())

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (counters + histograms)."""
        ns = self.namespace
        lines: list[str] = []
        for name, series in sorted(self._counters.items()):
            lines.append(f"# TYPE {ns}_{name} counter")
            for key, value in sorted(series.items()):
                label = ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in key
                )
                suffix = f"{{{label}}}" if label else ""
                lines.append(f"{ns}_{name}{suffix} {int(value)}")
        for name, series in sorted(self._gauges.items()):
            lines.append(f"# TYPE {ns}_{name} gauge")
            for key, value in sorted(series.items()):
                label = ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in key
                )
                suffix = f"{{{label}}}" if label else ""
                val = int(value) if float(value).is_integer() else value
                lines.append(f"{ns}_{name}{suffix} {val}")
        for name, h in sorted(self._hists.items()):
            lines.append(f"# TYPE {ns}_{name} histogram")
            cum = 0
            # The device layout's LAST bin is a catch-all (>= top edge), so
            # it folds into +Inf rather than getting a finite `le`.
            for i, c in enumerate(h["counts"][:-1]):
                cum += c
                le = (i + 1) * h["bin_width"]
                lines.append(f'{ns}_{name}_bucket{{le="{le}"}} {cum}')
            cum += h["counts"][-1] if h["counts"] else 0
            lines.append(f'{ns}_{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{ns}_{name}_count {cum}")
        return "\n".join(lines) + "\n"


@contextlib.contextmanager
def trace_scope(name: str) -> Iterator[None]:
    """Named region in device profiles (no-op overhead when not profiling)."""
    with jax.profiler.TraceAnnotation(name):
        yield
