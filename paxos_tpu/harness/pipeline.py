"""Asynchronous pipelined dispatch — keep the device ahead of the host.

bench.py records a 10-17% dispatch-boundary tax at the operating chunk of
64 (config2 fused: 321.8M rounds/s @ chunk 64 vs 378.1M @ chunk 1024), and
ROOFLINE.json puts the fused kernel at ~0.69 VPU utilization — the
remaining headroom is host-side coordination, not compute.  Chunk 64 is
schedule-relevant for long-log Multi-Paxos (the decided-prefix compaction
cadence), so the chunk size cannot simply be raised.  This module closes
the gap from the host side instead:

- :func:`pipelined_run` groups up to ``depth`` chunk bodies into ONE device
  dispatch (``advance(state, n_ticks, groups)`` — see
  ``run.make_advance_grouped``), so the per-dispatch tunnel cost is paid
  once per ``depth`` chunks instead of once per chunk, and consecutive
  dispatches enqueue back-to-back via JAX async dispatch with nothing
  blocking between them.  Grouping only regroups the chunk sequence — tick
  PRNG streams derive from ``state.tick``, never from dispatch boundaries —
  so schedules stay bit-identical at any depth (tests/test_pipeline.py
  pins this against the serial loop on both engines).
- Termination probes (``until_all_chosen``, long-log ``done``) fetch a
  tiny on-device done-flag scalar (``copy_to_host_async`` started first),
  so the big state pytree never round-trips mid-run.  The probe runs per
  *dispatch*, not per chunk: an early exit overshoots the serial exit tick
  by strictly less than ``depth * chunk`` ticks.
- :class:`AsyncSummary` starts the report readback (one composite pytree —
  ``run.summarize_device``) without blocking, so a soak can dispatch seed
  N+1's campaign while seed N's report is still in flight.

Depth-vs-latency tradeoff: depth 1 is the exact serial loop (probe every
chunk boundary); higher depths amortize dispatch cost ~1/depth but coarsen
probe granularity and per-chunk observability (the CLI's per-chunk metrics
loop and checkpoint cadence need depth 1).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


def start_transfer(tree) -> None:
    """Start device->host transfer of every array leaf without blocking.

    Best-effort: backends whose arrays lack ``copy_to_host_async`` just
    skip the hint and the later ``device_get`` does a blocking fetch.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            start()


def pipelined_run(
    state,
    advance: Callable,
    *,
    budget: int,
    chunk: int,
    depth: int,
    done_fn: Optional[Callable] = None,
    on_dispatch: Optional[Callable[[int], None]] = None,
    spans=None,
):
    """Drive ``advance(state, n_ticks, groups)`` for ``budget`` ticks.

    Each device dispatch covers up to ``depth`` full chunks of ``chunk``
    ticks (a trailing remainder shorter than one chunk dispatches alone),
    preserving the serial loop's exact chunk boundaries — and therefore the
    long-log compaction cadence — inside fewer dispatches.

    ``done_fn(state) -> 0-d bool array`` enables early exit: the scalar
    flag's transfer is started asynchronously and drained before the next
    dispatch is enqueued, so an exit lands on the first dispatch boundary
    at or past the serial exit tick — overshoot < ``depth * chunk`` ticks,
    and at depth 1 the semantics are exactly the serial per-chunk probe.
    Without ``done_fn`` nothing blocks until the caller reads the state.

    ``on_dispatch(ticks_done)`` is called after each dispatch is enqueued
    (host-side bookkeeping such as per-dispatch log records).

    ``spans`` is an optional ``obs.host_spans.HostSpanRecorder``: each
    grouped dispatch and each done-flag probe becomes a wall-clock span on
    the host track of a merged Perfetto trace, with the dispatch's tick
    window in its args (the causal device<->host correlation).  Purely
    observational — ``None`` (the default) takes the identical code path.

    Returns ``(state, ticks_dispatched, exit_tick)`` — ``exit_tick`` is the
    dispatch boundary where the done flag first read true, or None.
    """
    from paxos_tpu.obs.host_spans import ensure_recorder

    sp = ensure_recorder(spans)
    done = 0
    exit_tick = None
    while done < budget:
        left = budget - done
        if left < chunk:
            n, g = left, 1
        else:
            n, g = chunk, min(depth, left // chunk)
        with sp.span("dispatch", tick_start=done, ticks=n * g, groups=g):
            state = advance(state, n, g)
        done += n * g
        if on_dispatch is not None:
            on_dispatch(done)
        if done_fn is not None:
            with sp.span("probe", tick=done):
                flag = done_fn(state)
                start_transfer(flag)
                is_done = bool(jax.device_get(flag))
            if is_done:
                exit_tick = done
                break
    return state, done, exit_tick


class AsyncSummary:
    """A :func:`run.summarize` split in two across time.

    Construction runs the on-device reductions and *starts* the host
    transfer of the one composite report pytree — nothing blocks, and the
    campaign's big state pytree never crosses.  ``get()`` drains the
    transfer and formats the host report (including the Multi-Paxos
    ballot-overflow guard, which raises ``MeasurementCorrupted`` exactly as
    the synchronous path does).  A soak overlaps seed N+1's dispatch with
    seed N's report transfer by constructing N+1's campaign between the
    two halves.
    """

    def __init__(
        self, state, liveness: bool = False, log_total: int = 0, spans=None
    ):
        from paxos_tpu.harness.run import summarize_device
        from paxos_tpu.obs.host_spans import ensure_recorder

        self._sp = ensure_recorder(spans)
        with self._sp.span("report_transfer_start"):
            self._dev, self._meta = summarize_device(
                state, liveness=liveness, log_total=log_total
            )
            start_transfer(self._dev)

    def get(self) -> dict[str, Any]:
        from paxos_tpu.harness.run import summarize_host

        with self._sp.span("report_drain"):
            host = jax.device_get(self._dev)
        return summarize_host(host, self._meta)
